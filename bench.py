"""Headline benchmark: ResNet-50 synthetic data-parallel throughput.

TPU-native port of the reference's measurement tool
(ref: examples/pytorch_synthetic_benchmark.py:93-117 — ResNet-50,
synthetic ImageNet batches, prints img/sec per GPU and total). Metric of
record (BASELINE.json): images/sec/chip; `vs_baseline` compares against
the reference's per-GPU ResNet-50 number from the same methodology
(docs/benchmarks.rst:16-43, ~170 img/sec on P100s).

Beyond throughput this also reports, in the same JSON line:
  - `mfu`: achieved model FLOPs utilization for the ResNet-50 step —
    XLA's cost analysis of the compiled train step divided by the
    chip's peak bf16 FLOPs. ResNet-50 with BatchNorm is HBM-bandwidth
    bound on TPU (see docs/benchmarks.md for the profile/roofline
    analysis), so this sits near the memory roofline, not the MXU peak.
  - `transformer_mfu`: the same measurement on a compute-dense
    flagship (BERT-base, seq 128 — one of the reference's own headline
    workloads, docs/benchmarks.rst:44-61). This is the MXU-bound
    number: ≥0.5 on v5e.
  - `gpt2_mfu`/`gpt2_mfu_dense`/`gpt2_flash_speedup`: the flagship
    GPT-2-small seq-2048 step, flash (Pallas) vs XLA dense at the SAME
    shape; `gpt2_long_mfu` at seq 4096 where dense cannot run
    (`gpt2_long_flops` labels the FLOP-numerator methodology).
  - `fused_bn_step_ms`/`fused_bn_delta_ms`: the ResNet step with the
    Pallas fused-BN kernel wired into stage 2 — keeps the wire-or-not
    question answered by a fresh measurement (docs/benchmarks.md).
  - `scaling_efficiency`: sharding-overhead efficiency, the north-star
    "allreduce scaling efficiency 1->N" trend (docs/benchmarks.rst:11-14
    measures 90% for ResNet on 512 GPUs). On a single host this is
    measured on an 8-virtual-device CPU mesh as t(1 device, batch B) /
    t(8 devices, same B): identical total compute on the same silicon,
    so any drop is the cost the GSPMD collectives add. Median over
    `--scaling-reps` order-statistic-paired probe samples;
    `scaling_spread` is the (max-min)/median across them and
    `scaling_samples` carries the raw per-rep seconds (+ the
    index-paired spread) for diagnosis. With >=2 real chips visible, a
    true weak-scaling sweep runs instead.

The training loop is a `lax.scan` over steps inside one jit (chunked),
so steps dispatch on-device back-to-back with no host round-trip
between them — the TPU-native shape of the reference's tight benchmark
loop (host dispatch gaps cost ~7% at ResNet-50 step times).

Prints ONE JSON line: {"metric","value","unit","vs_baseline",...}.
"""
from __future__ import annotations

import argparse
import json
import os
import functools
import statistics
import subprocess
import sys
import time


# Reference per-GPU ResNet-50 throughput implied by docs/benchmarks.rst
# (tf_cnn_benchmarks on 25GbE P100 clusters, ~170 img/sec/GPU).
BASELINE_IMG_SEC_PER_CHIP = 170.0

# Peak dense bf16 FLOP/s per chip by device kind (public figures).
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}


def _peak_flops(device) -> float:
    env = os.environ.get("HOROVOD_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "")
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 275e12  # v4 default


def _force_cpu(n_devices: int):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from horovod_tpu.utils.compat import force_host_device_count

    force_host_device_count(n_devices)


def _build(model_name, n_chips, batch_per_chip, image_size=224, mesh=None,
           donate=True, model_kw=None, seq_len=None, zero=False):
    import jax
    import numpy as np
    import optax

    from horovod_tpu.models import get_model
    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.parallel.train import (
        lm_loss,
        make_train_step,
        softmax_xent,
    )

    if mesh is None:
        mesh = create_mesh({"dp": n_chips})
    spec = get_model(model_name)
    model_kw = dict(model_kw or {})
    if spec.kind in ("lm", "encoder"):
        # The bench path opts INTO bf16 logits (the measured config:
        # 6.0 ms of a 98 ms GPT-2 step on v5e, docs/benchmarks.md r5).
        # The library default stays f32 — external logits consumers
        # keep full precision unless they ask otherwise (ADVICE r14).
        import jax.numpy as jnp

        model_kw.setdefault("logits_dtype", jnp.bfloat16)
    model = spec.make_model(**model_kw)
    rng = np.random.RandomState(42)
    global_batch = batch_per_chip * n_chips
    if spec.kind == "image":
        inputs = rng.rand(global_batch, image_size, image_size, 3).astype(
            np.float32
        )
        labels = rng.randint(0, 1000, size=(global_batch,), dtype=np.int32)
        loss_fn = softmax_xent
        tx = optax.sgd(0.01, momentum=0.9)
        has_bn = True
    else:  # lm / encoder: next-token loss over synthetic ids
        bkw = {} if seq_len is None else {"seq_len": seq_len}
        inputs = spec.make_batch(global_batch, **bkw)[0]
        labels = inputs
        loss_fn = lm_loss
        tx = optax.adamw(1e-4)
        has_bn = False

    build = make_train_step(
        model, tx, loss_fn, mesh=mesh, has_batch_stats=has_bn,
        donate=donate, zero=zero,
    )
    init_fn, step_fn, _ = build(jax.random.PRNGKey(0), inputs, labels)
    state = init_fn(jax.random.PRNGKey(0))

    from jax.sharding import NamedSharding, PartitionSpec as P

    dsh = NamedSharding(mesh, P(mesh.axis_names[0]))
    inputs = jax.device_put(inputs, dsh)
    labels = jax.device_put(labels, dsh)
    return state, step_fn, inputs, labels, global_batch, mesh


def _make_scan_step(step_fn, mesh, chunk: int):
    """One jit running `chunk` train steps back-to-back via lax.scan.

    Removes the per-step host dispatch gap (the device otherwise idles
    ~5-10ms between steps waiting for the next enqueue over the device
    transport)."""
    import jax

    inner = getattr(step_fn, "raw", None) or getattr(
        step_fn, "__wrapped__", step_fn)
    shardings = getattr(step_fn, "shardings", None)
    kw = {}
    if shardings is not None:
        kw = {"in_shardings": shardings,
              "out_shardings": (shardings[0], None),
              "donate_argnums": (0,)}

    @functools.partial(jax.jit, **kw)
    def multi(state, inputs, labels):
        def body(s, _):
            s, loss = inner(s, inputs, labels)
            return s, loss

        return jax.lax.scan(body, state, None, length=chunk)

    def run(state, inputs, labels):
        from horovod_tpu.utils.compat import set_mesh as _set_mesh
        with _set_mesh(mesh):
            return multi(state, inputs, labels)

    return run


def _hard_sync(x):
    import jax

    # device_get forces materialization; block_until_ready alone is
    # not a reliable fence on tunneled device transports.
    jax.device_get(jax.tree.leaves(x)[0]).ravel()[:1]


def _time_scan(state, scan_fn, inputs, labels, chunk, chunks, warmup=1):
    for _ in range(warmup):
        state, losses = scan_fn(state, inputs, labels)
    _hard_sync(losses)

    t0 = time.perf_counter()
    for _ in range(chunks):
        state, losses = scan_fn(state, inputs, labels)
    _hard_sync(losses)
    return (time.perf_counter() - t0) / (chunk * chunks), state


def _step_flops(step_fn, state, inputs, labels):
    """Per-step FLOPs from XLA's cost analysis of the compiled step."""
    try:
        compiled = step_fn.__wrapped__.lower(state, inputs, labels).compile() \
            if hasattr(step_fn, "__wrapped__") else None
    except Exception:
        compiled = None
    if compiled is None:
        try:
            import jax

            compiled = jax.jit(lambda s, i, l: step_fn(s, i, l)).lower(
                state, inputs, labels).compile()
        except Exception:
            return None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return float(ca.get("flops", 0.0)) or None
    except Exception:
        return None


def _measure_mfu(model, batch, peak, image_size=224, chunk=8, chunks=2):
    """Steps/sec + cost-analysis MFU for one model on the real chip."""
    import jax

    state, step_fn, inputs, labels, global_batch, mesh = _build(
        model, 1, batch, image_size
    )
    scan_fn = _make_scan_step(step_fn, mesh, chunk)
    dt, state = _time_scan(state, scan_fn, inputs, labels, chunk, chunks)
    flops = _step_flops(step_fn, state, inputs, labels)
    mfu = (flops / dt) / peak if flops else None
    return dt, global_batch, mfu


def _measure_gpt2(peak, seq=2048, batch=4, chunk=12, chunks=2):
    """Long-sequence GPT-2 MFU headline: flash (Pallas) vs XLA dense at
    the SAME shape, so the kernel's contribution is a printed delta
    (ref methodology: docs/benchmarks.rst:16-43 — measure the flagship
    at its working sequence length, not a toy one).

    Model FLOPs for BOTH numbers come from the DENSE compiled step's
    cost analysis: the two implementations compute the same math, and
    counting the flash kernel's internal bwd recompute would inflate
    its own MFU (standard MFU methodology charges model FLOPs only).
    """
    times = {}
    flops = None
    for impl in ("dense", "flash"):
        state, step_fn, inputs, labels, _, mesh = _build(
            "gpt2-small", 1, batch,
            model_kw={"attn_impl": impl, "max_len": seq}, seq_len=seq,
        )
        scan_fn = _make_scan_step(step_fn, mesh, chunk)
        dt, state = _time_scan(state, scan_fn, inputs, labels, chunk,
                               chunks)
        if impl == "dense":
            flops = _step_flops(step_fn, state, inputs, labels)
        times[impl] = dt
        # Release this impl's train state before building the next one:
        # two full param+AdamW states resident at once can OOM shapes
        # each impl fits individually.
        del state, step_fn, scan_fn, inputs, labels
    if not flops:
        return None
    return {
        "gpt2_mfu": round((flops / times["flash"]) / peak, 4),
        "gpt2_mfu_dense": round((flops / times["dense"]) / peak, 4),
        "gpt2_model": "gpt2-small",
        "gpt2_seq": seq,
        "gpt2_flash_speedup": round(times["dense"] / times["flash"], 3),
    }


def _measure_gpt2_long(peak, seq=4096, batch=4, chunk=8, chunks=2):
    """Long-context headline: GPT-2 at a sequence length where the
    DENSE step cannot even fit on the chip (the materialized attention
    probabilities alone exceed HBM) but the flash path trains. Model
    FLOPs still come from the dense program's cost analysis —
    lower().compile() never executes, so the infeasible-to-RUN dense
    step still yields the honest FLOP count; if even compilation
    refuses, the count is recovered analytically from two smaller
    dense compiles (model flops are exactly a*S + b*S^2 in sequence
    length at fixed batch)."""
    state, step_fn, inputs, labels, _, mesh = _build(
        "gpt2-small", 1, batch,
        model_kw={"attn_impl": "flash", "max_len": seq}, seq_len=seq,
    )
    scan_fn = _make_scan_step(step_fn, mesh, chunk)
    dt, state = _time_scan(state, scan_fn, inputs, labels, chunk, chunks)
    del state, step_fn, scan_fn, inputs, labels

    def dense_flops(s):
        st, fn, ins, lbs, _, _m = _build(
            "gpt2-small", 1, batch,
            model_kw={"attn_impl": "dense", "max_len": s}, seq_len=s,
        )
        fl = _step_flops(fn, st, ins, lbs)
        del st, fn, ins, lbs
        return fl

    flops = None
    flops_method = "dense-compile"
    try:
        flops = dense_flops(seq)
    except Exception:
        pass
    if not flops:
        try:
            f1, f2 = dense_flops(seq // 4), dense_flops(seq // 2)
            if f1 and f2:
                s1, s2 = seq // 4, seq // 2
                # Solve f = a*s + b*s^2 through the two points.
                b = (f2 / s2 - f1 / s1) / (s2 - s1)
                a = f1 / s1 - b * s1
                flops = a * seq + b * seq * seq
                flops_method = "extrapolated-quadratic"
        except Exception:
            return None
    if not flops:
        return None
    return {
        "gpt2_long_mfu": round((flops / dt) / peak, 4),
        "gpt2_long_seq": seq,
        # Methodology label: dense-equivalent FLOPs (full S^2 attention
        # work, incl. the masked half the causal flash kernel skips),
        # and whether the dense count was compiled at this seq or fit
        # through two smaller dense compiles — so nobody quotes the
        # number as fully measured when it is extrapolated.
        "gpt2_long_flops": flops_method,
        "gpt2_long_mfu_convention": "dense-equivalent",
    }


def _scaling_probe(n_devices: int, batch: int, image_size: int,
                   iters: int, reps: int = 1):
    """Child-process entry: time `reps` independent samples of `iters`
    steps of a FIXED global batch on an n-device CPU mesh (one compile,
    reps cheap runs); print a seconds list on the last line.

    Plain per-step dispatch, not the scan loop: compiling a scan-of-
    steps ResNet-50 on this single CPU core takes several minutes,
    which would dwarf the signal. Per-call dispatch overhead is
    identical for both device counts, so the ratio stays a valid
    overhead trend (see module docstring).

    Every rep restarts from the SAME initial state (donation off): CPU
    per-step cost depends on the parameter trajectory (denormal-heavy
    regions run far slower), so timing a continuing trajectory makes
    reps incomparable — with a fixed start, every rep on every device
    count times the identical computation."""
    _force_cpu(n_devices)
    state0, step_fn, images, labels, _, mesh = _build(
        "resnet50", n_devices, batch // n_devices, image_size,
        donate=False,
    )
    # Warm with one full discarded rep (compile + first-touch paging),
    # then take comparable samples.
    state = state0
    for _ in range(iters):
        state, loss = step_fn(state, images, labels)
    _hard_sync(loss)
    samples = []
    for _ in range(reps):
        state = state0
        t0 = time.perf_counter()
        for _ in range(iters):
            state, loss = step_fn(state, images, labels)
        _hard_sync(loss)
        samples.append(time.perf_counter() - t0)
    print(json.dumps({"seconds": samples}))


def _measure_scaling(batch=32, image_size=64, iters=8, reps=5):
    """t(1 dev)/t(8 dev) for the same global batch: one subprocess per
    device count (fresh backend), `reps` timed samples inside each (one
    compile per count). Returns (median-ratio, spread, samples) or
    None.

    Variance handling (r5, after the r4 spread regression to 0.089):
    per-rep samples within one process are independent replays of the
    identical computation, so their scatter is pure host noise — rep i
    of the 1-device run shares nothing with rep i of the 8-device run.
    Index-pairing those reps (r4) therefore MANUFACTURED ratio variance
    from unrelated noise draws. Pairing order statistics instead
    (sorted t1 against sorted t8) compares like against like — fastest
    clean sample to fastest, most-contended to most-contended — so the
    quoted spread reflects genuine between-sample disagreement, not
    pairing luck. The raw per-rep seconds for both device counts ride
    along in the JSON so a regression is diagnosable from the artifact
    (tight t1 + scattered t8 → collective/dispatch jitter; both lists
    drifting monotonically → host thermal/contention drift)."""
    times = {}
    for n in (1, 8):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--scaling-probe", str(n), "--batch-size", str(batch),
               "--image-size", str(image_size),
               "--num-iters", str(iters), "--scaling-reps", str(reps)]
        try:
            out = subprocess.run(
                cmd, capture_output=True, text=True, timeout=1800,
                cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
            )
        except subprocess.TimeoutExpired:
            return None
        if out.returncode != 0:
            return None
        times[n] = json.loads(
            out.stdout.strip().splitlines()[-1])["seconds"]
    ratios = [t1 / t8 for t1, t8 in zip(sorted(times[1]),
                                        sorted(times[8]))]
    med = statistics.median(ratios)
    spread = (max(ratios) - min(ratios)) / med if med else 0.0
    # Order-statistic pairing minimizes (max-min) over pairings, so the
    # primary spread is a LOWER bound on ratio uncertainty; the
    # r3/r4-comparable index-paired spread rides along so cross-round
    # trends (and one-sided per-count jitter it would catch) stay
    # visible.
    iratios = [t1 / t8 for t1, t8 in zip(times[1], times[8])]
    imed = statistics.median(iratios)
    ispread = (max(iratios) - min(iratios)) / imed if imed else 0.0
    samples = {"t1": [round(t, 4) for t in times[1]],
               "t8": [round(t, 4) for t in times[8]],
               "spread_indexpair": round(ispread, 3)}
    return med, spread, samples


def _real_weak_scaling(n_chips, model, batch_per_chip, image_size, iters):
    """True weak scaling on real chips: img/sec/chip at n vs at 1."""
    import jax
    from horovod_tpu.parallel.mesh import create_mesh

    per_chip = {}
    for n in (1, n_chips):
        devices = jax.devices()[:n]
        mesh = create_mesh({"dp": n}, devices=devices)
        state, step_fn, images, labels, global_batch, mesh = _build(
            model, n, batch_per_chip, image_size, mesh=mesh
        )
        scan_fn = _make_scan_step(step_fn, mesh, iters)
        dt, _ = _time_scan(state, scan_fn, images, labels, iters, 1)
        per_chip[n] = global_batch / dt / n
    return per_chip[n_chips] / per_chip[1]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=0,
                   help="per-chip batch; 0 = sweep {256,512} and keep best")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-iters", type=int, default=24,
                   help="total timed steps (scan chunks of 8)")
    p.add_argument("--cpu", action="store_true",
                   help="force CPU (tiny shapes) for smoke runs")
    p.add_argument("--no-scaling", action="store_true")
    p.add_argument("--no-transformer", action="store_true",
                   help="skip the BERT-base MFU measurement")
    p.add_argument("--no-fused-bn", action="store_true",
                   help="skip the fused-BN-wired ResNet step comparison")
    p.add_argument("--no-gpt2", action="store_true",
                   help="skip the long-sequence GPT-2 flash/dense MFU")
    p.add_argument("--gpt2-seq", type=int, default=2048)
    p.add_argument("--gpt2-batch", type=int, default=4)
    p.add_argument("--zero", action="store_true",
                   help="shard optimizer state over dp (GSPMD ZeRO; "
                        "docs/running.md 'ZeRO sharded optimizer state')")
    p.add_argument("--scaling-reps", type=int, default=5)
    p.add_argument("--scaling-probe", type=int, default=0,
                   help="internal: run the N-device CPU scaling probe")
    args = p.parse_args()

    if args.scaling_probe:
        _scaling_probe(args.scaling_probe, args.batch_size or 32,
                       args.image_size, args.num_iters, args.scaling_reps)
        return

    if args.cpu:
        _force_cpu(1)
        args.batch_size = min(args.batch_size or 16, 16)
        args.image_size = min(args.image_size, 64)
        args.num_iters = min(args.num_iters, 4)

    import jax

    import horovod_tpu as hvd

    hvd.init()
    n_chips = len(jax.devices())
    peak = _peak_flops(jax.devices()[0])

    chunk = max(min(args.num_iters // 3, 8), 1)
    candidates = [args.batch_size] if args.batch_size else [256, 512]
    best = None
    for bs in candidates:
        try:
            state, step_fn, images, labels, global_batch, mesh = _build(
                args.model, n_chips, bs, args.image_size, zero=args.zero
            )
            scan_fn = _make_scan_step(step_fn, mesh, chunk)
            # Short probe decides the sweep; two chunks, not one — a
            # single-chunk probe has occasionally crowned the slower
            # batch size on scheduler noise. The winner gets the full
            # run.
            dt, state = _time_scan(state, scan_fn, images, labels, chunk, 2)
            rate = global_batch / dt
        except Exception:
            continue
        if best is None or rate > best[1]:
            best = (bs, rate, state, step_fn, scan_fn, images, labels,
                    global_batch)
    if best is None:
        raise RuntimeError("no batch size compiled/ran successfully")
    (bs, _, state, step_fn, scan_fn, images, labels, global_batch) = best

    chunks = max(args.num_iters // chunk, 1)
    dt, state = _time_scan(state, scan_fn, images, labels, chunk, chunks)
    img_sec_total = global_batch / dt
    img_sec_chip = img_sec_total / n_chips

    flops = _step_flops(step_fn, state, images, labels)
    mfu = None
    if flops and not args.cpu:
        # cost_analysis() reports the SPMD-partitioned (per-device)
        # module, so this is per-chip utilization already — no division
        # by chip count.
        mfu = (flops / dt) / peak

    fused_bn_ms = None
    if (args.model == "resnet50" and not args.cpu
            and not args.no_fused_bn):
        # End-to-end measurement of the Pallas fused BN+ReLU+1x1 kernel
        # wired into stage 2 (the shape where it beats XLA 1.36x in
        # isolation, docs/kernels.md) — the r5 answer to "would wiring
        # it in actually move the step?" (docs/benchmarks.md).
        try:
            fstate, fstep, fim, flb, _, fmesh = _build(
                args.model, n_chips, bs, args.image_size,
                model_kw={"fuse_bn_conv_stages": (1,)},
            )
            fscan = _make_scan_step(fstep, fmesh, chunk)
            fdt, _ = _time_scan(fstate, fscan, fim, flb, chunk, chunks)
            fused_bn_ms = fdt * 1e3
            del fstate, fstep, fscan, fim, flb
        except Exception:
            fused_bn_ms = None

    tr_mfu = None
    if not (args.no_transformer or args.cpu):
        try:
            _, _, tr_mfu = _measure_mfu("bert-base", 256, peak)
        except Exception:
            tr_mfu = None

    gpt2 = None
    if not (args.no_gpt2 or args.cpu):
        try:
            gpt2 = _measure_gpt2(peak, seq=args.gpt2_seq,
                                 batch=args.gpt2_batch)
        except Exception:
            gpt2 = None
        try:
            long_res = _measure_gpt2_long(peak)
            if long_res:
                gpt2 = {**(gpt2 or {}), **long_res}
        except Exception:
            pass

    scaling = spread = scaling_samples = None
    if args.no_scaling or args.cpu:
        pass
    elif n_chips > 1:
        scaling = _real_weak_scaling(n_chips, args.model, bs,
                                     args.image_size,
                                     max(args.num_iters // 2, 1))
    else:
        res = _measure_scaling(reps=args.scaling_reps)
        if res is not None:
            scaling, spread, scaling_samples = res

    result = {
        "metric": f"{args.model}_synthetic_img_sec_per_chip",
        "value": round(img_sec_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_sec_chip / BASELINE_IMG_SEC_PER_CHIP, 3),
        "batch_per_chip": bs,
        "n_chips": n_chips,
    }
    if args.zero:
        result["zero"] = True
    if mfu is not None:
        result["mfu"] = round(mfu, 4)
    if fused_bn_ms is not None:
        # Positive delta = fused kernel made the step faster.
        result["fused_bn_step_ms"] = round(fused_bn_ms, 2)
        result["fused_bn_delta_ms"] = round(dt * 1e3 - fused_bn_ms, 2)
    if tr_mfu is not None:
        result["transformer_mfu"] = round(tr_mfu, 4)
        result["transformer_model"] = "bert-base"
    if gpt2 is not None:
        result.update(gpt2)
    if scaling is not None:
        result["scaling_efficiency"] = round(scaling, 3)
        result["scaling_mode"] = ("weak_real" if n_chips > 1
                                  else "overhead_cpu8")
        if spread is not None:
            result["scaling_spread"] = round(spread, 3)
        if scaling_samples is not None:
            result["scaling_samples"] = scaling_samples
    print(json.dumps(result))


if __name__ == "__main__":
    main()
