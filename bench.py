"""Headline benchmark: ResNet-50 synthetic data-parallel throughput.

TPU-native port of the reference's measurement tool
(ref: examples/pytorch_synthetic_benchmark.py:93-117 — ResNet-50,
synthetic ImageNet batches, prints img/sec per GPU and total). Metric of
record (BASELINE.json): images/sec/chip. The baseline reference point is
the published ResNet-101 example output scaled to the metric table in
BASELINE.md; `vs_baseline` compares per-chip throughput against the
reference's per-GPU number for the same script family
(docs/benchmarks.rst:43: 1656.82 total img/sec on 16 GPUs ≈ 103.6
img/sec/GPU for ResNet-101; the ResNet-50 per-GPU equivalent from the
same table's methodology is ~170 img/sec on P100s).

Prints ONE JSON line: {"metric","value","unit","vs_baseline"}.
"""
from __future__ import annotations

import argparse
import json
import time


# Reference per-GPU ResNet-50 throughput implied by docs/benchmarks.rst
# (tf_cnn_benchmarks on 25GbE P100 clusters, ~170 img/sec/GPU).
BASELINE_IMG_SEC_PER_CHIP = 170.0


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-warmup", type=int, default=3)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--cpu", action="store_true",
                   help="force CPU (tiny shapes) for smoke runs")
    args = p.parse_args()

    import os

    if args.cpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
        import jax.extend.backend as _jeb

        _jeb.clear_backends()
        args.batch_size = min(args.batch_size, 16)
        args.image_size = min(args.image_size, 64)
        args.num_iters = min(args.num_iters, 3)

    import numpy as np
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models import get_model
    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.parallel.train import make_train_step, softmax_xent

    hvd.init()
    n_chips = len(jax.devices())
    mesh = create_mesh({"dp": n_chips})

    spec = get_model(args.model)
    model = spec.make_model()
    rng = np.random.RandomState(42)
    global_batch = args.batch_size * n_chips
    images = rng.rand(global_batch, args.image_size, args.image_size, 3).astype(
        np.float32
    )
    labels = rng.randint(0, 1000, size=(global_batch,), dtype=np.int32)

    build = make_train_step(
        model,
        optax.sgd(0.01, momentum=0.9),
        softmax_xent,
        mesh=mesh,
        has_batch_stats=True,
    )
    init_fn, step_fn, _ = build(jax.random.PRNGKey(0), images, labels)
    state = init_fn(jax.random.PRNGKey(0))

    # Put batch on device once; per-step H2D is not part of the measured
    # path (the reference keeps its synthetic batch resident too,
    # ref: pytorch_synthetic_benchmark.py:80-91).
    from jax.sharding import NamedSharding, PartitionSpec as P

    dsh = NamedSharding(mesh, P("dp"))
    images = jax.device_put(images, dsh)
    labels = jax.device_put(labels, dsh)

    def hard_sync(state, loss):
        # device_get forces materialization; block_until_ready alone is
        # not a reliable fence on tunneled device transports.
        jax.device_get(loss)
        jax.device_get(jax.tree.leaves(state.params)[0]).ravel()[:1]

    for _ in range(args.num_warmup):
        state, loss = step_fn(state, images, labels)
    hard_sync(state, loss)

    t0 = time.perf_counter()
    for _ in range(args.num_iters):
        state, loss = step_fn(state, images, labels)
    hard_sync(state, loss)
    dt = time.perf_counter() - t0

    img_sec_total = global_batch * args.num_iters / dt
    img_sec_chip = img_sec_total / n_chips
    print(
        json.dumps(
            {
                "metric": f"{args.model}_synthetic_img_sec_per_chip",
                "value": round(img_sec_chip, 2),
                "unit": "images/sec/chip",
                "vs_baseline": round(img_sec_chip / BASELINE_IMG_SEC_PER_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
