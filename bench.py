"""Headline benchmark: ResNet-50 synthetic data-parallel throughput.

TPU-native port of the reference's measurement tool
(ref: examples/pytorch_synthetic_benchmark.py:93-117 — ResNet-50,
synthetic ImageNet batches, prints img/sec per GPU and total). Metric of
record (BASELINE.json): images/sec/chip; `vs_baseline` compares against
the reference's per-GPU ResNet-50 number from the same methodology
(docs/benchmarks.rst:16-43, ~170 img/sec on P100s).

Beyond throughput this also reports, in the same JSON line:
  - `mfu`: achieved model FLOPs utilization — XLA's cost analysis of the
    compiled train step divided by the chip's peak bf16 FLOPs
    (north-star asks for an efficiency number, not just img/sec).
  - `scaling_efficiency`: sharding-overhead efficiency, the north-star
    "allreduce scaling efficiency 1->N" trend (docs/benchmarks.rst:11-14
    measures 90% for ResNet on 512 GPUs). On a single host this is
    measured on an 8-virtual-device CPU mesh as t(1 device, batch B) /
    t(8 devices, same B): identical total compute on the same silicon,
    so any drop is the cost the GSPMD collectives add. With >=2 real
    chips visible, a true weak-scaling sweep runs instead.

Prints ONE JSON line: {"metric","value","unit","vs_baseline",...}.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


# Reference per-GPU ResNet-50 throughput implied by docs/benchmarks.rst
# (tf_cnn_benchmarks on 25GbE P100 clusters, ~170 img/sec/GPU).
BASELINE_IMG_SEC_PER_CHIP = 170.0

# Peak dense bf16 FLOP/s per chip by device kind (public figures).
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU7x": 2307e12,
}


def _peak_flops(device) -> float:
    env = os.environ.get("HOROVOD_TPU_PEAK_FLOPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "")
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 275e12  # v4 default


def _force_cpu(n_devices: int):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.extend.backend as _jeb

    _jeb.clear_backends()
    jax.config.update("jax_num_cpu_devices", n_devices)
    _jeb.clear_backends()


def _build(model_name, n_chips, batch_per_chip, image_size, mesh=None):
    import jax
    import numpy as np
    import optax

    from horovod_tpu.models import get_model
    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.parallel.train import make_train_step, softmax_xent

    if mesh is None:
        mesh = create_mesh({"dp": n_chips})
    model = get_model(model_name).make_model()
    rng = np.random.RandomState(42)
    global_batch = batch_per_chip * n_chips
    images = rng.rand(global_batch, image_size, image_size, 3).astype(
        np.float32
    )
    labels = rng.randint(0, 1000, size=(global_batch,), dtype=np.int32)

    build = make_train_step(
        model,
        optax.sgd(0.01, momentum=0.9),
        softmax_xent,
        mesh=mesh,
        has_batch_stats=True,
    )
    init_fn, step_fn, _ = build(jax.random.PRNGKey(0), images, labels)
    state = init_fn(jax.random.PRNGKey(0))

    from jax.sharding import NamedSharding, PartitionSpec as P

    dsh = NamedSharding(mesh, P(mesh.axis_names[0]))
    images = jax.device_put(images, dsh)
    labels = jax.device_put(labels, dsh)
    return state, step_fn, images, labels, global_batch


def _time_steps(state, step_fn, images, labels, warmup, iters):
    import jax

    def hard_sync(state, loss):
        # device_get forces materialization; block_until_ready alone is
        # not a reliable fence on tunneled device transports.
        jax.device_get(loss)
        jax.device_get(jax.tree.leaves(state.params)[0]).ravel()[:1]

    for _ in range(warmup):
        state, loss = step_fn(state, images, labels)
    hard_sync(state, loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step_fn(state, images, labels)
    hard_sync(state, loss)
    return time.perf_counter() - t0, state


def _step_flops(step_fn, state, images, labels):
    """Per-step FLOPs from XLA's cost analysis of the compiled step."""
    try:
        compiled = step_fn.__wrapped__.lower(state, images, labels).compile() \
            if hasattr(step_fn, "__wrapped__") else None
    except Exception:
        compiled = None
    if compiled is None:
        try:
            import jax

            compiled = jax.jit(lambda s, i, l: step_fn(s, i, l)).lower(
                state, images, labels).compile()
        except Exception:
            return None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return float(ca.get("flops", 0.0)) or None
    except Exception:
        return None


def _scaling_probe(n_devices: int, batch: int, image_size: int,
                   iters: int) -> float:
    """Child-process entry: time `iters` steps of a FIXED global batch
    on an n-device CPU mesh; print seconds on the last line."""
    _force_cpu(n_devices)
    state, step_fn, images, labels, _ = _build(
        "resnet50", n_devices, batch // n_devices, image_size
    )
    dt, _ = _time_steps(state, step_fn, images, labels, warmup=2,
                        iters=iters)
    print(json.dumps({"seconds": dt}))
    return dt


def _measure_scaling(batch=32, image_size=64, iters=8):
    """t(1 dev)/t(8 dev) for the same global batch, in subprocesses so
    each gets a fresh backend (trend metric; see module docstring).
    iters=8 keeps single-core timing noise under a few percent."""
    times = {}
    for n in (1, 8):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--scaling-probe", str(n), "--batch-size", str(batch),
               "--image-size", str(image_size), "--num-iters", str(iters)]
        try:
            out = subprocess.run(
                cmd, capture_output=True, text=True, timeout=900,
                cwd=os.path.dirname(os.path.abspath(__file__)) or ".",
            )
        except subprocess.TimeoutExpired:
            return None
        if out.returncode != 0:
            return None
        times[n] = json.loads(out.stdout.strip().splitlines()[-1])["seconds"]
    return times[1] / times[8]


def _real_weak_scaling(n_chips, model, batch_per_chip, image_size, iters):
    """True weak scaling on real chips: img/sec/chip at n vs at 1."""
    import jax
    from horovod_tpu.parallel.mesh import create_mesh

    per_chip = {}
    for n in (1, n_chips):
        devices = jax.devices()[:n]
        mesh = create_mesh({"dp": n}, devices=devices)
        state, step_fn, images, labels, global_batch = _build(
            model, n, batch_per_chip, image_size, mesh=mesh
        )
        dt, _ = _time_steps(state, step_fn, images, labels, 3, iters)
        per_chip[n] = global_batch * iters / dt / n
    return per_chip[n_chips] / per_chip[1]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=0,
                   help="per-chip batch; 0 = sweep {128,256} and keep best")
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-warmup", type=int, default=3)
    p.add_argument("--num-iters", type=int, default=20)
    p.add_argument("--cpu", action="store_true",
                   help="force CPU (tiny shapes) for smoke runs")
    p.add_argument("--no-scaling", action="store_true")
    p.add_argument("--scaling-probe", type=int, default=0,
                   help="internal: run the N-device CPU scaling probe")
    args = p.parse_args()

    if args.scaling_probe:
        _scaling_probe(args.scaling_probe, args.batch_size or 32,
                       args.image_size, args.num_iters)
        return

    if args.cpu:
        _force_cpu(1)
        args.batch_size = min(args.batch_size or 16, 16)
        args.image_size = min(args.image_size, 64)
        args.num_iters = min(args.num_iters, 3)

    import jax

    import horovod_tpu as hvd

    hvd.init()
    n_chips = len(jax.devices())

    candidates = [args.batch_size] if args.batch_size else [128, 256]
    best = None
    for bs in candidates:
        try:
            state, step_fn, images, labels, global_batch = _build(
                args.model, n_chips, bs, args.image_size
            )
            # Short probe decides the sweep; the winner gets the full run.
            dt, state = _time_steps(state, step_fn, images, labels,
                                    args.num_warmup, max(args.num_iters // 4, 2))
            rate = global_batch * max(args.num_iters // 4, 2) / dt
        except Exception:
            continue
        if best is None or rate > best[1]:
            best = (bs, rate, state, step_fn, images, labels, global_batch)
    if best is None:
        raise RuntimeError("no batch size compiled/ran successfully")
    bs, _, state, step_fn, images, labels, global_batch = best

    dt, state = _time_steps(state, step_fn, images, labels, 1,
                            args.num_iters)
    img_sec_total = global_batch * args.num_iters / dt
    img_sec_chip = img_sec_total / n_chips

    flops = _step_flops(step_fn, state, images, labels)
    mfu = None
    if flops:
        # cost_analysis() reports the SPMD-partitioned (per-device)
        # module, so this is per-chip utilization already — no division
        # by chip count.
        peak = _peak_flops(jax.devices()[0])
        mfu = (flops * args.num_iters / dt) / peak

    if args.no_scaling or args.cpu:
        scaling = None
    elif n_chips > 1:
        scaling = _real_weak_scaling(n_chips, args.model, bs,
                                     args.image_size, args.num_iters // 2)
    else:
        scaling = _measure_scaling()

    result = {
        "metric": f"{args.model}_synthetic_img_sec_per_chip",
        "value": round(img_sec_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_sec_chip / BASELINE_IMG_SEC_PER_CHIP, 3),
        "batch_per_chip": bs,
        "n_chips": n_chips,
    }
    if mfu is not None:
        result["mfu"] = round(mfu, 4)
    if scaling is not None:
        result["scaling_efficiency"] = round(scaling, 3)
        result["scaling_mode"] = ("weak_real" if n_chips > 1
                                  else "overhead_cpu8")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
