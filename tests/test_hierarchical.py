"""Hierarchical (local/cross) eager allreduce + autotune categorical arms.

(ref: NCCLHierarchicalAllreduce, nccl_operations.cc:190-405 — intra-node
reduce-scatter, cross-node allreduce per slice, intra-node allgather;
parameter_manager.h:163-228 — hierarchical/cache categorical tuning.)
"""
import os
import threading
import time

import numpy as np
import pytest

from horovod_tpu.backend.threaded import ThreadedGroup
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.engine.engine import Engine
from horovod_tpu.engine.parameter_manager import ParameterManager


def _run_backend_ranks(size, topo, fn):
    """fn(backend, rank) on `size` ThreadedBackends with topology set."""
    group = ThreadedGroup(size)
    backends = []
    for r in range(size):
        b = group.backend(r)
        lr, ls, cr, cs = topo(r)
        b.set_topology(lr, ls, cr, cs)
        b.hierarchical = True
        backends.append(b)
    results = [None] * size
    errors = [None] * size

    def worker(r):
        try:
            results[r] = fn(backends[r], r)
        except BaseException as e:  # noqa: BLE001
            errors[r] = e

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    for e in errors:
        if e is not None:
            raise e
    return results


def _topo_2x2(r):
    # 2 hosts x 2 slots, contiguous packing: rank = cross*2 + local.
    return (r % 2, 2, r // 2, 2)


@pytest.mark.parametrize("n", [1, 3, 8, 1000, 4096 + 3])
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_hierarchical_matches_sum(n, dtype):
    def fn(b, r):
        arr = (np.arange(n, dtype=dtype) + r * 10.0).reshape(-1)
        return b._hierarchical_allreduce(arr, ReduceOp.SUM)

    out = _run_backend_ranks(4, _topo_2x2, fn)
    expect = sum(np.arange(n, dtype=dtype) + r * 10.0 for r in range(4))
    for o in out:
        np.testing.assert_allclose(o, expect, rtol=1e-6)


@pytest.mark.parametrize("op,combine", [
    (ReduceOp.MIN, lambda xs: np.minimum.reduce(xs)),
    (ReduceOp.MAX, lambda xs: np.maximum.reduce(xs)),
    (ReduceOp.PRODUCT, lambda xs: np.multiply.reduce(xs)),
    (ReduceOp.AVERAGE, lambda xs: np.add.reduce(xs) / len(xs)),
])
def test_hierarchical_ops(op, combine):
    rng = np.random.RandomState(0)
    inputs = [rng.rand(257).astype(np.float64) + 0.5 for _ in range(4)]

    def fn(b, r):
        return b._hierarchical_allreduce(inputs[r].copy(), op)

    out = _run_backend_ranks(4, _topo_2x2, fn)
    expect = combine(inputs)
    for o in out:
        np.testing.assert_allclose(o, expect, rtol=1e-10)


def test_allreduce_dispatches_hierarchical(monkeypatch):
    """backend.allreduce takes the hierarchical path when toggled, the
    topology is valid, and the payload clears the ring threshold; it
    falls back to star below the threshold and to flat ring on invalid
    topology."""
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "64")
    calls = []

    def fn(b, r):
        orig = b._hierarchical_allreduce

        def spy(arr, op):
            calls.append(r)
            return orig(arr, op)

        b._hierarchical_allreduce = spy
        return b.allreduce(np.ones(100, np.float32), ReduceOp.SUM)

    out = _run_backend_ranks(4, _topo_2x2, fn)
    for o in out:
        np.testing.assert_allclose(o, np.full(100, 4.0))
    assert sorted(calls) == [0, 1, 2, 3]

    # Sub-threshold payloads stay on the latency-optimal star path.
    calls.clear()

    def fn_small(b, r):
        b._hierarchical_allreduce = lambda arr, op: calls.append(r)
        return b.allreduce(np.ones(4, np.float32), ReduceOp.SUM)

    out = _run_backend_ranks(4, _topo_2x2, fn_small)
    for o in out:
        np.testing.assert_allclose(o, np.full(4, 4.0))
    assert calls == []

    # Invalid topology (local_size=1): falls back to flat even when the
    # toggle is on.
    calls.clear()
    out = _run_backend_ranks(4, lambda r: (0, 1, r, 4), fn)
    for o in out:
        np.testing.assert_allclose(o, np.full(100, 4.0))
    assert calls == []


def test_engine_hierarchical_end_to_end(monkeypatch):
    """4 engines with 2x2 topology + HOROVOD_HIERARCHICAL_ALLREDUCE=1:
    the negotiated eager path produces correct sums over the
    hierarchical data plane (engine agrees validity collectively)."""
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
    group = ThreadedGroup(4)
    engines = []
    for r in range(4):
        e = Engine(rank=r, size=4, backend=group.backend(r),
                   local_rank=r % 2, local_size=2,
                   cross_rank=r // 2, cross_size=2)
        e.cycle_time_s = 0.001
        engines.append(e)
    for e in engines:
        e.start()

    results = [None] * 4
    errors = [None] * 4

    def worker(r):
        try:
            eng = engines[r]
            outs = []
            for i in range(3):
                h = eng.enqueue_allreduce(
                    np.full(300, float(r + 1), np.float32), name=f"t{i}"
                )
                outs.append(eng.synchronize(h, timeout=30))
            results[r] = outs
        except BaseException as e:  # noqa: BLE001
            errors[r] = e

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    # By now every loop has run (allreduces completed), so the
    # collectively-agreed toggle is observable.
    for e in engines:
        assert e.backend.hierarchical, "validity agreement should pass"
    stop = [threading.Thread(target=e.shutdown) for e in engines]
    for t in stop:
        t.start()
    for t in stop:
        t.join(timeout=60)
    for e in errors:
        if e is not None:
            raise e
    for r in range(4):
        for o in results[r]:
            np.testing.assert_allclose(o, np.full(300, 10.0))


def test_engine_rejects_mixed_hierarchy(monkeypatch):
    """One rank with a non-contiguous packing vetoes hierarchical on
    every rank (collective AND), so no rank diverges onto a different
    data-plane algorithm."""
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
    group = ThreadedGroup(2)
    topos = [(0, 2, 0, 1), (0, 1, 1, 2)]  # inconsistent packing
    engines = [
        Engine(rank=r, size=2, backend=group.backend(r),
               local_rank=topos[r][0], local_size=topos[r][1],
               cross_rank=topos[r][2], cross_size=topos[r][3])
        for r in range(2)
    ]
    for e in engines:
        e.cycle_time_s = 0.001
        e.start()
    try:
        # Run one allreduce so both loops have passed the agreement.
        def worker(r):
            h = engines[r].enqueue_allreduce(
                np.ones(4, np.float32), name="t"
            )
            engines[r].synchronize(h, timeout=30)

        ts = [threading.Thread(target=worker, args=(r,)) for r in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        for e in engines:
            assert not e.backend.hierarchical
    finally:
        stop = [threading.Thread(target=e.shutdown) for e in engines]
        for t in stop:
            t.start()
        for t in stop:
            t.join(timeout=60)


def test_autotune_categorical_arms():
    """The tuner cycles (hierarchical, cache) arms and pins the best
    combination at the end."""
    pm = ParameterManager(
        is_coordinator=True, enabled=True, warmup_samples=0,
        cycles_per_sample=1, max_samples=8, tune_hierarchical=True,
    )
    assert len(pm._arms) == 4
    seen = set()
    # Score arms so (hierarchical=True, cache=True) wins decisively.
    while not pm.done:
        seen.add((pm.hierarchical, pm.cache_enabled))
        score = 100.0 if (pm.hierarchical and pm.cache_enabled) else 1.0
        pm._on_sample(score)
    assert seen == {(False, True), (False, False), (True, True),
                    (True, False)}  # rotated through every arm
    assert pm.hierarchical is True
    assert pm.cache_enabled is True


def test_autotune_serialize_roundtrip_categorical():
    pm = ParameterManager(is_coordinator=True, enabled=True,
                          tune_hierarchical=True)
    pm.hierarchical = True
    pm.cache_enabled = False
    pm.fusion_threshold = 123456
    pm.cycle_time_ms = 7.5
    pm.done = True
    other = ParameterManager(is_coordinator=False, enabled=True)
    other.apply(pm.serialize())
    assert other.hierarchical is True
    assert other.cache_enabled is False
    assert other.fusion_threshold == 123456
    assert other.cycle_time_ms == 7.5
    assert other.done is True


@pytest.mark.parametrize("dims", [[2, 0, 3, 1], [5, 5, 5, 5]])
def test_hierarchical_allgatherv(dims, monkeypatch):
    """Two-level allgather matches the flat result, incl. a zero-row
    rank (ref: MPIHierarchicalAllgather, mpi_operations.cc:190)."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)

    def fn(b, r):
        b.hier_allgather = True
        arr = np.full((dims[r], 3), float(r), np.float32)
        return b.allgatherv(arr, list(dims))

    out = _run_backend_ranks(4, _topo_2x2, fn)
    expect = np.concatenate(
        [np.full((dims[r], 3), float(r), np.float32) for r in range(4)]
    )
    for o in out:
        np.testing.assert_allclose(o, expect)


def test_engine_hierarchical_allgather_end_to_end(monkeypatch, tmp_path):
    """HOROVOD_HIERARCHICAL_ALLGATHER=1 on a 2x2 world: the engine
    selects the two-level op (timeline shows HIERARCHICAL_ALLGATHER)."""
    import json
    import os
    import sys

    sys.path.insert(0, os.path.dirname(__file__))

    path = tmp_path / "tl.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(path))
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "64")
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLGATHER", "1")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)

    group = ThreadedGroup(4)
    engines = [
        Engine(rank=r, size=4, backend=group.backend(r),
               local_rank=r % 2, local_size=2,
               cross_rank=r // 2, cross_size=2)
        for r in range(4)
    ]
    for e in engines:
        e.cycle_time_s = 0.001
        e.start()
    results = [None] * 4
    errors = [None] * 4

    def worker(r):
        try:
            arr = np.full((r + 1, 50), float(r), np.float32)
            results[r] = engines[r].synchronize(
                engines[r].enqueue_allgather(arr, name="g"), timeout=30)
        except BaseException as ex:  # noqa: BLE001
            errors[r] = ex

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    stop = [threading.Thread(target=e.shutdown) for e in engines]
    for t in stop:
        t.start()
    for t in stop:
        t.join(timeout=60)
    for e in errors:
        if e is not None:
            raise e
    expect = np.concatenate([
        np.full((r + 1, 50), float(r), np.float32) for r in range(4)
    ])
    for o in results:
        np.testing.assert_allclose(o, expect)
    events = json.loads(path.read_text())
    assert "HIERARCHICAL_ALLGATHER" in {e.get("name") for e in events}


def test_hierarchical_allgather_scalar_falls_back(monkeypatch):
    """0-d (scalar) allgathers use stack semantics the two-level path
    doesn't implement: the engine must select ring/star instead."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLGATHER", "1")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)

    group = ThreadedGroup(4)
    engines = [
        Engine(rank=r, size=4, backend=group.backend(r),
               local_rank=r % 2, local_size=2,
               cross_rank=r // 2, cross_size=2)
        for r in range(4)
    ]
    for e in engines:
        e.cycle_time_s = 0.001
        e.start()
    results = [None] * 4
    errors = [None] * 4

    def worker(r):
        try:
            results[r] = engines[r].synchronize(
                engines[r].enqueue_allgather(
                    np.float32(r), name="s"), timeout=30)
        except BaseException as ex:  # noqa: BLE001
            errors[r] = ex

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    stop = [threading.Thread(target=e.shutdown) for e in engines]
    for t in stop:
        t.start()
    for t in stop:
        t.join(timeout=60)
    for e in errors:
        if e is not None:
            raise e
    for o in results:
        np.testing.assert_allclose(np.ravel(o),
                                   np.arange(4, dtype=np.float32))


# ---------------------------------------------------------------------------
# leader-based two-level schedule (HOROVOD_HIERARCHICAL_MODE=leader):
# intra-host reduce-scatter -> gather to the host leader -> ONE
# segmented inter-host ring between leaders -> intra-host bcast.
@pytest.mark.parametrize("size,topo", [
    (4, lambda r: (r % 2, 2, r // 2, 2)),
    (6, lambda r: (r % 3, 3, r // 3, 2)),
    (8, lambda r: (r % 2, 2, r // 2, 4)),
])
def test_leader_hierarchical_matches_sum(size, topo, monkeypatch):
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "leader")
    n = 4099

    def fn(b, r):
        arr = np.arange(n, dtype=np.float64) + r * 10.0
        return b._hierarchical_allreduce(arr, ReduceOp.SUM)

    results = _run_backend_ranks(size, topo, fn)
    want = (np.arange(n, dtype=np.float64) * size
            + 10.0 * sum(range(size)))
    for r in range(size):
        np.testing.assert_allclose(results[r], want)


@pytest.mark.parametrize("n", [1, 2, 3, 5])
def test_leader_hierarchical_tiny_and_average(n, monkeypatch):
    """Element counts below the group size exercise empty owned slices
    on both the member-send and leader-gather sides — the skip logic
    must agree or the exchange deadlocks."""
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "leader")

    def fn(b, r):
        return b._hierarchical_allreduce(
            np.full(n, float(r + 1)), ReduceOp.AVERAGE)

    results = _run_backend_ranks(4, _topo_2x2, fn)
    for r in range(4):
        np.testing.assert_allclose(results[r], 2.5)


# ---------------------------------------------------------------------------
# host-scoped arena legs for the leader schedule (HOROVOD_HIER_ARENA):
# fused gather-reduce to the leader + overlapped bcast through the
# per-host shm arena instead of the per-pair rings.

def _arena_backends(size, L, tmp_path, slot_bytes=4096):
    """ThreadedGroup backends with per-host ShmArenaSets attached and
    the (normally engine-agreed) arena capability bit set — the same
    hand-wiring the other backend-level tests use for toggles."""
    from horovod_tpu.backend.shm import ShmArenaSet

    group = ThreadedGroup(size)
    backends = []
    for r in range(size):
        b = group.backend(r)
        b.set_topology(r % L, L, r // L, size // L)
        b.hierarchical = True
        b.arena_hier_ok = True
        host = r // L
        local_group = list(range(host * L, host * L + L))
        b.arena_set = ShmArenaSet(
            str(tmp_path), "t", "n0", group=local_group, rank=r,
            slot_bytes=slot_bytes)
        backends.append(b)
    return backends


def _run_ranks(backends, fn, timeout=60):
    size = len(backends)
    results = [None] * size
    errors = [None] * size

    def worker(r):
        try:
            results[r] = fn(backends[r], r)
        except BaseException as e:  # noqa: BLE001
            errors[r] = e

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    return results, errors


@pytest.mark.parametrize("size,L", [(4, 2), (6, 3), (8, 2), (8, 4)])
@pytest.mark.parametrize("n", [4099, 5])
def test_leader_arena_matches_sum(size, L, n, monkeypatch, tmp_path):
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "leader")
    monkeypatch.delenv("HOROVOD_HIER_ARENA", raising=False)
    monkeypatch.delenv("HOROVOD_TRANSPORT", raising=False)
    backends = _arena_backends(size, L, tmp_path)

    def fn(b, r):
        arr = np.arange(n, dtype=np.float64) + r * 10.0
        return b._hierarchical_allreduce(arr, ReduceOp.SUM)

    results, errors = _run_ranks(backends, fn)
    for e in errors:
        if e is not None:
            raise e
    want = (np.arange(n, dtype=np.float64) * size
            + 10.0 * sum(range(size)))
    for r in range(size):
        np.testing.assert_allclose(results[r], want)
    # The legs really rode the arena (not a silent ring fallback).
    arenas = backends[0].arena_set._arenas
    assert arenas and all(a._gen > 0 for a in arenas.values())


@pytest.mark.parametrize("n", [0, 1, 2, 3, 5])
def test_leader_arena_tiny_and_average(n, monkeypatch, tmp_path):
    """Element counts below the group size exercise empty chunks and
    empty segment ranges on both the deposit and replay sides — the
    range sequences must agree or the session deadlocks."""
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "leader")
    backends = _arena_backends(4, 2, tmp_path)

    def fn(b, r):
        return b._hierarchical_allreduce(
            np.full(n, float(r + 1)), ReduceOp.AVERAGE)

    results, errors = _run_ranks(backends, fn)
    for e in errors:
        if e is not None:
            raise e
    for r in range(4):
        np.testing.assert_allclose(results[r], np.full(n, 2.5))


def test_leader_arena_input_never_mutated(monkeypatch, tmp_path):
    """The arena legs read the input and write a separate output, so a
    caller-owned tensor survives unmutated — the defensive copy the
    ring schedules must take disappears here (like the whole-world
    arena plane)."""
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "leader")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "256")
    backends = _arena_backends(4, 2, tmp_path)
    inputs = [np.arange(1000, dtype=np.float32) + r for r in range(4)]
    keep = [a.copy() for a in inputs]

    def fn(b, r):
        return b._hierarchical_allreduce(inputs[r], ReduceOp.SUM,
                                         owned=False)

    results, errors = _run_ranks(backends, fn)
    for e in errors:
        if e is not None:
            raise e
    want = sum(inputs)
    for r in range(4):
        np.testing.assert_allclose(results[r], want)
        np.testing.assert_array_equal(inputs[r], keep[r])


def test_leader_arena_bitwise_under_compression(monkeypatch, tmp_path):
    """Compressed leader-arena schedule: the inter-host ring narrows to
    bf16 (with the allgather grid projection), the arena legs stay
    full-width memcpys — every rank must finish BITWISE identical."""
    from horovod_tpu.backend.base import wire_codec_scope
    from horovod_tpu.common import compression as C

    bf16 = C.codec_by_name("bf16")
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "leader")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "256")
    backends = _arena_backends(4, 2, tmp_path)

    def fn(b, r):
        rng = np.random.RandomState(r)
        x = rng.rand(3001).astype(np.float32)
        with wire_codec_scope(bf16):
            return b._hierarchical_allreduce(x, ReduceOp.SUM)

    results, errors = _run_ranks(backends, fn)
    for e in errors:
        if e is not None:
            raise e
    for r in range(1, 4):
        assert np.array_equal(results[0], results[r]), (
            f"rank {r} diverged under compression")


def test_leader_arena_wedged_leader_raises_verdict(monkeypatch, tmp_path):
    """Chaos contract (docs/fault_tolerance.md): a host leader wedged
    mid-arena-leg parks its members on arena barriers and its peer
    leader in the inter-host ring; when the liveness verdict lands
    (dead_cb / declare_dead — heartbeats ride TCP), EVERY survivor
    raises the attributed TransportError promptly — no parked arena
    barrier outlives the verdict."""
    from horovod_tpu.backend.transport import make_inproc_backends
    from horovod_tpu.backend.shm import ShmArenaSet
    from horovod_tpu.common.exceptions import TransportError

    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "leader")
    verdict = {"reason": None}
    size, L = 4, 2
    backends = make_inproc_backends(size)
    for r in range(size):
        b = backends[r]
        b.set_topology(r % L, L, r // L, size // L)
        b.hierarchical = True
        b.arena_hier_ok = True
        host = r // L
        local_group = list(range(host * L, host * L + L))
        b.arena_set = ShmArenaSet(
            str(tmp_path), "t", "n0", group=local_group, rank=r,
            slot_bytes=4096)
        b.arena_set.dead_cb = lambda: verdict["reason"]
        b._arena_dead_reason = lambda: verdict["reason"]

    errors = [None] * size

    def worker(r):
        if r == 0:
            return  # the wedged leader: never enters the collective
        try:
            backends[r]._hierarchical_allreduce(
                np.ones(100000, np.float32), ReduceOp.SUM)
        except BaseException as e:  # noqa: BLE001
            errors[r] = e

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(1, size)]
    for t in threads:
        t.start()
    time.sleep(0.4)
    reason = ("rank 0 (host hostA) declared dead by rank 1: "
              "no heartbeat for 2.0s")
    verdict["reason"] = reason
    for r in range(1, size):
        backends[r].declare_dead(0, reason)
    for t in threads:
        t.join(timeout=15)
    assert not any(t.is_alive() for t in threads), (
        "a survivor's arena barrier outlived the verdict")
    for r in range(1, size):
        assert errors[r] is not None, f"rank {r} did not raise"
        assert isinstance(errors[r], TransportError), errors[r]
        assert reason in str(errors[r]), (r, errors[r])
    for b in backends:
        b.shutdown()


def test_host_arena_gating(monkeypatch, tmp_path):
    """_host_arena engages only with the agreed capability bit, an
    exactly-matching group, and per-call knobs still routing intra-host
    data to shared memory."""
    from horovod_tpu.backend.shm import ShmArenaSet

    monkeypatch.delenv("HOROVOD_HIER_ARENA", raising=False)
    monkeypatch.delenv("HOROVOD_TRANSPORT", raising=False)
    backends = _arena_backends(4, 2, tmp_path)
    b = backends[0]
    assert b._host_arena([0, 1]) is b.arena_set
    assert b._host_arena([0, 1, 2]) is None       # group mismatch
    b.arena_hier_ok = False
    assert b._host_arena([0, 1]) is None          # no agreed bit
    b.arena_hier_ok = True
    monkeypatch.setenv("HOROVOD_HIER_ARENA", "off")
    assert b._host_arena([0, 1]) is None          # legs pinned off
    monkeypatch.setenv("HOROVOD_HIER_ARENA", "auto")
    monkeypatch.setenv("HOROVOD_TRANSPORT", "tcp")
    assert b._host_arena([0, 1]) is None          # shm routed off
    monkeypatch.setenv("HOROVOD_TRANSPORT", "auto")
    assert b._host_arena([0, 1]) is b.arena_set


def test_engine_leader_arena_end_to_end(monkeypatch, tmp_path):
    """4 engines, 2x2 topology, injected host arenas + a local arena
    vote: the engine's AND-agreed capability word sets arena_hier_ok on
    every rank, and the negotiated leader-mode path produces correct
    sums over the arena legs."""
    from horovod_tpu.backend.shm import ShmArenaSet

    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "leader")
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "64")
    monkeypatch.delenv("HOROVOD_HIER_ARENA", raising=False)
    group = ThreadedGroup(4)
    engines = []
    for r in range(4):
        b = group.backend(r)
        host = r // 2
        b.arena_set = ShmArenaSet(
            str(tmp_path), "t", "n0",
            group=[host * 2, host * 2 + 1], rank=r, slot_bytes=4096)
        b.prefers_arena_hierarchy = lambda: True
        e = Engine(rank=r, size=4, backend=b,
                   local_rank=r % 2, local_size=2,
                   cross_rank=r // 2, cross_size=2)
        e.cycle_time_s = 0.001
        engines.append(e)
    for e in engines:
        e.start()
    results = [None] * 4
    errors = [None] * 4

    def worker(r):
        try:
            eng = engines[r]
            outs = []
            for i in range(3):
                h = eng.enqueue_allreduce(
                    np.full(300, float(r + 1), np.float32), name=f"a{i}")
                outs.append(eng.synchronize(h, timeout=30))
            results[r] = outs
        except BaseException as e:  # noqa: BLE001
            errors[r] = e

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    for e in engines:
        assert e.backend.arena_hier_ok, "capability bit not agreed"
    arenas = engines[0].backend.arena_set._arenas
    assert arenas and all(a._gen > 0 for a in arenas.values()), (
        "arena legs never ran through the engine")
    stop = [threading.Thread(target=e.shutdown) for e in engines]
    for t in stop:
        t.start()
    for t in stop:
        t.join(timeout=60)
    for e in errors:
        if e is not None:
            raise e
    for r in range(4):
        for o in results[r]:
            np.testing.assert_allclose(o, np.full(300, 10.0))


def test_hier_arena_setting_parse(monkeypatch):
    from horovod_tpu.utils import env as env_cfg

    monkeypatch.delenv("HOROVOD_HIER_ARENA", raising=False)
    monkeypatch.delenv("HVD_TPU_HIER_ARENA", raising=False)
    assert env_cfg.hier_arena_setting() == "auto"
    for v, want in [("off", "off"), ("0", "off"), ("false", "off"),
                    ("no", "off"), ("auto", "auto"), ("1", "auto"),
                    ("bogus", "auto")]:
        monkeypatch.setenv("HOROVOD_HIER_ARENA", v)
        assert env_cfg.hier_arena_setting() == want, v
    monkeypatch.delenv("HOROVOD_HIER_ARENA", raising=False)
    monkeypatch.setenv("HVD_TPU_HIER_ARENA", "off")
    assert env_cfg.hier_arena_setting() == "off"


def test_hierarchical_mode_resolution(monkeypatch):
    """auto resolves through the ENGINE-agreed leader_hier_ok flag
    (never a per-rank local answer); explicit values win outright."""
    from horovod_tpu.backend.ring import hierarchical_mode

    class B:
        leader_hier_ok = False

    b = B()
    monkeypatch.delenv("HOROVOD_HIERARCHICAL_MODE", raising=False)
    assert hierarchical_mode(b) == "slice"
    b.leader_hier_ok = True
    assert hierarchical_mode(b) == "leader"
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "slice")
    assert hierarchical_mode(b) == "slice"
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "leader")
    b.leader_hier_ok = False
    assert hierarchical_mode(b) == "leader"
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_MODE", "bogus")
    assert hierarchical_mode(b) == "slice"  # auto fallback, flag off


def test_hierarchical_allreduce_setting(monkeypatch):
    from horovod_tpu.utils import env as env_cfg

    for v, want in [("", "off"), ("0", "off"), ("false", "off"),
                    ("off", "off"), ("1", "on"), ("true", "on"),
                    ("auto", "auto")]:
        if v:
            monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", v)
        else:
            monkeypatch.delenv("HOROVOD_HIERARCHICAL_ALLREDUCE",
                               raising=False)
        assert env_cfg.hierarchical_allreduce_setting() == want, v


def test_hierarchical_auto_engages_on_valid_topology(monkeypatch):
    """HOROVOD_HIERARCHICAL_ALLREDUCE=auto turns the two-level path on
    exactly when the agreed topology is hierarchical: the engine's
    allreduce dispatch must pick the hierarchical plane."""
    from horovod_tpu.backend.ring import hierarchical_eligible

    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "auto")
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")

    def fn(b, r):
        # Engine wiring equivalent: valid topology + setting != off.
        from horovod_tpu.utils import env as env_cfg

        b.hierarchical = env_cfg.hierarchical_allreduce_setting() != "off"
        return hierarchical_eligible(b, 1 << 20, ReduceOp.SUM)

    assert all(_run_backend_ranks(4, _topo_2x2, fn))
