"""Liveness plane (ISSUE 5): heartbeat failure detection, root-cause
attribution, and bounded-time elastic recovery.

Fast tests (tier-1): detector miss-limit math, monitor end-to-end over
real TCP backends (silent-worker declaration, coordinator-death
symmetry, healthy-mesh no-false-positives), dead-declaration broadcast
through real engines, wedge/hang fault rules, TransportError
attribution, notification-manager shutdown, rendezvous delete retry,
reset-timeout knob. The subprocess wedge chaos test (wedge — not kill —
1 of 4 elastic workers, plus the heartbeats-disabled hang control) is
marked `slow`.
"""
import os
import pickle
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common import fault_injection, health
from horovod_tpu.common.exceptions import (
    HorovodInternalError,
    TransportError,
)
from horovod_tpu.common.fault_injection import FaultInjector, Rule, parse_spec
from horovod_tpu.common.health import FailureDetector, HeartbeatMonitor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injection.injector.clear()
    yield
    fault_injection.injector.clear()


# ---------------------------------------------------------------------------
# TransportError attribution fields
def test_transport_error_attribution_fields():
    e = TransportError("rank 2 died", peer=2, reporter=0,
                       root_cause="liveness verdict")
    assert isinstance(e, HorovodInternalError)
    assert (e.peer, e.reporter, e.root_cause) == (2, 0, "liveness verdict")
    assert e.phase is None
    e.phase = "allreduce"
    assert str(e) == "rank 2 died (during allreduce)"


def test_transport_error_message_only_still_works():
    e = TransportError("plain")
    assert str(e) == "plain" and e.peer is None and e.root_cause is None


# ---------------------------------------------------------------------------
# FailureDetector: pure miss-limit math
def test_detector_miss_limit_math():
    det = FailureDetector([1, 2], interval=1.0, miss_limit=5, now=100.0)
    assert det.window == 5.0
    det.note(1, now=103.0)
    # rank 2 silent since arming (t=100): not yet past the window...
    assert det.check(now=104.9) == []
    # ...then past it; rank 1 (heard at 103) survives.
    newly = det.check(now=105.1)
    assert [p for p, _ in newly] == [2]
    assert newly[0][1] == pytest.approx(5.1)
    assert det.age(1, now=105.1) == pytest.approx(2.1)


def test_detector_declares_each_peer_once():
    det = FailureDetector([1], interval=0.5, miss_limit=2, now=0.0)
    assert [p for p, _ in det.check(now=1.5)] == [1]
    assert det.check(now=10.0) == []          # latched
    assert 1 in det.dead


def test_detector_note_never_moves_time_backwards():
    det = FailureDetector([1], interval=1.0, miss_limit=3, now=50.0)
    det.note(1, now=60.0)
    det.note(1, now=55.0)  # stale activity timestamp must not regress
    assert det.age(1, now=61.0) == pytest.approx(1.0)


def test_detector_zero_is_never_watched():
    det = FailureDetector([], interval=1.0, miss_limit=1, now=0.0)
    assert det.check(now=1e9) == []


# ---------------------------------------------------------------------------
# wedge / hang fault rules
def test_parse_wedge_and_hang_rules():
    rules = parse_spec("wedge:step=3;hang:peer=1:op=recv:after=2")
    assert rules[0].action == "wedge" and rules[0].step == 3
    assert rules[1].action == "hang" and rules[1].peer == 1
    assert rules[1].op == "recv" and rules[1].after == 2


def test_parse_wedge_requires_step():
    with pytest.raises(ValueError, match="wedge rule needs step"):
        parse_spec("wedge")


def test_wedge_fires_at_step_and_freezes_io():
    inj = FaultInjector()
    inj.install([Rule(action="wedge", step=2)])
    done = []

    def stepper():
        inj.advance_step()          # step 1: survives
        done.append(1)
        inj.advance_step()          # step 2: parks forever
        done.append(2)              # never reached

    t = threading.Thread(target=stepper, daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while not inj.wedged and time.monotonic() < deadline:
        time.sleep(0.01)
    assert inj.wedged
    t.join(timeout=0.3)
    assert t.is_alive() and done == [1]
    # All I/O of the wedged process freezes too (sockets stay open, the
    # bytes just stop) — exercised via a side thread that never returns.
    io_done = []

    def io():
        inj.check_io(0, 1, "send")
        io_done.append(1)

    t2 = threading.Thread(target=io, daemon=True)
    t2.start()
    t2.join(timeout=0.3)
    assert t2.is_alive() and not io_done


def test_step_rules_honor_rank_targeting(monkeypatch):
    """rank=R confines the job-wide env var to one rank's process
    (module contract): everyone else keeps stepping."""
    monkeypatch.setenv("HOROVOD_RANK", "1")
    inj = FaultInjector()
    inj.install([Rule(action="wedge", step=1, rank=2)])
    assert inj.advance_step() == 1    # not rank 2: survives
    assert inj.advance_step() == 2
    assert not inj.wedged
    # The targeted rank wedges at its step.
    monkeypatch.setenv("HOROVOD_RANK", "2")
    inj2 = FaultInjector()
    inj2.install([Rule(action="wedge", step=1, rank=2)])
    t = threading.Thread(target=inj2.advance_step, daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while not inj2.wedged and time.monotonic() < deadline:
        time.sleep(0.01)
    assert inj2.wedged


def test_hang_rule_blocks_only_matching_io():
    inj = FaultInjector()
    inj.install([Rule(action="hang", peer=1, op="recv")])
    # Non-matching I/O flows.
    assert inj.check_io(0, 1, "send") == fault_injection.PASS
    assert inj.check_io(0, 2, "recv") == fault_injection.PASS
    hung = []

    def io():
        inj.check_io(0, 1, "recv")
        hung.append(1)

    t = threading.Thread(target=io, daemon=True)
    t.start()
    t.join(timeout=0.3)
    assert t.is_alive() and not hung
    # ...and other I/O still flows while one is parked (the hang must
    # not hold the injector lock).
    assert inj.check_io(0, 1, "send") == fault_injection.PASS


# ---------------------------------------------------------------------------
# heartbeat monitor over real TCP backends
def _tcp_mesh(scope, monkeypatch, n=2):
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.backend.tcp import TcpBackend
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    monkeypatch.setenv("HVDRUN_FORCE_LOCAL", "1")
    server = RendezvousServer()
    port = server.start()
    rdv = RendezvousClient("127.0.0.1", port)
    backends = [None] * n
    errs = []

    def build(rank):
        try:
            backends[rank] = TcpBackend(rank, n, rendezvous=rdv, scope=scope)
        except BaseException as e:  # pragma: no cover - bootstrap bug
            errs.append(e)

    threads = [threading.Thread(target=build, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs, errs
    assert all(b is not None for b in backends)
    return server, backends


def _wait_for(cond, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    pytest.fail(f"timed out waiting for {what}")


def test_drain_never_consumes_partial_frame(monkeypatch):
    """A frame still arriving must not be consumed — or its peer
    severed — by the idle drain: its byte-count growth counts as
    progress evidence, and the complete frame drains intact once it
    lands. Severing after one stalled read would contradict the
    documented miss_limit x interval tolerance."""
    from horovod_tpu.backend.base import CTRL_CHANNEL
    from horovod_tpu.backend.tcp import _HDR

    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "5")
    server, (b0, b1) = _tcp_mesh("t_drain_partial", monkeypatch)
    try:
        payload = b"p" * 100
        raw = b0.peers[1]  # rank 0's socket to rank 1, driven by hand
        raw.sendall(_HDR.pack(len(payload), CTRL_CHANNEL) + payload[:50])
        # The arriving bytes are stashed and counted as progress
        # evidence; no complete frame, no sever.
        _wait_for(lambda: (b1.try_drain_idle(0) == 0
                           and b1.peer_activity(0) is not None),
                  what="partial-frame progress evidence")
        assert b1.peers.get(0) is not None      # not severed
        assert b1.death_reason(0) is None
        act1 = b1.peer_activity(0)
        # Stalled (no new bytes): no fresh evidence, still no sever.
        assert b1.try_drain_idle(0) == 0
        assert b1.peer_activity(0) == act1
        assert b1.peers.get(0) is not None
        # A normal reader arriving first completes the stash and gets
        # its frame from the inbox re-check.
        raw.sendall(payload[50:])
        got = b1.recv_from(0)
        assert bytes(got) == payload
        assert b1.peer_activity(0) > act1
        # And the pure-drain completion path: stash started by one
        # drain, finished by a later one.
        p2 = b"q" * 40
        raw.sendall(_HDR.pack(len(p2), CTRL_CHANNEL) + p2[:10])
        _wait_for(lambda: (b1.try_drain_idle(0) == 0
                           and len(b1._demux_for(0).partial) == 19),
                  what="second partial stashed")
        raw.sendall(p2[10:])
        _wait_for(lambda: b1.try_drain_idle(0) == 1,
                  what="completed frame drained")
        assert bytes(b1._demux_for(0).inbox[CTRL_CHANNEL].popleft()) == p2
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_monitor_declares_silent_worker_and_attributes(monkeypatch):
    """A worker whose process is alive (socket open, kernel ACKing) but
    silent must be declared dead within miss_limit x interval — with
    HOROVOD_TCP_TIMEOUT_SECONDS=0 — and every later TransportError must
    carry the verdict, not 'connection reset'. The verdict also lands
    in the rendezvous KV for the elastic driver."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "0")
    server, (b0, b1) = _tcp_mesh("t_hb_silent", monkeypatch)
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(server.port))
    mon = HeartbeatMonitor(b0, rank=0, size=2, interval=0.1, miss_limit=3)
    mon.start()
    try:
        t0 = time.monotonic()
        _wait_for(lambda: mon.verdicts, what="dead declaration")
        # Bounded: well within a few windows (window = 0.3s).
        assert time.monotonic() - t0 < 10 * mon.window + 2.0
        reason = mon.verdicts[1]
        assert "rank 1" in reason and "declared dead" in reason
        assert "HOROVOD_HEARTBEAT_MISS_LIMIT" in reason
        # Root cause latched on the transport:
        assert b0.death_reason(1) == reason
        with pytest.raises(TransportError) as ei:
            b0.recv_from(1)
        assert str(ei.value) == reason
        assert ei.value.peer == 1 and ei.value.root_cause == reason
        # KV verdict for the elastic driver's eviction fast path (the
        # HTTP put is async relative to the in-memory verdict).
        _wait_for(lambda: server.handle_get("health/verdict_e0") is not None,
                  what="KV verdict")
        assert server.handle_get("health/verdict_e0").decode().startswith("1|")
    finally:
        mon.stop()
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_monitor_healthy_mesh_no_false_positives(monkeypatch):
    """Two live monitors beating each other across several windows:
    nobody is declared dead."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "0")
    server, (b0, b1) = _tcp_mesh("t_hb_ok", monkeypatch)
    m0 = HeartbeatMonitor(b0, rank=0, size=2, interval=0.05, miss_limit=4)
    m1 = HeartbeatMonitor(b1, rank=1, size=2, interval=0.05, miss_limit=4)
    m0.start()
    m1.start()
    try:
        time.sleep(8 * m0.window)  # many windows
        assert not m0.verdicts and not m1.verdicts
        assert not m0.detector.dead and not m1.detector.dead
        # Beats flowed and were consumed.
        assert m0._m_recv.value > 0 and m1._m_recv.value > 0
    finally:
        m0.stop()
        m1.stop()
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_worker_declares_dead_coordinator_symmetric(monkeypatch):
    """Missing acks: the worker-side detector declares the coordinator
    dead, severs the socket, and names it in the verdict."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "0")
    server, (b0, b1) = _tcp_mesh("t_hb_coord", monkeypatch)
    mon = HeartbeatMonitor(b1, rank=1, size=2, interval=0.1, miss_limit=3)
    mon.start()
    try:
        _wait_for(lambda: mon.verdicts, what="coordinator declaration")
        reason = mon.verdicts[0]
        assert "coordinator" in reason and "rank 0" in reason
        with pytest.raises(TransportError, match="coordinator"):
            b1.recv_from(0)
    finally:
        mon.stop()
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_heartbeats_survive_active_collectives(monkeypatch):
    """Heartbeat frames interleave with data frames on the same socket
    (HEALTH_CHANNEL tag): a mesh busy with ring allreduces must neither
    corrupt payloads nor declare anyone dead."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "0")
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    server, (b0, b1) = _tcp_mesh("t_hb_busy", monkeypatch)
    m0 = HeartbeatMonitor(b0, rank=0, size=2, interval=0.03, miss_limit=5)
    m1 = HeartbeatMonitor(b1, rank=1, size=2, interval=0.03, miss_limit=5)
    m0.start()
    m1.start()
    try:
        results, errors = [None, None], [None, None]

        def w(i, b):
            try:
                for _ in range(20):
                    x = np.arange(4096, dtype=np.float32) * (i + 1)
                    results[i] = b.allreduce(x)
            except BaseException as e:  # noqa: BLE001
                errors[i] = e

        ts = [threading.Thread(target=w, args=(i, b))
              for i, b in ((0, b0), (1, b1))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert errors == [None, None], errors
        np.testing.assert_allclose(
            results[0], np.arange(4096, dtype=np.float32) * 3)
        assert not m0.verdicts and not m1.verdicts
    finally:
        m0.stop()
        m1.stop()
        b0.shutdown()
        b1.shutdown()
        server.stop()


# ---------------------------------------------------------------------------
# dead-declaration broadcast: the verdict reaches every survivor through
# the negotiation plane (the stall-abort path), tensor-less ERROR +
# shutdown, with the attributed reason.
def _tcp_engines(scope, monkeypatch, n=3):
    from horovod_tpu.engine.engine import Engine

    server, backends = _tcp_mesh(scope, monkeypatch, n=n)
    engines = [Engine(rank=r, size=n, backend=backends[r])
               for r in range(n)]
    for e in engines:
        e.cycle_time_s = 0.002
    errs = []

    def _start(e):
        try:
            e.start()
        except BaseException as exc:  # pragma: no cover - init bug
            errs.append(exc)

    ts = [threading.Thread(target=_start, args=(e,)) for e in engines]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs, errs
    return server, backends, engines


def _shutdown_engines(engines):
    ts = [threading.Thread(target=e.shutdown) for e in engines]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)


def test_dead_declaration_broadcast_reaches_survivors(monkeypatch):
    """3 real engines; the liveness plane declares rank 2 dead on the
    coordinator. Ranks 0 AND 1 must fail their next collective with the
    attributed verdict ('rank 2 ... declared dead'), broadcast as a
    tensor-less ERROR — rank 1 never touched rank 2's socket."""
    monkeypatch.setenv("HOROVOD_HEARTBEAT_INTERVAL_SECONDS", "0")
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "0")
    server, backends, engines = _tcp_engines("t_bcast", monkeypatch)
    try:
        # Healthy round first (mesh + cache warm).
        outs = [None] * 3

        def ar(i):
            h = engines[i].enqueue_allreduce(
                np.ones(4, np.float32), name="warm")
            outs[i] = engines[i].synchronize(h, timeout=30)

        ts = [threading.Thread(target=ar, args=(i,)) for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert all(o is not None and float(o[0]) == 3.0 for o in outs)

        # The coordinator's detector declares rank 2 dead (this is
        # exactly what HeartbeatMonitor._declare_dead does).
        reason = ("rank 2 (host hostC) declared dead by rank 0: no "
                  "heartbeat or traffic for 2.0s (> "
                  "HOROVOD_HEARTBEAT_MISS_LIMIT=4 x "
                  "HOROVOD_HEARTBEAT_INTERVAL_SECONDS=0.5)")
        backends[0].declare_dead(2, reason)

        errs = [None, None]

        def ar_fail(i):
            try:
                h = engines[i].enqueue_allreduce(
                    np.ones(4, np.float32), name="post")
                engines[i].synchronize(h, timeout=30)
            except HorovodInternalError as e:
                errs[i] = str(e)

        t0 = time.monotonic()
        ts = [threading.Thread(target=ar_fail, args=(i,)) for i in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert time.monotonic() - t0 < 20, "not bounded"
        for i in (0, 1):
            assert errs[i] is not None, f"rank {i} hung"
            assert "rank 2" in errs[i] and "declared dead" in errs[i], (
                i, errs[i])
    finally:
        _shutdown_engines(engines)
        server.stop()


def test_engine_starts_and_stops_monitor(monkeypatch):
    """Engines over TCP arm the liveness plane when enabled, expose it
    in /status, and tear the monitor thread down on shutdown."""
    monkeypatch.setenv("HOROVOD_HEARTBEAT_INTERVAL_SECONDS", "0.05")
    monkeypatch.setenv("HOROVOD_HEARTBEAT_MISS_LIMIT", "50")
    server, backends, engines = _tcp_engines("t_mon_life", monkeypatch, n=2)
    try:
        # The monitor arms on the background thread after init returns.
        _wait_for(lambda: all(e._health is not None for e in engines),
                  what="monitors armed")
        _wait_for(lambda: engines[1]._health._m_sent.value > 0,
                  what="worker beats")
        st = engines[0].status()
        assert st["health"]["role"] == "coordinator"
        assert "1" in st["health"]["peers"]
        assert st["health"]["dead"] == {}
        monitors = [e._health for e in engines]
    finally:
        _shutdown_engines(engines)
        server.stop()
    for mon in monitors:
        assert not mon._thread.is_alive(), "monitor thread leaked"


def test_engine_monitor_disabled_by_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_HEARTBEAT_MISS_LIMIT", "0")
    server, backends, engines = _tcp_engines("t_mon_off", monkeypatch, n=2)
    try:
        # A completed collective proves the background loops are well
        # past the would-be monitor arm point.
        outs = [None, None]

        def ar(i):
            h = engines[i].enqueue_allreduce(np.ones(2, np.float32),
                                             name="warm")
            outs[i] = engines[i].synchronize(h, timeout=30)

        ts = [threading.Thread(target=ar, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert all(o is not None for o in outs)
        for e in engines:
            assert e._health is None
            assert "health" not in e.status()
    finally:
        _shutdown_engines(engines)
        server.stop()


# ---------------------------------------------------------------------------
# satellites: notification-manager shutdown, rendezvous delete retry,
# reset-timeout knob
def test_notification_manager_shutdown_stops_threads(monkeypatch):
    from horovod_tpu.backend.elastic_env import WorkerNotificationManager
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    server = RendezvousServer()
    port = server.start()
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(port))
    monkeypatch.setenv("HOROVOD_ELASTIC", "1")
    monkeypatch.setenv("HOROVOD_HOSTNAME", "localhost")
    monkeypatch.setenv("HOROVOD_ELASTIC_EPOCH_POLL", "0.05")
    try:
        mgr = WorkerNotificationManager()

        class _L:
            def __init__(self):
                self.hits = []

            def on_hosts_updated(self, ts, res):
                self.hits.append((ts, res))

        listener = _L()
        mgr.register_listener(listener)
        before = set(threading.enumerate())
        mgr.init()
        started = set(threading.enumerate()) - before
        assert mgr._httpd is not None
        assert {t.name for t in started} >= {"hvd-notify", "hvd-epoch-watch"}
        # The notify endpoint registered itself in the KV.
        assert server.handle_get("workers_notify/localhost:0") is not None

        mgr.shutdown()
        for t in started:
            t.join(timeout=10)
            assert not t.is_alive(), f"{t.name} leaked past shutdown()"
        assert mgr._httpd is None and not mgr._initialized
        # Listeners survive shutdown (the elastic run loop re-inits the
        # manager after each reset and its State must stay subscribed),
        # and init() works again.
        mgr.init()
        assert mgr._httpd is not None
        mgr.shutdown()
        assert listener in mgr._listeners
    finally:
        server.stop()


def test_rendezvous_delete_routed_through_retry(monkeypatch):
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.common import telemetry
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    server = RendezvousServer()
    port = server.start()
    try:
        client = RendezvousClient("127.0.0.1", port, secret_key=None)
        client.put("s_del", "k", b"v")
        assert client.get("s_del", "k") == b"v"
        client.delete("s_del")
        assert client.get("s_del", "k") is None
    finally:
        server.stop()
    # Against a dead server the delete must retry (counting attempts)
    # and surface OSError only after the budget — not on the first
    # refused connection.
    monkeypatch.setenv("HOROVOD_CONNECT_ATTEMPTS", "3")
    monkeypatch.setenv("HOROVOD_CONNECT_BACKOFF_SECONDS", "0.01")
    retry_counter = telemetry.counter("horovod_retry_attempts_total")
    before = retry_counter.value
    dead = RendezvousClient("127.0.0.1", port, secret_key=None)
    with pytest.raises(OSError):
        dead.delete("s_del")
    assert retry_counter.value - before >= 3


def test_refresh_topology_honors_reset_timeout_knob(monkeypatch):
    from horovod_tpu.backend.elastic_env import (
        refresh_topology_from_rendezvous,
    )
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    server = RendezvousServer()
    port = server.start()
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_ADDR", "127.0.0.1")
    monkeypatch.setenv("HOROVOD_GLOO_RENDEZVOUS_PORT", str(port))
    monkeypatch.setenv("HOROVOD_ELASTIC_RESET_TIMEOUT", "0.3")
    try:
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="epoch"):
            refresh_topology_from_rendezvous()  # no driver: no epoch ever
        assert time.monotonic() - t0 < 5.0
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# chaos: wedge — not kill — 1 of 4 elastic workers (the acceptance
# headline), plus the heartbeats-disabled hang control.
_WEDGE_WORKER = textwrap.dedent("""
    import os, pickle, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.backend.elastic_env import spawn_identity
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.common import fault_injection
    from horovod_tpu.common.exceptions import HorovodInternalError
    from horovod_tpu.elastic.state import ObjectState
    from horovod_tpu.utils import env as env_cfg

    TOTAL = int(os.environ["TEST_TOTAL_BATCHES"])
    rdv = RendezvousClient(env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR),
                           env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0))

    hvd.init()
    state = ObjectState(batch=0, history=[])

    @hvd.elastic.run
    def train(state):
        while state.batch < TOTAL:
            rdv.put("step_ts", spawn_identity(), repr(time.time()).encode())
            try:
                # commit() runs a collective too (host-update broadcast)
                # so the whole step body records its failure time+reason.
                hvd.allreduce(np.ones(2, np.float32), name="g")
                fault_injection.advance_step()   # the doomed rank wedges here
                state.history.append((hvd.rank(), hvd.size()))
                state.batch += 1
                state.commit()
            except HorovodInternalError as e:
                rdv.put("hie", spawn_identity(),
                        (repr(time.time()) + "|" + str(e)).encode())
                raise
            time.sleep(0.05)
        return list(state.history)

    hist = train(state)
    rdv.put("test_results", spawn_identity(), pickle.dumps(hist))
    print(f"worker {spawn_identity()} done as rank {hvd.rank()}", flush=True)
""")

_HOSTS = ["hostA", "hostB", "hostC", "hostD"]
_WEDGE_HOST = "hostA"   # rank 0 — the coordinator wedges, so detection
#                         is the workers' ack-loss path and eviction is
#                         the driver's ready-deadline watchdog.


def _launch_wedge_job(tmp_path, monkeypatch, heartbeat_env):
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.launch import slot_env, spawn_worker
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    monkeypatch.setenv("HVDRUN_FORCE_LOCAL", "1")
    monkeypatch.setenv("HOROVOD_ELASTIC_READY_TIMEOUT", "8")
    server = RendezvousServer()
    port = server.start()
    driver = ElasticDriver(
        server, FixedHosts({h: 1 for h in _HOSTS}), min_np=2, max_np=4,
        poll_interval=0.25,
    )
    script = tmp_path / "worker.py"
    script.write_text(_WEDGE_WORKER)

    def create_worker(slot, extra_env):
        env = slot_env(slot, "127.0.0.1", port, elastic=True)
        env.update(extra_env)
        env["PYTHONPATH"] = REPO
        env["HVDRUN_FORCE_LOCAL"] = "1"
        env["HOROVOD_CYCLE_TIME"] = "1"
        env["HOROVOD_TCP_TIMEOUT_SECONDS"] = "0"   # unbounded: the point
        env["TEST_TOTAL_BATCHES"] = "12"
        env.update(heartbeat_env)
        env.pop("HOROVOD_FAULT_INJECT", None)
        if slot.hostname == _WEDGE_HOST:
            env["HOROVOD_FAULT_INJECT"] = "wedge:step=3"
        handle = spawn_worker(slot, [sys.executable, str(script)], env,
                              prefix_output=False)
        return handle.proc

    driver.start(create_worker)
    return server, driver


def _kv_times(server, scope):
    out = {}
    for h in _HOSTS:
        blob = server.handle_get(f"{scope}/{h}:0")
        if blob is not None:
            ts, _, rest = blob.decode().partition("|")
            out[h] = (float(ts), rest)
    return out


@pytest.mark.slow
def test_chaos_wedge_elastic_recovery_and_hang_control(tmp_path, monkeypatch):
    """The headline: with HOROVOD_TCP_TIMEOUT_SECONDS=0, WEDGE (not
    kill) 1 of 4 real elastic workers mid-step. Every survivor must
    raise HorovodInternalError naming the wedged rank within
    miss_limit x interval + epsilon, the driver must evict the wedged
    slot at the ready deadline and blacklist its host, and training
    must resume and COMPLETE at np=3. Control: the same scenario with
    heartbeats disabled (HOROVOD_HEARTBEAT_MISS_LIMIT=0) demonstrably
    hangs."""
    interval, miss = 0.5, 4
    server, driver = _launch_wedge_job(tmp_path, monkeypatch, {
        "HOROVOD_HEARTBEAT_INTERVAL_SECONDS": str(interval),
        "HOROVOD_HEARTBEAT_MISS_LIMIT": str(miss),
    })
    try:
        code = driver.wait(timeout=240)
        assert code == 0, f"job did not recover and finish (exit {code})"

        # Survivors finished at np=3 after the reset.
        results = {}
        for h in _HOSTS:
            blob = server.handle_get(f"test_results/{h}:0")
            if blob is not None:
                results[h] = pickle.loads(blob)
        survivors = set(_HOSTS) - {_WEDGE_HOST}
        assert set(results) == survivors, results.keys()
        for h, hist in results.items():
            assert hist[-1][1] == 3, f"{h} did not finish at np=3: {hist[-1]}"

        # Every survivor raised HorovodInternalError NAMING the wedged
        # rank (rank 0 — the coordinator), within the bound.
        wedge_ts = _kv_times(server, "step_ts")[_WEDGE_HOST][0]
        hies = _kv_times(server, "hie")
        assert set(hies) >= survivors, (
            f"survivors without an attributed failure: "
            f"{survivors - set(hies)}")
        budget = miss * interval + 20.0   # epsilon: 4 procs on a small box
        for h in survivors:
            ts, msg = hies[h]
            assert "rank 0" in msg and "declared dead" in msg, (h, msg)
            assert ts - wedge_ts < budget, (
                f"{h} took {ts - wedge_ts:.1f}s > {budget:.1f}s: {msg}")

        # The driver evicted the wedged slot and blacklisted its host.
        assert driver._m_evictions.value >= 1
        assert driver.host_manager.blacklist_strikes(_WEDGE_HOST) >= 1
        assert driver.epoch >= 1
    finally:
        driver.stop()
        server.stop()

    # ---- control: heartbeats disabled => the same wedge hangs -------
    server2, driver2 = _launch_wedge_job(tmp_path, monkeypatch, {
        "HOROVOD_HEARTBEAT_MISS_LIMIT": "0",
    })
    # the counter is process-global and still carries phase 1's count
    evictions_before = driver2._m_evictions.value
    try:
        # Wait until the doomed worker has actually wedged (its step_ts
        # puts stop at batch 3)...
        deadline = time.monotonic() + 120
        last = None
        while time.monotonic() < deadline:
            times = _kv_times(server2, "step_ts")
            if _WEDGE_HOST in times:
                if last is not None and times[_WEDGE_HOST][0] == last:
                    break  # two observations, no progress: wedged
                last = times[_WEDGE_HOST][0]
            time.sleep(2.0)
        # ...then observe for well past the detection budget used above:
        # nobody raises, nobody is evicted, the epoch never advances.
        time.sleep(miss * interval + 12.0)
        assert _kv_times(server2, "hie") == {}, (
            "survivors failed without heartbeats — control is broken")
        assert driver2.epoch == 0 and not driver2.finished
        assert driver2._m_evictions.value == evictions_before
    finally:
        driver2.stop()
        server2.stop()
