"""Wire-level gradient compression (ISSUE 12): codec roundtrip error
bounds and edge cases, error-feedback convergence on a quadratic,
coordinator codec-assignment policy, engine integration (negotiated
codec + cache replay + residual lifecycle), compressed ring/star/arena
data planes with cross-rank bitwise agreement, codec-mismatch desync
attribution over real TCP, and env-knob parsing per house convention.
"""
import os
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

from horovod_tpu.backend.base import (
    channel_scope,
    current_wire_codec,
    wire_codec_scope,
)
from horovod_tpu.common import compression as C
from horovod_tpu.common import telemetry
from horovod_tpu.common.exceptions import (
    HorovodInternalError,
    TransportError,
)
from horovod_tpu.common.message import (
    Response,
    ResponseType,
)
from horovod_tpu.common.types import DataType, ReduceOp


BF16 = C.codec_by_name("bf16")
FP16 = C.codec_by_name("fp16")
INT8 = C.codec_by_name("int8")


# ---------------------------------------------------------------------------
# codec roundtrip properties

@pytest.mark.parametrize("codec,rel_bound", [(BF16, 2 ** -8),
                                             (FP16, 2 ** -10)])
def test_fixed_width_roundtrip_error_bound(codec, rel_bound):
    rng = np.random.default_rng(7)
    for scale in (1e-3, 1.0, 1e4):
        # magnitudes bounded away from 0 so the bound tests the
        # MANTISSA error, not fp16's subnormal flush near zero
        x = (rng.uniform(0.5, 2.0, 4096)
             * rng.choice([-1.0, 1.0], 4096) * scale).astype(np.float32)
        enc = codec.encode(x)
        assert enc.dtype == np.uint8
        assert enc.nbytes == codec.wire_bytes(x.size) == 2 * x.size
        y = codec.decode(enc, x.size)
        assert y.dtype == np.float32
        rel = np.max(np.abs(y - x) / np.maximum(np.abs(x), 1e-30))
        assert rel <= rel_bound, (codec.name, scale, rel)


@pytest.mark.parametrize("codec", [BF16, FP16, INT8])
def test_codec_empty_and_wire_bytes(codec):
    e = np.zeros(0, np.float32)
    enc = codec.encode(e)
    assert enc.nbytes == codec.wire_bytes(0)
    assert codec.decode(enc, 0).size == 0
    x = np.ones(33, np.float32)
    assert codec.encode(x).nbytes == codec.wire_bytes(33)


@pytest.mark.parametrize("codec", [BF16, FP16])
def test_fixed_width_special_values(codec):
    s = np.array([np.inf, -np.inf, np.nan, 0.0, -0.0, 1e-40],
                 np.float32)
    y = codec.decode(codec.encode(s), s.size)
    assert np.isposinf(y[0]) and np.isneginf(y[1])
    assert np.isnan(y[2])
    assert y[3] == 0.0 and y[4] == 0.0
    # fp32 denormal: representable (bf16 shares the fp32 exponent) or
    # flushed toward zero (fp16) — never inf/nan.
    assert np.isfinite(y[5])


@pytest.mark.parametrize("codec", [BF16, FP16])
def test_fixed_width_grid_idempotent(codec):
    """decode∘encode is a projection: applying it twice equals once.
    The ring allgather's owner-side projection and the lossless
    first-hop re-encode both rely on this."""
    x = np.random.default_rng(3).standard_normal(1024).astype(np.float32)
    g = codec.roundtrip(x)
    assert np.array_equal(g, codec.roundtrip(g))
    assert np.array_equal(codec.encode(g), codec.encode(g))


def test_bf16_fallback_bit_identical_to_ml_dtypes():
    if C._BF16_DTYPE is None:
        pytest.skip("ml_dtypes not available")
    x = np.random.default_rng(11).standard_normal(4096).astype(np.float32)
    x[:3] = [np.inf, -np.inf, np.nan]
    fast = BF16.encode(x).copy()
    try:
        C._BF16_DTYPE = None
        slow = BF16.encode(x)
        # NaN payloads may differ bit-wise; compare decoded semantics
        # elementwise and exact bits everywhere finite.
        yf = BF16.decode(fast, x.size)
    finally:
        C._BF16_DTYPE = np.dtype(__import__("ml_dtypes").bfloat16)
    ys = BF16.decode(slow, x.size)
    finite = np.isfinite(x)
    assert np.array_equal(yf[finite], ys[finite])
    assert np.isnan(ys[2]) and np.isnan(yf[2])


def test_int8_scale_and_edge_cases():
    x = np.array([-1.0, -0.5, 0.0, 0.25, 1.27], np.float32)
    y = INT8.decode(INT8.encode(x), x.size)
    scale = 1.27 / 127.0
    assert np.max(np.abs(y - x)) <= scale / 2 + 1e-7
    # all-zero -> zeros, zero scale
    z = np.zeros(16, np.float32)
    assert np.array_equal(INT8.decode(INT8.encode(z), 16), z)
    # non-finite-only input must not crash or poison the frame
    s = np.array([np.inf, -np.inf, np.nan], np.float32)
    out = INT8.decode(INT8.encode(s), 3)
    assert np.all(np.isfinite(out))
    # mixed: finite values set the scale, non-finite clip to extremes
    m = np.array([np.inf, 2.0, -np.inf, np.nan], np.float32)
    om = INT8.decode(INT8.encode(m), 4)
    assert om[0] == pytest.approx(2.0) and om[2] == pytest.approx(-2.0)
    assert om[3] == 0.0
    # denormals quantize to zero at any reasonable scale
    d = np.array([1e-40, 1.0], np.float32)
    od = INT8.decode(INT8.encode(d), 2)
    assert od[0] == 0.0


def test_codec_registry_lookup():
    assert C.codec_by_id(C.CODEC_BF16) is BF16
    assert C.codec_by_id(0) is None
    assert C.codec_by_id(999) is None  # unknown id degrades, not crash
    assert C.codec_by_name("nope") is None
    assert not BF16.applicable(np.float64)
    assert BF16.applicable(np.float32)


# ---------------------------------------------------------------------------
# error feedback

def _quadratic_descent(codec, use_ef, steps=300, lr=0.1):
    t = np.linspace(-3.0, 7.0, 256).astype(np.float32)
    x = np.zeros_like(t)
    res = np.zeros_like(t)
    for _ in range(steps):
        g = x - t
        if codec is not None:
            if use_ef:
                pre = g + res
                wire = codec.roundtrip(pre)
                res = pre - wire
                g = wire
            else:
                g = codec.roundtrip(g)
        x = x - lr * g
    return 0.5 * float(np.mean((x - t) ** 2)), float(np.max(np.abs(res)))


def test_error_feedback_fixes_quantized_descent():
    """EF-SGD on a quadratic (int8 — the coarsest codec): with error
    feedback the final loss matches uncompressed within tolerance and
    the residual stays bounded (the Karimireddy et al. 2019 claim).
    The engine-level mean-recovery test below covers the case where a
    single compressed round is provably off-grid."""
    plain, _ = _quadratic_descent(None, False)
    ef, res_max = _quadratic_descent(INT8, True)
    assert ef <= plain + 1e-6
    assert ef < 1e-4
    # residual bounded by one quantization step's worth of gradient
    assert res_max < 1.0


def test_error_feedback_survives_fp16_saturation():
    """fp16 saturates finite fp32 values past 65504 to inf; the
    residual (pre - inf = -inf) must reset to 0 instead of poisoning
    every later round into NaN (inf - inf). The round that overflowed
    still ships inf — the user sees it — but once gradients return to
    range, error feedback resumes cleanly."""
    ef = C.ErrorFeedback()
    big = np.array([1e6, 1.0], np.float32)  # element 0 overflows fp16
    pre = big.copy()
    wire = FP16.roundtrip(pre)
    assert np.isposinf(wire[0])
    ef.update("k", pre, wire)
    r = ef.get("k", 2)
    assert np.isfinite(r).all() and r[0] == 0.0
    # next round with a normal gradient: no NaN anywhere
    g = np.array([2.0, 3.0], np.float32)
    pre2 = g + r
    wire2 = FP16.roundtrip(pre2)
    ef.update("k", pre2, wire2)
    assert np.isfinite(wire2).all()
    assert np.isfinite(ef.get("k", 2)).all()
    # same defense on the fresh-allocation path
    ef2 = C.ErrorFeedback()
    ef2.update("fresh", pre, wire)
    assert np.isfinite(ef2.get("fresh", 2)).all()


def test_error_feedback_store_bounded():
    """A workload with uniquely-named allreduces must not leak one
    full-width residual per name forever: the store caps at its
    capacity, evicting the least recently updated."""
    ef = C.ErrorFeedback(capacity=4)
    for i in range(10):
        ef.update(f"t{i}", np.ones(4, np.float32),
                  np.zeros(4, np.float32))
    assert ef.size() == 4
    assert ef.get("t0", 4) is None      # oldest evicted
    assert ef.get("t9", 4) is not None  # newest kept
    # an update refreshes recency
    ef.update("t6", np.ones(4, np.float32), np.zeros(4, np.float32))
    ef.update("new", np.ones(4, np.float32), np.zeros(4, np.float32))
    assert ef.get("t6", 4) is not None
    assert C.ErrorFeedback().capacity == 1024


def test_error_feedback_store_lifecycle():
    ef = C.ErrorFeedback()
    assert ef.get("k", 4) is None
    r0 = np.ones(4, np.float32)
    ef.put("k", r0)
    assert ef.get("k", 4) is r0
    # size mismatch (renegotiated shape) drops rather than misapplies
    assert ef.get("k", 8) is None
    # update reuses the dead residual's buffer when shapes match
    pre = np.full(4, 2.0, np.float32)
    wire = np.full(4, 1.5, np.float32)
    ef.update("k", pre, wire)
    assert ef.get("k", 4) is r0  # same buffer, new contents
    assert np.allclose(r0, 0.5)
    assert ef.size() == 1 and ef.nbytes() == 16
    ef.reset()
    assert ef.size() == 0


# ---------------------------------------------------------------------------
# wire message + coordinator policy

def test_response_codec_rides_the_wire():
    resp = Response(response_type=ResponseType.ALLREDUCE,
                    tensor_names=["t"], tensor_shapes=[(8,)],
                    channel=1, codec=C.CODEC_FP16)
    r2, _ = Response.deserialize(resp.serialize())
    assert r2.codec == C.CODEC_FP16
    assert r2 == resp
    assert Response.deserialize(Response().serialize())[0].codec == 0


class _DummyTransport:
    rank = 0
    size = 2


def _controller():
    from horovod_tpu.engine.controller import Controller

    return Controller(_DummyTransport(), 2, 0,
                      registry=telemetry.MetricsRegistry())


def _resp(nelems=65536, dtype=DataType.FLOAT32, channel=0,
          rtype=ResponseType.ALLREDUCE, reduce_op=0):
    return Response(response_type=rtype, tensor_names=["g"],
                    tensor_shapes=[(nelems,)], tensor_type=dtype,
                    channel=channel, reduce_op=reduce_op)


def test_assign_codecs_policy(monkeypatch):
    ctrl = _controller()
    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION", "bf16")
    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION_MIN_BYTES", "65536")
    big, small = _resp(65536), _resp(1024)
    not_f32 = _resp(65536, dtype=DataType.FLOAT64)
    maxred = _resp(65536, reduce_op=int(ReduceOp.MAX))
    gather = _resp(65536, rtype=ResponseType.ALLGATHER)
    ctrl._assign_codecs([big, small, not_f32, maxred, gather])
    assert big.codec == C.CODEC_BF16        # >= min_bytes
    assert small.codec == 0                 # below min_bytes
    assert not_f32.codec == 0               # fp32 only
    assert maxred.codec == 0                # SUM only
    assert gather.codec == 0                # allreduce only

    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION", "fp16")
    r = _resp(65536)
    ctrl._assign_codecs([r])
    assert r.codec == C.CODEC_FP16

    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION", "auto")
    r = _resp(65536)
    ctrl._assign_codecs([r])
    assert r.codec == C.CODEC_BF16          # auto = TPU-native bf16

    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION", "none")
    r = _resp(1 << 24)
    ctrl._assign_codecs([r])
    assert r.codec == 0                     # none wins at any size


def test_assign_codecs_int8_latency_lane(monkeypatch):
    ctrl = _controller()
    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION", "bf16")
    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION_MIN_BYTES", "0")
    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION_INT8", "1")
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    lane = _resp(1024, channel=1)   # the latency lane (nchan-1)
    bulk = _resp(65536, channel=0)
    ctrl._assign_codecs([lane, bulk])
    assert lane.codec == C.CODEC_INT8
    assert bulk.codec == C.CODEC_BF16
    # int8 only for STAR-BOUND sizes: a ring/arena-eligible payload
    # would pay the coarse int8 projection while shipping full-width
    # (variable-width codecs can't be sliced by element offsets), so
    # with the ring threshold at 0 the lane falls back to the wide
    # codec instead.
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    lane_ring = _resp(1024, channel=1)
    ctrl._assign_codecs([lane_ring])
    assert lane_ring.codec == C.CODEC_BF16
    monkeypatch.delenv("HOROVOD_RING_THRESHOLD")
    # int8 stays opt-in: without the knob the lane follows size policy
    monkeypatch.delenv("HOROVOD_WIRE_COMPRESSION_INT8")
    lane2 = _resp(1024, channel=1)
    ctrl._assign_codecs([lane2])
    assert lane2.codec == C.CODEC_BF16


# ---------------------------------------------------------------------------
# compressed data planes (direct mixin use under an explicit scope)

def _run_pair(fn):
    from horovod_tpu.backend.transport import make_inproc_backends

    backends = make_inproc_backends(2)
    results = [None, None]
    errors = [None, None]

    def worker(r):
        try:
            results[r] = fn(backends[r], r)
        except BaseException as ex:  # noqa: BLE001
            errors[r] = ex

    threads = [threading.Thread(target=worker, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    for b in backends:
        b.shutdown()
    return results, errors


def test_compressed_ring_allreduce_bitwise_agreement(monkeypatch):
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "0")

    def fn(b, r):
        x = np.full(1000, (r + 1) / 3.0, np.float32)
        with channel_scope(1), wire_codec_scope(BF16):
            return b.allreduce(x, ReduceOp.SUM)

    (a, bb), errors = _run_pair(fn)
    assert not any(errors), errors
    assert np.array_equal(a, bb), "ranks diverged under compression"
    assert abs(float(a[0]) - 1.0) < 0.01


def test_compressed_segmented_ring(monkeypatch):
    """Segment bounds stay in element space, so a segmented compressed
    ring's frame sizes agree ((b-a) * wire_itemsize on both sides)."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "256")

    def fn(b, r):
        x = np.full(1000, float(r + 1), np.float32)
        with channel_scope(1), wire_codec_scope(FP16):
            return b.allreduce(x, ReduceOp.SUM)

    (a, bb), errors = _run_pair(fn)
    assert not any(errors), errors
    assert np.array_equal(a, bb)
    assert float(a[0]) == 3.0  # exact in fp16


def test_compressed_star_allreduce(monkeypatch):
    monkeypatch.setenv("HOROVOD_CPU_OPERATIONS", "star")
    stats = C.CompressionStats(telemetry.MetricsRegistry())

    def fn(b, r):
        x = np.full(64, (r + 1) * 0.5, np.float32)
        with wire_codec_scope(INT8, stats):
            return b.allreduce(x, ReduceOp.SUM)

    (a, bb), errors = _run_pair(fn)
    assert not any(errors), errors
    assert np.array_equal(a, bb)
    assert abs(float(a[0]) - 1.5) < 1.5 / 127 + 1e-6
    saved = stats.saved_snapshot()
    # worker gather frame + root bcast: both counted, exactly
    assert saved.get("int8") == 2 * (64 * 4 - (64 + 4))


def test_uncompressed_scope_is_inert():
    assert current_wire_codec() is None

    def fn(b, r):
        x = np.full(64, float(r + 1), np.float32)
        return b.allreduce(x, ReduceOp.SUM)

    (a, bb), errors = _run_pair(fn)
    assert not any(errors), errors
    assert float(a[0]) == 3.0 and np.array_equal(a, bb)


def test_arena_compressed_deposits(tmp_path):
    from horovod_tpu.backend.shm import ShmArena

    path = str(tmp_path / "arena")
    arenas = [ShmArena(path, i, 2, 1 << 16) for i in range(2)]
    inputs = [np.full(5000, (i + 1) / 3.0, np.float32) for i in range(2)]
    outs = [np.empty_like(inputs[i]) for i in range(2)]
    stats = C.CompressionStats(telemetry.MetricsRegistry())
    errors = [None, None]

    def worker(i):
        try:
            arenas[i].allreduce_into(
                inputs[i], lambda d, s: np.add(d, s, out=d),
                out=outs[i], codec=BF16, stats=stats)
        except BaseException as ex:  # noqa: BLE001
            errors[i] = ex

    threads = [threading.Thread(target=worker, args=(i,)) for i in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(errors), errors
    # the shared result is computed once per subslice: bitwise equal
    assert np.array_equal(outs[0], outs[1])
    expect = BF16.roundtrip(inputs[0]) + BF16.roundtrip(inputs[1])
    assert np.allclose(outs[0], expect, rtol=0, atol=0)
    # deposits streamed in >=1 chunk; every chunk saved half its bytes
    assert stats.saved_snapshot()["bf16"] == 2 * inputs[0].nbytes // 2
    for a in arenas:
        a.close()


# ---------------------------------------------------------------------------
# zero-redundancy first hop + pipelined codec/wire overlap


def test_first_hop_reuse_bitwise_identical(monkeypatch):
    """A star allreduce fed the engine's pre-encoded first-hop bytes
    finishes bitwise identical to one that re-encodes — encode is
    value-deterministic, so shipping the stash IS shipping the
    re-encode."""
    monkeypatch.setenv("HOROVOD_CPU_OPERATIONS", "star")
    rng = np.random.RandomState(7)
    xs = [rng.rand(777).astype(np.float32) for _ in range(2)]

    def run(reuse):
        def fn(b, r):
            stash = BF16.encode(xs[r]) if reuse else None
            with wire_codec_scope(BF16, first_hop=stash):
                out = b.allreduce(xs[r].copy(), ReduceOp.SUM)
                if reuse:
                    # consume-once: the data plane took it.
                    from horovod_tpu.backend.base import (
                        take_first_hop_encoded,
                    )

                    assert take_first_hop_encoded(stash.nbytes) is None
                return out
        (a, bb), errors = _run_pair(fn)
        assert not any(errors), errors
        assert np.array_equal(a, bb)
        return a

    np.testing.assert_array_equal(run(True), run(False))


def test_first_hop_stash_size_mismatch_is_ignored():
    """Defense in depth: a stash whose byte size does not match the
    buffer being shipped is dropped, never sliced wrong."""
    from horovod_tpu.backend.base import take_first_hop_encoded

    x = np.ones(64, np.float32)
    with wire_codec_scope(BF16, first_hop=BF16.encode(x)):
        assert take_first_hop_encoded(999) is None
        # consumed by the failed take: a second take sees nothing.
        assert take_first_hop_encoded(128) is None


def test_engine_first_hop_single_encode_count_star():
    """Acceptance proof (ISSUE 14): exactly ONE encode pass per
    compressed op on the first hop. Every encode site observes into
    horovod_compression_seconds{phase="encode"}, so the observation
    COUNT is the pass count: on the star path a worker pays only the
    engine's error-feedback encode (1/op — the gather ships the stash),
    and the root pays the engine's plus its result-broadcast re-encode
    (2/op). A re-encoding first hop would read 2/op on the worker."""
    iters = 4
    regs = [telemetry.MetricsRegistry() for _ in range(2)]

    def fn(eng, r):
        outs = []
        for i in range(iters):
            h = eng.enqueue_allreduce(
                np.full(300, float(r + 1), np.float32), name="t")
            outs.append(eng.synchronize(h, timeout=30))
        return outs

    results, engines, regs = _run_engines(
        2, fn, dict(_CMP_ENV, HOROVOD_CPU_OPERATIONS="star"),
        registries=regs)
    key = 'horovod_compression_seconds{phase="encode"}_count'
    assert regs[0].scalars().get(key, 0) == 2 * iters  # root
    assert regs[1].scalars().get(key, 0) == 1 * iters  # worker
    np.testing.assert_array_equal(results[0][0], results[1][0])


def test_engine_first_hop_single_encode_count_ring(monkeypatch):
    """Ring closed form at np=2: the engine's EF encode (1) + the
    allgather owner projection (1, whose bytes step 0 ships — the old
    separate step-0 re-encode is gone) = exactly 2/op per rank; the
    reduce-scatter's only step ships the engine's stash."""
    iters = 3
    regs = [telemetry.MetricsRegistry() for _ in range(2)]

    def fn(eng, r):
        outs = []
        for i in range(iters):
            h = eng.enqueue_allreduce(
                np.full(5000, float(r + 1), np.float32), name="t")
            outs.append(eng.synchronize(h, timeout=30))
        return outs

    results, engines, regs = _run_engines(
        2, fn, dict(_CMP_ENV, HOROVOD_RING_THRESHOLD="0",
                    HOROVOD_RING_SEGMENT_BYTES="0"),
        registries=regs)
    key = 'horovod_compression_seconds{phase="encode"}_count'
    for r in (0, 1):
        assert regs[r].scalars().get(key, 0) == 2 * iters, (
            r, regs[r].scalars().get(key, 0))
    np.testing.assert_array_equal(results[0][0], results[1][0])


def test_arena_first_hop_deposit(tmp_path):
    """The whole-world arena's encoded deposits slice the engine's
    first-hop bytes: zero encode observations, exact byte-savings
    accounting, full-width results bitwise identical to the
    recomputed-encode run, and deposit/copy-out conservation (sent =
    encoded bytes, recv = full-width bytes)."""
    from horovod_tpu.backend.shm import ShmArena

    inputs = [np.full(5000, (i + 1) / 3.0, np.float32)
              for i in range(2)]
    expect = BF16.roundtrip(inputs[0]) + BF16.roundtrip(inputs[1])

    def run(reuse, tag):
        arenas = [ShmArena(str(tmp_path / tag), i, 2, 1 << 16)
                  for i in range(2)]
        reg = telemetry.MetricsRegistry()
        sent = reg.counter("sent", "")
        recv = reg.counter("recv", "")
        for a in arenas:
            a.m_sent, a.m_recv = sent, recv
        stats = C.CompressionStats(telemetry.MetricsRegistry())
        outs = [np.empty_like(inputs[i]) for i in range(2)]
        errors = [None, None]

        def worker(i):
            try:
                fh = BF16.encode(inputs[i]) if reuse else None
                arenas[i].allreduce_into(
                    inputs[i], lambda d, s: np.add(d, s, out=d),
                    out=outs[i], codec=BF16, stats=stats, first_hop=fh)
            except BaseException as ex:  # noqa: BLE001
                errors[i] = ex

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in (0, 1)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not any(errors), errors
        for a in arenas:
            a.close()
        return outs, stats, sent.value, recv.value

    outs, stats, sent, recv = run(True, "a1")
    assert np.array_equal(outs[0], outs[1])
    np.testing.assert_allclose(outs[0], expect, rtol=0, atol=0)
    # no encode pass ran in the arena; savings still counted exactly
    snap = stats._seconds
    assert "encode" not in snap
    assert stats.saved_snapshot()["bf16"] == 2 * inputs[0].nbytes // 2
    # conservation: sent counts encoded deposits, recv full-width outs
    assert sent == 2 * inputs[0].nbytes // 2
    assert recv == 2 * inputs[0].nbytes
    outs2, stats2, _, _ = run(False, "a2")
    np.testing.assert_array_equal(outs[0], outs2[0])
    assert "encode" in stats2._seconds  # the recompute arm DID encode


@pytest.mark.parametrize("nranks", [2, 3, 4])
def test_pipelined_ring_bitwise_vs_serial(nranks, monkeypatch):
    """HOROVOD_RING_CODEC_OVERLAP moves codec passes onto bounded
    worker stages without changing a single wire byte: results are
    bitwise identical to the serial schedule and bitwise identical
    across ranks, segments and remainders included."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "256")

    def run(nr, overlap):
        monkeypatch.setenv("HOROVOD_RING_CODEC_OVERLAP",
                           "1" if overlap else "0")
        from horovod_tpu.backend.transport import make_inproc_backends

        backends = make_inproc_backends(nr)
        results = [None] * nr
        errors = [None] * nr

        def worker(r):
            try:
                rng = np.random.RandomState(r)
                x = rng.rand(5003).astype(np.float32)
                with channel_scope(1), wire_codec_scope(BF16):
                    results[r] = backends[r].allreduce(x, ReduceOp.SUM)
            except BaseException as e:  # noqa: BLE001
                errors[r] = e

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(nr)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for b in backends:
            b.shutdown()
        assert not any(errors), errors
        return results

    serial = run(nranks, False)
    over = run(nranks, True)
    for r in range(nranks):
        assert np.array_equal(serial[0], serial[r])
        assert np.array_equal(over[0], over[r])
    assert np.array_equal(serial[0], over[0])


def test_pipeline_stage_fifo_and_error_propagation():
    """The bounded single-worker stage runs jobs strictly FIFO and
    parks a job's exception in its future (later jobs still run)."""
    from horovod_tpu.common.compression import PipelineStage

    seen = []
    with PipelineStage("t", depth=2) as stage:
        futs = [stage.submit(lambda i=i: seen.append(i) or i)
                for i in range(8)]
        assert [f.result() for f in futs] == list(range(8))
        assert seen == list(range(8))

        def boom():
            raise ValueError("job failed")

        bad = stage.submit(boom)
        good = stage.submit(lambda: "after")
        with pytest.raises(ValueError, match="job failed"):
            bad.result()
        assert good.result() == "after"


def test_ring_codec_overlap_parse(monkeypatch):
    from horovod_tpu.utils import env as env_cfg

    monkeypatch.delenv("HOROVOD_RING_CODEC_OVERLAP", raising=False)
    assert env_cfg.ring_codec_overlap() is True
    monkeypatch.setenv("HOROVOD_RING_CODEC_OVERLAP", "0")
    assert env_cfg.ring_codec_overlap() is False
    monkeypatch.setenv("HOROVOD_RING_CODEC_OVERLAP", "1")
    assert env_cfg.ring_codec_overlap() is True


# ---------------------------------------------------------------------------
# engine integration: negotiated codec, cache replay, residuals

def _run_engines(size, fn, env, registries=None):
    from horovod_tpu.backend.threaded import ThreadedGroup
    from horovod_tpu.engine.engine import Engine

    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        group = ThreadedGroup(size)
        regs = registries or [telemetry.MetricsRegistry()
                              for _ in range(size)]
        engines = [Engine(rank=r, size=size, backend=group.backend(r),
                          registry=regs[r]) for r in range(size)]
        for e in engines:
            e.cycle_time_s = 0.001
            e.start()
        results = [None] * size
        errors = [None] * size

        def worker(r):
            try:
                results[r] = fn(engines[r], r)
            except BaseException as ex:  # noqa: BLE001
                errors[r] = ex

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(size)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        stop = [threading.Thread(target=e.shutdown) for e in engines]
        for t in stop:
            t.start()
        for t in stop:
            t.join(timeout=60)
        for err in errors:
            if err is not None:
                raise err
        return results, engines, regs
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


_CMP_ENV = {
    "HOROVOD_WIRE_COMPRESSION": "bf16",
    "HOROVOD_WIRE_COMPRESSION_MIN_BYTES": "0",
}


def test_engine_negotiated_compression_and_cache_replay():
    """The coordinator assigns bf16, the codec id rides the wire, and
    cache-replayed cycles keep compressing (bytes-saved keeps growing
    after the first negotiation) with bitwise cross-rank agreement."""
    iters = 4

    def fn(eng, rank):
        outs = []
        for _ in range(iters):  # steady name -> cache replay after #1
            x = np.full(512, (rank + 1) * 0.1, np.float32)
            outs.append(eng.synchronize(
                eng.enqueue_allreduce(x, name="cmp"), timeout=30))
        return outs

    results, engines, regs = _run_engines(2, fn, _CMP_ENV)
    for o0, o1 in zip(results[0], results[1]):
        assert np.array_equal(o0, o1)
        assert abs(float(o0[0]) - 0.3) < 0.01
    for reg in regs:
        saved = reg.snapshot().get(
            'horovod_wire_bytes_saved_total{codec="bf16"}', 0)
        # every iteration compressed: star worker/bcast frames save
        # 512 * 2 bytes each, once per iteration on each rank
        assert saved == iters * 512 * 2, saved
    # per-(tensor-name) residual exists on both ranks
    for eng in engines:
        assert eng._error_feedback.size() == 1


def test_engine_error_feedback_recovers_mean():
    """1/3 is not bf16-representable; with error feedback the
    time-average of compressed allreduce results converges to the true
    sum (the EF guarantee), while any single round is off-grid."""
    iters = 50
    true = 2.0 / 3.0  # (1/3) * 2 ranks... per-rank value 1/3

    def fn(eng, rank):
        acc = 0.0
        for _ in range(iters):
            x = np.full(8, 1.0 / 3.0, np.float32)
            out = eng.synchronize(
                eng.enqueue_allreduce(x, name="ef"), timeout=30)
            acc += float(np.asarray(out)[0])
        return acc / iters

    results, engines, _ = _run_engines(2, fn, _CMP_ENV)
    for mean in results:
        assert abs(mean - true) < 1e-4, mean


def test_engine_residuals_reset_with_engine_lifecycle():
    """An elastic reset builds a fresh Engine on every rank; residuals
    are engine-owned, so the reset zeroes them consistently."""

    def fn(eng, rank):
        x = np.full(16, 1.0 / 3.0, np.float32)
        eng.synchronize(eng.enqueue_allreduce(x, name="r"), timeout=30)
        return eng._error_feedback.size()

    results, engines, _ = _run_engines(2, fn, _CMP_ENV)
    assert results == [1, 1]
    # the "reset": a new engine pair starts with zero residuals
    def probe(eng, rank):
        return eng._error_feedback.size()

    results2, _, _ = _run_engines(2, probe, _CMP_ENV)
    assert results2 == [0, 0]


def test_engine_join_under_compression():
    """A joined rank must enter the compressed collective with encoded
    zero frames — full-width frames from the joined rank would desync
    the stream (frame sizes are codec-derived)."""

    def fn(eng, rank):
        if rank == 1:
            return eng.synchronize(eng.enqueue_join(), timeout=30)
        x = np.full(512, 2.0, np.float32)
        out = eng.synchronize(
            eng.enqueue_allreduce(x, name="j"), timeout=30)
        eng.synchronize(eng.enqueue_join(), timeout=30)
        return out

    results, _, _ = _run_engines(2, fn, _CMP_ENV)
    assert float(np.asarray(results[0])[0]) == 2.0  # zeros joined in


def test_engine_status_has_wire_compression_row():
    def fn(eng, rank):
        x = np.full(512, 1.0, np.float32)
        eng.synchronize(eng.enqueue_allreduce(x, name="s"), timeout=30)
        return eng.status()["wire_compression"]

    results, _, _ = _run_engines(2, fn, _CMP_ENV)
    row = results[0]
    assert row["mode"] == "bf16"
    assert row["residual_tensors"] == 1
    assert row["bytes_saved"].get("bf16", 0) > 0


def test_engine_training_loss_parity_bf16_vs_none():
    """Accuracy-parity check through the REAL engine data plane: a
    2-rank data-parallel least-squares model trained with gradient
    allreduce under bf16+error-feedback must reach the same final loss
    as the uncompressed run within noise (the bench.py models ride the
    traced/XLA path, which the wire codec never touches — this loop is
    the eager engine's equivalent)."""
    rng = np.random.default_rng(5)
    w_true = rng.standard_normal(8).astype(np.float32)
    data = [rng.standard_normal((64, 8)).astype(np.float32)
            for _ in range(2)]
    targets = [d @ w_true for d in data]

    def train(env):
        def fn(eng, rank):
            w = np.zeros(8, np.float32)
            X, y = data[rank], targets[rank]
            for _ in range(100):
                pred = X @ w
                grad = (X.T @ (pred - y)) / len(y)
                g = np.asarray(eng.synchronize(
                    eng.enqueue_allreduce(grad, name="g",
                                          op=ReduceOp.AVERAGE),
                    timeout=30))
                w = w - 0.4 * g
            resid = np.concatenate([Xr @ w - yr
                                    for Xr, yr in zip(data, targets)])
            return float(np.mean(resid ** 2))

        results, _, _ = _run_engines(2, fn, env)
        assert results[0] == pytest.approx(results[1])
        return results[0]

    loss_cmp = train(_CMP_ENV)
    loss_none = train({"HOROVOD_WIRE_COMPRESSION": "none"})
    assert loss_none < 1e-3
    # parity within noise: compressed-with-EF tracks uncompressed
    assert loss_cmp < max(2 * loss_none, 1e-3)


# ---------------------------------------------------------------------------
# codec-mismatch desync attribution over real TCP

def test_codec_mismatch_desyncs_with_attribution(monkeypatch):
    """One rank ring-reduces compressed, the other full-width: the
    half-width frame meets the full-width recv_into and every involved
    rank fails with the single-source desync message naming BOTH knobs
    that change frame sizes — never a hang, never a raw socket error."""
    from test_fault_tolerance import _tcp_pair

    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "0")
    server, (b0, b1) = _tcp_pair("t_codec_desync", monkeypatch)
    errors = [None, None]

    def worker(r, backend, codec):
        x = np.full(1000, float(r + 1), np.float32)
        try:
            with channel_scope(1), wire_codec_scope(codec):
                backend.allreduce(x, ReduceOp.SUM)
        except HorovodInternalError as ex:
            errors[r] = ex

    threads = [
        threading.Thread(target=worker, args=(0, b0, BF16)),
        threading.Thread(target=worker, args=(1, b1, None)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    try:
        assert not any(t.is_alive() for t in threads), "desync hung"
        # Both ranks fail promptly; WHICHEVER side reads the
        # mismatched frame first raises the attributed single-source
        # message (the other sees its peer's sever as a transport
        # death — still an attributed TransportError, never a hang).
        assert errors[0] is not None and errors[1] is not None
        msgs = [str(e) for e in errors]
        attributed = [m for m in msgs if "desynced peer" in m]
        assert attributed, msgs
        for m in attributed:
            assert "HOROVOD_WIRE_COMPRESSION" in m
            assert "HOROVOD_RING_SEGMENT_BYTES" in m
        for e in errors:
            assert isinstance(e, (TransportError, HorovodInternalError))
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


# ---------------------------------------------------------------------------
# env knobs (house convention: parse tests incl. alias + bogus values)

def test_wire_compression_env_knobs(monkeypatch):
    from horovod_tpu.utils import env as env_cfg

    for k in ("HOROVOD_WIRE_COMPRESSION",
              "HOROVOD_WIRE_COMPRESSION_MIN_BYTES",
              "HOROVOD_WIRE_COMPRESSION_INT8"):
        monkeypatch.delenv(k, raising=False)
        monkeypatch.delenv(k.replace("HOROVOD_", "HVD_TPU_", 1),
                           raising=False)
    assert env_cfg.wire_compression_mode() == "none"
    assert env_cfg.wire_compression_min_bytes() == 65536
    assert env_cfg.wire_compression_int8() is False

    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION", "BF16")
    assert env_cfg.wire_compression_mode() == "bf16"
    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION", "bogus")
    assert env_cfg.wire_compression_mode() == "none"  # typo != surprise
    monkeypatch.delenv("HOROVOD_WIRE_COMPRESSION")
    monkeypatch.setenv("HVD_TPU_WIRE_COMPRESSION", "fp16")
    assert env_cfg.wire_compression_mode() == "fp16"

    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION_MIN_BYTES", "-5")
    assert env_cfg.wire_compression_min_bytes() == 0  # floored
    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION_MIN_BYTES", "1024")
    assert env_cfg.wire_compression_min_bytes() == 1024

    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION_INT8", "1")
    assert env_cfg.wire_compression_int8() is True
    monkeypatch.setenv("HOROVOD_WIRE_COMPRESSION_INT8", "off")
    assert env_cfg.wire_compression_int8() is False


# ---------------------------------------------------------------------------
# namespace dedupe: one core, thin framework re-exports

def test_compression_namespaces_share_the_core():
    from horovod_tpu.ops import compression as jax_comp

    assert jax_comp.Compressor is C.Compressor
    assert jax_comp.NoneCompressor is C.NoneCompressor
    assert jax_comp.Compression.none is C.NoneCompressor
    # adapters stay framework-local but subclass the shared interface
    assert issubclass(jax_comp.BF16Compressor, C.Compressor)
    t, ctx = jax_comp.Compression.none.compress("x")
    assert t == "x" and ctx is None
