"""Timeline tests (ref: test/test_timeline.py — validate Chrome-trace
JSON is produced with negotiation + op phases)."""
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
from test_engine import run_ranks


def test_timeline_writes_valid_chrome_trace(tmp_path, monkeypatch):
    path = tmp_path / "timeline.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(path))
    monkeypatch.setenv("HOROVOD_TIMELINE_MARK_CYCLES", "1")

    def fn(eng, rank):
        for i in range(3):
            eng.synchronize(
                eng.enqueue_allreduce(np.ones(4, np.float32), name="t"),
                timeout=30)
        return True

    run_ranks(2, fn)
    assert path.exists()
    events = json.loads(path.read_text())
    assert isinstance(events, list) and events
    names = {e.get("name") for e in events}
    assert "ALLREDUCE" in names          # op phase
    assert any(n and n.startswith("NEGOTIATE") for n in names if n)
    assert "CYCLE" in names              # mark-cycles enabled
    # The clock-anchor metadata event leads the file (the wall-clock
    # identity of t=0, for splicing against mesh_timeline device lanes).
    assert events[0]["ph"] == "M" and events[0]["name"] == "horovod_clock"
    assert "wall_anchor_ns" in events[0]["args"]
    for e in events[1:]:
        assert e["ph"] in ("B", "E", "i")
        assert "ts" in e and "tid" in e


def test_timeline_phase_nesting(tmp_path, monkeypatch):
    """The per-tensor state machine must match the reference:
    NEGOTIATE_<OP> (with per-rank ready instants inside) closes before
    the top-level op phase opens; activities nest inside the op phase
    (ref: timeline.h:81-126 NEGOTIATING->TOP_LEVEL->ACTIVITY)."""
    path = tmp_path / "timeline.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(path))

    def fn(eng, rank):
        eng.synchronize(
            eng.enqueue_allreduce(np.ones(4, np.float32), name="nest"),
            timeout=30)
        return True

    run_ranks(2, fn)
    events = json.loads(path.read_text())

    # Find the lane (tid) carrying the allreduce.* tensor negotiation.
    neg_b = [e for e in events
             if e["ph"] == "B" and e["name"] == "NEGOTIATE_ALLREDUCE"]
    assert neg_b, events
    tid = neg_b[0]["tid"]
    lane = [e for e in events
            if e.get("tid") == tid and e["ph"] != "M"]

    # Phase sequence on the lane: NEGOTIATE B ... rank instants ... E,
    # then op B ... activities ... E, with balanced B/E throughout.
    seq = [(e["ph"], e.get("name")) for e in lane]
    i_neg_b = seq.index(("B", "NEGOTIATE_ALLREDUCE"))
    i_op_b = seq.index(("B", "ALLREDUCE"))
    assert i_neg_b < i_op_b
    # rank-ready instants for both ranks land inside negotiation
    ready = [i for i, (ph, nm) in enumerate(seq)
             if ph == "i" and nm in ("0", "1")]
    assert len(ready) >= 2
    neg_e = seq.index(("E", "NEGOTIATE_ALLREDUCE"))
    assert all(i_neg_b < i < i_op_b for i in ready[:2])
    assert i_neg_b < neg_e <= i_op_b
    # B/E balance on the lane (activities nested in op phase)
    depth = 0
    for ph, _ in seq:
        if ph == "B":
            depth += 1
        elif ph == "E":
            depth -= 1
            assert depth >= 0
    assert depth == 0, seq
