"""Timeline tests (ref: test/test_timeline.py — validate Chrome-trace
JSON is produced with negotiation + op phases)."""
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
from test_engine import run_ranks


def test_timeline_writes_valid_chrome_trace(tmp_path, monkeypatch):
    path = tmp_path / "timeline.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(path))
    monkeypatch.setenv("HOROVOD_TIMELINE_MARK_CYCLES", "1")

    def fn(eng, rank):
        for i in range(3):
            eng.synchronize(
                eng.enqueue_allreduce(np.ones(4, np.float32), name="t"),
                timeout=30)
        return True

    run_ranks(2, fn)
    assert path.exists()
    events = json.loads(path.read_text())
    assert isinstance(events, list) and events
    names = {e.get("name") for e in events}
    assert "ALLREDUCE" in names          # op phase
    assert any(n and n.startswith("NEGOTIATE") for n in names if n)
    assert "CYCLE" in names              # mark-cycles enabled
    for e in events:
        assert e["ph"] in ("B", "E", "i")
        assert "ts" in e and "tid" in e
