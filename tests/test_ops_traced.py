"""Traced collective tests on the 8-device CPU mesh — the analogue of the
reference's per-op × dtype × fused/unfused matrix (ref: test/
test_tensorflow.py:218+ test_horovod_allreduce_* family)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.utils.compat import shard_map


@pytest.fixture(autouse=True)
def _init():
    hvd.shutdown()
    hvd.init()
    yield
    hvd.shutdown()


def _run(fn, x, out_spec=P("hvd")):
    return shard_map(
        fn, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=out_spec
    )(x)


N = 8  # device count


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.int32])
def test_allreduce_sum(dtype):
    x = jnp.arange(N * 4).astype(dtype)
    out = _run(lambda v: hvd.allreduce(v, op=hvd.Sum), x)
    shards = np.asarray(x, dtype=np.float64).reshape(N, 4)
    expected = np.tile(shards.sum(0), N)
    np.testing.assert_allclose(np.asarray(out, np.float64), expected, rtol=1e-2)


def test_allreduce_average():
    x = jnp.arange(N * 4, dtype=jnp.float32)
    out = _run(lambda v: hvd.allreduce(v), x)
    expected = np.tile(np.asarray(x).reshape(N, 4).mean(0), N)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


def test_allreduce_min_max():
    x = jnp.arange(N * 4, dtype=jnp.float32)
    mn = _run(lambda v: hvd.allreduce(v, op=hvd.Min), x)
    mx = _run(lambda v: hvd.allreduce(v, op=hvd.Max), x)
    shards = np.asarray(x).reshape(N, 4)
    np.testing.assert_allclose(np.asarray(mn), np.tile(shards.min(0), N))
    np.testing.assert_allclose(np.asarray(mx), np.tile(shards.max(0), N))


def test_allreduce_prescale_postscale():
    # (ref: test_tensorflow.py prescale/postscale tests; operations.cc:851-858)
    x = jnp.ones(N * 4, dtype=jnp.float32)
    out = _run(
        lambda v: hvd.allreduce(v, op=hvd.Sum, prescale_factor=2.0,
                                postscale_factor=0.5),
        x,
    )
    np.testing.assert_allclose(np.asarray(out), np.full(N * 4, N * 1.0))


def test_grouped_allreduce_matches_individual():
    xs = [jnp.arange(N * 2, dtype=jnp.float32),
          jnp.ones((N, 3), dtype=jnp.float32)]

    def grouped(a, b):
        r = hvd.grouped_allreduce([a, b], op=hvd.Sum)
        return tuple(r)

    got = shard_map(grouped, mesh=hvd.mesh(),
                    in_specs=(P("hvd"), P("hvd")),
                    out_specs=(P("hvd"), P("hvd")))(*xs)
    want0 = np.tile(np.asarray(xs[0]).reshape(N, 2).sum(0), N)
    np.testing.assert_allclose(np.asarray(got[0]), want0)
    np.testing.assert_allclose(np.asarray(got[1]), np.full((N, 3), float(N)))


def test_allgather():
    x = jnp.arange(N * 2, dtype=jnp.float32)
    out = _run(lambda v: hvd.allgather(v), x)
    # Each shard gathers all: result is x tiled per shard.
    assert out.shape == (N * N * 2,)
    np.testing.assert_allclose(np.asarray(out)[: N * 2], np.asarray(x))


def test_broadcast_root_value():
    x = jnp.arange(N, dtype=jnp.float32)
    for root in (0, 3, 7):
        out = _run(lambda v: hvd.broadcast(v, root), x)
        np.testing.assert_allclose(np.asarray(out), np.full(N, float(root)))


def test_alltoall_transpose():
    # Classic property: alltoall of [rank]*N yields [0..N-1] on every rank.
    x = jnp.repeat(jnp.arange(N, dtype=jnp.float32), N)

    def f(v):
        return hvd.alltoall(v)

    out = _run(f, x)
    np.testing.assert_allclose(np.asarray(out)[:N], np.arange(N, dtype=np.float32))


def test_reducescatter():
    x = jnp.ones((N * N,), dtype=jnp.float32)

    def f(v):
        return hvd.reducescatter(v, op=hvd.Sum)

    out = _run(f, x)
    assert out.shape == (N,)
    np.testing.assert_allclose(np.asarray(out), np.full(N, float(N)))


def test_hierarchical_allreduce_equals_flat():
    from horovod_tpu.ops.traced import hierarchical_allreduce
    from horovod_tpu.parallel.mesh import create_mesh

    mesh = create_mesh({"dp": 2, "tp": 4})
    x = jnp.arange(8 * 3, dtype=jnp.float32).reshape(8, 3)

    def f(v):
        return hierarchical_allreduce(v, inner_axis="tp", outer_axis="dp",
                                      op=hvd.Sum)

    got = shard_map(f, mesh=mesh, in_specs=P(("dp", "tp")),
                    out_specs=P(("dp", "tp")))(x)
    want = np.tile(np.asarray(x).reshape(8, 1, 3).sum(0), (8, 1)).reshape(8, 3)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_barrier_compiles():
    out = _run(lambda v: v + hvd.barrier() if False else v, jnp.ones(N))
    assert out.shape == (N,)


def test_allreduce_of_gradients():
    # The DistributedOptimizer hot path: per-shard grads, averaged by
    # allreduce (ref: horovod/tensorflow/__init__.py:242-274).
    mesh = hvd.mesh()

    def step(w, x):
        g = jax.grad(lambda w_: jnp.sum(w_ * x))(w)
        return hvd.allreduce(g)  # AVERAGE over ranks

    g = shard_map(step, mesh=mesh, in_specs=(P(), P("hvd")),
                  out_specs=P())(jnp.float32(1.0),
                                 jnp.arange(N, dtype=jnp.float32))
    # local grad on shard r = x_r; average over ranks = mean(0..7) = 3.5
    np.testing.assert_allclose(np.asarray(g), 3.5)
