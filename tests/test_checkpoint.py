"""Durability plane tests (docs/checkpoint.md): atomic writes, the
sharded CheckpointManager's full save → commit → kill → restore
roundtrip with bitwise parity, torn-write recovery, world-size
re-sharding, disk fault injection, GC, and the JaxState.restore
aliasing regression."""
import json
import os
import pickle
import threading
import zlib

import numpy as np
import pytest

from horovod_tpu.common import checkpoint as ck
from horovod_tpu.common import telemetry
from horovod_tpu.common.fault_injection import (
    InjectedDiskFault, Rule, injector, parse_spec,
)
from horovod_tpu.elastic.state import JaxState, ObjectState
from horovod_tpu.utils import atomic_file


@pytest.fixture(autouse=True)
def _clean_injector():
    injector.clear()
    yield
    injector.clear()


def _params():
    return {
        "w": np.arange(12, dtype=np.float32).reshape(3, 4),
        "b": np.ones(4, np.float32),
    }


def _state(batch=5):
    st = JaxState(
        params=_params(),
        opt_state=[np.zeros(3, np.float32), {"m": np.full((2, 2), 7.0)}],
        batch=batch, history=[(1, 2)],
    )
    st.save()
    return st


def _fresh_state():
    return JaxState(
        params={"w": np.zeros((3, 4), np.float32),
                "b": np.zeros(4, np.float32)},
        opt_state=[np.zeros(3, np.float32), {"m": np.zeros((2, 2))}],
        batch=0, history=[],
    )


def _write_world(td, state, step, size, **kw):
    """Write a complete checkpoint at `step` as a `size`-rank world
    (one manager per rank sharing the dir; coordinator last so its
    ack-collection finds every shard already durable)."""
    mgrs = [ck.CheckpointManager(str(td), rank=r, size=size,
                                 interval_steps=1, commit_timeout=10, **kw)
            for r in range(size)]
    for m in mgrs[1:]:
        assert m.save(state, step=step, blocking=True)
    assert mgrs[0].save(state, step=step, blocking=True)
    for m in mgrs:
        m.stop()
    return mgrs[0]


# ---------------------------------------------------------------------------
# utils/atomic_file.py


def test_atomic_write_and_read(tmp_path):
    p = str(tmp_path / "sub" / "f.bin")
    atomic_file.atomic_write_bytes(p, b"hello", fsync=True)
    assert atomic_file.checked_read_bytes(p) == b"hello"
    atomic_file.atomic_write_text(p, "world")
    with open(p) as f:
        assert f.read() == "world"
    # No tmp debris after successful writes.
    assert not [n for n in os.listdir(tmp_path / "sub")
                if atomic_file.is_tmp_debris(n)]


def test_atomic_write_failure_leaves_destination_and_no_tmp(tmp_path):
    p = str(tmp_path / "f.bin")
    atomic_file.atomic_write_bytes(p, b"v1")

    def boom(f):
        f.write(b"partial")
        raise RuntimeError("writer died")

    with pytest.raises(RuntimeError):
        atomic_file.atomic_write(p, boom, mode="wb")
    with open(p, "rb") as f:
        assert f.read() == b"v1"  # previous version intact
    assert not [n for n in os.listdir(tmp_path)
                if atomic_file.is_tmp_debris(n)]


def test_atomic_write_diskfail_rule(tmp_path):
    p = str(tmp_path / "f.bin")
    atomic_file.atomic_write_bytes(p, b"v1")
    injector.install([Rule(action="diskfail", op="write")])
    with pytest.raises(OSError):
        atomic_file.atomic_write_bytes(p, b"v2")
    injector.clear()
    with open(p, "rb") as f:
        assert f.read() == b"v1"


# ---------------------------------------------------------------------------
# Fault-injection grammar


def test_parse_disk_rules():
    rules = parse_spec("diskfail:op=write:path=shard:after=2;"
                       "diskslow:secs=0.1:rank=3")
    assert rules[0].action == "diskfail"
    assert rules[0].op == "write" and rules[0].path == "shard"
    assert rules[0].after == 2
    assert rules[1].action == "diskslow" and rules[1].secs == 0.1
    assert rules[1].rank == 3


def test_parse_disk_rules_rejects_bad_specs():
    with pytest.raises(ValueError):
        parse_spec("diskslow")  # needs secs
    with pytest.raises(ValueError):
        parse_spec("diskfail:op=send")  # net op on a disk rule
    with pytest.raises(ValueError):
        parse_spec("sever:path=x")  # path on a net rule
    with pytest.raises(ValueError):
        parse_spec("delay:op=write:secs=1")  # disk op on a net rule


def test_disk_rules_do_not_fire_on_network_io():
    injector.install([Rule(action="diskfail")])
    # A disk rule must never sever the data plane.
    assert injector.check_io(0, 1, "send") == "pass"
    with pytest.raises(InjectedDiskFault):
        injector.check_disk("write", "/tmp/x")


def test_diskfail_after_and_path_filters(tmp_path):
    injector.install([
        Rule(action="diskfail", op="write", path="shard", after=1)])
    injector.check_disk("write", "/a/shard-0.pkl")  # first match passes
    injector.check_disk("write", "/a/manifest.json")  # path filtered out
    with pytest.raises(InjectedDiskFault):
        injector.check_disk("write", "/a/shard-1.pkl")


# ---------------------------------------------------------------------------
# JaxState restore aliasing regression (the bug: restore handed back the
# snapshot arrays themselves, so in-place mutation corrupted the
# rollback point)


def test_restore_does_not_alias_saved_snapshot():
    st = _state()
    committed = {k: v.copy() for k, v in st.params.items()}
    st.restore()
    # Mutate the restored params IN PLACE — an optimizer step on numpy
    # state does exactly this.
    st.params["w"] += 100.0
    st.params["b"] *= 0.0
    # A second restore must still yield the committed values.
    st.restore()
    np.testing.assert_array_equal(st.params["w"], committed["w"])
    np.testing.assert_array_equal(st.params["b"], committed["b"])
    # And the restored arrays are fresh on every restore.
    assert st.params["w"] is not st._saved_trees["params"]["w"]


def test_save_does_not_alias_numpy_leaves():
    """np.asarray on an np.ndarray returns the SAME object, so the
    snapshot must copy numpy-backed leaves explicitly — otherwise an
    in-place training update corrupts the rollback point AND whatever
    the background checkpoint writer is pickling."""
    st = _state()
    saved_w = st._saved_trees["params"]["w"]
    assert saved_w is not st.params["w"]
    st.params["w"][:] = -777.0  # in-place, no rebind, no save()
    assert saved_w[0, 0] == 0.0  # the committed snapshot is untouched
    st.restore()
    np.testing.assert_array_equal(st.params["w"], _params()["w"])


# ---------------------------------------------------------------------------
# shard_ranges


def test_shard_ranges_tile_and_balance():
    sizes = [100, 1, 1, 100, 50, 50]
    for n in (1, 2, 3, 4, 6, 9):
        ranges = ck.shard_ranges(sizes, n)
        assert len(ranges) == n
        assert ranges[0][0] == 0 and ranges[-1][1] == len(sizes)
        for (a, b), (c, d) in zip(ranges, ranges[1:]):
            assert b == c  # contiguous tiling
    # More shards than leaves: the extras are empty, never negative.
    ranges = ck.shard_ranges([10], 4)
    assert all(a <= b for a, b in ranges)
    assert sum(b - a for a, b in ranges) == 1


# ---------------------------------------------------------------------------
# The full durability roundtrip


def test_roundtrip_kill_all_and_restore_bitwise(tmp_path):
    st = _state()
    _write_world(tmp_path, st, step=4, size=2)

    found = ck.find_latest_manifest(str(tmp_path))
    assert found is not None
    step, man, _ = found
    assert step == 4 and man["world_size"] == 2
    # Shard ranges tile the leaf space (the re-sharding metadata).
    ranges = sorted(s["leaves"] for s in man["shards"])
    assert ranges[0][0] == 0 and ranges[-1][1] == man["num_leaves"]

    # "Kill": nothing survives but the files. A fresh state + manager
    # (any world size) restores bitwise-identically.
    st2 = _fresh_state()
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1)
    try:
        assert m.restore_latest(st2) == 4
    finally:
        m.stop()
    for k in ("w", "b"):
        assert st2.params[k].tobytes() == st.params[k].tobytes()
    assert st2.opt_state[1]["m"].tobytes() == st.opt_state[1]["m"].tobytes()
    assert st2.batch == 5 and st2.history == [(1, 2)]
    # The restored state is re-snapshotted: an in-memory rollback goes
    # to the restored values.
    st2.params["w"] = st2.params["w"] + 1
    st2.restore()
    assert st2.params["w"].tobytes() == st.params["w"].tobytes()


@pytest.mark.parametrize("restore_size", [1, 3])
def test_restore_at_different_world_size(tmp_path, restore_size):
    st = _state()
    _write_world(tmp_path, st, step=7, size=2)
    st2 = _fresh_state()
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=restore_size)
    try:
        assert m.restore_latest(st2) == 7
    finally:
        m.stop()
    assert st2.params["w"].tobytes() == st.params["w"].tobytes()


def test_object_state_only_roundtrip(tmp_path):
    st = ObjectState(batch=9, lr=0.125, history=["a"])
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1,
                             interval_steps=1, commit_timeout=5)
    try:
        assert m.save(st, step=1, blocking=True)
    finally:
        m.stop()
    st2 = ObjectState(batch=0, lr=0.0, history=[])
    m2 = ck.CheckpointManager(str(tmp_path), rank=0, size=1)
    try:
        assert m2.restore_latest(st2) == 1
    finally:
        m2.stop()
    assert (st2.batch, st2.lr, st2.history) == (9, 0.125, ["a"])


def test_torn_write_recovery(tmp_path):
    """A `.tmp` orphan, a manifest-less shard dir, a manifest with a
    missing shard, and a CRC-corrupt shard must all be ignored in favor
    of the last complete checkpoint."""
    st = _state()
    _write_world(tmp_path, st, step=4, size=1)

    # 1) Orphan shard dir from a kill mid-write (no manifest) + tmp.
    d8 = ck.step_dir(str(tmp_path), 8)
    os.makedirs(d8)
    with open(os.path.join(d8, "shard-00000.pkl.tmp.123.456"), "wb") as f:
        f.write(b"partial")
    with open(os.path.join(d8, "shard-00000.pkl"), "wb") as f:
        f.write(b"complete-but-uncommitted")

    # 2) A manifest referencing a shard that never landed.
    with open(ck.manifest_path(str(tmp_path), 9), "w") as f:
        json.dump({"format": 1, "step": 9, "world_size": 1,
                   "num_leaves": 0, "attrs": [], "attr_counts": {},
                   "objects_shard": 0,
                   "shards": [{"rank": 0, "file": "ckpt-0000000009/x.pkl",
                               "leaves": [0, 0], "bytes": 10, "crc32": 0}]},
                  f)

    # 3) A newer COMMITTED checkpoint whose shard bytes rotted (same
    # size, wrong CRC).
    _write_world(tmp_path, _state(batch=99), step=12, size=1)
    man12 = ck.load_manifest(ck.manifest_path(str(tmp_path), 12))
    shard12 = os.path.join(str(tmp_path), man12["shards"][0]["file"])
    blob = bytearray(open(shard12, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(shard12, "wb") as f:
        f.write(bytes(blob))

    st2 = _fresh_state()
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1)
    try:
        assert m.restore_latest(st2) == 4  # fell back past 12, 9 and 8
    finally:
        m.stop()
    assert st2.batch == 5
    assert st2.params["w"].tobytes() == st.params["w"].tobytes()


# ---------------------------------------------------------------------------
# Disk fault injection through the manager


def test_diskfail_counts_failure_and_never_commits(tmp_path):
    st = _state()
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1,
                             interval_steps=1, commit_timeout=2)
    failures0 = m._m_failures.value
    injector.install([Rule(action="diskfail", op="write", path="shard")])
    try:
        m.save(st, step=1, blocking=True)
        # The failed write is counted and no manifest references the
        # missing shard — there is no manifest at all.
        assert m._m_failures.value == failures0 + 1
        assert ck.find_latest_manifest(str(tmp_path)) is None
        assert m.status()["last_error"] is not None

        # The fault clears; the next interval succeeds cleanly.
        injector.clear()
        assert m.save(st, step=2, blocking=True)
        found = ck.find_latest_manifest(str(tmp_path))
        assert found is not None and found[0] == 2
    finally:
        m.stop()


def test_diskslow_write_survives(tmp_path):
    st = _state()
    injector.install([Rule(action="diskslow", secs=0.05, op="write",
                           path="shard")])
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1,
                             interval_steps=1, commit_timeout=5)
    writes0 = m._m_writes.value
    try:
        assert m.save(st, step=1, blocking=True)
        assert m._m_writes.value == writes0 + 1
        assert ck.find_latest_manifest(str(tmp_path))[0] == 1
    finally:
        m.stop()


def test_commit_abandoned_when_a_rank_never_acks(tmp_path):
    """Coordinator in a 2-rank world, rank 1 never writes: the commit
    must time out, count a failure, and leave no manifest."""
    st = _state()
    m0 = ck.CheckpointManager(str(tmp_path), rank=0, size=2,
                              interval_steps=1, commit_timeout=0.3)
    failures0 = m0._m_failures.value
    try:
        m0.save(st, step=1, blocking=True, timeout=30)
        assert ck.find_latest_manifest(str(tmp_path)) is None
        assert m0._m_failures.value == failures0 + 1
    finally:
        m0.stop()


# ---------------------------------------------------------------------------
# Writer backpressure + interval


def test_maybe_save_respects_interval(tmp_path):
    st = _state()
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1,
                             interval_steps=3, commit_timeout=5)
    try:
        enq = []
        for _ in range(7):
            enq.append(m.maybe_save(st))
            m.flush(timeout=30)  # keep the writer idle: no skip races
        assert enq == [False, False, True, False, False, True, False]
        found = ck.find_latest_manifest(str(tmp_path))
        assert found is not None and found[0] == 6
    finally:
        m.stop()


def test_busy_writer_skips_and_counts(tmp_path):
    st = _state()
    injector.install([Rule(action="diskslow", secs=0.5, op="write",
                           path="shard")])
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1,
                             interval_steps=1, commit_timeout=5)
    # Counters live in the process-default registry (deduped by name),
    # so assert deltas, not absolutes.
    skipped0 = m._m_skipped.value
    try:
        assert m.save(st, step=1)  # writer parks in the diskslow sleep
        assert not m.save(st, step=2)  # single-slot backpressure: skipped
        assert m._m_skipped.value == skipped0 + 1
        m.flush(timeout=30)
    finally:
        m.stop()
        injector.clear()


# ---------------------------------------------------------------------------
# GC


def test_gc_keeps_last_k(tmp_path):
    st = _state()
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1,
                             interval_steps=1, keep=2, commit_timeout=5)
    try:
        for step in (1, 2, 3, 4, 5):
            assert m.save(st, step=step, blocking=True)
    finally:
        m.stop()
    steps = [s for s, _ in ck.list_manifests(str(tmp_path))]
    assert steps == [4, 5]
    # Old shard dirs are gone with their manifests.
    dirs = sorted(n for n in os.listdir(tmp_path)
                  if n.startswith(ck.STEP_DIR_PREFIX))
    assert dirs == [os.path.basename(ck.step_dir("", 4)),
                    os.path.basename(ck.step_dir("", 5))]
    # No tmp debris anywhere.
    for root, _, files in os.walk(tmp_path):
        assert not [f for f in files if atomic_file.is_tmp_debris(f)]


def test_gc_sweeps_orphans_from_abandoned_commits(tmp_path):
    st = _state()
    # An abandoned attempt (kill-all mid-checkpoint) left a shard dir
    # with no manifest.
    d2 = ck.step_dir(str(tmp_path), 2)
    os.makedirs(d2)
    with open(os.path.join(d2, "shard-00000.pkl"), "wb") as f:
        f.write(b"uncommitted")
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1,
                             interval_steps=1, keep=2, commit_timeout=5)
    try:
        assert m.save(st, step=5, blocking=True)
    finally:
        m.stop()
    assert not os.path.exists(d2)
    assert ck.find_latest_manifest(str(tmp_path))[0] == 5


# ---------------------------------------------------------------------------
# KV ack path (the control-plane leg of the two-phase commit)


class _FakeKV:
    """Dict-backed stand-in for backend.rendezvous.RendezvousClient."""

    def __init__(self):
        self.store = {}
        self.lock = threading.Lock()

    def put(self, scope, key, value):
        with self.lock:
            self.store[f"{scope}/{key}"] = value

    def get(self, scope, key):
        with self.lock:
            return self.store.get(f"{scope}/{key}")


def test_kv_acks_and_latest_publish(tmp_path):
    st = _state()
    kv = _FakeKV()
    m1 = ck.CheckpointManager(str(tmp_path), rank=1, size=2,
                              interval_steps=1, commit_timeout=10,
                              rendezvous=kv)
    m0 = ck.CheckpointManager(str(tmp_path), rank=0, size=2,
                              interval_steps=1, commit_timeout=10,
                              rendezvous=kv)
    try:
        assert m1.save(st, step=3, blocking=True)
        assert m0.save(st, step=3, blocking=True)
    finally:
        m0.stop()
        m1.stop()
    # Both ranks acked durability over the KV...
    for r in (0, 1):
        meta = json.loads(kv.get(f"{ck.ACK_SCOPE_PREFIX}3", str(r)).decode())
        assert meta["step"] == 3 and meta["rank"] == r
        # ...and the acked CRC matches the bytes on disk.
        payload = open(os.path.join(str(tmp_path), meta["file"]), "rb").read()
        assert zlib.crc32(payload) == meta["crc32"]
        assert len(payload) == meta["bytes"]
    # Phase 2 published the committed step.
    latest = json.loads(kv.get(ck.LATEST_SCOPE, ck.LATEST_KEY).decode())
    assert latest["step"] == 3 and latest["world_size"] == 2


# ---------------------------------------------------------------------------
# Env wiring + status


def test_manager_from_env(tmp_path, monkeypatch):
    monkeypatch.delenv("HOROVOD_CHECKPOINT_DIR", raising=False)
    assert ck.manager_from_env() is None
    monkeypatch.setenv("HOROVOD_CHECKPOINT_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_CHECKPOINT_INTERVAL_STEPS", "7")
    monkeypatch.setenv("HOROVOD_CHECKPOINT_KEEP", "5")
    m = ck.manager_from_env(rank=2, size=4)
    try:
        assert m is not None
        assert m.rank == 2 and m.size == 4
        assert m.interval_steps == 7 and m.keep == 5
        st = m.status()
        assert st["directory"] == str(tmp_path)
        assert st["last_committed_step"] is None
    finally:
        m.stop()


def test_current_manager_is_status_visible(tmp_path):
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1)
    ck.set_current(m)
    try:
        assert ck.current() is m
        assert "interval_steps" in ck.current().status()
    finally:
        ck.set_current(None)
        m.stop()


def test_restore_purges_stale_acks(tmp_path):
    """Aborted-commit leftovers NEWER than the restore point — above
    all their ``.meta.json`` durability acks — are swept at restore:
    when the restarted run re-reaches the same step number, the commit
    barrier must wait for a FRESH ack, never fill from pre-crash
    bytes."""
    st = _state()
    _write_world(tmp_path, st, step=4, size=2)
    # Kill-all at step 6 mid-commit: rank 1's shard + ack landed
    # before the crash, the manifest did not.
    d6 = ck.step_dir(str(tmp_path), 6)
    os.makedirs(d6)
    stale = os.path.join(d6, "shard-00001.pkl")
    with open(stale, "wb") as f:
        f.write(b"pre-crash bytes")
    with open(stale + ".meta.json", "w") as f:
        json.dump({"format": 1, "step": 6, "rank": 1, "world_size": 2,
                   "file": ck.shard_file(6, 1), "leaves": [3, 6],
                   "bytes": 15,
                   "crc32": zlib.crc32(b"pre-crash bytes")}, f)

    st2 = _fresh_state()
    m0 = ck.CheckpointManager(str(tmp_path), rank=0, size=2,
                              interval_steps=1, commit_timeout=0.3)
    failures0 = m0._m_failures.value
    try:
        assert m0.restore_latest(st2) == 4
        assert not os.path.exists(d6)  # the stale ack is gone
        # The restarted run re-reaches step 6 with rank 1 slower (its
        # write never lands): the barrier must abandon — without the
        # sweep it would have committed a manifest referencing the
        # pre-crash shard.
        m0.save(st2, step=6, blocking=True, timeout=30)
        found = ck.find_latest_manifest(str(tmp_path))
        assert found is not None and found[0] == 4
        assert m0._m_failures.value == failures0 + 1
    finally:
        m0.stop()


def test_fresh_start_sweeps_unrestorable_debris(tmp_path):
    """With NO complete checkpoint, restore sweeps every leftover —
    a fresh run must not inherit stale acks at any step."""
    d3 = ck.step_dir(str(tmp_path), 3)
    os.makedirs(d3)
    with open(os.path.join(d3, "shard-00000.pkl"), "wb") as f:
        f.write(b"junk")
    with open(os.path.join(d3, "shard-00000.pkl.meta.json"), "w") as f:
        json.dump({"step": 3, "rank": 0, "file": ck.shard_file(3, 0),
                   "leaves": [0, 1], "bytes": 4, "crc32": 0}, f)
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1)
    try:
        assert m.restore_latest(_fresh_state()) is None
        assert not os.path.exists(d3)
    finally:
        m.stop()


def test_purge_keeps_manifested_checkpoints_but_sheds_their_acks(tmp_path):
    """A dir WITH a manifest above the restore point is preserved
    (complete = a concurrently-landed real checkpoint; incomplete =
    forensics that discovery skips anyway) — but its sidecar acks are
    shed so they can never fill a repeated commit barrier."""
    st = _state()
    _write_world(tmp_path, st, step=4, size=1)
    # An incomplete newer checkpoint: manifest references a shard that
    # never landed, but another shard + its sidecar did.
    d9 = ck.step_dir(str(tmp_path), 9)
    os.makedirs(d9)
    with open(os.path.join(d9, "shard-00001.pkl"), "wb") as f:
        f.write(b"landed")
    side9 = os.path.join(d9, "shard-00001.pkl.meta.json")
    with open(side9, "w") as f:
        json.dump({"step": 9, "rank": 1, "file": ck.shard_file(9, 1),
                   "leaves": [3, 6], "bytes": 6, "crc32": 0}, f)
    man9 = ck.manifest_path(str(tmp_path), 9)
    with open(man9, "w") as f:
        json.dump({"format": 1, "step": 9, "world_size": 2,
                   "num_leaves": 6, "attrs": [], "attr_counts": {},
                   "objects_shard": 0,
                   "shards": [{"rank": 0, "file": ck.shard_file(9, 0),
                               "leaves": [0, 3], "bytes": 10, "crc32": 0}]},
                  f)
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1)
    try:
        assert m.restore_latest(_fresh_state()) == 4
    finally:
        m.stop()
    assert os.path.exists(man9)                          # forensics kept
    assert os.path.exists(os.path.join(d9, "shard-00001.pkl"))
    assert not os.path.exists(side9)                     # ack disarmed


def test_resync_after_reset_re_anchors_counter(tmp_path):
    """Elastic join: the joiner's counter anchors at the restored step
    while a survivor kept counting — drifted counters would snapshot
    on different commits and no ack barrier would ever fill again.
    resync_after_reset re-anchors both on the newest committed
    manifest, and sweeps manifest-less attempt debris above it (the
    committed manifest itself stays)."""
    st = _state()
    _write_world(tmp_path, st, step=40, size=1)
    # Aborted-attempt debris above the anchor: shard + sidecar ack at
    # step 45, no manifest (the reset interrupted the commit).
    d45 = ck.step_dir(str(tmp_path), 45)
    os.makedirs(d45)
    with open(os.path.join(d45, "shard-00000.pkl"), "wb") as f:
        f.write(b"pre-reset bytes")
    with open(os.path.join(d45, "shard-00000.pkl.meta.json"), "w") as f:
        json.dump({"step": 45, "rank": 0, "file": ck.shard_file(45, 0),
                   "leaves": [0, 6], "bytes": 15, "crc32": 0}, f)
    survivor = ck.CheckpointManager(str(tmp_path), rank=0, size=2,
                                    interval_steps=10, commit_timeout=1)
    joiner = ck.CheckpointManager(str(tmp_path), rank=1, size=2,
                                  interval_steps=10, commit_timeout=1)
    try:
        survivor._commit_count = 57  # counted every commit since start
        assert joiner.restore_latest(_fresh_state()) == 40
        assert joiner._commit_count == 40
        survivor.resync_after_reset()
        joiner.resync_after_reset()
        assert survivor._commit_count == joiner._commit_count == 40
        assert not os.path.exists(d45)  # attempt debris swept
        # ... but the committed checkpoint survives the sweep.
        assert ck.find_latest_manifest(str(tmp_path))[0] == 40
    finally:
        survivor.stop()
        joiner.stop()


def test_resync_cancels_inflight_commit_and_cleans(tmp_path):
    """A coordinator mid-commit at reset time is polling for acks that
    will never come; resync must abandon it promptly (not wedge the
    reset for commit_timeout) and remove the attempt — shards, sidecar
    acks and all."""
    import time

    st = _state()
    m0 = ck.CheckpointManager(str(tmp_path), rank=0, size=2,
                              interval_steps=1, commit_timeout=60)
    try:
        m0.save(st, step=1)  # rank 1 never writes: _commit polls
        d1 = ck.step_dir(str(tmp_path), 1)
        deadline = time.monotonic() + 10
        while not os.path.exists(d1) and time.monotonic() < deadline:
            time.sleep(0.01)
        t0 = time.monotonic()
        m0.resync_after_reset(flush_timeout=30)
        assert time.monotonic() - t0 < 10  # did not wait commit_timeout
        assert ck.find_latest_manifest(str(tmp_path)) is None
        assert not os.path.exists(d1)  # abandoned attempt cleaned up
        assert m0._commit_count == 0
    finally:
        m0.stop()


def test_commit_rejects_ack_not_backed_by_shard(tmp_path):
    """A stale ack whose shard file is gone (swept by the restore or
    reset purges) — e.g. a leftover KV ack — must keep the barrier
    waiting, never fill it: here rank 1's sidecar claims bytes that
    are not on disk, so the commit abandons."""
    st = _state()
    d2 = ck.step_dir(str(tmp_path), 2)
    os.makedirs(d2)
    with open(os.path.join(d2, "shard-00001.pkl.meta.json"), "w") as f:
        json.dump({"step": 2, "rank": 1, "file": ck.shard_file(2, 1),
                   "leaves": [3, 6], "bytes": 15, "crc32": 0}, f)
    m0 = ck.CheckpointManager(str(tmp_path), rank=0, size=2,
                              interval_steps=1, commit_timeout=0.3)
    failures0 = m0._m_failures.value
    try:
        m0.save(st, step=2, blocking=True, timeout=30)
        assert ck.find_latest_manifest(str(tmp_path)) is None
        assert m0._m_failures.value == failures0 + 1
    finally:
        m0.stop()


def test_state_without_hooks_reports_no_durability():
    """The elastic loop gates manager wiring on supports_durability():
    a custom State without the hooks must neither commit (empty)
    checkpoints nor crash a restart trying to load one back."""
    from horovod_tpu.elastic.state import State

    class Custom(State):
        def save(self):
            pass

        def restore(self):
            pass

        def sync(self):
            pass

    assert not Custom().supports_durability()
    assert _state().supports_durability()
    assert ObjectState(x=1).supports_durability()


def test_commit_integration_via_state(tmp_path, hvd_single):
    """state.commit() drives the durability plane end to end (the
    elastic loop's trigger point), including under mesh-mode init."""
    st = _state()
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1,
                             interval_steps=2, commit_timeout=5)
    st.set_checkpoint_manager(m)
    try:
        st.batch = 1
        st.commit()  # commit 1: no checkpoint yet
        assert ck.find_latest_manifest(str(tmp_path)) is None
        st.batch = 2
        st.commit()  # commit 2: checkpoint fires
        m.flush(timeout=30)
        found = ck.find_latest_manifest(str(tmp_path))
        assert found is not None
        # The checkpoint carries the committed batch value.
        st2 = _fresh_state()
        m2 = ck.CheckpointManager(str(tmp_path), rank=0, size=1)
        try:
            assert m2.restore_latest(st2) == found[0]
        finally:
            m2.stop()
        assert st2.batch == 2
    finally:
        st.set_checkpoint_manager(None)
        m.stop()
