"""Operation-manager registry tests.

(ref: horovod/common/ops/operation_manager.cc:42-122 — ordered op lists
per response type, first Enabled() implementation executes;
operations.cc:142-249 CreateOperationManager priority order.)
"""
import threading

import numpy as np
import pytest

from horovod_tpu.backend.threaded import ThreadedGroup
from horovod_tpu.common.message import ResponseType
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.engine.operation_manager import (
    OperationManager,
    OpEntry,
    build_default,
)


def test_first_enabled_wins_and_order_matters():
    mgr = OperationManager()
    calls = []
    mgr.register(ResponseType.ALLREDUCE, OpEntry(
        "SPECIAL", lambda nbytes, reduce_op: nbytes >= 100,
        lambda buf, rop: calls.append("SPECIAL") or buf,
    ))
    mgr.register(ResponseType.ALLREDUCE, OpEntry(
        "FALLBACK", lambda nbytes, reduce_op: True,
        lambda buf, rop: calls.append("FALLBACK") or buf,
    ))
    big = mgr.select(ResponseType.ALLREDUCE, nbytes=200,
                     reduce_op=ReduceOp.SUM)
    small = mgr.select(ResponseType.ALLREDUCE, nbytes=4,
                       reduce_op=ReduceOp.SUM)
    assert big.name == "SPECIAL" and small.name == "FALLBACK"


def test_select_raises_when_nothing_enabled():
    mgr = OperationManager()
    mgr.register(ResponseType.ALLREDUCE, OpEntry(
        "NEVER", lambda **_: False, lambda *a: None))
    with pytest.raises(RuntimeError):
        mgr.select(ResponseType.ALLREDUCE, nbytes=1, reduce_op=ReduceOp.SUM)


def _topo(b, lr, ls, cr, cs, hier):
    b.set_topology(lr, ls, cr, cs)
    b.hierarchical = hier
    return b


def test_build_default_priority(monkeypatch):
    """On a 2x2 hierarchical-toggled backend: hierarchical ring above
    threshold, star below; flat ring when hierarchy invalid; star when
    HOROVOD_CPU_OPERATIONS=star."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "64")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)
    g = ThreadedGroup(4)
    b = _topo(g.backend(0), 0, 2, 0, 2, hier=True)
    mgr = build_default(b)
    names = [e.name for e in mgr.entries(ResponseType.ALLREDUCE)]
    assert names == ["SHM_ARENA_ALLREDUCE", "HIERARCHICAL_RING_ALLREDUCE",
                     "RING_ALLREDUCE", "STAR_ALLREDUCE"]

    pick = lambda n: mgr.select(ResponseType.ALLREDUCE, nbytes=n,
                                reduce_op=ReduceOp.SUM).name
    assert pick(1024) == "HIERARCHICAL_RING_ALLREDUCE"
    assert pick(8) == "STAR_ALLREDUCE"

    b.hierarchical = False
    assert pick(1024) == "RING_ALLREDUCE"

    # Unsupported reduce op for rings -> star regardless of size.
    assert mgr.select(ResponseType.ALLREDUCE, nbytes=1024,
                      reduce_op=ReduceOp.ADASUM).name == "STAR_ALLREDUCE"

    monkeypatch.setenv("HOROVOD_CPU_OPERATIONS", "star")
    b.hierarchical = True
    assert pick(1024) == "STAR_ALLREDUCE"


def test_engine_uses_registry_and_timelines_op_name(tmp_path, monkeypatch):
    """End to end: the engine dispatches through the registry and the
    timeline activity carries the winning op's name (the reference's
    NCCL_ALLREDUCE/MPI_ALLREDUCE lanes, common.h:32-62)."""
    import json
    import os
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_engine import run_ranks

    path = tmp_path / "tl.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(path))
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "64")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)

    def fn(eng, rank):
        big = eng.synchronize(eng.enqueue_allreduce(
            np.full(1000, float(rank + 1), np.float32), name="big"),
            timeout=30)
        small = eng.synchronize(eng.enqueue_allreduce(
            np.full(2, float(rank + 1), np.float32), name="small"),
            timeout=30)
        np.testing.assert_allclose(big, np.full(1000, 3.0))
        np.testing.assert_allclose(small, np.full(2, 3.0))
        return True

    run_ranks(2, fn)
    events = json.loads(path.read_text())
    names = {e.get("name") for e in events}
    assert "RING_ALLREDUCE" in names   # big tensor rode the ring
    assert "STAR_ALLREDUCE" in names   # small tensor stayed on star
