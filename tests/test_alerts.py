"""Alert-engine tests: each rule type (fire -> latch -> resolve,
duration hysteresis, stale-data never fires), the HOROVOD_ALERT_RULES
grammar, fleet folding with rank attribution, and the end-to-end
persistent-straggler scenario on a 2-engine TCP mesh with an injected
`delay:` fault (docs/health.md)."""
import json
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common import alerts, telemetry, timeseries as ts
from horovod_tpu.common.fault_injection import Rule as FaultRule
from horovod_tpu.common import fault_injection


def _store(points, key="m", capacity=64, base=None):
    """Synthetic store: [(t, value-or-snapdict)] with mono stamps offset
    from a base >= now, so last_age() reads ~0 (never stale)."""
    base = time.monotonic() if base is None else base
    st = ts.TimeSeriesStore(capacity)
    for t, v in points:
        snap = v if isinstance(v, dict) else {key: v}
        st.add_sample(snap, wall=t, mono=base + t)
    return st, base


def _engine(store, rules, stale_after=1e9, registry=None, tracer=None):
    return alerts.AlertEngine(
        store, registry or telemetry.MetricsRegistry(), rules=rules,
        rules_spec="", tracer=tracer, stale_after=stale_after)


# ---------------------------------------------------------------------------
# Threshold: fire -> latch -> resolve with duration hysteresis


def test_threshold_fire_latch_resolve_with_hysteresis():
    reg = telemetry.MetricsRegistry()
    rule = alerts.ThresholdRule("hot", "m", threshold=10.0,
                                for_seconds=15.0, clear_seconds=15.0)
    st, base = _store([(0, 20.0)])
    eng = _engine(st, [rule], registry=reg)

    eng.evaluate(st, now=base + 0)     # breach starts: not yet firing
    assert eng.firing() == []
    st.add_sample({"m": 25.0}, wall=10, mono=base + 10)
    eng.evaluate(st, now=base + 10)    # 10s < for_seconds
    assert eng.firing() == []
    st.add_sample({"m": 25.0}, wall=16, mono=base + 16)
    eng.evaluate(st, now=base + 16)    # 16s >= 15 -> FIRE
    assert [f["rule"] for f in eng.firing()] == ["hot"]
    # Clear hysteresis: a momentary dip must not resolve.
    st.add_sample({"m": 1.0}, wall=20, mono=base + 20)
    eng.evaluate(st, now=base + 20)
    assert eng.firing(), "resolved without clear_seconds"
    # Dip interrupted by a new breach: clear window resets.
    st.add_sample({"m": 30.0}, wall=25, mono=base + 25)
    eng.evaluate(st, now=base + 25)
    st.add_sample({"m": 1.0}, wall=30, mono=base + 30)
    eng.evaluate(st, now=base + 30)
    eng.evaluate(st, now=base + 40)
    assert eng.firing(), "clear window did not reset on re-breach"
    eng.evaluate(st, now=base + 46)    # 16s clear -> RESOLVE
    assert eng.firing() == []
    snap = reg.snapshot()
    assert snap['horovod_alerts_total{rule="hot",state="fire"}'] == 1
    assert snap['horovod_alerts_total{rule="hot",state="resolve"}'] == 1
    assert snap["horovod_alerts_firing"] == 0


def test_threshold_breach_window_resets_on_data_gap():
    rule = alerts.ThresholdRule("hot", "m", threshold=10.0,
                                for_seconds=10.0)
    st, base = _store([(0, 20.0)])
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + 0)
    # The metric disappears (owner went away): pending breach drops.
    st.add_sample({}, wall=5, mono=base + 5)
    eng.evaluate(st, now=base + 5)
    st.add_sample({"m": 20.0}, wall=11, mono=base + 11)
    eng.evaluate(st, now=base + 11)  # breach restarts at t=11
    assert eng.firing() == []


def test_threshold_below_and_rate_modes():
    below = alerts.ThresholdRule("low", "m", threshold=5.0, op="below")
    st, base = _store([(0, 2.0)])
    eng = _engine(st, [below])
    eng.evaluate(st, now=base)
    assert [f["rule"] for f in eng.firing()] == ["low"]

    rate = alerts.ThresholdRule("fast", "c", threshold=5.0, mode="rate",
                                window_s=100)
    st2, base2 = _store([(0, 0), (10, 200)], key="c")
    eng2 = _engine(st2, [rate])
    eng2.evaluate(st2, now=base2 + 10)  # 20/s > 5
    assert [f["rule"] for f in eng2.firing()] == ["fast"]


def test_threshold_family_max_names_series():
    rule = alerts.ThresholdRule("hb", "age", threshold=4.0,
                                mode="family_max")
    st, base = _store([(0, {'age{peer="1"}': 1.0, 'age{peer="2"}': 9.0})])
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base)
    f = eng.firing()[0]
    assert f["detail"]["series"] == 'age{peer="2"}'
    assert f["value"] == 9.0


# ---------------------------------------------------------------------------
# Stale data never fires


def test_stale_data_never_fires():
    rule = alerts.ThresholdRule("hot", "m", threshold=10.0)
    st = ts.TimeSeriesStore(8)
    # Newest sample is 100 s old (real monotonic clock).
    st.add_sample({"m": 99.0}, wall=0, mono=time.monotonic() - 100)
    eng = _engine(st, [rule], stale_after=5.0)
    eng.evaluate(st)
    assert eng.firing() == []
    assert eng.status()["stale"] is True
    # An empty store is stale too.
    empty = ts.TimeSeriesStore(8)
    eng2 = _engine(empty, [alerts.ThresholdRule("h", "m", threshold=0)],
                   stale_after=5.0)
    eng2.evaluate(empty)
    assert eng2.status()["stale"] is True and eng2.firing() == []


def test_stale_data_never_resolves_either():
    rule = alerts.ThresholdRule("hot", "m", threshold=10.0)
    st, base = _store([(0, 20.0)])
    eng = _engine(st, [rule], stale_after=1e9)
    eng.evaluate(st, now=base)
    assert eng.firing()
    # Data stops arriving; the latched alert must stay latched.
    eng.stale_after = 0.0
    eng.evaluate(st)
    assert eng.firing(), "stale evaluation resolved a latched alert"


# ---------------------------------------------------------------------------
# Burn rate


def _hist(counts, bounds=(0.05, 0.1, 0.2), total=None, s=0.0):
    counts = list(counts)
    return {"count": total if total is not None else sum(counts),
            "sum": s, "bounds": list(bounds), "counts": counts}


def test_burn_rate_needs_both_windows():
    rule = alerts.BurnRateRule("slo", "h", target_s=0.1, quantile=0.5,
                               fast_window_s=10, slow_window_s=100,
                               min_count=1)
    # Slow history healthy (1000 obs in (0.05, 0.1] across the slow
    # window), recent burst slow (40 obs in (0.1, 0.2] in the fast
    # window) -> fast breaches, slow does not: no fire.
    st, base = _store([
        (0, {"h": _hist([0, 0, 0, 0])}),
        (90, {"h": _hist([0, 1000, 0, 0])}),
        (100, {"h": _hist([0, 1000, 40, 0])}),
    ])
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + 100)
    assert eng.firing() == []
    # Sustained: the slow window's quantile crosses too.
    st2, base2 = _store([
        (0, {"h": _hist([0, 10, 0, 0])}),
        (95, {"h": _hist([0, 10, 3000, 0])}),
        (100, {"h": _hist([0, 10, 4000, 0])}),
    ])
    eng2 = _engine(st2, [rule])
    eng2.evaluate(st2, now=base2 + 100)
    assert [f["rule"] for f in eng2.firing()] == ["slo"]
    assert eng2.firing()[0]["detail"]["target_s"] == 0.1


def test_burn_rate_disarmed_without_target():
    rule = alerts.BurnRateRule("slo", "h", target_s=0.0, min_count=1)
    st, base = _store([(0, {"h": _hist([0, 0, 1000, 0])}),
                       (100, {"h": _hist([0, 0, 9000, 0])})])
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + 100)
    assert eng.firing() == []


def test_burn_rate_min_count_guard():
    rule = alerts.BurnRateRule("slo", "h", target_s=0.01, min_count=50,
                               fast_window_s=10, slow_window_s=100)
    st, base = _store([(0, {"h": _hist([0, 0, 2, 0])}),
                       (100, {"h": _hist([0, 0, 4, 0])})])
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + 100)
    assert eng.firing() == []  # 2 in-window obs < min_count


# ---------------------------------------------------------------------------
# Regression


def _cycle_hist_samples(slow_from=None, n=12, step=30.0):
    """n samples 30 s apart of a cycle-seconds histogram: fast buckets
    fill at 100 obs/sample; from `slow_from` (sample index) on, new
    observations land 2 buckets higher (4x slower)."""
    bounds = [0.01, 0.02, 0.04, 0.08]
    fast = 0
    slow = 0
    out = []
    for i in range(n):
        if slow_from is not None and i >= slow_from:
            slow += 100
        else:
            fast += 100
        out.append((i * step, {
            "h": {"count": fast + slow, "sum": 0.0, "bounds": bounds,
                  "counts": [0, fast, 0, slow, 0]}}))
    return out


def test_regression_fires_on_slowdown():
    rule = alerts.RegressionRule("slow", "h", window_s=30, baselines=5,
                                 min_baselines=2, tolerance=0.75,
                                 min_count=20)
    pts = _cycle_hist_samples(slow_from=11)
    st, base = _store(pts)
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + pts[-1][0])
    f = eng.firing()
    assert [x["rule"] for x in f] == ["slow"], eng.status()["rules"]["slow"]
    assert f[0]["detail"]["ratio"] > 1.75


def test_regression_quiet_on_steady_state_and_cold_start():
    rule = alerts.RegressionRule("slow", "h", window_s=30, baselines=5,
                                 min_baselines=2, tolerance=0.75,
                                 min_count=20)
    pts = _cycle_hist_samples(slow_from=None)
    st, base = _store(pts)
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + pts[-1][0])
    assert eng.firing() == []
    # Cold start: only one window of history -> no baselines -> silent.
    st2, base2 = _store(pts[:2])
    eng2 = _engine(st2, [rule])
    eng2.evaluate(st2, now=base2 + pts[1][0])
    assert eng2.firing() == []


# ---------------------------------------------------------------------------
# Straggler


def _straggler_samples(ranks, act_step=10):
    """Each sample: straggler gauge value + advancing activity."""
    return [(i * 10.0, {"horovod_straggler_rank": r,
                        "horovod_responses_total": (i + 1) * act_step})
            for i, r in enumerate(ranks)]


def test_straggler_k_of_n_with_attribution():
    rule = alerts.StragglerRule("strag", k=4, n=5, for_seconds=0)
    st, base = _store(_straggler_samples([1, 1, 0, 1, 1]))
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + 40)
    f = eng.firing()
    assert f and f[0]["detail"]["rank"] == 1 and f[0]["detail"]["hits"] == 4


def test_straggler_balanced_mesh_quiet():
    rule = alerts.StragglerRule("strag", k=4, n=5, for_seconds=0)
    st, base = _store(_straggler_samples([0, 1, 0, 1, 0]))
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + 40)
    assert eng.firing() == []


def test_straggler_idle_mesh_never_fires():
    """A frozen gauge on an idle mesh (no negotiations) is history,
    not evidence: the activity guard must keep the rule silent."""
    rule = alerts.StragglerRule("strag", k=4, n=5, for_seconds=0)
    pts = [(i * 10.0, {"horovod_straggler_rank": 1,
                       "horovod_responses_total": 50})  # frozen counter
           for i in range(5)]
    st, base = _store(pts)
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + 40)
    assert eng.firing() == []


# ---------------------------------------------------------------------------
# Overdue (checkpoint cadence)


def _commit_samples(commit_times, until, step=10.0):
    out = []
    commits = 0
    t = 0.0
    while t <= until:
        commits += sum(1 for ct in commit_times if t - step < ct <= t)
        out.append((t, {"horovod_checkpoint_commits_total": commits}))
        t += step
    return out


def test_overdue_fires_after_factor_times_cadence():
    rule = alerts.OverdueRule("ckpt", "horovod_checkpoint_commits_total",
                              factor=2.0)
    # Commits every ~30 s until t=120, then silence until t=250:
    # age 130 > 2 x 30.
    st, base = _store(_commit_samples([30, 60, 90, 120], until=250))
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + 250)
    f = eng.firing()
    assert f and f[0]["detail"]["overdue_seconds"] > 120
    # And it resolves when commits restart.
    st.add_sample({"horovod_checkpoint_commits_total": 5},
                  wall=260, mono=base + 260)
    eng.evaluate(st, now=base + 260)
    assert eng.firing() == []


def test_overdue_quiet_on_healthy_cadence_and_without_history():
    rule = alerts.OverdueRule("ckpt", "horovod_checkpoint_commits_total",
                              factor=2.0)
    st, base = _store(_commit_samples([30, 60, 90, 120], until=140))
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base + 140)
    assert eng.firing() == []
    # One commit ever: no cadence to calibrate -> silent forever.
    st2, base2 = _store(_commit_samples([30], until=500))
    eng2 = _engine(st2, [rule])
    eng2.evaluate(st2, now=base2 + 500)
    assert eng2.firing() == []


# ---------------------------------------------------------------------------
# Rule spec grammar


def test_rules_spec_disable_enable_override():
    rules = alerts.default_rules()
    alerts.apply_rules_spec(
        "-cycle_time_regression,"
        "persistent_straggler:k=3:n=4:for_seconds=1.5", rules)
    by = {r.name: r for r in rules}
    assert by["cycle_time_regression"].enabled is False
    strag = by["persistent_straggler"]
    assert strag.enabled and strag.k == 3 and strag.n == 4
    assert strag.for_seconds == pytest.approx(1.5)


def test_rules_spec_none_disables_all():
    rules = alerts.apply_rules_spec("none", alerts.default_rules())
    assert all(not r.enabled for r in rules)


def test_rules_spec_unknown_rule_and_param_are_loud():
    with pytest.raises(ValueError, match="unknown alert rule"):
        alerts.apply_rules_spec("no_such_rule", alerts.default_rules())
    with pytest.raises(ValueError, match="no parameter"):
        alerts.apply_rules_spec("persistent_straggler:bogus=1",
                                alerts.default_rules())
    with pytest.raises(ValueError, match="bad alert override"):
        alerts.apply_rules_spec("persistent_straggler:k",
                                alerts.default_rules())


def test_default_serving_rule_armed_by_env(monkeypatch):
    monkeypatch.delenv("HOROVOD_SERVING_SLO_P99_MS", raising=False)
    by = {r.name: r for r in alerts.default_rules()}
    assert by["serving_p99_slo"].target_s == 0.0  # disarmed
    monkeypatch.setenv("HOROVOD_SERVING_SLO_P99_MS", "250")
    by = {r.name: r for r in alerts.default_rules()}
    assert by["serving_p99_slo"].target_s == pytest.approx(0.25)


# ---------------------------------------------------------------------------
# Spans + fleet fold


def test_alert_spans_land_in_flight_recorder():
    from horovod_tpu.common import tracing

    reg = telemetry.MetricsRegistry()
    tracer = tracing.Tracer(registry=reg, capacity=64)
    rule = alerts.ThresholdRule("hot", "m", threshold=1.0)
    st, base = _store([(0, 5.0)])
    eng = _engine(st, [rule], registry=reg, tracer=tracer)
    eng.evaluate(st, now=base)
    st.add_sample({"m": 0.0}, wall=10, mono=base + 10)
    eng.evaluate(st, now=base + 10)
    names = [(e[2], e[7]) for e in tracer.recorder.snapshot()]
    assert ("alert.fire", {"rule": "hot", "value": 5.0,
                           "threshold": 1.0}) in names
    assert any(n == "alert.resolve" for n, _ in names)


def test_fleet_alerts_fold_and_attribution():
    fleet = alerts.FleetAlerts(4)
    blob = telemetry.encode_push(
        telemetry.MetricsRegistry(), 2,
        extra={"alerts": {"firing": [
            {"rule": "persistent_straggler", "value": 3.0,
             "detail": {"rank": 3}, "since": 1.0}]}})
    fleet.ingest_blob(2, blob)
    fleet.ingest_blob(1, telemetry.encode_push(
        telemetry.MetricsRegistry(), 1, extra={"alerts": {"firing": []}}))
    fleet.ingest_blob(0, b"not json")  # must not throw
    snap = fleet.snapshot()
    assert snap["firing_by_rule"] == {"persistent_straggler": [2]}
    assert snap["ranks"][2]["firing"][0]["detail"]["rank"] == 3
    assert snap["ranks"][1]["firing"] == []


# ---------------------------------------------------------------------------
# End to end: 2-engine TCP mesh, injected delay fault -> rank-attributed
# straggler alert fires at the coordinator, resolves after the clear.


def _tcp_engine_pair(scope, monkeypatch):
    from test_fault_tolerance import _tcp_pair

    from horovod_tpu.engine.engine import Engine

    server, backends = _tcp_pair(scope, monkeypatch)
    regs = [telemetry.MetricsRegistry() for _ in range(2)]
    engines = [Engine(rank=r, size=2, backend=backends[r],
                      registry=regs[r]) for r in range(2)]
    for e in engines:
        e.cycle_time_s = 0.001
    errs = []

    def _start(e):
        try:
            e.start()
        except BaseException as exc:  # pragma: no cover - init bug
            errs.append(exc)

    threads = [threading.Thread(target=_start, args=(e,)) for e in engines]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs, errs
    return server, engines


def test_straggler_alert_end_to_end_with_injected_delay(monkeypatch):
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "30")
    monkeypatch.setenv("HOROVOD_METRICS_SYNC_SECONDS", "0.05")
    monkeypatch.setenv("HOROVOD_METRICS_SAMPLE_SECONDS", "0.1")
    monkeypatch.setenv("HOROVOD_METRICS_HISTORY_SAMPLES", "64")
    monkeypatch.setenv(
        "HOROVOD_ALERT_RULES",
        "persistent_straggler:k=4:n=5:for_seconds=0.2")
    server, engines = _tcp_engine_pair("t_alert_strag", monkeypatch)
    stop = threading.Event()
    errors = []

    def traffic(r):
        i = 0
        try:
            while not stop.is_set():
                h = engines[r].enqueue_allreduce(
                    np.ones(256, np.float32), name="t")
                engines[r].synchronize(h, timeout=60)
                i += 1
                time.sleep(0.01)
        except BaseException as e:  # noqa: BLE001
            errors.append((r, e))

    threads = [threading.Thread(target=traffic, args=(r,))
               for r in range(2)]
    try:
        # Rank 1's sends are always late -> it is the straggler on
        # every negotiation the coordinator sees.
        fault_injection.injector.install(
            [FaultRule(action="delay", rank=1, peer=0, op="send",
                       secs=0.02)])
        for t in threads:
            t.start()
        deadline = time.monotonic() + 60
        fired = None
        while time.monotonic() < deadline and not errors:
            al = engines[0].alerts
            if al is not None:
                f = [x for x in al.firing()
                     if x["rule"] == "persistent_straggler"]
                if f:
                    fired = f[0]
                    break
            time.sleep(0.05)
        assert fired is not None, (errors,
                                   engines[0].alerts.status())
        assert fired["detail"]["rank"] == 1, fired
        # The alert is visible on the /alerts view body and in /status.
        body = engines[0]._alerts_view()
        assert "persistent_straggler" in body["local"]["firing"]
        st = engines[0].status()
        assert "persistent_straggler" in st["alerts"]["firing"]
        # Fleet fold: rank 0's own firing set reaches the fleet view
        # through the ordinary telemetry piggyback.
        fdeadline = time.monotonic() + 30
        while time.monotonic() < fdeadline:
            fleet = engines[0]._fleet_alerts.snapshot()
            if fleet["firing_by_rule"].get("persistent_straggler") == [0]:
                break
            time.sleep(0.05)
        assert fleet["firing_by_rule"]["persistent_straggler"] == [0]
        # Clear the fault: dominance breaks, the alert resolves.
        fault_injection.injector.clear()
        rdeadline = time.monotonic() + 60
        while time.monotonic() < rdeadline and not errors:
            if not engines[0].alerts.firing():
                break
            time.sleep(0.05)
        assert engines[0].alerts.firing() == [], \
            engines[0].alerts.status()
        assert not errors, errors
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        fault_injection.injector.clear()
        stops = [threading.Thread(target=e.shutdown) for e in engines]
        for t in stops:
            t.start()
        for t in stops:
            t.join(timeout=60)
        server.stop()


def test_post_mortem_dump_carries_timeseries_and_alerts(
        tmp_path, monkeypatch):
    """The flight dump written on a fatal latch embeds the scalar
    series and the alert state — the 'what was trending wrong before
    it died' half of the post-mortem."""
    monkeypatch.setenv("HOROVOD_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_METRICS_SAMPLE_SECONDS", "0.1")
    monkeypatch.setenv("HOROVOD_METRICS_HISTORY_SAMPLES", "32")

    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init(mode="process")  # single-rank process engine
    try:
        from horovod_tpu.common import basics

        eng = basics.engine()
        assert eng.sampler is not None and eng.alerts is not None
        eng.synchronize(eng.enqueue_allreduce(
            np.ones(8, np.float32), name="x"), timeout=30)
        eng._dump_post_mortem(RuntimeError("injected for test"))
        flight = json.load(open(tmp_path / "flight_rank0.json"))
        assert flight["timeseries"]["samples"], flight.get("timeseries")
        scalars = flight["timeseries"]["samples"][-1][1]
        assert "horovod_allreduce_bytes_total" in scalars
        assert "firing" in flight["alerts"]
        # And the stitched post-mortem summary counts the series.
        from horovod_tpu.common import tracing

        out = tracing.stitch_post_mortem(str(tmp_path), verdict="test",
                                         expect_ranks=1)
        pm = json.load(open(out))["horovod_postmortem"]
        assert pm["per_rank"]["0"]["timeseries_samples"] > 0
    finally:
        hvd.shutdown()


def test_default_heartbeat_rule_names_the_silent_peer(monkeypatch):
    """The heartbeat_stale default rule: armed from the liveness
    knobs, fires on the max peer age approaching the declaration
    bound, and the detail names the peer's series."""
    monkeypatch.setenv("HOROVOD_HEARTBEAT_INTERVAL_SECONDS", "1")
    monkeypatch.setenv("HOROVOD_HEARTBEAT_MISS_LIMIT", "5")
    by = {r.name: r for r in alerts.default_rules()}
    rule = by["heartbeat_stale"]
    assert rule.enabled and rule.threshold == pytest.approx(4.0)
    st, base = _store([(0, {
        'horovod_heartbeat_age_seconds{peer="1"}': 0.2,
        'horovod_heartbeat_age_seconds{peer="2"}': 4.5,
    })])
    eng = _engine(st, [rule])
    eng.evaluate(st, now=base)
    f = eng.firing()
    assert f and 'peer="2"' in f[0]["detail"]["series"]
    # Liveness plane off -> rule disabled entirely.
    monkeypatch.setenv("HOROVOD_HEARTBEAT_INTERVAL_SECONDS", "0")
    by = {r.name: r for r in alerts.default_rules()}
    assert not by["heartbeat_stale"].enabled


def test_serving_rule_wiring_respects_user_overrides(monkeypatch):
    """serve()'s live re-wiring (queue capacity, SLO target) must not
    clobber parameters the user pinned via HOROVOD_ALERT_RULES."""
    import horovod_tpu.serving as serving_mod
    from horovod_tpu.common import basics

    rules = alerts.apply_rules_spec(
        "serving_p99_slo:target_s=0.05,"
        "admission_queue_saturated:threshold=10",
        alerts.default_rules())
    by = {r.name: r for r in rules}

    class _StubAlerts:
        pass

    class _StubEngine:
        alerts = _StubAlerts()

    _StubEngine.alerts.rules = rules

    class _StubQueue:
        maxsize = 512

    class _StubFrontend:
        queue = _StubQueue()

    monkeypatch.setattr(basics, "engine", lambda: _StubEngine())
    monkeypatch.delenv("HOROVOD_SERVING_SLO_P99_MS", raising=False)
    serving_mod._wire_alert_rules(_StubFrontend())
    # Pinned values survive; without the pin they would have become
    # 0.0 (env unset) and 0.9*512.
    assert by["serving_p99_slo"].target_s == pytest.approx(0.05)
    assert by["admission_queue_saturated"].threshold == pytest.approx(10.0)

    # And WITHOUT user pins the wiring does derive from live config.
    rules2 = alerts.default_rules()
    _StubEngine.alerts.rules = rules2
    serving_mod._wire_alert_rules(_StubFrontend())
    by2 = {r.name: r for r in rules2}
    assert by2["admission_queue_saturated"].threshold == pytest.approx(
        0.9 * 512)
