"""Health-plane time-series tests: sampler ring capacity/drop
accounting, windowed-quantile math vs exact values on synthetic bucket
deltas, rate computation across counter resets, gauge windows, and the
env knobs (docs/health.md)."""
import time

import pytest

from horovod_tpu.common import telemetry, timeseries as ts
from horovod_tpu.utils import env as env_cfg


def _mk_samples(points, key="m"):
    """[(t, value)] -> Sample list (wall == mono == t)."""
    return [(t, t, {key: v}) for t, v in points]


# ---------------------------------------------------------------------------
# Ring capacity / drop accounting


def test_ring_capacity_and_drop_accounting():
    reg = telemetry.MetricsRegistry()
    store = ts.TimeSeriesStore(5, registry=reg)
    for i in range(8):
        store.add_sample({"v": i}, wall=float(i), mono=float(i))
    assert store.depth() == 5
    assert store.dropped == 3
    snap = reg.snapshot()
    assert snap["horovod_timeseries_samples_total"] == 8
    assert snap["horovod_timeseries_samples_dropped_total"] == 3
    # Oldest retained is sample 3 — the ring keeps the newest.
    assert store.samples()[0][2]["v"] == 3


def test_zero_capacity_disables():
    store = ts.TimeSeriesStore(0)
    assert not store.enabled
    store.add_sample({"v": 1})
    assert store.depth() == 0


def test_last_age_before_first_sample():
    store = ts.TimeSeriesStore(4)
    assert store.last_age() == -1.0


# ---------------------------------------------------------------------------
# Counter rates (incl. resets)


def test_counter_rate_simple():
    samples = _mk_samples([(0, 0), (10, 100), (20, 300)])
    # 300 over 20s
    assert ts.counter_rate(samples, "m", window_s=100) == pytest.approx(15.0)


def test_counter_rate_across_reset():
    # 0 -> 100, reset to 5 (contributes 5, not -95), then 25 (+20):
    # total 125 over 30 s.
    samples = _mk_samples([(0, 0), (10, 100), (20, 5), (30, 25)])
    assert ts.counter_rate(samples, "m", window_s=100) == pytest.approx(
        125 / 30)


def test_counter_rate_windows_and_insufficient_data():
    samples = _mk_samples([(0, 0), (10, 100), (20, 200), (30, 330)])
    # Window catches only the last two samples: 130 over 10 s.
    assert ts.counter_rate(samples, "m", window_s=15) == pytest.approx(13.0)
    assert ts.counter_rate(samples[:1], "m", window_s=15) is None
    assert ts.counter_rate([], "m", window_s=15) is None
    assert ts.counter_rate(samples, "missing", window_s=15) is None


# ---------------------------------------------------------------------------
# Windowed histogram quantiles


def test_quantile_from_counts_exact():
    bounds = [0.5, 1.0, 2.0, 4.0]
    # 90 obs in (0.5, 1], 10 in (1, 2].
    counts = [0, 90, 10, 0, 0]
    # p50: target 50 inside the (0.5,1] bucket -> 0.5 + 0.5*50/90
    assert ts.quantile_from_counts(bounds, counts, 0.5) == pytest.approx(
        0.5 + 0.5 * 50 / 90)
    # p99: target 99, cum 90 -> (1,2] bucket -> 1 + 1*(99-90)/10
    assert ts.quantile_from_counts(bounds, counts, 0.99) == pytest.approx(
        1.0 + (99 - 90) / 10)


def test_quantile_overflow_and_empty():
    bounds = [1.0, 2.0]
    assert ts.quantile_from_counts(bounds, [0, 0, 5], 0.5) == 2.0  # +Inf
    assert ts.quantile_from_counts(bounds, [0, 0, 0], 0.5) is None


def test_histogram_window_deltas():
    h0 = {"count": 10, "sum": 5.0, "bounds": [1.0, 2.0],
          "counts": [10, 0, 0]}
    h1 = {"count": 40, "sum": 50.0, "bounds": [1.0, 2.0],
          "counts": [10, 30, 0]}
    samples = [(0, 0, {"h": h0}), (30, 30, {"h": h1})]
    w = ts.histogram_window(samples, "h", window_s=20, now=30)
    assert w["count"] == 30 and w["counts"] == [0, 30, 0]
    assert w["sum"] == pytest.approx(45.0)
    # p50 of the window is inside (1,2] even though the all-time p50
    # straddles both buckets — windowing works.
    assert 1.0 < ts.quantile_from_counts(w["bounds"], w["counts"], 0.5) <= 2.0


def test_histogram_window_reset_falls_back_to_current():
    big = {"count": 100, "sum": 50.0, "bounds": [1.0, 2.0],
           "counts": [100, 0, 0]}
    fresh = {"count": 7, "sum": 3.5, "bounds": [1.0, 2.0],
             "counts": [7, 0, 0]}
    samples = [(0, 0, {"h": big}), (30, 30, {"h": fresh})]
    w = ts.histogram_window(samples, "h", window_s=20, now=30)
    assert w["count"] == 7  # not -93


def test_window_quantile_matches_live_registry_histogram():
    """End to end against a REAL registry histogram: observations made
    between two snapshots must be quantile-recoverable from the
    deltas."""
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("lat", min_exp=-10, max_exp=4)
    for _ in range(50):
        h.observe(0.004)  # noise before the window
    s0 = (0.0, 0.0, reg.snapshot())
    for _ in range(99):
        h.observe(0.010)
    for _ in range(1):
        h.observe(3.0)
    s1 = (60.0, 60.0, reg.snapshot())
    q50 = ts.window_quantile([s0, s1], "lat", 0.5, window_s=50, now=60)
    # 0.010 lands in the (2^-7, 2^-6] bucket.
    assert 2 ** -7 < q50 <= 2 ** -6, q50
    q999 = ts.window_quantile([s0, s1], "lat", 0.999, window_s=50, now=60)
    assert q999 > 2.0, q999


# ---------------------------------------------------------------------------
# Gauge windows + family scan


def test_gauge_window_min_max_last():
    samples = _mk_samples([(0, 5.0), (10, 1.0), (20, 3.0)])
    w = ts.gauge_window(samples, "m", window_s=100)
    assert w == {"min": 1.0, "max": 5.0, "last": 3.0, "count": 3}
    assert ts.gauge_window(samples, "m", window_s=5) == {
        "min": 3.0, "max": 3.0, "last": 3.0, "count": 1}
    assert ts.gauge_window(samples, "nope", window_s=100) is None


def test_gauge_window_skips_nan():
    samples = _mk_samples([(0, 1.0), (10, float("nan")), (20, 2.0)])
    assert ts.gauge_window(samples, "m", 100)["count"] == 2


def test_family_items():
    snap = {"hb": 1.0, 'hb{peer="1"}': 2.0, 'hb{peer="2"}': 3.0,
            "hbx": 9.0}
    fam = ts.family_items(snap, "hb")
    assert sorted(fam) == ["hb", 'hb{peer="1"}', 'hb{peer="2"}']


def test_flatten_scalars():
    snap = {"c": 3, "g": 1.5,
            "h": {"count": 4, "sum": 2.0, "bounds": [1], "counts": [4, 0]},
            "nan": float("nan")}
    flat = ts.flatten_scalars(snap)
    assert flat == {"c": 3, "g": 1.5, "h_count": 4, "h_sum": 2.0}


# ---------------------------------------------------------------------------
# The sampler thread


def test_sampler_thread_ticks_and_callbacks():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("work_total")
    sampler = ts.MetricsSampler(reg, capacity=16, interval=0.05)
    ticks = []
    sampler.add_tick_callback(lambda store: ticks.append(store.depth()))
    sampler.start()
    try:
        deadline = time.monotonic() + 5
        while sampler.store.depth() < 3 and time.monotonic() < deadline:
            c.inc()
            time.sleep(0.02)
        assert sampler.store.depth() >= 3
        assert ticks, "tick callback never ran"
        assert sampler.store.rate("work_total", 60) is not None
        st = sampler.status()
        assert st["enabled"] and st["capacity"] == 16
    finally:
        sampler.stop()
    depth = sampler.store.depth()
    time.sleep(0.15)
    assert sampler.store.depth() == depth  # stopped means stopped


def test_sampler_disabled_by_zero_interval_or_capacity():
    reg = telemetry.MetricsRegistry()
    assert not ts.MetricsSampler(reg, capacity=0, interval=1).enabled
    assert not ts.MetricsSampler(reg, capacity=10, interval=0).enabled
    s = ts.MetricsSampler(reg, capacity=10, interval=0)
    s.start()
    assert s._thread is None


def test_sampler_broken_pull_gauge_does_not_kill_loop():
    reg = telemetry.MetricsRegistry()
    g = reg.gauge("broken")
    g.set_function(lambda: 1 / 0)
    sampler = ts.MetricsSampler(reg, capacity=8, interval=0.05)
    sampler.sample_once()
    # Gauge.value catches the exception and reports NaN; the sample
    # itself lands.
    assert sampler.store.depth() == 1


def test_store_view_shape():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("c_total")
    h = reg.histogram("h_seconds", min_exp=-10, max_exp=2)
    store = ts.TimeSeriesStore(8, registry=reg)
    for i in range(4):
        c.inc(10)
        h.observe(0.01)
        store.add_sample(reg.snapshot(), wall=float(i), mono=float(i))
    view = store.view(window_s=100)
    assert view["depth"] == 4
    assert view["derived"]["c_total"]["rate_per_s"] > 0
    assert view["derived"]["h_seconds"]["kind"] == "histogram"
    assert view["derived"]["h_seconds"]["p50"] is not None
    assert view["points"]["c_total"][-1][1] == 40
    dump = store.dump_scalars(max_samples=2)
    assert len(dump["samples"]) == 2
    assert dump["samples"][-1][1]["h_seconds_count"] == 4


# ---------------------------------------------------------------------------
# Env knobs (the house parse-test convention)


def test_env_sample_seconds(monkeypatch):
    monkeypatch.delenv("HOROVOD_METRICS_SAMPLE_SECONDS", raising=False)
    assert env_cfg.metrics_sample_seconds() == pytest.approx(10.0)
    monkeypatch.setenv("HOROVOD_METRICS_SAMPLE_SECONDS", "2.5")
    assert env_cfg.metrics_sample_seconds() == pytest.approx(2.5)
    monkeypatch.setenv("HOROVOD_METRICS_SAMPLE_SECONDS", "0")
    assert env_cfg.metrics_sample_seconds() == 0.0
    assert not env_cfg.health_plane_enabled()
    # Floor: a tiny positive cadence must not busy-loop.
    monkeypatch.setenv("HOROVOD_METRICS_SAMPLE_SECONDS", "0.001")
    assert env_cfg.metrics_sample_seconds() == pytest.approx(0.05)


def test_env_history_samples(monkeypatch):
    monkeypatch.delenv("HOROVOD_METRICS_HISTORY_SAMPLES", raising=False)
    assert env_cfg.metrics_history_samples() == 360
    monkeypatch.setenv("HOROVOD_METRICS_HISTORY_SAMPLES", "7")
    assert env_cfg.metrics_history_samples() == 7
    monkeypatch.setenv("HOROVOD_METRICS_HISTORY_SAMPLES", "0")
    assert env_cfg.metrics_history_samples() == 0
    assert not env_cfg.health_plane_enabled()
    monkeypatch.setenv("HOROVOD_METRICS_HISTORY_SAMPLES", "-3")
    assert env_cfg.metrics_history_samples() == 0


def test_env_health_plane_enabled_default(monkeypatch):
    monkeypatch.delenv("HOROVOD_METRICS_SAMPLE_SECONDS", raising=False)
    monkeypatch.delenv("HOROVOD_METRICS_HISTORY_SAMPLES", raising=False)
    assert env_cfg.health_plane_enabled()


def test_env_serving_slo(monkeypatch):
    monkeypatch.delenv("HOROVOD_SERVING_SLO_P99_MS", raising=False)
    assert env_cfg.serving_slo_p99_ms() == 0.0
    monkeypatch.setenv("HOROVOD_SERVING_SLO_P99_MS", "150")
    assert env_cfg.serving_slo_p99_ms() == pytest.approx(150.0)
    monkeypatch.setenv("HOROVOD_SERVING_SLO_P99_MS", "-5")
    assert env_cfg.serving_slo_p99_ms() == 0.0


def test_env_alert_rules_spec(monkeypatch):
    monkeypatch.delenv("HOROVOD_ALERT_RULES", raising=False)
    assert env_cfg.alert_rules_spec() == ""
    monkeypatch.setenv("HOROVOD_ALERT_RULES", "-cycle_time_regression")
    assert env_cfg.alert_rules_spec() == "-cycle_time_regression"
    # HVD_TPU_ alias prefix works here like every other knob.
    monkeypatch.delenv("HOROVOD_ALERT_RULES", raising=False)
    monkeypatch.setenv("HVD_TPU_ALERT_RULES", "none")
    assert env_cfg.alert_rules_spec() == "none"


def test_build_info_registration():
    reg = telemetry.MetricsRegistry()
    info = telemetry.register_build_info(reg)
    assert info["version"]
    snap = reg.snapshot()
    key = [k for k in snap if k.startswith("horovod_build_info")]
    assert len(key) == 1 and snap[key[0]] == 1
    assert "jax=" in key[0] and "version=" in key[0]
    assert snap["horovod_uptime_seconds"] > 0
    # Idempotent (init + elastic re-init both call it).
    telemetry.register_build_info(reg)
    assert len([k for k in reg.snapshot()
                if k.startswith("horovod_build_info")]) == 1


def test_histogram_window_honors_past_upper_edge():
    """A window ending in the past (trailing-baseline windows) must not
    absorb observations newer than its `now` — otherwise a regression's
    own slow data inflates every baseline and masks itself."""
    bounds = [1.0, 2.0]
    h0 = {"count": 10, "sum": 0.0, "bounds": bounds, "counts": [10, 0, 0]}
    h1 = {"count": 20, "sum": 0.0, "bounds": bounds, "counts": [20, 0, 0]}
    h2 = {"count": 60, "sum": 0.0, "bounds": bounds, "counts": [20, 40, 0]}
    samples = [(0, 0, {"h": h0}), (30, 30, {"h": h1}),
               (60, 60, {"h": h2})]
    # Baseline window [0, 30]: upper edge = sample@30, base = sample@0
    # -> 10 fast obs only; the 40 slow obs at t=60 must NOT appear.
    w = ts.histogram_window(samples, "h", window_s=30, now=30)
    assert w["counts"] == [10, 0, 0], w
    # Current window [30, 60] sees exactly the slow burst.
    w2 = ts.histogram_window(samples, "h", window_s=30, now=60)
    assert w2["counts"] == [0, 40, 0], w2
    # A `now` before any sample has no upper edge -> None.
    assert ts.histogram_window(samples, "h", window_s=30, now=-5) is None
