"""Native C++ core tests: every kernel is verified against its NumPy
fallback (the reference's pattern of validating Adasum against a NumPy
model, test/test_adasum_pytorch.py).

Property-style coverage (docs/native.md):

* reduce/reduce_into/reduce_strided — BITWISE equality vs the ufunc
  fallback over every dtype x op combo at odd/empty/unaligned sizes;
* codec passes (bf16/fp16/int8) — bitwise native-vs-fallback parity on
  adversarial bit patterns (subnormals, ties, inf/NaN payloads) plus
  fp32-tolerance roundtrips;
* error-feedback residual update — bitwise vs np.subtract+nan_to_num;
* graceful decline: non-contiguous / read-only / mismatched inputs
  return False/None so callers run the numpy path;
* HOROVOD_DISABLE_NATIVE honored per call by every wrapper.
"""
import os

import numpy as np
import pytest

import horovod_tpu.cc.native as native
from horovod_tpu.common import compression
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.backend.base import _reduce
from horovod_tpu.ops.adasum import adasum_numpy

try:
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - baked into the image
    _BF16 = None

# The numpy mirror of each native op (sequential left fold — the order
# the kernels accumulate in, so float results must match bitwise).
_UFUNC = {"sum": np.add, "min": np.minimum, "max": np.maximum,
          "prod": np.multiply}

ALL_DTYPES = [np.dtype(d) for d in (np.float32, np.float64, np.int32,
                                    np.int64, np.uint8, np.float16)]
if _BF16 is not None:
    ALL_DTYPES.append(_BF16)

ODD_SIZES = [0, 1, 3, 257, 1023]


def _rand(dtype, n, seed):
    rng = np.random.RandomState(seed)
    if np.issubdtype(dtype, np.integer):
        # Small positives: prod stays meaningful, u8 wraps identically
        # in C and numpy (mod-256 both sides).
        return rng.randint(1, 5, n).astype(dtype)
    return (rng.rand(n).astype(np.float32) + 0.5).astype(dtype)


@pytest.fixture(scope="module", autouse=True)
def require_native():
    # These tests compare native against fallback, so they must run the
    # native kernels even when the whole suite is driven under
    # HOROVOD_DISABLE_NATIVE=1 (the ci.sh fallback-parity arm): unset
    # it for this module only.
    saved = os.environ.pop("HOROVOD_DISABLE_NATIVE", None)
    # The adaptive size floor would route tiny arrays to numpy on a
    # single-core box; pin it to 0 so every size exercises the kernels.
    saved_floor = os.environ.get("HOROVOD_NATIVE_REDUCE_MIN_BYTES")
    os.environ["HOROVOD_NATIVE_REDUCE_MIN_BYTES"] = "0"
    try:
        # g++ is part of the baked toolchain; the build must succeed.
        assert native.available(), "native core failed to build"
        yield
    finally:
        if saved is not None:
            os.environ["HOROVOD_DISABLE_NATIVE"] = saved
        if saved_floor is None:
            os.environ.pop("HOROVOD_NATIVE_REDUCE_MIN_BYTES", None)
        else:
            os.environ["HOROVOD_NATIVE_REDUCE_MIN_BYTES"] = saved_floor


# -- k-way reduce -------------------------------------------------------
@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                   np.int64])
@pytest.mark.parametrize("op", ["sum", "min", "max", "prod"])
def test_reduce_matches_numpy(op, dtype):
    rng = np.random.RandomState(0)
    if np.issubdtype(dtype, np.integer):
        arrays = [rng.randint(1, 5, 257).astype(dtype) for _ in range(4)]
    else:
        arrays = [rng.rand(257).astype(dtype) + 0.5 for _ in range(4)]
    got = native.reduce_arrays(op, arrays)
    ref = {
        "sum": lambda: np.sum(arrays, axis=0, dtype=dtype),
        "min": lambda: np.minimum.reduce(arrays),
        "max": lambda: np.maximum.reduce(arrays),
        "prod": lambda: np.prod(np.stack(arrays), axis=0, dtype=dtype),
    }[op]()
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    assert got.dtype == dtype


@pytest.mark.parametrize("dtype", ALL_DTYPES, ids=str)
@pytest.mark.parametrize("op", ["sum", "min", "max", "prod"])
def test_reduce_kway_bitwise_widened_dtypes(op, dtype):
    """The widened table (u8/f16/bf16) reduces bitwise like the
    sequential ufunc fold the numpy fallback runs."""
    arrays = [_rand(dtype, 257, 30 + i) for i in range(4)]
    got = native.reduce_arrays(op, arrays)
    assert got is not None and got.dtype == dtype
    ref = arrays[0].copy()
    for a in arrays[1:]:
        _UFUNC[op](ref, a, out=ref)
    assert got.tobytes() == ref.tobytes()


def test_reduce_large_parallel_path():
    rng = np.random.RandomState(1)
    arrays = [rng.rand(1 << 18).astype(np.float32) for _ in range(3)]
    got = native.reduce_arrays("sum", arrays)
    np.testing.assert_allclose(got, np.sum(arrays, axis=0), rtol=1e-5)


def test_reduce_unsupported_dtype_falls_back():
    # complex64 is genuinely outside the dtype table (u8/f16/bf16 are
    # native now — docs/native.md).
    arrays = [np.ones(4, np.complex64) for _ in range(2)]
    assert native.reduce_arrays("sum", arrays) is None
    # _reduce still works through the NumPy path.
    out = _reduce(ReduceOp.SUM, arrays)
    np.testing.assert_array_equal(out, np.full(4, 2, np.complex64))


# -- in-place segment reduce (the ring's recv+reduce step) --------------
@pytest.mark.parametrize("dtype", ALL_DTYPES, ids=str)
@pytest.mark.parametrize("op", ["sum", "min", "max", "prod"])
@pytest.mark.parametrize("n", ODD_SIZES)
def test_reduce_into_bitwise_vs_ufunc(op, dtype, n):
    tgt = _rand(dtype, n, 10)
    src = _rand(dtype, n, 11)
    ref = tgt.copy()
    if n:
        _UFUNC[op](ref, src, out=ref)
    assert native.reduce_into(op, tgt, src)
    assert tgt.tobytes() == ref.tobytes()


def test_reduce_into_unaligned_buffers():
    """Byte-offset views (arena slices land anywhere): still bitwise."""
    n = 257
    raw_t, raw_s = bytearray(4 * n + 1), bytearray(4 * n + 3)
    tgt = np.frombuffer(raw_t, np.float32, n, offset=1)
    src = np.frombuffer(raw_s, np.float32, n, offset=3)
    tgt[:] = _rand(np.float32, n, 40)
    src[:] = _rand(np.float32, n, 41)
    ref = tgt.copy()
    np.add(ref, src, out=ref)
    assert native.reduce_into("sum", tgt, src)
    assert tgt.tobytes() == ref.tobytes()


def test_reduce_into_declines_bad_inputs():
    good = np.ones(10, np.float32)
    # Non-contiguous src / tgt.
    assert not native.reduce_into("sum", good.copy(),
                                  np.arange(20, dtype=np.float32)[::2])
    assert not native.reduce_into("sum",
                                  np.ones(20, np.float32)[::2], good)
    # Read-only target.
    ro = np.ones(10, np.float32)
    ro.setflags(write=False)
    assert not native.reduce_into("sum", ro, good)
    # dtype / size mismatches.
    assert not native.reduce_into("sum", good.copy(),
                                  np.ones(10, np.float64))
    assert not native.reduce_into("sum", good.copy(),
                                  np.ones(11, np.float32))
    assert not native.reduce_into("sum", np.ones(4, np.complex64),
                                  np.ones(4, np.complex64))


def test_reduce_into_size_floor(monkeypatch):
    """HOROVOD_NATIVE_REDUCE_MIN_BYTES routes small arrays back to
    numpy (the ctypes round-trip loses to in-cache ufuncs); the env
    var is read per call so tests and operators can flip it live."""
    tgt = np.ones(256, np.float32)
    src = np.ones(256, np.float32)
    monkeypatch.setenv("HOROVOD_NATIVE_REDUCE_MIN_BYTES", str(1 << 20))
    assert not native.reduce_into("sum", tgt, src)
    monkeypatch.setenv("HOROVOD_NATIVE_REDUCE_MIN_BYTES", "0")
    assert native.reduce_into("sum", tgt, src)
    np.testing.assert_array_equal(tgt, np.full(256, 2, np.float32))


# -- fused arena gather-reduce ------------------------------------------
def _strided_case(nsrc, n, dtype, seed):
    """Arena-shaped byte buffer: nsrc peer slices at offset + r*stride,
    deliberately odd offset/stride, surrounded by random junk the
    kernel must not read or write."""
    rng = np.random.RandomState(seed)
    itemsize = np.dtype(dtype).itemsize
    off0, stride = 24 + itemsize, n * itemsize + 40
    nbytes = off0 + max(nsrc - 1, 0) * stride + n * itemsize + 8
    buf = np.frombuffer(bytearray(rng.bytes(nbytes)), np.uint8).copy()
    srcs = []
    for r in range(nsrc):
        a = _rand(dtype, n, seed + 1 + r)
        start = off0 + r * stride
        buf[start:start + n * itemsize] = a.view(np.uint8)
        srcs.append(a)
    return buf, off0, stride, srcs


@pytest.mark.parametrize("dtype",
                         [np.dtype(np.float32), np.dtype(np.float16)]
                         + ([_BF16] if _BF16 is not None else []),
                         ids=str)
@pytest.mark.parametrize("op", ["sum", "min", "max", "prod"])
def test_reduce_strided_init_bitwise(op, dtype):
    n = 257
    buf, off, stride, srcs = _strided_case(5, n, dtype, 20)
    out = np.empty(n, dtype)
    assert native.reduce_strided(op, buf, off, stride, 5, -1, out,
                                 init=True)
    ref = srcs[0].copy()
    for s in srcs[1:]:
        _UFUNC[op](ref, s, out=ref)
    assert out.tobytes() == ref.tobytes()


@pytest.mark.parametrize("skip", [0, 2, 3])
def test_reduce_strided_accumulate_with_skip(skip):
    """init=False accumulates into the existing out, skipping the root
    slot — the hierarchical reduce_to_member shape."""
    n = 129
    buf, off, stride, srcs = _strided_case(4, n, np.float32, 21)
    out = _rand(np.float32, n, 99)
    ref = out.copy()
    assert native.reduce_strided("sum", buf, off, stride, 4, skip, out,
                                 init=False)
    for r, s in enumerate(srcs):
        if r != skip:
            np.add(ref, s, out=ref)
    assert out.tobytes() == ref.tobytes()


def test_reduce_strided_single_source_is_copy():
    n = 63
    buf, off, stride, srcs = _strided_case(1, n, np.float32, 22)
    out = np.empty(n, np.float32)
    assert native.reduce_strided("sum", buf, off, stride, 1, -1, out,
                                 init=True)
    assert out.tobytes() == srcs[0].tobytes()


def test_reduce_strided_declines_out_of_bounds():
    buf = np.zeros(100, np.uint8)
    out = np.empty(30, np.float32)
    # offset + (nsrc-1)*stride + nbytes = 0 + 100 + 120 > 100.
    assert not native.reduce_strided("sum", buf, 0, 50, 3, -1, out,
                                     init=True)
    # init=True with every source skipped has no seed.
    buf2, off, stride, _ = _strided_case(1, 8, np.float32, 23)
    out2 = np.empty(8, np.float32)
    assert not native.reduce_strided("sum", buf2, off, stride, 1, 0,
                                     out2, init=True)


# -- fusion pack/unpack -------------------------------------------------
def test_pack_unpack_roundtrip_mixed_shapes():
    rng = np.random.RandomState(2)
    arrays = [rng.rand(*s).astype(np.float32)
              for s in [(3, 4), (7,), (2, 2, 2), (1,)]]
    packed = native.pack(arrays)
    assert packed.nbytes == sum(a.nbytes for a in arrays)
    outs = native.unpack(packed, [a.shape for a in arrays], np.float32)
    for a, b in zip(arrays, outs):
        np.testing.assert_array_equal(a, b)


def test_pack_with_empty_segment():
    arrays = [np.arange(3, dtype=np.float32), np.empty(0, np.float32),
              np.ones(2, np.float32)]
    packed = native.pack(arrays)
    assert packed is not None
    assert packed.view(np.float32).tolist() == [0.0, 1.0, 2.0, 1.0, 1.0]


def test_pack_large_parallel_path():
    rng = np.random.RandomState(3)
    arrays = [rng.rand(1 << 17).astype(np.float32) for _ in range(8)]
    packed = native.pack(arrays).view(np.float32)
    np.testing.assert_array_equal(
        packed, np.concatenate([a.ravel() for a in arrays])
    )


# -- wire codec passes --------------------------------------------------
def _adversarial_f32():
    """fp32 arrays hitting every rounding edge: signed zeros, inf, NaN
    payloads, fp16 overflow boundary (65504/65520), fp16 subnormal
    boundary (2^-24/2^-25), fp32 subnormals, RNE ties, plus a dense
    sweep of raw random bit patterns."""
    rng = np.random.RandomState(7)
    specials = np.array(
        [0.0, -0.0, np.inf, -np.inf, np.nan, 65504.0, 65519.0, 65520.0,
         2.0 ** -24, 2.0 ** -25, -(2.0 ** -24), 1e-40, -1e-40, 1.0,
         -1.0, 3.14159, 1e38, -1e38], np.float32)
    bits = rng.randint(0, 2 ** 32, 4096,
                       dtype=np.uint32).view(np.float32)
    return [specials, bits, np.concatenate([specials, bits]),
            np.zeros(0, np.float32),
            np.full(33, np.nan, np.float32),
            np.full(5, np.inf, np.float32)]


@pytest.mark.parametrize("codec_name", ["bf16", "fp16", "int8"])
def test_codec_native_vs_fallback_bitwise(codec_name, monkeypatch):
    """The native encode/decode must emit the exact bytes the numpy
    fallback emits — ranks mixing native and fallback builds would
    otherwise disagree on the wire."""
    codec = compression.codec_by_name(codec_name)
    for i, a in enumerate(_adversarial_f32()):
        monkeypatch.delenv("HOROVOD_DISABLE_NATIVE", raising=False)
        enc_nat = codec.encode(a)
        monkeypatch.setenv("HOROVOD_DISABLE_NATIVE", "1")
        enc_fb = codec.encode(a)
        assert enc_nat.tobytes() == enc_fb.tobytes(), (codec_name, i)
        dec_fb = codec.decode(enc_fb, a.size)
        monkeypatch.delenv("HOROVOD_DISABLE_NATIVE")
        dec_nat = codec.decode(enc_nat, a.size)
        assert dec_nat.tobytes() == dec_fb.tobytes(), (codec_name, i)


def test_fp16_decode_exhaustive_bitwise():
    """All 65536 half patterns — subnormals, NaN payloads, the lot."""
    bits = np.arange(65536, dtype=np.uint16)
    got = native.fp16_decode(bits.tobytes(), bits.size)
    ref = bits.view(np.float16).astype(np.float32)
    assert got.tobytes() == ref.tobytes()


def test_bf16_decode_exhaustive_bitwise():
    if _BF16 is None:
        pytest.skip("ml_dtypes not available")
    bits = np.arange(65536, dtype=np.uint16)
    got = native.bf16_decode(bits.tobytes(), bits.size)
    ref = np.frombuffer(bits.tobytes(), dtype=_BF16).astype(np.float32)
    assert got.tobytes() == ref.tobytes()


@pytest.mark.parametrize("codec_name,rtol", [("bf16", 1.0 / 128),
                                             ("fp16", 1e-3),
                                             ("int8", None)])
def test_codec_roundtrip_tolerance(codec_name, rtol):
    rng = np.random.RandomState(8)
    a = (rng.randn(1001) * 10).astype(np.float32)
    codec = compression.codec_by_name(codec_name)
    out = codec.decode(codec.encode(a), a.size)
    if rtol is None:  # int8: absolute error bounded by scale/2
        scale = float(np.max(np.abs(a))) / 127.0
        assert float(np.max(np.abs(out - a))) <= scale * 0.5 + 1e-7
    else:
        np.testing.assert_allclose(out, a, rtol=rtol, atol=1e-6)


def test_codec_wrappers_decline_bad_inputs():
    noncontig = np.ones(20, np.float32)[::2]
    assert native.bf16_encode(noncontig) is None
    assert native.fp16_encode(noncontig) is None
    assert native.int8_encode(noncontig) is None
    wrong_dtype = np.ones(4, np.float64)
    assert native.bf16_encode(wrong_dtype) is None


# -- error-feedback residual update -------------------------------------
def test_ef_update_bitwise_vs_numpy():
    rng = np.random.RandomState(9)
    pre = rng.randn(513).astype(np.float32)
    wire = (pre + rng.randn(513).astype(np.float32) * 0.01).astype(
        np.float32)
    pre[3], wire[7] = np.inf, np.nan
    pre[11], wire[11] = -np.inf, np.inf
    res = np.empty_like(pre)
    assert native.ef_update(res, pre, wire)
    ref = np.subtract(pre, wire)
    np.nan_to_num(ref, copy=False, nan=0.0, posinf=0.0, neginf=0.0)
    assert res.tobytes() == ref.tobytes()


def test_ef_update_declines_bad_inputs():
    f32 = np.ones(4, np.float32)
    assert not native.ef_update(np.ones(4, np.float64), f32, f32)
    assert not native.ef_update(f32.copy(), f32, np.ones(5, np.float32))
    ro = np.ones(4, np.float32)
    ro.setflags(write=False)
    assert not native.ef_update(ro, f32, f32)


def test_error_feedback_store_matches_fallback(monkeypatch):
    """ErrorFeedback.update lands the same residual either way."""
    rng = np.random.RandomState(12)
    pre = rng.randn(257).astype(np.float32)
    wire = (pre * 0.5).astype(np.float32)
    pre[5] = np.inf

    def run():
        ef = compression.ErrorFeedback()
        ef.put("k", np.zeros(257, np.float32))
        ef.update("k", pre.copy(), wire.copy())
        return ef.get("k", 257).copy()

    got_native = run()
    monkeypatch.setenv("HOROVOD_DISABLE_NATIVE", "1")
    got_fb = run()
    assert got_native.tobytes() == got_fb.tobytes()


# -- adasum -------------------------------------------------------------
@pytest.mark.parametrize("n", [2, 4, 8])
def test_adasum_matches_numpy_oracle(n):
    rng = np.random.RandomState(4)
    arrays = [rng.randn(33).astype(np.float32) for _ in range(n)]
    got = native.adasum(arrays)
    ref = adasum_numpy(arrays)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)
        assert g.dtype == np.float32


def test_adasum_identical_vectors_identity():
    """n identical vectors adasum-combine to the same vector."""
    v = np.linspace(-1, 1, 17).astype(np.float64)
    got = native.adasum([v.copy() for _ in range(4)])
    for g in got:
        np.testing.assert_allclose(g, v, rtol=1e-12)


def test_adasum_rejects_non_power_of_two():
    assert native.adasum([np.ones(4) for _ in range(3)]) is None


# -- dispatch, status, disable ------------------------------------------
def test_reduce_through_backend_dispatch():
    """_reduce uses the native path for f32 and agrees with NumPy."""
    rng = np.random.RandomState(5)
    arrays = [rng.rand(100).astype(np.float32) for _ in range(3)]
    out = _reduce(ReduceOp.AVERAGE, arrays)
    np.testing.assert_allclose(out, np.mean(arrays, axis=0), rtol=1e-6)


def test_status_and_inventory_shape():
    st = native.status()
    assert {"built", "loaded", "disabled", "abi", "threads",
            "kernels"} <= set(st)
    assert st["built"] and st["loaded"] and not st["disabled"]
    assert st["abi"] == native.ABI_VERSION
    inv = native.kernel_inventory()
    assert set(inv) == set(native._KERNELS)
    assert all(inv.values())
    assert native.threads() >= 1


def test_disable_native_env_all_wrappers(monkeypatch):
    """HOROVOD_DISABLE_NATIVE is honored per call: every wrapper
    reports unavailable while set, no reload dance needed."""
    monkeypatch.setenv("HOROVOD_DISABLE_NATIVE", "1")
    assert native.load() is None
    assert native.reduce_arrays("sum",
                                [np.ones(3, np.float32)] * 2) is None
    tgt = np.ones(3, np.float32)
    assert not native.reduce_into("sum", tgt, tgt.copy())
    out = np.empty(3, np.float32)
    assert not native.reduce_strided("sum", np.zeros(64, np.uint8), 0,
                                     16, 2, -1, out, init=True)
    assert native.bf16_encode(np.ones(3, np.float32)) is None
    assert native.fp16_decode(b"\x00" * 6, 3) is None
    assert native.int8_encode(np.ones(3, np.float32)) is None
    assert not native.ef_update(out, tgt, tgt)
    st = native.status()
    assert st["disabled"] and not st["loaded"]
    monkeypatch.delenv("HOROVOD_DISABLE_NATIVE")
    assert native.available()
