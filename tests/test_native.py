"""Native C++ core tests: every kernel is verified against its NumPy
fallback (the reference's pattern of validating Adasum against a NumPy
model, test/test_adasum_pytorch.py)."""
import numpy as np
import pytest

from horovod_tpu.cc import native
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.backend.base import _reduce
from horovod_tpu.ops.adasum import adasum_numpy


@pytest.fixture(scope="module", autouse=True)
def require_native():
    # g++ is part of the baked toolchain; the build must succeed here.
    assert native.available(), "native core failed to build"


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32,
                                   np.int64])
@pytest.mark.parametrize("op", ["sum", "min", "max", "prod"])
def test_reduce_matches_numpy(op, dtype):
    rng = np.random.RandomState(0)
    if np.issubdtype(dtype, np.integer):
        arrays = [rng.randint(1, 5, 257).astype(dtype) for _ in range(4)]
    else:
        arrays = [rng.rand(257).astype(dtype) + 0.5 for _ in range(4)]
    got = native.reduce_arrays(op, arrays)
    ref = {
        "sum": lambda: np.sum(arrays, axis=0, dtype=dtype),
        "min": lambda: np.minimum.reduce(arrays),
        "max": lambda: np.maximum.reduce(arrays),
        "prod": lambda: np.prod(np.stack(arrays), axis=0, dtype=dtype),
    }[op]()
    np.testing.assert_allclose(got, ref, rtol=1e-6)
    assert got.dtype == dtype


def test_reduce_large_parallel_path():
    rng = np.random.RandomState(1)
    arrays = [rng.rand(1 << 18).astype(np.float32) for _ in range(3)]
    got = native.reduce_arrays("sum", arrays)
    np.testing.assert_allclose(got, np.sum(arrays, axis=0), rtol=1e-5)


def test_reduce_unsupported_dtype_falls_back():
    arrays = [np.ones(4, np.uint8) for _ in range(2)]
    assert native.reduce_arrays("sum", arrays) is None
    # _reduce still works through the NumPy path.
    out = _reduce(ReduceOp.SUM, arrays)
    np.testing.assert_array_equal(out, np.full(4, 2, np.uint8))


def test_pack_unpack_roundtrip_mixed_shapes():
    rng = np.random.RandomState(2)
    arrays = [rng.rand(*s).astype(np.float32)
              for s in [(3, 4), (7,), (2, 2, 2), (1,)]]
    packed = native.pack(arrays)
    assert packed.nbytes == sum(a.nbytes for a in arrays)
    outs = native.unpack(packed, [a.shape for a in arrays], np.float32)
    for a, b in zip(arrays, outs):
        np.testing.assert_array_equal(a, b)


def test_pack_large_parallel_path():
    rng = np.random.RandomState(3)
    arrays = [rng.rand(1 << 17).astype(np.float32) for _ in range(8)]
    packed = native.pack(arrays).view(np.float32)
    np.testing.assert_array_equal(
        packed, np.concatenate([a.ravel() for a in arrays])
    )


@pytest.mark.parametrize("n", [2, 4, 8])
def test_adasum_matches_numpy_oracle(n):
    rng = np.random.RandomState(4)
    arrays = [rng.randn(33).astype(np.float32) for _ in range(n)]
    got = native.adasum(arrays)
    ref = adasum_numpy(arrays)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)
        assert g.dtype == np.float32


def test_adasum_identical_vectors_identity():
    """n identical vectors adasum-combine to the same vector."""
    v = np.linspace(-1, 1, 17).astype(np.float64)
    got = native.adasum([v.copy() for _ in range(4)])
    for g in got:
        np.testing.assert_allclose(g, v, rtol=1e-12)


def test_adasum_rejects_non_power_of_two():
    assert native.adasum([np.ones(4) for _ in range(3)]) is None


def test_reduce_through_backend_dispatch():
    """_reduce uses the native path for f32 and agrees with NumPy."""
    rng = np.random.RandomState(5)
    arrays = [rng.rand(100).astype(np.float32) for _ in range(3)]
    out = _reduce(ReduceOp.AVERAGE, arrays)
    np.testing.assert_allclose(out, np.mean(arrays, axis=0), rtol=1e-6)


def test_disable_native_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_DISABLE_NATIVE", "1")
    # Force a fresh load decision.
    import horovod_tpu.cc.native as nat

    old_lib, old_tried = nat._lib, nat._tried
    nat._lib, nat._tried = None, False
    try:
        assert nat.load() is None
        assert nat.reduce_arrays("sum", [np.ones(3, np.float32)] * 2) is None
    finally:
        nat._lib, nat._tried = old_lib, old_tried
