"""Engine + controller tests: negotiation, fusion, response cache, join,
error surfacing — run as N in-process ranks over the threaded backend
(ref test model: test/test_torch.py mpi-ops tests under horovodrun -np 2,
and controller unit behavior in horovod/common/controller.cc)."""
import os
import threading

import numpy as np
import pytest

from horovod_tpu.backend.threaded import ThreadedGroup
from horovod_tpu.common.exceptions import HorovodInternalError
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.engine.engine import Engine


def run_ranks(size, fn, env=None):
    """Run fn(engine, rank) on `size` engines backed by a shared group."""
    group = ThreadedGroup(size)
    engines = [
        Engine(rank=r, size=size, backend=group.backend(r)) for r in range(size)
    ]
    for e in engines:
        e.cycle_time_s = 0.001
        e.start()
    results = [None] * size
    errors = [None] * size

    def worker(r):
        try:
            results[r] = fn(engines[r], r)
        except BaseException as ex:  # noqa: BLE001
            errors[r] = ex

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    # Coordinated shutdown: all engines request together.
    stop_threads = [threading.Thread(target=e.shutdown) for e in engines]
    for t in stop_threads:
        t.start()
    for t in stop_threads:
        t.join(timeout=60)
    for err in errors:
        if err is not None:
            raise err
    return results


def test_allreduce_two_ranks():
    def fn(eng, rank):
        x = np.full(4, float(rank + 1), np.float32)
        return eng.synchronize(eng.enqueue_allreduce(x, name="t"), timeout=30)

    out = run_ranks(2, fn)
    for o in out:
        np.testing.assert_allclose(o, np.full(4, 3.0))


def test_allreduce_average():
    def fn(eng, rank):
        x = np.full(3, float(rank), np.float64)
        h = eng.enqueue_allreduce(x, name="avg", op=ReduceOp.AVERAGE)
        return eng.synchronize(h, timeout=30)

    out = run_ranks(4, fn)
    for o in out:
        np.testing.assert_allclose(o, np.full(3, 1.5))


def test_fusion_multiple_tensors_one_cycle():
    # Many small tensors enqueued together → fused into one response
    # (ref: FuseResponses, controller.cc:686-809).
    K = 8

    def fn(eng, rank):
        handles = [
            eng.enqueue_allreduce(
                np.full(2, float(rank + i), np.float32), name=f"f{i}"
            )
            for i in range(K)
        ]
        return [eng.synchronize(h, timeout=30) for h in handles]

    out = run_ranks(2, fn)
    for i in range(K):
        expected = np.full(2, float(0 + i) + float(1 + i))
        np.testing.assert_allclose(out[0][i], expected)
        np.testing.assert_allclose(out[1][i], expected)


def test_response_cache_steady_state():
    # Same named tensor reduced repeatedly → cache fast path after the
    # first negotiation (ref: response_cache.h:44-167).
    def fn(eng, rank):
        outs = []
        for it in range(5):
            h = eng.enqueue_allreduce(
                np.full(2, float(rank + it), np.float32), name="steady"
            )
            outs.append(eng.synchronize(h, timeout=30))
        return outs

    out = run_ranks(2, fn)
    for it in range(5):
        np.testing.assert_allclose(out[0][it], np.full(2, 2.0 * it + 1.0))


def test_allgather_variable_first_dim():
    # (ref: test_tensorflow.py:1017-1238 variable-size allgather)
    def fn(eng, rank):
        x = np.arange((rank + 1) * 2, dtype=np.float32).reshape(rank + 1, 2)
        return eng.synchronize(eng.enqueue_allgather(x, name="ag"), timeout=30)

    out = run_ranks(3, fn)
    assert out[0].shape == (6, 2)
    np.testing.assert_allclose(out[0], out[1])
    np.testing.assert_allclose(out[0], out[2])


def test_broadcast_from_each_root():
    def fn(eng, rank):
        res = {}
        for root in range(3):
            x = np.full(3, float(rank * 10), np.float32)
            h = eng.enqueue_broadcast(x, root, name=f"b{root}")
            res[root] = eng.synchronize(h, timeout=30)
        return res

    out = run_ranks(3, fn)
    for root in range(3):
        for r in range(3):
            np.testing.assert_allclose(out[r][root], np.full(3, float(root * 10)))


def test_alltoall_uneven_splits():
    # rank r sends (r+1) rows to each peer (ref: alltoall splits,
    # operations.cc:979-1042).
    def fn(eng, rank):
        n = 2 * (rank + 1)
        x = np.arange(n, dtype=np.float32) + 100 * rank
        h = eng.enqueue_alltoall(x, splits=[rank + 1, rank + 1], name="a2a")
        return eng.synchronize(h, timeout=30)

    out = run_ranks(2, fn)
    got0, splits0 = out[0]
    got1, splits1 = out[1]
    assert splits0 == [1, 2]
    assert splits1 == [1, 2]
    np.testing.assert_allclose(got0, [0.0, 100.0, 101.0])
    np.testing.assert_allclose(got1, [1.0, 102.0, 103.0])


def test_shape_mismatch_surfaces_error():
    # (ref: test_tensorflow.py:601-671 error-mismatch negotiation tests)
    def fn(eng, rank):
        shape = (2,) if rank == 0 else (3,)
        h = eng.enqueue_allreduce(np.ones(shape, np.float32), name="bad")
        with pytest.raises(HorovodInternalError, match="[Mm]ismatch"):
            eng.synchronize(h, timeout=30)
        return True

    assert all(run_ranks(2, fn))


def test_dtype_mismatch_surfaces_error():
    def fn(eng, rank):
        dt = np.float32 if rank == 0 else np.float64
        h = eng.enqueue_allreduce(np.ones(2, dt), name="baddt")
        with pytest.raises(HorovodInternalError, match="[Mm]ismatch"):
            eng.synchronize(h, timeout=30)
        return True

    assert all(run_ranks(2, fn))


def test_duplicate_name_rejected():
    def fn(eng, rank):
        # Block negotiation so the first stays in flight: only rank 0
        # enqueues, then enqueues the same name again immediately.
        h1 = eng.enqueue_allreduce(np.ones(2, np.float32), name="dup")
        h2 = eng.enqueue_allreduce(np.ones(2, np.float32), name="dup")
        # One of them must fail with the duplicate-name error unless the
        # first already completed (timing); accept either completion or
        # duplicate error on h2.
        try:
            eng.synchronize(h2, timeout=30)
            dup_err = False
        except HorovodInternalError as e:
            dup_err = "same name" in str(e)
        eng.synchronize(h1, timeout=30)
        return dup_err or True

    assert all(run_ranks(2, fn))


def test_join_uneven_batches():
    # rank 1 exhausts data after 1 step; rank 0 runs 3 steps
    # (ref: controller.cc:220-308 join protocol).
    def fn(eng, rank):
        outs = []
        steps = 3 if rank == 0 else 1
        for i in range(steps):
            h = eng.enqueue_allreduce(
                np.full(2, float(rank + 1), np.float32), name=f"j{i}"
            )
            outs.append(eng.synchronize(h, timeout=30))
        eng.synchronize(eng.enqueue_join(), timeout=30)
        return outs

    out = run_ranks(2, fn)
    np.testing.assert_allclose(out[0][0], np.full(2, 3.0))  # both ranks
    np.testing.assert_allclose(out[0][1], np.full(2, 1.0))  # rank 0 alone
    np.testing.assert_allclose(out[0][2], np.full(2, 1.0))
    np.testing.assert_allclose(out[1][0], np.full(2, 3.0))


def test_barrier():
    def fn(eng, rank):
        eng.synchronize(eng.enqueue_barrier(), timeout=30)
        return True

    assert all(run_ranks(3, fn))


def test_adasum_identical_vectors():
    # Adasum of identical vectors returns the vector itself.
    def fn(eng, rank):
        x = np.array([1.0, 2.0, 3.0], np.float64)
        h = eng.enqueue_allreduce(x, name="ad", op=ReduceOp.ADASUM)
        return eng.synchronize(h, timeout=30)

    out = run_ranks(2, fn)
    for o in out:
        np.testing.assert_allclose(o, [1.0, 2.0, 3.0], rtol=1e-12)


def test_adasum_orthogonal_vectors_sum():
    # Orthogonal vectors: dot=0 → plain sum (ref: adasum.h combination).
    def fn(eng, rank):
        x = np.array([1.0, 0.0] if rank == 0 else [0.0, 1.0], np.float64)
        h = eng.enqueue_allreduce(x, name="ad2", op=ReduceOp.ADASUM)
        return eng.synchronize(h, timeout=30)

    out = run_ranks(2, fn)
    for o in out:
        np.testing.assert_allclose(o, [1.0, 1.0], rtol=1e-12)


def test_allgather_uint8_and_bool_dtypes():
    # Regression: numpy dtype.str for uint8 is '|u1' — the wire header
    # separator must not collide with it.
    def fn(eng, rank):
        a = eng.synchronize(
            eng.enqueue_allgather(np.full(2 + rank, rank, np.uint8), name="u8"),
            timeout=30,
        )
        b = eng.synchronize(
            eng.enqueue_allreduce(np.ones(3, np.float32), name="f32b"), timeout=30
        )
        return a, b

    out = run_ranks(2, fn)
    np.testing.assert_array_equal(out[0][0], np.array([0, 0, 1, 1, 1], np.uint8))
    np.testing.assert_allclose(out[0][1], np.full(3, 2.0))


def test_int_average_not_truncated_to_zero():
    # Regression: postscale 1/size must not be cast to int dtype first.
    def fn(eng, rank):
        x = np.array([2, 4, 6], dtype=np.int64)
        h = eng.enqueue_allreduce(x, name="iavg", op=ReduceOp.AVERAGE)
        return eng.synchronize(h, timeout=30)

    out = run_ranks(2, fn)
    for o in out:
        np.testing.assert_array_equal(o, np.array([2, 4, 6], np.int64))


def test_join_with_cached_steady_state_tensor():
    # Regression: a joined rank must not veto the cache-bit AND nor skip
    # the data plane, or steady-state tensors deadlock after a join.
    def fn(eng, rank):
        steps = 4 if rank == 0 else 2
        outs = []
        for i in range(steps):
            h = eng.enqueue_allreduce(
                np.full(2, float(rank + 1), np.float32), name="steady_join"
            )
            outs.append(eng.synchronize(h, timeout=30))
        eng.synchronize(eng.enqueue_join(), timeout=30)
        return outs

    out = run_ranks(2, fn)
    np.testing.assert_allclose(out[0][0], np.full(2, 3.0))
    np.testing.assert_allclose(out[0][1], np.full(2, 3.0))
    np.testing.assert_allclose(out[0][2], np.full(2, 1.0))  # rank 1 joined
    np.testing.assert_allclose(out[0][3], np.full(2, 1.0))


def test_allgather_rejected_after_join():
    # (ref: controller.cc:487-494 — only allreduce supports join)
    def fn(eng, rank):
        if rank == 1:
            jh = eng.enqueue_join()
            import time as _t
            _t.sleep(0.2)  # let the join land at the coordinator
            eng.synchronize(jh, timeout=30)
            return True
        import time as _t
        _t.sleep(0.1)
        h = eng.enqueue_allgather(np.ones((2, 2), np.float32), name="agj")
        with pytest.raises(HorovodInternalError, match="joined"):
            eng.synchronize(h, timeout=30)
        eng.synchronize(eng.enqueue_join(), timeout=30)
        return True

    assert all(run_ranks(2, fn))


def test_cache_invalidation_shape_change_no_deadlock():
    """Regression: after a tensor is cached (steady state), one rank
    re-submits it with a NEW shape (INVALID) while peers still see a HIT.
    The invalid bit must propagate through the OR pass so every rank
    drops the stale entry and renegotiates — previously the HIT ranks
    parked the request forever (deadlock)."""

    def fn(eng, rank):
        # Warm the cache: two identical-signature cycles.
        for _ in range(2):
            out = eng.synchronize(
                eng.enqueue_allreduce(
                    np.full(4, 1.0, np.float32), name="t"), timeout=30)
        # Same name, new shape on ALL ranks (a legal re-shape, e.g. last
        # batch of an epoch). Every rank flips HIT->INVALID here; the
        # cross-rank case is exercised below.
        out = eng.synchronize(
            eng.enqueue_allreduce(np.full(8, 2.0, np.float32), name="t"),
            timeout=30,
        )
        return out

    out = run_ranks(2, fn)
    for o in out:
        np.testing.assert_allclose(o, np.full(8, 4.0))


def test_cache_hit_invalid_divergence_renegotiates():
    """The cross-rank divergence: rank 0 re-enqueues the cached name with
    the OLD shape (HIT), rank 1 with a NEW shape (INVALID). The negotiated
    result must surface the shape-mismatch error on both ranks rather
    than hanging."""

    def fn(eng, rank):
        for _ in range(2):
            eng.synchronize(
                eng.enqueue_allreduce(
                    np.full(4, 1.0, np.float32), name="t"), timeout=30)
        shape = 4 if rank == 0 else 8
        try:
            eng.synchronize(
                eng.enqueue_allreduce(
                    np.full(shape, 2.0, np.float32), name="t"), timeout=30)
            return None
        except HorovodInternalError as e:
            return str(e)

    out = run_ranks(2, fn)
    for o in out:
        assert o is not None and "Mismatched allreduce tensor shapes" in o
