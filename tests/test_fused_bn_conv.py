"""Fused BN-apply + ReLU + 1x1-conv + stats kernel vs the unfused
composition (interpret mode on CPU; the real win is measured on TPU —
see docs/kernels.md)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.fused_bn_conv import (
    _reference_bn_relu_matmul,
    bn_relu_conv1x1,
    fused_bn_relu_matmul,
)


def _inputs(m=1024, cin=256, cout=128, seed=0, dtype=jnp.bfloat16):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(m, cin), dtype)
    mu = jnp.asarray(rng.randn(cin), jnp.float32) * 0.1
    var = jnp.asarray(rng.rand(cin) + 0.5, jnp.float32)
    gamma = jnp.asarray(rng.rand(cin) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(cin) * 0.1, jnp.float32)
    w = jnp.asarray(rng.randn(cin, cout) / np.sqrt(cin), dtype)
    return x, mu, var, gamma, beta, w


def test_fused_matches_reference():
    args = _inputs()
    y, s1, s2 = fused_bn_relu_matmul(*args, interpret=True)
    yr, s1r, s2r = _reference_bn_relu_matmul(*args, 1e-5)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(yr, np.float32),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(s1, s1r, rtol=2e-2, atol=2.0)
    np.testing.assert_allclose(s2, s2r, rtol=3e-2, atol=3.0)


def test_fused_multiblock_stats_accumulate():
    """M spans several grid blocks: the epilogue must accumulate stats
    across the revisited output block, not overwrite them."""
    args = _inputs(m=2048, cin=128, cout=256)
    y, s1, s2 = fused_bn_relu_matmul(*args, interpret=True, block_m=512)
    _, s1r, s2r = _reference_bn_relu_matmul(*args, 1e-5)
    np.testing.assert_allclose(s1, s1r, rtol=2e-2, atol=4.0)
    np.testing.assert_allclose(s2, s2r, rtol=3e-2, atol=6.0)


def test_custom_vjp_matches_reference_grads():
    args = _inputs(m=512, cin=128, cout=128, dtype=jnp.float32)

    def loss_fused(x, gamma, beta, w):
        y, s1, s2 = bn_relu_conv1x1(x, args[1], args[2], gamma, beta, w)
        return (jnp.sum(y.astype(jnp.float32) ** 2) * 1e-3
                + jnp.sum(s1) * 1e-3 + jnp.sum(s2) * 1e-4)

    def loss_ref(x, gamma, beta, w):
        y, s1, s2 = _reference_bn_relu_matmul(
            x, args[1], args[2], gamma, beta, w, 1e-5)
        return (jnp.sum(y.astype(jnp.float32) ** 2) * 1e-3
                + jnp.sum(s1) * 1e-3 + jnp.sum(s2) * 1e-4)

    x, _, _, gamma, beta, w = args
    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, gamma, beta, w)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, gamma, beta, w)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_block_divisibility_error():
    args = _inputs(m=1000)  # not divisible by 512
    with pytest.raises(ValueError, match="divisible"):
        fused_bn_relu_matmul(*args, interpret=True)
