"""Fused BN-apply + ReLU + 1x1-conv + stats kernel vs the unfused
composition (interpret mode on CPU; the real win is measured on TPU —
see docs/kernels.md)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.fused_bn_conv import (
    _reference_bn_relu_matmul,
    bn_relu_conv1x1,
    fused_bn_relu_matmul,
)


def _inputs(m=1024, cin=256, cout=128, seed=0, dtype=jnp.bfloat16):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(m, cin), dtype)
    mu = jnp.asarray(rng.randn(cin), jnp.float32) * 0.1
    var = jnp.asarray(rng.rand(cin) + 0.5, jnp.float32)
    gamma = jnp.asarray(rng.rand(cin) + 0.5, jnp.float32)
    beta = jnp.asarray(rng.randn(cin) * 0.1, jnp.float32)
    w = jnp.asarray(rng.randn(cin, cout) / np.sqrt(cin), dtype)
    return x, mu, var, gamma, beta, w


@pytest.mark.parametrize("accum", ["scratch", "revisit"])
def test_fused_matches_reference(accum):
    args = _inputs()
    y, s1, s2 = fused_bn_relu_matmul(*args, interpret=True, accum=accum)
    yr, s1r, s2r = _reference_bn_relu_matmul(*args, 1e-5)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(yr, np.float32),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(s1, s1r, rtol=2e-2, atol=2.0)
    np.testing.assert_allclose(s2, s2r, rtol=3e-2, atol=3.0)


@pytest.mark.parametrize("accum", ["scratch", "revisit"])
def test_fused_multiblock_stats_accumulate(accum):
    """M spans several grid blocks: the epilogue must accumulate stats
    across blocks, not overwrite them — in both grid layouts."""
    args = _inputs(m=2048, cin=128, cout=256)
    y, s1, s2 = fused_bn_relu_matmul(*args, interpret=True, block_m=512,
                                     accum=accum)
    _, s1r, s2r = _reference_bn_relu_matmul(*args, 1e-5)
    np.testing.assert_allclose(s1, s1r, rtol=2e-2, atol=4.0)
    np.testing.assert_allclose(s2, s2r, rtol=3e-2, atol=6.0)


def test_custom_vjp_matches_reference_grads():
    args = _inputs(m=512, cin=128, cout=128, dtype=jnp.float32)

    def loss_fused(x, gamma, beta, w):
        y, s1, s2 = bn_relu_conv1x1(x, args[1], args[2], gamma, beta, w)
        return (jnp.sum(y.astype(jnp.float32) ** 2) * 1e-3
                + jnp.sum(s1) * 1e-3 + jnp.sum(s2) * 1e-4)

    def loss_ref(x, gamma, beta, w):
        y, s1, s2 = _reference_bn_relu_matmul(
            x, args[1], args[2], gamma, beta, w, 1e-5)
        return (jnp.sum(y.astype(jnp.float32) ** 2) * 1e-3
                + jnp.sum(s1) * 1e-3 + jnp.sum(s2) * 1e-4)

    x, _, _, gamma, beta, w = args
    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(x, gamma, beta, w)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(x, gamma, beta, w)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_block_divisibility_error():
    args = _inputs(m=1000)  # not divisible by 512
    with pytest.raises(ValueError, match="divisible"):
        fused_bn_relu_matmul(*args, interpret=True)


@pytest.mark.parametrize("shape", [(2, 4, 4, 64), (3, 16, 16, 64)])
def test_fused_module_matches_unfused_composition(shape):
    """FusedBNReluConv1x1 (the model-wired form) == BatchNorm(train) →
    ReLU → 1x1 conv with the same parameters, running stats update
    included. The second shape has M=768 — above the 512 block but not
    a multiple of it — exercising the module's pad-and-slice path."""
    from horovod_tpu.models.resnet import FusedBNReluConv1x1

    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(*shape), jnp.float32)
    mod = FusedBNReluConv1x1(128, dtype=jnp.float32)
    variables = mod.init(jax.random.PRNGKey(0), x, train=True)
    y, updates = mod.apply(x=x, train=True, mutable=["batch_stats"],
                           variables=variables)

    p = variables["params"]
    x2d = np.asarray(x.reshape(-1, 64), np.float64)
    mu = x2d.mean(0)
    var = x2d.var(0)
    ref = np.maximum(
        (x2d - mu) / np.sqrt(var + 1e-5) * np.asarray(p["scale"])
        + np.asarray(p["bias"]), 0.0
    ) @ np.asarray(p["kernel"], np.float64)
    np.testing.assert_allclose(
        np.asarray(y).reshape(-1, 128), ref, rtol=2e-4, atol=2e-4)
    # Running stats moved toward the batch stats (momentum 0.9).
    np.testing.assert_allclose(
        np.asarray(updates["batch_stats"]["mean"]), 0.1 * mu, rtol=1e-3,
        atol=1e-5)


def test_resnet50_fused_stage_trains():
    """resnet50 with fuse_bn_conv_stages=(1,) runs a full train step
    (interpret-mode kernel on CPU) with a finite decreasing loss."""
    import optax

    from horovod_tpu.models import get_model
    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.parallel.train import make_train_step, softmax_xent

    import jax as _jax

    spec = get_model("resnet50")
    model = spec.make_model(num_classes=10, fuse_bn_conv_stages=(1,))
    rng = np.random.RandomState(0)
    n = len(_jax.devices())
    images = rng.rand(n, 32, 32, 3).astype(np.float32)
    labels = rng.randint(0, 10, size=(n,), dtype=np.int32)
    mesh = create_mesh({"dp": n})
    build = make_train_step(model, optax.sgd(0.1, momentum=0.9),
                            softmax_xent, mesh=mesh,
                            has_batch_stats=True)
    init_fn, step_fn, _ = build(jax.random.PRNGKey(0), images, labels)
    state = init_fn(jax.random.PRNGKey(0))
    losses = []
    for _ in range(3):
        state, loss = step_fn(state, images, labels)
        losses.append(float(loss))
    assert all(np.isfinite(v) for v in losses), losses
    assert losses[-1] < losses[0], losses
