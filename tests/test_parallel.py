"""Sequence/context & pipeline parallelism tests on the 8-device CPU
mesh (SURVEY.md §4 lesson: distributed tests without hardware)."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.utils.compat import set_mesh as _set_mesh
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.parallel.pipeline import gpipe, stack_stage_params
from horovod_tpu.parallel.ring import dense_attention, ring_attention
from horovod_tpu.parallel.ulysses import ulysses_attention
from horovod_tpu.utils.compat import shard_map


def _qkv(B=2, S=32, H=4, D=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(B, S, H, D).astype(np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
def test_sp_attention_matches_dense(impl, causal):
    q, k, v = _qkv()
    mesh = create_mesh({"dp": 2, "sp": 4})
    want = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           causal=causal)

    fn = shard_map(
        functools.partial(impl, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_differentiable():
    q, k, v = _qkv(S=16)
    mesh = create_mesh({"dp": 2, "sp": 4})

    def loss(q, k, v):
        f = shard_map(
            functools.partial(ring_attention, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
        )
        return jnp.sum(f(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v)) ** 2)

    g_ring = jax.jit(jax.grad(loss))(q, k, v)
    g_dense = jax.grad(loss_dense)(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=2e-3, atol=2e-4)


# ---------------------------------------------------------------------------
def _mlp_stage(params, x):
    w1, w2 = params["w1"], params["w2"]
    return x + jnp.tanh(x @ w1) @ w2


def _make_stage_params(rng, n_stages, d, dh):
    return {
        "w1": rng.randn(n_stages, d, dh).astype(np.float32) * 0.1,
        "w2": rng.randn(n_stages, dh, d).astype(np.float32) * 0.1,
    }


def test_gpipe_matches_sequential():
    rng = np.random.RandomState(0)
    S, d, dh, B = 4, 8, 16, 8
    params = _make_stage_params(rng, S, d, dh)
    x = rng.randn(B, d).astype(np.float32)

    # Sequential reference.
    want = jnp.asarray(x)
    for s in range(S):
        want = _mlp_stage({"w1": params["w1"][s], "w2": params["w2"][s]}, want)

    mesh = create_mesh({"pp": 4, "dp": 2})
    stacked = stack_stage_params(params, S)  # (S, 1, d, dh)

    def stage_fn(p, act):
        # one layer per stage (inner layer dim 1)
        return _mlp_stage(jax.tree.map(lambda a: a[0], p), act)

    got = jax.jit(
        lambda p, x: gpipe(stage_fn, p, x, mesh=mesh, num_microbatches=4)
    )(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_gpipe_differentiable_and_trains():
    rng = np.random.RandomState(1)
    S, d, dh, B = 2, 4, 8, 8
    params = _make_stage_params(rng, S, d, dh)
    stacked = stack_stage_params(params, S)
    x = rng.randn(B, d).astype(np.float32)
    y = rng.randn(B, d).astype(np.float32)
    mesh = create_mesh({"pp": 2, "dp": 4})

    def stage_fn(p, act):
        return _mlp_stage(jax.tree.map(lambda a: a[0], p), act)

    @jax.jit
    def step(p, x, y):
        def loss(p):
            out = gpipe(stage_fn, p, x, mesh=mesh, num_microbatches=4)
            return jnp.mean((out - y) ** 2)

        l, g = jax.value_and_grad(loss)(p)
        return jax.tree.map(lambda a, b: a - 0.1 * b, p, g), l

    p = jax.tree.map(jnp.asarray, stacked)
    losses = []
    for _ in range(10):
        p, l = step(p, x, y)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9, losses


def test_pipelined_lm_matches_and_trains():
    """PipelinedLM forward ≈ TransformerLM forward on identical params;
    pipelined train step reduces loss (pp×dp×tp mesh)."""
    import flax.linen as nn
    import optax

    from horovod_tpu.models import TransformerConfig, TransformerLM
    from horovod_tpu.models.pipelined import PipelinedLM
    from horovod_tpu.parallel.sharding import PIPELINE_RULES
    from horovod_tpu.parallel.train import lm_loss, make_train_step

    cfg = TransformerConfig(vocab_size=128, d_model=32, n_heads=4,
                            n_layers=4, d_ff=64, max_len=64,
                            scan_layers=True)
    mesh = create_mesh({"pp": 2, "dp": 2, "tp": 2})
    ids = np.random.RandomState(0).randint(0, 128, (8, 16), dtype=np.int32)

    base = TransformerLM(cfg)
    plm = PipelinedLM(cfg, mesh, num_microbatches=4)
    vu = nn.unbox(base.init(jax.random.PRNGKey(0), ids))
    with _set_mesh(mesh):
        out_base = jax.jit(lambda v, i: base.apply(v, i))(vu, ids)
        out_pipe = jax.jit(lambda v, i: plm.apply(v, i))(vu, ids)
    np.testing.assert_allclose(np.asarray(out_base), np.asarray(out_pipe),
                               rtol=5e-2, atol=2e-2)

    build = make_train_step(plm, optax.adam(1e-3), lm_loss, mesh=mesh,
                            rules=PIPELINE_RULES, shard_seq=True)
    init_fn, step_fn, ssh = build(jax.random.PRNGKey(0), ids)
    spec = jax.tree.leaves(ssh.params["stack"]["layers"])[0].spec
    assert "pp" in jax.tree.leaves(tuple(spec))
    state = init_fn(jax.random.PRNGKey(0))
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, ids)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_wrap_step_grad_semantics(hvd_mesh):
    """A jax.grad inside wrap_step must yield the Horovod semantics:
    hvd.allreduce(AVERAGE) of per-rank gradients equals the global-batch
    gradient — not the cross-rank sum (regression: jax's manual-axes
    cotangent auto-psum would inflate grads by world size)."""
    import jax
    import jax.numpy as jnp

    import horovod_tpu as hvd

    X = np.arange(32, dtype=np.float32).reshape(32, 1)
    w = jnp.ones(1)

    def loss_fn(w, xb):
        return jnp.mean(xb[:, 0] * w[0])

    @hvd.wrap_step
    def step(w, xb):
        g = jax.grad(loss_fn)(w, xb)
        return hvd.allreduce(g, op=hvd.ReduceOp.AVERAGE)

    got = np.asarray(step(w, X))
    true_avg = np.asarray(jax.grad(loss_fn)(w, jnp.asarray(X)))
    np.testing.assert_allclose(got, true_avg, rtol=1e-6)


def test_wrap_step_distributed_optimizer_converges(hvd_mesh):
    """Linear regression via wrap_step + DistributedOptimizer: 8 shards,
    sgd(0.3), 30 steps -> loss < 1e-3 (the verify-skill template)."""
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd

    rng = np.random.RandomState(0)
    X = rng.randn(64, 4).astype(np.float32)
    w_true = np.array([1.0, -2.0, 3.0, 0.5], np.float32)
    y = X @ w_true

    tx = hvd.DistributedOptimizer(optax.sgd(0.3), axis_name="hvd")
    w = jnp.zeros(4)
    ostate = tx.init(w)

    def loss_fn(w, xb, yb):
        return jnp.mean((xb @ w - yb) ** 2)

    @hvd.wrap_step
    def step(carry, xb, yb):
        w, ostate = carry
        g = jax.grad(loss_fn)(w, xb, yb)
        u, ostate2 = tx.update(g, ostate)
        return w + u, ostate2

    for _ in range(30):
        w, ostate = step((w, ostate), X, y)
    assert float(loss_fn(w, X, y)) < 1e-3


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("impl", [ring_attention, ulysses_attention])
def test_sp_attention_padding_mask(impl, causal):
    """SP kernels with a BERT-style padding mask match the dense masked
    reference (ring rotates the mask with K/V; Ulysses all-gathers it)."""
    q, k, v = _qkv()
    B, S = q.shape[0], q.shape[1]
    rng = np.random.RandomState(1)
    # Ragged lengths incl. one fully-padded block on the last sp rank.
    lengths = [S - 2, S // 2]
    mask = np.zeros((B, S), np.float32)
    for b, L in enumerate(lengths):
        mask[b, :L] = 1.0
    mesh = create_mesh({"dp": 2, "sp": 4})
    want = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           causal=causal, mask=jnp.asarray(mask))

    fn = shard_map(
        lambda q, k, v, m: impl(q, k, v, axis_name="sp", causal=causal,
                                mask=m),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"),
                  P(None, "sp")),
        out_specs=P(None, "sp"),
    )
    got = jax.jit(fn)(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    assert np.isfinite(np.asarray(got)).all()


def test_ring_attention_mask_differentiable():
    q, k, v = _qkv(S=16)
    B, S = q.shape[0], q.shape[1]
    mask = np.ones((B, S), np.float32)
    mask[:, S // 2:] = 0.0
    mesh = create_mesh({"dp": 2, "sp": 4})

    def loss(q, k, v):
        f = shard_map(
            lambda q, k, v, m: ring_attention(q, k, v, axis_name="sp",
                                              causal=True, mask=m),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp"),
                      P(None, "sp")),
            out_specs=P(None, "sp"),
        )
        return jnp.sum(f(q, k, v, jnp.asarray(mask)) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True,
                                       mask=jnp.asarray(mask)) ** 2)

    g_ring = jax.jit(jax.grad(loss))(q, k, v)
    g_dense = jax.grad(loss_dense)(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense),
                               rtol=2e-3, atol=2e-4)
