"""Elastic driver unit tests (ref: test/test_elastic_driver.py — simulated
discovery, registry transitions, assignment stability, blacklisting; no
real worker processes)."""
import threading
import time

import pytest

from horovod_tpu.runner.elastic.discovery import (
    FixedHosts,
    HostManager,
    HostUpdateResult,
)
from horovod_tpu.runner.elastic.driver import ElasticDriver, INVALID_ROW
from horovod_tpu.runner.elastic.registration import WorkerStateRegistry
from horovod_tpu.runner.rendezvous_server import RendezvousServer


class FakeProc:
    def __init__(self):
        self._rc = None
        self._done = threading.Event()

    def poll(self):
        return self._rc

    def wait(self, timeout=None):
        self._done.wait(timeout)
        return self._rc

    def exit(self, rc):
        self._rc = rc
        self._done.set()

    def terminate(self):
        self.exit(-15)

    def kill(self):
        self.exit(-9)


def make_driver(hosts, min_np, max_np=None, reset_limit=None):
    server = RendezvousServer()  # not started: driver uses handle_* directly
    discovery = FixedHosts(hosts)
    driver = ElasticDriver(server, discovery, min_np, max_np,
                           reset_limit=reset_limit, poll_interval=0.1)
    procs = {}

    def create_worker(slot, extra_env):
        p = FakeProc()
        procs[(slot.hostname, slot.local_rank)] = p
        return p

    return server, discovery, driver, procs, create_worker


def test_host_manager_update_results():
    d = FixedHosts({"a": 2})
    m = HostManager(d)
    assert m.update_available_hosts() == HostUpdateResult.ADDED
    assert m.update_available_hosts() == HostUpdateResult.NO_UPDATE
    d.set({"a": 2, "b": 2})
    assert m.update_available_hosts() == HostUpdateResult.ADDED
    d.set({"b": 2})
    assert m.update_available_hosts() == HostUpdateResult.REMOVED
    d.set({"a": 1})
    assert m.update_available_hosts() == HostUpdateResult.MIXED


def test_host_manager_blacklist_and_order():
    d = FixedHosts({"a": 1, "b": 1, "c": 1})
    m = HostManager(d)
    m.update_available_hosts()
    assert [h for h, _ in m.current_hosts] == ["a", "b", "c"]
    m.blacklist("a")
    assert [h for h, _ in m.current_hosts] == ["b", "c"]
    assert m.available_slots() == 2
    # Oldest-first order is stable across membership churn.
    d.set({"c": 1, "b": 1, "d": 1})
    m.update_available_hosts()
    assert [h for h, _ in m.current_hosts] == ["b", "c", "d"]


def test_driver_initial_assignment_published():
    server, discovery, driver, procs, create = make_driver(
        {"a": 2, "b": 2}, 4)
    driver.start(create)
    try:
        assert driver.epoch == 0
        assert len(procs) == 4
        row = server.handle_get("rank_and_size_e0/a:0")
        assert row is not None and row.decode().startswith("0,4,")
        assert server.handle_get("meta/epoch") == b"0"
    finally:
        driver.stop()


def test_driver_host_added_keeps_old_ranks_stable():
    server, discovery, driver, procs, create = make_driver({"a": 2}, 2, 8)
    driver.start(create)
    try:
        discovery.set({"a": 2, "b": 2})
        deadline = time.monotonic() + 5
        while (driver.epoch < 1 or len(procs) < 4) and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert driver.epoch >= 1
        e = driver.epoch
        # Old host keeps ranks 0-1 (oldest-first order, ref driver.py:227-259)
        assert server.handle_get(f"rank_and_size_e{e}/a:0").decode().startswith("0,4,")
        assert server.handle_get(f"rank_and_size_e{e}/a:1").decode().startswith("1,4,")
        assert server.handle_get(f"rank_and_size_e{e}/b:0").decode().startswith("2,4,")
        assert len(procs) == 4
    finally:
        driver.stop()


def test_driver_worker_failure_blacklists_and_resumes():
    server, discovery, driver, procs, create = make_driver(
        {"a": 1, "b": 1}, 1, 2)
    driver.start(create)
    try:
        # b's worker dies; a's worker parks READY at the barrier.
        procs[("b", 0)].exit(1)
        time.sleep(0.1)
        server.handle_put("ready_e0/a:0", b"1")
        deadline = time.monotonic() + 5
        while driver.epoch < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert driver.epoch >= 1
        assert driver.host_manager.is_blacklisted("b")
        e = driver.epoch
        # New world is a alone, size 1; b's worker got an INVALID row or
        # none (it is dead).
        assert server.handle_get(f"rank_and_size_e{e}/a:0").decode().startswith("0,1,")
        assert not driver.finished
    finally:
        driver.stop()


def test_driver_all_failures_finishes_nonzero():
    server, discovery, driver, procs, create = make_driver({"a": 2}, 2)
    driver.start(create)
    procs[("a", 0)].exit(1)
    procs[("a", 1)].exit(1)
    assert driver.wait(timeout=5) == 1
    driver.stop()


def test_driver_all_success_finishes_zero():
    server, discovery, driver, procs, create = make_driver({"a": 2}, 2)
    driver.start(create)
    procs[("a", 0)].exit(0)
    procs[("a", 1)].exit(0)
    assert driver.wait(timeout=5) == 0
    driver.stop()


def test_reset_limit_enforced():
    server, discovery, driver, procs, create = make_driver(
        {"a": 1, "b": 1, "c": 1}, 1, 3, reset_limit=1)
    driver.start(create)
    try:
        # Failure 1: reset_count=1 <= limit → resume.
        procs[("c", 0)].exit(1)
        server.handle_put("ready_e0/a:0", b"1")
        server.handle_put("ready_e0/b:0", b"1")
        deadline = time.monotonic() + 5
        while driver.epoch < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert driver.epoch >= 1 and not driver.finished
        # Failure 2: exceeds limit → finish(1).
        e = driver.epoch
        procs[("b", 0)].exit(1)
        server.handle_put(f"ready_e{e}/a:0", b"1")
        assert driver.wait(timeout=5) == 1
    finally:
        driver.stop()


def test_host_manager_blacklist_cooldown_then_escalation(monkeypatch):
    """First failure parks the host for the cooldown; a repeat failure
    is permanent (ISSUE 5: cooldown-with-escalation instead of the old
    forever-set)."""
    monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN_SECONDS", "0.2")
    d = FixedHosts({"a": 1, "b": 1})
    m = HostManager(d)
    m.update_available_hosts()
    m.blacklist("a")
    assert m.is_blacklisted("a")
    assert [h for h, _ in m.current_hosts] == ["b"]
    # Cooldown expires: the host is eligible again.
    deadline = time.monotonic() + 5
    while m.is_blacklisted("a") and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not m.is_blacklisted("a")
    assert [h for h, _ in m.current_hosts] == ["a", "b"]
    assert m.blacklist_strikes("a") == 1
    # Second strike: permanent.
    m.blacklist("a")
    time.sleep(0.3)
    assert m.is_blacklisted("a")
    assert m.blacklist_strikes("a") == 2


def test_host_manager_cooldown_expiry_is_an_added_update(monkeypatch):
    """The discovery loop only re-assigns on a non-NO_UPDATE result, so
    a lapsed cooldown must surface as ADDED: the recovered host is
    filtered out of the previous view (pre-prune blacklist) but present
    in the new one — otherwise a driver parked on "not enough slots"
    never sees the host come back."""
    monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN_SECONDS", "0.2")
    m = HostManager(FixedHosts({"a": 1, "b": 1}))
    m.update_available_hosts()
    m.blacklist("a")
    assert m.update_available_hosts() == HostUpdateResult.NO_UPDATE
    time.sleep(0.3)
    assert m.update_available_hosts() == HostUpdateResult.ADDED
    assert m.update_available_hosts() == HostUpdateResult.NO_UPDATE


def test_registry_driver_callouts_run_outside_registry_lock():
    """The barrier action and barrier-opened hook call into the driver,
    whose eviction paths take the driver lock BEFORE querying
    registry.epoch/verdicts — so record() must never hold the registry
    lock across a driver callout (AB-BA deadlock between the watchdog
    timer and the evicted worker's exit monitor)."""
    observed = []

    class _D:
        finished = False

        def _probe(self):
            # Mirrors the driver's lock order: driver-side code under
            # its own lock queries the registry. If record() called us
            # with the registry lock held, this acquire would fail.
            acquired = reg._lock.acquire(blocking=False)
            if acquired:
                reg._lock.release()
            observed.append(acquired)

        def _on_barrier_opened(self, reg_epoch):
            self._probe()

        def finish(self, code):
            self._probe()

        def resume(self):
            self._probe()

    class _H:
        def blacklist(self, host):
            pass

    reg = WorkerStateRegistry(_D(), _H())
    reg.reset(2)
    reg.record_ready("a", 0)       # barrier-opened hook
    reg.record_failure("b", 0)     # barrier action -> resume
    assert len(observed) == 2 and all(observed)


def test_host_manager_blacklist_permanent_with_zero_cooldown(monkeypatch):
    monkeypatch.setenv("HOROVOD_BLACKLIST_COOLDOWN_SECONDS", "0")
    m = HostManager(FixedHosts({"a": 1}))
    m.update_available_hosts()
    m.blacklist("a")
    time.sleep(0.1)
    assert m.is_blacklisted("a")  # the pre-cooldown behavior


def test_registry_one_barrier_action_per_epoch():
    """A late verdict landing after the barrier fired (evicted slot's
    process dying afterwards) must not re-trigger blacklist/resume."""
    actions = []

    class _D:
        finished = False

        def finish(self, code):
            actions.append(("finish", code))

        def resume(self):
            actions.append(("resume",))

    class _H:
        def blacklist(self, host):
            actions.append(("blacklist", host))

    reg = WorkerStateRegistry(_D(), _H())
    reg.reset(2)
    reg.record_ready("a", 0)
    reg.record_failure("b", 0)     # barrier fires: blacklist b + resume
    assert actions == [("blacklist", "b"), ("resume",)]
    reg.record_failure("b", 0)     # late duplicate: no second action
    assert actions == [("blacklist", "b"), ("resume",)]


def test_driver_ready_timeout_evicts_wedged_slot(monkeypatch):
    """3 hosts; b's worker dies, a announces READY, c never answers
    (wedged). The ready-deadline watchdog must kill c's worker, record
    it failed, fire the barrier, blacklist b AND c, and resume with a —
    the barrier can never park forever (ISSUE 5)."""
    monkeypatch.setenv("HOROVOD_ELASTIC_READY_TIMEOUT", "0.5")
    server, discovery, driver, procs, create = make_driver(
        {"a": 1, "b": 1, "c": 1}, 1, 3)
    driver.start(create)
    try:
        procs[("b", 0)].exit(1)          # first verdict arms the watchdog
        server.handle_put("ready_e0/a:0", b"1")
        # c:0 stays silent -> evicted at the deadline.
        deadline = time.monotonic() + 10
        while driver.epoch < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert driver.epoch >= 1, "barrier never fired"
        assert driver.host_manager.is_blacklisted("b")
        assert driver.host_manager.is_blacklisted("c")
        assert procs[("c", 0)].poll() is not None, "wedged worker not killed"
        assert driver._m_evictions.value >= 1
        e = driver.epoch
        row = server.handle_get(f"rank_and_size_e{e}/a:0")
        assert row is not None and row.decode().startswith("0,1,")
        assert not driver.finished
    finally:
        driver.stop()


def test_driver_stale_barrier_opened_hook_never_evicts_healthy_epoch(
        monkeypatch):
    """record() invokes the barrier-opened hook OUTSIDE the registry
    lock, so the hook can be delayed past the barrier's own resolution
    (remaining verdicts land, _activate resets the registry). A stale
    hook must not arm a ready deadline that later expires against the
    NEXT epoch's untouched barrier — that would evict every healthy
    worker on an idle mesh — and a genuine opening of the new barrier
    must replace any stale timer."""
    monkeypatch.setenv("HOROVOD_ELASTIC_READY_TIMEOUT", "0.3")
    server, discovery, driver, procs, create = make_driver(
        {"a": 1, "b": 1}, 1, 2)
    driver.start(create)
    try:
        evictions_before = driver._m_evictions.value  # process-wide counter
        # A hook carrying the token of a barrier that already resolved.
        driver._on_barrier_opened(driver.registry.epoch - 1)
        time.sleep(0.8)  # well past the deadline
        assert driver._m_evictions.value == evictions_before
        assert all(p.poll() is None for p in procs.values())
        assert not driver.finished
        # The stale timer (fired inert) does not shadow a real opening.
        driver._on_barrier_opened(driver.registry.epoch - 1)
        driver._on_barrier_opened(driver.registry.epoch)
        assert driver._watchdog_token == driver.registry.epoch
    finally:
        driver.stop()


def test_driver_liveness_verdict_fast_path_evicts(monkeypatch):
    """A health/verdict_e<epoch> KV put from the coordinator's monitor
    names the dead rank: the driver kills that worker and records the
    failure immediately — blacklisting the host that FAILED, not the
    one that reported."""
    monkeypatch.setenv("HOROVOD_ELASTIC_READY_TIMEOUT", "60")  # not the path
    server, discovery, driver, procs, create = make_driver(
        {"a": 1, "b": 1, "c": 1}, 1, 3)
    driver.start(create)
    try:
        # Coordinator (rank 0 on a) declares rank 2 (c's worker) dead.
        server.handle_put(
            "health/verdict_e0",
            b"2|c|rank 2 (host c) declared dead by rank 0: no heartbeat")
        deadline = time.monotonic() + 5
        while procs[("c", 0)].poll() is None and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert procs[("c", 0)].poll() is not None, "verdict did not evict"
        # Survivors announce ready; the barrier completes normally.
        server.handle_put("ready_e0/a:0", b"1")
        server.handle_put("ready_e0/b:0", b"1")
        deadline = time.monotonic() + 5
        while driver.epoch < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert driver.epoch >= 1
        assert driver.host_manager.is_blacklisted("c")
        assert not driver.host_manager.is_blacklisted("a")
        assert not driver.host_manager.is_blacklisted("b")
        # A stale verdict (old epoch) is ignored.
        server.handle_put("health/verdict_e0", b"0|a|stale")
        time.sleep(0.3)
        assert not driver.host_manager.is_blacklisted("a")
    finally:
        driver.stop()


def test_driver_recovery_duration_histogram(monkeypatch):
    """failure -> re-meshed activation is observed into
    horovod_elastic_recovery_seconds."""
    server, discovery, driver, procs, create = make_driver(
        {"a": 1, "b": 1}, 1, 2)
    driver.start(create)
    try:
        before = driver._m_recovery.count
        procs[("b", 0)].exit(1)
        server.handle_put("ready_e0/a:0", b"1")
        # Poll for the OBSERVATION, not the epoch: the ready put can
        # drive the epoch-1 activation before the exit monitor notes
        # the failure, in which case the recovery sample lands on a
        # later re-activation (same epoch) — waiting on the epoch
        # alone races that by design.
        deadline = time.monotonic() + 5
        while (driver._m_recovery.count < before + 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert driver.epoch >= 1
        assert driver._m_recovery.count == before + 1
    finally:
        driver.stop()


def test_registry_invalid_worker_exit_not_counted():
    """A worker that exits 0 after receiving an INVALID row must not be
    recorded as a SUCCESS verdict for the new epoch."""
    server, discovery, driver, procs, create = make_driver({"a": 2}, 1, 2)
    driver.start(create)
    try:
        discovery.set({"a": 1})  # shrink: a:1 loses its slot
        deadline = time.monotonic() + 5
        while driver.epoch < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        e = driver.epoch
        assert server.handle_get(f"rank_and_size_e{e}/a:1").decode() == INVALID_ROW
        procs[("a", 1)].exit(0)  # removed worker exits cleanly
        time.sleep(0.3)
        assert not driver.finished  # job keeps running with a:0
    finally:
        driver.stop()
