"""Elastic driver unit tests (ref: test/test_elastic_driver.py — simulated
discovery, registry transitions, assignment stability, blacklisting; no
real worker processes)."""
import threading
import time

import pytest

from horovod_tpu.runner.elastic.discovery import (
    FixedHosts,
    HostManager,
    HostUpdateResult,
)
from horovod_tpu.runner.elastic.driver import ElasticDriver, INVALID_ROW
from horovod_tpu.runner.elastic.registration import WorkerStateRegistry
from horovod_tpu.runner.rendezvous_server import RendezvousServer


class FakeProc:
    def __init__(self):
        self._rc = None
        self._done = threading.Event()

    def poll(self):
        return self._rc

    def wait(self, timeout=None):
        self._done.wait(timeout)
        return self._rc

    def exit(self, rc):
        self._rc = rc
        self._done.set()

    def terminate(self):
        self.exit(-15)

    def kill(self):
        self.exit(-9)


def make_driver(hosts, min_np, max_np=None, reset_limit=None):
    server = RendezvousServer()  # not started: driver uses handle_* directly
    discovery = FixedHosts(hosts)
    driver = ElasticDriver(server, discovery, min_np, max_np,
                           reset_limit=reset_limit, poll_interval=0.1)
    procs = {}

    def create_worker(slot, extra_env):
        p = FakeProc()
        procs[(slot.hostname, slot.local_rank)] = p
        return p

    return server, discovery, driver, procs, create_worker


def test_host_manager_update_results():
    d = FixedHosts({"a": 2})
    m = HostManager(d)
    assert m.update_available_hosts() == HostUpdateResult.ADDED
    assert m.update_available_hosts() == HostUpdateResult.NO_UPDATE
    d.set({"a": 2, "b": 2})
    assert m.update_available_hosts() == HostUpdateResult.ADDED
    d.set({"b": 2})
    assert m.update_available_hosts() == HostUpdateResult.REMOVED
    d.set({"a": 1})
    assert m.update_available_hosts() == HostUpdateResult.MIXED


def test_host_manager_blacklist_and_order():
    d = FixedHosts({"a": 1, "b": 1, "c": 1})
    m = HostManager(d)
    m.update_available_hosts()
    assert [h for h, _ in m.current_hosts] == ["a", "b", "c"]
    m.blacklist("a")
    assert [h for h, _ in m.current_hosts] == ["b", "c"]
    assert m.available_slots() == 2
    # Oldest-first order is stable across membership churn.
    d.set({"c": 1, "b": 1, "d": 1})
    m.update_available_hosts()
    assert [h for h, _ in m.current_hosts] == ["b", "c", "d"]


def test_driver_initial_assignment_published():
    server, discovery, driver, procs, create = make_driver(
        {"a": 2, "b": 2}, 4)
    driver.start(create)
    try:
        assert driver.epoch == 0
        assert len(procs) == 4
        row = server.handle_get("rank_and_size_e0/a:0")
        assert row is not None and row.decode().startswith("0,4,")
        assert server.handle_get("meta/epoch") == b"0"
    finally:
        driver.stop()


def test_driver_host_added_keeps_old_ranks_stable():
    server, discovery, driver, procs, create = make_driver({"a": 2}, 2, 8)
    driver.start(create)
    try:
        discovery.set({"a": 2, "b": 2})
        deadline = time.monotonic() + 5
        while (driver.epoch < 1 or len(procs) < 4) and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert driver.epoch >= 1
        e = driver.epoch
        # Old host keeps ranks 0-1 (oldest-first order, ref driver.py:227-259)
        assert server.handle_get(f"rank_and_size_e{e}/a:0").decode().startswith("0,4,")
        assert server.handle_get(f"rank_and_size_e{e}/a:1").decode().startswith("1,4,")
        assert server.handle_get(f"rank_and_size_e{e}/b:0").decode().startswith("2,4,")
        assert len(procs) == 4
    finally:
        driver.stop()


def test_driver_worker_failure_blacklists_and_resumes():
    server, discovery, driver, procs, create = make_driver(
        {"a": 1, "b": 1}, 1, 2)
    driver.start(create)
    try:
        # b's worker dies; a's worker parks READY at the barrier.
        procs[("b", 0)].exit(1)
        time.sleep(0.1)
        server.handle_put("ready_e0/a:0", b"1")
        deadline = time.monotonic() + 5
        while driver.epoch < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert driver.epoch >= 1
        assert driver.host_manager.is_blacklisted("b")
        e = driver.epoch
        # New world is a alone, size 1; b's worker got an INVALID row or
        # none (it is dead).
        assert server.handle_get(f"rank_and_size_e{e}/a:0").decode().startswith("0,1,")
        assert not driver.finished
    finally:
        driver.stop()


def test_driver_all_failures_finishes_nonzero():
    server, discovery, driver, procs, create = make_driver({"a": 2}, 2)
    driver.start(create)
    procs[("a", 0)].exit(1)
    procs[("a", 1)].exit(1)
    assert driver.wait(timeout=5) == 1
    driver.stop()


def test_driver_all_success_finishes_zero():
    server, discovery, driver, procs, create = make_driver({"a": 2}, 2)
    driver.start(create)
    procs[("a", 0)].exit(0)
    procs[("a", 1)].exit(0)
    assert driver.wait(timeout=5) == 0
    driver.stop()


def test_reset_limit_enforced():
    server, discovery, driver, procs, create = make_driver(
        {"a": 1, "b": 1, "c": 1}, 1, 3, reset_limit=1)
    driver.start(create)
    try:
        # Failure 1: reset_count=1 <= limit → resume.
        procs[("c", 0)].exit(1)
        server.handle_put("ready_e0/a:0", b"1")
        server.handle_put("ready_e0/b:0", b"1")
        deadline = time.monotonic() + 5
        while driver.epoch < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert driver.epoch >= 1 and not driver.finished
        # Failure 2: exceeds limit → finish(1).
        e = driver.epoch
        procs[("b", 0)].exit(1)
        server.handle_put(f"ready_e{e}/a:0", b"1")
        assert driver.wait(timeout=5) == 1
    finally:
        driver.stop()


def test_registry_invalid_worker_exit_not_counted():
    """A worker that exits 0 after receiving an INVALID row must not be
    recorded as a SUCCESS verdict for the new epoch."""
    server, discovery, driver, procs, create = make_driver({"a": 2}, 1, 2)
    driver.start(create)
    try:
        discovery.set({"a": 1})  # shrink: a:1 loses its slot
        deadline = time.monotonic() + 5
        while driver.epoch < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        e = driver.epoch
        assert server.handle_get(f"rank_and_size_e{e}/a:1").decode() == INVALID_ROW
        procs[("a", 1)].exit(0)  # removed worker exits cleanly
        time.sleep(0.3)
        assert not driver.finished  # job keeps running with a:0
    finally:
        driver.stop()
