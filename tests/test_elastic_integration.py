"""Elastic end-to-end tests with REAL worker processes and a scripted
discovery whose output changes mid-training (ref test model:
test/integration/elastic_common.py — hosts added, fault tolerance via
injected worker death)."""
import os
import pickle
import subprocess
import sys
import textwrap
import time

import pytest

from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
from horovod_tpu.runner.elastic.driver import ElasticDriver
from horovod_tpu.runner.launch import slot_env, spawn_worker
from horovod_tpu.runner.rendezvous_server import RendezvousServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent(
    """
    import os, pickle, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.backend.elastic_env import spawn_identity
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.elastic.state import ObjectState
    from horovod_tpu.utils import env as env_cfg

    TOTAL = int(os.environ["TEST_TOTAL_BATCHES"])
    FAIL_KEY = os.environ.get("TEST_FAIL_KEY")
    FAIL_SENTINEL = os.environ.get("TEST_FAIL_SENTINEL")

    hvd.init()
    state = ObjectState(batch=0, history=[])

    @hvd.elastic.run
    def train(state):
        while state.batch < TOTAL:
            if (
                FAIL_KEY
                and spawn_identity() == FAIL_KEY
                and not os.path.exists(FAIL_SENTINEL)
                and state.batch >= 3
            ):
                open(FAIL_SENTINEL, "w").close()
                os._exit(1)
            hvd.allreduce(np.ones(2, np.float32), name="g")
            state.history.append((hvd.rank(), hvd.size()))
            state.batch += 1
            state.commit()
            time.sleep(0.05)
        return list(state.history)

    hist = train(state)
    rdv = RendezvousClient(
        env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR),
        env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0),
    )
    rdv.put("test_results", spawn_identity(), pickle.dumps((hvd.rank(), hist)))
    print(f"worker {spawn_identity()} done as rank {hvd.rank()}")
    """
)


def _run_elastic(tmp_path, discovery_script, min_np, max_np, worker_env,
                 timeout=180, on_worker_meshed=None):
    """on_worker_meshed: optional callback fired (from a watcher thread)
    once the first worker has registered its notification endpoint —
    i.e. it is initialized and entering the training loop (a size-1
    worker builds no TCP mesh, so the notify registration is the
    reliable liveness signal). Event-driven replacement for fixed
    sleeps when a test needs to change topology mid-run."""
    os.environ["HVDRUN_FORCE_LOCAL"] = "1"
    server = RendezvousServer()
    port = server.start()

    if on_worker_meshed is not None:
        import threading

        def _watch():
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if server.handle_get("workers_notify/hostA:0") is not None:
                    on_worker_meshed()
                    return
                time.sleep(0.05)

        threading.Thread(target=_watch, daemon=True).start()
    driver = ElasticDriver(
        server, HostDiscoveryScript(discovery_script, 1), min_np, max_np,
        poll_interval=0.25,
    )

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)

    def create_worker(slot, extra_env):
        env = slot_env(slot, "127.0.0.1", port, dict(worker_env),
                       elastic=True)
        env.update(extra_env)
        env["PYTHONPATH"] = REPO
        env["HVDRUN_FORCE_LOCAL"] = "1"
        env["HOROVOD_CYCLE_TIME"] = "1"
        handle = spawn_worker(slot, [sys.executable, str(script)], env,
                              prefix_output=False)
        return handle.proc

    try:
        driver.start(create_worker)
        code = driver.wait(timeout=timeout)
        results = {}
        for key in ("hostA:0", "hostB:0"):
            blob = server.handle_get(f"test_results/{key}")
            if blob is not None:
                results[key] = pickle.loads(blob)
        return code, results
    finally:
        driver.stop()
        server.stop()
        os.environ.pop("HVDRUN_FORCE_LOCAL", None)


def test_elastic_host_added_mid_training(tmp_path):
    """Start with one host; a second appears mid-run. Training must
    continue through the reset and finish at size 2."""
    phase2 = tmp_path / "phase2"
    script = tmp_path / "discover.sh"
    script.write_text(
        f"#!/bin/sh\necho hostA:1\n[ -f {phase2} ] && echo hostB:1\nexit 0\n"
    )
    script.chmod(0o755)

    code, results = _run_elastic(
        tmp_path, str(script), min_np=1, max_np=2,
        worker_env={"TEST_TOTAL_BATCHES": "120"},
        # Event-driven: hostB appears only once hostA's worker is up and
        # training, so batches remain for the post-reset size-2 phase no
        # matter how slow worker startup was.
        on_worker_meshed=phase2.touch,
    )
    assert code == 0, code
    assert "hostA:0" in results
    rank, hist = results["hostA:0"]
    sizes = {s for _, s in hist}
    assert 1 in sizes and 2 in sizes, sizes
    assert "hostB:0" in results  # the added worker also finished


def test_elastic_fault_tolerance_worker_death(tmp_path):
    """Two hosts; hostB's worker kills itself mid-run. The driver must
    blacklist hostB and the survivor finishes alone."""
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho hostA:1\necho hostB:1\n")
    script.chmod(0o755)
    sentinel = tmp_path / "failed_once"

    code, results = _run_elastic(
        tmp_path, str(script), min_np=1, max_np=2,
        worker_env={
            "TEST_TOTAL_BATCHES": "30",
            "TEST_FAIL_KEY": "hostB:0",
            "TEST_FAIL_SENTINEL": str(sentinel),
        },
    )
    assert code == 0, code
    assert sentinel.exists()  # the failure really happened
    assert "hostA:0" in results
    rank, hist = results["hostA:0"]
    sizes = [s for _, s in hist]
    assert 2 in sizes and sizes[-1] == 1, sizes  # shrank to 1 and finished
