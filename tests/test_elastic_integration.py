"""Elastic end-to-end tests with REAL worker processes and a scripted
discovery whose output changes mid-training (ref test model:
test/integration/elastic_common.py — hosts added, fault tolerance via
injected worker death)."""
import os
import pickle
import subprocess
import sys
import textwrap
import time

import pytest

from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
from horovod_tpu.runner.elastic.driver import ElasticDriver
from horovod_tpu.runner.launch import slot_env, spawn_worker
from horovod_tpu.runner.rendezvous_server import RendezvousServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = textwrap.dedent(
    """
    import os, pickle, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.backend.elastic_env import spawn_identity
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.elastic.state import ObjectState
    from horovod_tpu.utils import env as env_cfg

    TOTAL = int(os.environ["TEST_TOTAL_BATCHES"])
    FAIL_KEY = os.environ.get("TEST_FAIL_KEY")
    FAIL_SENTINEL = os.environ.get("TEST_FAIL_SENTINEL")

    hvd.init()
    state = ObjectState(batch=0, history=[])

    @hvd.elastic.run
    def train(state):
        while state.batch < TOTAL:
            if (
                FAIL_KEY
                and spawn_identity() == FAIL_KEY
                and not os.path.exists(FAIL_SENTINEL)
                and state.batch >= 3
            ):
                open(FAIL_SENTINEL, "w").close()
                os._exit(1)
            hvd.allreduce(np.ones(2, np.float32), name="g")
            state.history.append((hvd.rank(), hvd.size()))
            state.batch += 1
            state.commit()
            time.sleep(0.05)
        return list(state.history)

    hist = train(state)
    rdv = RendezvousClient(
        env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR),
        env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0),
    )
    rdv.put("test_results", spawn_identity(), pickle.dumps((hvd.rank(), hist)))
    print(f"worker {spawn_identity()} done as rank {hvd.rank()}")
    """
)


_GSPMD_WORKER = textwrap.dedent(
    """
    import os, pickle, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        # jax >= 0.5 spells the device-count override as a config
        # option; on older versions the XLA_FLAGS above (read at lazy
        # backend creation, after clear_backends below) does the same
        # job — the worker only needs >= 2 devices and slices
        # jax.devices()[:2]. The same dance as tests/conftest.py.
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:
        pass
    import jax.extend.backend as _jeb
    _jeb.clear_backends()
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.backend.elastic_env import spawn_identity
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.elastic.state import JaxState
    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.utils import env as env_cfg

    TOTAL = int(os.environ["TEST_TOTAL_BATCHES"])

    hvd.init()
    TRACES = {"n": 0}

    def build_step():
        # Mesh REBUILD on every (re)entry: a fresh 2-device local mesh
        # and a fresh wrap_step jit. The closure reads hvd.size(), so a
        # topology change makes the retraced computation genuinely
        # different (world-size scaling baked into the trace).
        mesh = create_mesh({"dp": 2}, devices=jax.devices()[:2])
        world = hvd.size()

        def step(w, x, y):
            TRACES["n"] += 1  # python body runs once per TRACE
            def loss_fn(w):
                return ((x @ w - y) ** 2).mean()

            loss, g = jax.value_and_grad(loss_fn)(w)
            # Local-mesh combine on the TRACED plane (XLA psum over the
            # dp axis inside shard_map), pre-scaled for the world
            # average that the engine completes across processes.
            g = hvd.allreduce(g, axis_name="dp") / world
            loss = hvd.allreduce(loss, axis_name="dp")
            return g, loss

        return hvd.wrap_step(step, mesh=mesh, replicated_argnums=(0,))

    state = JaxState(params=np.zeros((4,), np.float32), batch=0,
                     history=[])

    X = np.arange(32.0, dtype=np.float32).reshape(8, 4) / 32.0
    W_TRUE = np.array([1.0, 2.0, -1.0, 0.5], np.float32)
    Y = X @ W_TRUE

    @hvd.elastic.run
    def train(state):
        step = build_step()  # mesh rebuild + retrace after every reset
        while state.batch < TOTAL:
            g_local, loss = step(state.params, X, Y)
            # Cross-worker combine rides the engine (process plane);
            # the traced step already divided by world size.
            g = hvd.allreduce(np.asarray(g_local), name="g",
                              average=False)
            state.params = state.params - 0.5 * np.asarray(g)
            state.history.append(
                (hvd.rank(), hvd.size(), TRACES["n"])
            )
            state.batch += 1
            state.commit()
            time.sleep(0.03)
        return list(state.history), np.asarray(state.params)

    hist, params = train(state)
    rdv = RendezvousClient(
        env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR),
        env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0),
    )
    rdv.put("test_results", spawn_identity(),
            pickle.dumps((hvd.rank(), (hist, params.tolist()))))
    print(f"worker {spawn_identity()} done as rank {hvd.rank()}")
    """
)


def _run_elastic(tmp_path, discovery_script, min_np, max_np, worker_env,
                 timeout=180, on_worker_meshed=None, worker_src=_WORKER):
    """on_worker_meshed: optional callback fired (from a watcher thread)
    once the first worker has registered its notification endpoint —
    i.e. it is initialized and entering the training loop (a size-1
    worker builds no TCP mesh, so the notify registration is the
    reliable liveness signal). Event-driven replacement for fixed
    sleeps when a test needs to change topology mid-run."""
    os.environ["HVDRUN_FORCE_LOCAL"] = "1"
    server = RendezvousServer()
    port = server.start()

    if on_worker_meshed is not None:
        import threading

        def _watch():
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if server.handle_get("workers_notify/hostA:0") is not None:
                    on_worker_meshed()
                    return
                time.sleep(0.05)

        threading.Thread(target=_watch, daemon=True).start()
    driver = ElasticDriver(
        server, HostDiscoveryScript(discovery_script, 1), min_np, max_np,
        poll_interval=0.25,
    )

    script = tmp_path / "worker.py"
    script.write_text(worker_src)

    def create_worker(slot, extra_env):
        env = slot_env(slot, "127.0.0.1", port, dict(worker_env),
                       elastic=True)
        env.update(extra_env)
        env["PYTHONPATH"] = REPO
        env["HVDRUN_FORCE_LOCAL"] = "1"
        env["HOROVOD_CYCLE_TIME"] = "1"
        handle = spawn_worker(slot, [sys.executable, str(script)], env,
                              prefix_output=False)
        return handle.proc

    try:
        driver.start(create_worker)
        code = driver.wait(timeout=timeout)
        results = {}
        for key in ("hostA:0", "hostB:0"):
            blob = server.handle_get(f"test_results/{key}")
            if blob is not None:
                results[key] = pickle.loads(blob)
        return code, results
    finally:
        driver.stop()
        server.stop()
        os.environ.pop("HVDRUN_FORCE_LOCAL", None)


def test_elastic_host_added_mid_training(tmp_path):
    """Start with one host; a second appears mid-run. Training must
    continue through the reset and finish at size 2."""
    phase2 = tmp_path / "phase2"
    script = tmp_path / "discover.sh"
    script.write_text(
        f"#!/bin/sh\necho hostA:1\n[ -f {phase2} ] && echo hostB:1\nexit 0\n"
    )
    script.chmod(0o755)

    code, results = _run_elastic(
        tmp_path, str(script), min_np=1, max_np=2,
        worker_env={"TEST_TOTAL_BATCHES": "120"},
        # Event-driven: hostB appears only once hostA's worker is up and
        # training, so batches remain for the post-reset size-2 phase no
        # matter how slow worker startup was.
        on_worker_meshed=phase2.touch,
    )
    assert code == 0, code
    assert "hostA:0" in results
    rank, hist = results["hostA:0"]
    sizes = {s for _, s in hist}
    assert 1 in sizes and 2 in sizes, sizes
    assert "hostB:0" in results  # the added worker also finished


def test_elastic_fault_tolerance_worker_death(tmp_path):
    """Two hosts; hostB's worker kills itself mid-run. The driver must
    blacklist hostB and the survivor finishes alone."""
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\necho hostA:1\necho hostB:1\n")
    script.chmod(0o755)
    sentinel = tmp_path / "failed_once"

    code, results = _run_elastic(
        tmp_path, str(script), min_np=1, max_np=2,
        worker_env={
            "TEST_TOTAL_BATCHES": "30",
            "TEST_FAIL_KEY": "hostB:0",
            "TEST_FAIL_SENTINEL": str(sentinel),
        },
    )
    assert code == 0, code
    assert sentinel.exists()  # the failure really happened
    assert "hostA:0" in results
    rank, hist = results["hostA:0"]
    sizes = [s for _, s in hist]
    assert 2 in sizes and sizes[-1] == 1, sizes  # shrank to 1 and finished


def test_elastic_gspmd_traced_step_across_topology_change(tmp_path):
    """Elastic over the traced/GSPMD surface (ref: common/elastic.py:
    147-168): the training step is a wrap_step-jitted SPMD function over
    a local 2-device mesh (XLA psum inside shard_map), composed with the
    engine's cross-worker allreduce. A host added mid-run must force a
    mesh rebuild + RETRACE (world size is baked into the trace) with the
    JaxState pytree carried through, and every worker must converge to
    identical weights."""
    phase2 = tmp_path / "phase2"
    script = tmp_path / "discover.sh"
    script.write_text(
        f"#!/bin/sh\necho hostA:1\n[ -f {phase2} ] && echo hostB:1\nexit 0\n"
    )
    script.chmod(0o755)

    code, results = _run_elastic(
        tmp_path, str(script), min_np=1, max_np=2,
        worker_env={"TEST_TOTAL_BATCHES": "40"},
        on_worker_meshed=phase2.touch,
        worker_src=_GSPMD_WORKER,
    )
    assert code == 0, code
    assert "hostA:0" in results and "hostB:0" in results

    rank_a, (hist_a, params_a) = results["hostA:0"]
    rank_b, (hist_b, params_b) = results["hostB:0"]

    # The topology really changed mid-run...
    sizes_a = [s for _, s, _ in hist_a]
    assert 1 in sizes_a and 2 in sizes_a, sizes_a
    # ...and the size change forced a retrace: the step's python body
    # ran again after the reset (trace counter bumped post-change).
    traces_at_size1 = {t for _, s, t in hist_a if s == 1}
    traces_at_size2 = {t for _, s, t in hist_a if s == 2}
    assert traces_at_size2 and max(traces_at_size2) > max(traces_at_size1), (
        hist_a
    )

    # State carried: batches continued past the reset up to TOTAL.
    assert len(hist_a) >= 40, len(hist_a)

    # Both workers end with identical, trained weights (the pytree was
    # re-synced into the grown world and updates stayed consistent).
    import numpy as np

    np.testing.assert_allclose(params_a, params_b, rtol=1e-5)
    w_true = np.array([1.0, 2.0, -1.0, 0.5])
    assert np.abs(np.asarray(params_a) - w_true).mean() < np.abs(w_true).mean(), (
        params_a
    )
