"""Goodput plane tests (docs/goodput.md): step demarcation, exposed-comm
attribution, checkpoint stall, restart/replay badput across elastic
resets and kill-all restarts, the durable ledger stamp, env knobs, the
default alert rules, and the critical-path step grouping."""
import importlib.util
import json
import os
import threading
import time

import pytest

from horovod_tpu.common import goodput, telemetry, tracing
from horovod_tpu.common.types import Status
from horovod_tpu.engine.engine import HandleManager
from horovod_tpu.utils import env as env_cfg

_SPEC = importlib.util.spec_from_file_location(
    "critical_path",
    os.path.join(os.path.dirname(__file__), "..", "scripts",
                 "critical_path.py"))
critical_path = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(critical_path)


def _ledger(**kw):
    kw.setdefault("registry", telemetry.MetricsRegistry())
    kw.setdefault("enabled", True)
    kw.setdefault("stamp_seconds", 0.0)
    return goodput.GoodputLedger(**kw)


# ---------------------------------------------------------------------------
# Step demarcation


def test_explicit_step_scope_times_and_attributes_exposed():
    led = _ledger()
    with led.step():
        led.note_exposed(0.02)
        time.sleep(0.03)
    v = led.view()
    assert v["steps"]["total"] == 1
    assert v["steps"]["current_step"] == 1
    assert v["steps"]["mean_step_seconds"] >= 0.028
    assert v["badput"]["exposed_comm_seconds"] == pytest.approx(0.02)
    # Per-step exposed landed in the histogram the regression rule uses.
    h = led.registry.get("horovod_exposed_comm_step_seconds")
    assert h.count == 1


def test_step_span_lands_in_flight_recorder():
    reg = telemetry.MetricsRegistry()
    tracer = tracing.Tracer(registry=reg, capacity=64)
    led = _ledger(registry=reg, tracer=tracer)
    with led.step():
        led.note_exposed(0.005)
    evs = [e for e in tracer.recorder.snapshot() if e[2] == "step"]
    assert len(evs) == 1
    _, _, name, cat, _, dur, _, args = evs[0]
    assert cat == tracing.CAT_STEP
    assert args["step"] == 1
    assert args["exposed_comm_ms"] == pytest.approx(5.0)


def test_pre_step_waits_do_not_pollute_step_attribution():
    led = _ledger()
    led.note_exposed(1.0)  # initial broadcast wait, before any step
    with led.step():
        led.note_exposed(0.01)
        time.sleep(0.015)  # the wait happened inside this wall time
    h = led.registry.get("horovod_exposed_comm_step_seconds")
    snap = h.snapshot()
    # Total exposed counts both; the step's histogram only its own.
    assert led.view()["badput"]["exposed_comm_seconds"] == pytest.approx(
        1.01)
    assert h.count == 1 and snap["sum"] == pytest.approx(0.01, abs=1e-3)


def test_auto_step_commit_boundaries_count_one_to_one():
    led = _ledger()
    for _ in range(5):
        time.sleep(0.002)
        led.note_commit()
    v = led.view()
    # N commits = N steps (the cursor must track commits for replay
    # accounting); the FIRST closes an unobserved-start step, so only
    # N-1 carry durations.
    assert v["steps"]["total"] == 5
    assert v["steps"]["committed_step"] == 5
    assert led.timed_steps == 4


def test_source_priority_explicit_beats_optim_beats_commit():
    led = _ledger()
    led.auto_step("commit")
    led.auto_step("commit")
    assert led.steps == 2
    # The optimizer path takes over: commit boundaries stop counting.
    led.auto_step("optim")
    led.auto_step("commit")
    led.auto_step("commit")
    assert led.steps == 3
    # An explicit scope takes over from the optimizer.
    with led.step():
        pass
    led.auto_step("optim")
    led.auto_step("commit")
    assert led.steps == 4


def test_commit_still_tracks_committed_cursor_when_not_the_step_source():
    led = _ledger()
    with led.step():
        pass
    with led.step():
        pass
    led.note_commit()  # boundary ignored (explicit owns steps) ...
    v = led.view()
    assert v["steps"]["total"] == 2
    assert v["steps"]["committed_step"] == 2  # ... but the cursor moves


# ---------------------------------------------------------------------------
# Replay + restore accounting


def test_restore_counts_lost_steps_once_and_never_negative():
    led = _ledger()
    for _ in range(6):
        time.sleep(0.001)
        led.note_commit()
    led.note_restore(4)
    v = led.view()
    assert v["badput"]["replayed_steps"] == 2
    assert v["steps"]["current_step"] == 4
    assert v["badput"]["replay_seconds"] > 0
    # Counted once: a repeated restore to the same point adds nothing.
    led.note_restore(4)
    assert led.view()["badput"]["replayed_steps"] == 2
    # Never negative: restoring "forward" counts nothing, cursor stays.
    led.note_restore(10)
    v = led.view()
    assert v["badput"]["replayed_steps"] == 2
    assert v["steps"]["current_step"] == 4
    # Re-running the lost work then losing it again counts the re-run.
    led.note_commit()
    led.note_commit()
    led.note_restore(4)
    assert led.view()["badput"]["replayed_steps"] == 4


def test_in_memory_restore_rolls_back_to_committed_step():
    led = _ledger()
    led.note_commit()
    led.note_commit()
    led.auto_step("commit")  # a step past the last commit... sort of:
    # commits ARE the boundary source here, so simulate divergence via
    # the cursor directly: two commits, then one uncommitted boundary.
    assert led.current_step == 3 and led.committed_step == 2
    led.note_restore()  # no arg = the last committed step
    v = led.view()
    assert v["badput"]["replayed_steps"] == 1
    assert v["steps"]["current_step"] == 2


# ---------------------------------------------------------------------------
# Disruption bracket (elastic reset downtime)


def test_disruption_window_lands_in_restart_badput():
    led = _ledger()
    led.note_commit()
    led.note_commit()
    led.disruption_begin("collective failure")
    time.sleep(0.05)
    led.disruption_end()
    v = led.view()
    assert v["badput"]["restart_downtime_seconds"] >= 0.045
    # The boundary timer was suspended: the next commit closes an
    # UNTIMED step, so the gap never reads as one giant step.
    timed = led.timed_steps
    led.note_commit()
    assert led.timed_steps == timed
    assert led.steps == 3


def test_disruption_end_without_begin_is_noop():
    led = _ledger()
    led.disruption_end()
    assert led.view()["badput"]["restart_downtime_seconds"] == 0.0


def test_nested_disruption_keeps_first_window():
    led = _ledger()
    led.disruption_begin("a")
    time.sleep(0.02)
    led.disruption_begin("b")  # second begin must not reset the clock
    led.disruption_end()
    assert led.view()["badput"]["restart_downtime_seconds"] >= 0.018


# ---------------------------------------------------------------------------
# Durable stamps: kill-all accounting across process lifetimes


def test_stamp_roundtrip_counts_downtime_and_replay(tmp_path):
    path = str(tmp_path / "goodput.json")
    led1 = _ledger(rank=0, stamp_path=path)
    for _ in range(7):
        time.sleep(0.002)
        led1.note_commit()
    assert os.path.exists(path)  # stamped every commit at the default
    doc = json.loads(open(path).read())
    assert doc["current_step"] == 7 and doc["steps"] == 7
    # "The job dies." A fresh ledger (new lifetime) resumes the book.
    time.sleep(0.06)
    led2 = _ledger(rank=0, stamp_path=path)
    assert led2.generation == 2
    assert led2.job_start_wall == pytest.approx(led1.job_start_wall)
    v = led2.view()
    assert v["badput"]["restart_downtime_seconds"] >= 0.05
    assert v["steps"]["current_step"] == 7
    # The restarted job restores the durable checkpoint at step 6: one
    # executed step is replayed.
    led2.note_restore(6)
    v = led2.view()
    assert v["badput"]["replayed_steps"] == 1
    assert v["badput"]["replay_seconds"] > 0  # prior mean step carried
    # Cumulative totals carried: steps from the first lifetime count.
    led2.note_commit()
    assert led2.view()["steps"]["total"] == 8


def test_stamp_only_rank0_writes(tmp_path):
    path = str(tmp_path / "goodput.json")
    led = _ledger(rank=1, stamp_path=path)
    led.note_commit()
    led.stamp(force=True)
    assert not os.path.exists(path)


def test_disabled_ledger_is_inert(tmp_path):
    path = str(tmp_path / "goodput.json")
    led = _ledger(enabled=False, rank=0, stamp_path=path)
    with led.step():
        led.note_exposed(0.5)
    led.note_commit()
    led.disruption_begin()
    led.disruption_end()
    led.stamp(force=True)
    assert led.steps == 0 and led.exposed_seconds == 0.0
    assert not os.path.exists(path)


# ---------------------------------------------------------------------------
# Ratio / accounting identity


def test_buckets_plus_goodput_account_for_wall_clock():
    led = _ledger()
    t0 = time.time()
    for _ in range(4):
        with led.step():
            led.note_exposed(0.004)
            time.sleep(0.02)
    led.disruption_begin()
    time.sleep(0.03)
    led.disruption_end()
    wall = led.wall_seconds()
    v = led.view()
    acct = (v["goodput"]["seconds"]
            + v["badput"]["exposed_comm_in_step_seconds"]
            + v["badput"]["ckpt_stall_in_step_seconds"]
            + v["badput"]["replay_seconds"]
            + v["badput"]["restart_downtime_seconds"]
            + v["badput"]["other_seconds"])
    assert acct == pytest.approx(wall, rel=0.1, abs=0.05)
    r = v["goodput"]["ratio"]
    assert r is not None and 0 < r < 1
    assert time.time() - t0 >= wall * 0.9  # wall is this test's elapsed


def test_ratio_none_before_first_step_and_gauge_nan():
    import math

    led = _ledger()
    assert led.ratio() is None
    g = led.registry.get("horovod_goodput_ratio")
    assert math.isnan(g.value)  # NaN: the threshold rule stays silent
    with led.step():
        pass
    assert led.ratio() is not None
    assert not math.isnan(g.value)


def test_mfu_from_declared_flops():
    led = _ledger(step_flops=1e9, peak_flops=1e11)
    with led.step():
        time.sleep(0.01)
    v = led.view()
    flops = v["flops"]
    assert flops["step_flops"] == 1e9
    assert flops["achieved_flops_per_second"] == pytest.approx(
        1e9 / v["steps"]["mean_step_seconds"], rel=1e-3)
    assert flops["mfu"] == pytest.approx(
        flops["achieved_flops_per_second"] / 1e11, rel=1e-3)


def test_out_of_step_exposed_not_subtracted_from_goodput():
    """Waits outside any step window (initial broadcast, eval
    collectives between scopes) count in the exposed TOTAL but live in
    other/downtime wall time — subtracting them from step compute
    would double-count the loss."""
    led = _ledger()
    led.note_exposed(5.0)  # out-of-step (before the first scope)
    with led.step():
        led.note_exposed(0.005)
        time.sleep(0.02)
    led.note_exposed(3.0)  # out-of-step (after the scope)
    v = led.view()
    assert v["badput"]["exposed_comm_seconds"] == pytest.approx(8.005)
    assert v["badput"]["exposed_comm_in_step_seconds"] == pytest.approx(
        0.005, abs=1e-3)
    # Goodput loses only the in-step share, and never goes negative
    # from out-of-step waits.
    assert v["goodput"]["seconds"] == pytest.approx(
        v["steps"]["mean_step_seconds"] - 0.005, abs=5e-3)


def test_out_of_step_stall_not_subtracted_from_goodput():
    """Snapshot stalls outside any step window (save-every-N invoked
    between explicit scopes) get the same treatment as out-of-step
    exposed comm: counted in the total, excluded from the goodput
    subtraction."""
    led = _ledger()
    led.note_ckpt_stall(4.0)  # between scopes: not step compute
    with led.step():
        led.note_ckpt_stall(0.003)
        time.sleep(0.02)
    v = led.view()
    assert v["badput"]["ckpt_stall_seconds"] == pytest.approx(4.003)
    assert v["badput"]["ckpt_stall_in_step_seconds"] == pytest.approx(
        0.003, abs=1e-3)
    assert v["goodput"]["seconds"] == pytest.approx(
        v["steps"]["mean_step_seconds"] - 0.003, abs=5e-3)


def test_restore_units_guard_under_finer_demarcation(tmp_path):
    """A checkpoint-manifest step counts elastic COMMITS; under
    optimizer/explicit demarcation the ledger cursor is finer-grained,
    so comparing the two would manufacture phantom replay. The ledger
    falls back to its own committed cursor — across lifetimes too (the
    stamp carries the demarcation source)."""
    led = _ledger()
    for _ in range(100):
        led.auto_step("optim")   # 100 optimizer steps...
    led.note_commit()            # ...amortized into few commits
    for _ in range(7):
        led.auto_step("optim")
    # Restore to "manifest step 10" (commit units): NOT comparable.
    led.note_restore(10)
    v = led.view()
    assert v["badput"]["replayed_steps"] == 7  # cursor - committed, not 97
    assert v["steps"]["current_step"] == 100
    # Same guard across a process lifetime: the stamp carries the
    # source, so a restarted ledger refuses the unit mixing too.
    path = str(tmp_path / "goodput.json")
    led1 = _ledger(rank=0, stamp_path=path)
    for _ in range(50):
        led1.auto_step("optim")
    led1.note_commit()
    led2 = _ledger(rank=0, stamp_path=path)
    led2.note_restore(3)  # manifest units; prior source was optim
    assert led2.view()["badput"]["replayed_steps"] == 0
    assert led2.view()["steps"]["current_step"] == 50


def test_promoted_rank0_never_overwrites_stamp(tmp_path):
    """A survivor promoted to rank 0 by elastic renumbering never
    loaded the job-lifetime stamp; writing it would replace the job
    history with fresh-lifetime numbers."""
    path = str(tmp_path / "goodput.json")
    led = _ledger(rank=1, stamp_path=path)
    led.rank = 0  # the elastic renumbering promotion
    led.note_commit()
    led.stamp(force=True)
    assert not os.path.exists(path)


def test_aborted_explicit_step_is_not_counted():
    """A step whose body raised never completed: counting it would
    inflate the cursor (phantom replay after the restore) and pollute
    the mean step time with a partial duration."""
    led = _ledger()
    with led.step():
        time.sleep(0.002)
    with pytest.raises(RuntimeError):
        with led.step():
            led.note_exposed(0.5)
            raise RuntimeError("collective failure mid-step")
    v = led.view()
    assert v["steps"]["total"] == 1
    assert v["steps"]["current_step"] == 1
    # The aborted step's exposure stays in the total but is dropped
    # from step attribution (and from the next step's window).
    assert v["badput"]["exposed_comm_seconds"] == pytest.approx(0.5)
    assert v["badput"]["exposed_comm_in_step_seconds"] < 0.01
    with led.step():
        time.sleep(0.002)
    assert led.registry.get(
        "horovod_exposed_comm_step_seconds").snapshot()["sum"] < 0.01


def test_current_rank_seed_controls_stamp_ownership(tmp_path,
                                                    monkeypatch):
    """Mesh mode has no HOROVOD_RANK, so basics.init seeds current()
    with the process index — a non-zero process must not become a
    stamp owner by env default."""
    monkeypatch.delenv("HOROVOD_RANK", raising=False)
    monkeypatch.setenv("HOROVOD_GOODPUT_DIR", str(tmp_path))
    prev = goodput.active()
    goodput.set_current(None)
    try:
        led = goodput.current(rank=2)
        assert led.rank == 2 and not led._stamp_owner
        led.note_commit()
        led.stamp(force=True)
        assert not os.path.exists(str(tmp_path / "goodput.json"))
    finally:
        goodput.set_current(prev)


def test_stamp_load_falls_back_to_kv_mirror(tmp_path):
    """The KV mirror is the READ fallback when the stamp file is gone
    (stamp dir lost, rendezvous survived) — not just a dashboard row."""

    class KV:
        def __init__(self):
            self.store = {}

        def put(self, scope, key, value):
            self.store[(scope, key)] = value

        def get(self, scope, key):
            return self.store.get((scope, key))

    kv = KV()
    led1 = _ledger(rank=0, stamp_path=str(tmp_path / "goodput.json"),
                   kv=kv)
    for _ in range(4):
        led1.note_commit()
    led1.stamp(force=True)
    deadline = time.monotonic() + 5
    while not kv.store and time.monotonic() < deadline:
        time.sleep(0.01)  # the mirror rides the background worker
    os.unlink(str(tmp_path / "goodput.json"))
    led2 = _ledger(rank=0, stamp_path=str(tmp_path / "goodput.json"),
                   kv=kv)
    assert led2.generation == 2
    assert led2.view()["steps"]["current_step"] == 4


def test_kv_mirror_never_blocks_the_stamping_thread():
    class SlowKV:
        def __init__(self):
            self.docs = []
            self.event = threading.Event()

        def put(self, scope, key, value):
            time.sleep(0.2)  # a retrying client against a dead server
            self.docs.append((scope, key, value))
            self.event.set()

    kv = SlowKV()
    led = _ledger(rank=0, kv=kv)
    led.note_commit()
    t0 = time.monotonic()
    led.stamp(force=True)
    assert time.monotonic() - t0 < 0.1  # handed off, not awaited
    assert kv.event.wait(5)  # the background worker delivered it
    assert kv.docs[0][0] == goodput.KV_SCOPE


# ---------------------------------------------------------------------------
# HandleManager exposed-comm attribution


def test_handle_wait_blocked_time_is_exposed():
    led = _ledger()
    hm = HandleManager(goodput=led)
    h = hm.allocate()

    def finish():
        time.sleep(0.05)
        hm.mark_done(h, Status.OK(), None)

    t = threading.Thread(target=finish)
    t.start()
    hm.wait(h, timeout=10)
    t.join()
    assert led.exposed_seconds == pytest.approx(0.05, abs=0.03)


def test_handle_wait_overlapped_comm_costs_nothing():
    led = _ledger()
    hm = HandleManager(goodput=led)
    h = hm.allocate()
    hm.mark_done(h, Status.OK(), None)  # completed while "computing"
    hm.wait(h, timeout=10)
    assert led.exposed_seconds == 0.0


def test_handle_wait_timeout_still_raises():
    led = _ledger()
    hm = HandleManager(goodput=led)
    h = hm.allocate()
    with pytest.raises(TimeoutError):
        hm.wait(h, timeout=0.01)
    assert led.exposed_seconds > 0.0  # the blocked time still counts


# ---------------------------------------------------------------------------
# Env knobs (utils/env.py house conventions)


def test_env_goodput_knobs(monkeypatch):
    for k in ("HOROVOD_GOODPUT", "HOROVOD_GOODPUT_DIR",
              "HOROVOD_GOODPUT_STAMP_SECONDS", "HOROVOD_STEP_FLOPS",
              "HOROVOD_GOODPUT_PEAK_FLOPS", "HOROVOD_CHECKPOINT_DIR"):
        monkeypatch.delenv(k, raising=False)
        monkeypatch.delenv(k.replace("HOROVOD_", "HVD_TPU_", 1),
                           raising=False)
    assert env_cfg.goodput_enabled() is True
    assert env_cfg.goodput_dir() == ""
    assert env_cfg.goodput_stamp_seconds() == 0.0
    assert env_cfg.step_flops() == 0.0
    assert env_cfg.goodput_peak_flops() == 0.0
    monkeypatch.setenv("HOROVOD_GOODPUT", "0")
    assert env_cfg.goodput_enabled() is False
    # The stamp dir defaults to the checkpoint dir (the ledger lives
    # next to the checkpoints it accounts for).
    monkeypatch.setenv("HOROVOD_CHECKPOINT_DIR", "/ckpt")
    assert env_cfg.goodput_dir() == "/ckpt"
    monkeypatch.setenv("HOROVOD_GOODPUT_DIR", "/gp")
    assert env_cfg.goodput_dir() == "/gp"
    monkeypatch.setenv("HOROVOD_GOODPUT_STAMP_SECONDS", "-3")
    assert env_cfg.goodput_stamp_seconds() == 0.0
    monkeypatch.setenv("HOROVOD_STEP_FLOPS", "2.5e12")
    assert env_cfg.step_flops() == 2.5e12
    # Bogus values fall to the default, never crash (house convention).
    monkeypatch.setenv("HOROVOD_STEP_FLOPS", "a lot")
    assert env_cfg.step_flops() == 0.0
    monkeypatch.setenv("HOROVOD_STEP_FLOPS", "-5")
    assert env_cfg.step_flops() == 0.0
    monkeypatch.setenv("HOROVOD_GOODPUT_PEAK_FLOPS", "bogus")
    assert env_cfg.goodput_peak_flops() == 0.0
    # HVD_TPU_ alias prefix.
    monkeypatch.delenv("HOROVOD_STEP_FLOPS", raising=False)
    monkeypatch.setenv("HVD_TPU_STEP_FLOPS", "1e9")
    assert env_cfg.step_flops() == 1e9


def test_ledger_from_env_constructor(monkeypatch, tmp_path):
    monkeypatch.setenv("HOROVOD_GOODPUT_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_STEP_FLOPS", "1e6")
    led = goodput.GoodputLedger(registry=telemetry.MetricsRegistry(),
                                rank=0)
    assert led.enabled is True
    assert led.step_flops == 1e6


# ---------------------------------------------------------------------------
# Default alert rules (common/alerts.py)


def test_default_rules_include_goodput_pair():
    from horovod_tpu.common import alerts

    names = {r.name for r in alerts.default_rules()}
    assert "goodput_degraded" in names
    assert "exposed_comm_regression" in names


def test_goodput_degraded_rule_fires_below_threshold():
    from horovod_tpu.common import alerts
    from horovod_tpu.common import timeseries as ts

    rule = [r for r in alerts.default_rules()
            if r.name == "goodput_degraded"][0]
    store = ts.TimeSeriesStore(16)
    store.add_sample({"horovod_goodput_ratio": 0.2}, mono=1.0)
    breach, value, detail = rule.evaluate(store)
    assert breach and value == 0.2
    store.add_sample({"horovod_goodput_ratio": 0.9}, mono=2.0)
    breach, value, _ = rule.evaluate(store)
    assert not breach
    # NaN (no steps yet) stays silent — not enough data is not breach.
    store.add_sample({"horovod_goodput_ratio": float("nan")}, mono=3.0)
    assert rule.evaluate(store) is None


# ---------------------------------------------------------------------------
# StepSummary columns (satellite: callbacks.py / common/telemetry.py)


def test_step_summary_line_has_goodput_and_comm_columns():
    reg = telemetry.MetricsRegistry()
    reg.counter("horovod_exposed_comm_seconds_total").inc(0.0)
    s = telemetry.StepSummary(reg)
    time.sleep(0.02)
    reg.get("horovod_exposed_comm_seconds_total").inc(0.01)
    line = s.line(10)
    assert "goodput " in line and "comm " in line
    # 10ms exposed over the window -> 1.0ms per batch.
    assert "comm 1.0ms" in line


# ---------------------------------------------------------------------------
# critical_path.py step grouping (satellite)


def _step_event(rank, step, ts, dur, exposed_ms):
    return {"ph": "X", "name": "step", "cat": "step", "pid": rank,
            "tid": 1, "ts": ts, "dur": dur,
            "args": {"step": step, "exposed_comm_ms": exposed_ms}}


def _exec_event(rank, trace_id, ts, dur):
    return {"ph": "X", "name": "exec.allreduce", "cat": "exec",
            "pid": rank, "tid": 2, "ts": ts, "dur": dur,
            "args": {"trace_id": trace_id}}


def test_critical_path_groups_collectives_under_steps():
    events = [
        # rank 0: two steps; the first holds one 400us collective of
        # which 100us was exposed, the second a fully exposed one.
        _step_event(0, 1, 0.0, 1000.0, 0.1),
        _exec_event(0, 2, 100.0, 400.0),
        _step_event(0, 2, 1000.0, 1000.0, 0.3),
        _exec_event(0, 4, 1200.0, 300.0),
        # a collective OUTSIDE any step window is not attributed
        _exec_event(0, 6, 5000.0, 500.0),
    ]
    out = critical_path.analyze_steps(events, top=5)
    assert out["steps_analyzed"] == 2
    pr = out["per_rank"]["0"]
    assert pr["steps"] == 2
    assert pr["comm_us"] == pytest.approx(700.0)
    assert pr["exposed_us"] == pytest.approx(400.0)
    assert pr["overlapped_us"] == pytest.approx(300.0)
    worst = out["worst_exposed_steps"][0]
    assert worst["step"] == 2 and worst["exposed_us"] == pytest.approx(
        300.0)
    # The section rides the full analysis too.
    full = critical_path.analyze(events)
    assert full["steps"]["steps_analyzed"] == 2


def test_critical_path_steps_section_absent_without_step_spans():
    events = [_exec_event(0, 2, 0.0, 100.0)]
    assert critical_path.analyze_steps(events) is None
    assert "steps" not in critical_path.analyze(events)


# ---------------------------------------------------------------------------
# Engine integration: /status section + ledger identity across engines


def test_engine_status_has_goodput_section():
    from horovod_tpu.engine.engine import Engine

    eng = Engine(rank=0, size=1, registry=telemetry.MetricsRegistry())
    eng.start()
    try:
        with eng.goodput.step():
            eng.synchronize(eng.enqueue_allreduce(
                __import__("numpy").ones(4, "float32"), name="g"),
                timeout=30)
        st = eng.status()
        assert st["goodput"]["steps"] == 1
        assert st["goodput"]["enabled"] is True
    finally:
        eng.shutdown()


def test_private_registry_engines_get_private_ledgers():
    from horovod_tpu.engine.engine import Engine

    e1 = Engine(rank=0, size=1, registry=telemetry.MetricsRegistry())
    e2 = Engine(rank=0, size=1, registry=telemetry.MetricsRegistry())
    assert e1.goodput is not e2.goodput
    assert e1.goodput is not goodput.active()


def test_default_registry_engine_shares_process_ledger():
    from horovod_tpu.engine.engine import Engine

    led0 = goodput.current()
    eng = Engine(rank=0, size=1)
    try:
        assert eng.goodput is led0
        assert eng.goodput is goodput.current()
    finally:
        # No start() was called; nothing to shut down but the gauges.
        pass
