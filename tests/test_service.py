"""Authenticated driver/task RPC + HMAC rendezvous tests.

(ref: test/test_service.py:1-142 — BasicDriver/TaskService registration
over localhost sockets; runner/common/util/network.py:50-110 HMAC wire.)
"""
import io
import os
import sys

import pytest

from horovod_tpu.backend.rendezvous import RendezvousClient
from horovod_tpu.runner.rendezvous_server import RendezvousServer
from horovod_tpu.runner.service import (
    AuthError,
    BasicClient,
    BasicService,
    DriverClient,
    DriverService,
    TaskClient,
    TaskService,
    Wire,
)
from horovod_tpu.runner.util import secret as secret_util


def test_wire_roundtrip_and_tamper():
    key = secret_util.make_secret_key()
    wire = Wire(key)
    buf = io.BytesIO()
    wire.write({"a": [1, 2, 3]}, buf)
    buf.seek(0)
    assert wire.read(buf) == {"a": [1, 2, 3]}

    # Tampered body: digest check must fail BEFORE unpickling.
    raw = bytearray(buf.getvalue())
    raw[-1] ^= 0xFF
    with pytest.raises(AuthError):
        wire.read(io.BytesIO(bytes(raw)))

    # Wrong key: same failure.
    with pytest.raises(AuthError):
        Wire(secret_util.make_secret_key()).read(io.BytesIO(buf.getvalue()))


def test_basic_service_ping_and_reject():
    key = secret_util.make_secret_key()
    svc = BasicService("svc", key)
    try:
        resp = BasicClient("127.0.0.1", svc.port, key).ping()
        assert resp.service_name == "svc"

        # A client with the wrong key is dropped without a response.
        bad = BasicClient("127.0.0.1", svc.port,
                          secret_util.make_secret_key(), timeout=5.0)
        with pytest.raises((EOFError, ConnectionError, OSError)):
            bad.ping()

        # The good client still works afterwards.
        assert BasicClient("127.0.0.1", svc.port, key).ping().service_name \
            == "svc"
    finally:
        svc.shutdown()


def test_task_service_run_command():
    key = secret_util.make_secret_key()
    svc = TaskService(index=0, key=key)
    try:
        client = TaskClient("127.0.0.1", svc.port, key)
        client.run_command(
            [sys.executable, "-c", "print('hello-from-task'); exit(7)"]
        )
        rc, output = client.wait_for_command(timeout=60)
        assert rc == 7
        assert b"hello-from-task" in output
    finally:
        svc.shutdown()


def test_task_service_terminate():
    key = secret_util.make_secret_key()
    svc = TaskService(index=0, key=key)
    try:
        client = TaskClient("127.0.0.1", svc.port, key)
        client.run_command(
            [sys.executable, "-c", "import time; time.sleep(300)"]
        )
        client.terminate()
        rc, _ = client.wait_for_command(timeout=60)
        assert rc != 0
    finally:
        svc.shutdown()


def test_driver_service_registration():
    key = secret_util.make_secret_key()
    driver = DriverService(num_tasks=3, key=key)
    tasks = [TaskService(index=i, key=key) for i in range(3)]
    try:
        for i, t in enumerate(tasks):
            DriverClient("127.0.0.1", driver.port, key).register_task(
                i, t.addresses(), f"host-{i}"
            )
        addrs = driver.wait_for_all_tasks(timeout=30)
        assert set(addrs) == {0, 1, 2}
        assert driver.task_hostname(1) == "host-1"
        # Any client can fetch the full address map (driver bcasts it in
        # the reference; here it is pull-based).
        got = DriverClient("127.0.0.1", driver.port, key).all_task_addresses()
        assert got == addrs
    finally:
        driver.shutdown()
        for t in tasks:
            t.shutdown()


# ---------------------------------------------------------------------------
def test_rendezvous_hmac_enforced():
    key = secret_util.make_secret_key()
    srv = RendezvousServer(secret_key=key)
    port = srv.start()
    try:
        signed = RendezvousClient("127.0.0.1", port, secret_key=key)
        signed.put("s", "k", b"v")
        assert signed.get("s", "k") == b"v"

        unsigned = RendezvousClient("127.0.0.1", port, secret_key=None)
        # Force no env fallback.
        unsigned.secret_key = None
        with pytest.raises(RuntimeError):
            unsigned.put("s", "k2", b"x")
        with pytest.raises(PermissionError):
            unsigned.get("s", "k")

        wrong = RendezvousClient(
            "127.0.0.1", port, secret_key=secret_util.make_secret_key()
        )
        with pytest.raises(PermissionError):
            wrong.get("s", "k")
        # Store unchanged by rejected writes.
        assert signed.get("s", "k2") is None
    finally:
        srv.stop()


def test_wire_oversized_frame_rejected_before_read():
    """The attacker-controlled length header is capped before the body
    is read, so an unauthenticated peer can't force GiB allocations."""
    import struct

    key = secret_util.make_secret_key()
    wire = Wire(key)
    frame = b"\x00" * secret_util.DIGEST_LENGTH + struct.pack(
        "<I", Wire.MAX_MESSAGE_BYTES + 1)
    with pytest.raises(AuthError, match="cap"):
        wire.read(io.BytesIO(frame))


def test_rendezvous_replay_and_stale_rejected():
    """A captured signed PUT must not be replayable, and timestamps
    outside the window are rejected outright."""
    import http.client
    import time

    from horovod_tpu.runner.rendezvous_server import sign_request

    key = secret_util.make_secret_key()
    srv = RendezvousServer(secret_key=key)
    port = srv.start()
    try:
        digest, ts = sign_request(key, "PUT", "/s/k", b"v1")
        headers = {"X-Horovod-Digest": digest, "X-Horovod-Timestamp": ts}
        def do(method, path, body, hdrs):
            c = http.client.HTTPConnection("127.0.0.1", port)
            try:
                c.request(method, path, body=body, headers=hdrs)
                r = c.getresponse()
                r.read()
                return r.status
            finally:
                c.close()

        assert do("PUT", "/s/k", b"v1", headers) == 200
        # Verbatim replay of the same signed request: rejected.
        assert do("PUT", "/s/k", b"v1", headers) == 403
        # Stale timestamp (signed long ago): rejected without a replay.
        digest, ts = sign_request(key, "PUT", "/s/k2", b"v",
                                  ts=repr(time.time() - 3600))
        assert do("PUT", "/s/k2", b"v",
                  {"X-Horovod-Digest": digest,
                   "X-Horovod-Timestamp": ts}) == 403
        # The legitimate value survived; the stale write never landed.
        signed = RendezvousClient("127.0.0.1", port, secret_key=key)
        assert signed.get("s", "k") == b"v1"
        assert signed.get("s", "k2") is None
    finally:
        srv.stop()


def test_rendezvous_unauthenticated_server_still_open():
    srv = RendezvousServer()
    port = srv.start()
    try:
        c = RendezvousClient("127.0.0.1", port)
        c.secret_key = None
        c.put("s", "k", b"v")
        assert c.get("s", "k") == b"v"
    finally:
        srv.stop()


def test_secret_env_roundtrip(monkeypatch):
    key = secret_util.make_secret_key()
    monkeypatch.setenv(secret_util.SECRET_ENV, secret_util.key_to_env(key))
    assert secret_util.key_from_env() == key
    # Clients pick the env key up automatically.
    c = RendezvousClient("127.0.0.1", 1)
    assert c.secret_key == key


# ---------------------------------------------------------------------------
def test_launch_static_via_task_service(tmp_path, monkeypatch):
    """HVDRUN_USE_TASK_SERVICE=all: launch_static bootstraps per-slot
    TaskServices, registers them with a DriverService, and runs every
    worker through the authenticated RPC instead of direct spawn."""
    import sys

    from horovod_tpu.runner.hosts import HostInfo, get_host_assignments
    from horovod_tpu.runner.launch import launch_static

    monkeypatch.setenv("HVDRUN_USE_TASK_SERVICE", "all")
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "1")
    slots = get_host_assignments([HostInfo("localhost", 2)], 2, 2)
    marker = tmp_path / "rank{}.txt"
    code = (
        "import os, numpy as np, horovod_tpu as hvd\n"
        "hvd.init()\n"
        "out = hvd.allreduce(np.full(3, float(hvd.rank()+1), np.float32),"
        " name='t')\n"
        f"open(r'{marker}'.format(hvd.rank()), 'w').write(str(float(out[0])))\n"
        "hvd.shutdown()\n"
    )
    rc = launch_static(slots, [sys.executable, "-c", code],
                       extra_env={"PYTHONPATH": os.getcwd(),
                                  "JAX_PLATFORMS": "cpu"})
    assert rc == 0
    # Both workers ran and allreduced through the engine: avg(1,2)=1.5.
    for r in range(2):
        assert (tmp_path / f"rank{r}.txt").read_text() == "1.5"


def test_launch_static_task_service_failure_propagates(monkeypatch):
    """A nonzero worker exit through the task-service path still tears
    the job down and surfaces the exit code."""
    import sys

    from horovod_tpu.runner.hosts import HostInfo, get_host_assignments
    from horovod_tpu.runner.launch import launch_static

    monkeypatch.setenv("HVDRUN_USE_TASK_SERVICE", "all")
    slots = get_host_assignments([HostInfo("localhost", 2)], 2, 2)
    code = ("import os, sys\n"
            "sys.exit(5 if os.environ['HOROVOD_RANK'] == '1' else 0)\n")
    rc = launch_static(slots, [sys.executable, "-c", code],
                       extra_env={"PYTHONPATH": os.getcwd()})
    assert rc == 5
