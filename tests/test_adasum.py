"""Adasum numerics: traced (ppermute VHDD) vs the NumPy oracle
(ref test model: test/test_adasum_pytorch.py compares against a NumPy
reference implementation)."""
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.ops.adasum import adasum_numpy
from horovod_tpu.utils.compat import shard_map


@pytest.fixture(autouse=True)
def _init():
    hvd.shutdown()
    hvd.init()
    yield
    hvd.shutdown()


N = 8


def _traced_adasum(per_rank: np.ndarray):
    """per_rank: [N, d] — rank r's vector in row r."""
    x = jnp.asarray(per_rank.reshape(-1))

    def f(v):
        return hvd.allreduce(v, op=hvd.Adasum)

    out = shard_map(f, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=P("hvd"))(x)
    return np.asarray(out).reshape(per_rank.shape)


def test_identical_vectors_fixed_point():
    v = np.array([1.0, -2.0, 3.0, 4.0], np.float32)
    per_rank = np.tile(v, (N, 1))
    out = _traced_adasum(per_rank)
    for r in range(N):
        np.testing.assert_allclose(out[r], v, rtol=1e-5)


def test_orthogonal_vectors_sum():
    per_rank = np.eye(N, dtype=np.float32) * 3.0
    out = _traced_adasum(per_rank)
    expected = np.full(N, 3.0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expected, rtol=1e-5)


def test_matches_numpy_oracle_random():
    rng = np.random.RandomState(42)
    per_rank = rng.randn(N, 16).astype(np.float32)
    got = _traced_adasum(per_rank)
    want = adasum_numpy([per_rank[r] for r in range(N)])
    for r in range(N):
        np.testing.assert_allclose(got[r], want[r], rtol=1e-4, atol=1e-5)
    # All ranks converge to the identical combined vector.
    for r in range(1, N):
        np.testing.assert_allclose(got[0], got[r], rtol=1e-5)


def test_scaling_insensitivity():
    # Adasum's defining property: scaling one rank's gradient by a large
    # factor doesn't blow up the combination the way SUM does
    # (ref: docs/adasum_user_guide.rst motivation).
    rng = np.random.RandomState(0)
    v = rng.randn(8).astype(np.float64)
    a, b = v.copy(), v.copy() * 1000.0
    out = adasum_numpy([a, b])[0]
    # result stays O(||b||): combination ≈ b when b dominates
    assert np.linalg.norm(out) < np.linalg.norm(a) + np.linalg.norm(b)
    assert np.linalg.norm(out) > 0.4 * np.linalg.norm(b)


def test_numpy_oracle_power_of_two_only():
    with pytest.raises(AssertionError):
        adasum_numpy([np.ones(2)] * 3)
