"""Pallas flash-attention kernel tests (interpret mode on CPU; the same
kernel compiles via Mosaic on TPU — validated on hardware, see
ops/flash_attention.py docstring).

Reference oracle: parallel/ring.py dense_attention (itself verified
against the ring/ulysses SP kernels in test_parallel.py).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.utils.compat import set_mesh as _set_mesh
from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.parallel.ring import dense_attention


def _qkv(B=2, S=96, H=2, D=32, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(
        jnp.asarray(rng.randn(B, S, H, D).astype(dtype)) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("S", [64, 96, 130])  # incl. non-multiple-of-block
def test_flash_matches_dense(causal, S):
    q, k, v = _qkv(S=S)
    got = flash_attention(q, k, v, causal=causal, block_q=64,
                          interpret=True)
    want = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_padding_mask(causal):
    q, k, v = _qkv(S=96)
    mask = np.ones((2, 96), np.float32)
    mask[0, 60:] = 0.0
    mask[1, 10:] = 0.0
    got = flash_attention(q, k, v, jnp.asarray(mask), causal=causal,
                          block_q=64, interpret=True)
    want = dense_attention(q, k, v, causal=causal, mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    assert np.isfinite(np.asarray(got)).all()


def test_flash_fully_masked_rows_zero():
    """An all-padding sequence yields zeros (BERT convention, matching
    the other kernels)."""
    q, k, v = _qkv(S=64)
    mask = np.ones((2, 64), np.float32)
    mask[1, :] = 0.0
    got = flash_attention(q, k, v, jnp.asarray(mask), causal=False,
                          block_q=64, interpret=True)
    np.testing.assert_allclose(np.asarray(got)[1], 0.0)
    assert np.isfinite(np.asarray(got)).all()


def _assert_grads_match(q, k, v, jmask, causal, block_q):
    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, jmask, causal=causal,
                                       block_q=block_q,
                                       interpret=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal,
                                       mask=jmask) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("S", [64, 96, 130])  # incl. q-padding paths
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("use_mask", [False, True])
def test_flash_gradients_match_dense(S, causal, use_mask):
    q, k, v = _qkv(S=S)
    if use_mask:
        mask = np.ones((2, S), np.float32)
        mask[0, S - 10:] = 0.0
        mask[1, S // 3:] = 0.0
        jmask = jnp.asarray(mask)
    else:
        jmask = None
    _assert_grads_match(q, k, v, jmask, causal, block_q=64)


@pytest.mark.parametrize("S", [1024, 1025])
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("use_mask", [False, True])
def test_flash_long_sequence_interior_tiles(S, causal, use_mask):
    """S > block_k (512): the kernels stream MULTIPLE k-tiles — a scale
    short-S tests (bk=min(512,S)=S → one tile) can never reach.

    S=1024 (512-multiple, pad_k=0): with no mask the below-diagonal
    tiles take the mask-free `plain` body, the only CI coverage of that
    path; with a mask, the multi-tile MASKED path at the same scale.
    S=1025: keys pad to 1536 with an (almost) fully-masked final
    k-tile, so `plain` is forced off even with mask=None and the
    synthesized all-ones-then-padded mask path runs multi-tile. Covers
    fwd and the fused single-sweep backward (interior/diagonal loop
    splits in both)."""
    q, k, v = _qkv(B=1, S=S, H=2, D=16)
    if use_mask:
        mask = np.ones((1, S), np.float32)
        mask[0, 900:] = 0.0
        jmask = jnp.asarray(mask)
    else:
        jmask = None
    got = flash_attention(q, k, v, jmask, causal=causal, block_q=128,
                          interpret=True)
    want = dense_attention(q, k, v, causal=causal, mask=jmask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    _assert_grads_match(q, k, v, jmask, causal, block_q=128)


def test_transformer_flash_impl_matches_dense():
    """Model-level: attn_impl='flash' produces the same forward as
    attn_impl='dense' (incl. padding mask)."""
    import dataclasses

    from horovod_tpu.models.transformer import (
        BERT_CONFIGS,
        TransformerEncoder,
    )

    base = dataclasses.replace(
        BERT_CONFIGS["bert-tiny"], max_len=64, n_layers=1,
        dtype=jnp.float32, param_dtype=jnp.float32,
        logits_dtype=jnp.float32,
    )
    ids = np.random.RandomState(0).randint(0, 1000, (2, 64), np.int32)
    mask = np.ones((2, 64), np.float32)
    mask[0, 40:] = 0.0

    m_dense = TransformerEncoder(dataclasses.replace(base,
                                                     attn_impl="dense"))
    variables = m_dense.init(jax.random.PRNGKey(0), ids, mask=mask)
    want = m_dense.apply(variables, ids, mask=mask)

    m_flash = TransformerEncoder(dataclasses.replace(base,
                                                     attn_impl="flash"))
    got = m_flash.apply(variables, ids, mask=mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_flash_under_gspmd_mesh_is_sharded_and_correct():
    """Under a dp x tp (x idle sp) mesh the dispatch manualizes batch/head axes with
    shard_map (an opaque pallas_call would otherwise force GSPMD to
    replicate); results match the dense path."""
    import dataclasses

    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models.transformer import (
        BERT_CONFIGS,
        TransformerEncoder,
    )
    from horovod_tpu.parallel.mesh import create_mesh

    base = dataclasses.replace(
        BERT_CONFIGS["bert-tiny"], max_len=64, n_layers=1,
        dtype=jnp.float32, param_dtype=jnp.float32,
        logits_dtype=jnp.float32,
    )
    ids = np.random.RandomState(0).randint(0, 1000, (4, 64), np.int32)
    mask = np.ones((4, 64), np.float32)
    mask[0, 40:] = 0.0

    m_dense = TransformerEncoder(dataclasses.replace(base,
                                                     attn_impl="dense"))
    variables = m_dense.init(jax.random.PRNGKey(0), ids, mask=mask)
    want = m_dense.apply(variables, ids, mask=mask)

    mesh = create_mesh({"dp": 2, "tp": 2, "sp": 2})
    m_flash = TransformerEncoder(dataclasses.replace(base,
                                                     attn_impl="flash"))
    with _set_mesh(mesh):
        got = jax.jit(lambda v, i, mk: m_flash.apply(v, i, mask=mk))(
            variables, ids, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_with_flash_matches_dense(causal):
    """sp_use_flash: Ulysses' per-head-group attention runs through the
    Pallas kernel inside shard_map and still matches dense."""
    import functools

    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel.mesh import create_mesh
    from horovod_tpu.parallel.ulysses import ulysses_attention
    from horovod_tpu.utils.compat import shard_map

    rng = np.random.RandomState(0)
    B, S, H, D = 2, 64, 4, 32
    q, k, v = (jnp.asarray(rng.randn(B, S, H, D).astype(np.float32))
               for _ in range(3))
    mask = np.ones((B, S), np.float32)
    mask[0, 40:] = 0.0
    mesh = create_mesh({"dp": 2, "sp": 4})
    want = dense_attention(q, k, v, causal=causal, mask=jnp.asarray(mask))

    fn = shard_map(
        lambda q, k, v, m: ulysses_attention(
            q, k, v, axis_name="sp", causal=causal, mask=m,
            use_flash=True),
        mesh=mesh,
        in_specs=(P(None, "sp"),) * 4,
        out_specs=P(None, "sp"),
    )
    got = jax.jit(fn)(q, k, v, jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_model_ulysses_flash_on_dp_sp_mesh():
    """Model-level sp_use_flash on a dp x sp mesh: the dispatch
    manualizes dp alongside sp (the opaque pallas_call would otherwise
    replicate per dp rank) and matches the dense forward."""
    import dataclasses

    from horovod_tpu.models.transformer import (
        BERT_CONFIGS,
        TransformerEncoder,
    )
    from horovod_tpu.parallel.mesh import create_mesh

    base = dataclasses.replace(
        BERT_CONFIGS["bert-tiny"], max_len=64, n_layers=1, n_heads=4,
        dtype=jnp.float32, param_dtype=jnp.float32,
        logits_dtype=jnp.float32,
    )  # 4 heads: Ulysses needs n_heads divisible by sp
    ids = np.random.RandomState(0).randint(0, 1000, (4, 64), np.int32)
    mask = np.ones((4, 64), np.float32)
    mask[0, 40:] = 0.0

    m_dense = TransformerEncoder(dataclasses.replace(base,
                                                     attn_impl="dense"))
    variables = m_dense.init(jax.random.PRNGKey(0), ids, mask=mask)
    want = m_dense.apply(variables, ids, mask=mask)

    mesh = create_mesh({"dp": 2, "sp": 4})
    m_uf = TransformerEncoder(dataclasses.replace(
        base, attn_impl="ulysses", sp_use_flash=True))
    with _set_mesh(mesh):
        got = jax.jit(lambda v, i, mk: m_uf.apply(v, i, mask=mk))(
            variables, ids, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
