"""perf_report baseline-compare tests: regression detection, missing
stage, NaN, tolerance boundary, per-stage tolerance overrides, and the
gate verdict (docs/health.md "Perf gate")."""
import importlib.util
import os

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "perf_report",
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "perf_report.py"))
perf_report = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(perf_report)


def _report(values):
    return {"schema": 1, "stages": {
        k: {"unit": "ms", "value": v} for k, v in values.items()}}


def _verdict_map(verdicts):
    return {v["stage"]: v["status"] for v in verdicts}


def test_clean_run_passes():
    base = _report({"a": 10.0, "b": 5.0})
    rep = _report({"a": 10.5, "b": 4.2})
    v = perf_report.compare(rep, base, default_tolerance=0.5)
    assert _verdict_map(v) == {"a": "ok", "b": "ok"}
    assert perf_report.gate_verdict(v)


def test_2x_slowdown_trips():
    base = _report({"a": 10.0})
    rep = _report({"a": 20.0})
    v = perf_report.compare(rep, base, default_tolerance=0.5)
    assert _verdict_map(v) == {"a": "regression"}
    assert not perf_report.gate_verdict(v)
    assert v[0]["ratio"] == pytest.approx(2.0)


def test_tolerance_boundary_passes_strictly_above_fails():
    base = _report({"a": 10.0})
    # Exactly 1 + tol: passes (regression is STRICTLY greater).
    v = perf_report.compare(_report({"a": 15.0}), base,
                            default_tolerance=0.5)
    assert _verdict_map(v) == {"a": "ok"}
    v = perf_report.compare(_report({"a": 15.0001}), base,
                            default_tolerance=0.5)
    assert _verdict_map(v) == {"a": "regression"}


def test_improvement_is_ok_not_flagged():
    v = perf_report.compare(_report({"a": 1.0}), _report({"a": 10.0}))
    assert _verdict_map(v) == {"a": "ok"}


def test_missing_stage_fails_gate():
    base = _report({"a": 10.0, "b": 5.0})
    rep = _report({"a": 10.0})
    v = perf_report.compare(rep, base)
    assert _verdict_map(v) == {"a": "ok", "b": "missing"}
    assert not perf_report.gate_verdict(v)


def test_nan_measurement_is_invalid():
    base = _report({"a": 10.0})
    rep = _report({"a": float("nan")})
    v = perf_report.compare(rep, base)
    assert _verdict_map(v) == {"a": "invalid"}
    assert not perf_report.gate_verdict(v)
    # Non-numeric value too.
    rep2 = {"schema": 1, "stages": {"a": {"unit": "ms", "value": "x"}}}
    assert _verdict_map(perf_report.compare(rep2, base)) == {"a": "invalid"}


def test_broken_baseline_is_skipped_not_failed():
    """A NaN/zero/negative baseline entry must not fail every future
    run — it is skipped (and visible as such)."""
    for bad in (float("nan"), 0.0, -1.0, None):
        base = {"schema": 1, "stages": {"a": {"unit": "ms", "value": bad}}}
        v = perf_report.compare(_report({"a": 10.0}), base)
        assert _verdict_map(v) == {"a": "skipped"}
        assert perf_report.gate_verdict(v)


def test_new_stage_is_informational():
    base = _report({"a": 10.0})
    rep = _report({"a": 10.0, "z": 3.0})
    v = perf_report.compare(rep, base)
    assert _verdict_map(v) == {"a": "ok", "z": "new"}
    assert perf_report.gate_verdict(v)


def test_per_stage_tolerance_overrides():
    base = _report({"noisy": 10.0, "tight": 10.0})
    base["tolerances"] = {"noisy": 1.5, "tight": 0.1}
    rep = _report({"noisy": 20.0, "tight": 12.0})
    v = perf_report.compare(rep, base, default_tolerance=0.5)
    assert _verdict_map(v) == {"noisy": "ok", "tight": "regression"}


def test_median():
    assert perf_report._median([3.0]) == 3.0
    assert perf_report._median([1.0, 9.0, 3.0]) == 3.0
    assert perf_report._median([1.0, 3.0]) == 2.0
    assert perf_report._median([]) != perf_report._median([])  # NaN


def test_render_table():
    base = _report({"a": 10.0, "b": 5.0})
    rep = _report({"a": 25.0})
    out = perf_report.render(perf_report.compare(rep, base))
    assert "regression" in out and "missing" in out


def test_committed_baseline_is_loadable_and_complete():
    """The checked-in BENCH_BASELINE.json must stay valid: every stage
    the harness measures is present with a usable value, so the CI
    warn-compare actually compares."""
    import json

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_BASELINE.json")
    base = json.load(open(path))
    assert base.get("kind") == "horovod_perf_report"
    assert base.get("build", {}).get("version")
    expected = {
        "latency_small_p50_ms", "ring_1mb_ms", "segring_1mb_ms",
        "transport_tcp_4mb_ms", "transport_shm_4mb_ms", "hier_1mb_ms",
        "serving_rtt_p50_ms", "native_ring_16mb_ms",
        "native_off_ring_16mb_ms",
    }
    assert expected <= set(base["stages"]), sorted(base["stages"])
    for name, st in base["stages"].items():
        assert st["value"] > 0, (name, st)
    # Tolerances (if present) must leave a 2x slowdown detectable.
    for name, tol in base.get("tolerances", {}).items():
        assert tol < 1.0, (name, tol)
