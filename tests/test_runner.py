"""Launcher tests (ref test model: test/test_run.py — arg parsing, exact
command/env construction golden tests, host parsing; plus live local
integration the way test/integration/test_static_run.py runs real jobs)."""
import os
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.runner.config_parser import args_to_env
from horovod_tpu.runner.hosts import (
    HostInfo,
    get_host_assignments,
    parse_hostfile,
    parse_hosts,
)
from horovod_tpu.runner.launch import (
    build_ssh_command,
    launch_static,
    make_parser,
    slot_env,
)


def test_parse_hosts():
    hosts = parse_hosts("h1:2,h2:4,h3")
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("h1", 2), ("h2", 4), ("h3", 1)
    ]


def test_parse_hostfile(tmp_path):
    f = tmp_path / "hostfile"
    f.write_text("h1 slots=2\n# comment\nh2:3\nh4\n")
    hosts = parse_hostfile(str(f))
    assert [(h.hostname, h.slots) for h in hosts] == [
        ("h1", 2), ("h2", 3), ("h4", 1)
    ]


def test_host_assignments_topology():
    """(ref: hosts.py:106-155 rank packing)"""
    slots = get_host_assignments([HostInfo("a", 2), HostInfo("b", 2)], 4)
    got = [
        (s.rank, s.hostname, s.local_rank, s.cross_rank, s.local_size,
         s.cross_size)
        for s in slots
    ]
    assert got == [
        (0, "a", 0, 0, 2, 2),
        (1, "a", 1, 0, 2, 2),
        (2, "b", 0, 1, 2, 2),
        (3, "b", 1, 1, 2, 2),
    ]
    assert all(s.size == 4 for s in slots)


def test_host_assignments_max_np_truncates():
    slots = get_host_assignments([HostInfo("a", 4), HostInfo("b", 4)], 2, 3)
    assert len(slots) == 3
    assert [s.hostname for s in slots] == ["a", "a", "a"]


def test_host_assignments_insufficient_slots():
    with pytest.raises(ValueError, match="only 2 slots"):
        get_host_assignments([HostInfo("a", 2)], 4)


def test_slot_env_golden():
    """Exact worker env contract (ref: gloo_run.py:65-198)."""
    slots = get_host_assignments([HostInfo("localhost", 2)], 2)
    env = slot_env(slots[1], "127.0.0.1", 9999)
    assert env == {
        "HOROVOD_RANK": "1",
        "HOROVOD_SIZE": "2",
        "HOROVOD_LOCAL_RANK": "1",
        "HOROVOD_LOCAL_SIZE": "2",
        "HOROVOD_CROSS_RANK": "0",
        "HOROVOD_CROSS_SIZE": "1",
        "HOROVOD_GLOO_RENDEZVOUS_ADDR": "127.0.0.1",
        "HOROVOD_GLOO_RENDEZVOUS_PORT": "9999",
        "HOROVOD_HOSTNAME": "localhost",
        "HOROVOD_CONTROLLER": "tcp",
        "HOROVOD_CPU_OPERATIONS": "tcp",
    }


def test_ssh_command_golden():
    cmd = build_ssh_command(
        "worker1", ["python", "train.py"], {"HOROVOD_RANK": "3"},
        ssh_port=2222,
    )
    assert cmd[:5] == ["ssh", "-o", "StrictHostKeyChecking=no", "-p", "2222"]
    assert cmd[5] == "worker1"
    assert "HOROVOD_RANK=3" in cmd[6]
    assert "python train.py" in cmd[6]


def test_args_to_env_mapping():
    """(ref: config_parser.py set_env_from_args)"""
    args = make_parser().parse_args(
        ["-np", "2", "--fusion-threshold-mb", "32", "--cycle-time-ms", "2.5",
         "--cache-capacity", "512", "--timeline-filename", "/tmp/t.json",
         "--log-level", "DEBUG", "--no-stall-check", "--", "python", "x.py"]
    )
    env = args_to_env(args)
    assert env == {
        "HOROVOD_FUSION_THRESHOLD": str(32 * 1024 * 1024),
        "HOROVOD_CYCLE_TIME": "2.5",
        "HOROVOD_CACHE_CAPACITY": "512",
        "HOROVOD_TIMELINE": "/tmp/t.json",
        "HOROVOD_LOG_LEVEL": "DEBUG",
        "HOROVOD_STALL_CHECK_DISABLE": "1",
    }


def test_parser_command_remainder():
    args = make_parser().parse_args(["-np", "4", "python", "train.py", "--lr",
                                     "0.1"])
    assert args.num_proc == 4
    assert args.command == ["python", "train.py", "--lr", "0.1"]


# ---------------------------------------------------------------------------
_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    hvd.init()
    out = hvd.allreduce(np.ones(3, np.float32) * (hvd.rank() + 1),
                        average=False)
    assert out.tolist() == [3.0, 3.0, 3.0], out
    print(f"worker rank {hvd.rank()} done")
    """
)


def test_launch_static_two_local_workers(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    slots = get_host_assignments([HostInfo("localhost", 2)], 2)
    rc = launch_static(
        slots, [sys.executable, str(script)],
        extra_env={"PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
                   "HOROVOD_CYCLE_TIME": "1"},
    )
    assert rc == 0


def test_launch_static_propagates_failure(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text("import sys; sys.exit(3)")
    slots = get_host_assignments([HostInfo("localhost", 2)], 2)
    rc = launch_static(slots, [sys.executable, str(script)])
    assert rc == 3


def test_run_func_mode():
    from horovod_tpu.runner import run

    def fn():
        import horovod_tpu as hvd

        hvd.init()
        return hvd.rank() * 10

    results = run(fn, np=2, extra_env={"HOROVOD_CYCLE_TIME": "1"})
    assert results == [0, 10]


def test_hvdrun_cli_end_to_end(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env["HOROVOD_CYCLE_TIME"] = "1"
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner.launch", "-np", "2",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    assert "[0]<stdout>:" in out.stdout and "[1]<stdout>:" in out.stdout


def test_config_file_yaml(tmp_path):
    """YAML config fills unset flags; CLI wins; unknown keys rejected
    (ref: horovodrun --config-file, launch.py:212+)."""
    from horovod_tpu.runner.launch import make_parser, _apply_config_file

    cfg = tmp_path / "cfg.yaml"
    cfg.write_text(
        "num-proc: 4\ntuning:\n  fusion-threshold-mb: 8\n  cycle-time-ms: 2\n"
    )
    parser = make_parser()
    args = parser.parse_args(
        ["--config-file", str(cfg), "--cycle-time-ms", "9", "x"]
    )
    _apply_config_file(parser, args)
    assert args.num_proc == 4
    assert args.fusion_threshold_mb == 8
    assert args.cycle_time_ms == 9  # CLI beats file

    bad = tmp_path / "bad.yaml"
    bad.write_text("not-a-flag: 1\n")
    args2 = parser.parse_args(["--config-file", str(bad), "x"])
    try:
        _apply_config_file(parser, args2)
        assert False, "unknown key accepted"
    except SystemExit as e:
        assert "not_a_flag" in str(e)


def test_discover_tpu_hosts_env(monkeypatch):
    """TPU-VM slice metadata drives host discovery (SURVEY.md §5.8)."""
    from horovod_tpu.runner.hosts import discover_tpu_hosts

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "tpu-a,tpu-b,tpu-c")
    hosts = discover_tpu_hosts()
    assert [h.hostname for h in hosts] == ["tpu-a", "tpu-b", "tpu-c"]
    assert all(h.slots == 1 for h in hosts)

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "solo")
    assert discover_tpu_hosts() is None  # single host -> not a pod


def test_ssh_command_keeps_secret_off_cmdline():
    """The per-job HMAC key must ride ssh stdin, never the command line
    (visible in /proc/*/cmdline otherwise)."""
    from horovod_tpu.runner.launch import build_ssh_command
    from horovod_tpu.utils import env as env_cfg

    env = {"HOROVOD_RANK": "3", env_cfg.SECRET_KEY: "deadbeef" * 8}
    argv = build_ssh_command("hostA", ["python", "train.py"], env)
    joined = " ".join(argv)
    assert "deadbeef" not in joined
    assert "HOROVOD_RANK=3" in joined
    # The remote command reads the key from stdin instead.
    assert f"IFS= read -r {env_cfg.SECRET_KEY}" in joined
    assert f"export {env_cfg.SECRET_KEY}" in joined

    # Without a secret, no stdin plumbing is injected.
    argv2 = build_ssh_command("hostA", ["python", "train.py"],
                              {"HOROVOD_RANK": "3"})
    assert "read -r" not in " ".join(argv2)


def test_check_build_golden():
    """hvdrun --check-build prints the availability report and exits 0
    (ref: horovod/runner/launch.py:106-149,225 — horovodrun -cb)."""
    from horovod_tpu.runner.launch import check_build, run_commandline

    out = check_build()
    # Structure: three sections, reference-style checkbox rows.
    for section in ("Available Frameworks:", "Available Controllers:",
                    "Available Tensor Operations:"):
        assert section in out, out
    # This build always ships the JAX/XLA path and the TCP controller.
    assert "[X] JAX" in out
    assert "[X] TCP (Gloo equivalent)" in out
    assert "[X] XLA collectives (ICI/DCN)" in out
    # Backends that do not exist by design are reported absent.
    assert "[ ] NCCL" in out
    assert "[ ] DDL" in out
    assert "[ ] CCL" in out
    assert "[ ] MPI" in out
    # CLI: --check-build works without -np or a command.
    assert run_commandline(["--check-build"]) == 0
