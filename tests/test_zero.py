"""ZeRO-sharded optimizer state across both planes (docs/running.md
"ZeRO sharded optimizer state"): traced reduce-scatter → shard update →
allgather parity vs the replicated optimizer, 2-D data×model
composition, error feedback carried as cross-step optimizer state under
jit (and the regression bound vs the stateless wire cast), the int8
traced wire lane, checkpoint re-cuts across world-size changes, the
eager process-mode plane's bitwise parity and global round-trip, the
GSPMD `make_train_step(zero=True)` lane, and the disabled-mode
pays-nothing contract."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.optim import zero as zero_mod
from horovod_tpu.optim.zero import (
    ZeroState,
    recut_state,
    state_specs,
    zero_init,
    zero_optimizer,
)
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.utils import env as env_cfg
from horovod_tpu.utils.compat import shard_map


@pytest.fixture(autouse=True)
def _clean_env():
    keys = ("HOROVOD_WIRE_COMPRESSION", "HOROVOD_WIRE_COMPRESSION_MIN_BYTES",
            "HOROVOD_WIRE_COMPRESSION_INT8", "HOROVOD_ZERO_SHARDING")
    saved = {k: os.environ.get(k) for k in keys}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _params():
    rng = np.random.RandomState(0)
    return {"w": jnp.asarray(rng.randn(31, 7).astype(np.float32)),
            "b": jnp.asarray(rng.randn(53).astype(np.float32))}


def _grads_per_device(n, seed=1):
    rng = np.random.RandomState(seed)
    p = _params()
    return {k: jnp.asarray(
        rng.randn(n, *np.shape(v)).astype(np.float32))
        for k, v in p.items()}


# ---------------------------------------------------------------------------
# Traced plane: parity vs the replicated optimizer

def test_traced_zero_matches_replicated(hvd_mesh):
    """zero=1 under shard_map produces the same updates as the
    replicated DistributedOptimizer on identical per-device grads —
    at fp32 reduction-order tolerance (psum_scatter vs psum orders) —
    while every state leaf carries the world-size shard dim."""
    n = 4
    mesh = create_mesh({"hvd": n}, devices=jax.devices()[:n])
    params = _params()
    grads = _grads_per_device(n)

    tx_z = hvd.DistributedOptimizer(optax.adam(1e-3), zero=1)
    tx_r = hvd.DistributedOptimizer(optax.adam(1e-3))
    state_z = zero_init(tx_z, params, mesh, axis_name="hvd")
    state_r = tx_r.init(params)

    def step(tx):
        def inner(p, g, s):
            g = jax.tree.map(lambda a: a[0], g)
            upd, s2 = tx.update(g, s, p)
            return upd, s2
        return inner

    upd_z, state_z2 = shard_map(
        step(tx_z), mesh=mesh,
        in_specs=(P(), P("hvd"), state_specs("hvd")),
        out_specs=(P(), state_specs("hvd")))(params, grads, state_z)
    upd_r, _ = shard_map(
        step(tx_r), mesh=mesh,
        in_specs=(P(), P("hvd"), P()),
        out_specs=(P(), P()))(params, grads, state_r)

    for k in upd_z:
        np.testing.assert_allclose(np.asarray(upd_z[k]),
                                   np.asarray(upd_r[k]),
                                   rtol=1e-5, atol=1e-6)
    # Stacked state: every leaf's leading dim is the world size, and
    # each device's shard is 1/n of the flat total (padded).
    total = sum(int(np.prod(np.shape(v))) for v in params.values())
    k_shard = (total + (-total) % n) // n
    for leaf in jax.tree.leaves(state_z2):
        assert np.shape(leaf)[0] == n, np.shape(leaf)
        if np.ndim(leaf) > 1:
            assert np.shape(leaf)[1] == k_shard, np.shape(leaf)


def test_traced_zero_2d_mesh_data_axis_only():
    """On a dp×tp mesh zero shards over the DATA axis only: updates are
    bitwise identical across dp replicas, different across tp shards,
    and the state's leading dim is the dp size."""
    hvd.shutdown()
    DP, TP, K = 2, 4, 8
    mesh = create_mesh({"dp": DP, "tp": TP})
    rng = np.random.RandomState(2)
    w = jnp.asarray(rng.randn(TP * K).astype(np.float32))
    g = jnp.asarray(rng.randn(DP, TP * K).astype(np.float32))

    tx = hvd.DistributedOptimizer(optax.adam(1e-2), zero=1)
    state = zero_init(tx, jnp.zeros((K,), jnp.float32), mesh,
                      axis_name="dp")

    def worker(w_shard, g_shard, s):
        upd, _ = tx.update(g_shard[0], s, w_shard)
        return upd[None, None, :]

    out = np.asarray(shard_map(
        worker, mesh=mesh,
        in_specs=(P("tp"), P("dp", "tp"), state_specs("dp")),
        out_specs=P("dp", "tp"))(w, g, state))  # (DP, TP, K)
    assert np.array_equal(out[0], out[1])
    assert not np.array_equal(out[0, 0], out[0, 1])
    for leaf in jax.tree.leaves(state):
        assert np.shape(leaf)[0] == DP, np.shape(leaf)


# ---------------------------------------------------------------------------
# Error feedback as optimizer state (the regression bound)

def _accumulate(tx, specs, steps=150, d=256):
    """`steps` sgd(1.0) updates of a constant gradient whose value is
    NOT representable in bf16 — the construction where a stateless
    cast's error grows linearly and error feedback telescopes."""
    hvd.shutdown()
    mesh = create_mesh({"hvd": 2}, devices=jax.devices()[:2])
    gval = 1.0 + 1.0 / 300.0
    g = jnp.full((2 * d,), gval, jnp.float32)
    p = jnp.zeros((d,), jnp.float32)
    state = shard_map(tx.init, mesh=mesh, in_specs=(P(),),
                      out_specs=specs)(p)

    @jax.jit
    def step(p, g, s):
        def inner(p, g, s):
            upd, s2 = tx.update(g, s, p)
            return optax.apply_updates(p, upd), s2
        return shard_map(inner, mesh=mesh,
                         in_specs=(P(), P("hvd"), specs),
                         out_specs=(P(), specs))(p, g, s)

    for _ in range(steps):
        p, state = step(p, g, state)
    want = -steps * gval
    return float(np.max(np.abs(np.asarray(p) - want)))


def test_traced_error_feedback_beats_stateless_cast():
    """Acceptance regression: where the stateless bf16 cast degrades a
    non-representable gradient accumulation, EF-as-optimizer-state
    converges — error at least 10x smaller, with or without ZeRO."""
    os.environ["HOROVOD_WIRE_COMPRESSION"] = "bf16"
    os.environ["HOROVOD_WIRE_COMPRESSION_MIN_BYTES"] = "0"

    err_stateless = _accumulate(
        hvd.DistributedOptimizer(optax.sgd(1.0)), P())
    err_ef = _accumulate(
        hvd.DistributedOptimizer(optax.sgd(1.0), error_feedback=True),
        state_specs("hvd", zero=False))
    err_zero_ef = _accumulate(
        hvd.DistributedOptimizer(optax.sgd(1.0), zero=1,
                                 error_feedback=True),
        state_specs("hvd"))

    assert err_stateless > 0.1, err_stateless  # the cast DOES degrade
    assert err_ef * 10 < err_stateless, (err_ef, err_stateless)
    assert err_zero_ef * 10 < err_stateless, (err_zero_ef, err_stateless)


def test_traced_zero_full_width_without_compression():
    """No codec configured: the zero path is exact (reduction-order
    tolerance only), and error_feedback residuals stay zero."""
    err = _accumulate(
        hvd.DistributedOptimizer(optax.sgd(1.0), zero=1,
                                 error_feedback=True),
        state_specs("hvd"), steps=20)
    assert err < 1e-3, err


# ---------------------------------------------------------------------------
# int8 traced wire lane

def _psum2(x, **env):
    hvd.shutdown()
    mesh = create_mesh({"hvd": 2}, devices=jax.devices()[:2])
    for k, v in env.items():
        os.environ[k] = v
    try:
        return np.asarray(shard_map(
            lambda v: hvd.allreduce(v, op=hvd.Sum),
            mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"))(x))
    finally:
        for k in env:
            os.environ.pop(k, None)


def test_traced_int8_lane_numerics_and_counter():
    """The int8 lane matches the closed-form quantize/decode-sum
    reference exactly, and counts `codec="int8"` call sites."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(2 * 2048).astype(np.float32))
    key = 'horovod_traced_compressed_ops_total{codec="int8"}'
    before = hvd.metrics()["metrics"].get(key, 0)
    got = _psum2(x, HOROVOD_WIRE_COMPRESSION="bf16",
                 HOROVOD_WIRE_COMPRESSION_INT8="1",
                 HOROVOD_WIRE_COMPRESSION_MIN_BYTES="0")
    halves = np.asarray(x).reshape(2, -1)
    dec = []
    for h in halves:
        scale = max(np.max(np.abs(h)) / 127.0, 1e-30)
        q = np.clip(np.round(h / scale), -127.0, 127.0).astype(np.int8)
        dec.append(q.astype(np.float32) * np.float32(scale))
    want = np.tile(dec[0] + dec[1], 2)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)
    # Quantization really happened (differs from the exact sum)...
    exact = np.tile(halves[0] + halves[1], 2)
    assert not np.array_equal(got, exact)
    # ...within int8 step bounds.
    np.testing.assert_allclose(got, exact, atol=2.5 * np.max(np.abs(x))
                               / 127.0)
    assert hvd.metrics()["metrics"].get(key, 0) > before


def test_traced_int8_lane_gating():
    """Opt-in only: the int8 knob without an active codec mode, or a
    payload under the min-bytes floor, ships full width (bitwise)."""
    x = jnp.asarray(np.random.RandomState(5).randn(512).astype(np.float32))
    full = _psum2(x)
    no_mode = _psum2(x, HOROVOD_WIRE_COMPRESSION_INT8="1",
                     HOROVOD_WIRE_COMPRESSION_MIN_BYTES="0")
    np.testing.assert_array_equal(full, no_mode)
    floored = _psum2(x, HOROVOD_WIRE_COMPRESSION="bf16",
                     HOROVOD_WIRE_COMPRESSION_INT8="1",
                     HOROVOD_WIRE_COMPRESSION_MIN_BYTES="1048576")
    np.testing.assert_array_equal(full, floored)


# ---------------------------------------------------------------------------
# Checkpoint re-cuts across world-size changes

def _materialized_state(n=4, error_feedback=True):
    """A traced ZeRO state with NONZERO moments and residual, as numpy
    (the JaxState/CheckpointManager materialized form)."""
    hvd.shutdown()
    mesh = create_mesh({"hvd": n}, devices=jax.devices()[:n])
    params = _params()
    grads = _grads_per_device(n, seed=6)
    if error_feedback:
        os.environ["HOROVOD_WIRE_COMPRESSION"] = "bf16"
        os.environ["HOROVOD_WIRE_COMPRESSION_MIN_BYTES"] = "0"
    tx = hvd.DistributedOptimizer(optax.adam(1e-3), zero=1,
                                  error_feedback=error_feedback)
    state = zero_init(tx, params, mesh, axis_name="hvd")

    def inner(p, g, s):
        g = jax.tree.map(lambda a: a[0], g)
        _, s2 = tx.update(g, s, p)
        return s2

    state = shard_map(inner, mesh=mesh,
                      in_specs=(P(), P("hvd"), state_specs("hvd")),
                      out_specs=state_specs("hvd"))(params, grads, state)
    os.environ.pop("HOROVOD_WIRE_COMPRESSION", None)
    os.environ.pop("HOROVOD_WIRE_COMPRESSION_MIN_BYTES", None)
    return params, jax.tree.map(np.asarray, state)


def _flat_content(state, total):
    out = []
    for leaf in jax.tree.leaves(state):
        a = np.asarray(leaf)
        if a.ndim >= 2:
            out.append(a.reshape(-1)[:total])
    return out


def test_recut_state_bitwise_across_world_sizes():
    """n=4 → m=2 → n=4: content is bitwise-preserved both ways (only
    the zero tail padding is re-sized), shard-scalar leaves broadcast,
    and the EF residual survives the re-cut."""
    params, state = _materialized_state(n=4, error_feedback=True)
    total = sum(int(np.prod(np.shape(v)))
                for v in jax.tree.leaves(params))
    assert state.residual is not None
    assert np.any(state.residual != 0)  # bf16 error actually carried

    down = recut_state(state, params, 2)
    for leaf in jax.tree.leaves(down):
        assert np.shape(leaf)[0] == 2, np.shape(leaf)
    for a, b in zip(_flat_content(state, total),
                    _flat_content(down, total)):
        np.testing.assert_array_equal(a, b)

    back = recut_state(down, params, 4)
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # optax count scalars: identical across shards, broadcast on re-cut.
    counts = [np.asarray(l) for l in jax.tree.leaves(state)
              if np.ndim(l) == 1]
    assert counts and all(np.all(c == c[0]) for c in counts)


def test_recut_state_rejects_unknown_layout():
    params, state = _materialized_state(n=4, error_feedback=False)
    bad = jax.tree.map(lambda a: a, state)._replace(
        inner=jax.tree.map(lambda a: a[:, :3] if a.ndim >= 2 else a,
                           state.inner))
    with pytest.raises(ValueError, match="unrecognized ZeroState leaf"):
        recut_state(bad, params, 2)


def test_ef_residual_survives_elastic_reset():
    """JaxState save → live mutation → restore keeps the EF residual
    (and moments) bitwise — an elastic rollback never drops the
    telescoped correction."""
    from horovod_tpu.elastic.state import JaxState

    params, state = _materialized_state(n=4, error_feedback=True)
    state = jax.tree.map(np.array, state)  # writable host copies
    js = JaxState(params=jax.tree.map(np.array, params),
                  opt_state=state)
    want = jax.tree.map(np.copy, state)
    # In-place live mutation (a numpy optimizer step would do this).
    for leaf in jax.tree.leaves(js.opt_state):
        np.asarray(leaf)[...] = -1.0
    js.restore()
    for a, b in zip(jax.tree.leaves(want),
                    jax.tree.leaves(js.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Eager plane (process mode, real subprocess ranks)

def _eager_worker():
    import numpy as np

    import jax
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.optim.zero import (
        _eager_cut,
        eager_state_from_global,
        eager_state_to_global,
    )

    hvd.init()
    n, rank = hvd.size(), hvd.rank()
    rng = np.random.RandomState(0)
    # 8192 elements = 16 ownership blocks (512 each): an even 4-way cut,
    # so the measured saving is the clean (n-1)/n.
    params = {"w": rng.randn(6000).astype(np.float32),
              "b": rng.randn(2192).astype(np.float32)}
    total = sum(v.size for v in params.values())
    inner = optax.adam(1e-3)
    tx = hvd.DistributedOptimizer(inner, zero=1)
    state = tx.init(params)
    ctl_state = inner.init(params)

    checks = {"rank": rank}
    for i in range(2):
        # Integer grads: the ring sum is exact, /n dyadic — parity with
        # the local replicated control must be BITWISE.
        grads = {k: (np.arange(v.size, dtype=np.int32) % 5
                     + rank + i).astype(np.float32).reshape(v.shape)
                 for k, v in params.items()}
        upd, state = tx.update(grads, state, params)
        mean = {k: sum((grads[k] - rank) + r
                       for r in range(n)) / np.float32(n) for k in grads}
        ctl_upd, ctl_state = inner.update(mean, ctl_state, params)
        checks["bitwise"] = all(
            np.array_equal(np.asarray(upd[k]), np.asarray(ctl_upd[k]))
            for k in upd)
        if not checks["bitwise"]:
            break

    snap = hvd.metrics()["metrics"]
    checks["sharded_gauge"] = int(snap.get(
        'horovod_optimizer_state_bytes{mode="sharded"}', 0))
    checks["replicated_gauge"] = int(snap.get(
        'horovod_optimizer_state_bytes{mode="replicated"}', 0))
    checks["measured"] = int(sum(
        np.asarray(l).nbytes for l in jax.tree.leaves(state.inner)))

    # Global round-trip: to_global is replicated and re-slices bitwise,
    # at the current world AND at a different one (the n→m restore).
    g = eager_state_to_global(inner, state, params)
    back = eager_state_from_global(inner, g, params)
    checks["roundtrip"] = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(state.inner),
                        jax.tree.leaves(back.inner)))
    lo2, hi2 = _eager_cut(total, 4, 2)[rank % 2]
    recut = eager_state_from_global(inner, g, params, world=2,
                                    rank=rank % 2)
    checks["recut"] = (recut.lo, recut.hi) == (lo2, hi2) and all(
        np.asarray(l).shape[0] in (hi2 - lo2,)
        for l in jax.tree.leaves(recut.inner)
        if np.ndim(l) == 1 and np.size(l) > 1)
    checks["global_bytes"] = int(sum(
        np.asarray(l).nbytes for l in jax.tree.leaves(g)))
    hvd.shutdown()
    return checks


def test_eager_zero_process_mode():
    """np=4 subprocess run: bitwise parity vs the replicated control,
    measured (n-1)/n gauges, and the to_global/from_global round-trip
    (including an n=4 → m=2 re-cut)."""
    from horovod_tpu.runner import run

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = run(_eager_worker, np=4, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_TCP_TIMEOUT_SECONDS": "60",
        # The worker unpickles `_eager_worker` by reference: it must be
        # able to import this test module.
        "PYTHONPATH": os.pathsep.join(
            [repo_root, os.path.dirname(os.path.abspath(__file__))]),
    })
    assert len(results) == 4
    for r in results:
        assert r["bitwise"], r
        assert r["roundtrip"], r
        assert r["recut"], r
        assert r["sharded_gauge"] == r["measured"], r
        # ~(n-1)/n saving, with block-granularity slack.
        assert r["sharded_gauge"] < r["replicated_gauge"] / 3, r
    # The gathered global state is identical (replicated) everywhere.
    assert len({r["global_bytes"] for r in results}) == 1, results


# ---------------------------------------------------------------------------
# GSPMD lane: make_train_step(zero=True)

def test_make_train_step_zero_parity_and_sharding():
    """zero=True shards the adam moments over dp (the sharding
    constraint XLA derives the reduce-scatter/allgather from) and the
    loss trajectory matches zero=False."""
    import flax.linen as nn

    from horovod_tpu.parallel.train import make_train_step

    hvd.shutdown()

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Dense(32)(x)
            x = nn.relu(x)
            return nn.Dense(8)(x)

    def loss_fn(logits, labels):
        return jnp.mean((logits - labels) ** 2)

    mesh = create_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
    rng = np.random.RandomState(7)
    x = rng.randn(8, 16).astype(np.float32)
    y = rng.randn(8, 8).astype(np.float32)

    losses = {}
    shardings = {}
    for zero in (False, True):
        build = make_train_step(MLP(), optax.adam(1e-2), loss_fn,
                                mesh=mesh, zero=zero)
        init_fn, step_fn, ssh = build(jax.random.PRNGKey(0), x, y)
        shardings[zero] = ssh
        state = init_fn(jax.random.PRNGKey(0))
        vals = []
        for _ in range(3):
            state, loss = step_fn(state, x, y)
            vals.append(float(loss))
        losses[zero] = vals

    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=1e-5, atol=1e-6)
    # At least one moment leaf carries dp on dim 0 under zero=True and
    # none do under zero=False.
    def dp_leaves(ssh):
        out = 0
        for s in jax.tree.leaves(
                ssh.opt_state,
                is_leaf=lambda l: hasattr(l, "spec")):
            spec = tuple(getattr(s, "spec", ()) or ())
            if spec and spec[0] is not None and "dp" in (
                    spec[0] if isinstance(spec[0], tuple)
                    else (spec[0],)):
                out += 1
        return out

    assert dp_leaves(shardings[True]) > 0
    assert dp_leaves(shardings[False]) == 0


# ---------------------------------------------------------------------------
# Disabled mode pays nothing; knobs; validation

def test_disabled_mode_is_the_original_path(hvd_mesh):
    """zero off, error_feedback off: state structure and update values
    are exactly the original DistributedOptimizer's — no ZeroState
    anywhere, no extra leaves."""
    params = _params()
    tx = hvd.DistributedOptimizer(optax.adam(1e-3))
    state = tx.init(params)
    want = optax.adam(1e-3).init(params)
    assert (jax.tree.structure(state) == jax.tree.structure(want))
    assert not any(isinstance(s, (ZeroState, zero_mod.ZeroEagerState))
                   for s in jax.tree.leaves(
                       state, is_leaf=lambda x: isinstance(
                           x, (ZeroState, zero_mod.ZeroEagerState))))


def test_env_knob_parsing():
    os.environ["HOROVOD_ZERO_SHARDING"] = "1"
    assert env_cfg.zero_sharding_default() == 1
    os.environ["HOROVOD_ZERO_SHARDING"] = "2"
    assert env_cfg.zero_sharding_default() == 2
    for bogus in ("banana", "3", "-1", ""):
        os.environ["HOROVOD_ZERO_SHARDING"] = bogus
        assert env_cfg.zero_sharding_default() == 0


def test_env_knob_engages_zero(hvd_mesh):
    """HOROVOD_ZERO_SHARDING=1 flips DistributedOptimizer to the zero
    path with no code change (mesh mode: the trivial 1-way cut)."""
    os.environ["HOROVOD_ZERO_SHARDING"] = "1"
    tx = hvd.DistributedOptimizer(optax.adam(1e-3))
    state = tx.init(_params())
    assert isinstance(state, zero_mod.ZeroEagerState)
    assert state.nshards == 1


def test_zero_optimizer_validation():
    with pytest.raises(ValueError, match="stage must be 0/1/2"):
        zero_optimizer(optax.adam(1e-3), stage=3)
    with pytest.raises(ValueError, match="stage>=1 or error_feedback"):
        zero_optimizer(optax.adam(1e-3), stage=0)
    tx = zero_optimizer(optax.adam(1e-3), stage=1)
    with pytest.raises(ValueError, match="need params"):
        tx.update({"w": jnp.zeros(4)}, None)


def test_status_snapshot_populated(hvd_mesh):
    os.environ["HOROVOD_ZERO_SHARDING"] = "1"
    tx = hvd.DistributedOptimizer(optax.adam(1e-3))
    tx.init(_params())
    st = zero_mod.status_snapshot()
    assert st.get("enabled") is True
    assert st.get("sharded_state_bytes", 0) > 0
    assert st.get("replicated_state_bytes", 0) > 0
