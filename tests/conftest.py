"""Test configuration: force an 8-device virtual CPU mesh (SURVEY.md §4
lesson: every distributed test must run without TPU hardware, the way the
reference's tests run under `horovodrun -np 2` on one CPU machine).

The environment's sitecustomize imports jax and registers a TPU plugin
before pytest starts, so env-var forcing is too late; instead we switch
platform via jax config and clear any already-created backends.
"""
import os

# For any worker subprocesses spawned by tests.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    # jax >= 0.5 spells the device-count override as a config option;
    # on older versions the XLA_FLAGS set above (before `import jax`)
    # does the same job, so an unknown option is not an error.
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
import jax.extend.backend as _jeb

_jeb.clear_backends()
assert len(jax.devices()) == 8 and jax.devices()[0].platform == "cpu"

import pytest


def pytest_sessionstart(session):
    """Offline-descope tripwire: this environment cannot install
    pyspark (no network), so tests/test_spark.py validates against a
    barrier-semantics mock and README documents the descope. The moment
    this repo lands somewhere pyspark IS importable, that caveat must
    turn into a red test — not a silently stale claim. (mxnet needs no
    tripwire: its tests importorskip and auto-unskip against the real
    package.) Set HOROVOD_REAL_SPARK_VALIDATED=1 once real-Spark runs
    are wired to acknowledge."""
    import importlib.util

    if (importlib.util.find_spec("pyspark") is not None
            and not os.environ.get("HOROVOD_REAL_SPARK_VALIDATED")):
        raise pytest.UsageError(
            "pyspark is importable, but tests/test_spark.py and "
            "tests/test_framework_estimators.py still validate against "
            "the mock barrier layer only. Run the estimators/runner "
            "against real Spark and set HOROVOD_REAL_SPARK_VALIDATED=1 "
            "(see README 'offline descopes')."
        )


@pytest.fixture
def hvd_mesh():
    """Fresh mesh-mode init for a test, torn down after."""
    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture()
def hvd_single():
    """Fresh SIZE-1 mesh-mode world (single device), torn down after —
    for tests of single-process semantics that must not inherit a
    leaked full-mesh world from an earlier in-process test."""
    import jax

    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init(devices=jax.devices()[:1])
    yield hvd
    hvd.shutdown()
