"""Pipelined execution engine: deterministic channel assignment,
per-channel FIFO ordering, fence semantics (BARRIER/JOIN/param-sync),
executor error propagation, the bounded in-flight window, and
event-driven cycles (ISSUE 4).

Single-rank tests drive a recording LocalBackend (the executor plumbing
is identical at any world size); cross-rank fences ride the in-process
ThreadedGroup harness from test_engine.
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

from horovod_tpu.backend.base import CTRL_CHANNEL, current_channel
from horovod_tpu.backend.local import LocalBackend
from horovod_tpu.backend.threaded import ThreadedGroup
from horovod_tpu.common.exceptions import (
    HorovodInternalError,
    TransportError,
)
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.engine.engine import Engine, HandleManager
from test_engine import run_ranks


# ---------------------------------------------------------------------------
# satellite bugfix: HandleManager.wait with an unknown handle
def test_handle_manager_unknown_handle_raises_value_error():
    hm = HandleManager()
    with pytest.raises(ValueError, match="unknown handle"):
        hm.wait(12345, timeout=0.1)


def test_handle_manager_double_wait_raises_value_error():
    from horovod_tpu.common.types import Status

    hm = HandleManager()
    h = hm.allocate()
    hm.mark_done(h, Status.OK(), np.ones(1))
    assert hm.wait(h) is not None
    with pytest.raises(ValueError, match="unknown handle"):
        hm.wait(h, timeout=0.1)


# ---------------------------------------------------------------------------
# recording backends
class RecordingBackend(LocalBackend):
    """LocalBackend that records (event, channel, nbytes, t) for every
    data-plane call, with an optional per-op delay to force queueing."""

    def __init__(self, delay: float = 0.0, engine_ref=None):
        super().__init__()
        self.events = []
        self.delay = delay
        self.engine_ref = engine_ref
        self.max_inflight_seen = 0
        self._lock = threading.Lock()

    def _record(self, what, nbytes=0):
        eng = self.engine_ref
        with self._lock:
            if eng is not None:
                self.max_inflight_seen = max(
                    self.max_inflight_seen, eng._inflight)
            self.events.append(
                (what, current_channel(), nbytes, time.monotonic()))

    def allreduce(self, arr, op=ReduceOp.SUM):
        if self.delay:
            time.sleep(self.delay)
        self._record("allreduce", arr.nbytes)
        return arr.copy()

    def barrier(self):
        self._record("barrier")


def _engine(backend, cycle_s=0.001, **kw):
    eng = Engine(rank=0, size=1, backend=backend, **kw)
    eng.cycle_time_s = cycle_s
    if isinstance(backend, RecordingBackend) and backend.engine_ref is None:
        backend.engine_ref = eng
    eng.start()
    return eng


# ---------------------------------------------------------------------------
# deterministic channel assignment
def test_round_robin_channel_assignment(monkeypatch):
    monkeypatch.setenv("HOROVOD_CHANNEL_POLICY", "rr")
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    be = RecordingBackend()
    eng = _engine(be)
    try:
        for i in range(6):
            eng.synchronize(
                eng.enqueue_allreduce(np.ones(i + 1, np.float32),
                                      name=f"rr{i}"), timeout=30)
    finally:
        eng.shutdown()
    chans = [c for what, c, _, _ in be.events if what == "allreduce"]
    assert chans == [0, 1, 0, 1, 0, 1]


def test_num_channels_env_respected(monkeypatch):
    monkeypatch.setenv("HOROVOD_CHANNEL_POLICY", "rr")
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "3")
    be = RecordingBackend()
    eng = _engine(be)
    try:
        for i in range(6):
            eng.synchronize(
                eng.enqueue_allreduce(np.ones(2, np.float32),
                                      name=f"nc{i}"), timeout=30)
    finally:
        eng.shutdown()
    chans = {c for what, c, _, _ in be.events if what == "allreduce"}
    assert chans == {0, 1, 2}


def test_cached_response_replays_its_negotiated_channel(monkeypatch):
    """Steady-state cache hits must execute on the channel assigned at
    negotiation time — on every rank — or per-channel FIFOs diverge."""
    monkeypatch.setenv("HOROVOD_CHANNEL_POLICY", "rr")
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    be = RecordingBackend()
    eng = _engine(be)
    try:
        for _ in range(4):
            eng.synchronize(
                eng.enqueue_allreduce(np.ones(3, np.float32), name="a"),
                timeout=30)
            eng.synchronize(
                eng.enqueue_allreduce(np.ones(5, np.float32), name="b"),
                timeout=30)
    finally:
        eng.shutdown()
    by_size = {}
    for what, c, nbytes, _ in be.events:
        if what == "allreduce":
            by_size.setdefault(nbytes, set()).add(c)
    # tensor "a" (12B) landed on one channel every time, "b" (20B) on
    # the other — cache replay kept the original assignment sticky.
    assert len(by_size[12]) == 1 and len(by_size[20]) == 1
    assert by_size[12] != by_size[20]


def test_size_policy_reserves_latency_lane(monkeypatch):
    """Default policy: small responses ride the highest channel (the
    latency lane) while bulk responses round-robin over the rest — a
    small op is never queued behind a streaming bulk collective."""
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    monkeypatch.setenv("HOROVOD_LATENCY_CHANNEL_BYTES", "1024")
    be = RecordingBackend()
    eng = _engine(be)
    try:
        for i in range(3):
            eng.synchronize(  # 4KB > 1024 -> bulk lane(s)
                eng.enqueue_allreduce(np.ones(1024, np.float32),
                                      name=f"big{i}"), timeout=30)
            eng.synchronize(  # 64B <= 1024 -> latency lane
                eng.enqueue_allreduce(np.ones(16, np.float32),
                                      name=f"small{i}"), timeout=30)
    finally:
        eng.shutdown()
    by_size = {}
    for what, c, nbytes, _ in be.events:
        if what == "allreduce":
            by_size.setdefault(nbytes, set()).add(c)
    assert by_size[4096] == {0}   # bulk: rr over channels [0]
    assert by_size[64] == {1}     # latency lane: highest channel


# ---------------------------------------------------------------------------
# per-channel FIFO ordering
def test_per_channel_fifo_order(monkeypatch):
    monkeypatch.setenv("HOROVOD_CHANNEL_POLICY", "rr")
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1")  # no fusion
    be = RecordingBackend(delay=0.01)
    eng = _engine(be)
    try:
        handles = [
            eng.enqueue_allreduce(np.ones(i + 1, np.float32), name=f"o{i}")
            for i in range(8)
        ]
        for h in handles:
            eng.synchronize(h, timeout=30)
    finally:
        eng.shutdown()
    # Per channel, execution order must equal dispatch (= enqueue) order:
    # sizes grow with the enqueue index, so each channel's recorded byte
    # counts must be strictly increasing.
    per_chan = {}
    for what, c, nbytes, _ in be.events:
        if what == "allreduce":
            per_chan.setdefault(c, []).append(nbytes)
    assert set(per_chan) == {0, 1}
    for chan, sizes in per_chan.items():
        assert sizes == sorted(sizes), (chan, sizes)


# ---------------------------------------------------------------------------
# fences
def test_barrier_fence_drains_inflight_ops(monkeypatch):
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    be = RecordingBackend(delay=0.3)
    eng = _engine(be)
    try:
        h = eng.enqueue_allreduce(np.ones(4, np.float32), name="slow")
        time.sleep(0.05)  # let the slow op get dispatched
        eng.synchronize(eng.enqueue_barrier(), timeout=30)
        assert eng.poll(h), "barrier completed before the in-flight op"
        eng.synchronize(h, timeout=30)
    finally:
        eng.shutdown()
    kinds = [what for what, _, _, _ in be.events]
    assert kinds.index("allreduce") < kinds.index("barrier")


def test_join_fence_completes_after_inflight_ops(monkeypatch):
    """JOIN drains every channel first: when the join handle completes,
    all previously enqueued collectives must already be done."""

    def fn(eng, rank):
        hs = [
            eng.enqueue_allreduce(
                np.full(1024, float(rank + 1), np.float32), name=f"j{i}")
            for i in range(4)
        ]
        eng.synchronize(eng.enqueue_join(), timeout=60)
        assert all(eng.poll(h) for h in hs), "join outran a pending op"
        return [eng.synchronize(h, timeout=30) for h in hs]

    out = run_ranks(2, fn)
    for i in range(4):
        np.testing.assert_allclose(out[0][i], np.full(1024, 3.0))


def test_param_sync_fence_sees_drained_channels(monkeypatch):
    """Autotune parameter sync is a fence: at the moment the collective
    sync runs, no response may be in flight on any channel."""
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    be = RecordingBackend(delay=0.01)
    eng = _engine(be)
    syncs = []
    orig = eng.controller.synchronize_parameters

    def spy(params):
        syncs.append(eng._inflight)
        return orig(params)

    eng.controller.synchronize_parameters = spy
    eng.param_manager.cycles_per_sample = 2
    eng.param_manager.max_samples = 2
    eng.param_manager.warmup_samples = 1
    try:
        for i in range(60):
            eng.synchronize(
                eng.enqueue_allreduce(np.ones(8, np.float32),
                                      name=f"t{i % 4}"), timeout=30)
            if eng.param_manager.done:
                break
    finally:
        eng.shutdown()
    assert syncs, "autotune never reached a sync boundary"
    assert all(v == 0 for v in syncs), syncs


# ---------------------------------------------------------------------------
# executor error propagation
class OneChannelFails(LocalBackend):
    """Channel 0 ops die with a transport error; channel 1 ops are slow
    but succeed — the failure must still take the whole engine down."""

    def allreduce(self, arr, op=ReduceOp.SUM):
        if current_channel() == 0:
            raise TransportError("rank 0: send to peer 1 failed: injected")
        time.sleep(0.1)
        return arr.copy()


def test_executor_error_kills_engine_and_fails_all_channels(monkeypatch):
    monkeypatch.setenv("HOROVOD_CHANNEL_POLICY", "rr")
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    eng = _engine(OneChannelFails())
    try:
        handles = [
            eng.enqueue_allreduce(np.ones(4, np.float32), name=f"x{i}")
            for i in range(4)
        ]
        failures = 0
        for h in handles:
            with pytest.raises(HorovodInternalError):
                eng.synchronize(h, timeout=30)
            failures += 1
        assert failures == 4
        # Latched: post-death enqueues fail immediately with the reason.
        h = eng.enqueue_allreduce(np.ones(4, np.float32), name="after")
        with pytest.raises(HorovodInternalError, match="peer 1"):
            eng.synchronize(h, timeout=30)
    finally:
        eng.shutdown()
    # Executors exited — no leaked worker threads.
    for ex in eng._executors.values():
        assert not ex.thread.is_alive()


# ---------------------------------------------------------------------------
# bounded in-flight window
def test_inflight_window_bounds_dispatch(monkeypatch):
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    monkeypatch.setenv("HOROVOD_MAX_INFLIGHT_RESPONSES", "1")
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1")
    be = RecordingBackend(delay=0.02)
    eng = _engine(be)
    try:
        handles = [
            eng.enqueue_allreduce(np.ones(4, np.float32), name=f"w{i}")
            for i in range(6)
        ]
        for h in handles:
            eng.synchronize(h, timeout=30)
    finally:
        eng.shutdown()
    assert be.max_inflight_seen == 1, be.max_inflight_seen


# ---------------------------------------------------------------------------
# event-driven cycles
def test_event_driven_cycle_beats_the_sleep_floor():
    be = RecordingBackend()
    eng = _engine(be, cycle_s=0.25)
    try:
        eng.synchronize(  # absorb startup straggle
            eng.enqueue_allreduce(np.ones(2, np.float32), name="warm"),
            timeout=30)
        t0 = time.monotonic()
        eng.synchronize(
            eng.enqueue_allreduce(np.ones(2, np.float32), name="fast"),
            timeout=30)
        dt = time.monotonic() - t0
        assert dt < 0.15, (
            f"enqueue did not wake the loop: {dt:.3f}s against a 0.25s "
            f"cycle time")
        reg = eng.registry
        assert reg.counter("horovod_cycle_wakeups_total",
                           labels={"reason": "enqueue"}).value > 0
    finally:
        eng.shutdown()


def test_fixed_sleep_baseline_keeps_the_floor(monkeypatch):
    monkeypatch.setenv("HOROVOD_CYCLE_EVENT_DRIVEN", "0")
    be = RecordingBackend()
    eng = _engine(be, cycle_s=0.2)
    try:
        t0 = time.monotonic()
        eng.synchronize(
            eng.enqueue_allreduce(np.ones(2, np.float32), name="slowpath"),
            timeout=30)
        assert time.monotonic() - t0 >= 0.15
        assert eng.registry.counter(
            "horovod_cycle_wakeups_total",
            labels={"reason": "timeout"}).value > 0
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# observability
def test_status_reports_channels_and_inflight(monkeypatch):
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    be = RecordingBackend()
    eng = _engine(be)
    try:
        eng.synchronize(
            eng.enqueue_allreduce(np.ones(2, np.float32), name="s"),
            timeout=30)
        st = eng.status()
        assert st["inflight_responses"] == 0
        assert set(st["channels"]) == {"0", "1"}
        for ch in st["channels"].values():
            assert ch["queue_depth"] == 0
            assert ch["executing"] == []
        # per-channel executor-depth gauges registered
        snap = eng.registry.snapshot()
        assert 'horovod_executor_queue_depth{channel="0"}' in snap
        assert 'horovod_executor_queue_depth{channel="1"}' in snap
        assert "horovod_inflight_responses" in snap
    finally:
        eng.shutdown()


def test_cross_rank_pipelined_correctness(monkeypatch):
    """2 ranks x 2 channels x unfused responses: a burst of concurrent
    collectives still reduces correctly (the ordering invariant holds
    end to end over the threaded transport)."""
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1")

    def fn(eng, rank):
        handles = [
            eng.enqueue_allreduce(
                np.full(256 * (1 + i % 3), float(rank + i), np.float32),
                name=f"p{i}")
            for i in range(12)
        ]
        return [eng.synchronize(h, timeout=60) for h in handles]

    out = run_ranks(2, fn)
    for i in range(12):
        want = float(0 + i) + float(1 + i)
        np.testing.assert_allclose(out[0][i], out[1][i])
        np.testing.assert_allclose(
            out[0][i], np.full(256 * (1 + i % 3), want))
