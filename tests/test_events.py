"""Events-plane tests (docs/events.md): the lifecycle ring's bounds and
drop accounting, epoch+step causal stamps, the JSONL spool's torn-tail
tolerance, the fleet fold's deterministic skew-adjusted ordering, every
subsystem emitter, the incident-report merge, the hvdtop frame, and the
<2% hot-path overhead bar against a disabled plane."""
import importlib.util
import json
import os
import statistics
import time
import types

import numpy as np
import pytest

from horovod_tpu.common import alerts, drain, goodput, telemetry
from horovod_tpu.common import events, timeseries as ts
from horovod_tpu.common import tracing
from horovod_tpu.common.exceptions import WorkerPreempted
from horovod_tpu.utils import chrome_trace, clock
from horovod_tpu.utils import env as env_cfg


@pytest.fixture(autouse=True)
def _fresh_plane(monkeypatch):
    """Every test starts with a clean singleton and no EVENTS_* env."""
    for var in (env_cfg.EVENTS_BUFFER, env_cfg.EVENTS_DIR,
                env_cfg.EVENTS_SPOOL_SECONDS):
        monkeypatch.delenv(var, raising=False)
        monkeypatch.delenv(var.replace("HOROVOD_", "HVD_TPU_", 1),
                           raising=False)
    events.set_current(None)
    events.set_epoch_provider(None)
    yield
    events.set_current(None)
    events.set_epoch_provider(None)


def _rec(**kw):
    kw.setdefault("registry", telemetry.MetricsRegistry())
    kw.setdefault("capacity", 64)
    kw.setdefault("rank", 0)
    kw.setdefault("spool_dir", "")  # ring only unless a test opts in
    return events.EventRecorder(**kw)


def _ev(seq, rank, wall, epoch=0, step=0, kind="k", sev="info",
        attrs=None):
    """A raw event tuple in the recorder's wire order."""
    return (seq, wall, wall, rank, epoch, step, sev, kind, attrs)


def _load_script(name):
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# Ring semantics


def test_ring_bounds_and_drop_counting():
    rec = _rec(capacity=8)
    for i in range(30):
        rec.record("test.tick", attrs={"i": i})
    assert rec.depth() == 8
    assert rec.dropped == 22  # exact: total 30, retained 8
    snap = rec.snapshot()
    assert [e[0] for e in snap] == list(range(22, 30))  # newest, sorted
    # Counters: every record counted; drops counted on (amortized) trim.
    assert rec._m_recorded.value == 30
    assert 0 < rec._m_dropped.value <= rec.dropped
    st = rec.status()
    assert st["enabled"] and st["capacity"] == 8
    assert st["depth"] == 8 and st["dropped"] == 22
    assert "spool" not in st


def test_tail_and_to_dict():
    rec = _rec()
    rec.record("a.one", severity=events.WARN, attrs={"x": 1})
    rec.record("a.two")
    tail = rec.tail(n=8)
    assert [d["kind"] for d in tail] == ["a.one", "a.two"]
    assert tail[0]["sev"] == "warn" and tail[0]["attrs"] == {"x": 1}
    assert "attrs" not in tail[1]  # None attrs elided from dict form
    assert tail[0]["wall_ns"] and tail[0]["mono_ns"]


def test_event_carries_epoch_and_step(monkeypatch):
    monkeypatch.setenv(env_cfg.MESH_SCOPE, "hvd_mesh_e7")
    led = goodput.GoodputLedger(registry=telemetry.MetricsRegistry(),
                                enabled=True, stamp_seconds=0.0)
    with led.step():
        pass
    goodput.set_current(led)
    try:
        rec = _rec()
        ev = rec.record("test.stamped")
        assert ev[4] == 7   # elastic topology epoch from MESH_SCOPE
        assert ev[5] == 1   # the ledger's step cursor
    finally:
        goodput.set_current(None)
    # Outside elastic mode, epoch is -1 and step falls back to 0.
    monkeypatch.delenv(env_cfg.MESH_SCOPE)
    ev = _rec().record("test.static")
    assert ev[4] == -1 and ev[5] == 0
    # A driver process has no MESH_SCOPE: the ElasticDriver installs an
    # epoch provider so its events interleave with the workers'.
    events.set_epoch_provider(lambda: 4)
    assert _rec().record("test.driver")[4] == 4
    events.set_epoch_provider(lambda: None)
    assert _rec().record("test.predriver")[4] == -1


def test_disabled_plane_is_inert(monkeypatch, tmp_path):
    rec = _rec(capacity=0, spool_dir=str(tmp_path))
    assert not rec.enabled
    assert rec.record("test.x") is None
    assert rec.depth() == 0 and rec.dropped == 0
    assert rec._spool_thread is None  # capacity 0 never arms the spool
    assert list(tmp_path.iterdir()) == []
    # And through the singleton emitter, driven by the env knob.
    monkeypatch.setenv(env_cfg.EVENTS_BUFFER, "0")
    assert events.emit("test.y", probe=1) is None
    assert events.active() is not None  # created, but inert
    assert not events.active().enabled


def test_env_knob_parsing(monkeypatch):
    assert env_cfg.events_buffer() == env_cfg.DEFAULT_EVENTS_BUFFER
    assert env_cfg.events_dir() == ""
    assert env_cfg.events_spool_seconds() == \
        env_cfg.DEFAULT_EVENTS_SPOOL_SECONDS
    # The HVD_TPU_ compatibility alias is honored.
    monkeypatch.setenv("HVD_TPU_EVENTS_BUFFER", "7")
    assert env_cfg.events_buffer() == 7
    monkeypatch.setenv(env_cfg.EVENTS_BUFFER, "12")  # canonical wins
    assert env_cfg.events_buffer() == 12
    # A typo must not silently disable the plane.
    monkeypatch.setenv(env_cfg.EVENTS_BUFFER, "bogus")
    monkeypatch.delenv("HVD_TPU_EVENTS_BUFFER")
    assert env_cfg.events_buffer() == env_cfg.DEFAULT_EVENTS_BUFFER
    monkeypatch.setenv(env_cfg.EVENTS_BUFFER, "-5")
    assert env_cfg.events_buffer() == 0
    monkeypatch.setenv(env_cfg.EVENTS_DIR, "/tmp/evj")
    assert env_cfg.events_dir() == "/tmp/evj"
    # Spool cadence: floored (no spinning writer), bogus -> default.
    monkeypatch.setenv(env_cfg.EVENTS_SPOOL_SECONDS, "0")
    assert env_cfg.events_spool_seconds() == 0.05
    monkeypatch.setenv(env_cfg.EVENTS_SPOOL_SECONDS, "nope")
    assert env_cfg.events_spool_seconds() == \
        env_cfg.DEFAULT_EVENTS_SPOOL_SECONDS


def test_batch_since_and_push_cursor():
    rec = _rec()
    for i in range(5):
        rec.record("test.t", attrs={"i": i})
    evs, nxt = rec.batch_since(0)
    assert [e[0] for e in evs] == [0, 1, 2, 3, 4] and nxt == 5
    evs, nxt = rec.batch_since(nxt)
    assert evs == [] and nxt == 5
    push = rec.make_push()
    blob = push()
    assert len(blob["batch"]) == 5
    assert "mono_anchor_ns" in blob["anchor"]
    assert push() is None  # cursor advanced: nothing new
    rec.record("test.more")
    assert len(push()["batch"]) == 1


def test_singleton_emit_and_set_rank():
    rec = _rec(rank=2)
    events.set_current(rec)
    ev = events.emit("test.a", foo=1)
    assert ev[3] == 2 and ev[8] == {"foo": 1}
    events.set_rank(5)  # elastic renumber: later events carry it
    assert events.emit("test.b")[3] == 5
    assert events.emit("test.c", rank=9)[3] == 9  # explicit wins
    assert events.active() is rec


def test_local_view_shapes():
    # No recorder installed -> disabled body (mesh-mode /events before
    # init, or a plane turned off).
    assert events.local_view() == {"local": {"enabled": False}}
    events.set_current(_rec(rank=1))
    events.emit("test.a", foo=1)
    body = events.local_view()
    assert body["local"]["enabled"] and body["local"]["depth"] == 1
    assert body["local"]["events"][0]["kind"] == "test.a"
    assert "fleet" not in body
    events.set_current(events.EventRecorder(capacity=0))
    assert events.local_view() == {"local": {"enabled": False}}


# ---------------------------------------------------------------------------
# Spool: durable JSONL journal


def test_spool_journal_anchor_and_torn_tail(tmp_path):
    rec = _rec(capacity=16, rank=3, spool_dir=str(tmp_path),
               spool_seconds=0.05)
    for i in range(4):
        rec.record("test.spooled", attrs={"i": i})
    rec.flush_spool()
    path = events.journal_path(str(tmp_path), 3)
    assert path.endswith("events_rank3.jsonl")
    assert rec.status()["spool"]["path"] == path
    docs = events.read_journal(path)
    assert [d["attrs"]["i"] for d in docs] == [0, 1, 2, 3]
    assert all(d["rank"] == 3 for d in docs)
    anchor = events.read_anchor(path)
    assert anchor["rank"] == 3 and "wall_anchor_ns" in anchor
    # A hard kill tears the tail line and can corrupt one in the
    # middle — replay must keep every complete event.
    with open(path, "a", encoding="utf-8") as f:
        f.write("not json at all\n")
        f.write('{"kind":"test.torn","seq":9')  # no closing newline
    docs = events.read_journal(path)
    assert len(docs) == 4
    rec.close_spool()
    assert rec._spool_thread is None
    # Driver processes (rank -1) get their own journal name.
    assert events.journal_path("/d", -1).endswith("events_driver.jsonl")


def test_set_current_closes_previous_spool(tmp_path):
    rec = _rec(capacity=8, rank=0, spool_dir=str(tmp_path),
               spool_seconds=0.05)
    rec.record("test.x")
    events.set_current(rec)
    events.set_current(None)  # must drain + stop the writer thread
    assert rec._spool_thread is None
    docs = events.read_journal(events.journal_path(str(tmp_path), 0))
    assert [d["kind"] for d in docs] == ["test.x"]


# ---------------------------------------------------------------------------
# Fleet fold: dedup, determinism, skew alignment


def test_fleet_fold_deterministic_across_ingest_orders():
    r0 = [_ev(i, 0, 1000 + 10 * i, kind=f"a{i}") for i in range(4)]
    r1 = [_ev(i, 1, 1005 + 10 * i, kind=f"b{i}") for i in range(4)]
    fa = events.FleetEvents(2)
    fa.ingest(0, [list(e) for e in r0])
    fa.ingest(1, [list(e) for e in r1])
    fb = events.FleetEvents(2)
    fb.ingest(1, [list(e) for e in r1[:2]])
    fb.ingest(0, [list(e) for e in r0])
    fb.ingest(1, [list(e) for e in r1[2:]])
    fb.ingest(0, [list(e) for e in r0])  # re-pushed batch: deduped
    assert fa.merged() == fb.merged()
    kinds = [d["kind"] for d in fa.merged()]
    assert kinds == ["a0", "b0", "a1", "b1", "a2", "b2", "a3", "b3"]
    snap = fa.snapshot()
    assert snap["ranks"] == [0, 1]
    assert snap["depths"] == {"0": 4, "1": 4}


def test_fleet_fold_epoch_and_step_dominate_wall():
    # A drained at (e3) sorts before the remesh that opened e4, even
    # when the remesh rank's wall clock reads EARLIER.
    f = events.FleetEvents(2)
    f.ingest(0, [list(_ev(0, 0, wall=9_000, epoch=3, step=120,
                          kind="drain.drained"))])
    f.ingest(1, [list(_ev(0, 1, wall=1_000, epoch=4, step=120,
                          kind="elastic.remesh"))])
    assert [d["kind"] for d in f.merged()] == \
        ["drain.drained", "elastic.remesh"]


def test_causal_order_interleaves_stepless_events():
    # Driver-process events carry no step cursor (step 0); they must
    # interleave at their wall position, not sort to the epoch front.
    w1 = {"epoch": 3, "step": 3, "wall_ns": 1000, "rank": 1, "seq": 0,
          "kind": "drain.drained"}
    drv = {"epoch": 3, "step": 0, "wall_ns": 1500, "rank": -1, "seq": 0,
           "kind": "host.quarantine"}
    w2 = {"epoch": 3, "step": 5, "wall_ns": 2000, "rank": 0, "seq": 1,
          "kind": "ckpt.commit"}
    assert [d["kind"] for d in events.causal_order([w2, drv, w1])] == \
        ["drain.drained", "host.quarantine", "ckpt.commit"]
    # A step-less event before any stepped one still leads its epoch.
    init = {"epoch": 3, "step": 0, "wall_ns": 10, "rank": 0, "seq": 0,
            "kind": "engine.init"}
    assert [d["kind"] for d in events.causal_order([w1, init])] == \
        ["engine.init", "drain.drained"]


def test_fleet_skew_alignment():
    SKEW = 5_000_000_000  # rank 1's wall clock runs 5s fast
    local = clock.anchor_meta()
    remote = {"mono_anchor_ns": local["mono_anchor_ns"],
              "wall_anchor_ns": local["wall_anchor_ns"] + SKEW}
    f = events.FleetEvents(2)
    base = local["wall_anchor_ns"]
    # True order: r1's notice fired 1s BEFORE r0's commit; r1's fast
    # wall stamps it 4s after.
    f.ingest(0, [list(_ev(0, 0, wall=base + 2_000_000_000,
                          kind="drain.commit_barrier"))])
    f.ingest(1, [list(_ev(0, 1, wall=base + 1_000_000_000 + SKEW,
                          kind="drain.notice"))], anchor=remote)
    # Without an RTT sample both walls are trusted: skew 0, wrong order.
    assert f.skew_ns(1) == 0
    assert [d["kind"] for d in f.merged()] == \
        ["drain.commit_barrier", "drain.notice"]
    # The health plane's mono offset unlocks the wall-anchor delta.
    f.set_offsets({1: 0})
    assert f.skew_ns(1) == SKEW
    merged = f.merged()
    assert [d["kind"] for d in merged] == \
        ["drain.notice", "drain.commit_barrier"]
    assert merged[0]["adj_wall_ns"] == base + 1_000_000_000
    assert f.snapshot()["skew_ns"]["1"] == SKEW


# ---------------------------------------------------------------------------
# Subsystem emitters (each stamps the ring through the singleton)


def _kinds(rec):
    return [e[7] for e in rec.snapshot()]


def _by_kind(rec, kind):
    return [events.to_dict(e) for e in rec.snapshot() if e[7] == kind]


def test_drain_emitters():
    rec = _rec()
    events.set_current(rec)
    coord = drain.DrainCoordinator()
    coord.set_managed(True)
    try:
        coord.request("test preemption")
        (notice,) = _by_kind(rec, events.DRAIN_NOTICE)
        assert notice["sev"] == "warn"
        assert notice["attrs"] == {"reason": "test preemption",
                                   "managed": True}
        # Survivor side: first commit-barrier observation of a peer
        # drain emits once (not per commit).
        drain._drain_commit(coord, object(), draining=False)
        drain._drain_commit(coord, object(), draining=False)
        assert len(_by_kind(rec, events.DRAIN_COMMIT)) == 2
        assert len(_by_kind(rec, events.DRAIN_PEER)) == 1
        # Draining side: the commit completes the drain.
        with pytest.raises(WorkerPreempted):
            drain._drain_commit(coord, object(), draining=True)
        (drained,) = _by_kind(rec, events.DRAIN_DRAINED)
        assert drained["attrs"]["reason"] == "test preemption"
    finally:
        coord.reset()


def test_alert_emitters():
    rec = _rec()
    events.set_current(rec)
    reg = telemetry.MetricsRegistry()
    rule = alerts.ThresholdRule("hot", "m", threshold=10.0,
                                for_seconds=15.0, clear_seconds=15.0)
    base = time.monotonic()
    st = ts.TimeSeriesStore(64)
    st.add_sample({"m": 25.0}, wall=0, mono=base)
    eng = alerts.AlertEngine(st, reg, rules=[rule], rules_spec="",
                             tracer=None, stale_after=1e9)
    eng.evaluate(st, now=base)
    st.add_sample({"m": 25.0}, wall=16, mono=base + 16)
    eng.evaluate(st, now=base + 16)  # 16s >= for_seconds -> FIRE
    (fire,) = _by_kind(rec, events.ALERT_FIRE)
    assert fire["sev"] == "warn" and fire["attrs"]["rule"] == "hot"
    st.add_sample({"m": 1.0}, wall=20, mono=base + 20)
    eng.evaluate(st, now=base + 20)
    st.add_sample({"m": 1.0}, wall=36, mono=base + 36)
    eng.evaluate(st, now=base + 36)  # 16s below -> resolve
    (clear,) = _by_kind(rec, events.ALERT_CLEAR)
    assert clear["attrs"]["rule"] == "hot"


def test_controller_decision_emitted_on_change_only():
    from horovod_tpu.runner.elastic import controller as ectl

    rec = _rec()
    events.set_current(rec)
    fake = types.SimpleNamespace(rendezvous=types.SimpleNamespace(
        handle_put=lambda key, val: None))
    ctl = ectl.ElasticityController(fake, interval=60.0)
    ctl._publish(ectl.HOLD, 2, 2, "steady state")
    ctl._publish(ectl.HOLD, 2, 2, "steady state")  # same fact: no spam
    assert len(_by_kind(rec, events.CONTROLLER_DECISION)) == 1
    ctl._publish(ectl.SCALE_UP, 4, 2, "2 slots available")
    decs = _by_kind(rec, events.CONTROLLER_DECISION)
    assert len(decs) == 2
    assert decs[0]["sev"] == "info" and decs[0]["rank"] == -1
    assert decs[1]["sev"] == "warn"
    assert decs[1]["attrs"]["action"] == ectl.SCALE_UP
    assert decs[1]["attrs"]["target_np"] == 4


def test_checkpoint_emitters(tmp_path):
    from horovod_tpu.common import checkpoint as ck
    from horovod_tpu.elastic.state import JaxState

    rec = _rec()
    events.set_current(rec)
    st = JaxState(params={"w": np.arange(6, dtype=np.float32)}, batch=1)
    st.save()
    m = ck.CheckpointManager(str(tmp_path), rank=0, size=1,
                             interval_steps=1, commit_timeout=30)
    try:
        assert m.save(st, step=3, blocking=True)
    finally:
        m.stop()
    (commit,) = _by_kind(rec, events.CKPT_COMMIT)
    assert commit["attrs"] == {"ckpt_step": 3, "shards": 1}
    st2 = JaxState(params={"w": np.zeros(6, np.float32)}, batch=0)
    m2 = ck.CheckpointManager(str(tmp_path), rank=0, size=1)
    try:
        assert m2.restore_latest(st2) == 3
    finally:
        m2.stop()
    (restore,) = _by_kind(rec, events.CKPT_RESTORE)
    assert restore["attrs"]["ckpt_step"] == 3
    assert restore["attrs"]["written_world"] == 1


def test_replay_emitter():
    rec = _rec()
    events.set_current(rec)
    led = goodput.GoodputLedger(registry=telemetry.MetricsRegistry(),
                                enabled=True, stamp_seconds=0.0, rank=2)
    for _ in range(2):
        with led.step():
            pass
    led.note_restore()  # rollback to committed (0): both steps lost
    (replay,) = _by_kind(rec, events.CKPT_REPLAY)
    assert replay["sev"] == "warn" and replay["rank"] == 2
    assert replay["attrs"]["lost_steps"] == 2
    assert replay["attrs"]["restored_step"] == 0
    led.note_restore()  # nothing newly lost: no second event
    assert len(_by_kind(rec, events.CKPT_REPLAY)) == 1


def test_serving_swap_emitter(monkeypatch):
    from horovod_tpu.serving import replicas

    rec = _rec()
    events.set_current(rec)
    monkeypatch.setattr(replicas.basics, "rank", lambda: 1)
    rs = replicas.ReplicaSet.__new__(replicas.ReplicaSet)
    rs.weight_step = -1
    rs.loader = types.SimpleNamespace(take=lambda step: {"w": 2})
    rs._m_weight_step = types.SimpleNamespace(set=lambda v: None)
    rs._m_swaps = types.SimpleNamespace(inc=lambda: None)
    rs._commit(5)
    rs._commit(5)  # replayed commit: no swap, no event
    (swap,) = _by_kind(rec, events.SERVING_SWAP)
    assert swap["rank"] == 1 and swap["attrs"]["ckpt_step"] == 5
    assert rs.weight_step == 5


# ---------------------------------------------------------------------------
# Trace integration: lifecycle instants + stitched skew


def test_chrome_instant_helpers():
    d = chrome_trace.instant("drain.notice", 12.5, pid=3,
                             cat="lifecycle", args={"reason": "x"})
    assert d["ph"] == "i" and d["s"] == "p" and d["pid"] == 3
    doc = {"traceEvents": [d, {"ph": "X", "name": "span"}]}
    assert chrome_trace.instant_events(doc) == [d]


def test_stitch_post_mortem_lifecycle_instants_and_skew(tmp_path):
    SKEW = 2_000_000_000
    anchor0 = {"mono_anchor_ns": 1_000, "wall_anchor_ns": 500_000}
    anchor1 = {"mono_anchor_ns": 1_000,
               "wall_anchor_ns": 500_000 + SKEW}

    def _life(rank, mono, kind):
        return {"seq": 0, "wall_ns": mono, "mono_ns": mono,
                "rank": rank, "epoch": 1, "step": 4, "sev": "warn",
                "kind": kind}

    for r, anchor, kind in ((0, anchor0, "drain.commit_barrier"),
                            (1, anchor1, "drain.notice")):
        with open(tracing.flight_path(str(tmp_path), r), "w") as f:
            json.dump({"rank": r, "events": [], "anchor": anchor,
                       "reason": "test",
                       "lifecycle": [_life(r, 5_000 + r, kind)]}, f)
    out = tracing.stitch_post_mortem(str(tmp_path), verdict="drill",
                                     expect_ranks=2, grace_s=0.5,
                                     offsets={0: 0, 1: SKEW})
    with open(out) as f:
        doc = json.load(f)
    pm = doc["horovod_postmortem"]
    assert pm["per_rank"]["1"]["skew_ns"] == SKEW
    assert pm["per_rank"]["0"]["lifecycle_events"] == 1
    inst = {d["name"]: d for d in chrome_trace.instant_events(doc)}
    assert inst["drain.notice"]["pid"] == 1
    assert inst["drain.notice"]["cat"] == "lifecycle"
    assert inst["drain.notice"]["args"]["kind"] == "drain.notice"
    # Rank 1's lane is shifted onto the coordinator timebase.
    base = anchor0["mono_anchor_ns"]
    assert inst["drain.commit_barrier"]["ts"] == (5_000 - base) / 1e3
    assert inst["drain.notice"]["ts"] == (5_001 - SKEW - base) / 1e3


# ---------------------------------------------------------------------------
# scripts/incident_report.py: the merged chronicle


def test_incident_report_merges_journals_with_skew(tmp_path):
    ir = _load_script("incident_report")
    SKEW = 5_000_000_000
    base = 1_000_000_000_000

    def _row(seq, rank, wall, kind, sev="warn", **attrs):
        return {"seq": seq, "wall_ns": wall, "mono_ns": wall,
                "rank": rank, "epoch": 3, "step": 0, "sev": sev,
                "kind": kind, "attrs": attrs or None}

    # Rank 1 (the preempted one) has a wall clock 5s fast; true order:
    # notice(r1) -> commit(r0) -> drained(r1) -> remesh(driver).
    r1 = [_row(0, 1, base + 1_000_000_000 + SKEW, "drain.notice"),
          _row(1, 1, base + 3_000_000_000 + SKEW, "drain.drained")]
    r0 = [_row(0, 0, base + 2_000_000_000, "drain.commit_barrier")]
    drv = [_row(0, -1, base + 4_000_000_000, "elastic.remesh")]
    with open(os.path.join(tmp_path, "events_rank0.jsonl"), "w") as f:
        f.writelines(json.dumps(d) + "\n" for d in r0)
        f.write('{"kind":"torn')  # hard-kill tail: ignored
    with open(os.path.join(tmp_path, "events_rank1.jsonl"), "w") as f:
        f.writelines(json.dumps(d) + "\n" for d in r1)
    with open(os.path.join(tmp_path, "events_driver.jsonl"), "w") as f:
        f.writelines(json.dumps(d) + "\n" for d in drv)
    # A flight dump re-carries r1's first event (deduped) + one unique.
    with open(os.path.join(tmp_path, "flight_rank1.json"), "w") as f:
        json.dump({"rank": 1, "lifecycle": [
            r1[0],
            _row(2, 1, base + 3_500_000_000 + SKEW, "host.quarantine"),
        ]}, f)
    with open(os.path.join(tmp_path, "postmortem.json"), "w") as f:
        json.dump({"horovod_postmortem": {
            "verdict": "rank 1 preempted",
            "per_rank": {"1": {"skew_ns": SKEW}},
        }}, f)

    report = ir.build_report([str(tmp_path)])
    s = report["summary"]
    assert s["events"] == 5
    assert s["ranks"] == [-1, 0, 1]
    assert s["skew_ns"] == {"1": str(SKEW)} or \
        s["skew_ns"] == {"1": SKEW}
    assert s["verdict"] == "rank 1 preempted"
    kinds = [d["kind"] for d in report["events"]]
    # With the skew applied the chronicle reads as one narrative; the
    # raw walls would have sorted every r1 event last.
    assert kinds == ["drain.notice", "drain.commit_barrier",
                     "drain.drained", "host.quarantine",
                     "elastic.remesh"]
    text = ir.render_text(report)
    assert "drain.notice" in text and "rank 1 preempted" in text
    assert "clock skew applied" in text
    # Empty directory: no events, exit code 1.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert ir.main([str(empty)]) == 1


# ---------------------------------------------------------------------------
# scripts/hvdtop.py: one rendered frame


def test_hvdtop_render_frame():
    top = _load_script("hvdtop")
    now = 1_700_000_000.0
    snap = {
        "wall": now,
        "status": {
            "size": 2,
            "goodput": {"steps": 120},
            "checkpoint": {"last_committed_step": 100},
        },
        "goodput": {"fleet": {
            "ranks": {
                "0": {"steps": 120, "goodput_ratio": 0.91,
                      "exposed_comm_seconds": 1.0},
                "1": {"steps": 118, "goodput_ratio": 0.62,
                      "exposed_comm_seconds": 9.5},
            },
            "max_exposed_comm_rank": 1,
        }},
        "alerts": {"fleet": {"firing_by_rule": {"stall": [1]}}},
        "events": {"fleet": {"events": [
            {"epoch": 3, "step": 100, "rank": 1, "sev": "warn",
             "kind": "drain.notice", "attrs": {"reason": "signal"}},
            {"epoch": 3, "step": 100, "rank": 1, "sev": "warn",
             "kind": "drain.drained"},
        ]}},
        "controller": {"wall": now - 30, "action": "scale_down",
                       "current_np": 2, "target_np": 1,
                       "reason": "grant shrank"},
        "grant": 1,
        "drain": {"phase": "requested", "wall": now - 5},
        "kv_epoch": 3,
    }
    frame = top.render(snap)
    assert "world 2" in frame and "epoch 3" in frame
    assert "last commit 100" in frame
    assert "<- max exposed" in frame
    assert "stall (ranks [1])" in frame
    assert "scale_down" in frame and "grant shrank" in frame
    assert "capacity grant: 1 slots" in frame
    assert "DRAIN in flight: phase requested" in frame
    assert "drain.notice" in frame and "reason=signal" in frame
    # Everything down: degrades, never crashes.
    dead = top.render({"wall": now, "status": None, "goodput": None,
                       "alerts": None, "events": None,
                       "controller": None, "grant": None, "drain": None,
                       "kv_epoch": None})
    assert "unreachable" in dead
    assert "no decision published" in dead
    assert "disabled or empty" in dead


# ---------------------------------------------------------------------------
# Overhead: recording must cost <2% vs a disabled plane


def test_emit_overhead_under_two_percent():
    # ~16 ms of real work per "step" — a lifecycle emit (~10 us) must
    # be invisible against even a small training step, let alone a real
    # one. The step must dwarf scheduler jitter too: at ~2 ms of work
    # the matmul's own round-to-round variance alone breaches 2%.
    a = np.ones((1024, 1024), np.float32)
    on = _rec(capacity=4096)
    off = _rec(capacity=0)
    steps = 20

    def _round(rec):
        events.set_current(rec)
        t0 = time.perf_counter()
        for i in range(steps):
            c = a @ a
            events.emit("perf.step", i=i)
        dt = time.perf_counter() - t0
        assert c is not None
        return dt

    # Order-alternated paired rounds, median ratio — the house idiom
    # (scripts/checkpoint_smoke.py run_overhead) that survives noisy CI.
    ratios = []
    for r in range(5):
        if r % 2 == 0:
            t_on, t_off = _round(on), _round(off)
        else:
            t_off, t_on = _round(off), _round(on)
        ratios.append(t_on / t_off - 1.0)
    overhead_pct = statistics.median(ratios) * 100.0
    assert on.depth() > 0 and off.depth() == 0
    assert overhead_pct < 2.0, f"events overhead {overhead_pct:.2f}%"
