"""horovod_tpu.torch adapter tests (ref test model: test/test_torch.py —
op coverage + DistributedOptimizer/broadcast-state under 2 real ranks;
processes launched through the func-mode runner).

Tiering: like test_tf_adapter.py, each 2-rank case costs ~20-30s of
subprocess spin-up, so the deep-coverage cases are marked `slow` and
tier-1 keeps a smoke subset (test_allreduce_and_inplace,
test_async_handle_api_single_process). `pytest -m slow` runs the
rest."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from horovod_tpu.runner import run


ENV = {"HOROVOD_CYCLE_TIME": "1", "JAX_PLATFORMS": "cpu"}


def _two(fn):
    return run(fn, np=2, extra_env=ENV)


def test_allreduce_and_inplace():
    def fn():
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        t = torch.ones(4) * (hvd.rank() + 1)
        out = hvd.allreduce(t, average=False)
        assert out.tolist() == [3.0] * 4
        assert t.tolist() == [float(hvd.rank() + 1)] * 4  # out-of-place
        hvd.allreduce_(t)  # average in place
        assert t.tolist() == [1.5] * 4
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_allgather_broadcast_alltoall():
    def fn():
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        r = hvd.rank()
        g = hvd.allgather(torch.full((r + 1, 2), float(r)))
        assert g.shape == (3, 2)
        b = hvd.broadcast(torch.arange(3.0) * (r + 1), root_rank=1)
        assert b.tolist() == [0.0, 2.0, 4.0]
        t = torch.arange(4.0) + 10 * r
        out, splits = hvd.alltoall(t, splits=[1, 3])
        if r == 0:
            assert out.tolist() == [0.0, 10.0] and splits.tolist() == [1, 1]
        else:
            assert out.tolist() == [1.0, 2.0, 3.0, 11.0, 12.0, 13.0]
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_distributed_optimizer_converges_and_syncs():
    def fn():
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        torch.manual_seed(42)  # same init on both ranks
        model = torch.nn.Linear(4, 1)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        opt = hvd.DistributedOptimizer(
            opt, named_parameters=model.named_parameters()
        )
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)

        # Rank-dependent data; identical updates require grad averaging.
        torch.manual_seed(hvd.rank())
        X = torch.randn(16, 4)
        W = torch.tensor([[1.0], [2.0], [-1.0], [0.5]])
        Y = X @ W
        losses = []
        for _ in range(30):
            opt.zero_grad()
            loss = torch.nn.functional.mse_loss(model(X), Y)
            loss.backward()
            opt.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.2, losses
        # Params must be identical across ranks after averaged updates.
        return [p.detach().numpy().tolist() for p in model.parameters()]

    out = _two(fn)
    assert out[0] == out[1]


@pytest.mark.slow
def test_broadcast_optimizer_state():
    def fn():
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        torch.manual_seed(hvd.rank())  # deliberately different
        model = torch.nn.Linear(3, 1)
        opt = torch.optim.Adam(model.parameters(), lr=0.01)
        # One local step so Adam state (exp_avg etc.) exists.
        loss = model(torch.randn(4, 3)).sum()
        loss.backward()
        opt.step()
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        hvd.broadcast_optimizer_state(opt, root_rank=0)
        sd = opt.state_dict()["state"]
        return [
            sd[k]["exp_avg"].numpy().tolist() for k in sorted(sd)
        ]

    out = _two(fn)
    assert out[0] == out[1]


@pytest.mark.slow
def test_backward_passes_per_step_accumulates():
    def fn():
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        torch.manual_seed(0)
        model = torch.nn.Linear(2, 1, bias=False)
        base = torch.optim.SGD(model.parameters(), lr=1.0)
        opt = hvd.DistributedOptimizer(
            base, named_parameters=model.named_parameters(),
            backward_passes_per_step=2,
        )
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        w0 = next(model.parameters()).detach().clone()
        x = torch.ones(1, 2)
        for i in range(2):
            opt.zero_grad()
            (model(x).sum()).backward()
            opt.step()
        w1 = next(model.parameters()).detach()
        # Two accumulated passes, applied once: delta = lr * 2 * grad.
        delta = (w0 - w1).abs().sum()
        assert abs(float(delta) - 4.0) < 1e-5, float(delta)
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_gradient_predivide_factor_splits_average():
    """The reference's `gradient_predivide_factor` kwarg works unchanged:
    the averaging splits into 1/f before the sum and f/size after it,
    Average-only (ref: horovod/torch/optimizer.py:428-435 guards,
    :100-111 split; the engine adds the 1/size when lowering AVERAGE)."""

    def fn():
        import torch

        import horovod_tpu.torch as hvd
        from horovod_tpu.common.types import ReduceOp

        hvd.init()
        f = 4.0
        torch.manual_seed(7)
        model = torch.nn.Linear(2, 1, bias=False)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.5),
            named_parameters=model.named_parameters(),
            gradient_predivide_factor=f,
        )
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)

        # Spy on the launch to assert the split factors reach the wire.
        seen = {}
        real = hvd.allreduce_async

        def spy(tensor, name=None, op=None, prescale_factor=1.0,
                postscale_factor=1.0):
            seen["op"] = op
            seen["pre"] = prescale_factor
            seen["post"] = postscale_factor
            return real(tensor, name=name, op=op,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor)

        hvd.allreduce_async = spy
        try:
            w0 = next(model.parameters()).detach().clone()
            # Dyadic values: every intermediate is exact in fp32, so the
            # split must land on the plain average bit-for-bit.
            x = torch.tensor([[2.0 ** (hvd.rank() + 1), 4.0]])
            opt.zero_grad()
            model(x).sum().backward()
            opt.step()
        finally:
            hvd.allreduce_async = real
        assert seen["op"] == ReduceOp.AVERAGE
        assert seen["pre"] == 1.0 / f and seen["post"] == f, seen
        # Net update equals lr * mean-grad: grad_r = x_r, mean = [3, 4].
        w1 = next(model.parameters()).detach()
        got = (w0 - w1).flatten().tolist()
        assert got == [0.5 * 3.0, 0.5 * 4.0], got

        # Reference guard: Average-only.
        try:
            hvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.5),
                op=ReduceOp.SUM, gradient_predivide_factor=2.0,
            )
        except ValueError as e:
            assert "op != Average" in str(e)
        else:
            raise AssertionError("expected ValueError for op != Average")
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_join_and_compression():
    def fn():
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        steps = 2 if hvd.rank() == 0 else 4
        for i in range(steps):
            hvd.allreduce(torch.ones(2), name=f"g{i % 2}")
        hvd.join()
        # fp16 compression roundtrip through the optimizer path.
        t = torch.ones(8) * (hvd.rank() + 1)
        c, ctx = hvd.Compression.fp16.compress(t)
        assert c.dtype == torch.float16
        out = hvd.allreduce(c, average=False)
        out = hvd.Compression.fp16.decompress(out, ctx)
        assert out.dtype == torch.float32 and out[0] == 3.0
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_optimizer_is_real_torch_optimizer_and_scheduler_works():
    def fn():
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        torch.manual_seed(0)
        model = torch.nn.Linear(4, 1)
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
        )
        # Real subclass: isinstance + lr_scheduler compatibility
        # (ref: optimizer.py:337-356 dynamic subclass).
        assert isinstance(opt, torch.optim.Optimizer)
        sched = torch.optim.lr_scheduler.StepLR(opt, step_size=1, gamma=0.5)
        X = torch.randn(8, 4)
        for i in range(3):
            opt.zero_grad()
            loss = model(X).pow(2).mean()
            loss.backward()
            opt.step()
            sched.step()
        assert abs(opt.param_groups[0]["lr"] - 0.1 * 0.5 ** 3) < 1e-9
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_torch_state_and_sync_batch_norm():
    def fn():
        import numpy as np
        import torch

        import horovod_tpu.torch as hvd
        from horovod_tpu.torch.elastic import TorchState

        hvd.init()
        r = hvd.rank()
        torch.manual_seed(100 + r)  # divergent init
        model = torch.nn.Linear(3, 1)
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        state = TorchState(model=model, optimizer=opt, epoch=5 * (r + 1))
        state.sync()
        assert state.epoch == 5
        g = hvd.allgather(model.weight.detach().reshape(1, -1))
        assert torch.allclose(g[0], g[1])

        # restore rolls back
        with torch.no_grad():
            model.weight.zero_()
        state.restore()
        assert not torch.allclose(
            model.weight.detach().reshape(-1), torch.zeros(3)
        )

        # SyncBatchNorm: global moments across rank-dependent batches
        sbn = hvd.SyncBatchNorm(2)
        x = torch.arange(8.0).reshape(2, 2, 2) + 10 * r
        out = sbn(x)
        # Per-channel global mean over both ranks' batches
        allx = torch.cat([torch.arange(8.0).reshape(2, 2, 2) + 10 * i
                          for i in range(hvd.size())])
        mu = allx.mean(dim=[0, 2])
        torch.testing.assert_close(
            sbn.running_mean, mu * sbn.momentum, atol=1e-4, rtol=1e-4
        )
        assert out.shape == x.shape

        # Backward flows through the global statistics: with a constant
        # per-channel cotangent, BN input-grads sum to ~0 per channel
        # (the -dmu/dx term must survive; ref: sync_batch_norm.py
        # backward).
        xg = (torch.arange(8.0).reshape(2, 2, 2) + 10 * r).requires_grad_()
        out2 = sbn(xg)
        out2.sum().backward()
        per_channel = xg.grad.sum(dim=[0, 2])
        assert torch.allclose(per_channel, torch.zeros(2), atol=1e-3), (
            per_channel
        )
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_adasum_delta_optimizer_matches_sequential_oracle():
    """DistributedOptimizer(op=Adasum) must be the delta-model optimizer:
    apply the LOCAL step, then Adasum-combine the weight deltas — not an
    Adasum allreduce of gradients (ref: torch/optimizer.py:210-321,
    dispatch :437-445). Oracle: local-step-then-VHDD on the same
    weights, via adasum_numpy."""
    def fn():
        import copy

        import numpy as np
        import torch

        import horovod_tpu.torch as hvd
        from horovod_tpu.ops.adasum import adasum_numpy

        hvd.init()
        torch.manual_seed(0)
        model = torch.nn.Linear(4, 2)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        start = copy.deepcopy(model)      # pre-step weights
        ref = copy.deepcopy(model)        # local-step oracle model

        opt = hvd.DistributedOptimizer(
            torch.optim.Adam(model.parameters(), lr=0.05),
            named_parameters=model.named_parameters(), op=hvd.Adasum,
        )
        # Delta optimizer contract: synchronize() is a no-op and
        # skip_synchronize() is an error (ref: optimizer.py:341-346).
        opt.synchronize()
        try:
            with opt.skip_synchronize():
                pass
            raised = False
        except AssertionError:
            raised = True
        assert raised, "skip_synchronize must be an error under Adasum"

        torch.manual_seed(hvd.rank() + 1)  # rank-dependent data
        X = torch.randn(8, 4)
        Y = torch.randn(8, 2)

        opt.zero_grad()
        torch.nn.functional.mse_loss(model(X), Y).backward()
        opt.step()

        # Oracle: plain local Adam step with the identical data, then
        # Adasum-combine the per-rank deltas (via allgather).
        ref_opt = torch.optim.Adam(ref.parameters(), lr=0.05)
        torch.nn.functional.mse_loss(ref(X), Y).backward()
        ref_opt.step()
        for (name, p), rp, sp in zip(
            model.named_parameters(), ref.parameters(), start.parameters()
        ):
            local_delta = (rp.data - sp.data).reshape(1, -1)
            g = hvd.allgather(local_delta)  # (world, n)
            combined = adasum_numpy(
                [g[i].numpy() for i in range(hvd.size())]
            )[0]
            expected = sp.data.numpy().reshape(-1) + combined
            np.testing.assert_allclose(
                p.data.numpy().reshape(-1), expected, rtol=1e-5,
                atol=1e-6, err_msg=name,
            )
        return [p.detach().numpy().tolist() for p in model.parameters()]

    out = _two(fn)
    assert out[0] == out[1]  # Adasum leaves every rank with identical weights


@pytest.mark.slow
def test_adasum_delta_trajectory_differs_from_grad_adasum():
    """Delta-Adasum and gradient-Adasum are different algorithms when
    the local optimizer is nonlinear (Adam): adasum(f(g)) != f(adasum(g))
    (ref dispatch: torch/optimizer.py:437-445). With plain SGD they
    coincide (VHDD is degree-1 homogeneous), so Adam is the probe."""
    def fn():
        import copy

        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        torch.manual_seed(3)
        model_a = torch.nn.Linear(4, 2)
        hvd.broadcast_parameters(model_a.state_dict(), root_rank=0)
        model_b = copy.deepcopy(model_a)

        opt_a = hvd.DistributedOptimizer(
            torch.optim.Adam(model_a.parameters(), lr=0.05),
            named_parameters=model_a.named_parameters(), op=hvd.Adasum,
        )
        opt_b = torch.optim.Adam(model_b.parameters(), lr=0.05)

        torch.manual_seed(10 * (hvd.rank() + 1))
        X = torch.randn(16, 4)
        Y = torch.randn(16, 2)
        for _ in range(5):
            opt_a.zero_grad()
            torch.nn.functional.mse_loss(model_a(X), Y).backward()
            opt_a.step()

            # Gradient-Adasum: combine grads, then local step.
            opt_b.zero_grad()
            torch.nn.functional.mse_loss(model_b(X), Y).backward()
            for p in model_b.parameters():
                p.grad.data.copy_(
                    hvd.allreduce(p.grad, op=hvd.Adasum)
                )
            opt_b.step()

        diff = sum(
            float((pa.data - pb.data).abs().sum())
            for pa, pb in zip(model_a.parameters(), model_b.parameters())
        )
        assert diff > 1e-4, (
            f"delta-Adasum trajectory unexpectedly equals grad-Adasum "
            f"(diff={diff})"
        )
        # Both must still be rank-consistent.
        for m in (model_a, model_b):
            for p in m.parameters():
                g = hvd.allgather(p.data.reshape(1, -1))
                assert torch.allclose(g[0], g[1], atol=1e-6)
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_adasum_delta_with_compression_and_accumulation():
    """fp16 compression compresses the DELTA before the Adasum combine
    (ref: optimizer.py:314), and backward_passes_per_step accumulates
    grads locally between boundaries."""
    def fn():
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        torch.manual_seed(1)
        model = torch.nn.Linear(3, 1)
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        opt = hvd.DistributedOptimizer(
            torch.optim.Adam(model.parameters(), lr=0.01),
            named_parameters=model.named_parameters(), op=hvd.Adasum,
            compression=hvd.Compression.fp16,
            backward_passes_per_step=2,
        )
        torch.manual_seed(hvd.rank())
        X = torch.randn(8, 3)
        Y = torch.randn(8, 1)
        w0 = [p.detach().clone() for p in model.parameters()]
        for i in range(4):
            opt.zero_grad()
            torch.nn.functional.mse_loss(model(X), Y).backward()
            opt.step()
        moved = sum(
            float((p.data - w).abs().sum())
            for p, w in zip(model.parameters(), w0)
        )
        assert moved > 1e-4
        for p in model.parameters():
            assert torch.isfinite(p.data).all()
            g = hvd.allgather(p.data.reshape(1, -1))
            assert torch.allclose(g[0], g[1], atol=1e-3)
        return True

    assert _two(fn) == [True, True]


def test_async_handle_api_single_process(hvd_single):
    """The async handle API must work without hvdrun at size 1, like the
    reference's size-1 MPI world (ref: torch/mpi_ops.py handles) — the
    DistributedOptimizer's grad hooks use it unconditionally."""
    import torch

    import horovod_tpu.torch as hvd

    assert hvd.size() == 1
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    h = hvd.allreduce_async(t, name="a")
    assert hvd.poll(h)
    out = hvd.synchronize(h)
    torch.testing.assert_close(out, t)

    t2 = t.clone()
    hvd.synchronize(hvd.allreduce_async_(t2, name="b"))
    torch.testing.assert_close(t2, t)

    g = hvd.synchronize(hvd.allgather_async(t, name="c"))
    torch.testing.assert_close(g, t)
    b = hvd.synchronize(hvd.broadcast_async(t, root_rank=0, name="d"))
    torch.testing.assert_close(b, t)

    # A model step through DistributedOptimizer end to end.
    m = torch.nn.Linear(3, 2)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(m.parameters(), lr=0.1),
        named_parameters=m.named_parameters())
    loss = m(t).sum()
    loss.backward()
    opt.step()
