"""Telemetry subsystem tests: registry semantics (concurrent increments,
log2 histogram bucketing, snapshot/reset), Prometheus/JSON exposition,
the live HTTP endpoint, and 2-worker cross-rank aggregation over the
threaded backend (docs/metrics.md)."""
import http.client
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common import metrics_export, telemetry

sys.path.insert(0, os.path.dirname(__file__))


# ---------------------------------------------------------------------------
# Registry


def test_counter_concurrent_increments():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("c_total")
    n_threads, per_thread = 8, 5000

    def worker():
        for _ in range(per_thread):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * per_thread


def test_counter_weighted_and_registry_identity():
    reg = telemetry.MetricsRegistry()
    a = reg.counter("bytes_total", "help text")
    b = reg.counter("bytes_total")
    assert a is b  # get-or-create returns the same object
    a.inc(10)
    b.inc(32)
    assert a.value == 42
    with pytest.raises(TypeError):
        reg.gauge("bytes_total")  # kind mismatch must be loud


def test_labels_distinguish_series():
    reg = telemetry.MetricsRegistry()
    x = reg.counter("op_total", labels={"op": "allreduce"})
    y = reg.counter("op_total", labels={"op": "allgather"})
    assert x is not y
    x.inc(3)
    y.inc(4)
    snap = reg.snapshot()
    assert snap['op_total{op="allreduce"}'] == 3
    assert snap['op_total{op="allgather"}'] == 4


def test_gauge_set_and_function():
    reg = telemetry.MetricsRegistry()
    g = reg.gauge("depth")
    g.set(5)
    assert g.value == 5
    g.inc(2)
    assert g.value == 7
    pulled = reg.gauge("pulled")
    pulled.set_function(lambda: 13)
    assert pulled.value == 13
    assert reg.snapshot()["pulled"] == 13


def test_histogram_log2_bucketing():
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("lat", min_exp=-3, max_exp=3)
    # bounds: 0.125, 0.25, 0.5, 1, 2, 4, 8 (+Inf overflow)
    assert h.bounds == [0.125, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0]
    h.observe(0.01)    # underflow -> first bucket (le 0.125)
    h.observe(0.125)   # exactly a bound -> that bucket
    h.observe(0.3)     # (0.25, 0.5]
    h.observe(1.0)     # exactly 1 -> le 1 bucket
    h.observe(1.5)     # (1, 2]
    h.observe(100.0)   # overflow -> +Inf
    snap = h.snapshot()
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(0.01 + 0.125 + 0.3 + 1.0 + 1.5 + 100.0)
    assert snap["counts"] == [2, 0, 1, 1, 1, 0, 0, 1]


def test_histogram_concurrent_observes():
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("lat")

    def worker():
        for _ in range(2000):
            h.observe(0.01)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == 8000
    assert sum(h.snapshot()["counts"]) == 8000


def test_snapshot_and_reset():
    reg = telemetry.MetricsRegistry()
    reg.counter("a_total").inc(3)
    reg.gauge("b").set(4)
    reg.histogram("c").observe(1.0)
    snap = reg.snapshot()
    assert snap["a_total"] == 3 and snap["b"] == 4
    assert snap["c"]["count"] == 1
    reg.reset()
    snap = reg.snapshot()
    assert snap["a_total"] == 0 and snap["b"] == 0
    assert snap["c"]["count"] == 0 and snap["c"]["sum"] == 0


def test_scalars_flattens_histograms():
    reg = telemetry.MetricsRegistry()
    reg.histogram("h").observe(2.0)
    reg.counter("c_total").inc()
    s = reg.scalars()
    assert s["h_count"] == 1
    assert s["h_sum"] == pytest.approx(2.0)
    assert s["c_total"] == 1


# ---------------------------------------------------------------------------
# Exposition formats


def _sample_registry():
    reg = telemetry.MetricsRegistry()
    reg.counter("horovod_allreduce_bytes_total", "bytes moved").inc(4096)
    reg.gauge("horovod_tensor_queue_depth", "pending").set(2)
    h = reg.histogram("horovod_cycle_seconds", "cycle", min_exp=-3, max_exp=1)
    h.observe(0.2)
    h.observe(0.7)
    h.observe(50.0)
    reg.counter("horovod_op_latency_total",
                labels={"op": "RING_ALLREDUCE"}).inc(5)
    return reg


def test_prometheus_exposition_format():
    text = metrics_export.to_prometheus(_sample_registry())
    lines = text.strip().splitlines()
    assert "# TYPE horovod_allreduce_bytes_total counter" in lines
    assert "horovod_allreduce_bytes_total 4096" in lines
    assert "# TYPE horovod_tensor_queue_depth gauge" in lines
    assert "horovod_tensor_queue_depth 2" in lines
    assert "# TYPE horovod_cycle_seconds histogram" in lines
    assert 'horovod_op_latency_total{op="RING_ALLREDUCE"} 5' in lines
    # Histogram buckets: cumulative, ending at +Inf == count.
    buckets = [l for l in lines if l.startswith("horovod_cycle_seconds_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert buckets[-1].startswith('horovod_cycle_seconds_bucket{le="+Inf"}')
    assert counts[-1] == 3
    assert "horovod_cycle_seconds_count 3" in lines
    # le="1" bucket holds the two sub-second observations
    le1 = [l for l in buckets if 'le="1.0"' in l]
    assert le1 and int(le1[0].rsplit(" ", 1)[1]) == 2


def test_json_export_roundtrip():
    doc = json.loads(metrics_export.to_json(_sample_registry()))
    m = doc["metrics"]
    assert m["horovod_allreduce_bytes_total"] == 4096
    assert m["horovod_cycle_seconds"]["count"] == 3
    assert "time" in doc


def test_metrics_file_writer(tmp_path):
    reg = _sample_registry()
    path = tmp_path / "metrics-{rank}.json"
    w = metrics_export.MetricsFileWriter(str(path), reg, interval=0.05, rank=3)
    w.start()
    target = tmp_path / "metrics-3.json"
    deadline = time.monotonic() + 10
    while not target.exists() and time.monotonic() < deadline:
        time.sleep(0.02)
    w.stop()
    doc = json.loads(target.read_text())
    assert doc["rank"] == 3
    assert doc["metrics"]["horovod_allreduce_bytes_total"] == 4096


def test_http_endpoints():
    reg = _sample_registry()
    status = {"rank": 0, "size": 2, "queue_depth": 1,
              "pending_tensors": ["allreduce.t"]}
    srv = metrics_export.MetricsHTTPServer(
        0, registry=reg, status_fn=lambda: status).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        assert "horovod_allreduce_bytes_total 4096" in body
        conn.request("GET", "/status")
        st = json.loads(conn.getresponse().read())
        assert st == status
        conn.request("GET", "/metrics.json")
        mj = json.loads(conn.getresponse().read())
        assert mj["metrics"]["horovod_tensor_queue_depth"] == 2
        conn.request("GET", "/bogus")
        assert conn.getresponse().read() and True
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Fleet aggregation primitives


def test_fleet_view_min_max_sum_tags_ranks():
    fleet = telemetry.FleetView(3)
    for r, v in enumerate([10.0, 50.0, 30.0]):
        fleet.ingest(json.dumps(
            {"rank": r, "time": time.time(),
             "metrics": {"horovod_allreduce_bytes_total": v}}).encode())
    snap = fleet.snapshot()
    agg = snap["aggregate"]["horovod_allreduce_bytes_total"]
    assert agg["min"] == 10.0 and agg["min_rank"] == 0
    assert agg["max"] == 50.0 and agg["max_rank"] == 1
    assert agg["sum"] == 90.0 and agg["count"] == 3
    assert sorted(snap["ranks"]) == [0, 1, 2]


def test_fleet_view_ignores_garbage():
    fleet = telemetry.FleetView(2)
    fleet.ingest(b"\xff\xfenot json")
    fleet.ingest(b"{}")  # no rank
    assert fleet.snapshot()["ranks"] == {}


# ---------------------------------------------------------------------------
# 2-worker cross-rank aggregation + exact byte accounting


def test_two_worker_aggregation_and_byte_accounting(monkeypatch):
    from test_engine import run_ranks

    # Push telemetry on (almost) every gather so the short run refreshes
    # the fleet view after bytes have been counted.
    monkeypatch.setenv("HOROVOD_METRICS_SYNC_SECONDS", "0.001")

    from horovod_tpu.backend.threaded import ThreadedGroup
    from horovod_tpu.engine.engine import Engine

    group = ThreadedGroup(2)
    regs = [telemetry.MetricsRegistry() for _ in range(2)]
    engines = [
        Engine(rank=r, size=2, backend=group.backend(r), registry=regs[r])
        for r in range(2)
    ]
    for e in engines:
        e.cycle_time_s = 0.001
        e.start()
    iters, elems = 4, 8
    expected_bytes = iters * elems * 4  # float32

    def work(r):
        out = []
        for i in range(iters):
            h = engines[r].enqueue_allreduce(
                np.full(elems, float(r + 1), np.float32), name=f"t{i}")
            out.append(engines[r].synchronize(h, timeout=30))
        return out

    errors = [None, None]
    results = [None, None]

    def runner(r):
        try:
            results[r] = work(r)
        except BaseException as ex:  # noqa: BLE001
            errors[r] = ex

    threads = [threading.Thread(target=runner, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        for err in errors:
            if err is not None:
                raise err
        for i in range(iters):
            np.testing.assert_allclose(results[0][i], np.full(elems, 3.0))
        # Per-rank registries: allreduce_bytes_total counts exactly the
        # input payload this rank contributed to reduced responses.
        for r in range(2):
            snap = regs[r].snapshot()
            assert snap["horovod_allreduce_bytes_total"] == expected_bytes
            assert snap["horovod_allreduce_tensors_total"] == iters
            assert snap["horovod_cycle_seconds"]["count"] > 0
            assert snap["horovod_responses_total"] >= 1
        # One more collective forces a fresh telemetry push AFTER the
        # byte counters above were bumped, so rank 0's fleet view holds
        # final per-rank numbers.
        def flush(r):
            engines[r].synchronize(
                engines[r].enqueue_allreduce(
                    np.ones(2, np.float32), name="flush"), timeout=30)

        fthreads = [threading.Thread(target=flush, args=(r,)) for r in range(2)]
        for t in fthreads:
            t.start()
        for t in fthreads:
            t.join(timeout=60)
        fleet = engines[0].controller.fleet.snapshot()
        assert sorted(fleet["ranks"]) == [0, 1]
        agg = fleet["aggregate"]["horovod_allreduce_bytes_total"]
        assert agg["count"] == 2
        assert agg["min"] >= expected_bytes
        # /status surfaces live queue/negotiation state + the fleet.
        status = engines[0].status()
        assert status["queue_depth"] == 0
        assert status["pending_tensors"] == []
        assert status["last_cycle_age_seconds"] >= 0
        assert "fleet" in status
    finally:
        stop = [threading.Thread(target=e.shutdown) for e in engines]
        for t in stop:
            t.start()
        for t in stop:
            t.join(timeout=60)


def test_response_cache_hit_metrics(monkeypatch):
    """Steady-state reduction of one named tensor: first cycle misses,
    later cycles hit; the counters must reflect it."""
    monkeypatch.setenv("HOROVOD_METRICS_SYNC_SECONDS", "0")

    from horovod_tpu.backend.threaded import ThreadedGroup
    from horovod_tpu.engine.engine import Engine

    group = ThreadedGroup(2)
    regs = [telemetry.MetricsRegistry() for _ in range(2)]
    engines = [
        Engine(rank=r, size=2, backend=group.backend(r), registry=regs[r])
        for r in range(2)
    ]
    for e in engines:
        e.cycle_time_s = 0.001
        e.start()

    def work(r):
        for it in range(6):
            engines[r].synchronize(
                engines[r].enqueue_allreduce(
                    np.full(2, float(it), np.float32), name="steady"),
                timeout=30)

    threads = [threading.Thread(target=work, args=(r,)) for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        snap = regs[0].snapshot()
        assert snap["horovod_response_cache_misses_total"] >= 1
        assert snap["horovod_response_cache_hits_total"] >= 1
    finally:
        stop = [threading.Thread(target=e.shutdown) for e in engines]
        for t in stop:
            t.start()
        for t in stop:
            t.join(timeout=60)


# ---------------------------------------------------------------------------
# Satellites: timeline drop accounting, retry counters


def test_timeline_drop_counting_and_flush(tmp_path):
    from horovod_tpu.engine.timeline import Timeline

    reg = telemetry.MetricsRegistry()
    path = tmp_path / "tl.json"
    tl = Timeline(filename=str(path), registry=reg, queue_size=4)
    # Saturate the tiny queue faster than the writer can drain: some
    # events must be counted as dropped, none may raise.
    for i in range(5000):
        tl.start(f"t{i % 3}", "ALLREDUCE")
        tl.end(f"t{i % 3}", "ALLREDUCE")
    tl.shutdown()
    # The timeline reports drops through the tracing plane's shared
    # counter (one metric for every trace output), tagged by source.
    dropped = reg.snapshot()[
        'horovod_trace_events_dropped_total{source="timeline"}']
    written = json.loads(path.read_text())
    assert dropped > 0
    # Everything not dropped reached the file (+1: the leading
    # clock-anchor metadata event): the writer drained the queue on
    # shutdown instead of abandoning it.
    assert len(written) + dropped == 10000 + 1


class _CaptureHandler(__import__("logging").Handler):
    """The horovod logger sets propagate=False, so caplog (root-handler
    based) never sees it; capture with a handler attached directly."""

    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


@pytest.fixture
def hvd_log():
    import logging

    from horovod_tpu.utils.logging import get_logger

    logger = get_logger()
    h = _CaptureHandler()
    prev = logger.level
    logger.addHandler(h)
    logger.setLevel(logging.DEBUG)
    yield h
    logger.removeHandler(h)
    logger.setLevel(prev)


def test_retry_attempts_counted_and_quiet(hvd_log):
    import logging

    from horovod_tpu.utils.retry import call_with_retry

    c = telemetry.counter("horovod_retry_attempts_total")
    start = c.value
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise OSError("transient")
        return "ok"

    out = call_with_retry(flaky, "flaky op", attempts=5, base=0.001,
                          cap=0.002)
    assert out == "ok"
    assert c.value - start == 3
    warnings = [r for r in hvd_log.records
                if r.levelno == logging.WARNING
                and "flaky op" in r.getMessage()]
    assert len(warnings) == 1  # first failure only; the rest are counted


def test_retry_final_failure_logs_warning(hvd_log):
    from horovod_tpu.utils.retry import call_with_retry

    with pytest.raises(OSError):
        call_with_retry(lambda: (_ for _ in ()).throw(OSError("down")),
                        "doomed op", attempts=3, base=0.001, cap=0.002)
    giving_up = [r for r in hvd_log.records if "giving up" in r.getMessage()]
    assert len(giving_up) == 1


# ---------------------------------------------------------------------------
# hvd.metrics() surface + MetricsCallback


def test_metrics_api_shape(hvd_single):
    m = hvd_single.metrics()
    assert m["size"] == 1 and m["mode"] == "mesh"
    assert isinstance(m["metrics"], dict)


def test_metrics_callback_logs_summary():
    from horovod_tpu.callbacks import MetricsCallback

    reg = telemetry.MetricsRegistry()
    reg.counter("horovod_allreduce_bytes_total").inc(10 * 1000 * 1000)
    lines = []
    cb = MetricsCallback(interval=5, log_fn=lines.append, root_only=False,
                         registry=reg)
    ctx = {}
    for b in range(10):
        cb.on_batch_end(b, ctx)
    assert len(lines) == 2
    assert "allreduce" in lines[0] and "cache hit" in lines[0]
    with pytest.raises(ValueError):
        MetricsCallback(interval=0)


# ---------------------------------------------------------------------------
# Prometheus exposition conformance: the round-trip audit
# (docs/health.md satellite). Whatever to_prometheus emits must parse
# back — escapes included — into exactly the registry's snapshot.


def test_prometheus_roundtrip_against_registry_snapshot():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("rt_ops_total",
                    help="ops with a\nnewline and a \\ backslash",
                    labels={"op": 'all"re\\duce', "phase": "x\ny"})
    c.inc(7)
    g = reg.gauge("rt_depth", help="plain")
    g.set(2.5)
    h = reg.histogram("rt_lat_seconds", min_exp=-3, max_exp=2)
    for v in (0.1, 0.1, 0.3, 1.5, 9.0):  # 9.0 -> +Inf bucket
        h.observe(v)
    text = metrics_export.to_prometheus(reg)
    samples, types, helps = metrics_export.parse_prometheus(text)

    # Scalars: exact values under the snapshot-identical keys.
    snap = reg.snapshot()
    ckey = [k for k in snap if k.startswith("rt_ops_total")][0]
    assert samples[ckey] == 7
    assert samples["rt_depth"] == 2.5
    # Escaped label values round-trip verbatim.
    assert 'op="all"re\\duce"' not in text  # raw quote must be escaped
    assert ckey in samples

    # HELP/TYPE: escaping round-trips, kinds are right.
    assert helps["rt_ops_total"] == "ops with a\nnewline and a \\ backslash"
    assert types["rt_ops_total"] == "counter"
    assert types["rt_depth"] == "gauge"
    assert types["rt_lat_seconds"] == "histogram"

    # Histogram: cumulative le-buckets + +Inf + _sum/_count must
    # reconstruct the registry's per-bucket counts exactly.
    hsnap = snap["rt_lat_seconds"]
    assert samples["rt_lat_seconds_count"] == hsnap["count"] == 5
    assert samples["rt_lat_seconds_sum"] == pytest.approx(hsnap["sum"])
    cums = []
    for b in hsnap["bounds"]:
        le = metrics_export._fmt(float(b))
        cums.append(samples[f'rt_lat_seconds_bucket{{le="{le}"}}'])
    cums.append(samples['rt_lat_seconds_bucket{le="+Inf"}'])
    assert cums == sorted(cums), "buckets must be cumulative"
    assert cums[-1] == hsnap["count"], "+Inf bucket must equal _count"
    per_bucket = [cums[0]] + [b - a for a, b in zip(cums, cums[1:])]
    assert per_bucket == hsnap["counts"]


def test_prometheus_labeled_families_stay_contiguous():
    """Strict exposition parsers reject interleaved families; all
    series of one family must render contiguously with one TYPE."""
    reg = telemetry.MetricsRegistry()
    reg.counter("fam_total", labels={"op": "a"}).inc()
    reg.counter("zz_other_total").inc()
    reg.counter("fam_total", labels={"op": "b"}).inc()
    text = metrics_export.to_prometheus(reg)
    fam_lines = [i for i, ln in enumerate(text.splitlines())
                 if ln.startswith("fam_total")]
    assert fam_lines == list(range(fam_lines[0], fam_lines[0] + 2))
    assert text.count("# TYPE fam_total") == 1


# ---------------------------------------------------------------------------
# critical_path --from-url: pull a live /trace endpoint.


def test_critical_path_from_url_pulls_live_trace():
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "critical_path",
        _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), "scripts", "critical_path.py"))
    critical_path = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(critical_path)

    doc = {"traceEvents": [
        {"ph": "X", "name": "exec.allreduce", "cat": "exec", "pid": 0,
         "tid": 1, "ts": 0.0, "dur": 50.0, "args": {"trace_id": 2}},
        {"ph": "X", "name": "exec.allreduce", "cat": "exec", "pid": 1,
         "tid": 1, "ts": 0.0, "dur": 90.0, "args": {"trace_id": 2}},
    ]}
    srv = metrics_export.MetricsHTTPServer(
        0, registry=telemetry.MetricsRegistry())
    srv.add_view("trace", lambda: json.dumps(doc))
    srv.start()
    try:
        for url in (f"127.0.0.1:{srv.port}",
                    f"http://127.0.0.1:{srv.port}",
                    f"http://127.0.0.1:{srv.port}/trace"):
            events, full = critical_path.fetch_url(url)
            out = critical_path.analyze(events)
            assert out["collectives_analyzed"] == 1
            assert out["stragglers"] == {
                "1": {"times_last": 1, "total_margin_us": 40.0}}
    finally:
        srv.stop()


def test_prometheus_help_backslash_n_roundtrip():
    """A literal backslash followed by 'n' in help text must survive
    the escape/unescape round-trip (chained replaces corrupt it)."""
    reg = telemetry.MetricsRegistry()
    reg.counter("esc_total", help=r"matches \n in input").inc()
    text = metrics_export.to_prometheus(reg)
    _, _, helps = metrics_export.parse_prometheus(text)
    assert helps["esc_total"] == r"matches \n in input"
