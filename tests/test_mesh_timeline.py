"""Mesh-path (GSPMD) timeline: device-lane splice + collective lane.

(ref: horovod/common/ops/gpu_operations.h:110-118 — the reference
splices device-side event timings into its timeline; here the source is
the XLA profiler and the splice is tested against a synthetic profiler
dump because the CPU backend publishes no device plane.)
"""
import gzip
import json
import os

import jax

from horovod_tpu.engine.mesh_timeline import MeshTimeline


def _write_fake_profile(tmp_path, events):
    d = tmp_path / "plugins" / "profile" / "2026_01_01_00_00_00"
    d.mkdir(parents=True)
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)


def test_splice_extracts_device_lanes_and_collectives(tmp_path):
    events = [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "name": "process_name", "pid": 3,
         "args": {"name": "/device:TPU:0"}},
        # host-side python event: must NOT be spliced
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 5,
         "name": "$api.py device_get"},
        # device compute
        {"ph": "X", "pid": 3, "tid": 1, "ts": 0, "dur": 50,
         "name": "fusion.42"},
        # device collectives -> also duplicated onto the ICI lane
        {"ph": "X", "pid": 3, "tid": 1, "ts": 50, "dur": 10,
         "name": "all-reduce-start.1"},
        {"ph": "X", "pid": 3, "tid": 2, "ts": 70, "dur": 4,
         "name": "collective-permute.3"},
    ]
    _write_fake_profile(tmp_path, events)
    out = tmp_path / "mesh.json"
    tl = MeshTimeline(str(out))
    tl._splice(str(tmp_path))

    got = json.load(open(out))["traceEvents"]
    names = [(e.get("pid"), e.get("name")) for e in got
             if e.get("ph") == "X"]
    assert (3, "fusion.42") in names
    assert (3, "all-reduce-start.1") in names
    # collective lane copies, host event excluded
    assert (999, "all-reduce-start.1") in names
    assert (999, "collective-permute.3") in names
    assert not any(n == "$api.py device_get" for _, n in names)
    lanes = {e["args"]["name"] for e in got
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert "ICI collectives" in lanes


def test_capture_smoke_writes_file(tmp_path):
    """capture() round-trips through the real jax.profiler (host-only
    planes on CPU) and always leaves a readable trace file."""
    out = tmp_path / "mesh.json"
    tl = MeshTimeline(str(out))
    with tl.capture():
        jax.block_until_ready(jax.jit(lambda x: x * 2)(jax.numpy.ones(8)))
    if out.exists():  # profiler produced a trace (version-dependent)
        data = json.load(open(out))
        assert "traceEvents" in data


def test_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv("HOROVOD_TIMELINE", raising=False)
    tl = MeshTimeline()
    assert not tl.enabled
    with tl.capture():
        pass


def test_output_path_derived_from_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_TIMELINE", "/tmp/x/trace.json")
    tl = MeshTimeline()
    assert tl.output_path == "/tmp/x/trace.mesh.json"
