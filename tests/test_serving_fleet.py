"""Fleet-serving tests (docs/serving.md "Redundant front doors",
"Streaming responses", "Serving autoscaler"): door leases + the
election epoch fence, the forwarding DoorManager (including the
half-streamed-interruption guarantee), the serving/load KV row
round-trip, the autoscaler policy + cooldown + change-only publish,
killdoor spec parsing, env knobs, and a streaming HTTP end-to-end.
"""
import json
import threading
import time

import pytest

from horovod_tpu.common.telemetry import MetricsRegistry
from horovod_tpu.serving.batcher import (STATUS_ERROR, STATUS_OK,
                                         STATUS_SHUTDOWN)
from horovod_tpu.serving.doors import (DoorGuard, DoorManager, WorkItem,
                                       admit_doc, lease_slots,
                                       publish_door_row, read_door_row)


class FakeKV:
    """In-memory rendezvous-KV double (put/get bytes by scope/key)."""

    def __init__(self):
        self.store = {}

    def put(self, scope, key, value):
        self.store[(scope, key)] = value

    def get(self, scope, key):
        return self.store.get((scope, key))


def _frontend(monkeypatch, port=0, **env):
    from horovod_tpu.serving.frontend import InferenceFrontend

    for k, v in env.items():
        monkeypatch.setenv(k, str(v))
    return InferenceFrontend(port=port, registry=MetricsRegistry()).start()


def _http(port, method, path, body=None):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request(method, path,
                 json.dumps(body) if body is not None else None)
    resp = conn.getresponse()
    out = (resp.status, json.loads(resp.read() or b"null"))
    conn.close()
    return out


# ---------------------------------------------------------------------------
# Leases, the door row, and the election epoch fence

def test_lease_slots_split():
    assert lease_slots(256, 2) == 128
    assert lease_slots(256, 3) == 85
    # Never below one slot: a door that cannot admit is not a door.
    assert lease_slots(3, 8) == 1
    assert lease_slots(0, 1) == 1
    assert lease_slots(10, 0) == 10  # degenerate n_doors clamps to 1


def test_door_row_roundtrip():
    kv = FakeKV()
    assert read_door_row(kv) is None
    publish_door_row(kv, epoch=3, door=1, doors=[1, 2], members=[1, 2, 5])
    row = read_door_row(kv)
    assert row["epoch"] == 3 and row["door"] == 1
    assert row["doors"] == [1, 2] and row["members"] == [1, 2, 5]
    assert row["stopped"] is False and row["wall"] > 0
    publish_door_row(kv, epoch=4, door=2, doors=[2], members=[2],
                     stopped=True)
    assert read_door_row(kv)["stopped"] is True
    # No KV / a KV blink degrade to None, never raise.
    publish_door_row(None, epoch=1, door=0, doors=[0], members=[0])
    assert read_door_row(None) is None


def test_door_guard_epoch_fence():
    """The fence: a door that did NOT participate in a re-mesh sees a
    newer row epoch and refuses to admit; participating (renew) moves
    its lease forward."""
    kv = FakeKV()
    guard = DoorGuard(kv, epoch=1, slots=4, refresh_s=0.0)
    publish_door_row(kv, epoch=1, door=0, doors=[0, 1], members=[0, 1])
    assert not guard.stale()
    # The fleet re-leased at epoch 2 without this door.
    publish_door_row(kv, epoch=2, door=1, doors=[1], members=[1, 2])
    assert guard.stale()
    # Participation renews the lease (and may resplit the slots).
    guard.renew(2, slots=8, active=False)
    assert not guard.stale()
    assert guard.slots == 8 and guard.active is False
    # No KV = own epoch = never stale (the classic single door).
    assert not DoorGuard(None, epoch=0).stale()


def test_stale_door_rejects_admission_with_503(monkeypatch):
    """A stale door's LATE admissions bounce: submit() -> None and the
    HTTP surface answers 503 naming both epochs — not a seat in a
    budget the fleet already re-leased."""
    kv = FakeKV()
    fe = _frontend(monkeypatch)
    try:
        fe.door_guard = DoorGuard(kv, epoch=1, refresh_s=0.0)
        publish_door_row(kv, epoch=1, door=0, doors=[0], members=[0])
        assert fe.submit("ok") is not None
        publish_door_row(kv, epoch=5, door=1, doors=[1], members=[1])
        assert fe.submit("late") is None
        code, body = _http(fe.port, "POST", "/v1/infer", {"inputs": 1})
        assert code == 503, body
        assert "epoch 1" in body["error"] and "epoch 5" in body["error"]
        snap = fe.registry.snapshot()
        assert snap[
            'horovod_serving_requests_total{status="rejected"}'] >= 2
    finally:
        fe.stop()


# ---------------------------------------------------------------------------
# WorkItem wire round-trip

def test_workitem_admit_roundtrip_and_expiry(monkeypatch):
    fe = _frontend(monkeypatch, port=None)
    try:
        req = fe.submit([1, 2], tokens=7, timeout_s=5.0, stream=True,
                        chunks=3)
        now = time.monotonic()
        doc = admit_doc(req, origin=2, now=now)
        assert doc["rid"] == f"2:{req.id}" and doc["origin"] == 2
        assert 0 < doc["timeout_rem"] <= 5.0
        # Rebuild on the coordinator: the deadline travels as REMAINING
        # seconds (monotonic clocks do not compare across processes).
        w = WorkItem.from_admit(doc, now=100.0)
        assert w.rid == doc["rid"] and w.payload == [1, 2]
        assert w.tokens == 7 and w.stream and w.n_chunks == 3
        assert w.req is None and w.chunk_seq == 0
        assert not w.expired(now=100.0)
        assert w.expired(now=100.0 + doc["timeout_rem"])
        # The local form keeps the future and the chunk cursor.
        wl = WorkItem.from_local(req, origin=2)
        assert wl.req is req and wl.rid == f"2:{req.id}"
    finally:
        fe.stop()


# ---------------------------------------------------------------------------
# DoorManager: forwarding, routed completion, failover fates

def test_door_manager_forwards_and_settles(monkeypatch):
    fe = _frontend(monkeypatch, port=None,
                   HOROVOD_SERVING_MAX_DELAY_MS=0)
    try:
        dm = DoorManager(fe, my_world=3)
        req = fe.submit(5.0)
        rf = dm.reply_fields()
        assert [d["rid"] for d in rf["admit"]] == [f"3:{req.id}"]
        assert rf["stop_req"] is False
        assert rf["door_pending"] == 1  # admitted, not yet answered
        # Another origin's completion is ignored; ours settles.
        dm.on_command({"complete": {
            f"9:{req.id}": {"status": STATUS_OK, "output": 0.0},
            f"3:{req.id}": {"status": STATUS_OK, "output": 10.0,
                            "weight_step": 7},
        }})
        assert req.done and req.status == STATUS_OK
        assert req.result == {"output": 10.0, "weight_step": 7}
        assert dm.reply_fields()["door_pending"] == 0
        snap = fe.registry.snapshot()
        assert snap['horovod_serving_requests_total{status="ok"}'] == 1
        # stop_req rises with the local stop flag.
        fe.request_stop()
        assert dm.reply_fields()["stop_req"] is True
    finally:
        fe.stop()


def test_door_manager_recovery_fates(monkeypatch):
    """After a re-mesh: unary forwards re-forward (idempotent — the
    coordinator dedups by rid); a HALF-STREAMED forward survives a
    replica loss but a coordinator loss ends it with an error frame —
    a stream never silently hangs."""
    fe = _frontend(monkeypatch, port=None)
    try:
        dm = DoorManager(fe, my_world=1)
        unary = fe.submit(1.0)
        stream = fe.submit(2.0, stream=True, chunks=4)
        rf = dm.reply_fields()
        assert len(rf["admit"]) == 2
        # Two chunks landed before the fault.
        dm.on_command({"chunks": {f"1:{stream.id}": [
            {"seq": 0, "output": 4.0, "weight_step": 3},
            {"seq": 1, "output": 4.0, "weight_step": 3},
        ]}})
        assert stream.chunk_seq == 2 and not stream.done
        # Replica (non-coordinator) loss: the coordinator still holds
        # the stream state — everything pends, the unary re-forwards.
        dm.on_recovery(coordinator_died=False)
        rf = dm.reply_fields()
        assert [d["rid"] for d in rf["admit"]] == [f"1:{unary.id}"]
        assert not stream.done
        # Coordinator loss: the stream state died with it.
        dm.on_recovery(coordinator_died=True)
        assert stream.done and stream.status == STATUS_ERROR
        frames = []
        while True:
            f = stream.next_chunk(0.1)
            if f is None:
                break
            frames.append(f)
        assert frames[-1]["final"] and frames[-1]["status"] == STATUS_ERROR
        assert "failover" in frames[-1]["error"]
        # The unary re-forwards once more; the origin future is intact.
        rf = dm.reply_fields()
        assert [d["rid"] for d in rf["admit"]] == [f"1:{unary.id}"]
        assert not unary.done
    finally:
        fe.stop()


def test_door_manager_promote_and_fail_pending(monkeypatch):
    fe = _frontend(monkeypatch, port=None)
    try:
        dm = DoorManager(fe, my_world=1)
        unary = fe.submit(1.0)
        half = fe.submit(2.0, stream=True, chunks=3)
        fresh_stream = fe.submit(3.0, stream=True, chunks=3)
        dm.reply_fields()
        dm.on_command({"chunks": {
            f"1:{half.id}": [{"seq": 0, "output": 1.0}]}})
        # This door WON the election: half-streamed ends loudly, the
        # rest comes back in admission order for the head requeue.
        keep = dm.promote()
        assert keep == [unary, fresh_stream]
        assert half.done and half.status == STATUS_ERROR
        assert not dm.pending  # the manager is spent
        # Terminal shutdown answers everything still pending.
        dm2 = DoorManager(fe, my_world=1)
        req = fe.submit(9.0)
        dm2.reply_fields()
        dm2.fail_pending("serving stopped")
        assert req.done and req.status == STATUS_SHUTDOWN
    finally:
        fe.stop()


# ---------------------------------------------------------------------------
# Verdict attribution for hard kills

def test_failed_rank_attribution_from_finalized_transport_text():
    """A hard-killed door surfaces as a transport error finalized
    through the engine: the structured .peer is lost and the TEXT
    leads with the REPORTER ("rank 1: recv from peer 0 failed") — the
    peer is the dead one. Grabbing the first "rank N" would make every
    survivor declare ITSELF dead and end serving."""
    from horovod_tpu.common.exceptions import HorovodInternalError
    from horovod_tpu.serving.replicas import failed_rank_from_error

    assert failed_rank_from_error(HorovodInternalError(
        "rank 1: recv from peer 0 failed: peer closed connection")) == 0
    assert failed_rank_from_error(HorovodInternalError(
        "rank 2: recv from peer 0 failed: [Errno 104] Connection "
        "reset by peer")) == 0
    # The liveness-verdict text still attributes the DECLARED rank.
    assert failed_rank_from_error(HorovodInternalError(
        "rank 2 (host x) declared dead by rank 0: no heartbeat")) == 2


# ---------------------------------------------------------------------------
# serving/load round-trip: coordinator publisher -> autoscaler consumer

def test_serving_load_row_roundtrip(monkeypatch):
    from horovod_tpu.serving.autoscaler import read_load
    from horovod_tpu.serving.replicas import ServingCoordinator

    kv = FakeKV()
    assert read_load(kv) is None and read_load(None) is None
    fe = _frontend(monkeypatch, port=None)
    try:
        fe.submit("queued")  # queue depth 1
        coord = ServingCoordinator.__new__(ServingCoordinator)
        coord.rendezvous = kv
        coord.frontend = fe
        coord._next_load_pub = 0.0
        coord._dispatching = [object(), object()]
        coord._remote_q = [object()]
        coord._continuations = []

        class RS:
            world = 3
            doors = [0, 1]
            members = [0, 1, 4]
            weight_step = 42

        coord.rs = RS()
        ServingCoordinator._publish_load(coord)
        row = read_load(kv)
        assert row["queue_depth"] == 1
        # inflight = dispatching(2) + forwarded(1) + continuations(0)
        # + queued(1): the fleet-wide admitted-but-unanswered signal.
        assert row["inflight"] == 4
        assert row["replicas"] == 3 and row["doors"] == 2
        assert row["weight_step"] == 42 and row["time"] > 0
        # Rate limit: an immediate second publish is a no-op.
        fe.submit("another")
        ServingCoordinator._publish_load(coord)
        assert read_load(kv)["queue_depth"] == 1
    finally:
        fe.stop()


# ---------------------------------------------------------------------------
# Autoscaler: pure policy, cooldown gate, change-only publish

def test_autoscaler_decide_policy():
    from horovod_tpu.serving.autoscaler import decide

    up = decide(backlog=8, replicas=2, min_replicas=1, max_replicas=4)
    assert up[0] == "scale_up" and up[1] == 3
    down = decide(backlog=0, replicas=3, min_replicas=1, max_replicas=4)
    assert down[0] == "scale_down" and down[1] == 2
    # At the cap / at the floor: hold, whatever the backlog says.
    assert decide(backlog=99, replicas=4, min_replicas=1,
                  max_replicas=4)[0] == "hold"
    assert decide(backlog=0, replicas=2, min_replicas=2,
                  max_replicas=4)[0] == "hold"
    # Between the watermarks: steady state.
    assert decide(backlog=2, replicas=2, min_replicas=1,
                  max_replicas=4)[0] == "hold"


def test_autoscaler_cadence_cooldown_and_publish(monkeypatch):
    from horovod_tpu.common import events as events_mod
    from horovod_tpu.serving.autoscaler import ServingAutoscaler

    emitted = []
    monkeypatch.setattr(events_mod, "emit",
                        lambda kind, **kw: emitted.append((kind, kw)))
    kv = FakeKV()
    reg = MetricsRegistry()
    au = ServingAutoscaler(kv, interval=1.0, min_replicas=1,
                           registry=reg)
    assert au.enabled
    assert not ServingAutoscaler(None, interval=1.0, registry=reg).enabled
    assert not ServingAutoscaler(kv, interval=0, registry=reg).enabled
    kv.put("serving", "load", json.dumps(
        {"queue_depth": 9, "inflight": 2}).encode())
    # backlog = max(queue_depth, inflight) = 9 over 2 replicas -> grow.
    plan = au.maybe(replicas=2, parked=2, now=100.0)
    assert plan is not None and plan[0] == "scale_up" and plan[1] == 3
    # Off-cadence: no decision at all.
    assert au.maybe(replicas=3, parked=1, now=100.5) is None
    # On cadence but inside the cooldown (3x interval): vetoed to hold.
    assert au.maybe(replicas=3, parked=1, now=101.5) is None
    snap = reg.snapshot()
    assert snap['horovod_serving_scale_decisions_total'
                '{decision="scale_up"}'] == 1
    assert snap['horovod_serving_scale_decisions_total'
                '{decision="hold"}'] == 1
    # Cooldown over, still hot -> grow again (cap = replicas + parked).
    plan = au.maybe(replicas=3, parked=1, now=104.0)
    assert plan is not None and plan[1] == 4
    # The KV mirror row tracks the latest decision for hvdtop.
    row = json.loads(kv.get("serving", "scale").decode())
    assert row["action"] == "scale_up" and row["target"] == 4
    # Journal on CHANGE only: two scale_ups at different targets = two
    # events; the interleaved cooldown-hold is a third. No HOLD spam.
    kinds = [k for k, _ in emitted]
    assert kinds.count("serving.scale") == len(emitted) == 3
    # Idle shrink respects the door floor via min_replicas.
    kv.put("serving", "load", json.dumps(
        {"queue_depth": 0, "inflight": 0}).encode())
    au.min_replicas = 2
    plan = au.maybe(replicas=4, parked=0, now=120.0)
    assert plan is not None and plan[0] == "scale_down" and plan[1] == 3
    assert au.maybe(replicas=2, parked=2, now=140.0) is None  # at floor


# ---------------------------------------------------------------------------
# killdoor chaos spec

def test_killdoor_spec_parsing():
    from horovod_tpu.common.fault_injection import parse_spec

    (rule,) = parse_spec("killdoor:after=5")
    assert rule.action == "killdoor" and rule.after == 5
    with pytest.raises(ValueError):
        parse_spec("killdoor:after=-1")
    with pytest.raises(ValueError):
        parse_spec("killdoor:op=send")  # op= is a transport-rule field


def test_killdoor_counts_active_door_only(monkeypatch):
    """A killdoor rule counts ACCEPTED admissions at the ACTIVE door
    only — standby-door traffic must never trip it. (The lethal hit
    itself is os._exit, so the test stays one hit short.)"""
    from horovod_tpu.common import fault_injection as fi

    inj = fi.FaultInjector()
    monkeypatch.setenv("HOROVOD_FAULT_INJECT", "killdoor:after=2")
    inj._load_env()
    assert inj.active
    for _ in range(5):
        inj.check_door_admit(active=False)  # standby: never counts
    inj.check_door_admit(active=True)
    inj.check_door_admit(active=True)  # hit 2 == after: still alive
    (rule,) = inj._rules
    assert rule.hits == 2


# ---------------------------------------------------------------------------
# Env knobs (the parse-test satellite)

def test_fleet_env_knob_parsing(monkeypatch):
    from horovod_tpu.utils import env as env_cfg

    for k in ("HOROVOD_SERVING_DOORS", "HOROVOD_SERVING_STREAM",
              "HOROVOD_SERVING_AUTOSCALE_INTERVAL_SECONDS",
              "HVD_TPU_SERVING_DOORS"):
        monkeypatch.delenv(k, raising=False)
    # Defaults: one door, streaming allowed, autoscaler off.
    assert env_cfg.serving_doors() == 1
    assert env_cfg.serving_stream_enabled() is True
    assert env_cfg.serving_autoscale_interval_seconds() == 0.0
    # Explicit values + floors.
    monkeypatch.setenv("HOROVOD_SERVING_DOORS", "3")
    assert env_cfg.serving_doors() == 3
    monkeypatch.setenv("HOROVOD_SERVING_DOORS", "0")
    assert env_cfg.serving_doors() == 1
    monkeypatch.setenv("HOROVOD_SERVING_STREAM", "0")
    assert env_cfg.serving_stream_enabled() is False
    monkeypatch.setenv(
        "HOROVOD_SERVING_AUTOSCALE_INTERVAL_SECONDS", "2.5")
    assert env_cfg.serving_autoscale_interval_seconds() == 2.5
    monkeypatch.setenv(
        "HOROVOD_SERVING_AUTOSCALE_INTERVAL_SECONDS", "-3")
    assert env_cfg.serving_autoscale_interval_seconds() == 0.0
    # Bogus values fall to the defaults — a typo must never silently
    # disable the redundancy (or enable a policy loop) the operator
    # did not ask for.
    monkeypatch.setenv("HOROVOD_SERVING_DOORS", "many")
    assert env_cfg.serving_doors() == 1
    monkeypatch.setenv(
        "HOROVOD_SERVING_AUTOSCALE_INTERVAL_SECONDS", "fast")
    assert env_cfg.serving_autoscale_interval_seconds() == 0.0
    # The HVD_TPU_ alias prefix works here like everywhere else.
    monkeypatch.delenv("HOROVOD_SERVING_DOORS")
    monkeypatch.setenv("HVD_TPU_SERVING_DOORS", "2")
    assert env_cfg.serving_doors() == 2


# ---------------------------------------------------------------------------
# Streaming HTTP end-to-end (one process, a fake completer thread)

def _completer(fe, stop, weight_step=11):
    """Stands in for the serving loop: one chunk per request per pass,
    then the final completion — the coordinator's exact contract."""
    while not stop.is_set():
        batch = fe.batcher.next_batch(0.05)
        for req in batch or []:
            if req.stream:
                for seq in range(req.n_chunks):
                    req.push_chunk({"seq": seq,
                                    "output": req.payload * 2,
                                    "weight_step": weight_step})
                req.complete({"output": req.payload * 2,
                              "weight_step": weight_step}, STATUS_OK)
            else:
                req.complete({"output": req.payload * 2,
                              "weight_step": weight_step}, STATUS_OK)


def _stream(port, body):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("POST", "/v1/infer", json.dumps(body))
    resp = conn.getresponse()
    ctype = resp.getheader("Content-Type", "")
    raw = resp.read()
    conn.close()
    if "ndjson" in ctype:
        frames = [json.loads(ln) for ln in raw.splitlines() if ln.strip()]
    else:
        frames = json.loads(raw or b"null")
    return resp.status, ctype, frames


def test_streaming_http_end_to_end(monkeypatch):
    fe = _frontend(monkeypatch, HOROVOD_SERVING_MAX_DELAY_MS=0)
    stop = threading.Event()
    t = threading.Thread(target=_completer, args=(fe, stop), daemon=True)
    t.start()
    try:
        status, ctype, frames = _stream(
            fe.port, {"inputs": 3.0, "stream": True, "chunks": 3})
        assert status == 200 and "ndjson" in ctype
        data = [f for f in frames if not f.get("final")]
        fin = [f for f in frames if f.get("final")]
        assert len(data) == 3, frames
        assert [f["seq"] for f in data] == [0, 1, 2]
        # Every chunk proves which weights produced it.
        assert all(f["weight_step"] == 11 for f in data)
        assert all(f["output"] == 6.0 for f in data)
        assert len(fin) == 1 and fin[0]["status"] == STATUS_OK
        assert fin[0]["chunks"] == 3
        # Unary JSON stays the default wire shape.
        status, ctype, body = _stream(fe.port, {"inputs": 2.0})
        assert status == 200 and "ndjson" not in ctype
        assert body == {"output": 4.0, "weight_step": 11}
        assert fe.registry.counter(
            "horovod_serving_streamed_chunks_total").value == 3
    finally:
        stop.set()
        t.join(timeout=5)
        fe.stop()


def test_streaming_master_switch_answers_unary(monkeypatch):
    """HOROVOD_SERVING_STREAM=0: a {"stream": true} request is served
    as plain unary JSON — the switch gates the wire shape only, never
    drops the request."""
    fe = _frontend(monkeypatch, HOROVOD_SERVING_MAX_DELAY_MS=0,
                   HOROVOD_SERVING_STREAM=0)
    stop = threading.Event()
    t = threading.Thread(target=_completer, args=(fe, stop), daemon=True)
    t.start()
    try:
        status, ctype, body = _stream(
            fe.port, {"inputs": 5.0, "stream": True, "chunks": 3})
        assert status == 200 and "ndjson" not in ctype
        assert body["output"] == 10.0 and body["weight_step"] == 11
    finally:
        stop.set()
        t.join(timeout=5)
        fe.stop()


def test_stream_deadline_mid_wait_terminal_frame(monkeypatch):
    """An admitted-but-never-dispatched streaming request answers at
    its deadline exactly like unary (504 semantics, before any bytes
    hit the wire) — not a hang."""
    fe = _frontend(monkeypatch,
                   HOROVOD_SERVING_REQUEST_TIMEOUT_SECONDS=0.1)
    try:
        t0 = time.monotonic()
        status, ctype, body = _stream(
            fe.port, {"inputs": 1.0, "stream": True, "chunks": 3})
        assert status == 504, (status, body)
        assert time.monotonic() - t0 < 5
        assert "deadline" in body["error"]
    finally:
        fe.stop()
