"""TCP full-mesh backend: bootstrap failure modes + the zero-copy
framing layer (scatter-gather sendmsg sends, recv-into receives,
persistent per-peer senders).

(ref: horovod/common/gloo/gloo_context.cc rendezvous bootstrap — gloo
bounds its store waits with a timeout; the accept side here needs the
same bound.)
"""
import socket

import numpy as np
import pytest


# ---------------------------------------------------------------------------
# framing helpers: scatter-gather send == old concat framing on the wire
def test_send_all_scatter_gather_framing_roundtrip():
    from horovod_tpu.backend.tcp import _recv_frame, _send_all

    a, b = socket.socketpair()
    try:
        payload = np.arange(1000, dtype=np.float32)
        header = b"hdr!"
        sent = _send_all(a, [header, memoryview(payload)])
        assert sent == 4 + payload.nbytes
        frame = _recv_frame(b)
        assert bytes(frame[:4]) == b"hdr!"
        np.testing.assert_array_equal(
            np.frombuffer(frame, np.float32, offset=4), payload)
    finally:
        a.close()
        b.close()


def test_send_all_accepts_all_buffer_shapes():
    from horovod_tpu.backend.tcp import _recv_frame, _send_all

    a, b = socket.socketpair()
    try:
        for data, expect in [
            (b"plain", b"plain"),
            (bytearray(b"ba"), b"ba"),
            (memoryview(b"mv"), b"mv"),
            (np.array([1, 2], np.uint8), b"\x01\x02"),
            ([b"x", b"", b"y"], b"xy"),   # empty buffer in the middle
            (b"", b""),                    # empty frame
            ([], b""),                     # empty list -> empty frame
            (np.zeros((0, 3), np.float32), b""),  # 0-dim'd array
        ]:
            _send_all(a, data)
            assert bytes(_recv_frame(b)) == expect
    finally:
        a.close()
        b.close()


def test_recv_frame_returns_writable_owned_buffer():
    """unpack_array aliases recv'd frames zero-copy — that is only safe
    because every recv allocates a fresh writable bytearray."""
    from horovod_tpu.backend.tcp import _recv_frame, _send_all

    a, b = socket.socketpair()
    try:
        _send_all(a, b"abc")
        f1 = _recv_frame(b)
        _send_all(a, b"xyz")
        f2 = _recv_frame(b)
        assert isinstance(f1, bytearray) and isinstance(f2, bytearray)
        f1[0] = 0x7A  # writable, and distinct buffers
        assert bytes(f2) == b"xyz"
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# recv_into_from + persistent senders over a real 2-backend mesh
def _pair(scope, monkeypatch):
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_fault_tolerance import _tcp_pair

    return _tcp_pair(scope, monkeypatch)


def test_recv_into_from_exact_and_zero_copy(monkeypatch):
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    server, (b0, b1) = _pair("t_recv_into", monkeypatch)
    try:
        src = np.arange(4096, dtype=np.float64)
        ticket = b0.send_async(1, src)
        dst = np.zeros(4096, np.float64)
        n = b1.recv_into_from(0, dst)
        ticket.wait()
        assert n == src.nbytes
        np.testing.assert_array_equal(dst, src)
        # empty frame into empty view
        t2 = b0.send_async(1, b"")
        assert b1.recv_into_from(0, np.zeros(0, np.float32)) == 0
        t2.wait()
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_recv_into_from_length_mismatch_severs(monkeypatch):
    """A frame that does not match the expected length is a protocol
    desync (e.g. HOROVOD_RING_SEGMENT_BYTES differing across ranks):
    unrecoverable, so the peer is severed with TransportError."""
    from horovod_tpu.common.exceptions import TransportError

    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    server, (b0, b1) = _pair("t_mismatch", monkeypatch)
    try:
        b0.send_to(1, b"12345678")
        with pytest.raises(TransportError, match="desynced peer"):
            b1.recv_into_from(0, bytearray(4))
        # severed: later I/O on that peer fails fast
        with pytest.raises(TransportError):
            b1.recv_from(0)
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_sync_sends_route_through_persistent_sender_fifo(monkeypatch):
    """Once a peer has a sender worker, a plain send_to must flow
    through the same FIFO — interleaved frames from two paths would
    corrupt the stream mid-frame."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    server, (b0, b1) = _pair("t_fifo", monkeypatch)
    try:
        tickets = [b0.send_async(1, f"async{i}".encode()) for i in range(3)]
        b0.send_to(1, b"sync")  # waits: queued behind the async frames
        got = [bytes(b1.recv_from(0)) for _ in range(4)]
        for t in tickets:
            t.wait()
        assert got == [b"async0", b"async1", b"async2", b"sync"]
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_sendmsg_frames_and_bytes_counters(monkeypatch):
    from horovod_tpu.common import telemetry

    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    server, (b0, b1) = _pair("t_counters", monkeypatch)
    try:
        reg = telemetry.default_registry()
        frames0 = reg.counter("horovod_tcp_sendmsg_frames_total").value
        sent0 = reg.counter("horovod_tcp_bytes_sent_total").value
        payload = np.arange(256, dtype=np.float32)
        b0.send_to(1, payload)
        b1.recv_from(0)
        assert reg.counter(
            "horovod_tcp_sendmsg_frames_total").value == frames0 + 1
        # exact accounting: payload + length+channel header
        from horovod_tpu.backend.tcp import _HDR_LEN

        assert reg.counter(
            "horovod_tcp_bytes_sent_total").value == (
                sent0 + payload.nbytes + _HDR_LEN)
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


# ---------------------------------------------------------------------------
# channel-tagged frames + per-peer receive demultiplexer
def test_channel_demux_routes_interleaved_frames(monkeypatch):
    """Frames for two channels interleaved on one socket must reach the
    right recv calls with intra-channel order preserved — the invariant
    that lets two in-flight collectives share a peer socket."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    server, (b0, b1) = _pair("t_demux", monkeypatch)
    try:
        tickets = [
            b0.send_async(1, b"ch0-first", channel=0),
            b0.send_async(1, b"ch1-first", channel=1),
            b0.send_async(1, b"ch0-second", channel=0),
            b0.send_async(1, b"ch1-second", channel=1),
        ]
        # Receive channel 1 first: the demux must read past (and park)
        # the channel-0 frames without consuming them.
        with b1.channel_scope(1):
            assert bytes(b1.recv_from(0)) == b"ch1-first"
            assert bytes(b1.recv_from(0)) == b"ch1-second"
        with b1.channel_scope(0):
            assert bytes(b1.recv_from(0)) == b"ch0-first"
            assert bytes(b1.recv_from(0)) == b"ch0-second"
        for t in tickets:
            t.wait()
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_channel_demux_concurrent_recvs(monkeypatch):
    """Two threads blocked on different channels of the same peer: each
    gets its own payload regardless of arrival order."""
    import threading

    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    server, (b0, b1) = _pair("t_demux_threads", monkeypatch)
    try:
        got = {}

        def recv(ch):
            with b1.channel_scope(ch):
                got[ch] = bytes(b1.recv_from(0))

        ts = [threading.Thread(target=recv, args=(c,)) for c in (0, 1)]
        for t in ts:
            t.start()
        import time

        time.sleep(0.1)  # both receivers parked before anything arrives
        b0.send_async(1, b"one", channel=1).wait()
        b0.send_async(1, b"zero", channel=0).wait()
        for t in ts:
            t.join(timeout=30)
        assert got == {0: b"zero", 1: b"one"}
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_channel_recv_into_from_cross_channel_deposit(monkeypatch):
    """recv_into on channel 0 that encounters a channel-1 frame first
    parks it for channel 1 and still lands its own payload (one copy on
    the deposited path, zero on its own)."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    server, (b0, b1) = _pair("t_demux_into", monkeypatch)
    try:
        other = np.arange(64, dtype=np.float32)
        mine = np.arange(128, dtype=np.float64)
        b0.send_async(1, other, channel=1).wait()
        b0.send_async(1, mine, channel=0).wait()
        dst = np.zeros(128, np.float64)
        with b1.channel_scope(0):
            assert b1.recv_into_from(0, dst) == mine.nbytes
        np.testing.assert_array_equal(dst, mine)
        dst1 = np.zeros(64, np.float32)
        with b1.channel_scope(1):
            assert b1.recv_into_from(0, dst1) == other.nbytes
        np.testing.assert_array_equal(dst1, other)
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_channel_frame_counters(monkeypatch):
    from horovod_tpu.common import telemetry

    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    server, (b0, b1) = _pair("t_chan_counters", monkeypatch)
    try:
        reg = telemetry.default_registry()

        def val(label):
            return reg.counter("horovod_tcp_channel_frames_total",
                               labels={"channel": label}).value

        c0, cc = val("0"), val("ctrl")
        b0.send_async(1, b"data", channel=0).wait()
        with b1.channel_scope(0):
            b1.recv_from(0)
        b0.send_to(1, b"ctrl-plane")  # no scope -> control channel
        b1.recv_from(0)
        assert val("0") == c0 + 1
        assert val("ctrl") == cc + 1
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_mesh_bootstrap_accept_timeout(monkeypatch):
    """A higher rank that never connects must surface as an error on
    the accepting rank, not an indefinite hang (caught live: rank 0
    blocked forever in accept() when a joining worker died during
    bootstrap)."""
    import pytest

    from horovod_tpu.backend.tcp import TcpBackend
    from horovod_tpu.common.exceptions import HorovodInternalError
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    monkeypatch.setenv("HOROVOD_MESH_BOOTSTRAP_TIMEOUT", "1.5")
    monkeypatch.setenv("HVDRUN_FORCE_LOCAL", "1")
    server = RendezvousServer()
    port = server.start()
    try:
        from horovod_tpu.backend.rendezvous import RendezvousClient

        rdv = RendezvousClient("127.0.0.1", port)
        with pytest.raises(HorovodInternalError, match=r"rank\(s\) \[1\]"):
            TcpBackend(0, 2, rendezvous=rdv, scope="t_accept")
    finally:
        server.stop()
