"""TCP full-mesh bootstrap failure modes.

(ref: horovod/common/gloo/gloo_context.cc rendezvous bootstrap — gloo
bounds its store waits with a timeout; the accept side here needs the
same bound.)
"""


def test_mesh_bootstrap_accept_timeout(monkeypatch):
    """A higher rank that never connects must surface as an error on
    the accepting rank, not an indefinite hang (caught live: rank 0
    blocked forever in accept() when a joining worker died during
    bootstrap)."""
    import pytest

    from horovod_tpu.backend.tcp import TcpBackend
    from horovod_tpu.common.exceptions import HorovodInternalError
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    monkeypatch.setenv("HOROVOD_MESH_BOOTSTRAP_TIMEOUT", "1.5")
    monkeypatch.setenv("HVDRUN_FORCE_LOCAL", "1")
    server = RendezvousServer()
    port = server.start()
    try:
        from horovod_tpu.backend.rendezvous import RendezvousClient

        rdv = RendezvousClient("127.0.0.1", port)
        with pytest.raises(HorovodInternalError, match=r"rank\(s\) \[1\]"):
            TcpBackend(0, 2, rendezvous=rdv, scope="t_accept")
    finally:
        server.stop()
