"""Fault-tolerant data plane: injection harness, bounded I/O, and clean
failure propagation (ISSUE 1; ref model: the reference's elastic
contract — every collective failure surfaces as HorovodInternalError,
horovod/common/exceptions.py:17-31).

Fast tests (tier-1): rule parsing, injector verdicts, bounded recv,
TcpBackend error translation, engine fail-all propagation, stall
inspector verdicts. The subprocess chaos test (kill 1 of 4 workers
mid-step) is marked `slow`.
"""
import logging
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common import fault_injection
from horovod_tpu.common.exceptions import (
    HorovodInternalError,
    TransportError,
)
from horovod_tpu.common.fault_injection import (
    DROP,
    PASS,
    FaultInjector,
    InjectedFault,
    Rule,
    parse_spec,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_injector():
    """Every test starts and ends with a disarmed process-wide injector."""
    fault_injection.injector.clear()
    yield
    fault_injection.injector.clear()


# ---------------------------------------------------------------------------
# rule grammar
def test_parse_spec_full_grammar():
    rules = parse_spec(
        "kill:step=5;sever:peer=0:after=3;drop:peer=2:rank=1;"
        "delay:peer=1:secs=0.25:op=recv"
    )
    assert [r.action for r in rules] == ["kill", "sever", "drop", "delay"]
    assert rules[0].step == 5
    assert rules[1].peer == 0 and rules[1].after == 3
    assert rules[2].rank == 1
    assert rules[3].secs == 0.25 and rules[3].op == "recv"


@pytest.mark.parametrize("bad", [
    "explode:peer=1",          # unknown action
    "sever:peer",              # field without '='
    "kill",                    # kill needs step=N
    "delay:peer=1",            # delay needs secs=S
    "sever:op=sideways:peer=1",  # bad op
    "drop:peer=1:op=recv",     # drop is send-only; reject, don't no-op
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


def test_env_spec_arms_injector(monkeypatch):
    monkeypatch.setenv(fault_injection.ENV_VAR, "sever:peer=1")
    inj = FaultInjector()
    inj._load_env()
    assert inj.active
    with pytest.raises(InjectedFault):
        inj.check_io(rank=0, peer=1, op="send")


# ---------------------------------------------------------------------------
# injector verdicts
def test_sever_after_n_frames():
    inj = FaultInjector()
    inj.install([Rule(action="sever", peer=1, after=2)])
    assert inj.check_io(0, 1, "send") == PASS
    assert inj.check_io(0, 1, "send") == PASS
    with pytest.raises(InjectedFault):
        inj.check_io(0, 1, "send")
    # other peers unaffected
    assert inj.check_io(0, 2, "send") == PASS


def test_drop_and_rank_scoping():
    inj = FaultInjector()
    inj.install([Rule(action="drop", peer=0, rank=1)])
    assert inj.check_io(1, 0, "send") == DROP
    assert inj.check_io(2, 0, "send") == PASS  # different rank
    # drop is send-only: a recv neither drops...
    assert inj.check_io(1, 0, "recv") == PASS


def test_drop_after_counts_sends_only():
    inj = FaultInjector()
    inj.install([Rule(action="drop", peer=0, after=2)])
    # ...nor advances the after=K hit counter.
    assert inj.check_io(0, 0, "recv") == PASS
    assert inj.check_io(0, 0, "recv") == PASS
    assert inj.check_io(0, 0, "send") == PASS   # hit 1
    assert inj.check_io(0, 0, "send") == PASS   # hit 2
    assert inj.check_io(0, 0, "send") == DROP   # hit 3 > after=2


def test_delay_sleeps():
    inj = FaultInjector()
    inj.install([Rule(action="delay", peer=0, secs=0.15)])
    t0 = time.monotonic()
    assert inj.check_io(0, 0, "send") == PASS
    assert time.monotonic() - t0 >= 0.15


def test_connect_rules_need_explicit_op():
    inj = FaultInjector()
    inj.install([Rule(action="sever", peer=1)])
    # data-plane default: connect is untouched...
    assert inj.check_io(0, 1, "connect") == PASS
    inj.install([Rule(action="sever", peer=1, op="connect")])
    with pytest.raises(InjectedFault):
        inj.check_io(0, 1, "connect")
    # ...and a connect-scoped rule leaves send/recv alone.
    assert inj.check_io(0, 1, "send") == PASS


def test_kill_rule_fires_at_step():
    """kill:step=N must down the process exactly at step N (subprocess:
    os._exit is unfakeable in-process)."""
    prog = textwrap.dedent("""
        import os
        os.environ["HOROVOD_FAULT_INJECT"] = "kill:step=3"
        from horovod_tpu.common import fault_injection
        for i in range(10):
            fault_injection.advance_step()
            print("survived", i + 1, flush=True)
    """)
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop(fault_injection.ENV_VAR, None)
    proc = subprocess.run(
        [sys.executable, "-c", prog], env=env, capture_output=True,
        text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert proc.stdout.splitlines() == ["survived 1", "survived 2"]


# ---------------------------------------------------------------------------
# bounded recv + translation
def test_recv_exact_bounded_times_out():
    from horovod_tpu.backend.tcp import _recv_exact_bounded

    a, b = socket.socketpair()
    try:
        t0 = time.monotonic()
        with pytest.raises(TimeoutError, match="HOROVOD_TCP_TIMEOUT"):
            _recv_exact_bounded(a, 8, timeout=0.4, poll=0.05)
        assert time.monotonic() - t0 < 2.0  # bounded, not hung
    finally:
        a.close()
        b.close()


def test_recv_exact_bounded_detects_peer_close():
    from horovod_tpu.backend.tcp import _recv_exact_bounded

    a, b = socket.socketpair()
    try:
        b.close()
        with pytest.raises(ConnectionError):
            _recv_exact_bounded(a, 8, timeout=0.0, poll=0.05)
    finally:
        a.close()


def _tcp_pair(scope, monkeypatch):
    """Two real TcpBackends full-meshed through a local rendezvous."""
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.backend.tcp import TcpBackend
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    monkeypatch.setenv("HVDRUN_FORCE_LOCAL", "1")
    # Pin the raw socket plane: the default transport is `auto` (shm
    # engages between co-located ranks), and this helper feeds the
    # tcp-only suites — fault injections on socket paths, exact
    # tcp byte/frame counter assertions.
    monkeypatch.setenv("HOROVOD_TRANSPORT", "tcp")
    server = RendezvousServer()
    port = server.start()
    rdv = RendezvousClient("127.0.0.1", port)
    backends = [None, None]
    errs = []

    def build(rank):
        try:
            backends[rank] = TcpBackend(rank, 2, rendezvous=rdv, scope=scope)
        except BaseException as e:  # pragma: no cover - bootstrap bug
            errs.append(e)

    threads = [threading.Thread(target=build, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs, errs
    assert backends[0] is not None and backends[1] is not None
    return server, backends


def test_tcp_dead_peer_translates_to_transport_error(monkeypatch):
    """A peer whose sockets die mid-collective must surface as
    TransportError (⊂ HorovodInternalError) on the survivor — never a
    raw ConnectionError (the elastic contract, exceptions.py:4-9)."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "5")
    server, (b0, b1) = _tcp_pair("t_dead_peer", monkeypatch)
    try:
        b1.shutdown()  # rank 1 "dies": OS closes its sockets
        with pytest.raises(TransportError, match="peer 1"):
            b0.gather_bytes(b"x")  # rank 0 recvs from rank 1
        # the failed peer is severed: later ops fail fast, same type
        with pytest.raises(TransportError):
            b0.gather_bytes(b"x")
    finally:
        b0.shutdown()
        server.stop()


def test_tcp_injected_sever_translates(monkeypatch):
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "5")
    server, (b0, b1) = _tcp_pair("t_sever", monkeypatch)
    try:
        fault_injection.injector.install(
            [Rule(action="sever", peer=1, rank=0, op="recv")]
        )
        with pytest.raises(TransportError, match="severed"):
            b0.gather_bytes(b"x")
    finally:
        fault_injection.injector.clear()
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_tcp_timeout_on_silent_peer(monkeypatch):
    """A peer that is alive but never sends must trip the bounded recv
    within HOROVOD_TCP_TIMEOUT_SECONDS — the hang this PR exists to
    kill."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "0.5")
    server, (b0, b1) = _tcp_pair("t_silent", monkeypatch)
    try:
        t0 = time.monotonic()
        with pytest.raises(TransportError, match="no progress"):
            b0.recv_from(1)
        assert time.monotonic() - t0 < 2.0
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


# ---------------------------------------------------------------------------
# engine: fail ALL pending handles, latch terminal state
class _FailingBackend:
    """LocalBackend shape whose data plane dies like a broken mesh."""

    rank, size = 0, 1
    local_rank, local_size, cross_rank, cross_size = 0, 1, 0, 1
    hierarchical = hier_allgather = False

    def set_topology(self, *a):
        pass

    def gather_bytes(self, payload):
        return [payload]

    def bcast_bytes(self, payload):
        return payload

    def allreduce_words(self, words, op):
        return list(words)

    def barrier(self):
        pass

    def allreduce(self, arr, op=None):
        raise TransportError("rank 0: send to peer 1 failed: injected")

    def allgatherv(self, arr, first_dims):
        raise TransportError("rank 0: send to peer 1 failed: injected")

    def broadcast(self, arr, root):
        raise TransportError("rank 0: send to peer 1 failed: injected")

    def alltoallv(self, arr, splits):
        raise TransportError("rank 0: send to peer 1 failed: injected")

    def adasum_allreduce_all(self, arr):
        raise TransportError("rank 0: send to peer 1 failed: injected")

    def shutdown(self):
        pass


def test_engine_transport_error_fails_all_pending_and_latches():
    from horovod_tpu.engine.engine import Engine

    eng = Engine(rank=0, size=1, backend=_FailingBackend())
    eng.start()
    try:
        h1 = eng.enqueue_allreduce(np.ones(4, np.float32), name="a")
        h2 = eng.enqueue_allreduce(np.ones(4, np.float32), name="b")
        with pytest.raises(HorovodInternalError, match="peer 1"):
            eng.synchronize(h1, timeout=30)
        with pytest.raises(HorovodInternalError, match="peer 1"):
            eng.synchronize(h2, timeout=30)
        # The engine is dead: a NEW enqueue must fail immediately with
        # the latched reason, not park forever.
        h3 = eng.enqueue_allreduce(np.ones(4, np.float32), name="c")
        with pytest.raises(HorovodInternalError, match="peer 1"):
            eng.synchronize(h3, timeout=30)
    finally:
        eng.shutdown()


def test_tensor_queue_finalize_latches_status():
    from horovod_tpu.common.message import Request
    from horovod_tpu.common.types import Status, StatusType
    from horovod_tpu.engine.tensor_queue import TensorQueue, TensorTableEntry

    q = TensorQueue()
    q.finalize(Status.Aborted("mesh down"))
    st = q.add_to_tensor_queue(
        TensorTableEntry(tensor_name="t", tensor=None), Request()
    )
    assert st.type == StatusType.ABORTED and "mesh down" in st.reason


# ---------------------------------------------------------------------------
# stall inspector (satellite: the abort path had no direct test)
@pytest.fixture
def _hvd_log_capture():
    records = []

    class _Cap(logging.Handler):
        def emit(self, record):
            records.append(record)

    h = _Cap(level=logging.DEBUG)
    lg = logging.getLogger("horovod_tpu")
    lg.addHandler(h)
    yield records
    lg.removeHandler(h)


def _make_inspector(monkeypatch, warn="0.05", shut="0"):
    monkeypatch.setenv("HOROVOD_STALL_CHECK_TIME_SECONDS", warn)
    monkeypatch.setenv("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", shut)
    from horovod_tpu.engine.stall import StallInspector

    insp = StallInspector(size=2)
    insp.last_check = 0.0  # open the rate gate for the first check()
    return insp


def test_stall_warning_emitted_once(monkeypatch, _hvd_log_capture):
    insp = _make_inspector(monkeypatch)
    insp.record("allreduce.g", 0)  # rank 1 never shows up
    time.sleep(0.08)
    assert insp.check() is None  # warn, not abort
    warnings = [r for r in _hvd_log_capture
                if "Stalled op: allreduce.g" in r.getMessage()]
    assert len(warnings) == 1
    assert "[missing ranks: [1]]" in warnings[0].getMessage()
    insp.last_check = 0.0
    assert insp.check() is None  # second check: already warned, no spam
    assert len([r for r in _hvd_log_capture
                if "Stalled op" in r.getMessage()]) == 1


def test_stall_shutdown_verdict(monkeypatch):
    insp = _make_inspector(monkeypatch, warn="0.01", shut="0.05")
    insp.record("allreduce.g", 0)
    time.sleep(0.08)
    reason = insp.check()
    assert reason is not None and "stall shutdown" in reason
    assert "allreduce.g" in reason and "[1]" in reason


def test_stall_remove_clears_warned_state(monkeypatch, _hvd_log_capture):
    insp = _make_inspector(monkeypatch)
    insp.record("allreduce.g", 0)
    time.sleep(0.08)
    insp.check()
    assert "allreduce.g" in insp.warned
    insp.remove("allreduce.g")
    assert not insp.pending and "allreduce.g" not in insp.warned
    # the op comes back (next batch) and stalls again -> fresh warning
    insp.record("allreduce.g", 0)
    time.sleep(0.08)
    insp.last_check = 0.0
    insp.check()
    assert len([r for r in _hvd_log_capture
                if "Stalled op" in r.getMessage()]) == 2


def test_stall_disabled_never_aborts(monkeypatch):
    monkeypatch.setenv("HOROVOD_STALL_CHECK_DISABLE", "1")
    monkeypatch.setenv("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", "0.01")
    from horovod_tpu.engine.stall import StallInspector

    insp = StallInspector(size=2)
    insp.record("allreduce.g", 0)
    insp.last_check = 0.0
    time.sleep(0.05)
    assert insp.check() is None


# ---------------------------------------------------------------------------
# fault injection x the zero-copy/pipelined I/O paths: sever mid-segment,
# delay on the persistent sender queue, timeout during recv_into. Every
# failure must still surface as TransportError (⊂ HorovodInternalError,
# the class the engine's fail-all-pending path keys on — covered by
# test_engine_transport_error_fails_all_pending_and_latches above).
def _ring_pair_allreduce(b0, b1, count=8192):
    """Drive a 2-rank ring allreduce on real TCP backends; returns
    (results, errors) without raising so callers can assert on the
    failure mode."""
    results, errors = [None, None], [None, None]

    def w(i, b):
        try:
            x = np.arange(count, dtype=np.float32) * (i + 1)
            results[i] = b.allreduce(x)
        except BaseException as e:  # noqa: BLE001
            errors[i] = e

    ts = [threading.Thread(target=w, args=(i, b))
          for i, b in ((0, b0), (1, b1))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    return results, errors


def test_sever_mid_segment_raises_transport_error(monkeypatch):
    """A sever that fires on the Nth frame lands MID-CHUNK on the
    segmented pipelined path (each ring step is several frames): the
    persistent sender's ticket must carry it back as TransportError."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    # 8192 floats / 2 ranks = 16KB chunks; 4KB segments -> 4 frames per
    # step, so after=2 fires mid-chunk.
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "4096")
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "5")
    server, (b0, b1) = _tcp_pair("t_sever_seg", monkeypatch)
    try:
        fault_injection.injector.install(
            [Rule(action="sever", rank=0, peer=1, op="send", after=2)]
        )
        results, errors = _ring_pair_allreduce(b0, b1)
        # rank 0 fails with TransportError: either the severed send's
        # ticket surfaces first, or its concurrent recv on the (now
        # hard-closed) socket does — both translate cleanly.
        assert isinstance(errors[0], TransportError), errors
        # rank 0's socket to peer 1 is hard-closed: fail fast afterwards
        with pytest.raises(TransportError):
            b0.send_to(1, b"x")
    finally:
        fault_injection.injector.clear()
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_sever_mid_segment_fails_the_exact_ticket(monkeypatch):
    """Driving the segmented send path directly: segment 3 of 4 hits the
    sever rule, and ITS ticket carries the translated error while the
    first two segments completed."""
    import numpy as np_

    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "5")
    server, (b0, b1) = _tcp_pair("t_sever_ticket", monkeypatch)
    try:
        fault_injection.injector.install(
            [Rule(action="sever", rank=0, peer=1, op="send", after=2)]
        )
        seg = np_.arange(1024, dtype=np_.float32)
        tickets = [b0.send_async(1, seg) for _ in range(4)]
        for _ in range(2):  # the two pre-sever segments arrive intact
            assert len(b1.recv_from(0)) == seg.nbytes
        tickets[0].wait()
        tickets[1].wait()
        with pytest.raises(TransportError, match="severed"):
            tickets[2].wait()
        # everything queued behind the sever fails too (peer gone)
        with pytest.raises(TransportError):
            tickets[3].wait()
    finally:
        fault_injection.injector.clear()
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_delay_on_persistent_sender_queue(monkeypatch):
    """A delay rule sleeps inside the persistent sender worker: the
    queued frame is late but correct, and the caller only feels the
    delay at ticket wait / recv time."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    server, (b0, b1) = _tcp_pair("t_delay_sender", monkeypatch)
    try:
        fault_injection.injector.install(
            [Rule(action="delay", rank=0, peer=1, op="send", secs=0.3)]
        )
        t0 = time.monotonic()
        ticket = b0.send_async(1, b"payload")  # returns immediately
        enqueue_dt = time.monotonic() - t0
        assert enqueue_dt < 0.25, f"send_async blocked {enqueue_dt:.2f}s"
        data = b1.recv_from(0)
        ticket.wait()
        assert bytes(data) == b"payload"
        assert time.monotonic() - t0 >= 0.3  # the worker slept
    finally:
        fault_injection.injector.clear()
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_timeout_during_recv_into(monkeypatch):
    """A silent peer must trip the bounded recv_into within
    HOROVOD_TCP_TIMEOUT_SECONDS — the zero-copy path keeps the
    dead-peer heartbeat."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "0.5")
    server, (b0, b1) = _tcp_pair("t_silent_into", monkeypatch)
    try:
        buf = np.zeros(64, np.float32)
        t0 = time.monotonic()
        with pytest.raises(TransportError, match="no progress"):
            b0.recv_into_from(1, buf)
        assert time.monotonic() - t0 < 2.0
        # the timed-out peer is severed: fail fast, same type
        with pytest.raises(TransportError):
            b0.recv_into_from(1, buf)
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_timeout_mid_frame_during_recv_into(monkeypatch):
    """A peer that sends a frame header then goes silent: recv_into is
    already parked on the payload and must still respect the idle
    deadline."""
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "0.5")
    server, (b0, b1) = _tcp_pair("t_half_frame", monkeypatch)
    try:
        import struct as _struct

        # Raw header promising 1024 bytes, then silence.
        b1.peers[0].sendall(_struct.pack("<Q", 1024))
        buf = bytearray(1024)
        with pytest.raises(TransportError, match="no progress"):
            b0.recv_into_from(1, buf)
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_drop_on_pipelined_send_hangs_peer_into_timeout(monkeypatch):
    """A dropped segment means the receiver's recv_into starves: it
    must fail via the bounded timeout, not hang."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "4096")
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "1")
    server, (b0, b1) = _tcp_pair("t_drop_seg", monkeypatch)
    try:
        fault_injection.injector.install(
            [Rule(action="drop", rank=0, peer=1, op="send", after=1)]
        )
        results, errors = _ring_pair_allreduce(b0, b1)
        assert isinstance(errors[1], TransportError), errors
        # Either the starved recv's own idle timeout fires, or the
        # other rank times out first and its sever delivers a FIN —
        # both are clean bounded TransportError failures.
        assert ("no progress" in str(errors[1])
                or "closed connection" in str(errors[1])), errors
    finally:
        fault_injection.injector.clear()
        b0.shutdown()
        b1.shutdown()
        server.stop()


# ---------------------------------------------------------------------------
# fault injection x the pipelined executor path: a transport death on one
# channel while another channel is mid-collective must fail EVERY pending
# handle on EVERY channel with the transport reason, kill the executors,
# and leave no thread hung (ISSUE 4 satellite).
def _tcp_engines(scope, monkeypatch, nranks=2):
    """Two real Engines over a TCP mesh in one process (the executor
    pool + channel-tagged data plane end to end)."""
    from horovod_tpu.engine.engine import Engine

    server, backends = _tcp_pair(scope, monkeypatch)
    engines = [Engine(rank=r, size=nranks, backend=backends[r])
               for r in range(nranks)]
    for e in engines:
        e.cycle_time_s = 0.001
    start_errs = []

    def _start(e):
        try:
            e.start()
        except BaseException as exc:  # pragma: no cover - init bug
            start_errs.append(exc)

    ts = [threading.Thread(target=_start, args=(e,)) for e in engines]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not start_errs, start_errs
    return server, engines


def _run_pipelined_workload(engines, count=1 << 14, ops=2):
    """Each rank enqueues `ops` allreduces (one response per op with
    fusion disabled -> round-robin over both channels), then waits.
    Returns per-rank lists of results-or-exceptions."""
    out = [[None] * ops for _ in engines]

    def w(i, eng):
        handles = [
            eng.enqueue_allreduce(
                np.full(count, float(i + 1), np.float32), name=f"c{k}")
            for k in range(ops)
        ]
        for k, h in enumerate(handles):
            try:
                out[i][k] = eng.synchronize(h, timeout=60)
            except BaseException as e:  # noqa: BLE001
                out[i][k] = e
    ts = [threading.Thread(target=w, args=(i, e))
          for i, e in enumerate(engines)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    return out


def _shutdown_engines(engines):
    ts = [threading.Thread(target=e.shutdown) for e in engines]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)


def test_sever_on_one_channel_fails_every_channel(monkeypatch):
    """Sever mid-stream with two channels in flight: all pending handles
    on both ranks fail with the transport reason, post-death enqueues
    fail fast, and the executor threads exit — no hang."""
    monkeypatch.setenv("HOROVOD_CHANNEL_POLICY", "rr")
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1")
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "4096")
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "5")
    server, engines = _tcp_engines("t_exec_sever", monkeypatch)
    try:
        # The sever lands partway into the segmented data stream (the
        # delay keeps rank 1's contributions slow enough that both
        # channels are still mid-collective when it fires).
        fault_injection.injector.install([
            Rule(action="delay", rank=1, peer=0, op="send", secs=0.02),
            Rule(action="sever", rank=0, peer=1, op="send", after=15),
        ])
        out = _run_pipelined_workload(engines)
        # Every handle either completed BEFORE the fault landed or
        # failed with the transport reason — never a hang (None /
        # TimeoutError), and the fault must have hit someone.
        failures = 0
        for r, per_rank in enumerate(out):
            for k, res in enumerate(per_rank):
                assert res is not None, (r, k, "synchronize hung")
                assert not isinstance(res, TimeoutError), (r, k, res)
                if isinstance(res, HorovodInternalError):
                    failures += 1
                    assert ("peer" in str(res) or "severed" in str(res)
                            or "shut down" in str(res)), (r, k, res)
                else:
                    assert isinstance(res, np.ndarray), (r, k, res)
        assert failures > 0, out
        # Terminal status latched: a post-death enqueue fails immediately.
        h = engines[0].enqueue_allreduce(
            np.ones(8, np.float32), name="after_death")
        with pytest.raises(HorovodInternalError):
            engines[0].synchronize(h, timeout=30)
    finally:
        fault_injection.injector.clear()
        _shutdown_engines(engines)
        server.stop()
    for eng in engines:
        for ex in eng._executors.values():
            assert not ex.thread.is_alive(), (
                f"rank {eng.rank} channel {ex.channel} executor leaked")


def test_timeout_on_one_channel_fails_every_channel(monkeypatch):
    """A dropped segment starves one channel's recv into the bounded
    timeout; the resulting TransportError must still take down every
    channel's pending handles on both ranks within the bound."""
    monkeypatch.setenv("HOROVOD_CHANNEL_POLICY", "rr")
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1")
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "4096")
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "1")
    server, engines = _tcp_engines("t_exec_drop", monkeypatch)
    try:
        fault_injection.injector.install([
            Rule(action="drop", rank=0, peer=1, op="send", after=15),
        ])
        t0 = time.monotonic()
        out = _run_pipelined_workload(engines)
        assert time.monotonic() - t0 < 60, "not bounded"
        failures = 0
        for r, per_rank in enumerate(out):
            for k, res in enumerate(per_rank):
                assert res is not None, (r, k, "synchronize hung")
                assert not isinstance(res, TimeoutError), (r, k, res)
                if isinstance(res, HorovodInternalError):
                    failures += 1
                else:
                    assert isinstance(res, np.ndarray), (r, k, res)
        assert failures > 0, out
    finally:
        fault_injection.injector.clear()
        _shutdown_engines(engines)
        server.stop()
    for eng in engines:
        for ex in eng._executors.values():
            assert not ex.thread.is_alive()


def test_pipelined_engines_healthy_path_correctness(monkeypatch):
    """Control experiment for the two tests above: the same 2-channel
    TCP engine pair with no fault injected completes correctly."""
    monkeypatch.setenv("HOROVOD_CHANNEL_POLICY", "rr")
    monkeypatch.setenv("HOROVOD_NUM_CHANNELS", "2")
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1")
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "4096")
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "30")
    server, engines = _tcp_engines("t_exec_ok", monkeypatch)
    try:
        out = _run_pipelined_workload(engines, ops=4)
        for per_rank in out:
            for res in per_rank:
                assert isinstance(res, np.ndarray), res
                np.testing.assert_allclose(res[:4], np.full(4, 3.0))
    finally:
        _shutdown_engines(engines)
        server.stop()


# ---------------------------------------------------------------------------
# chaos: kill 1 of 4 real workers mid-step (the acceptance scenario)
_CHAOS_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np
    import horovod_tpu as hvd
    from horovod_tpu.common import fault_injection
    from horovod_tpu.common.exceptions import HorovodInternalError

    STEPS = int(os.environ.get("TEST_CHAOS_STEPS", "50"))
    hvd.init()
    try:
        for step in range(STEPS):
            out = hvd.allreduce(np.ones(8, np.float32), name="g")
            fault_injection.advance_step()  # doomed rank dies here
        sys.exit(0)
    except HorovodInternalError:
        sys.exit(42)   # the contract: collective failure -> HIE
    except ConnectionError:
        sys.exit(13)   # raw transport error leaked: forbidden
    except Exception:
        sys.exit(14)
""")


@pytest.mark.slow
def test_chaos_kill_one_of_four_workers(tmp_path):
    """Kill 1 of 4 subprocess workers mid-step; every survivor must
    raise HorovodInternalError within 2x HOROVOD_TCP_TIMEOUT_SECONDS of
    the death — no indefinite hang, no raw ConnectionError escaping."""
    from horovod_tpu.runner.hosts import parse_hosts, get_host_assignments
    from horovod_tpu.runner.launch import slot_env
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    timeout_s = 5.0
    np_world = 4
    kill_rank = 2

    server = RendezvousServer()
    port = server.start()
    script = tmp_path / "worker.py"
    script.write_text(_CHAOS_WORKER)

    hosts = parse_hosts(f"localhost:{np_world}")
    slots = get_host_assignments(hosts, np_world)
    procs = {}
    try:
        for slot in slots:
            env = dict(os.environ)
            env.update(slot_env(slot, "127.0.0.1", port))
            env["PYTHONPATH"] = REPO
            env["HVDRUN_FORCE_LOCAL"] = "1"
            env["HOROVOD_CYCLE_TIME"] = "1"
            env["HOROVOD_TCP_TIMEOUT_SECONDS"] = str(timeout_s)
            env.pop("HOROVOD_FAULT_INJECT", None)
            if slot.rank == kill_rank:
                env["HOROVOD_FAULT_INJECT"] = "kill:step=3"
            procs[slot.rank] = subprocess.Popen(
                [sys.executable, str(script)], env=env,
            )
        # The doomed worker exits first (around step 3)...
        t_death = None
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            if procs[kill_rank].poll() is not None:
                t_death = time.monotonic()
                break
            time.sleep(0.1)
        assert t_death is not None, "doomed worker never died"
        assert procs[kill_rank].returncode == 1

        # ...and every survivor must fail CLEANLY within 2x the timeout.
        budget = 2 * timeout_s + 30  # + slack for jax import/teardown
        for rank, proc in procs.items():
            if rank == kill_rank:
                continue
            remaining = budget - (time.monotonic() - t_death)
            try:
                proc.wait(timeout=max(remaining, 1.0))
            except subprocess.TimeoutExpired:
                pytest.fail(f"survivor rank {rank} hung past the bound")
        codes = {r: p.returncode for r, p in procs.items() if r != kill_rank}
        assert all(c == 42 for c in codes.values()), (
            f"survivors must exit via HorovodInternalError (42): {codes}"
        )
    finally:
        for p in procs.values():
            if p.poll() is None:
                p.kill()
        server.stop()
