"""Subset-communicator (process set) tests
(ref: horovod/common/basics.py:33-65 init with sub-communicator)."""
import numpy as np
import pytest

from horovod_tpu.runner import run

ENV = {"HOROVOD_CYCLE_TIME": "1", "JAX_PLATFORMS": "cpu"}


def test_subset_communicator_process_mode():
    """3 workers; ranks 0 and 2 form a communicator of size 2 and
    allreduce within it; rank 1 stays out."""

    def fn():
        import os

        import numpy as np

        import horovod_tpu as hvd

        world_rank = int(os.environ["HOROVOD_RANK"])
        if world_rank == 1:
            return "outside"
        hvd.init(ranks=[0, 2])
        assert hvd.size() == 2
        assert hvd.rank() == (0 if world_rank == 0 else 1)
        out = hvd.allreduce(np.ones(3, np.float32) * (world_rank + 1),
                            average=False)
        # contributions: world ranks 0 (=1.0) and 2 (=3.0) -> 4.0
        return out.tolist()

    out = run(fn, np=3, extra_env=ENV)
    assert out[1] == "outside"
    assert out[0] == out[2] == [4.0, 4.0, 4.0]


def test_non_member_init_rejected():
    def fn():
        import os

        import horovod_tpu as hvd

        world_rank = int(os.environ["HOROVOD_RANK"])
        if world_rank == 0:
            try:
                hvd.init(ranks=[1])
                return "no-error"
            except ValueError as e:
                return "rejected"
        hvd.init(ranks=[1])
        assert hvd.size() == 1 and hvd.rank() == 0
        return "member"

    out = run(fn, np=2, extra_env=ENV)
    assert out == ["rejected", "member"]


def test_subset_mesh_mode(hvd_mesh):
    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init(ranks=[0, 1, 2, 3])
    assert hvd.size() == 4
    hvd.shutdown()
