"""Serving plane tests (docs/serving.md): continuous batcher edge
cases, bounded admission + HTTP backpressure, weight sources + the
staged hot-swap loader, the extensible metrics-endpoint views, env
knobs, and an end-to-end 2-rank serve() with a mid-traffic hot swap.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common.telemetry import MetricsRegistry
from horovod_tpu.serving.batcher import (
    STATUS_DEADLINE, STATUS_OK, AdmissionQueue, ContinuousBatcher,
    InferenceRequest,
)


def _mk(reg=None, maxsize=16, max_batch=4, max_tokens=1000,
        max_delay_s=0.2):
    reg = reg or MetricsRegistry()
    q = AdmissionQueue(maxsize, registry=reg)
    b = ContinuousBatcher(q, max_batch=max_batch, max_tokens=max_tokens,
                          max_delay_s=max_delay_s, registry=reg)
    return reg, q, b


# ---------------------------------------------------------------------------
# Batcher edge cases (the satellite checklist)

def test_empty_queue_wakeup_on_enqueue():
    """next_batch parks on an empty queue and an offer wakes it NOW —
    no poll tick, no full max-delay stall before the first take."""
    _, q, b = _mk(max_delay_s=0.05)
    t0 = time.monotonic()

    def later():
        time.sleep(0.15)
        q.offer(InferenceRequest("x", timeout_s=5))

    threading.Thread(target=later).start()
    batch = b.next_batch(wait_timeout=10.0)
    took = time.monotonic() - t0
    assert batch is not None and len(batch) == 1
    # 0.15s arrival + 0.05s coalesce window + slack; a 1s+ result would
    # mean the wait polled or slept through the enqueue.
    assert took < 1.0, took


def test_empty_queue_timeout_returns_none():
    _, q, b = _mk()
    t0 = time.monotonic()
    assert b.next_batch(wait_timeout=0.05) is None
    assert time.monotonic() - t0 < 2.0


def test_deadline_expired_dropped_before_dispatch():
    """An admitted request whose deadline lapses in the queue is
    completed with status=deadline and COUNTED, and next_batch never
    hands it out."""
    reg, q, b = _mk()
    dead = InferenceRequest("late", timeout_s=0.01)
    live = InferenceRequest("fine", timeout_s=10)
    q.offer(dead)
    q.offer(live)
    time.sleep(0.05)
    batch = b.next_batch(wait_timeout=1.0)
    assert [r.payload for r in batch] == ["fine"]
    assert dead.done and dead.status == STATUS_DEADLINE
    snap = reg.snapshot()
    assert snap[
        'horovod_serving_requests_total{status="deadline"}'] == 1, snap


def test_deadline_drop_only_path_returns_none():
    """A queue holding ONLY expired requests yields no batch (and every
    dropped request is answered), not an empty list."""
    _, q, b = _mk()
    reqs = [InferenceRequest(i, timeout_s=0.01) for i in range(3)]
    for r in reqs:
        q.offer(r)
    time.sleep(0.05)
    assert b.next_batch(wait_timeout=0.05) is None
    assert all(r.status == STATUS_DEADLINE for r in reqs)


def test_max_size_beats_max_delay():
    """A full batch dispatches immediately — the max-delay window is a
    bound, not a floor (the race the satellite names)."""
    _, q, b = _mk(max_batch=3, max_delay_s=5.0)
    for i in range(5):
        q.offer(InferenceRequest(i, timeout_s=30))
    t0 = time.monotonic()
    batch = b.next_batch(wait_timeout=1.0)
    assert len(batch) == 3
    assert time.monotonic() - t0 < 1.0  # nowhere near the 5s window
    # The remainder is still queued for the next batch, FIFO.
    assert [r.payload for r in b.next_batch(1.0)] == [3, 4]


def test_max_delay_closes_partial_batch():
    _, q, b = _mk(max_batch=100, max_delay_s=0.05)
    q.offer(InferenceRequest("only", timeout_s=30))
    t0 = time.monotonic()
    batch = b.next_batch(wait_timeout=1.0)
    took = time.monotonic() - t0
    assert len(batch) == 1
    assert took < 1.0, took


def test_single_request_latency_bounded_by_max_delay():
    """The satellite's latency bound: a lone request waits AT MOST the
    coalescing delay, and max_delay=0 dispatches with no wait at all."""
    _, q, b = _mk(max_delay_s=0.0)
    q.offer(InferenceRequest("now", timeout_s=30))
    t0 = time.monotonic()
    assert len(b.next_batch(1.0)) == 1
    assert time.monotonic() - t0 < 0.1


def test_token_budget_caps_batch():
    _, q, b = _mk(max_batch=100, max_tokens=10, max_delay_s=0.5)
    for tok in (4, 4, 4):
        q.offer(InferenceRequest("p", tokens=tok, timeout_s=30))
    batch = b.next_batch(1.0)
    # 4+4 admitted; the third would exceed 10 and waits its turn.
    assert len(batch) == 2
    assert len(b.next_batch(1.0)) == 1


def test_oversized_single_request_still_dispatches():
    _, q, b = _mk(max_tokens=10)
    q.offer(InferenceRequest("big", tokens=999, timeout_s=30))
    assert len(b.next_batch(1.0)) == 1


def test_admission_queue_bound_and_requeue_bypass():
    _, q, _ = _mk(maxsize=2)
    r1, r2, r3 = (InferenceRequest(i, timeout_s=30) for i in range(3))
    assert q.offer(r1) and q.offer(r2)
    assert not q.offer(r3)  # full -> the frontend's 429
    # Rerouted (already-admitted) work re-enters at the HEAD past the
    # bound — an eviction retry must never be 429'd.
    q._pop_locked()
    taken = [q._pop_locked()]
    q.requeue_front([r1] + taken)
    assert q.depth() == 2
    assert q._peek_locked() is r1


def test_first_completion_wins():
    r = InferenceRequest("x", timeout_s=30)
    r.complete({"output": 1}, STATUS_OK)
    r.complete(None, STATUS_DEADLINE, "late loser")
    assert r.status == STATUS_OK and r.result == {"output": 1}


# ---------------------------------------------------------------------------
# Frontend: HTTP admission / backpressure / deadlines

def _http(port, method, path, body=None):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request(method, path,
                 json.dumps(body) if body is not None else None)
    resp = conn.getresponse()
    out = (resp.status, json.loads(resp.read() or b"null"))
    conn.close()
    return out


def _frontend(monkeypatch, **env):
    from horovod_tpu.serving.frontend import InferenceFrontend

    for k, v in env.items():
        monkeypatch.setenv(k, str(v))
    return InferenceFrontend(port=0, registry=MetricsRegistry()).start()


def test_frontend_backpressure_429(monkeypatch):
    fe = _frontend(monkeypatch, HOROVOD_SERVING_QUEUE_DEPTH=1,
                   HOROVOD_SERVING_REQUEST_TIMEOUT_SECONDS=30)
    try:
        assert fe.submit("a") is not None
        # Queue full: HTTP answers 429 + Retry-After without blocking.
        code, body = _http(fe.port, "POST", "/v1/infer", {"inputs": "b"})
        assert code == 429, body
        snap = fe.registry.snapshot()
        assert snap[
            'horovod_serving_requests_total{status="rejected"}'] == 1
    finally:
        fe.stop()


def test_frontend_deadline_504(monkeypatch):
    fe = _frontend(monkeypatch,
                   HOROVOD_SERVING_REQUEST_TIMEOUT_SECONDS=0.1)
    try:
        # Nobody dispatches: the request comes back 504 AT its deadline
        # (undispatched -> no grace window), counted exactly once even
        # though the batcher would also have dropped it.
        t0 = time.monotonic()
        code, body = _http(fe.port, "POST", "/v1/infer", {"inputs": 1})
        assert code == 504, body
        assert time.monotonic() - t0 < 5
        # The late batcher pass finds the corpse and must NOT recount.
        assert fe.batcher.next_batch(0.05) is None
        snap = fe.registry.snapshot()
        assert snap[
            'horovod_serving_requests_total{status="deadline"}'] == 1
    finally:
        fe.stop()


def test_frontend_client_cannot_raise_deadline(monkeypatch):
    fe = _frontend(monkeypatch,
                   HOROVOD_SERVING_REQUEST_TIMEOUT_SECONDS=0.5)
    try:
        req = fe.submit("x", timeout_s=9999)
        assert req.deadline - req.enqueued <= 0.5 + 1e-6
        req2 = fe.submit("y", timeout_s=0.1)
        assert req2.deadline - req2.enqueued <= 0.1 + 1e-6
    finally:
        fe.stop()


def test_frontend_inflight_tracks_programmatic_submits(monkeypatch):
    """The inflight gauge derives from the request futures, so the
    programmatic submit() path (no infer() handler to decrement)
    cannot inflate it forever."""
    fe = _frontend(monkeypatch)
    try:
        reqs = [fe.submit(i) for i in range(3)]
        assert fe.registry.gauge(
            "horovod_serving_inflight_requests").value == 3
        for r in reqs[:2]:
            r.complete({"output": 0}, STATUS_OK)
        assert fe.registry.gauge(
            "horovod_serving_inflight_requests").value == 1
        reqs[2].complete(None, STATUS_DEADLINE, "x")
        assert fe.basic_status()["inflight"] == 0
    finally:
        fe.stop()


def test_frontend_healthz_and_stop(monkeypatch):
    fe = _frontend(monkeypatch)
    try:
        code, body = _http(fe.port, "GET", "/healthz")
        assert code == 200 and body["queue_depth"] == 0
        code, body = _http(fe.port, "POST", "/admin/stop")
        assert code == 200 and body["stopping"]
        code, body = _http(fe.port, "POST", "/v1/infer", {"inputs": 1})
        assert code == 503
    finally:
        fe.stop()


# ---------------------------------------------------------------------------
# Weight sources + staged loader

def test_publish_and_checkpoint_weight_source(tmp_path):
    from horovod_tpu.serving.weights import (CheckpointWeightSource,
                                             publish_weights)

    src = CheckpointWeightSource(
        str(tmp_path),
        to_weights=lambda step, objects, trees: {
            "w": float(np.asarray(trees["w"][0])), "step": step})
    assert src.poll() is None
    publish_weights(str(tmp_path), 7, {"w": [np.float64(3.5)]},
                    objects={"note": "v7"})
    assert src.poll() == 7
    w = src.load(7)
    assert w == {"w": 3.5, "step": 7}
    # Default converter hands back (objects, trees) unchanged.
    raw = CheckpointWeightSource(str(tmp_path))
    objects, trees = raw.load(7)
    assert objects == {"note": "v7"}
    assert float(trees["w"][0]) == 3.5
    # Newer publish wins the poll.
    publish_weights(str(tmp_path), 9, {"w": [np.float64(4.0)]})
    assert src.poll() == 9


def test_background_loader_stages_and_supersedes(tmp_path):
    from horovod_tpu.serving.weights import (BackgroundLoader,
                                             CheckpointWeightSource,
                                             publish_weights)

    publish_weights(str(tmp_path), 1, {"w": [np.float64(1.0)]})
    publish_weights(str(tmp_path), 2, {"w": [np.float64(2.0)]})
    src = CheckpointWeightSource(
        str(tmp_path),
        to_weights=lambda s, o, t: float(np.asarray(t["w"][0])))
    loader = BackgroundLoader(src)
    loader.prepare(1)
    deadline = time.monotonic() + 10
    while loader.staged() != 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert loader.staged() == 1
    # A newer prepare supersedes; commit takes exactly the staged step.
    loader.prepare(2)
    while loader.staged() != 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert loader.take(2) == 2.0
    with pytest.raises(RuntimeError):
        loader.take(1)


def test_background_loader_error_reported(tmp_path):
    from horovod_tpu.serving.weights import (BackgroundLoader,
                                             CheckpointWeightSource)

    loader = BackgroundLoader(CheckpointWeightSource(str(tmp_path)))
    loader.prepare(99)  # no such manifest
    deadline = time.monotonic() + 10
    while loader.error() is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert "99" in loader.error()
    assert loader.staged() is None


# ---------------------------------------------------------------------------
# Batch split math + verdict parsing

def test_slice_bounds_tile_exactly():
    from horovod_tpu.serving.replicas import slice_bounds

    for n in (0, 1, 2, 5, 7, 32):
        for w in (1, 2, 3, 4, 8):
            cuts = [slice_bounds(n, w, i) for i in range(w)]
            assert cuts[0][0] == 0 and cuts[-1][1] == n
            for (a, b), (c, d) in zip(cuts, cuts[1:]):
                assert b == c and a <= b


def test_failed_rank_from_error():
    from horovod_tpu.common.exceptions import (HorovodInternalError,
                                               TransportError)
    from horovod_tpu.serving.replicas import failed_rank_from_error

    assert failed_rank_from_error(
        TransportError("boom", peer=3)) == 3
    assert failed_rank_from_error(HorovodInternalError(
        "rank 2 (host x) declared dead by rank 0: no heartbeat")) == 2
    assert failed_rank_from_error(HorovodInternalError("boom")) is None


def test_swap_state_machine_piggybacks_and_replays():
    """The coordinator's hot-swap state machine: commit only travels
    after EVERY reply staged the target, and an eviction mid-swap
    (half the survivors may have flipped already) re-proves staged
    state on the new communicator before another commit."""
    from horovod_tpu.serving.replicas import ServingCoordinator

    coord = ServingCoordinator.__new__(ServingCoordinator)
    coord._swap_target = 10
    coord._all_staged = False

    def note(replies):
        ServingCoordinator._note_staged(coord, replies)

    rep = lambda staged, committed: {"staged": staged,  # noqa: E731
                                     "committed": committed}
    # Partial staging: no commit yet.
    note([rep(10, -1), rep(None, -1)])
    assert coord._all_staged is False and coord._swap_target == 10
    # All staged: the next round may attach commit.
    note([rep(10, -1), rep(10, -1)])
    assert coord._all_staged is True
    # Eviction mid-commit: recovery resets _all_staged; a half-flipped
    # reply set (one committed, one only staged) re-proves and the
    # idempotent commit replays.
    coord._all_staged = False
    note([rep(10, 10), rep(10, -1)])
    assert coord._all_staged is True and coord._swap_target == 10
    # Everyone committed: the swap is done.
    note([rep(10, 10), rep(10, 10)])
    assert coord._swap_target is None and coord._all_staged is False


# ---------------------------------------------------------------------------
# Extensible metrics-endpoint views (the add_view satellite)

def test_metrics_server_add_view_and_404_listing():
    from horovod_tpu.common.metrics_export import MetricsHTTPServer

    reg = MetricsRegistry()
    srv = MetricsHTTPServer(0, registry=reg,
                            status_fn=lambda: {"ok": 1}).start()
    try:
        srv.add_view("serving", lambda: {"role": "coordinator"})
        code, body = _http(srv.port, "GET", "/serving")
        assert code == 200 and body == {"role": "coordinator"}
        # ctor sugar still lands on /status
        code, body = _http(srv.port, "GET", "/status")
        assert code == 200 and body == {"ok": 1}
        # string providers pass through verbatim (the /trace shape)
        srv.add_view("trace", lambda: '{"traceEvents": []}')
        code, body = _http(srv.port, "GET", "/trace")
        assert code == 200 and body == {"traceEvents": []}
        # unknown views 404 and NAME the registered ones
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        conn.request("GET", "/nope")
        resp = conn.getresponse()
        text = resp.read().decode()
        assert resp.status == 404 and "/serving" in text, text
        conn.close()
        # conditional removal: a replaced provider's stale remove is a
        # no-op, unconditional remove detaches
        old = srv.get_view("serving")
        srv.add_view("serving", lambda: {"role": "new"})
        srv.remove_view("serving", old)
        assert srv.get_view("serving") is not None
        srv.remove_view("serving")
        assert srv.get_view("serving") is None
        with pytest.raises(ValueError):
            srv.add_view("metrics", lambda: {})
        with pytest.raises(ValueError):
            srv.add_view("bad/name", lambda: {})
    finally:
        srv.stop()


def test_engine_gauge_detach_is_conditional():
    """The stale-gauge fix: a dying Engine's shutdown must not wipe a
    replacement's gauge registration (teardown overlapping re-init on
    a shared registry)."""
    from horovod_tpu.engine.engine import Engine

    reg = MetricsRegistry()
    eng = Engine(rank=0, size=1, registry=reg)
    eng.start()
    try:
        replacement = lambda: 42.0  # noqa: E731
        for name in ("horovod_tensor_queue_depth",
                     "horovod_last_cycle_age_seconds",
                     "horovod_inflight_responses"):
            reg.gauge(name).set_function(replacement)
    finally:
        eng.shutdown()
    for name in ("horovod_tensor_queue_depth",
                 "horovod_last_cycle_age_seconds",
                 "horovod_inflight_responses"):
        assert reg.gauge(name).value == 42.0, name


# ---------------------------------------------------------------------------
# Env knobs (the parse-test satellite)

def test_serving_env_knob_parsing(monkeypatch):
    from horovod_tpu.utils import env as env_cfg

    # Defaults.
    for k in ("HOROVOD_SERVING_PORT", "HOROVOD_SERVING_MAX_BATCH",
              "HOROVOD_SERVING_MAX_BATCH_TOKENS",
              "HOROVOD_SERVING_MAX_DELAY_MS",
              "HOROVOD_SERVING_QUEUE_DEPTH",
              "HOROVOD_SERVING_REQUEST_TIMEOUT_SECONDS",
              "HOROVOD_SERVING_WEIGHT_REFRESH_SECONDS"):
        monkeypatch.delenv(k, raising=False)
    assert env_cfg.serving_port() == -1
    assert env_cfg.serving_max_batch() == 32
    assert env_cfg.serving_max_batch_tokens() == 16384
    assert env_cfg.serving_max_delay_ms() == 5.0
    assert env_cfg.serving_queue_depth() == 256
    assert env_cfg.serving_request_timeout() == 30.0
    assert env_cfg.serving_weight_refresh_seconds() == 10.0
    assert env_cfg.serving_addr() == "127.0.0.1"
    # Explicit values + floors.
    monkeypatch.setenv("HOROVOD_SERVING_PORT", "8500")
    monkeypatch.setenv("HOROVOD_SERVING_MAX_BATCH", "0")
    monkeypatch.setenv("HOROVOD_SERVING_MAX_BATCH_TOKENS", "-5")
    monkeypatch.setenv("HOROVOD_SERVING_MAX_DELAY_MS", "-1")
    monkeypatch.setenv("HOROVOD_SERVING_QUEUE_DEPTH", "0")
    monkeypatch.setenv("HOROVOD_SERVING_REQUEST_TIMEOUT_SECONDS", "0")
    monkeypatch.setenv("HOROVOD_SERVING_WEIGHT_REFRESH_SECONDS", "0")
    assert env_cfg.serving_port() == 8500
    assert env_cfg.serving_max_batch() == 1
    assert env_cfg.serving_max_batch_tokens() == 1
    assert env_cfg.serving_max_delay_ms() == 0.0
    assert env_cfg.serving_queue_depth() == 1
    assert env_cfg.serving_request_timeout() == 0.001
    assert env_cfg.serving_weight_refresh_seconds() == 0.0
    # The HVD_TPU_ alias prefix works here like everywhere else.
    monkeypatch.delenv("HOROVOD_SERVING_PORT")
    monkeypatch.setenv("HVD_TPU_SERVING_PORT", "8600")
    assert env_cfg.serving_port() == 8600


def test_transport_default_is_auto(monkeypatch):
    from horovod_tpu.utils import env as env_cfg

    monkeypatch.delenv("HOROVOD_TRANSPORT", raising=False)
    monkeypatch.delenv("HVD_TPU_TRANSPORT", raising=False)
    assert env_cfg.transport_mode() == "auto"
    monkeypatch.setenv("HOROVOD_TRANSPORT", "tcp")
    assert env_cfg.transport_mode() == "tcp"
    monkeypatch.setenv("HOROVOD_TRANSPORT", "bogus")
    assert env_cfg.transport_mode() == "auto"


# ---------------------------------------------------------------------------
# End-to-end: 2-rank mesh, HTTP traffic, mid-traffic hot swap

def test_serve_two_ranks_with_hot_swap(tmp_path):
    """Real 2-process mesh: concurrent HTTP clients through the front
    door, then a publish_weights mid-traffic; every request answers
    200, the last answers provably carry the new weights, and zero
    requests are dropped across the swap."""
    from horovod_tpu.runner import run

    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    results = run(_swap_worker, np=2, extra_env={
        "JAX_PLATFORMS": "cpu",
        "HOROVOD_CYCLE_TIME": "1",
        "HOROVOD_SERVING_MAX_DELAY_MS": "5",
        "HOROVOD_SERVING_WEIGHT_REFRESH_SECONDS": "0.1",
        "TEST_CKPT_DIR": str(tmp_path),
        # The worker unpickles a function living in this test module.
        "PYTHONPATH": os.pathsep.join([repo, here]),
    })
    assert len(results) == 2
    for rep in results:
        assert rep["weight_step"] == 50, rep
        assert rep["evictions"] == 0, rep


def _swap_worker():
    import http.client

    import horovod_tpu as hvd
    from horovod_tpu.common import basics
    from horovod_tpu.common.metrics_export import MetricsHTTPServer
    from horovod_tpu.serving.weights import (CheckpointWeightSource,
                                             publish_weights)

    hvd.init()
    ckpt_dir = os.environ["TEST_CKPT_DIR"]
    source = CheckpointWeightSource(
        ckpt_dir,
        to_weights=lambda s, o, t: {"w": float(np.asarray(t["w"][0]))})

    def model_fn(weights, payloads):
        return [weights["w"] * p for p in payloads]

    outcome = {}
    port = None
    if hvd.rank() == 0:
        # Pick a free port up front so the client thread knows it.
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()

        def client():
            from horovod_tpu.serving import replicas

            deadline = time.monotonic() + 30
            while (replicas.current() is None
                   or replicas.current().rounds == 0):
                time.sleep(0.02)
                assert time.monotonic() < deadline, "serving never started"
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            vals = []
            for i in range(16):
                if i == 5:
                    publish_weights(ckpt_dir, 50,
                                    {"w": [np.float64(5.0)]})
                conn.request("POST", "/v1/infer",
                             json.dumps({"inputs": 2.0}))
                r = conn.getresponse()
                body = json.loads(r.read())
                assert r.status == 200, (r.status, body)
                vals.append((body["output"], body["weight_step"]))
                time.sleep(0.05)
            outcome["vals"] = vals

        threading.Thread(target=client, daemon=True).start()
    report = hvd.serving.serve(model_fn, weights={"w": 1.0},
                               weight_source=source, port=port,
                               max_requests=16, tick_seconds=0.05)
    if hvd.rank() == 0:
        vals = outcome["vals"]
        assert vals[0] == (2.0, -1), vals
        assert vals[-1] == (10.0, 50), vals
        assert all(v in ((2.0, -1), (10.0, 50)) for v in vals), vals
        # The /serving view unregisters when serve() returns — a stale
        # view would pin the dead plane and answer with frozen state.
        for exp in basics.engine()._exporters:
            if isinstance(exp, MetricsHTTPServer):
                assert exp.get_view("serving") is None
    hvd.shutdown()
    return report
