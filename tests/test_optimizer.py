"""DistributedOptimizer / distributed_value_and_grad tests
(ref test model: test/test_torch.py optimizer tests — distributed SGD
equals serial SGD on the combined batch)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.utils.compat import shard_map


@pytest.fixture(autouse=True)
def _init():
    hvd.shutdown()
    hvd.init()
    yield
    hvd.shutdown()


N = 8


def _loss(w, x, y):
    pred = x @ w
    return jnp.mean((pred - y) ** 2)


def test_distributed_sgd_equals_global_sgd():
    # DP-trained step (grads averaged over shards) must equal single-chip
    # SGD on the full batch (ref: the core Horovod contract, README.rst:80-99).
    rng = np.random.RandomState(0)
    w0 = jnp.asarray(rng.randn(4).astype(np.float32))
    X = jnp.asarray(rng.randn(N * 2, 4).astype(np.float32))
    Y = jnp.asarray(rng.randn(N * 2).astype(np.float32))

    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    opt_state = tx.init(w0)

    def step(w, state, x, y):
        grads = jax.grad(_loss)(w, x, y)
        red = hvd.allreduce(grads)  # average across shards
        updates, state = optax.sgd(0.1).update(red, state, w)
        return optax.apply_updates(w, updates), state

    w_dp, _ = shard_map(
        lambda w, s, x, y: step(w, s, x, y),
        mesh=hvd.mesh(),
        in_specs=(P(), P(), P("hvd"), P("hvd")),
        out_specs=(P(), P()),
    )(w0, opt_state, X, Y)

    # serial: full-batch grad = mean of shard grads (each shard has equal
    # element count, and _loss is a mean)
    shard_grads = [
        jax.grad(_loss)(w0, X[i * 2 : (i + 1) * 2], Y[i * 2 : (i + 1) * 2])
        for i in range(N)
    ]
    g_serial = jnp.mean(jnp.stack(shard_grads), axis=0)
    w_serial = w0 - 0.1 * g_serial
    np.testing.assert_allclose(np.asarray(w_dp), np.asarray(w_serial), rtol=1e-5)


def test_distributed_optimizer_transform():
    # The optax-wrapper form: tx.update allreduces grads internally.
    w0 = jnp.ones(3)
    tx = hvd.DistributedOptimizer(optax.sgd(1.0))
    state = tx.init(w0)

    def upd(g, s, w):
        updates, s2 = tx.update(g, s, w)
        return optax.apply_updates(w, updates)

    # per-shard grads = axis index → average = 3.5
    g = jnp.repeat(jnp.arange(N, dtype=jnp.float32), 3)
    out = shard_map(
        lambda g_, s, w: upd(g_.reshape(3), s, w),
        mesh=hvd.mesh(),
        in_specs=(P("hvd"), P(), P()),
        out_specs=P(),
    )(g, state, w0)
    np.testing.assert_allclose(np.asarray(out), np.ones(3) - 3.5, rtol=1e-6)


def test_distributed_value_and_grad():
    vg = hvd.distributed_value_and_grad(lambda w, x: jnp.sum(w * x))
    x = jnp.arange(N, dtype=jnp.float32)

    def f(w, x_):
        val, g = vg(w, x_)
        return val[None], g  # per-shard loss, replicated grad

    vals, g = shard_map(
        f, mesh=hvd.mesh(), in_specs=(P(), P("hvd")), out_specs=(P("hvd"), P()),
    )(jnp.float32(2.0), x)
    np.testing.assert_allclose(np.asarray(g), 3.5)  # mean of 0..7
    np.testing.assert_allclose(np.asarray(vals), 2.0 * np.arange(N))


def test_grouped_fused_matches_unfused():
    params = {"a": jnp.ones((2, 2)), "b": jnp.zeros(5)}

    def loss(p, x):
        return jnp.sum(p["a"]) * jnp.mean(x) + jnp.sum(p["b"] * 2) * jnp.mean(x)

    vg_f = hvd.distributed_value_and_grad(loss, fuse=True)
    vg_u = hvd.distributed_value_and_grad(loss, fuse=False)
    x = jnp.arange(N, dtype=jnp.float32)
    run = lambda f: shard_map(
        f, mesh=hvd.mesh(), in_specs=(P(), P("hvd")), out_specs=(P(), P()),
    )(params, x)
    (_, gf), (_, gu) = run(vg_f), run(vg_u)
    for k in params:
        np.testing.assert_allclose(np.asarray(gf[k]), np.asarray(gu[k]), rtol=1e-6)


def test_compression_bf16_roundtrip():
    from horovod_tpu.ops.compression import Compression

    x = jnp.asarray(np.random.RandomState(1).randn(32).astype(np.float32))
    c, ctx = Compression.fp16.compress(x)
    assert c.dtype == jnp.bfloat16
    out = Compression.fp16.decompress(c, ctx)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-2, atol=1e-2)


def test_backward_passes_per_step_accumulates():
    tx = hvd.DistributedOptimizer(optax.sgd(1.0), backward_passes_per_step=2)
    w = jnp.zeros(2)
    state = tx.init(w)

    def apply(g, s, w):
        u, s2 = tx.update(g, s, w)
        return optax.apply_updates(w, u), s2

    g1 = jnp.ones(2)
    w, state = jax.jit(apply)(g1, state, w)
    np.testing.assert_allclose(np.asarray(w), 0.0)  # accumulating, no step yet
    w, state = jax.jit(apply)(g1, state, w)
    # MultiSteps averages accumulated grads → update = -1.0 * 1.0
    np.testing.assert_allclose(np.asarray(w), -1.0)
