"""Model-zoo tests (parity model: the reference's per-framework op/model
coverage, test/test_torch.py & examples; SURVEY.md §4)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.utils.compat import set_mesh as _set_mesh
from horovod_tpu.models import (
    GPT2_CONFIGS,
    TransformerConfig,
    TransformerEncoder,
    TransformerLM,
    get_model,
    list_models,
)
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.parallel.train import lm_loss, make_train_step, softmax_xent


def test_registry_lists_all_families():
    names = list_models()
    for required in ["mnist-mlp", "mnist-cnn", "resnet50", "resnet101",
                     "gpt2-small", "gpt2-1p3b", "bert-base", "vit-l16"]:
        assert required in names


@pytest.mark.parametrize("name", ["mnist-mlp", "mnist-cnn", "gpt2-tiny",
                                  "bert-tiny", "vit-tiny"])
def test_forward_shapes(name):
    spec = get_model(name)
    m = spec.make_model()
    batch = spec.make_batch(2)
    variables = m.init(jax.random.PRNGKey(0), *batch)
    out = m.apply(variables, *batch)
    assert out.shape[0] == 2
    if name in ("gpt2-tiny", "bert-tiny"):
        # Transformers emit FULL-precision logits by default — the
        # public model.apply surface must not silently narrow (ADVICE
        # r14); the measured bench/train paths opt into bf16 (see
        # TransformerConfig.logits_dtype).
        assert out.dtype == jnp.float32
        m16 = spec.make_model(logits_dtype=jnp.bfloat16)
        assert m16.apply(variables, *batch).dtype == jnp.bfloat16
    else:
        assert out.dtype == jnp.float32


def test_bf16_logits_loss_matches_f32_logits():
    """The bf16-logits OPT-IN (the bench/train measured config) must
    not move the loss: softmax_xent computes in f32 internally, so the
    only difference is the logits' own bf16 rounding."""
    ids = np.random.RandomState(3).randint(0, 512, (4, 32), dtype=np.int32)
    base = dict(vocab_size=512, d_model=64, n_heads=4, n_layers=2,
                d_ff=128, max_len=32)
    m16 = TransformerLM(TransformerConfig(**base,
                                          logits_dtype=jnp.bfloat16))
    m32 = TransformerLM(TransformerConfig(**base))
    variables = m16.init(jax.random.PRNGKey(0), ids)
    l16 = lm_loss(m16.apply(variables, ids), ids)
    l32 = lm_loss(m32.apply(variables, ids), ids)
    assert l16.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(l16), np.asarray(l32),
                               rtol=2e-3)


def test_resnet_batchstats_update():
    spec = get_model("resnet18")
    m = spec.make_model(num_classes=10)
    x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out, updates = m.apply(variables, x, train=True, mutable=["batch_stats"])
    assert out.shape == (2, 10)
    before = variables["batch_stats"]["bn_init"]["mean"]
    after = updates["batch_stats"]["bn_init"]["mean"]
    assert not np.allclose(np.asarray(before), np.asarray(after))


def test_scan_remat_matches_loop():
    """nn.scan'd stack must compute the same function as the python-loop
    stack given identically-initialized params."""
    ids = np.random.RandomState(0).randint(0, 64, (2, 8), dtype=np.int32)
    base = dict(vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                max_len=16)
    m_loop = TransformerLM(TransformerConfig(**base))
    m_scan = TransformerLM(TransformerConfig(**base, scan_layers=True,
                                             remat=True))
    v_scan = m_scan.init(jax.random.PRNGKey(0), ids)

    # Restructure scanned params (stacked "layers" axis) into loop layout.
    import flax

    v_scan_plain = flax.core.unfreeze(jax.tree.map(lambda x: x,
                                                   flax.linen.unbox(v_scan)))
    stacked = v_scan_plain["params"]["stack"].pop("layers")
    for i in range(2):
        v_scan_plain["params"]["stack"][f"layer_{i}"] = jax.tree.map(
            lambda x: x[i], stacked
        )
    out_scan = m_scan.apply(v_scan, ids)
    out_loop = m_loop.apply(v_scan_plain, ids)
    np.testing.assert_allclose(np.asarray(out_scan), np.asarray(out_loop),
                               rtol=2e-2, atol=2e-3)


def test_moe_aux_loss_sown():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, max_len=16, n_experts=2, moe_every=2)
    m = TransformerLM(cfg)
    ids = np.random.RandomState(0).randint(0, 64, (2, 8), dtype=np.int32)
    variables = m.init(jax.random.PRNGKey(0), ids)
    _, aux = m.apply(variables, ids, mutable=["losses"])
    leaves = jax.tree.leaves(aux["losses"])
    assert leaves and float(jnp.sum(jnp.asarray(leaves[0]))) > 0.0


def test_train_step_loss_decreases_lm_moe_mesh():
    """GPT-2-tiny + MoE training over a dp×ep×tp mesh: loss decreases and
    tp params are genuinely sharded."""
    cfg = TransformerConfig(vocab_size=128, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, max_len=32, n_experts=2, moe_every=2)
    mesh = create_mesh({"dp": 2, "ep": 2, "tp": 2})
    build = make_train_step(TransformerLM(cfg), optax.adam(1e-3), lm_loss,
                            mesh=mesh, moe_aux_weight=0.01)
    ids = np.random.RandomState(0).randint(0, 128, (8, 16), dtype=np.int32)
    init_fn, step_fn, _ = build(jax.random.PRNGKey(0), ids)
    state = init_fn(jax.random.PRNGKey(0))
    losses = []
    for _ in range(4):
        state, loss = step_fn(state, ids)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    spec = state.params["stack"]["layer_0"]["mlp"]["wi"]["kernel"].sharding.spec
    assert "tp" in jax.tree.leaves(tuple(spec))


def test_train_step_resnet_dp_mesh():
    mesh = create_mesh({"dp": 8})
    spec = get_model("resnet18")
    m = spec.make_model(num_classes=10)
    build = make_train_step(m, optax.sgd(0.1), softmax_xent, mesh=mesh,
                            has_batch_stats=True)
    rng = np.random.RandomState(0)
    x = rng.rand(8, 32, 32, 3).astype(np.float32)
    y = rng.randint(0, 10, (8,), dtype=np.int32)
    init_fn, step_fn, _ = build(jax.random.PRNGKey(0), x, y)
    state = init_fn(jax.random.PRNGKey(0))
    losses = []
    for _ in range(3):
        state, loss = step_fn(state, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_bert_ring_attention_with_padding_mask():
    """BERT-style encoder with attn_impl=ring + padding mask on a dp x sp
    mesh matches the dense-attention forward (the BASELINE BERT configs
    are padded-batch workloads; VERDICT r1 flagged this gap)."""
    import dataclasses

    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models.transformer import BERT_CONFIGS

    base = dataclasses.replace(
        BERT_CONFIGS["bert-tiny"], max_len=32, n_layers=1, dtype=jnp.float32,
        param_dtype=jnp.float32, logits_dtype=jnp.float32,
    )
    ids = np.random.RandomState(0).randint(0, 1000, (2, 32), dtype=np.int32)
    mask = np.ones((2, 32), np.float32)
    mask[0, 24:] = 0.0
    mask[1, 10:] = 0.0

    m_dense = TransformerEncoder(dataclasses.replace(base, attn_impl="dense"))
    variables = m_dense.init(jax.random.PRNGKey(0), ids, mask=mask)
    want = m_dense.apply(variables, ids, mask=mask)

    mesh = create_mesh({"dp": 2, "sp": 4})
    m_ring = TransformerEncoder(dataclasses.replace(base, attn_impl="ring"))
    with _set_mesh(mesh):
        got = jax.jit(lambda v, i, mk: m_ring.apply(v, i, mask=mk))(
            variables, ids, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
