"""Ring data plane + eager MIN/MAX/PRODUCT tests under real processes
(ref test model: Gloo ring allreduce coverage in test/test_torch.py
op-variant tests; ring algorithm ref: gloo_operations.cc:119-166)."""
import numpy as np
import pytest

from horovod_tpu.runner import run

ENV = {
    "HOROVOD_CYCLE_TIME": "1",
    "JAX_PLATFORMS": "cpu",
    # Force the ring path for every payload so small tests exercise it.
    "HOROVOD_RING_THRESHOLD": "0",
}


def test_ring_allreduce_three_ranks():
    def fn():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r, n = hvd.rank(), hvd.size()
        # Uneven element count (not divisible by n) exercises the
        # remainder chunk.
        x = np.arange(10001, dtype=np.float32) * (r + 1)
        out = hvd.allreduce(x, op=hvd.ReduceOp.SUM, name="ringsum")
        expect = np.arange(10001, dtype=np.float32) * sum(
            i + 1 for i in range(n)
        )
        assert np.allclose(np.asarray(out), expect)

        avg = hvd.allreduce(x, op=hvd.ReduceOp.AVERAGE, name="ringavg")
        assert np.allclose(np.asarray(avg), expect / n)

        # fused: two tensors in one cycle still reduce correctly
        h1 = hvd.allreduce_async(np.full(2048, float(r)), name="f1")
        h2 = hvd.allreduce_async(np.full(1024, 2.0 * r), name="f2")
        o1 = np.asarray(hvd.synchronize(h1))
        o2 = np.asarray(hvd.synchronize(h2))
        assert np.allclose(o1, np.mean(np.arange(n)))
        assert np.allclose(o2, 2.0 * np.mean(np.arange(n)))
        return True

    assert run(fn, np=3, extra_env=ENV) == [True, True, True]


def test_eager_min_max_product():
    def fn():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r, n = hvd.rank(), hvd.size()
        y = (np.arange(64, dtype=np.float64) + 1) * (r + 1)
        mn = hvd.allreduce(y, op=hvd.ReduceOp.MIN, name="mn")
        assert np.allclose(np.asarray(mn), np.arange(64) + 1)
        mx = hvd.allreduce(y, op=hvd.ReduceOp.MAX, name="mx")
        assert np.allclose(np.asarray(mx), (np.arange(64) + 1) * n)
        pr = hvd.allreduce(
            np.full(8, float(r + 2)), op=hvd.ReduceOp.PRODUCT, name="pr"
        )
        assert np.allclose(
            np.asarray(pr), np.prod([i + 2 for i in range(n)])
        )
        return True

    assert run(fn, np=2, extra_env=ENV) == [True, True]


def test_reduce_op_mismatch_errors():
    def fn():
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu.common.exceptions import HorovodInternalError

        hvd.init()
        op = hvd.ReduceOp.MIN if hvd.rank() == 0 else hvd.ReduceOp.MAX
        try:
            hvd.allreduce(np.ones(4), op=op, name="mismatch")
            return False
        except HorovodInternalError as e:
            return "reduce op" in str(e).lower()

    assert run(fn, np=2, extra_env=ENV) == [True, True]


def test_ring_with_join():
    def fn():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r, n = hvd.rank(), hvd.size()
        if r == 0:
            z = hvd.allreduce(np.ones(5000, np.float32), name="uneven")
            # Joined ranks contribute full-shape zeros; AVERAGE divides
            # by world size (ref: JoinOp + AVERAGE postscale semantics).
            assert np.allclose(np.asarray(z), 1.0 / n)
        hvd.join()
        return True

    assert run(fn, np=3, extra_env=ENV) == [True, True, True]


# ---------------------------------------------------------------------------
# Ring allgather (ref: GlooAllgather ring, gloo_operations.cc:184)
def _run_ring_backends(size, fn):
    import threading

    from horovod_tpu.backend.threaded import ThreadedGroup

    group = ThreadedGroup(size)
    backends = [group.backend(r) for r in range(size)]
    results = [None] * size
    errors = [None] * size

    def worker(r):
        try:
            results[r] = fn(backends[r], r)
        except BaseException as e:  # noqa: BLE001
            errors[r] = e

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    for e in errors:
        if e is not None:
            raise e
    return results


def test_ring_allgatherv_variable_dims(monkeypatch):
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)
    dims = [2, 0, 3, 1]  # includes a zero-row rank

    def fn(b, r):
        arr = np.full((dims[r], 3), float(r), np.float32)
        return b.allgatherv(arr, list(dims))

    out = _run_ring_backends(4, fn)
    expect = np.concatenate(
        [np.full((dims[r], 3), float(r), np.float32) for r in range(4)]
    )
    for o in out:
        np.testing.assert_allclose(o, expect)


def test_ring_allgatherv_matches_star(monkeypatch):
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)
    rng = np.random.RandomState(0)
    blocks = [rng.rand(5, 7).astype(np.float64) for _ in range(3)]

    def ring_fn(b, r):
        return b._ring_allgatherv(blocks[r].copy(), [5, 5, 5])

    out = _run_ring_backends(3, ring_fn)
    expect = np.concatenate(blocks)
    for o in out:
        np.testing.assert_allclose(o, expect)


def test_small_allgather_stays_on_star(monkeypatch):
    """Below the threshold the latency-optimal star path runs."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", str(1 << 20))
    calls = []

    def fn(b, r):
        orig = b._ring_allgatherv
        b._ring_allgatherv = lambda *a: calls.append(r) or orig(*a)
        return b.allgatherv(np.ones((2, 2), np.float32), [2, 2])

    out = _run_ring_backends(2, fn)
    for o in out:
        assert o.shape == (4, 2)
    assert calls == []


# ---------------------------------------------------------------------------
# _bounds / _segment_bounds degenerate chunking (the pipelined path must
# handle zero-size chunks, remainder-in-last-chunk and non-divisible
# segment sizes without desyncing frame counts)
def test_bounds_total_smaller_than_group():
    from horovod_tpu.backend.ring import RingCollectivesMixin

    # total < n: base chunk is 0 elements, the whole payload lands in
    # the last chunk; every earlier chunk is zero-size.
    b = RingCollectivesMixin._bounds(2, 4)
    assert b == [0, 0, 0, 0, 2]
    sizes = [b[i + 1] - b[i] for i in range(4)]
    assert sizes == [0, 0, 0, 2]


def test_bounds_remainder_in_last_chunk():
    from horovod_tpu.backend.ring import RingCollectivesMixin

    b = RingCollectivesMixin._bounds(10, 3)
    assert b == [0, 3, 6, 10]
    assert b[-1] - b[-2] == 4  # remainder rides the last chunk


def test_segment_bounds_degenerate_cases():
    from horovod_tpu.backend.ring import RingCollectivesMixin

    seg = RingCollectivesMixin._segment_bounds
    # zero-size chunk: exactly ONE empty segment (the frame still flows
    # so ring steps stay aligned)
    assert seg(0, 4) == [0, 0]
    # single-shot (seg_elems=0) and seg >= chunk: one segment
    assert seg(10, 0) == [0, 10]
    assert seg(10, 100) == [0, 10]
    # non-divisible: remainder in the last segment
    assert seg(10, 4) == [0, 4, 8, 10]
    # exact division
    assert seg(8, 4) == [0, 4, 8]


@pytest.mark.parametrize("total,seg_bytes", [
    (2, 0),      # total < n: zero-size chunks, empty frames
    (10001, 0),  # remainder-in-last-chunk, single-shot
    (10001, 52), # non-divisible segment size on the pipelined path
    (3, 8),      # total < n AND segmentation armed
])
def test_ring_allreduce_degenerate_chunking(monkeypatch, total, seg_bytes):
    """4-rank ring allreduce across the degenerate chunk geometries:
    zero-size chunks must send/recv empty frames cleanly and
    non-divisible segment sizes must not desync the pipelined path."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", str(seg_bytes))
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)

    def fn(b, r):
        x = np.arange(total, dtype=np.float32) * (r + 1)
        return b.allreduce(x)

    out = _run_ring_backends(4, fn)
    expect = np.arange(total, dtype=np.float32) * 10.0  # 1+2+3+4
    for o in out:
        np.testing.assert_allclose(o, expect)


def test_ring_allgatherv_segmented(monkeypatch):
    """The segmented path covers the allgather phase too (chunks land
    straight in their final slice, segment by segment)."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "64")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)
    dims = [7, 0, 3]

    def fn(b, r):
        arr = np.full((dims[r], 5), float(r), np.float32)
        return b.allgatherv(arr, list(dims))

    out = _run_ring_backends(3, fn)
    expect = np.concatenate(
        [np.full((dims[r], 5), float(r), np.float32) for r in range(3)]
    )
    for o in out:
        np.testing.assert_allclose(o, expect)


def test_engine_ring_allgather_end_to_end(monkeypatch, tmp_path):
    """Engine-level: a large allgather rides the ring (timeline shows
    RING_ALLGATHER) and returns correct variable-dim output."""
    import json
    import os
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_engine import run_ranks

    path = tmp_path / "tl.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(path))
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "64")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)

    def fn(eng, rank):
        arr = np.full((rank + 1, 100), float(rank), np.float32)
        out = eng.synchronize(
            eng.enqueue_allgather(arr, name="g"), timeout=30)
        expect = np.concatenate([
            np.full((r + 1, 100), float(r), np.float32) for r in range(2)
        ])
        np.testing.assert_allclose(out, expect)
        return True

    run_ranks(2, fn)
    events = json.loads(path.read_text())
    assert "RING_ALLGATHER" in {e.get("name") for e in events}


# ---------------------------------------------------------------------------
# structural guarantee behind the thread-per-step fix: persistent peer
# senders are created once at warm-up and REUSED — a full ring allreduce
# must not create any new thread afterwards.
def test_ring_allreduce_no_new_threads_after_warmup(monkeypatch):
    import os
    import sys
    import threading

    sys.path.insert(0, os.path.dirname(__file__))
    from test_fault_tolerance import _tcp_pair

    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_RING_SEGMENT_BYTES", "4096")
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "30")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)
    server, (b0, b1) = _tcp_pair("t_threads", monkeypatch)
    try:
        def both(fn):
            res = [None, None]
            errs = []

            def w(i, b):
                try:
                    res[i] = fn(b, i)
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)

            ts = [threading.Thread(target=w, args=(i, b))
                  for i, b in ((0, b0), (1, b1))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            assert not errs, errs
            return res

        x = np.arange(5000, dtype=np.float32)
        both(lambda b, i: b.allreduce(x * (i + 1)))  # warm-up: senders spawn
        threads_after_warmup = set(threading.enumerate())
        for _ in range(3):
            out = both(lambda b, i: b.allreduce(x * (i + 1)))
        for o in out:
            np.testing.assert_allclose(o, x * 3)
        new = set(threading.enumerate()) - threads_after_warmup
        assert not new, f"ring steps spawned new threads: {new}"
        # ...and the warm-up created exactly one persistent sender per
        # live peer on each backend.
        assert set(b0._senders) == {1} and set(b1._senders) == {0}
    finally:
        b0.shutdown()
        b1.shutdown()
        server.stop()


def test_shutdown_stops_persistent_senders(monkeypatch):
    import os
    import sys
    import time as _time

    sys.path.insert(0, os.path.dirname(__file__))
    from test_fault_tolerance import _tcp_pair

    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "30")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)
    server, (b0, b1) = _tcp_pair("t_sender_shutdown", monkeypatch)
    try:
        t0 = b0.send_async(1, b"x")
        data = b1.recv_from(0)
        t0.wait()
        assert bytes(data) == b"x"
        sender_threads = [s.thread for s in b0._senders.values()]
        assert sender_threads and all(t.is_alive() for t in sender_threads)
        b0.shutdown()
        deadline = _time.monotonic() + 10
        while (any(t.is_alive() for t in sender_threads)
               and _time.monotonic() < deadline):
            _time.sleep(0.01)
        assert not any(t.is_alive() for t in sender_threads), (
            "sender workers survived shutdown")
        assert not b0._senders
    finally:
        b1.shutdown()
        server.stop()
