"""Ring data plane + eager MIN/MAX/PRODUCT tests under real processes
(ref test model: Gloo ring allreduce coverage in test/test_torch.py
op-variant tests; ring algorithm ref: gloo_operations.cc:119-166)."""
import numpy as np

from horovod_tpu.runner import run

ENV = {
    "HOROVOD_CYCLE_TIME": "1",
    "JAX_PLATFORMS": "cpu",
    # Force the ring path for every payload so small tests exercise it.
    "HOROVOD_RING_THRESHOLD": "0",
}


def test_ring_allreduce_three_ranks():
    def fn():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r, n = hvd.rank(), hvd.size()
        # Uneven element count (not divisible by n) exercises the
        # remainder chunk.
        x = np.arange(10001, dtype=np.float32) * (r + 1)
        out = hvd.allreduce(x, op=hvd.ReduceOp.SUM, name="ringsum")
        expect = np.arange(10001, dtype=np.float32) * sum(
            i + 1 for i in range(n)
        )
        assert np.allclose(np.asarray(out), expect)

        avg = hvd.allreduce(x, op=hvd.ReduceOp.AVERAGE, name="ringavg")
        assert np.allclose(np.asarray(avg), expect / n)

        # fused: two tensors in one cycle still reduce correctly
        h1 = hvd.allreduce_async(np.full(2048, float(r)), name="f1")
        h2 = hvd.allreduce_async(np.full(1024, 2.0 * r), name="f2")
        o1 = np.asarray(hvd.synchronize(h1))
        o2 = np.asarray(hvd.synchronize(h2))
        assert np.allclose(o1, np.mean(np.arange(n)))
        assert np.allclose(o2, 2.0 * np.mean(np.arange(n)))
        return True

    assert run(fn, np=3, extra_env=ENV) == [True, True, True]


def test_eager_min_max_product():
    def fn():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r, n = hvd.rank(), hvd.size()
        y = (np.arange(64, dtype=np.float64) + 1) * (r + 1)
        mn = hvd.allreduce(y, op=hvd.ReduceOp.MIN, name="mn")
        assert np.allclose(np.asarray(mn), np.arange(64) + 1)
        mx = hvd.allreduce(y, op=hvd.ReduceOp.MAX, name="mx")
        assert np.allclose(np.asarray(mx), (np.arange(64) + 1) * n)
        pr = hvd.allreduce(
            np.full(8, float(r + 2)), op=hvd.ReduceOp.PRODUCT, name="pr"
        )
        assert np.allclose(
            np.asarray(pr), np.prod([i + 2 for i in range(n)])
        )
        return True

    assert run(fn, np=2, extra_env=ENV) == [True, True]


def test_reduce_op_mismatch_errors():
    def fn():
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu.common.exceptions import HorovodInternalError

        hvd.init()
        op = hvd.ReduceOp.MIN if hvd.rank() == 0 else hvd.ReduceOp.MAX
        try:
            hvd.allreduce(np.ones(4), op=op, name="mismatch")
            return False
        except HorovodInternalError as e:
            return "reduce op" in str(e).lower()

    assert run(fn, np=2, extra_env=ENV) == [True, True]


def test_ring_with_join():
    def fn():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r, n = hvd.rank(), hvd.size()
        if r == 0:
            z = hvd.allreduce(np.ones(5000, np.float32), name="uneven")
            # Joined ranks contribute full-shape zeros; AVERAGE divides
            # by world size (ref: JoinOp + AVERAGE postscale semantics).
            assert np.allclose(np.asarray(z), 1.0 / n)
        hvd.join()
        return True

    assert run(fn, np=3, extra_env=ENV) == [True, True, True]
