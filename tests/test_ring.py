"""Ring data plane + eager MIN/MAX/PRODUCT tests under real processes
(ref test model: Gloo ring allreduce coverage in test/test_torch.py
op-variant tests; ring algorithm ref: gloo_operations.cc:119-166)."""
import numpy as np

from horovod_tpu.runner import run

ENV = {
    "HOROVOD_CYCLE_TIME": "1",
    "JAX_PLATFORMS": "cpu",
    # Force the ring path for every payload so small tests exercise it.
    "HOROVOD_RING_THRESHOLD": "0",
}


def test_ring_allreduce_three_ranks():
    def fn():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r, n = hvd.rank(), hvd.size()
        # Uneven element count (not divisible by n) exercises the
        # remainder chunk.
        x = np.arange(10001, dtype=np.float32) * (r + 1)
        out = hvd.allreduce(x, op=hvd.ReduceOp.SUM, name="ringsum")
        expect = np.arange(10001, dtype=np.float32) * sum(
            i + 1 for i in range(n)
        )
        assert np.allclose(np.asarray(out), expect)

        avg = hvd.allreduce(x, op=hvd.ReduceOp.AVERAGE, name="ringavg")
        assert np.allclose(np.asarray(avg), expect / n)

        # fused: two tensors in one cycle still reduce correctly
        h1 = hvd.allreduce_async(np.full(2048, float(r)), name="f1")
        h2 = hvd.allreduce_async(np.full(1024, 2.0 * r), name="f2")
        o1 = np.asarray(hvd.synchronize(h1))
        o2 = np.asarray(hvd.synchronize(h2))
        assert np.allclose(o1, np.mean(np.arange(n)))
        assert np.allclose(o2, 2.0 * np.mean(np.arange(n)))
        return True

    assert run(fn, np=3, extra_env=ENV) == [True, True, True]


def test_eager_min_max_product():
    def fn():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r, n = hvd.rank(), hvd.size()
        y = (np.arange(64, dtype=np.float64) + 1) * (r + 1)
        mn = hvd.allreduce(y, op=hvd.ReduceOp.MIN, name="mn")
        assert np.allclose(np.asarray(mn), np.arange(64) + 1)
        mx = hvd.allreduce(y, op=hvd.ReduceOp.MAX, name="mx")
        assert np.allclose(np.asarray(mx), (np.arange(64) + 1) * n)
        pr = hvd.allreduce(
            np.full(8, float(r + 2)), op=hvd.ReduceOp.PRODUCT, name="pr"
        )
        assert np.allclose(
            np.asarray(pr), np.prod([i + 2 for i in range(n)])
        )
        return True

    assert run(fn, np=2, extra_env=ENV) == [True, True]


def test_reduce_op_mismatch_errors():
    def fn():
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu.common.exceptions import HorovodInternalError

        hvd.init()
        op = hvd.ReduceOp.MIN if hvd.rank() == 0 else hvd.ReduceOp.MAX
        try:
            hvd.allreduce(np.ones(4), op=op, name="mismatch")
            return False
        except HorovodInternalError as e:
            return "reduce op" in str(e).lower()

    assert run(fn, np=2, extra_env=ENV) == [True, True]


def test_ring_with_join():
    def fn():
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        r, n = hvd.rank(), hvd.size()
        if r == 0:
            z = hvd.allreduce(np.ones(5000, np.float32), name="uneven")
            # Joined ranks contribute full-shape zeros; AVERAGE divides
            # by world size (ref: JoinOp + AVERAGE postscale semantics).
            assert np.allclose(np.asarray(z), 1.0 / n)
        hvd.join()
        return True

    assert run(fn, np=3, extra_env=ENV) == [True, True, True]


# ---------------------------------------------------------------------------
# Ring allgather (ref: GlooAllgather ring, gloo_operations.cc:184)
def _run_ring_backends(size, fn):
    import threading

    from horovod_tpu.backend.threaded import ThreadedGroup

    group = ThreadedGroup(size)
    backends = [group.backend(r) for r in range(size)]
    results = [None] * size
    errors = [None] * size

    def worker(r):
        try:
            results[r] = fn(backends[r], r)
        except BaseException as e:  # noqa: BLE001
            errors[r] = e

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    for e in errors:
        if e is not None:
            raise e
    return results


def test_ring_allgatherv_variable_dims(monkeypatch):
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)
    dims = [2, 0, 3, 1]  # includes a zero-row rank

    def fn(b, r):
        arr = np.full((dims[r], 3), float(r), np.float32)
        return b.allgatherv(arr, list(dims))

    out = _run_ring_backends(4, fn)
    expect = np.concatenate(
        [np.full((dims[r], 3), float(r), np.float32) for r in range(4)]
    )
    for o in out:
        np.testing.assert_allclose(o, expect)


def test_ring_allgatherv_matches_star(monkeypatch):
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)
    rng = np.random.RandomState(0)
    blocks = [rng.rand(5, 7).astype(np.float64) for _ in range(3)]

    def ring_fn(b, r):
        return b._ring_allgatherv(blocks[r].copy(), [5, 5, 5])

    out = _run_ring_backends(3, ring_fn)
    expect = np.concatenate(blocks)
    for o in out:
        np.testing.assert_allclose(o, expect)


def test_small_allgather_stays_on_star(monkeypatch):
    """Below the threshold the latency-optimal star path runs."""
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", str(1 << 20))
    calls = []

    def fn(b, r):
        orig = b._ring_allgatherv
        b._ring_allgatherv = lambda *a: calls.append(r) or orig(*a)
        return b.allgatherv(np.ones((2, 2), np.float32), [2, 2])

    out = _run_ring_backends(2, fn)
    for o in out:
        assert o.shape == (4, 2)
    assert calls == []


def test_engine_ring_allgather_end_to_end(monkeypatch, tmp_path):
    """Engine-level: a large allgather rides the ring (timeline shows
    RING_ALLGATHER) and returns correct variable-dim output."""
    import json
    import os
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_engine import run_ranks

    path = tmp_path / "tl.json"
    monkeypatch.setenv("HOROVOD_TIMELINE", str(path))
    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "64")
    monkeypatch.delenv("HOROVOD_CPU_OPERATIONS", raising=False)

    def fn(eng, rank):
        arr = np.full((rank + 1, 100), float(rank), np.float32)
        out = eng.synchronize(
            eng.enqueue_allgather(arr, name="g"), timeout=30)
        expect = np.concatenate([
            np.full((r + 1, 100), float(r), np.float32) for r in range(2)
        ])
        np.testing.assert_allclose(out, expect)
        return True

    run_ranks(2, fn)
    events = json.loads(path.read_text())
    assert "RING_ALLGATHER" in {e.get("name") for e in events}
