"""Store + estimator data-path tests.

(ref: horovod/spark/common/store.py:29-260 LocalStore path scheme and
parquet checks; horovod/spark/keras/estimator.py per-epoch checkpoints
written to the store, resume from last checkpoint.)
"""
import os

import numpy as np
import pandas as pd
import pytest

from horovod_tpu.spark.store import (
    FilesystemStore,
    HDFSStore,
    LocalStore,
    Store,
)


def test_store_create_dispatch(tmp_path):
    s = Store.create(str(tmp_path / "prefix"))
    assert isinstance(s, LocalStore)
    with pytest.raises(ValueError):
        Store.create("gs://bucket/path")
    # hdfs:// dispatches to HDFSStore; with no usable libhdfs on the
    # host the constructor raises the FUSE-mount guidance.
    with pytest.raises(RuntimeError, match="hdfs-fuse"):
        Store.create("hdfs://nn:8020/path")


def test_hdfs_store_url_parsing(tmp_path):
    """The reference's three prefix forms (ref: store.py:300-311):
    hdfs://host:port/path, hdfs:///path, /path."""
    import pyarrow.fs as pafs

    fs = pafs.LocalFileSystem()
    for url, authority in ((f"hdfs://nn:8020{tmp_path}/h",
                            "hdfs://nn:8020"),
                           (f"hdfs://{tmp_path}/h", "hdfs://"),
                           (f"{tmp_path}/h", "hdfs://")):
        s = HDFSStore(url, fs=fs)
        assert s.prefix_path == f"{tmp_path}/h", url
        # Spark writes must target the SAME authority the pyarrow fs
        # talks to (ref: store.py _url_prefix).
        assert s._url_prefix == authority, url
    with pytest.raises(ValueError, match="parse"):
        HDFSStore("hdfs://host-only:8020", fs=fs)


def test_local_store_paths(tmp_path):
    s = LocalStore(f"file://{tmp_path}/st")
    assert s.prefix_path == str(tmp_path / "st")
    assert s.get_train_data_path().endswith("intermediate_train_data")
    assert s.get_train_data_path(2).endswith("intermediate_train_data.2")
    assert s.get_run_path("r1") == os.path.join(s.get_runs_path(), "r1")
    assert s.get_checkpoint_path("r1").endswith("r1/checkpoint.pkl")


def test_write_read_atomic(tmp_path):
    s = LocalStore(str(tmp_path))
    p = os.path.join(s.prefix_path, "sub", "blob.bin")
    s.write(p, b"payload")
    assert s.exists(p)
    assert s.read(p) == b"payload"
    # No temp files left behind.
    assert sorted(os.listdir(os.path.dirname(p))) == ["blob.bin"]


def test_parquet_materialization(tmp_path):
    s = LocalStore(str(tmp_path))
    df = pd.DataFrame({"a": [1.0, 2.0, 3.0], "y": [0, 1, 0]})
    path = s.get_train_data_path()
    assert not s.is_parquet_dataset(path)
    s.save_data_frame(df, path)
    assert s.is_parquet_dataset(path)
    back = s.read_parquet(path)
    np.testing.assert_allclose(back["a"].to_numpy(), [1.0, 2.0, 3.0])
    # Re-materialization overwrites cleanly.
    s.save_data_frame(pd.DataFrame({"a": [9.0], "y": [1]}), path)
    assert len(s.read_parquet(path)) == 1


def test_checkpoint_roundtrip(tmp_path):
    s = LocalStore(str(tmp_path))
    assert not s.has_checkpoint("run")
    s.save_checkpoint("run", {"params": np.arange(3), "epoch": 0}, epoch=0)
    s.save_checkpoint("run", {"params": np.arange(3) * 2, "epoch": 1}, epoch=1)
    assert s.has_checkpoint("run")
    ck = s.load_checkpoint("run")
    assert ck["epoch"] == 1
    np.testing.assert_array_equal(ck["params"], np.arange(3) * 2)
    # Per-epoch history kept alongside the latest.
    run_dir = s.get_run_path("run")
    names = sorted(os.listdir(run_dir))
    assert "checkpoint.epoch0.pkl" in names and "checkpoint.epoch1.pkl" in names


def test_filesystem_store_matches_local_store(tmp_path):
    """FilesystemStore over pyarrow's LocalFileSystem behaves exactly
    like LocalStore on the same data: same writes, same parquet view,
    same shard math (ref: store.py:148-260 FilesystemStore — one
    implementation shared by every pyarrow filesystem)."""
    import pyarrow.fs as pafs

    fss = FilesystemStore(str(tmp_path / "fss"), fs=pafs.LocalFileSystem())
    loc = LocalStore(str(tmp_path / "loc"))
    df = pd.DataFrame({
        "x": np.arange(23, dtype=np.float32),
        "y": np.arange(23, dtype=np.float32) * 2,
    })
    for s in (fss, loc):
        p = s.get_train_data_path()
        s.save_data_frame(df, p)
        assert s.is_parquet_dataset(p)
        blob = os.path.join(s.get_run_path("r"), "blob.bin")
        s.write(blob, b"abc")
        assert s.read(blob) == b"abc"
        # No tmp residue from the write-then-rename.
        assert sorted(os.listdir(os.path.dirname(blob))) == ["blob.bin"]
    pd.testing.assert_frame_equal(
        fss.read_parquet(fss.get_train_data_path()),
        loc.read_parquet(loc.get_train_data_path()))
    for rank in range(2):
        fp, lp = fss.get_train_data_path(), loc.get_train_data_path()
        assert fss.shard_num_rows(fp, rank, 2) \
            == loc.shard_num_rows(lp, rank, 2)
        fchunks = pd.concat(fss.iter_parquet_batches(
            fp, shard_rank=rank, shard_size=2, batch_rows=8),
            ignore_index=True)
        lchunks = pd.concat(loc.iter_parquet_batches(
            lp, shard_rank=rank, shard_size=2, batch_rows=8),
            ignore_index=True)
        pd.testing.assert_frame_equal(fchunks, lchunks)


def test_hdfs_store_estimator_fit(tmp_path):
    """An estimator fits end-to-end against HDFSStore with the
    LocalFileSystem stand-in: materialization, per-epoch checkpoints,
    and resume all flow through the pyarrow fs interface
    (ref: store.py:263-433 HDFSStore backing the estimators)."""
    import pyarrow.fs as pafs

    store = HDFSStore(f"hdfs://nn:8020{tmp_path}/h",
                      fs=pafs.LocalFileSystem())
    est = _make_estimator(store=store, run_id="hfit", epochs=8)
    df = _toy_df()
    model = est.fit(df)
    assert store.is_parquet_dataset(store.get_train_data_path())
    assert store.has_checkpoint("hfit")
    assert store.load_checkpoint("hfit")["epoch"] == 7
    pred = model.transform(df)
    err = np.abs(pred["prediction"].to_numpy()
                 - df["y"].to_numpy()).mean()
    assert err < 0.5


# ---------------------------------------------------------------------------
def _toy_df(n=64):
    rng = np.random.RandomState(0)
    x = rng.rand(n).astype(np.float32)
    return pd.DataFrame({"x": x, "y": 3.0 * x + 1.0})


def _make_estimator(store=None, run_id=None, epochs=2, num_proc=None):
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from horovod_tpu.spark.estimator import JaxEstimator

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x).squeeze(-1)

    return JaxEstimator(
        model=Lin(),
        optimizer=optax.sgd(0.5),
        loss=lambda pred, y: jnp.mean((pred - y) ** 2),
        feature_cols=["x"],
        label_col="y",
        epochs=epochs,
        batch_size=16,
        num_proc=num_proc,
        store=store,
        run_id=run_id,
    )


def test_estimator_fit_with_store_checkpoints(tmp_path):
    store = LocalStore(str(tmp_path))
    est = _make_estimator(store=store, run_id="fit1", epochs=8)
    df = _toy_df()
    model = est.fit(df)
    # Data was materialized to store parquet.
    assert store.is_parquet_dataset(store.get_train_data_path())
    # Per-epoch checkpoints exist and the latest carries the last epoch.
    assert store.has_checkpoint("fit1")
    assert store.load_checkpoint("fit1")["epoch"] == 7
    # The fitted model predicts the line reasonably.
    pred = model.transform(df)
    err = np.abs(pred["prediction"].to_numpy()
                 - df["y"].to_numpy()).mean()
    assert err < 0.5


def test_estimator_resumes_from_checkpoint(tmp_path):
    store = LocalStore(str(tmp_path))
    df = _toy_df()
    est1 = _make_estimator(store=store, run_id="resume", epochs=2)
    est1.fit(df)
    p0 = store.load_checkpoint("resume")

    # Second fit with more epochs resumes at epoch 2, not epoch 0.
    est2 = _make_estimator(store=store, run_id="resume", epochs=4)
    est2.fit(df)
    p1 = store.load_checkpoint("resume")
    assert p0["epoch"] == 1 and p1["epoch"] == 3


def test_estimator_without_store_still_works():
    est = _make_estimator(epochs=2)
    model = est.fit(_toy_df())
    assert model.params is not None


def test_refit_with_new_data_retrains(tmp_path):
    """Changing the DataFrame on the same store + run_id must
    re-materialize AND retrain — not resume past the new data."""
    store = LocalStore(str(tmp_path))
    df1 = _toy_df()
    est1 = _make_estimator(store=store, run_id="swap", epochs=2)
    est1.fit(df1)
    assert store.load_checkpoint("swap")["epoch"] == 1

    # Different data: y = -3x (opposite slope).
    x = np.random.RandomState(1).rand(64).astype(np.float32)
    df2 = pd.DataFrame({"x": x, "y": -3.0 * x})
    est2 = _make_estimator(store=store, run_id="swap", epochs=2)
    model2 = est2.fit(df2)
    # Data was re-materialized (fingerprints differ) ...
    assert store.matches_fingerprint(df2, store.get_train_data_path())
    assert not store.matches_fingerprint(df1, store.get_train_data_path())
    # ... and training restarted on df2 (checkpoint bound to df2's
    # fingerprint, params moved toward the NEW slope).
    ck = store.load_checkpoint("swap")
    assert ck["data_fp"] == store.dataset_fingerprint(df2)
    pred = model2.transform(df2)
    err = np.abs(pred["prediction"].to_numpy() - df2["y"].to_numpy()).mean()
    err_old = np.abs(pred["prediction"].to_numpy() - (3.0 * x + 1.0)).mean()
    assert err < err_old  # fitted the new relation, not the old one


# ---------------------------------------------------------------------------
# Streaming shard reader (ref: spark/common/util.py:697 — the reference
# streams worker shards through Petastorm batch readers so shards larger
# than RAM train; iter_parquet_batches is the pyarrow-native equivalent).

def _multi_rowgroup_parquet(tmp_path, rows=1000, row_group_size=100):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.RandomState(0)
    pdf = pd.DataFrame({
        "x": rng.rand(rows).astype(np.float32),
        "y": rng.rand(rows).astype(np.float32),
    })
    path = tmp_path / "data"
    path.mkdir()
    pq.write_table(pa.Table.from_pandas(pdf),
                   str(path / "part-00000.parquet"),
                   row_group_size=row_group_size)
    return str(path), pdf


def test_iter_parquet_batches_streams_row_groups(tmp_path, monkeypatch):
    """Chunks are bounded and the whole-table read path is never used —
    row groups stream one at a time."""
    import pyarrow.parquet as pq

    path, pdf = _multi_rowgroup_parquet(tmp_path)
    store = LocalStore(str(tmp_path))
    monkeypatch.setattr(
        pq, "read_table",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("whole-table read in the streaming path")),
    )
    got = []
    for chunk in store.iter_parquet_batches(path, columns=["x", "y"],
                                            batch_rows=64):
        assert len(chunk) <= 64
        got.append(chunk)
    out = pd.concat(got, ignore_index=True)
    pd.testing.assert_frame_equal(out, pdf)


def test_iter_parquet_batches_global_stride_matches_metadata(tmp_path):
    """Strided sharding (single part file, many ranks) is disjoint,
    complete, and sized exactly as shard_num_rows predicts — the
    estimator's collective step agreement depends on the exact count."""
    path, pdf = _multi_rowgroup_parquet(tmp_path, rows=997)  # ragged
    store = LocalStore(str(tmp_path))
    seen = []
    for rank in range(3):
        n_meta = store.shard_num_rows(path, rank, 3)
        chunks = list(store.iter_parquet_batches(
            path, shard_rank=rank, shard_size=3, batch_rows=128))
        shard = pd.concat(chunks, ignore_index=True)
        assert len(shard) == n_meta == len(range(rank, 997, 3))
        expect = pdf.iloc[rank::3].reset_index(drop=True)
        pd.testing.assert_frame_equal(shard, expect)
        seen.append(shard)
    assert sum(len(s) for s in seen) == 997


def test_iter_parquet_batches_by_parts(tmp_path):
    """With >= shard_size part files each rank streams only its own
    files (same sharding read_parquet uses)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = tmp_path / "parts"
    path.mkdir()
    for i in range(4):
        pdf = pd.DataFrame({"x": np.full(10 + i, float(i), np.float32)})
        pq.write_table(pa.Table.from_pandas(pdf),
                       str(path / f"part-{i:05d}.parquet"))
    store = LocalStore(str(tmp_path))
    for rank in range(2):
        shard = pd.concat(
            store.iter_parquet_batches(str(path), shard_rank=rank,
                                       shard_size=2, batch_rows=8),
            ignore_index=True)
        assert len(shard) == store.shard_num_rows(str(path), rank, 2)
        assert set(shard["x"]) == {float(rank), float(rank + 2)}
