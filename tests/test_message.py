"""Wire-format round-trip tests (ref: horovod/common/message.cc
serialization via FlatBuffers — ours is the struct-packed codec that the
C++ engine mirrors)."""
import numpy as np

from horovod_tpu.common.message import (
    Request,
    RequestList,
    RequestType,
    Response,
    ResponseList,
    ResponseType,
)
from horovod_tpu.common.types import DataType, TensorShape, to_wire_dtype


def test_request_roundtrip():
    r = Request(
        request_rank=3,
        request_type=RequestType.ALLGATHER,
        tensor_type=DataType.BFLOAT16,
        tensor_name="layer1/weights.grad",
        root_rank=1,
        device=7,
        tensor_shape=(4, 1024, 3),
        prescale_factor=0.25,
        postscale_factor=2.0,
    )
    r2, off = Request.deserialize(r.serialize())
    assert r2 == r
    assert off == len(r.serialize())


def test_request_list_roundtrip():
    rl = RequestList(
        [Request(tensor_name=f"t{i}", tensor_shape=(i,)) for i in range(5)],
        shutdown=True,
    )
    rl2 = RequestList.deserialize(rl.serialize())
    assert rl2.shutdown
    assert [r.tensor_name for r in rl2.requests] == [f"t{i}" for i in range(5)]


def test_response_roundtrip():
    resp = Response(
        response_type=ResponseType.ERROR,
        tensor_names=["a", "b"],
        error_message="Mismatched shapes",
        devices=[0, 1],
        tensor_sizes=[3, 9],
        tensor_type=DataType.FLOAT64,
        prescale_factor=1.5,
        postscale_factor=0.5,
        last_joined_rank=2,
    )
    r2, _ = Response.deserialize(resp.serialize())
    assert r2 == resp


def test_response_channel_rides_the_wire():
    """The executor-channel id must survive serialization — workers
    follow the coordinator's assignment through it."""
    resp = Response(
        response_type=ResponseType.ALLREDUCE,
        tensor_names=["t"],
        tensor_shapes=[(2, 3)],
        channel=3,
    )
    r2, _ = Response.deserialize(resp.serialize())
    assert r2.channel == 3
    assert r2 == resp
    # default stays 0 (fences, pre-channel payloads)
    assert Response.deserialize(Response().serialize())[0].channel == 0


def test_response_codec_rides_the_wire():
    """The wire-codec id must survive serialization — codec choice is
    collectively agreed through the Response message, exactly like the
    channel id (a per-rank env read would desync frame widths)."""
    resp = Response(
        response_type=ResponseType.ALLREDUCE,
        tensor_names=["t"],
        tensor_shapes=[(2, 3)],
        channel=1,
        codec=1,
    )
    r2, _ = Response.deserialize(resp.serialize())
    assert r2.codec == 1
    assert r2 == resp
    # default stays 0 (full-width) for every pre-codec payload
    assert Response.deserialize(Response().serialize())[0].codec == 0


def test_response_list_roundtrip():
    rl = ResponseList([Response(tensor_names=["x"]), Response(tensor_names=["y"])])
    rl2 = ResponseList.deserialize(rl.serialize())
    assert len(rl2.responses) == 2
    assert not rl2.shutdown


def test_dtype_mapping():
    for np_dt in [np.float32, np.float64, np.int32, np.int64, np.uint8, np.bool_]:
        wire = to_wire_dtype(np.dtype(np_dt))
        assert isinstance(wire, DataType)
    import jax.numpy as jnp

    assert to_wire_dtype(jnp.bfloat16) == DataType.BFLOAT16


def test_tensor_shape():
    s = TensorShape.of(np.zeros((2, 3, 4)))
    assert s.num_elements() == 24
    assert s.to_string() == "[2, 3, 4]"
