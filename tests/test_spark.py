"""Spark-integration tests with a mock SparkContext (the reference tests
against a local Spark cluster, test/test_spark.py; here the Spark API
surface is mocked so the orchestration logic is covered hermetically)."""
import os
import threading

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def isolate_env():
    """_task_fn sets the worker env contract via os.environ — correct in
    real Spark executors (separate processes), but the threaded mock
    shares this process, so snapshot/restore around every test."""
    snap = dict(os.environ)
    yield
    for k in set(os.environ) - set(snap):
        del os.environ[k]
    os.environ.update(snap)
    import horovod_tpu as hvd

    hvd.shutdown()


class FakeRDD:
    """Runs each 'partition' in a thread — same concurrency shape as
    barrier-mode Spark tasks on one box."""

    def __init__(self, n):
        self.n = n

    def barrier(self):
        return self

    def mapPartitionsWithIndex(self, f):
        self._f = f
        return self

    def collect(self):
        results = [None] * self.n
        errors = [None] * self.n

        def worker(i):
            try:
                results[i] = list(self._f(i, iter([i])))
            except BaseException as e:  # noqa: BLE001
                errors[i] = e

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(self.n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for e in errors:
            if e is not None:
                raise e
        return [r for part in results if part for r in part]


class FakeSparkContext:
    defaultParallelism = 2

    def parallelize(self, data, n):
        return FakeRDD(n)


def test_spark_run_two_tasks(monkeypatch):
    # Tasks run in-process threads; process-mode env must not leak.
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "1")
    from horovod_tpu.spark import run

    def fn():
        import os

        # Inside the task, the env contract must be set.
        rank = int(os.environ["HOROVOD_RANK"])
        size = int(os.environ["HOROVOD_SIZE"])
        assert size == 2
        return rank * 100

    out = run(fn, num_proc=2, spark_context=FakeSparkContext())
    assert out == [0, 100]


def test_spark_run_requires_context():
    from horovod_tpu.spark import run

    with pytest.raises((ImportError, ValueError)):
        run(lambda: 1, num_proc=1)


def test_jax_estimator_local_pandas():
    pd = pytest.importorskip("pandas")
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from horovod_tpu.spark import JaxEstimator

    class Reg(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)[..., 0]

    rng = np.random.RandomState(0)
    X = rng.randn(256, 3).astype(np.float32)
    w = np.array([1.0, -2.0, 0.5], np.float32)
    df = pd.DataFrame({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2], "y": X @ w,
    })
    est = JaxEstimator(
        Reg(), optax.adam(0.05),
        loss=lambda pred, y: jnp.mean((pred - y) ** 2),
        feature_cols=["a", "b", "c"], label_col="y",
        epochs=200, batch_size=64,
    )
    model = est.fit(df)
    out = model.transform(df)
    err = float(np.mean((np.asarray(list(out["prediction"])) -
                         df["y"].to_numpy()) ** 2))
    assert err < 0.05, err


# ---------------------------------------------------------------------------
# Mid-job elastic rescale (ref: horovod/spark/runner.py:303 run_elastic)

_ELASTIC_TRAIN_SRC = r"""
def _elastic_train():
    import os
    import time

    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.elastic.state import ObjectState

    hvd.init()
    state = ObjectState(batch=0, history=[], w=np.zeros(2, np.float32))

    @hvd.elastic.run
    def train(state):
        while state.batch < 25:
            kill_at = os.environ.get("TEST_KILL_AT")
            sent = os.environ.get("TEST_KILL_SENTINEL")
            if (kill_at and hvd.size() == 2 and hvd.rank() == 1
                    and state.batch >= int(kill_at)
                    and not os.path.exists(sent)):
                open(sent, "w").close()
                os._exit(1)
            g = hvd.allreduce(np.ones(2, np.float32), name="g")
            state.w = state.w + np.asarray(g)  # deterministic "training"
            state.history.append((hvd.rank(), hvd.size()))
            state.batch += 1
            state.commit()
            gate = os.environ.get("TEST_GATE_FILE")
            if gate and state.batch >= 3 and not os.path.exists(gate):
                open(gate, "w").close()
            time.sleep(0.05)
        return list(state.history), state.w.tolist()

    return train(state)
"""
exec(_ELASTIC_TRAIN_SRC)


class GatedFakeRDD(FakeRDD):
    """Partition 0 starts immediately; partition i>0 waits for a gate
    file — the mock's stand-in for Spark dynamic allocation bringing a
    task up mid-job."""

    def __init__(self, n, gate_file):
        super().__init__(n)
        self._gate = gate_file

    def collect(self):
        import time as _t

        results = [None] * self.n
        errors = [None] * self.n

        def worker(i):
            if i > 0:
                deadline = _t.monotonic() + 60
                while not os.path.exists(self._gate):
                    if _t.monotonic() > deadline:
                        errors[i] = TimeoutError("gate never opened")
                        return
                    _t.sleep(0.1)
            try:
                results[i] = list(self._f(i, iter([i])))
            except BaseException as e:  # noqa: BLE001
                errors[i] = e

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(self.n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        for e in errors:
            if e is not None:
                raise e
        return [r for part in results if part for r in part]


def test_spark_run_elastic_shrinks_on_task_death(monkeypatch, tmp_path):
    """np=2 job; the rank-1 worker dies mid-fit. The elastic driver must
    blacklist its slot, reset at np=1, and hvd.elastic state must carry:
    the survivor finishes all 25 batches with its accumulated state
    intact (ref: horovod/spark/runner.py:303 — rescale via respawn)."""
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "1")
    monkeypatch.setenv("HOROVOD_ELASTIC_DISCOVERY_INTERVAL", "0.25")
    from horovod_tpu.spark import run_elastic

    sentinel = tmp_path / "killed_once"
    out = run_elastic(
        _elastic_train, num_proc=2, min_np=1, max_np=2,
        spark_context=FakeSparkContext(),
        extra_env={
            "TEST_KILL_AT": "4",
            "TEST_KILL_SENTINEL": str(sentinel),
        },
    )
    assert sentinel.exists()  # the death really happened
    hist, w = out[0]
    sizes = [s for _, s in hist]
    assert 2 in sizes and sizes[-1] == 1, sizes  # shrank mid-job
    assert len(hist) >= 25, len(hist)  # state carried through the reset
    # Every batch added allreduce(ones) (AVERAGE -> ones) to w exactly
    # once per committed batch: restores must not double-count.
    assert w == [float(len(hist))] * 2, (w, len(hist))


def test_spark_run_elastic_grows_when_task_appears(monkeypatch, tmp_path):
    """min_np=1: the job starts with one live task while the second is
    delayed; when it appears the driver must rescale UP mid-job and
    finish at np=2 with both ranks returning results."""
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "1")
    monkeypatch.setenv("HOROVOD_ELASTIC_DISCOVERY_INTERVAL", "0.25")
    from horovod_tpu.spark import run_elastic

    gate = tmp_path / "gate"

    class Ctx(FakeSparkContext):
        def parallelize(self, data, n):
            return GatedFakeRDD(n, str(gate))

    out = run_elastic(
        _elastic_train, num_proc=2, min_np=1, max_np=2,
        spark_context=Ctx(),
        extra_env={"TEST_GATE_FILE": str(gate)},
    )
    assert len(out) == 2, len(out)  # final topology np=2, both posted
    hist, _ = out[0]
    sizes = [s for _, s in hist]
    assert 1 in sizes and 2 in sizes, sizes  # grew mid-job
    assert sizes[-1] == 2, sizes
    assert len(hist) >= 25
