"""Spark-integration tests with a mock SparkContext (the reference tests
against a local Spark cluster, test/test_spark.py; here the Spark API
surface is mocked so the orchestration logic is covered hermetically)."""
import os
import threading

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def isolate_env():
    """_task_fn sets the worker env contract via os.environ — correct in
    real Spark executors (separate processes), but the threaded mock
    shares this process, so snapshot/restore around every test."""
    snap = dict(os.environ)
    yield
    for k in set(os.environ) - set(snap):
        del os.environ[k]
    os.environ.update(snap)
    import horovod_tpu as hvd

    hvd.shutdown()


class FakeRDD:
    """Runs each 'partition' in a thread — same concurrency shape as
    barrier-mode Spark tasks on one box."""

    def __init__(self, n):
        self.n = n

    def barrier(self):
        return self

    def mapPartitionsWithIndex(self, f):
        self._f = f
        return self

    def collect(self):
        results = [None] * self.n
        errors = [None] * self.n

        def worker(i):
            try:
                results[i] = list(self._f(i, iter([i])))
            except BaseException as e:  # noqa: BLE001
                errors[i] = e

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(self.n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for e in errors:
            if e is not None:
                raise e
        return [r for part in results if part for r in part]


class FakeSparkContext:
    defaultParallelism = 2

    def parallelize(self, data, n):
        return FakeRDD(n)


def test_spark_run_two_tasks(monkeypatch):
    # Tasks run in-process threads; process-mode env must not leak.
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "1")
    from horovod_tpu.spark import run

    def fn():
        import os

        # Inside the task, the env contract must be set.
        rank = int(os.environ["HOROVOD_RANK"])
        size = int(os.environ["HOROVOD_SIZE"])
        assert size == 2
        return rank * 100

    out = run(fn, num_proc=2, spark_context=FakeSparkContext())
    assert out == [0, 100]


def test_spark_run_requires_context():
    from horovod_tpu.spark import run

    with pytest.raises((ImportError, ValueError)):
        run(lambda: 1, num_proc=1)


def test_jax_estimator_local_pandas():
    pd = pytest.importorskip("pandas")
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from horovod_tpu.spark import JaxEstimator

    class Reg(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(1)(x)[..., 0]

    rng = np.random.RandomState(0)
    X = rng.randn(256, 3).astype(np.float32)
    w = np.array([1.0, -2.0, 0.5], np.float32)
    df = pd.DataFrame({
        "a": X[:, 0], "b": X[:, 1], "c": X[:, 2], "y": X @ w,
    })
    est = JaxEstimator(
        Reg(), optax.adam(0.05),
        loss=lambda pred, y: jnp.mean((pred - y) ** 2),
        feature_cols=["a", "b", "c"], label_col="y",
        epochs=200, batch_size=64,
    )
    model = est.fit(df)
    out = model.transform(df)
    err = float(np.mean((np.asarray(list(out["prediction"])) -
                         df["y"].to_numpy()) ** 2))
    assert err < 0.05, err
