"""MXNet adapter tests (skipped when mxnet is not installed — the
reference gates the binding the same way via HOROVOD_WITH_MXNET;
ref: horovod/mxnet/__init__.py:17-19 check_extension).

The numpy-bridge machinery underneath is the same code path the torch
adapter exercises with 2 real ranks in test_torch_adapter.py.
"""
import numpy as np
import pytest

mx = pytest.importorskip("mxnet")

import horovod_tpu.mxnet as hvd  # noqa: E402


@pytest.fixture(autouse=True)
def _hvd():
    hvd.init()
    yield
    hvd.shutdown()


def test_size1_allreduce():
    t = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    out = hvd.allreduce(t, average=True, name="t")
    np.testing.assert_allclose(out.asnumpy(), t.asnumpy())


def test_size1_allreduce_inplace():
    t = mx.nd.array(np.ones((4,), np.float32))
    hvd.allreduce_(t, average=True, name="t2")
    np.testing.assert_allclose(t.asnumpy(), np.ones(4))


def test_size1_broadcast_and_allgather():
    t = mx.nd.array(np.arange(4, dtype=np.float32))
    out = hvd.broadcast(t, root_rank=0, name="b")
    np.testing.assert_allclose(out.asnumpy(), t.asnumpy())
    g = hvd.allgather(t, name="g")
    np.testing.assert_allclose(g.asnumpy(), t.asnumpy())


def test_distributed_trainer_smoke():
    net = mx.gluon.nn.Dense(2)
    net.initialize()
    params = net.collect_params()
    trainer = hvd.DistributedTrainer(params, "sgd",
                                     {"learning_rate": 0.1})
    x = mx.nd.ones((3, 4))
    with mx.autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(3)
