"""Torch/Keras Spark estimators over the shared Store data path.

(ref: test/test_spark.py torch-estimator and keras-estimator suites —
fit on a DataFrame, transform, checkpoint/resume.)
"""
import numpy as np
import pandas as pd
import pytest

from horovod_tpu.spark import (
    KerasEstimator,
    TorchEstimator,
)
from horovod_tpu.spark.store import LocalStore

torch = pytest.importorskip("torch")


def _toy_df(n=256, slope=3.0, seed=0):
    x = np.random.RandomState(seed).rand(n).astype(np.float32)
    return pd.DataFrame({"x": x, "y": slope * x + 1.0})


def _torch_estimator(store=None, run_id=None, epochs=3, num_proc=None):
    model = torch.nn.Linear(1, 1)
    return TorchEstimator(
        model=model,
        optimizer=torch.optim.SGD(model.parameters(), lr=0.5),
        loss=lambda out, y: torch.nn.functional.mse_loss(
            out.squeeze(-1), y),
        feature_cols=["x"], label_col="y",
        epochs=epochs, batch_size=32, store=store, run_id=run_id,
        num_proc=num_proc,
    )


def test_torch_estimator_fits_and_transforms(tmp_path, hvd_single):
    store = LocalStore(str(tmp_path))
    est = _torch_estimator(store=store, run_id="t1", epochs=12)
    df = _toy_df()
    model = est.fit(df)
    pred = model.transform(df)
    err = np.abs(np.stack(pred["prediction"].to_numpy()).ravel()
                 - df["y"].to_numpy()).mean()
    assert err < 0.25, err
    # per-epoch checkpoints landed, tagged with the data fingerprint
    ck = store.load_checkpoint("t1")
    assert ck["epoch"] == 11
    assert ck["data_fp"] == store.dataset_fingerprint(df)


def test_torch_estimator_resumes(tmp_path, hvd_single):
    store = LocalStore(str(tmp_path))
    df = _toy_df()
    _torch_estimator(store=store, run_id="t2", epochs=2).fit(df)
    assert store.load_checkpoint("t2")["epoch"] == 1
    # Re-fit with more epochs resumes at epoch 2 (not 0).
    _torch_estimator(store=store, run_id="t2", epochs=4).fit(df)
    assert store.load_checkpoint("t2")["epoch"] == 3


def test_torch_estimator_two_procs(tmp_path):
    """End-to-end across 2 real worker processes (engine path)."""
    store = LocalStore(str(tmp_path))
    est = _torch_estimator(store=store, run_id="t3", epochs=8, num_proc=2)
    df = _toy_df()
    model = est.fit(df)
    pred = model.transform(df)
    err = np.abs(np.stack(pred["prediction"].to_numpy()).ravel()
                 - df["y"].to_numpy()).mean()
    assert err < 0.4, err


def test_torch_estimator_validation_and_sample_weight(tmp_path):
    """validation (float split) + sample_weight_col across 2 real
    workers: fit returns a history dict with train+val loss series,
    both averaged across ranks, and weights skew training toward the
    heavily-weighted rows (ref: horovod/spark/common/params.py:30-106)."""
    store = LocalStore(str(tmp_path))
    n = 256
    x = np.random.RandomState(0).rand(n).astype(np.float32)
    # Two clusters with different targets; weight one cluster 2000x.
    # (Keras sample_weight semantics: loss = mean(per_sample * w), so
    # the weights scale the effective lr — keep w*lr stable.)
    y = np.where(x < 0.5, 1.0, 3.0).astype(np.float32)
    w = np.where(x < 0.5, 20.0, 0.01).astype(np.float32)
    df = pd.DataFrame({"x": x, "y": y, "wt": w})

    model = torch.nn.Linear(1, 1, bias=False)
    est = TorchEstimator(
        model=model,
        optimizer=torch.optim.SGD(model.parameters(), lr=0.02),
        # Per-sample losses, as the sample_weight_col contract requires.
        loss=lambda out, t: (out.squeeze(-1) - t) ** 2,
        feature_cols=["x"], label_col="y",
        epochs=15, batch_size=32, store=store, run_id="vw1",
        num_proc=2, validation=0.25, sample_weight_col="wt",
    )
    fitted = est.fit(df)
    h = fitted.history
    assert set(h) == {"loss", "val_loss"}
    assert len(h["loss"]) == 15 and len(h["val_loss"]) == 15
    assert h["loss"][-1] < h["loss"][0], h["loss"]
    assert all(np.isfinite(v) for v in h["val_loss"])
    # With cluster A weighted 100x vs 0.01x, the single weight must land
    # near A's mean target region, not the unweighted blend.
    wgt = float(fitted.model.weight.detach().ravel()[0])
    pred_a = wgt * 0.25   # a typical cluster-A input
    assert abs(pred_a - 1.0) < 1.0, (wgt, pred_a)


def test_torch_estimator_validation_column(hvd_single):
    """validation as an indicator COLUMN: val rows are exactly the
    truthy ones and never train (train on y=2x, validate on y=0 rows —
    val loss must stay far from train loss)."""
    n = 128
    rng = np.random.RandomState(1)
    x = rng.rand(n).astype(np.float32)
    is_val = (np.arange(n) % 4 == 0)
    y = np.where(is_val, 0.0, 2.0 * x).astype(np.float32)
    df = pd.DataFrame({"x": x, "y": y, "isval": is_val.astype(np.int64)})

    model = torch.nn.Linear(1, 1, bias=False)
    est = TorchEstimator(
        model=model,
        optimizer=torch.optim.SGD(model.parameters(), lr=0.5),
        loss=lambda out, t: torch.nn.functional.mse_loss(
            out.squeeze(-1), t),
        feature_cols=["x"], label_col="y",
        epochs=15, batch_size=32, validation="isval",
    )
    fitted = est.fit(df)
    h = fitted.history
    assert h["loss"][-1] < 0.05, h["loss"]       # fits y=2x well
    assert h["val_loss"][-1] > 0.2, h["val_loss"]  # val rows are y=0


def test_torch_estimator_weight_requires_per_sample_loss(hvd_single):
    df = _toy_df(64)
    df["wt"] = 1.0
    model = torch.nn.Linear(1, 1)
    est = TorchEstimator(
        model=model,
        optimizer=torch.optim.SGD(model.parameters(), lr=0.1),
        loss=lambda out, t: torch.nn.functional.mse_loss(
            out.squeeze(-1), t),  # scalar loss: invalid with weights
        feature_cols=["x"], label_col="y", epochs=1,
        sample_weight_col="wt",
    )
    with pytest.raises(ValueError, match="per-sample"):
        est.fit(df)


def test_keras_estimator_validation_and_weights(tmp_path, hvd_single):
    keras = pytest.importorskip("keras")

    store = LocalStore(str(tmp_path))
    df = _toy_df(192)
    df["wt"] = np.ones(len(df), np.float32)

    model = keras.Sequential(
        [keras.Input((1,)), keras.layers.Dense(1, use_bias=False)]
    )
    est = KerasEstimator(
        model=model, optimizer=keras.optimizers.SGD(0.3),
        loss="mse", feature_cols=["x"], label_col="y",
        epochs=8, batch_size=32, store=store, run_id="kv1",
        validation=0.2, sample_weight_col="wt",
        metrics=["mae"],
    )
    fitted = est.fit(df)
    h = fitted.history
    assert set(h) == {"loss", "val_loss", "mae", "val_mae"}, set(h)
    assert len(h["loss"]) == 8 and len(h["val_mae"]) == 8
    assert h["loss"][-1] < h["loss"][0], h["loss"]
    # Unit weights must not break convergence toward y = 3x + 1.
    assert h["val_loss"][-1] < h["val_loss"][0], h["val_loss"]
    # Compiled metric improves alongside the loss and stays finite.
    assert h["mae"][-1] < h["mae"][0], h["mae"]
    assert all(np.isfinite(v) for v in h["val_mae"])


def test_torch_estimator_preserves_param_groups(tmp_path, hvd_single):
    """Per-param-group hyperparameters survive the worker rebuild: a
    group with lr=0 must not move while the lr>0 group trains (the
    reference serializes the optimizer whole, preserving groups)."""
    store = LocalStore(str(tmp_path))
    model = torch.nn.Sequential(
        torch.nn.Linear(1, 4), torch.nn.Linear(4, 1)
    )
    frozen0 = model[0].weight.detach().clone()
    trained0 = model[1].weight.detach().clone()
    opt = torch.optim.SGD([
        {"params": model[0].parameters(), "lr": 0.0},
        {"params": model[1].parameters(), "lr": 0.3},
    ])
    est = TorchEstimator(
        model=model, optimizer=opt,
        loss=lambda out, y: torch.nn.functional.mse_loss(
            out.squeeze(-1), y),
        feature_cols=["x"], label_col="y",
        epochs=3, batch_size=32, store=store, run_id="pg1",
    )
    fitted = est.fit(_toy_df())
    sd = fitted.model.state_dict()
    assert torch.allclose(sd["0.weight"], frozen0), (
        "lr=0 group moved — param-group hyperparams were dropped"
    )
    assert not torch.allclose(sd["1.weight"], trained0), (
        "lr=0.3 group did not train"
    )


def test_torch_estimator_rejects_foreign_optimizer_params(hvd_single):
    model = torch.nn.Linear(1, 1)
    other = torch.nn.Linear(1, 1)
    est = TorchEstimator(
        model=model,
        optimizer=torch.optim.SGD(other.parameters(), lr=0.1),
        loss=lambda out, y: torch.nn.functional.mse_loss(
            out.squeeze(-1), y),
        feature_cols=["x"], label_col="y", epochs=1,
    )
    with pytest.raises(ValueError, match="constructed over parameters"):
        est.fit(_toy_df())


def test_keras_estimator_fits_and_resumes(tmp_path, hvd_single):
    keras = pytest.importorskip("keras")

    store = LocalStore(str(tmp_path))
    df = _toy_df()

    def make_est(epochs, run_id="k1"):
        model = keras.Sequential([
            keras.layers.Input(shape=(1,)),
            keras.layers.Dense(1),
        ])
        return KerasEstimator(
            model=model,
            optimizer=keras.optimizers.SGD(0.5),
            loss="mse",
            feature_cols=["x"], label_col="y",
            epochs=epochs, batch_size=32, store=store, run_id=run_id,
        )

    model = make_est(epochs=10).fit(df)
    pred = model.transform(df)
    err = np.abs(np.stack(pred["prediction"].to_numpy()).ravel()
                 - df["y"].to_numpy()).mean()
    assert err < 0.3, err
    assert store.load_checkpoint("k1")["epoch"] == 9
    # resume
    make_est(epochs=12).fit(df)
    assert store.load_checkpoint("k1")["epoch"] == 11


def test_keras_estimator_two_procs(tmp_path):
    """The worker closure must survive pickling WITHOUT the live Keras
    model riding along (Keras 3 models don't pickle — only the .keras
    blob and optimizer config may cross the process boundary)."""
    keras = pytest.importorskip("keras")

    store = LocalStore(str(tmp_path))
    model = keras.Sequential([
        keras.layers.Input(shape=(1,)),
        keras.layers.Dense(1),
    ])
    est = KerasEstimator(
        model=model,
        optimizer=keras.optimizers.SGD(0.5),
        loss="mse",
        feature_cols=["x"], label_col="y",
        epochs=6, batch_size=32, store=store, run_id="k2", num_proc=2,
    )
    df = _toy_df()
    fitted = est.fit(df)
    pred = fitted.transform(df)
    err = np.abs(np.stack(pred["prediction"].to_numpy()).ravel()
                 - df["y"].to_numpy()).mean()
    assert err < 0.5, err
    assert store.load_checkpoint("k2")["epoch"] == 5


def test_torch_estimator_float64_labels(tmp_path, hvd_single):
    """pandas float columns default to float64; the worker must cast
    targets to the model's float32 instead of crashing in the loss."""
    store = LocalStore(str(tmp_path))
    x = np.random.RandomState(0).rand(128).astype(np.float32)
    df = pd.DataFrame({"x": x, "y": (3.0 * x + 1.0).astype(np.float64)})
    est = _torch_estimator(store=store, run_id="t4", epochs=6)
    model = est.fit(df)
    pred = model.transform(df)
    err = np.abs(np.stack(pred["prediction"].to_numpy()).ravel()
                 - df["y"].to_numpy()).mean()
    assert err < 0.5, err
