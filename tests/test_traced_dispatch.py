"""One allreduce, two planes (ROADMAP item 2 / docs/running.md "Traced
collectives"): the same `hvd.allreduce` / `hvd.DistributedOptimizer`
call must run eagerly on the engine, under plain jit (closed forms over
GSPMD arrays), and under shard_map (XLA collectives over the resolved
mesh axis) — with cross-path numerical agreement, a collectively
consistent axis-resolution rule, 2-D data×model mesh composition, the
traced-path wire cast, and host-boundary goodput demarcation for jitted
optimizer loops."""
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.backend.threaded import ThreadedGroup
from horovod_tpu.common import telemetry
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.engine.engine import Engine
from horovod_tpu.ops import resolve_axis
from horovod_tpu.parallel.mesh import create_mesh
from horovod_tpu.utils.compat import shard_map


def _run_engine_ranks(size, fn):
    """fn(engine, rank) on `size` in-process engines (the eager TCP/
    inproc data plane — real negotiation, real wire framing)."""
    group = ThreadedGroup(size)
    engines = [
        Engine(rank=r, size=size, backend=group.backend(r))
        for r in range(size)
    ]
    for e in engines:
        e.cycle_time_s = 0.001
        e.start()
    results, errors = [None] * size, [None] * size

    def worker(r):
        try:
            results[r] = fn(engines[r], r)
        except BaseException as ex:  # noqa: BLE001
            errors[r] = ex

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(size)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    stop = [threading.Thread(target=e.shutdown) for e in engines]
    for t in stop:
        t.start()
    for t in stop:
        t.join(timeout=60)
    for err in errors:
        if err is not None:
            raise err
    return results


@pytest.fixture(autouse=True)
def _clean_env():
    saved = {k: os.environ.get(k) for k in
             ("HOROVOD_WIRE_COMPRESSION", "HOROVOD_WIRE_COMPRESSION_MIN_BYTES")}
    yield
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


# ---------------------------------------------------------------------------
# Axis resolution rule

def test_resolve_axis_explicit_wins():
    assert resolve_axis("sp") == "sp"
    assert resolve_axis(("dp", "sp")) == ("dp", "sp")


def test_resolve_axis_none_outside_trace():
    # Eager / plain jit: nothing bound -> None (closed forms / engine).
    assert resolve_axis() is None


def test_resolve_axis_picks_data_axis_on_2d_mesh():
    """On a data×model mesh the rule resolves the DATA axis only —
    model axes (tp) are never gradient-reduction axes."""
    hvd.shutdown()
    mesh = create_mesh({"dp": 2, "tp": 4})
    seen = {}

    def worker(x):
        seen["axis"] = resolve_axis()
        return x

    shard_map(worker, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(
        jnp.ones(2))
    assert seen["axis"] == "dp"


def test_resolve_axis_prefers_init_axis(hvd_mesh):
    seen = {}

    def worker(x):
        seen["axis"] = resolve_axis()
        return x

    shard_map(worker, mesh=hvd_mesh.mesh(), in_specs=P("hvd"),
              out_specs=P("hvd"))(jnp.ones(8))
    assert seen["axis"] == "hvd"


# ---------------------------------------------------------------------------
# One API: cross-path agreement (eager engine vs traced psum)

@pytest.mark.parametrize("op,prescale", [
    (ReduceOp.AVERAGE, 1.0),
    (ReduceOp.AVERAGE, 2.5),
    (ReduceOp.SUM, 1.0),
    (ReduceOp.SUM, 0.5),
])
def test_allreduce_engine_vs_traced_agreement(op, prescale):
    """The acceptance matrix: the SAME call, engine plane vs XLA plane,
    per-rank data identical across the arms — results allclose at fp32
    tolerances for AVERAGE and SUM with prescale."""
    hvd.shutdown()
    n = 2
    rng = np.random.RandomState(7)
    data = rng.randn(n, 1024).astype(np.float32)

    def fn(eng, rank):
        h = eng.enqueue_allreduce(data[rank].copy(), name="xp", op=op,
                                  prescale=prescale)
        return eng.synchronize(h, timeout=60)

    engine_out = _run_engine_ranks(n, fn)

    mesh = create_mesh({"hvd": n}, devices=jax.devices()[:n])

    def step(x):
        return hvd.allreduce(x, op=op, prescale_factor=prescale)

    traced = shard_map(step, mesh=mesh, in_specs=P("hvd"),
                       out_specs=P("hvd"))(
        jnp.asarray(data.reshape(n * 1024)))
    traced = np.asarray(traced).reshape(n, 1024)

    for r in range(n):
        np.testing.assert_allclose(engine_out[r], traced[r],
                                   rtol=1e-6, atol=1e-6)
    # ...and the shards agree with each other (it was a real allreduce).
    np.testing.assert_allclose(traced[0], traced[1], rtol=0, atol=0)


def test_one_call_eager_jit_shardmap_consistent(hvd_mesh):
    """The same script line runs in all three regimes and agrees:
    mesh-mode eager (closed form), plain jit (closed form over the
    global array), shard_map (real psum)."""
    n = hvd_mesh.size()
    x = jnp.full((n * 4,), 3.0, jnp.float32)

    eager = hvd.allreduce(x, op=hvd.Sum)

    jitted = jax.jit(lambda v: hvd.allreduce(v, op=hvd.Sum))(x)

    sharded = shard_map(lambda v: hvd.allreduce(v, op=hvd.Sum),
                        mesh=hvd_mesh.mesh(), in_specs=P(),
                        out_specs=P())(x)

    np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted))
    np.testing.assert_allclose(np.asarray(eager), np.asarray(sharded))


def test_distributed_optimizer_engine_vs_traced(hvd_mesh):
    """DistributedOptimizer: the traced (shard_map) update equals the
    engine-plane update on the same per-rank gradients."""
    n = 2
    rng = np.random.RandomState(3)
    grads = rng.randn(n, 64).astype(np.float32)
    params = rng.randn(64).astype(np.float32)

    # Engine arm: eager update per rank (allreduce rides the engine).
    def fn(eng, rank):
        h = eng.enqueue_allreduce(grads[rank].copy(), name="g",
                                  op=ReduceOp.AVERAGE)
        red = eng.synchronize(h, timeout=60)
        return params - 0.1 * red

    engine_params = _run_engine_ranks(n, fn)

    # Traced arm: the SAME DistributedOptimizer API under shard_map.
    tx = hvd.DistributedOptimizer(optax.sgd(0.1))
    mesh = create_mesh({"hvd": n}, devices=jax.devices()[:n])
    state = tx.init(jnp.asarray(params))

    def step(p, g, s):
        upd, s2 = tx.update(g, s, p)
        return optax.apply_updates(p, upd)

    out = shard_map(step, mesh=mesh,
                    in_specs=(P(), P("hvd"), P()), out_specs=P())(
        jnp.asarray(params), jnp.asarray(grads.reshape(-1)), state)

    for r in range(n):
        np.testing.assert_allclose(engine_params[r], np.asarray(out),
                                   rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# 2-D data×model mesh composition

def test_distributed_optimizer_2d_mesh_psums_data_axis_only():
    """Acceptance: on a dp×tp mesh, DistributedOptimizer psums over the
    data axis ONLY — gradients come out bitwise-identical across
    data-parallel replicas while tensor-parallel shards keep their own
    (different) values."""
    hvd.shutdown()
    DP, TP, K = 2, 4, 8
    mesh = create_mesh({"dp": DP, "tp": TP})
    rng = np.random.RandomState(0)
    # Params sharded over tp; batch sharded over dp.
    w = rng.randn(TP * K).astype(np.float32)
    x = rng.randn(DP * 4, TP * K).astype(np.float32)

    tx = hvd.DistributedOptimizer(optax.sgd(1.0), op=ReduceOp.AVERAGE)
    state = tx.init(jnp.asarray(w))

    def worker(w_shard, x_shard, s):
        # Per-replica gradient of a toy loss on this dp shard's batch
        # and this tp shard's parameter slice.
        g = jax.grad(lambda wv: jnp.sum((x_shard[:, :K] * wv) ** 2))(
            w_shard)
        upd, _ = tx.update(g, s, w_shard)
        # Expose every device's reduced update: leading (1, 1) dims map
        # onto (dp, tp) in the out spec.
        return upd[None, None, :]

    out = shard_map(
        worker, mesh=mesh,
        in_specs=(P("tp"), P("dp"), P()),
        out_specs=P("dp", "tp"),
    )(jnp.asarray(w), jnp.asarray(x), state)
    out = np.asarray(out)  # (DP, TP, K)

    # Bitwise identical across data-parallel replicas...
    assert np.array_equal(out[0], out[1])
    # ...and genuinely different across tensor-parallel shards (it did
    # NOT reduce over tp).
    assert not np.array_equal(out[0, 0], out[0, 1])

    # And the value is the dp-average of the per-replica gradients.
    for t in range(TP):
        g_reps = []
        for d in range(DP):
            xs = x[d * 4:(d + 1) * 4]
            ws = w[t * K:(t + 1) * K]
            g_reps.append(2 * np.sum(xs[:, :K] * (xs[:, :K] * ws), axis=0))
        want = -np.mean(g_reps, axis=0)  # sgd(1.0) update = -avg grad
        np.testing.assert_allclose(out[0, t], want, rtol=1e-5, atol=1e-5)


def test_allreduce_composes_with_model_axis_collective():
    """hvd.allreduce (data axis, auto-resolved) composes with an
    explicit model-axis psum in the same program."""
    hvd.shutdown()
    mesh = create_mesh({"dp": 2, "tp": 4})
    x = jnp.arange(8.0, dtype=jnp.float32)

    def worker(v):
        tp_sum = jax.lax.psum(v, "tp")          # model-parallel combine
        return hvd.allreduce(tp_sum, op=hvd.Sum)  # data-axis reduce

    out = shard_map(worker, mesh=mesh, in_specs=P(("dp", "tp")),
                    out_specs=P(("dp", "tp")))(x)
    # Each shard: psum over its dp-group's 4 tp shards, then summed
    # across the 2 dp groups -> the full sum of all 8 shard values.
    total = float(np.asarray(x).sum())
    np.testing.assert_allclose(np.asarray(out), np.full(8, total))


# ---------------------------------------------------------------------------
# Traced wire cast (the eager codec's stateless analogue)

def _psum2(x, **env):
    hvd.shutdown()
    mesh = create_mesh({"hvd": 2}, devices=jax.devices()[:2])
    for k, v in env.items():
        os.environ[k] = v
    try:
        return np.asarray(shard_map(
            lambda v: hvd.allreduce(v, op=hvd.Sum),
            mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"))(x))
    finally:
        for k in env:
            os.environ.pop(k, None)


def test_traced_wire_cast_bf16_rounds_and_upcasts():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2 * 4096).astype(np.float32))
    full = _psum2(x)
    cast = _psum2(x, HOROVOD_WIRE_COMPRESSION="bf16",
                  HOROVOD_WIRE_COMPRESSION_MIN_BYTES="0")
    assert cast.dtype == np.float32
    # bf16 rounding happened (values differ from the f32 path)...
    assert not np.array_equal(full, cast)
    # ...but stays within bf16 error bounds.
    np.testing.assert_allclose(full, cast, rtol=2e-2, atol=2e-2)
    # And matches the explicit cast-then-sum reference.
    import ml_dtypes

    halves = np.asarray(x).reshape(2, -1).astype(ml_dtypes.bfloat16)
    want = np.tile((halves[0] + halves[1]).astype(np.float32), 2)
    np.testing.assert_allclose(cast, want, rtol=1e-6, atol=1e-6)


def test_traced_wire_cast_respects_min_bytes_floor():
    x = jnp.asarray(np.random.RandomState(2).randn(64).astype(np.float32))
    full = _psum2(x)
    floored = _psum2(x, HOROVOD_WIRE_COMPRESSION="bf16",
                     HOROVOD_WIRE_COMPRESSION_MIN_BYTES="65536")
    # Payload under the floor: full-width, bitwise unchanged.
    np.testing.assert_array_equal(full, floored)


def test_traced_wire_cast_fp16_and_auto():
    x = jnp.asarray(np.random.RandomState(3).randn(2048).astype(np.float32))
    fp16 = _psum2(x, HOROVOD_WIRE_COMPRESSION="fp16",
                  HOROVOD_WIRE_COMPRESSION_MIN_BYTES="0")
    halves = np.asarray(x).reshape(2, -1).astype(np.float16)
    want = np.tile((halves[0] + halves[1]).astype(np.float32), 2)
    np.testing.assert_allclose(fp16, want, rtol=1e-6, atol=1e-6)
    # auto resolves to bf16 on the traced path.
    auto = _psum2(x, HOROVOD_WIRE_COMPRESSION="auto",
                  HOROVOD_WIRE_COMPRESSION_MIN_BYTES="0")
    bf16 = _psum2(x, HOROVOD_WIRE_COMPRESSION="bf16",
                  HOROVOD_WIRE_COMPRESSION_MIN_BYTES="0")
    np.testing.assert_array_equal(auto, bf16)


def test_traced_wire_cast_f32_only_and_sum_avg_only():
    # Integer tensors never cast.
    xi = jnp.arange(2 * 512, dtype=jnp.int32)
    full = _psum2(xi)
    cast = _psum2(xi, HOROVOD_WIRE_COMPRESSION="bf16",
                  HOROVOD_WIRE_COMPRESSION_MIN_BYTES="0")
    np.testing.assert_array_equal(full, cast)

    # MIN/MAX never cast.
    hvd.shutdown()
    mesh = create_mesh({"hvd": 2}, devices=jax.devices()[:2])
    xf = jnp.asarray(np.random.RandomState(4).randn(1024).astype(np.float32))
    os.environ["HOROVOD_WIRE_COMPRESSION"] = "bf16"
    os.environ["HOROVOD_WIRE_COMPRESSION_MIN_BYTES"] = "0"
    try:
        mn = shard_map(lambda v: hvd.allreduce(v, op=hvd.Min),
                       mesh=mesh, in_specs=P("hvd"), out_specs=P("hvd"))(xf)
    finally:
        os.environ.pop("HOROVOD_WIRE_COMPRESSION")
        os.environ.pop("HOROVOD_WIRE_COMPRESSION_MIN_BYTES")
    halves = np.asarray(xf).reshape(2, -1)
    np.testing.assert_array_equal(np.asarray(mn),
                                  np.tile(np.minimum(halves[0], halves[1]), 2))


def test_traced_compressed_counter_counts_at_trace_time():
    before = telemetry.default_registry().snapshot().get(
        'horovod_traced_compressed_ops_total{codec="bf16"}', 0)
    x = jnp.ones(4096, jnp.float32)
    _psum2(x, HOROVOD_WIRE_COMPRESSION="bf16",
           HOROVOD_WIRE_COMPRESSION_MIN_BYTES="0")
    after = telemetry.default_registry().snapshot().get(
        'horovod_traced_compressed_ops_total{codec="bf16"}', 0)
    assert after == before + 1


def test_traced_dispatch_counter(hvd_mesh):
    before = telemetry.default_registry().snapshot().get(
        'horovod_traced_ops_total{op="allreduce"}', 0)
    shard_map(lambda v: hvd.allreduce(v, op=hvd.Sum),
              mesh=hvd_mesh.mesh(), in_specs=P("hvd"),
              out_specs=P("hvd"))(jnp.ones(8))
    after = telemetry.default_registry().snapshot().get(
        'horovod_traced_ops_total{op="allreduce"}', 0)
    assert after == before + 1


# ---------------------------------------------------------------------------
# Goodput: traced optimizer updates demarcate at the host call boundary

def test_traced_optimizer_updates_demarcate_goodput(hvd_mesh):
    from horovod_tpu.common import goodput
    from horovod_tpu.common.telemetry import MetricsRegistry

    led = goodput.GoodputLedger(registry=MetricsRegistry(), rank=0,
                                enabled=True, stamp_path=None)
    prev = goodput.active()
    goodput.set_current(led)
    try:
        tx = hvd.DistributedOptimizer(optax.sgd(0.1))
        w = jnp.zeros(16, jnp.float32)
        state = tx.init(w)

        @jax.jit
        def step(w, s, g):
            upd, s2 = tx.update(g, s, w)
            return optax.apply_updates(w, upd), s2

        N = 5
        g = jnp.ones(16, jnp.float32)
        for _ in range(N):
            w, state = step(w, state, g)
        jax.block_until_ready(w)
        jax.effects_barrier()
        # One auto_step per EXECUTED step (the update body traced only
        # once) — the jitted loop is demarcated.
        assert led.steps == N, led.steps
        assert led.ratio() is not None and not np.isnan(led.ratio())
    finally:
        goodput.set_current(prev)


def test_traced_optimizer_demarcates_under_shard_map(hvd_mesh):
    """Under wrap_step/shard_map the marker fires once per host step,
    not once per device shard."""
    from horovod_tpu.common import goodput
    from horovod_tpu.common.telemetry import MetricsRegistry

    led = goodput.GoodputLedger(registry=MetricsRegistry(), rank=0,
                                enabled=True, stamp_path=None)
    prev = goodput.active()
    goodput.set_current(led)
    try:
        tx = hvd.DistributedOptimizer(optax.sgd(0.1))
        w = jnp.zeros(8, jnp.float32)
        state = tx.init(w)
        mesh = hvd_mesh.mesh()

        def step(w, s, x):
            g = jax.grad(lambda wv: jnp.sum(wv * x))(w)
            upd, s2 = tx.update(g, s, w)
            return optax.apply_updates(w, upd), s2

        sm = jax.jit(shard_map(step, mesh=mesh,
                               in_specs=(P(), P(), P("hvd")),
                               out_specs=(P(), P())))
        N = 4
        x = jnp.arange(8.0, dtype=jnp.float32)
        for _ in range(N):
            w, state = sm(w, state, x)
        jax.block_until_ready(w)
        jax.effects_barrier()
        assert led.steps == N, led.steps
    finally:
        goodput.set_current(prev)


def test_disabled_ledger_stages_no_marker(hvd_mesh):
    from horovod_tpu.common import goodput
    from horovod_tpu.common.telemetry import MetricsRegistry

    led = goodput.GoodputLedger(registry=MetricsRegistry(), rank=0,
                                enabled=False, stamp_path=None)
    prev = goodput.active()
    goodput.set_current(led)
    try:
        tx = hvd.DistributedOptimizer(optax.sgd(0.1))
        w = jnp.zeros(4, jnp.float32)
        state = tx.init(w)

        @jax.jit
        def step(w, s, g):
            upd, s2 = tx.update(g, s, w)
            return optax.apply_updates(w, upd), s2

        for _ in range(3):
            w, state = step(w, state, jnp.ones(4))
        jax.block_until_ready(w)
        jax.effects_barrier()
        assert led.steps == 0
    finally:
        goodput.set_current(prev)
