"""Autotuner tests (ref: the reference exercises its Bayesian machinery
through HOROVOD_AUTOTUNE runs; here: GP regression sanity, BO
convergence on a known surface, windowed parameter manager behavior, and
a live 2-rank engine run with autotuning enabled)."""
import os

import numpy as np
import pytest

from horovod_tpu.engine.bayesian import (
    BayesianOptimization,
    GaussianProcess,
    expected_improvement,
)
from horovod_tpu.engine.parameter_manager import ParameterManager


def test_gp_interpolates_training_points():
    gp = GaussianProcess(length_scale=0.5, noise=1e-8)
    x = np.array([[0.0], [0.5], [1.0]])
    y = np.array([0.0, 1.0, 0.0])
    gp.fit(x, y)
    mean, std = gp.predict(x)
    np.testing.assert_allclose(mean, y, atol=1e-3)
    assert (std < 0.01).all()


def test_gp_uncertainty_grows_away_from_data():
    gp = GaussianProcess(length_scale=0.2)
    gp.fit(np.array([[0.0]]), np.array([1.0]))
    _, std_near = gp.predict(np.array([[0.01]]))
    _, std_far = gp.predict(np.array([[1.0]]))
    assert std_far[0] > std_near[0]


def test_bo_finds_peak_of_quadratic():
    """Maximize -(x-0.7)^2-(y-0.3)^2 over [0,1]^2 in 25 samples."""
    bo = BayesianOptimization([(0.0, 1.0), (0.0, 1.0)], seed=1)

    def f(p):
        return -((p[0] - 0.7) ** 2) - (p[1] - 0.3) ** 2

    for _ in range(25):
        x = bo.next_sample()
        bo.register(x, f(x))
    best, best_y = bo.best
    assert abs(best[0] - 0.7) < 0.15 and abs(best[1] - 0.3) < 0.15, best


def test_parameter_manager_window_and_convergence(tmp_path):
    log = tmp_path / "autotune.csv"
    pm = ParameterManager(
        is_coordinator=True, enabled=True, warmup_samples=1,
        cycles_per_sample=5, max_samples=6, log_path=str(log),
    )
    initial = (pm.fusion_threshold, pm.cycle_time_ms)
    syncs = 0
    for cycle in range(500):
        if pm.update(1 << 20):
            syncs += 1
        if pm.done:
            break
    assert pm.done
    # warmup window is discarded; each subsequent window syncs.
    assert syncs == 6
    lines = log.read_text().strip().splitlines()
    assert lines[0].startswith("sample,")
    assert len(lines) == 1 + 6
    # Tuned values stay inside the box.
    assert 1 * 1024 * 1024 <= pm.fusion_threshold <= 64 * 1024 * 1024
    assert 1.0 <= pm.cycle_time_ms <= 25.0


def test_parameter_manager_disabled_noop():
    pm = ParameterManager(is_coordinator=True, enabled=False)
    assert pm.done
    assert pm.update(123) is False


def test_parameter_sync_serialization_roundtrip():
    pm0 = ParameterManager(is_coordinator=True, enabled=True)
    pm0.fusion_threshold = 12345678
    pm0.cycle_time_ms = 7.5
    pm0.done = True
    pm1 = ParameterManager(is_coordinator=False, enabled=True)
    pm1.apply(pm0.serialize())
    assert pm1.fusion_threshold == 12345678
    assert pm1.cycle_time_ms == 7.5
    assert pm1.done


def test_autotune_live_two_rank_engine(monkeypatch):
    """End to end: two in-process ranks run allreduces with autotune on;
    tuning completes, both ranks converge to identical parameters, and
    results stay correct throughout."""
    import sys

    sys.path.insert(0, os.path.dirname(__file__))
    from test_engine import run_ranks

    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")

    def fn(eng, rank):
        # Small windows so tuning finishes fast.
        eng.param_manager.cycles_per_sample = 2
        eng.param_manager.max_samples = 3
        eng.param_manager.warmup_samples = 1
        for i in range(200):
            out = eng.synchronize(
                eng.enqueue_allreduce(
                    np.full(8, float(rank + 1), np.float32), name=f"g{i % 4}"
                ),
                timeout=30,
            )
            np.testing.assert_allclose(out, np.full(8, 3.0))
            if eng.param_manager.done:
                break
        return (eng.param_manager.done, eng.param_manager.fusion_threshold,
                eng.param_manager.cycle_time_ms)

    out = run_ranks(2, fn)
    assert out[0][0] and out[1][0], out
    assert out[0][1] == out[1][1]  # identical tuned fusion threshold
    assert out[0][2] == out[1][2]  # identical tuned cycle time
