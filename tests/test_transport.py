"""Transport conformance suite: the SAME contract checks run against
every data-plane transport — the TCP socket mesh, the shared-memory
overlay (rings + arena), and the in-process test transport.

What "conformance" pins down (backend/transport.py):

* framing round-trip — bytes | bytearray | memoryview | numpy | list of
  buffers | empty frames all arrive intact, as exclusively-owned
  writable buffers;
* channel demux — frames on different channel tags never steal each
  other's payloads, whatever order they are consumed in;
* recv_into exact-length contract — a length mismatch is a desynced
  peer: sever + TransportError with the shared
  HOROVOD_RING_SEGMENT_BYTES hint (base.desync_message — the text can
  no longer drift between transports);
* sever propagation — declare_dead unblocks parked I/O NOW and every
  later op carries the attributed verdict;
* activity evidence — received frames (and the idle drain / progress
  sweep) feed peer_activity, the liveness plane's food;
* fault injection — sever / delay / drop rules fire identically via
  the shared injector hooks (wedge is process-level and exercised by
  scripts/chaos_smoke.py --transport shm and tests/test_health.py).
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from horovod_tpu.backend.base import channel_scope, desync_message
from horovod_tpu.common import fault_injection
from horovod_tpu.common.exceptions import TransportError
from horovod_tpu.common.fault_injection import Rule
from horovod_tpu.common.telemetry import MetricsRegistry

KINDS = ["inproc", "tcp", "shm"]

# Data-plane channel used by every check: shm routing only engages for
# data channels (control/heartbeat frames always ride the sockets), so
# running the whole suite inside this scope exercises the overlay on
# the "shm" kind and plain sockets on "tcp".
DATA_CH = 0


class _Pair:
    def __init__(self, kind, b0, b1, regs, server):
        self.kind = kind
        self.b0 = b0
        self.b1 = b1
        self.regs = regs
        self.server = server

    def close(self):
        for b in (self.b0, self.b1):
            try:
                b.shutdown()
            except Exception:
                pass
        if self.server is not None:
            self.server.stop()


def _make_pair(kind, scope, monkeypatch) -> _Pair:
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")
    if kind == "inproc":
        from horovod_tpu.backend.transport import make_inproc_backends

        b0, b1 = make_inproc_backends(2, timeout=10.0)
        return _Pair(kind, b0, b1, None, None)

    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.backend.tcp import TcpBackend
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    monkeypatch.setenv("HVDRUN_FORCE_LOCAL", "1")
    if kind == "shm":
        monkeypatch.setenv("HOROVOD_TRANSPORT", "auto")
    else:
        # Explicit pin: the default is `auto` now, and this is the leg
        # whose whole point is exercising the raw socket plane (its
        # byte/frame assertions are tcp-only).
        monkeypatch.setenv("HOROVOD_TRANSPORT", "tcp")
    server = RendezvousServer()
    port = server.start()
    rdv = RendezvousClient("127.0.0.1", port)
    regs = [MetricsRegistry(), MetricsRegistry()]
    backends = [None, None]
    errs = []

    def build(rank):
        try:
            backends[rank] = TcpBackend(rank, 2, rendezvous=rdv,
                                        scope=scope, registry=regs[rank])
        except BaseException as e:  # pragma: no cover - bootstrap bug
            errs.append(e)

    threads = [threading.Thread(target=build, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errs, errs
    assert backends[0] is not None and backends[1] is not None
    if kind == "shm":
        # Establishment must actually have happened — a silent fallback
        # to tcp would make the whole suite vacuous.
        assert 1 in backends[0]._overlays and 0 in backends[1]._overlays
    return _Pair(kind, backends[0], backends[1], regs, server)


@pytest.fixture(params=KINDS)
def pair(request, monkeypatch):
    scope = f"t_conform_{request.param}_{request.node.name[:24]}"
    scope = "".join(c if c.isalnum() or c == "_" else "_" for c in scope)
    p = _make_pair(request.param, scope, monkeypatch)
    try:
        yield p
    finally:
        fault_injection.injector.clear()
        p.close()


def _both(fn0, fn1, timeout=30):
    out = [None, None]
    errs = [None, None]

    def run(i, fn):
        try:
            out[i] = fn()
        except BaseException as e:  # noqa: BLE001
            errs[i] = e

    ts = [threading.Thread(target=run, args=(i, f))
          for i, f in enumerate((fn0, fn1))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=timeout)
    for e in errs:
        if e is not None:
            raise e
    return out


# ---------------------------------------------------------------------------
def test_framing_roundtrip_all_buffer_shapes(pair):
    payloads = [
        (b"plain", b"plain"),
        (bytearray(b"ba"), b"ba"),
        (memoryview(b"mv"), b"mv"),
        (np.array([1, 2], np.uint8), b"\x01\x02"),
        ([b"x", b"", b"y"], b"xy"),
        (b"", b""),
        (np.arange(1000, dtype=np.float32),
         np.arange(1000, dtype=np.float32).tobytes()),
    ]

    def sender():
        with channel_scope(DATA_CH):
            for data, _ in payloads:
                pair.b0.send_to(1, data)

    def receiver():
        got = []
        with channel_scope(DATA_CH):
            for _ in payloads:
                got.append(pair.b1.recv_from(0))
        return got

    _, got = _both(sender, receiver)
    for (_, expect), buf in zip(payloads, got):
        assert bytes(buf) == expect
        # Owned-buffer contract: every received frame is writable and
        # exclusively the receiver's (unpack_array aliases it).
        if len(buf):
            view = memoryview(buf)
            assert not view.readonly


def test_channel_demux_out_of_order_consumption(pair):
    def sender():
        with channel_scope(3):
            pair.b0.send_to(1, b"ch3-first")
        with channel_scope(5):
            pair.b0.send_to(1, b"ch5-second")

    def receiver():
        with channel_scope(5):
            five = pair.b1.recv_from(0)
        with channel_scope(3):
            three = pair.b1.recv_from(0)
        return bytes(five), bytes(three)

    _, (five, three) = _both(sender, receiver)
    assert five == b"ch5-second" and three == b"ch3-first"


def test_recv_into_exact_and_desync_severs(pair):
    src = np.arange(256, dtype=np.float32)

    def sender():
        with channel_scope(DATA_CH):
            pair.b0.send_to(1, src)
            pair.b0.send_to(1, b"runt")

    def receiver():
        with channel_scope(DATA_CH):
            dst = np.empty_like(src)
            n = pair.b1.recv_into_from(0, dst)
            assert n == src.nbytes
            np.testing.assert_array_equal(dst, src)
            # Second frame: 4 bytes against a 1KB buffer = desynced.
            with pytest.raises(
                    (TransportError, Exception),
                    match="HOROVOD_RING_SEGMENT_BYTES") as ei:
                pair.b1.recv_into_from(0, np.empty_like(src))
            return ei

    _both(sender, receiver)


def test_desync_message_is_the_single_source_of_truth():
    msg = desync_message(4, 1024, rank=1, peer=0)
    assert "frame length 4 != expected 1024" in msg
    assert "HOROVOD_RING_SEGMENT_BYTES" in msg
    assert "desynced peer" in msg


def test_sever_unblocks_parked_recv_with_verdict(pair):
    reason = "rank 0 declared dead by rank 1: no heartbeat (test)"
    errs = {}

    def receiver():
        try:
            with channel_scope(DATA_CH):
                pair.b1.recv_from(0)
        except TransportError as e:
            errs["e"] = e

    t = threading.Thread(target=receiver)
    t.start()
    time.sleep(0.3)
    pair.b1.declare_dead(0, reason)
    t.join(timeout=10)
    assert not t.is_alive(), "sever did not unblock the parked recv"
    assert reason in str(errs["e"])
    # Later ops fail fast with the same latched root cause.
    with pytest.raises(TransportError, match="no heartbeat"):
        with channel_scope(DATA_CH):
            pair.b1.recv_from(0)
    assert pair.b1.death_reason(0) == reason


def test_send_async_ticket_completes_and_fails_after_sever(pair):
    def sender():
        with channel_scope(DATA_CH):
            t1 = pair.b0.send_async(1, b"ticketed")
            t1.wait()

    def receiver():
        with channel_scope(DATA_CH):
            return bytes(pair.b1.recv_from(0))

    _, got = _both(sender, receiver)
    assert got == b"ticketed"
    pair.b0.declare_dead(1, "peer 1 is gone (test)")
    with pytest.raises(TransportError):
        with channel_scope(DATA_CH):
            pair.b0.send_async(1, b"late").wait()


def test_activity_evidence_from_frames_and_idle_drain(pair):
    assert pair.b1.peer_activity(0) is None

    def sender():
        with channel_scope(DATA_CH):
            pair.b0.send_to(1, b"proof-of-life")

    def receiver():
        with channel_scope(DATA_CH):
            pair.b1.recv_from(0)

    _both(sender, receiver)
    t0 = pair.b1.peer_activity(0)
    assert t0 is not None

    # A frame nobody receives still surfaces as evidence through the
    # liveness sweep (consumed into an inbox on tcp/inproc; observed
    # as ring write-cursor progress on shm).
    with channel_scope(DATA_CH):
        pair.b0.send_to(1, b"unclaimed")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        pair.b1.try_drain_idle(0)
        t1 = pair.b1.peer_activity(0)
        if t1 is not None and t1 > t0:
            break
        time.sleep(0.05)
    assert pair.b1.peer_activity(0) > t0, \
        "idle drain produced no activity evidence"


def test_injected_sever_translates(pair):
    fault_injection.injector.install(
        [Rule(action="sever", peer=0, rank=1, op="recv")])
    try:
        with pytest.raises(TransportError, match="severed"):
            with channel_scope(DATA_CH):
                pair.b1.recv_from(0)
    finally:
        fault_injection.injector.clear()


def test_injected_delay_applies(pair):
    fault_injection.injector.install(
        [Rule(action="delay", peer=1, rank=0, secs=0.4, op="send")])
    try:
        def sender():
            t0 = time.monotonic()
            with channel_scope(DATA_CH):
                pair.b0.send_to(1, b"slow")
            return time.monotonic() - t0

        def receiver():
            with channel_scope(DATA_CH):
                return bytes(pair.b1.recv_from(0))

        elapsed, got = _both(sender, receiver)
        assert got == b"slow"
        assert elapsed >= 0.4
    finally:
        fault_injection.injector.clear()


def test_injected_drop_starves_receiver_into_timeout(pair, monkeypatch):
    # Diskless drop: the send silently vanishes; the receiver's idle
    # bound must fire (bounded-time detection, not a hang).
    fault_injection.injector.install(
        [Rule(action="drop", peer=1, rank=0, op="send")])
    try:
        def sender():
            with channel_scope(DATA_CH):
                pair.b0.send_to(1, b"dropped")

        def receiver():
            with pytest.raises(TransportError):
                with channel_scope(DATA_CH):
                    pair.b1.recv_from(0)

        _both(sender, receiver, timeout=60)
    finally:
        fault_injection.injector.clear()


# ---------------------------------------------------------------------------
# transport-specific conformance extras
def test_shm_route_actually_moves_bytes_over_shm(monkeypatch):
    p = _make_pair("shm", "t_shm_counters", monkeypatch)
    try:
        payload = np.arange(4096, dtype=np.float32)

        def sender():
            with channel_scope(DATA_CH):
                p.b0.send_to(1, payload)

        def receiver():
            with channel_scope(DATA_CH):
                return p.b1.recv_from(0)

        _both(sender, receiver)
        sent = p.regs[0].snapshot().get(
            'horovod_transport_bytes_total'
            '{direction="sent",transport="shm"}', 0)
        recv = p.regs[1].snapshot().get(
            'horovod_transport_bytes_total'
            '{direction="recv",transport="shm"}', 0)
        # Exact per-transport accounting: payload + 9-byte frame header.
        assert sent == payload.nbytes + 9, sent
        assert recv == payload.nbytes + 9, recv

        # Control-plane bytes must NOT ride shm: a ctrl round moves tcp
        # counters only.
        before = sent

        def words0():
            return p.b0.allreduce_words([3], "and")

        def words1():
            return p.b1.allreduce_words([1], "and")

        w0, _ = _both(words0, words1)
        assert w0 == [1]
        assert p.regs[0].snapshot().get(
            'horovod_transport_bytes_total'
            '{direction="sent",transport="shm"}', 0) == before
        assert p.regs[0].snapshot().get(
            'horovod_transport_bytes_total'
            '{direction="sent",transport="tcp"}', 0) > 0
    finally:
        p.close()


def test_shm_ring_backpressure_counted(monkeypatch):
    monkeypatch.setenv("HOROVOD_SHM_RING_BYTES", str(1 << 16))
    p = _make_pair("shm", "t_shm_backpressure", monkeypatch)
    try:
        big = np.zeros(1 << 18, dtype=np.float32)  # 1MB through 64KB ring

        def sender():
            with channel_scope(DATA_CH):
                p.b0.send_to(1, big)

        def receiver():
            time.sleep(0.2)  # let the ring fill before draining
            with channel_scope(DATA_CH):
                return p.b1.recv_from(0)

        _, got = _both(sender, receiver)
        assert len(got) == big.nbytes
        stalls = p.regs[0].snapshot().get("horovod_shm_ring_full_total", 0)
        assert stalls >= 1, "a 1MB frame through a 64KB ring never stalled?"
    finally:
        p.close()


def test_shm_transport_route_flips_per_call(monkeypatch):
    p = _make_pair("shm", "t_shm_flip", monkeypatch)
    try:
        key = ('horovod_transport_bytes_total'
               '{direction="sent",transport="shm"}')

        def xfer():
            def s():
                with channel_scope(DATA_CH):
                    p.b0.send_to(1, b"x" * 64)

            def r():
                with channel_scope(DATA_CH):
                    return p.b1.recv_from(0)

            _both(s, r)

        xfer()
        after_shm = p.regs[0].snapshot().get(key, 0)
        assert after_shm > 0
        os.environ["HOROVOD_TRANSPORT"] = "tcp"
        try:
            xfer()
            assert p.regs[0].snapshot().get(key, 0) == after_shm
        finally:
            os.environ["HOROVOD_TRANSPORT"] = "auto"
        xfer()
        assert p.regs[0].snapshot().get(key, 0) > after_shm
    finally:
        p.close()


def test_shm_arena_allreduce_and_sever(monkeypatch):
    from horovod_tpu.common.types import ReduceOp

    monkeypatch.setenv("HOROVOD_RING_THRESHOLD", "0")
    p = _make_pair("shm", "t_shm_arena", monkeypatch)
    try:
        assert p.b0.arena_set is not None and p.b1.arena_set is not None
        n = 100001

        def r0():
            with channel_scope(DATA_CH):
                return p.b0.allreduce(
                    np.arange(n, dtype=np.float64), ReduceOp.SUM)

        def r1():
            with channel_scope(DATA_CH):
                return p.b1.allreduce(
                    np.arange(n, dtype=np.float64) * 2, ReduceOp.SUM)

        out0, out1 = _both(r0, r1)
        want = np.arange(n, dtype=np.float64) * 3
        np.testing.assert_array_equal(out0, want)
        np.testing.assert_array_equal(out1, want)
        # Arena bytes count under the shm transport label.
        assert p.regs[0].snapshot().get(
            'horovod_transport_bytes_total'
            '{direction="sent",transport="shm"}', 0) >= n * 8

        # A death verdict unblocks a parked arena barrier with the
        # attributed reason (heartbeats ride TCP; the verdict severs).
        reason = "rank 1 declared dead by rank 0: wedged (test)"
        errs = {}

        def stuck():
            try:
                with channel_scope(DATA_CH):
                    p.b0.allreduce(np.ones(1024, np.float32),
                                   ReduceOp.SUM)
            except TransportError as e:
                errs["e"] = e

        t = threading.Thread(target=stuck)
        t.start()
        time.sleep(0.3)
        p.b0.declare_dead(1, reason)
        t.join(timeout=10)
        assert not t.is_alive(), "arena barrier did not unblock on sever"
        assert reason in str(errs["e"])
    finally:
        p.close()


def test_tcp_base_transport_objects_cover_every_peer(monkeypatch):
    p = _make_pair("tcp", "t_base_transports", monkeypatch)
    try:
        from horovod_tpu.backend.tcp import TcpTransport

        assert set(p.b0._transports) == {1}
        assert isinstance(p.b0._transports[1], TcpTransport)
        assert p.b0._transports[1].alive
        st = p.b0.transport_status()
        assert st["mode"] == "tcp"
        assert st["peers"]["1"]["overlay"] is None
    finally:
        p.close()


def test_transport_registry_rejects_unknown_names():
    from horovod_tpu.backend.transport import (
        create_transport,
        transport_names,
    )

    assert {"tcp", "inproc"} <= set(transport_names())
    with pytest.raises(ValueError, match="unknown transport"):
        create_transport("carrier-pigeon", None, 0)


def test_one_sided_shm_failure_degrades_whole_pair_to_tcp(monkeypatch):
    """Establishment is pairwise agreed: if one side cannot set up its
    rings (unwritable shm dir), BOTH sides must stay on tcp — a
    one-sided route would park the succeeding side's recv on a ring
    nobody writes, forever under unbounded timeouts."""
    from horovod_tpu.backend import shm as shm_mod
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.backend.tcp import TcpBackend
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    monkeypatch.setenv("HVDRUN_FORCE_LOCAL", "1")
    monkeypatch.setenv("HOROVOD_TRANSPORT", "auto")
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "10")

    orig_init = shm_mod.ShmTransport.__init__

    def failing_init(self, backend, peer, **kw):
        if backend.rank == 1:
            raise OSError("simulated unwritable shm dir")
        orig_init(self, backend, peer, **kw)

    monkeypatch.setattr(shm_mod.ShmTransport, "__init__", failing_init)

    server = RendezvousServer()
    port = server.start()
    rdv = RendezvousClient("127.0.0.1", port)
    backends = [None, None]
    errs = []

    def build(rank):
        try:
            backends[rank] = TcpBackend(rank, 2, rendezvous=rdv,
                                        scope="t_one_sided_shm")
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=build, args=(r,)) for r in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    try:
        assert not errs, errs
        b0, b1 = backends
        # Rank 1's local failure votes the PAIR down on both sides.
        assert b0._overlays == {} and b1._overlays == {}
        assert b0.arena_set is None and b1.arena_set is None
        # ...and data-channel traffic still flows, over the sockets.
        got = {}

        def sender():
            with channel_scope(DATA_CH):
                b0.send_to(1, b"over tcp after all")

        def receiver():
            with channel_scope(DATA_CH):
                got["v"] = bytes(b1.recv_from(0))

        _both(sender, receiver)
        assert got["v"] == b"over tcp after all"
    finally:
        for b in backends:
            if b is not None:
                b.shutdown()
        server.stop()
