"""Init/rank/size introspection tests (ref: reference test/test_torch.py
rank/size fixtures + basics API)."""
import numpy as np
import pytest


def test_init_mesh_mode(hvd_mesh):
    hvd = hvd_mesh
    assert hvd.is_initialized()
    assert hvd.mode() == "mesh"
    assert hvd.size() == 8  # virtual CPU devices
    assert hvd.rank() == 0
    assert hvd.local_size() == 8
    assert hvd.mesh() is not None
    assert hvd.axis_name() == "hvd"


def test_double_init_is_noop(hvd_mesh):
    hvd = hvd_mesh
    m = hvd.mesh()
    hvd.init()
    assert hvd.mesh() is m


def test_shutdown_and_reinit():
    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init()
    assert hvd.is_initialized()
    hvd.shutdown()
    assert not hvd.is_initialized()
    with pytest.raises(RuntimeError):
        hvd.rank()
    hvd.init()
    assert hvd.size() == 8
    hvd.shutdown()


def test_builtins_introspection(hvd_mesh):
    hvd = hvd_mesh
    assert hvd.xla_built()
    assert hvd.gloo_built()  # TCP backend is the gloo-equivalent
    assert not hvd.mpi_built()
    assert not hvd.nccl_built()
    assert hvd.is_homogeneous()
