"""Preemption plane tests (docs/fault_tolerance.md "Announced
preemption"): drain knob parsing, the chaos injector's `preempt`
action, DrainCoordinator semantics, preemption-vs-failure badput
attribution with the stamp release/adopt handoff, the elasticity
controller's decision table, per-job KV namespaces on one rendezvous
server, and the strike-free drain quarantine."""
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common import drain as drain_mod
from horovod_tpu.common import goodput as goodput_mod
from horovod_tpu.common import telemetry
from horovod_tpu.common.exceptions import WorkerPreempted
from horovod_tpu.common.fault_injection import injector, parse_spec
from horovod_tpu.runner.elastic import controller as ctl
from horovod_tpu.runner.elastic.discovery import (
    FixedHosts, HostManager, HostUpdateResult,
)
from horovod_tpu.runner.rendezvous_server import (
    RendezvousServer, arbitrate_capacity,
)
from horovod_tpu.utils import env as env_cfg


@pytest.fixture(autouse=True)
def _clean_drain_state(monkeypatch):
    """Every test starts with a fresh coordinator and injector, and
    none of the drain knobs leaking in from the host environment."""
    for var in (env_cfg.DRAIN_GRACE_SECONDS, env_cfg.PREEMPT_SIGNAL,
                env_cfg.CONTROLLER_INTERVAL_SECONDS, env_cfg.JOB_NAME,
                env_cfg.FLEET_SLOTS):
        monkeypatch.delenv(var, raising=False)
        monkeypatch.delenv(var.replace("HOROVOD_", "HVD_TPU_", 1),
                           raising=False)
    injector.clear()
    drain_mod.coordinator.reset()
    yield
    injector.clear()
    drain_mod.coordinator.reset()


def _registry():
    return telemetry.MetricsRegistry()


# ---------------------------------------------------------------------------
# Env knobs


def test_drain_knob_defaults():
    assert env_cfg.drain_grace_seconds() == 30.0
    assert env_cfg.preempt_signal() == signal.SIGTERM
    assert env_cfg.controller_interval_seconds() == 30.0
    assert env_cfg.job_name() == ""
    assert env_cfg.job_kv_prefix() == ""
    assert env_cfg.fleet_slots() == 0


def test_drain_knobs_parse(monkeypatch):
    monkeypatch.setenv(env_cfg.DRAIN_GRACE_SECONDS, "12.5")
    monkeypatch.setenv(env_cfg.PREEMPT_SIGNAL, "SIGUSR1")
    monkeypatch.setenv(env_cfg.CONTROLLER_INTERVAL_SECONDS, "5")
    monkeypatch.setenv(env_cfg.JOB_NAME, "trainer-a")
    monkeypatch.setenv(env_cfg.FLEET_SLOTS, "16")
    assert env_cfg.drain_grace_seconds() == 12.5
    assert env_cfg.preempt_signal() == signal.SIGUSR1
    assert env_cfg.controller_interval_seconds() == 5.0
    assert env_cfg.job_name() == "trainer-a"
    assert env_cfg.job_kv_prefix() == "jobs/trainer-a/"
    assert env_cfg.fleet_slots() == 16


def test_drain_knobs_hvd_tpu_alias(monkeypatch):
    monkeypatch.setenv("HVD_TPU_DRAIN_GRACE_SECONDS", "7")
    monkeypatch.setenv("HVD_TPU_PREEMPT_SIGNAL", "USR2")
    monkeypatch.setenv("HVD_TPU_JOB_NAME", "b")
    assert env_cfg.drain_grace_seconds() == 7.0
    assert env_cfg.preempt_signal() == signal.SIGUSR2
    assert env_cfg.job_kv_prefix() == "jobs/b/"


def test_drain_knobs_bogus_fall_back_to_defaults(monkeypatch):
    monkeypatch.setenv(env_cfg.DRAIN_GRACE_SECONDS, "soon")
    monkeypatch.setenv(env_cfg.PREEMPT_SIGNAL, "SIGBOGUS")
    monkeypatch.setenv(env_cfg.CONTROLLER_INTERVAL_SECONDS, "often")
    monkeypatch.setenv(env_cfg.FLEET_SLOTS, "many")
    assert env_cfg.drain_grace_seconds() == 30.0
    assert env_cfg.preempt_signal() == signal.SIGTERM
    assert env_cfg.controller_interval_seconds() == 30.0
    assert env_cfg.fleet_slots() == 0


def test_preempt_signal_numeric(monkeypatch):
    monkeypatch.setenv(env_cfg.PREEMPT_SIGNAL, str(int(signal.SIGUSR1)))
    assert env_cfg.preempt_signal() == signal.SIGUSR1


def test_job_name_sanitized(monkeypatch):
    # A name with path-meta characters must not break the KV layout.
    monkeypatch.setenv(env_cfg.JOB_NAME, "a/b c!")
    prefix = env_cfg.job_kv_prefix()
    assert prefix.startswith("jobs/") and prefix.endswith("/")
    assert "/" not in prefix[len("jobs/"):-1]
    assert " " not in prefix and "!" not in prefix


# ---------------------------------------------------------------------------
# Chaos injector: the `preempt` action


def test_preempt_rule_parses_step_and_secs():
    rules = parse_spec("preempt:step=4;preempt:secs=2.5:rank=1")
    assert rules[0].action == "preempt" and rules[0].step == 4
    assert rules[1].secs == 2.5 and rules[1].rank == 1


def test_preempt_rule_requires_trigger():
    with pytest.raises(ValueError):
        parse_spec("preempt")


def test_preempt_step_trigger_fires_once(monkeypatch):
    """advance_step past the trigger delivers the preemption signal to
    the process exactly once — the installed drain handler turns it
    into a drain request instead of a death."""
    monkeypatch.setenv(env_cfg.DRAIN_GRACE_SECONDS, "600")
    coord = drain_mod.coordinator
    assert coord.install(managed=True)
    counter_before = drain_mod._m_preemptions().value
    injector.add_rule(parse_spec("preempt:step=2")[0])
    injector.advance_step()
    assert not coord.pending()
    injector.advance_step()
    assert coord.pending()
    # Fire-once: further steps do not re-deliver.
    injector.advance_step()
    assert drain_mod._m_preemptions().value == counter_before + 1


def test_preempt_rules_do_not_consume_io_checks():
    injector.add_rule(parse_spec("preempt:step=100")[0])
    assert injector.check_io(0, 1, "send") == "pass"  # no hit consumed
    assert injector._rules[0].hits == 0


# ---------------------------------------------------------------------------
# DrainCoordinator semantics


def test_unmanaged_notice_exits_zero():
    coord = drain_mod.coordinator
    exits = []
    coord._exit = exits.append
    coord.request("platform notice")
    assert exits == [0]


def test_managed_notice_defers_to_commit(monkeypatch):
    monkeypatch.setenv(env_cfg.DRAIN_GRACE_SECONDS, "600")
    coord = drain_mod.coordinator
    coord.set_managed(True)
    exits = []
    coord._exit = exits.append
    coord.request("spot reclaim")
    assert coord.pending() and exits == []
    assert coord.reason == "spot reclaim"
    # Idempotent: a duplicate signal neither re-counts nor re-arms.
    before = drain_mod._m_preemptions().value
    coord.request("dup")
    assert coord.reason == "spot reclaim"
    assert drain_mod._m_preemptions().value == before


def test_grace_deadline_forces_exit(monkeypatch):
    monkeypatch.setenv(env_cfg.DRAIN_GRACE_SECONDS, "0.05")
    coord = drain_mod.coordinator
    coord.set_managed(True)
    exited = threading.Event()
    coord._exit = lambda code: exited.set()
    coord.request("reclaim")
    assert exited.wait(5.0), "grace deadline never fired"


def test_checkpoint_budget_tracks_grace(monkeypatch):
    monkeypatch.setenv(env_cfg.DRAIN_GRACE_SECONDS, "20")
    coord = drain_mod.coordinator
    coord.set_managed(True)
    coord._exit = lambda code: None
    coord.request("reclaim")
    assert 1.0 <= coord.checkpoint_budget() <= 18.0


def test_execute_releases_and_raises(monkeypatch, tmp_path):
    monkeypatch.setenv(env_cfg.DRAIN_GRACE_SECONDS, "600")
    reg = _registry()
    led = goodput_mod.GoodputLedger(
        registry=reg, rank=0, enabled=True,
        stamp_path=str(tmp_path / "goodput.json"))
    goodput_mod.set_current(led)
    try:
        coord = drain_mod.coordinator
        coord.set_managed(True)
        coord._exit = lambda code: None
        coord.request("reclaim")
        with pytest.raises(WorkerPreempted):
            coord.execute(state=None)
        doc = json.loads((tmp_path / "goodput.json").read_text())
        assert doc["draining"] is True
    finally:
        goodput_mod.set_current(None)


def test_worker_preempted_is_clean_exit():
    assert issubclass(WorkerPreempted, SystemExit)
    assert WorkerPreempted("x").code == 0


def test_fleet_draining_peer_attribution():
    coord = drain_mod.coordinator
    assert not coord.fleet_draining()
    coord.note_peer_draining()
    assert coord.fleet_draining()
    assert not coord.fleet_draining(window=0.0)


def test_commit_barrier_runs_save_now_uninitialized(monkeypatch):
    """Outside an initialized world the barrier skips the collective
    but a pending drain still checkpoints and departs."""
    monkeypatch.setenv(env_cfg.DRAIN_GRACE_SECONDS, "600")
    coord = drain_mod.coordinator
    coord.set_managed(True)
    coord._exit = lambda code: None
    coord.request("reclaim")

    calls = []

    class FakeMgr:
        def save_now(self, state, timeout):
            calls.append(timeout)
            return True

    class FakeState:
        _checkpoint_manager = FakeMgr()

    with pytest.raises(WorkerPreempted):
        drain_mod.commit_barrier(FakeState())
    assert len(calls) == 1 and calls[0] >= 1.0


def test_commit_barrier_noop_when_unmanaged():
    state = object()  # would explode if touched
    drain_mod.commit_barrier(state)


# ---------------------------------------------------------------------------
# Badput attribution: preemption vs failure, stamp handoff


def test_disruption_bucket_routing():
    led = goodput_mod.GoodputLedger(registry=_registry(), rank=0,
                                    enabled=True)
    led.disruption_begin("drain", bucket="preemption")
    time.sleep(0.01)
    led.disruption_end()
    assert led.preempt_seconds > 0.0
    assert led.downtime_seconds == 0.0


def test_disruption_upgrades_to_preemption():
    """The collective failure is bracketed first; the drain notice
    arrives after — the open window upgrades, never downgrades."""
    led = goodput_mod.GoodputLedger(registry=_registry(), rank=0,
                                    enabled=True)
    led.disruption_begin("collective failure", bucket="failure")
    led.disruption_begin("peer draining", bucket="preemption")
    led.disruption_begin("late failure evidence", bucket="failure")
    time.sleep(0.01)
    led.disruption_end()
    assert led.preempt_seconds > 0.0
    assert led.downtime_seconds == 0.0


def test_stamp_release_and_adopt_roundtrip(tmp_path):
    """Owner releases at drain; a promoted survivor adopts: totals fold
    into its prior lifetime, generation advances, no double count."""
    p = str(tmp_path / "goodput.json")
    led1 = goodput_mod.GoodputLedger(registry=_registry(), rank=0,
                                     enabled=True, stamp_path=p)
    led1.steps = 5
    led1.step_seconds = 2.0
    led1.committed_step = 5
    assert led1.release_stamp()

    led2 = goodput_mod.GoodputLedger(registry=_registry(), rank=1,
                                     enabled=True, stamp_path=p)
    led2.steps = 3  # survivor's own (already-stamped-by-owner) window
    assert led2.try_adopt_stamp()
    assert led2.prior_steps == 5
    assert led2.steps == 0          # own window dropped, not doubled
    assert led2.generation == 2
    # Adoption confers ownership: the survivor stamps from here on.
    assert led2._stamp_owner


def test_adopt_refuses_unreleased_stamp(tmp_path):
    p = str(tmp_path / "goodput.json")
    led1 = goodput_mod.GoodputLedger(registry=_registry(), rank=0,
                                     enabled=True, stamp_path=p)
    led1.stamp(force=True)  # a live, NOT-draining stamp
    led2 = goodput_mod.GoodputLedger(registry=_registry(), rank=1,
                                     enabled=True, stamp_path=p)
    assert not led2.try_adopt_stamp()


def test_restart_gap_after_drain_is_preemption_badput(tmp_path):
    """A follow-up lifetime that loads a `draining` stamp attributes
    the restart gap to the preemption bucket, not failure."""
    p = tmp_path / "goodput.json"
    led1 = goodput_mod.GoodputLedger(registry=_registry(), rank=0,
                                     enabled=True, stamp_path=str(p))
    assert led1.release_stamp()
    doc = json.loads(p.read_text())
    doc["stamp_wall"] = time.time() - 5.0
    p.write_text(json.dumps(doc))

    led2 = goodput_mod.GoodputLedger(registry=_registry(), rank=0,
                                     enabled=True, stamp_path=str(p))
    assert led2.preempt_seconds >= 4.0
    assert led2.downtime_seconds == 0.0
    assert led2.generation == 2


# ---------------------------------------------------------------------------
# Elasticity controller: the decision table


def test_decide_scale_up_on_idle_capacity():
    action, target, _ = ctl.decide(current_np=4, min_np=2, max_np=8,
                                   available_slots=6)
    assert (action, target) == (ctl.SCALE_UP, 6)


def test_decide_scale_up_capped_by_max_np():
    action, target, _ = ctl.decide(current_np=4, min_np=2, max_np=5,
                                   available_slots=8)
    assert (action, target) == (ctl.SCALE_UP, 5)


def test_decide_scale_up_capped_by_grant():
    action, target, _ = ctl.decide(current_np=4, min_np=2, max_np=8,
                                   available_slots=8, grant=5)
    assert (action, target) == (ctl.SCALE_UP, 5)


def test_decide_grant_shrink_binds():
    action, target, reason = ctl.decide(current_np=6, min_np=2, max_np=8,
                                        available_slots=6, grant=3)
    assert (action, target) == (ctl.SCALE_DOWN, 3)
    assert "grant" in reason


def test_decide_grant_never_shrinks_below_min_np():
    action, target, _ = ctl.decide(current_np=4, min_np=4, max_np=8,
                                   available_slots=4, grant=1)
    assert action == ctl.HOLD


def test_decide_straggler_drains_one():
    action, target, reason = ctl.decide(current_np=4, min_np=2, max_np=8,
                                        available_slots=4,
                                        straggler_rank=3)
    assert (action, target) == (ctl.SCALE_DOWN, 3)
    assert "rank 3" in reason


def test_decide_straggler_needs_min_np_headroom():
    action, _, _ = ctl.decide(current_np=2, min_np=2, max_np=8,
                              available_slots=2, straggler_rank=1)
    assert action == ctl.HOLD


def test_decide_drain_in_flight_freezes():
    action, _, _ = ctl.decide(current_np=4, min_np=2, max_np=8,
                              available_slots=8, fleet_draining=True)
    assert action == ctl.HOLD


def test_decide_steady_state_holds():
    action, _, _ = ctl.decide(current_np=4, min_np=2, max_np=4,
                              available_slots=6)
    assert action == ctl.HOLD


# -- controller tick against a fake driver ----------------------------------


class _FakeProc:
    def __init__(self):
        self.signals = []

    def poll(self):
        return None

    def send_signal(self, sig):
        self.signals.append(sig)


class _FakeRec:
    def __init__(self):
        self.proc = _FakeProc()


class _FakeSlot:
    def __init__(self, rank):
        self.rank = rank


class _FakeHostManager:
    def __init__(self, slots):
        self.slots = slots

    def available_slots(self):
        return self.slots


class _FakeDriver:
    def __init__(self, np_=4, slots=4, min_np=2, max_np=8):
        self._lock = threading.RLock()
        self._assignments = {(f"h{r}", 0): _FakeSlot(r)
                             for r in range(np_)}
        self._workers = {k: _FakeRec() for k in self._assignments}
        self._draining = {}
        self.min_np = min_np
        self.max_np = max_np
        self.host_manager = _FakeHostManager(slots)
        self.rendezvous = RendezvousServer()
        self.finished = False
        self.resumed = 0

    def resume(self):
        self.resumed += 1


def _firing(ranks):
    return json.dumps({"wall": time.time(),
                       "firing_by_rule":
                           {"step_stall": list(ranks)}}).encode()


def test_controller_straggler_needs_consecutive_strikes():
    drv = _FakeDriver()
    c = ctl.ElasticityController(drv, interval=10.0)
    drv.rendezvous.handle_put("alerts/fleet", _firing([2]))
    for _ in range(ctl.STRAGGLER_STRIKES - 1):
        action, _, _ = c.tick()
        assert action == ctl.HOLD
    action, target, _ = c.tick()
    assert (action, target) == (ctl.SCALE_DOWN, 3)
    # The named straggler got the preemption notice, nobody else did.
    victim = drv._workers[("h2", 0)].proc
    assert victim.signals == [env_cfg.preempt_signal()]
    others = [r.proc.signals for k, r in drv._workers.items()
              if k != ("h2", 0)]
    assert all(s == [] for s in others)


def test_controller_one_clean_tick_clears_strikes():
    drv = _FakeDriver()
    c = ctl.ElasticityController(drv, interval=10.0)
    drv.rendezvous.handle_put("alerts/fleet", _firing([2]))
    c.tick()
    c.tick()
    drv.rendezvous.handle_put("alerts/fleet", _firing([]))
    c.tick()  # clean tick: strikes reset
    drv.rendezvous.handle_put("alerts/fleet", _firing([2]))
    action, _, _ = c.tick()
    assert action == ctl.HOLD


def test_controller_cooldown_rate_limits():
    drv = _FakeDriver(np_=4, slots=8)
    c = ctl.ElasticityController(drv, interval=10.0)
    action, _, _ = c.tick()
    assert action == ctl.SCALE_UP and drv.resumed == 1
    action, _, reason = c.tick()
    assert action == ctl.HOLD and "cooldown" in reason
    assert drv.resumed == 1


def test_controller_holds_while_draining():
    drv = _FakeDriver(np_=4, slots=8)
    drv._draining[("h0", 0)] = time.monotonic()
    c = ctl.ElasticityController(drv, interval=10.0)
    action, _, _ = c.tick()
    assert action == ctl.HOLD and drv.resumed == 0


def test_controller_publishes_last_decision():
    drv = _FakeDriver(np_=4, slots=4)
    c = ctl.ElasticityController(drv, interval=10.0)
    c.tick()
    doc = json.loads(drv.rendezvous.handle_get("controller/last").decode())
    assert doc["action"] == ctl.HOLD and doc["current_np"] == 4


def test_controller_reads_namespaced_grant(monkeypatch):
    monkeypatch.setenv(env_cfg.JOB_NAME, "a")
    drv = _FakeDriver(np_=6, slots=6, min_np=2)
    c = ctl.ElasticityController(drv, interval=10.0)
    drv.rendezvous.handle_put("jobs/a/capacity/grant", b"3")
    action, target, _ = c.tick()
    assert (action, target) == (ctl.SCALE_DOWN, 3)


def test_controller_decision_counters():
    drv = _FakeDriver(np_=4, slots=4)
    c = ctl.ElasticityController(drv, interval=10.0)
    before = c._m[ctl.HOLD].value
    c.tick()
    assert c._m[ctl.HOLD].value == before + 1


# ---------------------------------------------------------------------------
# Per-job KV namespaces and capacity arbitration


def test_arbitrate_capacity_max_min_fair():
    assert arbitrate_capacity({"a": 10, "b": 2, "c": 5}, 12) == \
        {"a": 5, "b": 2, "c": 5}
    assert arbitrate_capacity({"a": 10, "b": 10}, 5) == {"a": 3, "b": 2}
    assert arbitrate_capacity({}, 5) == {}
    assert arbitrate_capacity({"a": 3}, 0) == {"a": 0}
    assert arbitrate_capacity({"a": 4, "b": 4}, 16) == {"a": 4, "b": 4}


def test_server_arbitrates_on_want_put():
    srv = RendezvousServer(fleet_slots=8)
    srv.handle_put("jobs/a/capacity/want", b"6")
    srv.handle_put("jobs/b/capacity/want", b"6")
    assert int(srv.handle_get("jobs/a/capacity/grant")) == 4
    assert int(srv.handle_get("jobs/b/capacity/grant")) == 4
    # A job shrinking its want frees slots for the other.
    srv.handle_put("jobs/b/capacity/want", b"2")
    assert int(srv.handle_get("jobs/a/capacity/grant")) == 6
    assert int(srv.handle_get("jobs/b/capacity/grant")) == 2


def test_server_ignores_wants_without_fleet_slots():
    srv = RendezvousServer()  # fleet_slots=0: plain KV store
    srv.handle_put("jobs/a/capacity/want", b"6")
    assert srv.handle_get("jobs/a/capacity/grant") is None


def test_kv_namespace_isolation():
    """Two namespaced clients on ONE server never see each other's
    keys — the whole elastic protocol is scoped by the prefix."""
    from horovod_tpu.backend.rendezvous import RendezvousClient

    srv = RendezvousServer()
    port = srv.start()
    try:
        a = RendezvousClient("127.0.0.1", port, timeout=5.0,
                             secret_key=None, namespace="jobs/a/")
        b = RendezvousClient("127.0.0.1", port, timeout=5.0,
                             secret_key=None, namespace="jobs/b/")
        a.put("meta", "epoch", b"3")
        b.put("meta", "epoch", b"7")
        assert a.get("meta", "epoch") == b"3"
        assert b.get("meta", "epoch") == b"7"
        assert srv.handle_get("jobs/a/meta/epoch") == b"3"
        assert srv.handle_get("jobs/b/meta/epoch") == b"7"
        # DELETE is scoped too.
        a.delete("meta")
        assert a.get("meta", "epoch") is None
        assert b.get("meta", "epoch") == b"7"
    finally:
        srv.stop()


def test_unnamespaced_client_layout_unchanged():
    from horovod_tpu.backend.rendezvous import RendezvousClient

    srv = RendezvousServer()
    port = srv.start()
    try:
        c = RendezvousClient("127.0.0.1", port, timeout=5.0,
                             secret_key=None, namespace="")
        c.put("meta", "epoch", b"1")
        assert srv.handle_get("meta/epoch") == b"1"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# Strike-free drain quarantine


def test_quarantine_excludes_without_strikes():
    mgr = HostManager(FixedHosts({"a": 1, "b": 1}), cooldown=600.0)
    mgr.update_available_hosts()
    mgr.quarantine("a", 60.0)
    assert [h for h, _ in mgr.current_hosts] == ["b"]
    assert mgr.is_quarantined("a")
    assert mgr.blacklist_strikes("a") == 0
    assert not mgr.is_blacklisted("a")


def test_quarantine_expiry_surfaces_as_added():
    mgr = HostManager(FixedHosts({"a": 1, "b": 1}), cooldown=600.0)
    mgr.update_available_hosts()
    mgr.quarantine("a", 0.01)
    assert [h for h, _ in mgr.current_hosts] == ["b"]
    time.sleep(0.05)
    res = mgr.update_available_hosts()
    assert res & HostUpdateResult.ADDED
    assert [h for h, _ in mgr.current_hosts] == ["a", "b"]
