"""horovod_tpu.tensorflow / horovod_tpu.keras adapter tests
(ref test model: test/test_tensorflow.py op coverage,
test/test_tensorflow2_keras.py optimizer/callback coverage — under 2
real ranks via the func-mode runner, like test_torch_adapter.py).

Tiering: each 2-rank case spawns TF in two subprocesses (~25-40s
apiece), and the full file (~360s) blew the tier-1 harness budget. The
deep-coverage cases are marked `slow`; tier-1 keeps a smoke subset —
basic collectives (test_tf_collectives_two_ranks), fusion/cache engine
behavior (test_tf_grads_fuse_in_few_engine_cycles), the keras fit path
(test_keras_fit_two_ranks_converges_and_syncs) and the cheap
single-process cases. `pytest -m slow tests/test_tf_adapter.py` runs
the rest."""
import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from horovod_tpu.runner import run

ENV = {
    "HOROVOD_CYCLE_TIME": "1",
    "JAX_PLATFORMS": "cpu",
    "TF_CPP_MIN_LOG_LEVEL": "2",
}


def _two(fn):
    return run(fn, np=2, extra_env=ENV)


def test_tf_collectives_two_ranks():
    def fn():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()

        # allreduce average + sum
        t = tf.ones([4]) * (r + 1)
        avg = hvd.allreduce(t)
        assert np.allclose(avg.numpy(), 1.5), avg
        s = hvd.allreduce(t, op=hvd.Sum)
        assert np.allclose(s.numpy(), 3.0), s

        # variable-first-dim allgather
        g = hvd.allgather(tf.fill([r + 1, 2], float(r)))
        assert g.shape == (3, 2), g.shape
        assert np.allclose(g.numpy()[0], 0.0) and np.allclose(g.numpy()[1:], 1.0)

        # broadcast
        b = hvd.broadcast(tf.range(3.0) * (r + 1), root_rank=1)
        assert np.allclose(b.numpy(), [0.0, 2.0, 4.0]), b

        # alltoall with uneven splits
        out, splits = hvd.alltoall(tf.range(4.0) + 10 * r, splits=[1, 3])
        if r == 0:
            assert np.allclose(out.numpy(), [0.0, 10.0]), out
            assert splits.numpy().tolist() == [1, 1]
        else:
            assert np.allclose(out.numpy(), [1.0, 2.0, 3.0, 11.0, 12.0, 13.0])

        # grouped allreduce
        outs = hvd.grouped_allreduce(
            [tf.ones([2]) * (r + 1), tf.ones([3]) * (10.0 * (r + 1))],
            op=hvd.Sum,
        )
        assert np.allclose(outs[0].numpy(), 3.0)
        assert np.allclose(outs[1].numpy(), 30.0)

        # fp16 compression path
        c = hvd.allreduce(t, compression=hvd.Compression.fp16)
        assert c.dtype == tf.float32 and np.allclose(c.numpy(), 1.5)

        # objects
        objs = hvd.allgather_object({"rank": r})
        assert [o["rank"] for o in objs] == [0, 1]
        obj = hvd.broadcast_object({"v": r * 7}, root_rank=1)
        assert obj["v"] == 7

        # broadcast_variables
        v = tf.Variable([float(r), float(r)])
        hvd.broadcast_variables([v], root_rank=1)
        assert np.allclose(v.numpy(), 1.0)
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_tf_tape_and_tf_function_grad():
    def fn():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()

        # DistributedGradientTape averages grads across ranks.
        w = tf.Variable([2.0])
        with tf.GradientTape() as tape:
            loss = w * w * float(r + 1)  # d/dw = 2w(r+1) = 4(r+1)
        tape = hvd.DistributedGradientTape(tape)
        (g,) = tape.gradient(loss, [w])
        assert np.allclose(g.numpy(), 4.0 * 1.5), g  # mean of 4,8

        # allreduce inside tf.function traces through py_function.
        @tf.function
        def fused(x):
            return hvd.allreduce(x, op=hvd.Sum, name="infn")

        out = fused(tf.ones([3]) * (r + 1))
        assert np.allclose(out.numpy(), 3.0), out

        # gradient THROUGH allreduce inside a tape
        with tf.GradientTape() as t2:
            y = hvd.allreduce(w * (r + 1.0), op=hvd.Sum, name="gthrough")
            z = tf.reduce_sum(y)
        (gw,) = t2.gradient(z, [w])
        # Backward of allreduce(SUM) is allreduce(SUM) of the incoming
        # cotangent (=1 per rank → 2), times the local jacobian (r+1).
        assert np.allclose(gw.numpy(), 2.0 * (r + 1)), gw
        return True

    assert _two(fn) == [True, True]


def test_tf_grads_fuse_in_few_engine_cycles():
    """The VERDICT-r2 regression: DistributedGradientTape must enqueue
    ALL gradients before awaiting any, so N allreduces negotiate in ~1-2
    engine cycles (fusion fires), not N serial cycles (ref: AsyncOpKernel
    concurrency, tensorflow/mpi_ops.cc:371-416)."""

    def fn():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd
        from horovod_tpu.common import basics

        hvd.init()
        r = hvd.rank()
        eng = basics.engine()

        hvd.allreduce(tf.ones([1]), name="warm")  # settle negotiation

        N = 16
        ws = [tf.Variable(tf.ones([4]) * (r + 1)) for _ in range(N)]
        with tf.GradientTape() as tape:
            loss = tf.add_n([tf.reduce_sum(v * v) for v in ws])
        tape = hvd.DistributedGradientTape(tape)
        before = eng.response_cycles
        grads = tape.gradient(loss, ws)
        cycles = eng.response_cycles - before
        # Serial enqueue-sync would cost N cycles; the grouped path must
        # land the whole batch in a handful (allow scheduler jitter).
        assert cycles <= 5, f"{N} grads took {cycles} response cycles"
        for g in grads:
            # d/dv sum(v^2) = 2v = 2(r+1); averaged over ranks = 3.
            assert np.allclose(g.numpy(), 3.0), g
        return cycles

    res = _two(fn)
    assert all(c <= 5 for c in res), res


@pytest.mark.slow
def test_tf_async_handles_and_tf_function_group():
    def fn():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()
        r = hvd.rank()

        # Async handle API: enqueue-all then synchronize-all.
        hs = [
            hvd.allreduce_async(tf.ones([2]) * (r + 1), op=hvd.Sum,
                                name=f"as.{i}")
            for i in range(4)
        ]
        outs = [hvd.synchronize(h) for h in hs]
        for o in outs:
            assert np.allclose(o.numpy(), 3.0), o
        hb = hvd.broadcast_async(tf.range(2.0) * (r + 1), root_rank=1)
        hg = hvd.allgather_async(tf.fill([1, 2], float(r)))
        assert np.allclose(hvd.synchronize(hb).numpy(), [0.0, 2.0])
        g = hvd.synchronize(hg).numpy()
        assert g.shape == (2, 2) and np.allclose(g[:, 0], [0.0, 1.0])
        assert hvd.poll(hb) is False  # consumed

        # grouped_allreduce inside tf.function traces as ONE py_function.
        @tf.function
        def fused(a, b):
            x, y = hvd.grouped_allreduce([a, b], op=hvd.Sum, name="gfn")
            return x + 0.0, y + 0.0

        x, y = fused(tf.ones([2]) * (r + 1), tf.ones([3]) * 10.0 * (r + 1))
        assert np.allclose(x.numpy(), 3.0) and np.allclose(y.numpy(), 30.0)

        # Gradient THROUGH a grouped allreduce.
        w = tf.Variable([2.0])
        with tf.GradientTape() as t:
            ys = hvd.grouped_allreduce([w * (r + 1.0)], op=hvd.Sum,
                                       name="ggrad")
            z = tf.reduce_sum(ys[0])
        (gw,) = t.gradient(z, [w])
        assert np.allclose(gw.numpy(), 2.0 * (r + 1)), gw
        return True

    assert _two(fn) == [True, True]


def test_keras_fit_two_ranks_converges_and_syncs():
    def fn():
        import numpy as np
        import tensorflow as tf
        import keras

        import horovod_tpu.keras as hvd

        hvd.init()
        r = hvd.rank()
        keras.utils.set_random_seed(1234 + r)  # deliberately different

        model = keras.Sequential(
            [keras.Input((4,)), keras.layers.Dense(1, use_bias=False)]
        )
        opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.1))
        model.compile(optimizer=opt, loss="mse", run_eagerly=True)

        # Rank-dependent data; identical updates require grad averaging.
        rng = np.random.RandomState(r)
        X = rng.randn(32, 4).astype(np.float32)
        Y = (X @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32))

        cbs = [
            hvd.callbacks.BroadcastGlobalVariablesCallback(0),
            hvd.callbacks.MetricAverageCallback(),
        ]
        h = model.fit(X, Y, epochs=8, batch_size=8, verbose=0, callbacks=cbs)
        losses = h.history["loss"]
        assert losses[-1] < losses[0] * 0.5, losses

        # Weights must be identical across ranks (broadcast + averaged
        # grads) — allgather both ranks' weights and compare.
        w = model.get_weights()[0].ravel()
        gathered = hvd.allgather(tf.constant(w[None, :])).numpy()
        assert np.allclose(gathered[0], gathered[1], atol=1e-6), gathered

        # Averaged metric must match on both ranks.
        m = hvd.allgather(
            tf.constant([[losses[-1]]], dtype=tf.float64)).numpy()
        assert np.allclose(m[0], m[1]), m
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_keras_adasum_delta_optimizer_matches_oracle():
    """hvd.DistributedOptimizer(op=Adasum) on the Keras surface must be
    the delta-model optimizer (ref: horovod/tensorflow/__init__.py:
    334-428): local step, then Adasum-combine the weight deltas —
    checked against the adasum_numpy oracle, and shown to differ from
    gradient-Adasum under Adam."""
    def fn():
        import keras
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.keras as hvd
        from horovod_tpu.ops.adasum import adasum_numpy

        hvd.init()
        r = hvd.rank()
        keras.utils.set_random_seed(7)  # identical init everywhere

        v = tf.Variable(np.arange(6, dtype=np.float32).reshape(2, 3))
        start = v.numpy().copy()
        opt = hvd.DistributedOptimizer(
            keras.optimizers.Adam(0.1), op=hvd.Adasum
        )
        assert type(opt).__name__ == "DistributedDeltaAdam"

        rng = np.random.RandomState(100 + r)
        g = tf.constant(rng.randn(2, 3).astype(np.float32))
        opt.apply_gradients([(g, v)])

        # Oracle: local Adam step on a clone, allgather deltas, VHDD.
        ref = tf.Variable(start)
        keras.optimizers.Adam(0.1).apply_gradients([(g, ref)])
        local_delta = (ref.numpy() - start).reshape(1, -1)
        gathered = hvd.allgather(tf.constant(local_delta)).numpy()
        combined = adasum_numpy(
            [gathered[i] for i in range(hvd.size())]
        )[0]
        np.testing.assert_allclose(
            v.numpy().reshape(-1), start.reshape(-1) + combined,
            rtol=1e-5, atol=1e-6,
        )

        # Gradient-Adasum gives a different trajectory under Adam.
        v2 = tf.Variable(start)
        opt2 = keras.optimizers.Adam(0.1)
        g2 = hvd.allreduce(g, op=hvd.Adasum)
        opt2.apply_gradients([(g2, v2)])
        assert float(tf.reduce_sum(tf.abs(v - v2))) > 1e-4

        # Every rank converges to the same combined weights.
        allv = hvd.allgather(tf.reshape(v, (1, -1))).numpy()
        assert np.allclose(allv[0], allv[1], atol=1e-6)
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_keras_adasum_fit_and_backward_passes():
    """Adasum wrapper inside model.fit: local steps every batch, deltas
    combined every k-th (ref schedule: tensorflow/__init__.py:356,
    383-386) — ranks agree at epoch end and loss decreases."""
    def fn():
        import keras
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.keras as hvd

        hvd.init()
        r = hvd.rank()
        keras.utils.set_random_seed(5)

        model = keras.Sequential(
            [keras.Input((4,)), keras.layers.Dense(1, use_bias=False)]
        )
        opt = hvd.DistributedOptimizer(
            keras.optimizers.SGD(0.05), op=hvd.Adasum,
            backward_passes_per_step=2,
        )
        model.compile(optimizer=opt, loss="mse", run_eagerly=True)
        rng = np.random.RandomState(r)
        X = rng.randn(32, 4).astype(np.float32)
        Y = (X @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32))
        cbs = [hvd.callbacks.BroadcastGlobalVariablesCallback(0)]
        h = model.fit(X, Y, epochs=6, batch_size=8, verbose=0,
                      callbacks=cbs)
        losses = h.history["loss"]
        assert losses[-1] < losses[0] * 0.7, losses
        # batches_per_epoch=4, k=2 → comm fires on even applies; after
        # fit every rank must hold identical weights.
        w = model.get_weights()[0].ravel()
        gathered = hvd.allgather(tf.constant(w[None, :])).numpy()
        assert np.allclose(gathered[0], gathered[1], atol=1e-5), gathered
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_v1_adasum_delta_optimizer():
    """The tf.compat.v1 surface dispatches op=Adasum to the delta-model
    wrapper too (ref dispatch: horovod/tensorflow/__init__.py:431-460)."""
    def fn():
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd
        from horovod_tpu.ops.adasum import adasum_numpy

        hvd.init()
        r = hvd.rank()
        v = tf.Variable(np.ones((3,), np.float32))
        start = v.numpy().copy()
        opt = hvd.DistributedOptimizer(
            tf.compat.v1.train.GradientDescentOptimizer(0.5),
            op=hvd.Adasum,
        )
        assert "DistributedDelta" in type(opt).__name__
        g = tf.constant(np.full((3,), float(r + 1), np.float32))
        opt.apply_gradients([(g, v)])
        # SGD delta = -lr*g; oracle combine of both ranks' deltas.
        deltas = [np.full((3,), -0.5 * (i + 1), np.float32)
                  for i in range(hvd.size())]
        expected = start + adasum_numpy(deltas)[0]
        np.testing.assert_allclose(v.numpy(), expected, rtol=1e-5)
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_keras_state_and_lr_callbacks():
    def fn():
        import numpy as np
        import keras

        import horovod_tpu.keras as hvd
        from horovod_tpu.tensorflow.elastic import TensorFlowKerasState

        hvd.init()
        r = hvd.rank()
        keras.utils.set_random_seed(99 + r)
        model = keras.Sequential(
            [keras.Input((2,)), keras.layers.Dense(1, use_bias=False)]
        )
        opt = keras.optimizers.SGD(0.01)
        model.compile(optimizer=opt, loss="mse")

        state = TensorFlowKerasState(model, opt, epoch=7 * (r + 1))
        state.sync()
        # After sync both ranks hold rank 0's weights and epoch.
        assert state.epoch == 7, state.epoch
        w = model.get_weights()[0].ravel()
        import tensorflow as tf

        gathered = hvd.allgather(tf.constant(w[None, :])).numpy()
        assert np.allclose(gathered[0], gathered[1]), gathered

        # restore() rolls back an in-place change.
        model.set_weights([model.get_weights()[0] * 0.0])
        state.restore()
        assert np.allclose(model.get_weights()[0].ravel(), gathered[0])

        # LR warmup callback scales toward size×initial.
        cb = hvd.callbacks.LearningRateWarmupCallback(
            initial_lr=0.01, warmup_epochs=2, steps_per_epoch=10)
        cb.set_model(model)
        cb.on_epoch_begin(0)
        cb.on_batch_begin(0)
        lr0 = float(np.asarray(model.optimizer.learning_rate))
        cb.current_epoch = 5
        cb.on_batch_begin(0)
        lr5 = float(np.asarray(model.optimizer.learning_rate))
        assert abs(lr5 - 0.02) < 1e-6 and lr0 <= lr5, (lr0, lr5)
        return True

    assert _two(fn) == [True, True]


def test_keras_optimizer_config_roundtrip(hvd_single):
    """get_config/from_config on the dynamic wrapper re-wraps without
    custom_objects, so clone/serialize paths that call
    type(opt).from_config(opt.get_config()) keep working
    (ref: horovod/keras/__init__.py:137-152)."""
    import keras

    import horovod_tpu.keras as hvd_keras

    opt = hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(0.05, momentum=0.9))
    cfg = opt.get_config()
    # The wrapper adds no hyperparameters of its own.
    assert float(np.asarray(cfg["learning_rate"])) == pytest.approx(0.05)
    clone = type(opt).from_config(cfg)
    assert getattr(clone, "_hvd_wrapped", False)
    assert type(clone).__name__ == "DistributedSGD"
    assert float(np.asarray(clone.get_config()["learning_rate"])) \
        == pytest.approx(0.05)
    assert float(np.asarray(clone.get_config()["momentum"])) \
        == pytest.approx(0.9)


def test_keras_load_model_rewraps_optimizer(tmp_path, hvd_single):
    """hvd.keras.load_model reconstructs a model saved with the wrapped
    DistributedOptimizer (ref: horovod/keras/__init__.py:127-158 —
    custom-object loader for the dynamically created optimizer class)."""
    import keras

    import horovod_tpu.keras as hvd_keras

    model = keras.Sequential([
        keras.layers.Input(shape=(4,)),
        keras.layers.Dense(2),
    ])
    opt = hvd_keras.DistributedOptimizer(keras.optimizers.SGD(0.1))
    model.compile(optimizer=opt, loss="mse")
    x = np.random.RandomState(0).rand(8, 4).astype(np.float32)
    y = np.random.RandomState(1).rand(8, 2).astype(np.float32)
    model.fit(x, y, epochs=1, verbose=0)

    path = tmp_path / "m.keras"
    model.save(path)
    loaded = hvd_keras.load_model(path)
    # Same predictions, and the optimizer is the wrapped kind again.
    np.testing.assert_allclose(loaded.predict(x, verbose=0),
                               model.predict(x, verbose=0),
                               rtol=1e-5, atol=1e-6)
    assert type(loaded.optimizer).__name__.startswith("Distributed")


@pytest.mark.slow
def test_singleton_collectives_in_trace_warn():
    """>=8 singleton collectives traced inside ONE tf.function warn and
    point at grouped_allreduce (each becomes its own stateful
    py_function serialized by auto-control-deps — see
    docs/tensorflow.md); the grouped path must NOT warn."""
    def fn():
        import warnings

        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()

        ts = [tf.ones([2]) * i for i in range(8)]

        @tf.function
        def many(xs):
            return [hvd.allreduce(x, name=f"w{i}")
                    for i, x in enumerate(xs)]

        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            many.get_concrete_function(ts)
        msgs = [str(w.message) for w in rec]
        assert any("grouped_allreduce" in m for m in msgs), msgs

        @tf.function
        def grouped(xs):
            return hvd.grouped_allreduce(xs, name="g")

        with warnings.catch_warnings(record=True) as rec2:
            warnings.simplefilter("always")
            grouped.get_concrete_function(ts)
        msgs2 = [str(w.message) for w in rec2
                 if "grouped_allreduce" in str(w.message)]
        assert not msgs2, msgs2
        # Both ranks must still drain the traced singletons they built
        # (the concrete functions were traced, not run — nothing to
        # drain; a final barrier keeps shutdown clean).
        hvd.barrier()
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_keras_adasum_fit_traced_k1():
    """Adasum wrapper inside a TRACED model.fit (no run_eagerly): with
    backward_passes_per_step=1 the combine has no schedule to gate, so
    the graph path must train and keep ranks identical."""
    def fn():
        import keras
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.keras as hvd

        hvd.init()
        r = hvd.rank()
        keras.utils.set_random_seed(9)

        model = keras.Sequential(
            [keras.Input((4,)), keras.layers.Dense(1, use_bias=False)]
        )
        opt = hvd.DistributedOptimizer(
            keras.optimizers.SGD(0.05), op=hvd.Adasum)
        model.compile(optimizer=opt, loss="mse")  # traced train_step
        rng = np.random.RandomState(r)
        X = rng.randn(32, 4).astype(np.float32)
        Y = (X @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32))
        cbs = [hvd.callbacks.BroadcastGlobalVariablesCallback(0)]
        h = model.fit(X, Y, epochs=4, batch_size=16, verbose=0,
                      callbacks=cbs)
        assert h.history["loss"][-1] < h.history["loss"][0]
        w = model.get_weights()[0].ravel()
        gathered = hvd.allgather(tf.constant(w[None, :])).numpy()
        assert np.allclose(gathered[0], gathered[1], atol=1e-5), gathered
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_keras_adasum_fit_traced_k2_in_graph_schedule():
    """Traced model.fit at backward_passes_per_step=2: the comm-step
    schedule is in-graph (ref: `_is_comm_step`,
    horovod/tensorflow/__init__.py:356,383-386), so ranks must be
    IDENTICAL right after every k-th (comm) step and DIVERGED after the
    local-only steps in between."""
    def fn():
        import keras
        import numpy as np
        import tensorflow as tf

        import horovod_tpu.keras as hvd

        hvd.init()
        r = hvd.rank()
        keras.utils.set_random_seed(11)

        model = keras.Sequential(
            [keras.Input((4,)), keras.layers.Dense(1, use_bias=False)]
        )
        opt = hvd.DistributedOptimizer(
            keras.optimizers.SGD(0.05), op=hvd.Adasum,
            backward_passes_per_step=2)
        model.compile(optimizer=opt, loss="mse")  # traced train_step
        rng = np.random.RandomState(r)  # rank-dependent data
        X = rng.randn(16, 4).astype(np.float32)
        Y = (X @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32))
        hvd.broadcast_variables(model.variables, root_rank=0)

        def spread():
            w = model.get_weights()[0].ravel()
            g = hvd.allgather(tf.constant(w[None, :])).numpy()
            return float(np.abs(g[0] - g[1]).max())

        first_loss = None
        # One full batch per fit call => exactly one apply per epoch.
        for step in range(1, 5):
            h = model.fit(X, Y, epochs=1, batch_size=16, verbose=0)
            if first_loss is None:
                first_loss = h.history["loss"][0]
            if step % 2 == 0:
                # comm step: Adasum-combined, ranks identical
                assert spread() < 1e-5, (step, spread())
            else:
                # local-only step on rank-dependent data: diverged
                assert spread() > 1e-4, (step, spread())
        assert h.history["loss"][-1] < first_loss
        return True

    assert _two(fn) == [True, True]


@pytest.mark.slow
def test_dynamic_topology_ops():
    """rank_op/size_op read the CURRENT topology at execution time, not
    trace time (ref: tensorflow/mpi_ops.py rank_op/size_op — the
    reference kernels query the controller per execution so traced
    functions see post-elastic-reset values)."""
    def fn():
        import tensorflow as tf

        import horovod_tpu.tensorflow as hvd

        hvd.init()

        @tf.function
        def topo():
            return hvd.rank_op(), hvd.size_op(), hvd.local_rank_op(), \
                hvd.local_size_op()

        r, s, lr, ls = topo()
        assert int(s) == 2 and int(r) == hvd.rank()
        assert int(ls) >= 1 and 0 <= int(lr) < int(ls)
        # Eager path too.
        assert int(hvd.size_op()) == 2
        return True

    assert _two(fn) == [True, True]


def test_tensorflow_keras_alias_surface(hvd_single):
    """`import horovod_tpu.tensorflow.keras as hvd` must expose the
    reference's tf.keras surface (ref:
    horovod/tensorflow/keras/__init__.py) — same objects as
    horovod_tpu.keras under the tf-flavored path."""
    import keras

    import horovod_tpu.keras as hk
    import horovod_tpu.tensorflow.keras as hvd

    for name in ("DistributedOptimizer", "allreduce", "broadcast",
                 "allgather", "load_model", "Compression", "Adasum",
                 "broadcast_global_variables", "init", "rank", "size",
                 "mpi_built", "cuda_built"):
        assert hasattr(hvd, name), name
    assert hvd.DistributedOptimizer is hk.DistributedOptimizer
    assert hvd.callbacks.BroadcastGlobalVariablesCallback \
        is hk.callbacks.BroadcastGlobalVariablesCallback
    assert hvd.elastic.KerasState is hk.KerasState

    # The surface is live, not just importable.
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.1))
    assert type(opt).__name__ == "DistributedSGD"
    assert hvd.size() == 1 and not hvd.cuda_built()
