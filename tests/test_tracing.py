"""Tracing-plane tests (ISSUE 6): flight-recorder ring semantics, span
nesting and trace-id scoping, the wire-carried trace id, 2-engine
merged-trace correlation, post-mortem dumps on an injected sever, and
the clock-offset alignment math."""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

from horovod_tpu.common import telemetry, tracing
from horovod_tpu.common.fault_injection import Rule, get_injector
from horovod_tpu.common.message import Response, ResponseList, ResponseType
from horovod_tpu.engine.engine import Engine
from horovod_tpu.utils import clock


# ---------------------------------------------------------------------------
# flight recorder: ring overwrite + drop accounting

def test_ring_overwrite_and_drop_accounting():
    reg = telemetry.MetricsRegistry()
    rec = tracing.SpanRecorder(4, registry=reg)
    for i in range(10):
        rec.append(i, f"e{i}", "cat", 1000 + i, 5, "t")
    assert rec.depth() == 4
    assert rec.dropped == 6
    snap = rec.snapshot()
    assert [e[2] for e in snap] == ["e6", "e7", "e8", "e9"]  # oldest first
    # seq is monotonic and survives the wrap
    assert [e[0] for e in snap] == [6, 7, 8, 9]
    # The drop counter advances amortized at trim time (the hot path is
    # a lock-free append); it never exceeds the exact property.
    key = 'horovod_trace_events_dropped_total{source="recorder"}'
    assert 0 < reg.snapshot()[key] <= rec.dropped


def test_batch_since_is_incremental_and_nondestructive():
    rec = tracing.SpanRecorder(8)
    for i in range(5):
        rec.append(0, f"a{i}", "c", i, 1, "t")
    evs, cur = rec.batch_since(0)
    assert len(evs) == 5 and cur == 5
    evs2, cur2 = rec.batch_since(cur)
    assert evs2 == [] and cur2 == 5
    rec.append(0, "late", "c", 9, 1, "t")
    evs3, _ = rec.batch_since(cur)
    assert [e[2] for e in evs3] == ["late"]
    assert rec.depth() == 6  # collection never consumes the ring


def test_batch_since_drains_backlog_across_pushes():
    # A backlog bigger than one batch must drain oldest-first over
    # successive calls — never silently skip the old events while the
    # drop counter stays at zero (the truncated trace would read as
    # complete).
    rec = tracing.SpanRecorder(16)
    for i in range(10):
        rec.append(0, f"a{i}", "c", i, 1, "t")
    evs, cur = rec.batch_since(0, limit=4)
    assert [e[0] for e in evs] == [0, 1, 2, 3] and cur == 4
    evs, cur = rec.batch_since(cur, limit=4)
    assert [e[0] for e in evs] == [4, 5, 6, 7] and cur == 8
    evs, cur = rec.batch_since(cur, limit=4)
    assert [e[0] for e in evs] == [8, 9] and cur == 10
    evs, cur = rec.batch_since(cur, limit=4)
    assert evs == [] and cur == 10


def test_zero_capacity_disables_everything():
    tr = tracing.Tracer(capacity=0)
    assert not tr.enabled
    with tr.span("x"):
        pass
    tr.emit("y", "c", 0, 1)
    assert tr.recorder.depth() == 0
    assert tr.status()["enabled"] is False


# ---------------------------------------------------------------------------
# span nesting + trace-id scope

def test_span_nesting_and_trace_scope():
    tr = tracing.Tracer(capacity=64, registry=telemetry.MetricsRegistry())
    with tracing.trace_scope(7):
        with tr.span("outer", cat="exec"):
            time.sleep(0.002)
            with tr.span("inner", cat="xfer"):
                time.sleep(0.001)
    assert tracing.current_trace() == 0  # scope restored
    evs = tr.recorder.snapshot()
    by_name = {e[2]: e for e in evs}
    inner, outer = by_name["inner"], by_name["outer"]
    # both inherited the scope id; inner nests inside outer in time
    assert inner[1] == 7 and outer[1] == 7
    assert outer[4] <= inner[4]
    assert inner[4] + inner[5] <= outer[4] + outer[5]
    # same thread -> same lane in the rendered trace
    assert inner[6] == outer[6]


def test_explicit_trace_id_overrides_scope():
    tr = tracing.Tracer(capacity=8, registry=telemetry.MetricsRegistry())
    with tracing.trace_scope(5):
        tr.emit("e", "c", 0, 1, trace_id=9)
    assert tr.recorder.snapshot()[0][1] == 9


# ---------------------------------------------------------------------------
# wire-carried trace id

def test_response_trace_id_wire_round_trip():
    r = Response(ResponseType.ALLREDUCE, ["t"], channel=2,
                 trace_id=1234567890123)
    r2, _ = Response.deserialize(r.serialize())
    assert r2.trace_id == 1234567890123
    assert r2.channel == 2
    rl = ResponseList([r, Response(ResponseType.BARRIER, trace_id=4)],
                      shutdown=True)
    rl2 = ResponseList.deserialize(rl.serialize())
    assert [x.trace_id for x in rl2.responses] == [1234567890123, 4]
    assert rl2.shutdown


# ---------------------------------------------------------------------------
# clock-offset alignment math

def test_estimate_offset_recovers_known_skew():
    # Peer clock runs D ns ahead; symmetric one-way delay d.
    D, d = 1_000_000_000, 50_000
    a0 = 10_000                      # our stamp, echoed by the peer
    b_recv = a0 + d + D              # peer receives it (peer clock)
    b1 = b_recv + 123_456            # peer holds, then sends its beat
    a1 = (b1 - D) + d                # we receive (our clock)
    off, rtt = tracing.estimate_offset(b1, a0, b_recv, a1)
    assert rtt == 2 * d
    assert off == D                  # exact under symmetric delay


def test_estimate_offset_asymmetry_bounded_by_rtt():
    # Asymmetric delays: error is bounded by rtt/2 (the NTP bound).
    D, d_out, d_back = 777_777, 10_000, 90_000
    a0 = 0
    b_recv = a0 + d_out + D
    b1 = b_recv + 1_000
    a1 = (b1 - D) + d_back
    off, rtt = tracing.estimate_offset(b1, a0, b_recv, a1)
    assert rtt == d_out + d_back
    assert abs(off - D) <= rtt // 2


def test_wall_anchor_offset_same_process_is_zero():
    a = clock.anchor_meta()
    assert tracing.wall_anchor_offset(a, a) == 0
    # A process whose monotonic clock started 5s "later" relative to
    # the same wall clock reads 5s behind: offset = -5s.
    b = dict(a, mono_anchor_ns=a["mono_anchor_ns"] - 5_000_000_000)
    assert tracing.wall_anchor_offset(b, a) == -5_000_000_000
    assert tracing.wall_anchor_offset(None, a) == 0


# ---------------------------------------------------------------------------
# collector dedup + rendering

def test_collector_dedups_overlapping_batches_and_renders_lanes():
    col = tracing.TraceCollector(size=2, capacity=16)
    evs = [(i, 2, f"e{i}", "exec", 1000 + i, 5, "thr", None)
           for i in range(4)]
    col.ingest(1, evs[:3], anchor=clock.anchor_meta())
    col.ingest(1, evs)  # overlap: only the new event lands
    assert col.status() == {"1": 4}
    col.ingest(0, [(0, 2, "mine", "exec", 1000, 5, "thr", None)],
               anchor=clock.anchor_meta())
    doc = tracing.render_chrome(
        col.segments({}, clock.anchor_meta()),
        base_ns=clock.MONO_ANCHOR_NS)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} == {0, 1}
    assert all(e["args"]["trace_id"] == 2 for e in xs)
    lanes = [e for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"]
    assert len(lanes) == 2


# ---------------------------------------------------------------------------
# 2-engine merged-trace correlation (in-process harness)

def _start_engines(n=2, cycle_s=0.001):
    from horovod_tpu.backend.threaded import ThreadedGroup

    group = ThreadedGroup(n)
    regs = [telemetry.MetricsRegistry() for _ in range(n)]
    engines = [Engine(rank=r, size=n, backend=group.backend(r),
                      registry=regs[r]) for r in range(n)]
    for e in engines:
        e.cycle_time_s = cycle_s
        e.start()
    return engines, regs


def _all(engines, fn, timeout=60):
    outs = [None] * len(engines)
    errs = [None] * len(engines)

    def w(r):
        try:
            outs[r] = fn(engines[r], r)
        except BaseException as e:  # noqa: BLE001
            errs[r] = e

    ts = [threading.Thread(target=w, args=(r,)) for r in range(len(engines))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=timeout)
    assert all(e is None for e in errs), errs
    return outs


def test_two_engine_merged_trace_shares_ids(monkeypatch):
    monkeypatch.setenv("HOROVOD_METRICS_SYNC_SECONDS", "0.05")
    engines, _ = _start_engines(2)
    try:
        def work(eng, r):
            for i in range(6):
                eng.synchronize(eng.enqueue_allreduce(
                    np.ones(16, np.float32), name=f"w{i}"), timeout=30)
                time.sleep(0.03)

        _all(engines, work)
        time.sleep(0.2)
        # Flush round: the final batches ride this gather.
        _all(engines, lambda e, r: e.synchronize(
            e.enqueue_allreduce(np.ones(4, np.float32), name="fin"),
            timeout=30))
        doc = engines[0].render_trace()
        xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert {e["pid"] for e in xs} >= {0, 1}
        ids = {p: {e["args"]["trace_id"] for e in xs
                   if e["pid"] == p and str(e["name"]).startswith("exec.")
                   and e["args"]["trace_id"]}
               for p in (0, 1)}
        shared = ids[0] & ids[1]
        assert len(shared) >= 4, (len(ids[0]), len(ids[1]), len(shared))
        # Each shared id covers the full span taxonomy on some rank:
        names = {e["name"] for e in xs
                 if e["args"]["trace_id"] in shared}
        assert any(n.startswith("exec.allreduce") for n in names), names
        assert "queue.dwell" in names, names
        # /status trace view
        st = engines[0].status()
        assert st["trace"]["enabled"] and st["trace"]["depth"] > 0
        assert set(st["trace"]["collected"]) >= {"0", "1"}
    finally:
        _all(engines, lambda e, r: e.shutdown(), timeout=90)


def test_cached_replay_ids_match_across_ranks(monkeypatch):
    """Steady-state (cache fast path) collectives exchange no
    per-response bytes — their trace ids come from the deterministic
    replay sequence and still must agree across ranks."""
    engines, _ = _start_engines(2)
    try:
        seen = [[] for _ in range(2)]
        orig = Engine._perform_operation

        def spy(self, resp):
            if resp.response_type == ResponseType.ALLREDUCE:
                seen[self.rank].append(resp.trace_id)
            return orig(self, resp)

        monkeypatch.setattr(Engine, "_perform_operation", spy)

        def work(eng, r):
            for i in range(8):
                eng.synchronize(eng.enqueue_allreduce(
                    np.ones(8, np.float32), name="steady"), timeout=30)

        _all(engines, work)
        assert seen[0] and seen[0] == seen[1]
        # Replays (odd ids) engaged after the first negotiation (even).
        assert seen[0][0] % 2 == 0
        assert any(t % 2 == 1 for t in seen[0])
        assert len(set(seen[0])) == len(seen[0])  # fresh id per step
    finally:
        _all(engines, lambda e, r: e.shutdown(), timeout=90)


# ---------------------------------------------------------------------------
# post-mortem dump on an injected sever (real TCP mesh)

def _tcp_engines(scope, monkeypatch, n=2):
    from horovod_tpu.backend.rendezvous import RendezvousClient
    from horovod_tpu.backend.tcp import TcpBackend
    from horovod_tpu.runner.rendezvous_server import RendezvousServer

    monkeypatch.setenv("HVDRUN_FORCE_LOCAL", "1")
    server = RendezvousServer()
    port = server.start()
    rdv = RendezvousClient("127.0.0.1", port)
    backends = [None] * n
    errs = []

    def build(rank):
        try:
            backends[rank] = TcpBackend(rank, n, rendezvous=rdv, scope=scope)
        except BaseException as e:  # pragma: no cover - bootstrap bug
            errs.append(e)

    ts = [threading.Thread(target=build, args=(r,)) for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs, errs
    regs = [telemetry.MetricsRegistry() for _ in range(n)]
    engines = [Engine(rank=r, size=n, backend=backends[r], registry=regs[r])
               for r in range(n)]
    for e in engines:
        e.cycle_time_s = 0.002
    errs2 = []

    def start(e):
        try:
            e.start()
        except BaseException as exc:  # pragma: no cover - init bug
            errs2.append(exc)

    ts = [threading.Thread(target=start, args=(e,)) for e in engines]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs2, errs2
    return server, engines


def test_post_mortem_dump_on_injected_sever(tmp_path, monkeypatch):
    monkeypatch.setenv("HOROVOD_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_HEARTBEAT_INTERVAL_SECONDS", "0")
    monkeypatch.setenv("HOROVOD_TCP_TIMEOUT_SECONDS", "5")
    server, engines = _tcp_engines("t_trace_pm", monkeypatch)
    inj = get_injector()
    try:
        # Healthy rounds first (spans in every recorder).
        def warm(eng, r):
            for i in range(3):
                eng.synchronize(eng.enqueue_allreduce(
                    np.ones(8, np.float32), name=f"w{i}"), timeout=30)

        _all(engines, warm)
        # Sever every future exchange with rank 1's socket to rank 0.
        inj.install([Rule(action="sever", peer=0)])

        def failing(eng, r):
            with pytest.raises(Exception):
                for i in range(10):
                    eng.synchronize(eng.enqueue_allreduce(
                        np.ones(8, np.float32), name=f"f{i}"), timeout=30)

        _all(engines, failing)
        # Dumps are written at latch; the stitch runs in rank 0's
        # background-loop teardown, which shutdown() joins below.
        _all(engines, lambda e, r: e.shutdown(), timeout=90)
        flights = sorted(p.name for p in tmp_path.iterdir()
                         if p.name.startswith("flight_rank"))
        assert flights == ["flight_rank0.json", "flight_rank1.json"], flights
        d1 = json.load(open(tmp_path / "flight_rank1.json"))
        assert d1["rank"] == 1 and d1["events"], d1.get("reason")
        assert "peer 0" in d1["reason"] or "rank" in d1["reason"]
        assert "mono_anchor_ns" in d1["anchor"]
        pm = json.load(open(tmp_path / "postmortem.json"))
        meta = pm["horovod_postmortem"]
        assert meta["ranks"] == [0, 1]
        assert meta["verdict"], meta
        assert {e["pid"] for e in pm["traceEvents"]
                if e.get("ph") == "X"} >= {0, 1}
    finally:
        inj.clear()
        server.stop()


def test_no_dump_without_trace_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("HOROVOD_TRACE_DIR", raising=False)
    from horovod_tpu.backend.local import LocalBackend
    from horovod_tpu.common.exceptions import HorovodInternalError

    eng = Engine(rank=0, size=1, backend=LocalBackend(),
                 registry=telemetry.MetricsRegistry())
    eng.cycle_time_s = 0.001
    eng.start()
    try:
        eng._latch_fatal(HorovodInternalError("boom"))
        assert eng.tracer.last_dump is None
    finally:
        eng.shutdown()


# ---------------------------------------------------------------------------
# satellites: /status trace view on a single engine, straggler gauges

def test_status_trace_view_single_engine():
    from horovod_tpu.backend.local import LocalBackend

    reg = telemetry.MetricsRegistry()
    eng = Engine(rank=0, size=1, backend=LocalBackend(), registry=reg)
    eng.cycle_time_s = 0.001
    eng.start()
    try:
        eng.synchronize(eng.enqueue_allreduce(
            np.ones(4, np.float32), name="x"), timeout=30)
        tr = eng.status()["trace"]
        assert tr["enabled"] and tr["buffer_events"] > 0
        assert tr["depth"] > 0 and tr["dropped"] == 0
    finally:
        eng.shutdown()


def test_straggler_gauges_name_the_last_rank(monkeypatch):
    engines, regs = _start_engines(2)
    try:
        # Up to 3 attempts: the scenario depends on rank 0's request
        # genuinely arriving first, and on a loaded 2-core CI box the
        # scheduler can occasionally delay rank 0's enqueue past rank
        # 1's deliberate 0.25s lag — that inversion is box noise, not
        # a gauge bug. Each attempt uses a fresh tensor name, so the
        # gauges re-stamp from a fresh negotiation.
        for attempt in range(3):
            barrier = threading.Barrier(2)

            def work(eng, r, a=attempt):
                barrier.wait()
                if r == 1:
                    time.sleep(0.25)  # rank 1 is deliberately late
                eng.synchronize(eng.enqueue_allreduce(
                    np.ones(8, np.float32), name=f"lag.{a}"), timeout=30)

            _all(engines, work)
            snap = regs[0].snapshot()
            w1 = snap['horovod_negotiation_wait_seconds{rank="1"}']
            w0 = snap['horovod_negotiation_wait_seconds{rank="0"}']
            if (snap["horovod_straggler_rank"] == 1
                    and w1 > 0.15 and w0 == 0.0):
                break
        assert snap["horovod_straggler_rank"] == 1, snap.get(
            "horovod_straggler_rank")
        assert w1 > 0.15 and w0 == 0.0, (w0, w1)
    finally:
        _all(engines, lambda e, r: e.shutdown(), timeout=90)
