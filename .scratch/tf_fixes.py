import os
os.environ["JAX_PLATFORMS"] = "cpu"; os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
import numpy as np, tensorflow as tf, keras, tempfile
import horovod_tpu.tensorflow as hvd
import horovod_tpu.keras as hk
hvd.init()

# dict sources through DistributedGradientTape
w = tf.Variable([2.0])
with tf.GradientTape() as tape:
    loss = tf.reduce_sum(w * w)
tape = hvd.DistributedGradientTape(tape)
g = tape.gradient(loss, {"w": w})
assert np.allclose(g["w"].numpy(), 4.0), g

# alltoall grad (size 1: identity exchange)
with tf.GradientTape() as t2:
    v = tf.Variable([1.0, 2.0])
    t2.watch(v)
    out, recv = hvd.alltoall(v * 3.0)
    z = tf.reduce_sum(out)
# size-1 fast path has no custom grad; just check it differentiates
gv = t2.gradient(z, v)
assert gv is not None and np.allclose(gv.numpy(), 3.0), gv

# elastic callbacks usable in fit
model = keras.Sequential([keras.Input((4,)), keras.layers.Dense(1)])
opt = hk.DistributedOptimizer(keras.optimizers.SGD(0.01))
model.compile(optimizer=opt, loss="mse", metrics=["mae"])
from horovod_tpu.keras.elastic import KerasState, CommitStateCallback, UpdateEpochStateCallback
st = KerasState(model, opt, epoch=0, batch=0)
X = np.random.randn(32, 4).astype(np.float32); Y = X.sum(1, keepdims=True).astype(np.float32)
model.fit(X, Y, epochs=1, verbose=0, callbacks=[
    hk.callbacks.BroadcastGlobalVariablesCallback(0),
    CommitStateCallback(st), UpdateEpochStateCallback(st)])

# load_model keeps metrics and wraps optimizer
with tempfile.TemporaryDirectory() as d:
    path = os.path.join(d, "m.keras")
    model.save(path)
    m2 = hk.load_model(path)
    assert getattr(m2.optimizer, "_hvd_wrapped", False), type(m2.optimizer)
    m2.fit(X, Y, epochs=1, verbose=0)
    ev = m2.evaluate(X, Y, verbose=0, return_dict=True)
    assert "mae" in ev, ev
print("TF FIXES OK")
