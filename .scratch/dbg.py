import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import jax
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np, optax, jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P
import horovod_tpu as hvd
hvd.init()
mesh = hvd.mesh()
rng = np.random.RandomState(0)
X = rng.randn(64, 4).astype(np.float32)
w_true = np.array([1.0, -2.0, 3.0, 0.5], np.float32)
y = X @ w_true

def loss_fn(w, xb, yb):
    return jnp.mean((xb @ w - yb) ** 2)

@jax.jit
def gradcheck(w, X, y):
    def shard_step(w, xb, yb):
        g = jax.grad(loss_fn)(w, xb, yb)
        return jax.lax.pmean(g, "hvd")
    return shard_map(shard_step, mesh=mesh,
                     in_specs=(P(), P("hvd"), P("hvd")),
                     out_specs=P())(w, X, y)

w = jnp.zeros(4)
g_sharded = gradcheck(w, X, y)
g_global = jax.grad(loss_fn)(w, X, y)
print("sharded", np.asarray(g_sharded))
print("global ", np.asarray(g_global))
