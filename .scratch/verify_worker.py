import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import numpy as np
import horovod_tpu as hvd

hvd.init()
r, n = hvd.rank(), hvd.size()
assert hvd.mode() == "process", hvd.mode()

# broadcast
x = np.full((4,), float(r), np.float32)
b = hvd.broadcast(x, root_rank=1)
assert np.allclose(np.asarray(b), 1.0), b

# allreduce average
a = hvd.allreduce(np.full((8,), float(r + 1), np.float32))
expect = np.mean([i + 1 for i in range(n)])
assert np.allclose(np.asarray(a), expect), (a, expect)

# variable-first-dim allgather
g = hvd.allgather(np.arange((r + 1) * 3, dtype=np.int32).reshape(r + 1, 3))
assert np.asarray(g).shape == (sum(i + 1 for i in range(n)), 3), g.shape

# allgather_object
objs = hvd.allgather_object({"rank": r, "tag": "x" * (r + 1)})
assert [o["rank"] for o in objs] == list(range(n)), objs

# join with uneven steps: rank 0 does 2 extra allreduces
extra = 2 if r == 0 else 0
for i in range(extra):
    out = hvd.allreduce(np.ones(4, np.float32), name=f"uneven.{i}")
j = hvd.join()
print(f"rank {r}: ALL OK (join returned {j})")
