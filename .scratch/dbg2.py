import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import jax
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np, jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P, Mesh
mesh = Mesh(np.array(jax.devices()), ("hvd",))
X = np.random.RandomState(0).randn(64, 4).astype(np.float32)

@jax.jit
def f(X):
    def s(xb):
        return jax.lax.pmean(jnp.mean(xb), "hvd")
    return shard_map(s, mesh=mesh, in_specs=P("hvd"), out_specs=P())(X)

print("pmean:", float(f(X)), "true mean:", X.mean())
