import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import jax
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np, jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P, Mesh
mesh = Mesh(np.array(jax.devices()), ("hvd",))
rng = np.random.RandomState(0)
X = rng.randn(64, 4).astype(np.float32)
y = X @ np.array([1.0, -2.0, 3.0, 0.5], np.float32)
w = jnp.zeros(4)

def loss_fn(w, xb, yb):
    return jnp.mean((xb @ w - yb) ** 2)

@jax.jit
def pershard(w, X, y):
    def s(w, xb, yb):
        g = jax.grad(loss_fn)(w, xb, yb)
        return g[None]  # keep per-shard
    return shard_map(s, mesh=mesh, in_specs=(P(), P("hvd"), P("hvd")),
                     out_specs=P("hvd"))(w, X, y)

gs = np.asarray(pershard(w, X, y))
print("per-shard grads:\n", gs)
print("mean of per-shard:", gs.mean(0))
print("global:", np.asarray(jax.grad(loss_fn)(w, X, y)))
