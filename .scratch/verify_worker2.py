import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import numpy as np
import horovod_tpu as hvd
from horovod_tpu.common.exceptions import HorovodInternalError

try:
    hvd.rank()
    print("FAIL: no error before init"); sys.exit(1)
except Exception as e:
    assert "init" in str(e).lower() or "NotInitialized" in type(e).__name__, e

hvd.init()
r = hvd.rank()
# uint8 through allgather
g = hvd.allgather(np.arange(4, dtype=np.uint8))
assert np.asarray(g).shape == (8,), g
# shape mismatch must raise with op + shapes named
try:
    hvd.allreduce(np.ones((2 + r, 3), np.float32), name="mismatch")
    print("FAIL: mismatch not raised"); sys.exit(1)
except Exception as e:
    msg = str(e)
    assert "mismatch" in msg.lower() or "shape" in msg.lower(), msg
# kill rank 1 mid-run; rank 0 must raise HorovodInternalError
if r == 1:
    os._exit(1)
try:
    hvd.allreduce(np.ones(4, np.float32), name="afterkill")
    print("FAIL: rank0 did not error"); sys.exit(1)
except HorovodInternalError:
    print(f"rank {r}: ERROR PROBES OK")
