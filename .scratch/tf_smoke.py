import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")
import numpy as np
import tensorflow as tf
import horovod_tpu.tensorflow as hvd

hvd.init()
print("size", hvd.size())
# size-1 fast paths
x = tf.constant([1.0, 2.0])
assert np.allclose(hvd.allreduce(x).numpy(), [1.0, 2.0])
assert np.allclose(hvd.allgather(x).numpy(), [1.0, 2.0])
assert np.allclose(hvd.broadcast(x, 0).numpy(), [1.0, 2.0])
out, splits = hvd.alltoall(x)
assert np.allclose(out.numpy(), [1.0, 2.0])

# DistributedGradientTape
w = tf.Variable([1.0, 2.0])
with tf.GradientTape() as tape:
    loss = tf.reduce_sum(w * w)
tape = hvd.DistributedGradientTape(tape)
g = tape.gradient(loss, [w])
assert np.allclose(g[0].numpy(), [2.0, 4.0]), g

# keras DistributedOptimizer single-rank fit
import horovod_tpu.keras as hk
import keras
model = keras.Sequential([keras.layers.Dense(1, input_shape=(4,))])
opt = hk.DistributedOptimizer(keras.optimizers.SGD(0.05))
model.compile(optimizer=opt, loss="mse")
X = np.random.RandomState(0).randn(64, 4).astype(np.float32)
Y = X @ np.array([[1.0], [2.0], [-1.0], [0.5]], np.float32)
h = model.fit(X, Y, epochs=12, batch_size=16, verbose=0,
              callbacks=[hk.callbacks.MetricAverageCallback(),
                         hk.callbacks.BroadcastGlobalVariablesCallback(0)])
l0, l1 = h.history["loss"][0], h.history["loss"][-1]
assert l1 < l0 * 0.2, (l0, l1)

# SyncBatchNorm single-rank
sbn = hvd.SyncBatchNormalization()
y = sbn(tf.random.normal((8, 4)), training=True)
assert y.shape == (8, 4)

# elastic state
st = hvd.__dict__.get("TensorFlowKerasState")
from horovod_tpu.tensorflow.elastic import TensorFlowKerasState
s = TensorFlowKerasState(model, opt, epoch=0, batch=0)
s.save(); s.restore(); s.commit()
print("TF SMOKE OK", l0, "->", l1)
