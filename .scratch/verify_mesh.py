import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import jax
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np, optax, jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
assert hvd.size() == 8, hvd.size()
rng = np.random.RandomState(0)
X = rng.randn(64, 4).astype(np.float32)
w_true = np.array([1.0, -2.0, 3.0, 0.5], np.float32)
y = X @ w_true

tx = hvd.DistributedOptimizer(optax.sgd(0.3), axis_name="hvd")
w = jnp.zeros(4)
ostate = tx.init(w)

def loss_fn(w, xb, yb):
    return jnp.mean((xb @ w - yb) ** 2)

@hvd.wrap_step
def step(carry, xb, yb):
    w, ostate = carry
    g = jax.grad(loss_fn)(w, xb, yb)
    u, ostate2 = tx.update(g, ostate)
    return w + u, ostate2

for i in range(30):
    w, ostate = step((w, ostate), X, y)
l = float(loss_fn(w, X, y))
assert l < 1e-3, l
print("MESH MODE OK loss=%.2e" % l)
