import os
os.environ["JAX_PLATFORMS"] = "cpu"; os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import jax
jax.config.update("jax_num_cpu_devices", 8)
import numpy as np, jax.numpy as jnp
import horovod_tpu as hvd

hvd.init()
X = np.arange(32, dtype=np.float32).reshape(32, 1)  # shards differ
w = jnp.ones(1)

def loss_fn(w, xb):
    return jnp.mean(xb[:, 0] * w[0])

@hvd.wrap_step
def step(w, xb):
    g = jax.grad(loss_fn)(w, xb)
    return hvd.allreduce(g, op=hvd.ReduceOp.AVERAGE)

got = np.asarray(step(w, X))
true_avg = np.asarray(jax.grad(loss_fn)(w, jnp.asarray(X)))
print("wrap_step result:", got, "true global avg:", true_avg,
      "ratio:", got / true_avg)
