import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import numpy as np
import horovod_tpu as hvd

hvd.init()
r, n = hvd.rank(), hvd.size()

# Big tensor -> ring path; check against star-computed truth
x = np.arange(100000, dtype=np.float32) * (r + 1)
out = hvd.allreduce(x, op=hvd.ReduceOp.SUM, name="big")
expect = np.arange(100000, dtype=np.float32) * sum(i + 1 for i in range(n))
assert np.allclose(np.asarray(out), expect), "ring sum wrong"

avg = hvd.allreduce(x, op=hvd.ReduceOp.AVERAGE, name="bigavg")
assert np.allclose(np.asarray(avg), expect / n)

# MIN/MAX/PRODUCT eager (small -> star; large -> ring)
for size in (10, 50000):
    y = (np.arange(size, dtype=np.float64) + 1) * (r + 1)
    mn = hvd.allreduce(y, op=hvd.ReduceOp.MIN, name=f"min{size}")
    assert np.allclose(np.asarray(mn), (np.arange(size) + 1) * 1.0), "min wrong"
    mx = hvd.allreduce(y, op=hvd.ReduceOp.MAX, name=f"max{size}")
    assert np.allclose(np.asarray(mx), (np.arange(size) + 1) * n), "max wrong"
    pr = hvd.allreduce(np.full(size, float(r + 2)), op=hvd.ReduceOp.PRODUCT, name=f"pr{size}")
    expect_pr = np.prod([i + 2 for i in range(n)])
    assert np.allclose(np.asarray(pr), expect_pr), "product wrong"

# join still works with ring enabled (joined -> falls back to star)
if r == 0:
    z = hvd.allreduce(np.ones(60000, np.float32), name="uneven.ring")
    assert np.allclose(np.asarray(z), 1.0 / n)  # zeros from joined ranks dilute the average
hvd.join()
print(f"rank {r}: RING OK")
