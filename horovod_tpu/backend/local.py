"""Trivial single-process backend (size 1): every collective is identity.
Lets user scripts run unmodified without a launcher, like the reference
running with -np 1."""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..common.types import ReduceOp
from .base import Backend


class LocalBackend(Backend):
    def __init__(self):
        self.rank = 0
        self.size = 1

    # control plane
    def gather_bytes(self, payload: bytes) -> Optional[List[bytes]]:
        return [payload]

    def bcast_bytes(self, payload: Optional[bytes]) -> bytes:
        assert payload is not None
        return payload

    def allreduce_words(self, words: List[int], op: str) -> List[int]:
        return list(words)

    def barrier(self):
        pass

    # data plane
    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        return arr.copy()

    def allgatherv(self, arr: np.ndarray, first_dims: List[int]) -> np.ndarray:
        return arr.copy()

    def broadcast(self, arr: Optional[np.ndarray], root: int) -> np.ndarray:
        assert arr is not None
        return arr.copy()

    def alltoallv(
        self, arr: np.ndarray, splits: List[int]
    ) -> Tuple[np.ndarray, List[int]]:
        return arr.copy(), list(splits)

    def adasum_allreduce_all(self, arr: np.ndarray) -> np.ndarray:
        return arr.copy()

    def scatter_bytes(self, payloads: Optional[List[bytes]]) -> bytes:
        assert payloads is not None
        return payloads[0]
