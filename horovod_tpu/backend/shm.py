"""Intra-host shared-memory data plane: mmap'd per-peer ring buffers.

The motivation is PAPER.md's hierarchical design: between co-located
ranks, TCP-through-the-kernel has nothing to offer — loopback frames
still pay two syscalls and two kernel copies per hop, and the PR 4
measurements showed lane concurrency nets ~parity there because there
is no wire latency to hide. An mmap ring buffer does not have that
problem: a frame costs one userspace memcpy in and one out, with no
kernel transition on the fast path.

Layout: one file per co-located peer PAIR (created under
``HOROVOD_SHM_DIR``, named by mesh scope + a rendezvous-published job
nonce so two jobs on one host can never collide), holding two
single-producer/single-consumer byte rings — one per direction. Each
ring is a 64-byte header (u64 write cursor, u64 read cursor, u8
closed flag; cursors are free-running, so ``head - tail`` is the
unread byte count) followed by ``HOROVOD_SHM_RING_BYTES`` of data.
Frames use the shared transport framing (u64 length + u8 channel tag)
and **stream** through the ring: a frame larger than the capacity is
written and consumed concurrently in bounded-buffer pipe fashion, so
the ring bounds memory, never message size. Cursor updates are
aligned 8-byte stores published strictly after their payload bytes
(x86-TSO makes that ordering visible cross-process; CPython executes
the statements in order).

Waiting is futex/eventfd-free polling with bounded spin→sleep: a
reader (or a writer stalled on a full ring — counted in
``horovod_shm_ring_full_total``) re-checks the cursors in a short
burst, yields the scheduler a few times (sleep(0) — GIL-releasing,
core-donating under oversubscription), then sleeps on an exponential
50µs→500µs backoff. The idle bound
honors the generic transport timeout (``HOROVOD_TCP_TIMEOUT_SECONDS``,
progress-reset like the TCP recv heartbeat), and every wait iteration
checks the sever flag — so when the liveness plane declares the peer
dead over TCP (heartbeats ALWAYS ride the sockets; the kernel FIN is
the bounded-detection substrate), parked shm I/O unblocks immediately
with the attributed verdict.

Failure model (docs/fault_tolerance.md): a peer that dies is detected
by the TCP plane (FIN/RST or heartbeat silence) and the backend severs
the whole peer — socket and shm overlay together; a desynced stream
(frame-length mismatch) severs exactly like TCP; the ring file of a
SIGKILLed job is unlinked by the surviving side's close, and stale
files from a whole-job kill are a few MB of /dev/shm reclaimed at the
next boot or by the next run's nonce-scoped establishment.
"""
from __future__ import annotations

import collections
import mmap
import os
import struct
import threading
import time
from typing import Dict, List, Optional

from ..common import fault_injection
from ..utils.logging import get_logger
from .base import HEALTH_CHANNEL, desync_message
from .star import as_byte_view
from .transport import (
    FRAME_HDR,
    FRAME_HDR_LEN,
    PeerSender,
    Transport,
    register_transport,
)

logger = get_logger()

_U64 = struct.Struct("<Q")
_RING_HDR = 64  # one cache line each for head/tail would be ideal;
                # 64 bytes total keeps the math simple and false
                # sharing negligible at these frame sizes.
_OFF_HEAD = 0
_OFF_TAIL = 8
_OFF_CLOSED = 16

# Spin→sleep schedule: a few cheap re-checks (a cursor load each),
# then sched_yield (sleep(0) — releases the GIL every call, so a
# waiting reader can never hold off its own process's other threads
# for a switch interval, and donates the core under oversubscription),
# then exponential real sleeps. The cap trades idle CPU for wake
# latency; 500µs keeps a parked reader well under 1% of a core while a
# streaming one never sleeps at all.
_SPIN = 8
_YIELDS = 32
_SLEEP_MIN = 50e-6
_SLEEP_MAX = 5e-4


class _Waiter:
    """One spin→yield→sleep backoff with a progress-reset idle
    deadline — the single wait policy every shm loop shares (ring
    reads, ring-full send stalls, arena barriers), so the schedule
    and its timeout semantics can never drift between them.
    ``progress()`` after each productive step; ``pause(what)`` for one
    backoff step (raises TimeoutError past the idle bound)."""

    __slots__ = ("timeout", "peer", "spin", "sleep_s", "deadline")

    def __init__(self, timeout: float, peer):
        self.timeout = timeout
        self.peer = peer
        self.spin = 0
        self.sleep_s = _SLEEP_MIN
        self.deadline = (time.monotonic() + timeout
                         if timeout > 0 else None)

    def progress(self) -> None:
        self.spin = 0
        self.sleep_s = _SLEEP_MIN
        if self.deadline is not None:
            self.deadline = time.monotonic() + self.timeout

    def pause(self, what: str) -> None:
        self.spin += 1
        if self.spin <= _SPIN:
            return
        if self.spin <= _SPIN + _YIELDS:
            time.sleep(0)
            return
        time.sleep(self.sleep_s)
        self.sleep_s = min(self.sleep_s * 2, _SLEEP_MAX)
        if self.deadline is not None \
                and time.monotonic() > self.deadline:
            raise TimeoutError(
                f"shm {what} involving peer {self.peer} made no "
                f"progress for {self.timeout:.1f}s "
                f"(HOROVOD_TCP_TIMEOUT_SECONDS)")


def ring_file_name(scope: str, nonce: str, a: int, b: int) -> str:
    lo, hi = (a, b) if a < b else (b, a)
    return f"hvd_shm_{scope}_{nonce}_{lo}x{hi}"


class _Ring:
    """One direction's SPSC byte ring over a shared memoryview. Bulk
    copies go through numpy uint8 views (`data`) — numpy's contiguous
    memcpy releases the GIL, so a 2MB ring write never holds off the
    same process's reader thread the way a memoryview slice assignment
    (GIL-held memcpy) would."""

    __slots__ = ("mv", "data", "cap")

    def __init__(self, mv: memoryview, cap: int):
        import numpy as np

        self.mv = mv          # header + data region
        self.cap = cap
        self.data = np.frombuffer(
            mv[_RING_HDR:_RING_HDR + cap], dtype=np.uint8)

    def head(self) -> int:
        return _U64.unpack_from(self.mv, _OFF_HEAD)[0]

    def set_head(self, v: int) -> None:
        _U64.pack_into(self.mv, _OFF_HEAD, v & 0xFFFFFFFFFFFFFFFF)

    def tail(self) -> int:
        return _U64.unpack_from(self.mv, _OFF_TAIL)[0]

    def set_tail(self, v: int) -> None:
        _U64.pack_into(self.mv, _OFF_TAIL, v & 0xFFFFFFFFFFFFFFFF)

    def closed(self) -> bool:
        return self.mv[_OFF_CLOSED] != 0

    def set_closed(self) -> None:
        self.mv[_OFF_CLOSED] = 1


class ShmTransport(Transport):
    """Shared-memory Transport endpoint for one co-located peer.

    Producer side is serialized by an in-process wire mutex (the
    process is the single producer the SPSC ring needs; its threads
    take the lock). Consumer side enforces the single-reader-at-a-time
    demux contract with the same inbox/condition structure the TCP
    demultiplexer uses. Sync sends fast-path the ring directly while
    their channel has nothing queued on the persistent sender worker —
    the same ordering rule as TCP's sender fast path."""

    name = "shm"

    def __init__(self, backend, peer: int, path: str, ring_bytes: int,
                 timeout: float = 0.0, poll: float = 1.0):
        self.backend = backend
        self.rank = backend.rank
        self.peer = peer
        self.path = path
        self.cap = int(ring_bytes)
        self._timeout = timeout
        self._poll = poll
        self._injector = fault_injection.get_injector()
        size = 2 * (_RING_HDR + self.cap)
        # Both sides open with O_CREAT and size the file identically —
        # ftruncate to the same length is idempotent, and a zero-filled
        # file IS the valid initial ring state (head == tail == 0), so
        # no initialization handshake is needed beyond the rendezvous
        # nonce in the name.
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        mv = memoryview(self._mm)
        half = _RING_HDR + self.cap
        lo_first = _Ring(mv[:half], self.cap)
        hi_first = _Ring(mv[half:], self.cap)
        if self.rank < peer:
            self._tx, self._rx = lo_first, hi_first
        else:
            self._tx, self._rx = hi_first, lo_first
        self._mv = mv
        self._severed = threading.Event()
        self._closed_local = False
        self._wire_lock = threading.Lock()
        self._sender: Optional[PeerSender] = None
        self._sender_lock = threading.Lock()
        # Receive demux (single reader at a time; foreign-channel frames
        # deposited into per-channel inboxes).
        self._cond = threading.Condition()
        self._inbox: Dict[int, "collections.deque"] = {}
        self._reading = False
        # drain_idle progress watermark: peer write-cursor position at
        # the last sweep (progress without consuming is still evidence
        # of life).
        self._seen_head = self._rx.head()
        # Telemetry, installed by the owning backend (transport byte
        # counters + ring-full backpressure stalls). Inert by default.
        self.m_sent = None
        self.m_recv = None
        self.m_ring_full = None
        self.activity_cb = None
        self.health_cb = None
        # The wire-write entry point for BOTH the sync fast path and
        # the sender worker. An owning backend rebinds it to a
        # translating wrapper so ticket errors honor the attributed
        # TransportError contract (never a raw ConnectionError).
        self.send_fn = self._send_direct

    # -- low-level ring I/O --------------------------------------------
    def _check_dead(self, what: str):
        if self._severed.is_set() or self._closed_local:
            raise ConnectionError(
                f"shm link to peer {self.peer} severed during {what}")

    def _write_views(self, views: List[memoryview], channel: int) -> int:
        """Stream [header, *views] into the tx ring. Caller holds the
        wire mutex. Returns payload bytes written."""
        total = sum(len(v) for v in views)
        pieces = [memoryview(FRAME_HDR.pack(total, channel))]
        pieces += [v for v in views if len(v)]
        ring = self._tx
        cap = self.cap
        head = ring.head()
        stalled = False
        import numpy as np

        waiter = _Waiter(self._timeout, self.peer)
        for piece in pieces:
            src = np.frombuffer(piece, dtype=np.uint8)
            off, n = 0, len(src)
            while off < n:
                free = cap - (head - ring.tail())
                if free > 0:
                    k = min(free, n - off)
                    pos = head % cap
                    run = min(k, cap - pos)
                    ring.data[pos:pos + run] = src[off:off + run]
                    if k > run:
                        ring.data[:k - run] = src[off + run:off + k]
                    head += k
                    # Publish strictly after the payload bytes land.
                    ring.set_head(head)
                    off += k
                    waiter.progress()
                    continue
                # Ring full: backpressure. Count once per stall episode.
                if not stalled:
                    stalled = True
                    if self.m_ring_full is not None:
                        self.m_ring_full.inc()
                self._check_dead("send")
                if self._rx.closed():
                    raise ConnectionError(
                        f"peer {self.peer} closed its shm endpoint")
                waiter.pause("send to")
        if self.m_sent is not None:
            self.m_sent.inc(total + FRAME_HDR_LEN)
        return total

    def _read_into(self, view: memoryview) -> None:
        """Stream exactly len(view) bytes out of the rx ring (caller
        holds the reading flag)."""
        import numpy as np

        ring = self._rx
        cap = self.cap
        tail = ring.tail()
        dst = np.frombuffer(view, dtype=np.uint8)
        got, n = 0, len(dst)
        waiter = _Waiter(self._timeout, self.peer)
        while got < n:
            avail = ring.head() - tail
            if avail > 0:
                k = min(avail, n - got)
                pos = tail % cap
                run = min(k, cap - pos)
                dst[got:got + run] = ring.data[pos:pos + run]
                if k > run:
                    dst[got + run:got + k] = ring.data[:k - run]
                tail += k
                # Publish consumption strictly after the copy-out: the
                # producer may overwrite the freed span immediately.
                ring.set_tail(tail)
                got += k
                waiter.progress()
                continue
            self._check_dead("recv")
            if ring.closed():
                raise ConnectionError(
                    f"peer {self.peer} closed its shm endpoint")
            waiter.pause("recv from")

    def _read_header(self):
        hdr = bytearray(FRAME_HDR_LEN)
        self._read_into(memoryview(hdr))
        return FRAME_HDR.unpack(bytes(hdr))

    # -- sends ---------------------------------------------------------
    def _send_direct(self, payload, channel: int) -> None:
        """The single wire-write path (sync fast path and sender worker
        both land here): fault-injection verdicts apply, then the frame
        streams into the ring under the wire mutex."""
        if self._injector.active:
            if (self._injector.check_io(self.rank, self.peer, "send")
                    == fault_injection.DROP):
                return
        self._check_dead("send")
        items = payload if isinstance(payload, (list, tuple)) else (payload,)
        views = [as_byte_view(i) for i in items]
        with self._wire_lock:
            self._write_views(views, channel)

    def _sender_for(self) -> PeerSender:
        with self._sender_lock:
            snd = self._sender
            if snd is None:
                snd = self._sender = PeerSender(
                    lambda payload, ch: self.send_fn(payload, ch),
                    f"shm-{self.peer}",
                    trace_emit=self._trace_dwell)
            return snd

    def _trace_dwell(self, channel: int, t_enq: int, trace_id) -> None:
        tr = getattr(self.backend, "tracer", None)
        if tr is not None and tr.enabled and channel != HEALTH_CHANNEL:
            from ..utils import clock

            tr.emit("shm.sender_dwell", "xfer", t_enq,
                    clock.mono_ns() - t_enq, trace_id=trace_id,
                    args={"peer": self.peer, "channel": channel})

    def send(self, payload, channel: int) -> None:
        snd = self._sender
        if snd is None or snd.channel_idle(channel):
            self.send_fn(payload, channel)
            return
        snd.send(payload, channel).wait()

    def send_async(self, payload, channel: int):
        """Async send with an inline fast path: when the frame fits in
        the ring's current free space (and the wire mutex is free, and
        this channel has nothing queued on the sender worker — FIFO
        within a channel is the ordering contract), write it NOW and
        return a completed ticket. The ring buffer itself is the async
        buffer, so this cannot block — and it keeps the hot ring-
        allreduce path free of thread ping-pong, which on an
        oversubscribed box costs more than the copies do. Anything
        that could block falls back to the persistent sender worker."""
        from .transport import COMPLETED

        self._check_dead("send")
        snd = self._sender
        if snd is None or snd.channel_idle(channel):
            items = (payload if isinstance(payload, (list, tuple))
                     else (payload,))
            views = [as_byte_view(i) for i in items]
            need = sum(len(v) for v in views) + FRAME_HDR_LEN
            if need <= self.cap and self._wire_lock.acquire(blocking=False):
                try:
                    ring = self._tx
                    if self.cap - (ring.head() - ring.tail()) >= need:
                        if self._injector.active:
                            if (self._injector.check_io(
                                    self.rank, self.peer, "send")
                                    == fault_injection.DROP):
                                return COMPLETED
                        self._write_views(views, channel)
                        return COMPLETED
                finally:
                    self._wire_lock.release()
        return self._sender_for().send(payload, channel)

    # -- receives ------------------------------------------------------
    def _demux_recv(self, channel: int,
                    view: Optional[memoryview]) -> Optional[bytearray]:
        """Same structure as the TCP per-peer demultiplexer: one reader
        at a time; foreign-channel frames deposited; health frames
        consumed on the spot."""
        while True:
            with self._cond:
                while True:
                    q = self._inbox.get(channel)
                    if q:
                        buf = q.popleft()
                        if view is None:
                            return buf
                        if len(buf) != len(view):
                            raise OSError(desync_message(
                                len(buf), len(view), peer=self.peer))
                        view[:] = buf
                        return None
                    if self._severed.is_set():
                        raise ConnectionError(
                            f"shm link to peer {self.peer} severed")
                    if not self._reading:
                        self._reading = True
                        break
                    self._cond.wait(self._poll)
            deposit = None
            got_mine = False
            try:
                n, ch = self._read_header()
                if ch == channel:
                    if view is not None:
                        if n != len(view):
                            raise OSError(desync_message(
                                n, len(view), peer=self.peer))
                        self._read_into(view)
                        result = None
                    else:
                        result = bytearray(n)
                        self._read_into(memoryview(result))
                    got_mine = True
                elif ch == HEALTH_CHANNEL:
                    payload = bytearray(n)
                    self._read_into(memoryview(payload))
                    hb = self.health_cb
                    if hb is not None:
                        hb(self.peer, bytes(payload))
                else:
                    payload = bytearray(n)
                    self._read_into(memoryview(payload))
                    deposit = (ch, payload)
                if self.m_recv is not None:
                    self.m_recv.inc(n + FRAME_HDR_LEN)
                cb = self.activity_cb
                if cb is not None:
                    cb(self.peer)
            finally:
                with self._cond:
                    self._reading = False
                    if deposit is not None:
                        self._inbox.setdefault(
                            deposit[0], collections.deque()
                        ).append(deposit[1])
                    self._cond.notify_all()
            if got_mine:
                return result

    def recv(self, channel: int) -> bytearray:
        return self._demux_recv(channel, None)

    def recv_into(self, view: memoryview, channel: int) -> int:
        self._demux_recv(channel, view)
        return len(view)

    # -- liveness ------------------------------------------------------
    def drain_idle(self, max_frames: int = 64) -> int:
        """Progress observation without consuming: the peer's write
        cursor advancing since the last sweep proves it is alive even
        if no reader is currently parked on this ring — the shm
        analogue of the TCP idle drain, minus the consuming (there is
        no kernel buffer to free here, so observation suffices)."""
        head = self._rx.head()
        if head != self._seen_head:
            self._seen_head = head
            cb = self.activity_cb
            if cb is not None:
                cb(self.peer)
        return 0

    def sever(self) -> None:
        self._severed.set()
        # Tell the peer too: its parked reads/writes see our closed
        # flag and unblock into their own sever path.
        if not self._closed_local:
            try:
                self._tx.set_closed()
            except (ValueError, IndexError):  # pragma: no cover - unmapped
                pass
        with self._cond:
            self._cond.notify_all()
        snd = self._sender
        if snd is not None:
            snd.stop()

    @property
    def alive(self) -> bool:
        return not (self._severed.is_set() or self._closed_local)

    def status(self) -> dict:
        return {
            "transport": self.name,
            "alive": self.alive,
            "path": self.path,
            "ring_bytes": self.cap,
            "tx_backlog_bytes": self._tx.head() - self._tx.tail(),
            "rx_backlog_bytes": self._rx.head() - self._rx.tail(),
        }

    def close(self) -> None:
        """Orderly local teardown: stop the sender, mark both the
        shared closed flag and the local sever, and unlink the ring
        file (both sides try; first wins, the mapping stays valid for
        any straggler thread until process exit — munmap under a
        racing reader would be a segfault, so we deliberately leak the
        map until GC)."""
        self.sever()
        snd = self._sender
        if snd is not None:
            snd.thread.join(timeout=5)
        self._closed_local = True
        try:
            os.unlink(self.path)
        except OSError:
            pass


def _shm_factory(backend, peer: int, *, path: str, ring_bytes: int,
                 timeout: float = 0.0, poll: float = 1.0) -> ShmTransport:
    return ShmTransport(backend, peer, path=path, ring_bytes=ring_bytes,
                        timeout=timeout, poll=poll)


register_transport("shm", _shm_factory)


# ---------------------------------------------------------------------------
# Shared-memory ARENA: true intra-host collectives for fully co-located
# groups. The per-pair rings above move bytes rank-to-rank — which, on
# one host, still costs the same aggregate memcpy a kernel socket does.
# The arena is the structural win shared memory uniquely enables: every
# rank deposits its flat buffer into a per-rank SLOT of one shared
# region, and each rank then reduces an equal SUBSLICE directly from
# every peer's slot into a shared result — no per-step neighbor
# ordering (2 data movements + 3 barriers per chunk, vs 2(n-1)
# scheduled segment exchanges for the ring), and the reduction reads
# peers' bytes IN PLACE instead of copying them through a private
# scratch first. This is the MPI-3 shared-memory-window / NCCL
# intra-node shape from the reference's hierarchical design.
#
# Concurrency contract: ONE arena instance serves ONE executor channel.
# Channel executors run collectives concurrently, and cross-rank
# ordering is only guaranteed WITHIN a channel (PR 4's invariant), so
# the owning backend keys arenas by channel — barrier generations then
# advance in lockstep on every rank by construction.
_ARENA_HDR_MIN = 4096  # u64 seq counter per rank at a 64-byte stride
_ARENA_SEQ_STRIDE = 64

# Streaming chunk bound for the hierarchical arena LEGS (reduce-to-
# member / bcast-from-member): unlike the whole-world allreduce (whose
# slot-sized chunks were measured fastest — every rank both writes and
# reads each chunk, so there is little serial chain to pipeline), the
# legs have producer->consumer structure (deposit -> reduce -> root
# copy-out; root deposit -> member copy-out). Capping chunks below the
# slot lets chunk k+1's deposit overlap chunk k's reduce/copy across
# cores — seq-counter barriers cost ~µs, so the extra barriers are
# noise next to the overlap. 2MB matches DEFAULT_RING_SEGMENT_BYTES.
_ARENA_LEG_CHUNK_BYTES = 2 << 20


def _arena_header_bytes(size: int) -> int:
    """Seq-counter region, page-rounded and sized from the GROUP so a
    co-located group larger than 64 ranks can never overflow into slot
    0's payload. Deterministic from `size` alone — every member
    computes the same layout."""
    need = _ARENA_SEQ_STRIDE * size
    return max(_ARENA_HDR_MIN, (need + 4095) // 4096 * 4096)


class ShmArena:
    """One channel's intra-host collective arena (see block comment)."""

    def __init__(self, path: str, index: int, size: int, slot_bytes: int,
                 timeout: float = 0.0):
        import numpy as np

        self.path = path
        self.index = index          # my position in the co-located group
        self.size = size            # group size
        self.slot_bytes = (int(slot_bytes) + 63) // 64 * 64
        self._timeout = timeout
        self._gen = 0
        self._severed: Optional[str] = None
        # Backend-installed: returns a root-cause string when any group
        # member has been declared dead (the TCP liveness plane's
        # verdict), bounding barrier waits without any shm-side
        # heartbeat.
        self.dead_cb = None
        # Transport byte counters (shm): deposit counts as "sent",
        # copy-out as "recv" — the arena's two private<->shared moves.
        self.m_sent = None
        self.m_recv = None
        self._hdr = _arena_header_bytes(size)
        file_size = self._hdr + (size + 1) * self.slot_bytes
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, file_size)
            self._mm = mmap.mmap(fd, file_size)
        finally:
            os.close(fd)
        self._mv = memoryview(self._mm)
        self._u8 = np.frombuffer(self._mm, dtype=np.uint8)

    # -- seq-counter barrier -------------------------------------------
    def _publish(self, value: int) -> None:
        _U64.pack_into(self._mv, _ARENA_SEQ_STRIDE * self.index, value)

    def _seq(self, r: int) -> int:
        return _U64.unpack_from(self._mv, _ARENA_SEQ_STRIDE * r)[0]

    def _wait_all(self, value: int, what: str) -> None:
        waiter = _Waiter(self._timeout, "arena group")
        while True:
            laggard = -1
            for r in range(self.size):
                if r != self.index and self._seq(r) < value:
                    laggard = r
                    break
            if laggard < 0:
                return
            if self._severed is not None:
                raise ConnectionError(
                    f"shm arena severed during {what}: {self._severed}")
            cb = self.dead_cb
            if cb is not None:
                reason = cb()
                if reason is not None:
                    raise ConnectionError(
                        f"shm arena {what} aborted: {reason}")
            waiter.pause(f"arena {what} (waiting on rank {laggard})")

    def _wait_rank(self, r: int, value: int, what: str) -> None:
        """Wait for ONE member's seq counter (the bcast leg's members
        wait on the root only). Bounded exactly like _wait_all: the
        sever flag and the TCP liveness verdict (dead_cb) both unblock
        a parked wait with the attributed reason."""
        waiter = _Waiter(self._timeout, "arena group")
        while self._seq(r) < value:
            if self._severed is not None:
                raise ConnectionError(
                    f"shm arena severed during {what}: {self._severed}")
            cb = self.dead_cb
            if cb is not None:
                reason = cb()
                if reason is not None:
                    raise ConnectionError(
                        f"shm arena {what} aborted: {reason}")
            waiter.pause(f"arena {what} (waiting on member {r})")

    # -- regions -------------------------------------------------------
    def _slot(self, r: int):
        off = self._hdr + r * self.slot_bytes
        return self._u8[off:off + self.slot_bytes]

    @property
    def _result(self):
        return self._slot(self.size)

    # -- collectives ---------------------------------------------------
    def allreduce_into(self, flat, reduce_fn, out=None, codec=None,
                       stats=None, first_hop=None, op_name=None) -> None:
        """Allreduce of a contiguous 1-D numpy array: reads ``flat``,
        writes ``out`` (defaults to ``flat`` — in place). Separate
        src/dst is what lets the caller skip the ring path's defensive
        input copy: the arena never mutates ``flat`` when given a
        fresh ``out``. ``reduce_fn(dst, src)`` accumulates src into
        dst in place (the caller picks the ufunc for the op; AVERAGE
        divides outside). Chunks of ``slot_bytes`` stream through the
        arena: deposit → barrier → every rank reduces its equal
        subslice straight from all slots into the shared result →
        barrier → copy out → barrier (so the next chunk can never
        clobber a result a laggard is still reading).

        With a fixed-width wire ``codec`` (docs/running.md "Wire
        compression") the DEPOSIT leg is encoded — the private→shared
        memcpy that dominates this path halves — and each reducer
        decodes peers' subslices on the fly; the shared result and the
        copy-out stay full-width, so results are bitwise identical on
        every rank exactly as before. Chunk layout is unchanged (the
        encoded chunk always fits the slot its full-width form fits),
        so compressed and uncompressed runs stream the same chunks.
        The per-transport byte counters stay wire truth: ``sent``
        counts deposited (encoded) bytes, ``recv`` counts the
        full-width copy-out — under compression the two legitimately
        differ (docs/metrics.md).

        ``first_hop`` (zero-redundancy first hop, docs/running.md) is
        the engine's already-encoded wire bytes for ``flat``: when
        given, deposits slice it instead of re-encoding — the arena IS
        the op's first hop, so the encode the grid projection already
        paid is the only one. Byte savings still count; no encode
        latency is observed because no encode runs.

        ``op_name`` ("sum"/"min"/"max"/"prod") engages the native fused
        gather-reduce (cc/core.cc hvd_reduce_strided) on the full-width
        leg: one GIL-free pass reading every peer's slot subslice and
        writing the result once, instead of per-peer numpy adds that
        re-read and re-write the accumulator each peer. Rank order is
        preserved, so results stay bitwise identical to ``reduce_fn``
        loops (and to fallback-only hosts)."""
        import numpy as np

        from ..cc import native

        if out is None:
            out = flat
        itemsize = flat.itemsize
        wis = codec.wire_itemsize if codec is not None else itemsize
        chunk_elems = max(self.slot_bytes // itemsize, 1)
        total = flat.size
        src_u8 = flat.view(np.uint8).reshape(-1)
        dst_u8 = out.view(np.uint8).reshape(-1)
        g = self._gen
        for start in range(0, max(total, 1), chunk_elems):
            n = min(chunk_elems, total - start)
            nbytes = n * itemsize
            # Phase 1: deposit my chunk (encoded when a codec rides;
            # sliced from the engine's first-hop encode when provided).
            if codec is None:
                dep_bytes = nbytes
                self._slot(self.index)[:nbytes] = \
                    src_u8[start * itemsize:start * itemsize + nbytes]
            elif first_hop is not None:
                enc = first_hop[start * wis:(start + n) * wis]
                dep_bytes = enc.nbytes
                self._slot(self.index)[:dep_bytes] = enc
                if stats is not None:
                    stats.saved(codec.name, nbytes - dep_bytes)
            else:
                t0 = time.perf_counter()
                enc = codec.encode(flat[start:start + n])
                dep_bytes = enc.nbytes
                self._slot(self.index)[:dep_bytes] = enc
                if stats is not None:
                    stats.observe("encode", time.perf_counter() - t0)
                    stats.saved(codec.name, nbytes - dep_bytes)
            self._publish(g + 1)
            self._wait_all(g + 1, "deposit barrier")
            # Phase 2: reduce my subslice from every slot into the
            # shared result (rank-ordered accumulation — every rank
            # computes its subslice in the same order, so results are
            # bitwise identical everywhere).
            base, rem = divmod(n, self.size)
            lo = self.index * base + min(self.index, rem)
            hi = lo + base + (1 if self.index < rem else 0)
            if hi > lo:
                res = np.frombuffer(
                    self._result[lo * itemsize:hi * itemsize],
                    dtype=flat.dtype)
                if codec is None:
                    fused = op_name is not None and native.reduce_strided(
                        op_name, self._u8,
                        self._hdr + lo * itemsize, self.slot_bytes,
                        self.size, -1, res, init=True)
                    if not fused:
                        span = slice(lo * itemsize, hi * itemsize)
                        res[:] = np.frombuffer(
                            self._slot(0)[span], dtype=flat.dtype)
                        for r in range(1, self.size):
                            reduce_fn(res, np.frombuffer(
                                self._slot(r)[span], dtype=flat.dtype))
                else:
                    span = slice(lo * wis, hi * wis)
                    t0 = time.perf_counter()
                    res[:] = codec.decode(self._slot(0)[span], hi - lo)
                    for r in range(1, self.size):
                        reduce_fn(res, codec.decode(
                            self._slot(r)[span], hi - lo))
                    if stats is not None:
                        # decode+reduce fused over peers' slots — the
                        # decode share dominates, close enough for the
                        # pays-off-here comparison docs/metrics.md
                        # prescribes.
                        stats.observe("decode",
                                      time.perf_counter() - t0)
            self._publish(g + 2)
            self._wait_all(g + 2, "reduce barrier")
            # Phase 3: copy the finished chunk out and PUBLISH the
            # drain generation — but never wait on it. Publishes are
            # program-ordered per rank, so the next chunk's deposit
            # barrier (all >= g+4) implies every rank already published
            # g+3, i.e. finished reading this result — the fence the
            # drain wait would have provided, for one less global sync
            # per chunk. (Slot overwrites are likewise fenced by the
            # reduce barrier: all >= g+2 means nobody still reads the
            # slots.)
            dst_u8[start * itemsize:start * itemsize + nbytes] = \
                self._result[:nbytes]
            self._publish(g + 3)
            g += 3
            if self.m_sent is not None:
                self.m_sent.inc(dep_bytes)
            if self.m_recv is not None:
                self.m_recv.inc(nbytes)
        self._gen = g

    def _leg_chunk_elems(self, itemsize: int) -> int:
        """Chunk size for the double-buffered leg streams: two chunks
        must fit one slot (buffer parity alternates per chunk), capped
        by the pipelining bound."""
        return max(min(self.slot_bytes // 2,
                       _ARENA_LEG_CHUNK_BYTES) // itemsize, 1)

    def reduce_to_member(self, flat, reduce_fn, root: int = 0,
                         out=None, op_name=None) -> None:
        """Fused intra-host gather-reduce to one member: every OTHER
        member deposits its vector chunk-by-chunk into its slot, and
        the member at group position ``root`` accumulates each chunk
        straight into its PRIVATE ``out`` (default ``flat`` in place;
        ``reduce_fn(dst, src)`` in member order, so the result is
        deterministic). This replaces the leader schedule's ring
        reduce-scatter + gather-to-leader pair with the minimum data
        movement the host allows — (L-1) deposits + (L-1) reads per
        chunk, no shared-result hop, no root deposit, no copy-out —
        which is what wins on an aggregate-memcpy-bound box. Chunks
        double-buffer inside each slot (parity offsets), so member k+1
        deposits while the root reduces chunk k; the root's publish
        after reducing chunk k is the members' reuse fence for that
        parity (lag-2 wait), and a closing wait keeps a next
        collective's deposits off buffers the root still reads.

        Full-width by design: intra-host bytes never leave the host, so
        the wire codec does not ride these legs (PR 11 measured codec
        passes on shm memcpy as pure cost; docs/running.md). Byte
        accounting: member deposits count ``sent``, the root's reads of
        member slots count ``recv`` — the leg's two private<->shared
        moves, conserved per host.

        ``op_name`` engages the native fused strided accumulate on the
        root's per-chunk reduce (cc/core.cc hvd_reduce_strided with
        ``init=0``): the root's critical path — pure aggregate
        memcpy+reduce, per PR 14's analysis — folds every member slot
        into its private chunk in one GIL-free pass, member order
        preserved (bitwise identical to the ``reduce_fn`` loop)."""
        import numpy as np

        from ..cc import native

        if out is None:
            out = flat
        itemsize = flat.itemsize
        chunk_elems = self._leg_chunk_elems(itemsize)
        total = flat.size
        src_u8 = flat.view(np.uint8).reshape(-1)
        g = self._gen
        k = 0
        starts = list(range(0, max(total, 1), chunk_elems))
        for start in starts:
            n = min(chunk_elems, total - start)
            nbytes = n * itemsize
            off = (k % 2) * (chunk_elems * itemsize)
            v = g + k + 1
            if self.index == root:
                self._wait_all(v, "reduce deposit wait")
                ochunk = out[start:start + n]
                if out is not flat and n:
                    ochunk[:] = flat[start:start + n]
                fused = n and op_name is not None and \
                    native.reduce_strided(
                        op_name, self._u8, self._hdr + off,
                        self.slot_bytes, self.size, root, ochunk,
                        init=False)
                if not fused:
                    for r in range(self.size):
                        if r == root or n == 0:
                            continue
                        reduce_fn(ochunk, np.frombuffer(
                            self._slot(r)[off:off + nbytes],
                            dtype=flat.dtype))
                self._publish(v)
                if self.m_recv is not None:
                    self.m_recv.inc((self.size - 1) * nbytes)
            else:
                if k >= 2:
                    # Buffer parity k%2 was last read by the root at
                    # chunk k-2; its publish frees it.
                    self._wait_rank(root, v - 2, "reduce reuse wait")
                self._slot(self.index)[off:off + nbytes] = \
                    src_u8[start * itemsize:start * itemsize + nbytes]
                self._publish(v)
                if self.m_sent is not None:
                    self.m_sent.inc(nbytes)
            k += 1
        if self.index != root:
            # Closing fence: the root may still be reducing the last
            # chunks; a next collective's deposit must not overwrite
            # them (the root's own per-chunk wait covers its side).
            self._wait_rank(root, g + len(starts), "reduce close wait")
        self._gen = g + len(starts)

    def bcast_session(self, flat, root: int = 0) -> "_BcastSession":
        """Incremental range-ordered broadcast from the member at group
        position ``root`` (see _BcastSession): the production path for
        the leader schedule's overlapped bcast — the leader deposits
        each element range the moment the inter-host allgather finishes
        it, so the intra-host fan-out hides behind inter-host wire
        time."""
        return _BcastSession(self, flat, root)

    def bcast_from_member(self, flat, root: int = 0) -> None:
        """Whole-vector broadcast from the member at group position
        ``root``: one bcast_session spanning [0, size). Full-width and
        bitwise (a memcpy both ways)."""
        s = self.bcast_session(flat, root)
        if self.index == root:
            s.deposit(0, flat.size)
        else:
            s.copy(0, flat.size)
        s.close()

    def sever(self, reason: str = "severed") -> None:
        self._severed = reason

    @property
    def alive(self) -> bool:
        return self._severed is None

    def status(self) -> dict:
        return {
            "path": self.path,
            "group_size": self.size,
            "slot_bytes": self.slot_bytes,
            "generation": self._gen,
            "alive": self.alive,
        }

    def close(self) -> None:
        self.sever("closed")
        try:
            os.unlink(self.path)
        except OSError:
            pass


class _BcastSession:
    """One incremental intra-host broadcast through an arena's result
    slot. The root calls ``deposit(lo, hi)`` for each element range as
    it becomes final (e.g. per completed inter-host allgather chunk);
    members call ``copy(lo, hi)`` for the SAME ranges in the SAME order
    (both sides derive the order from the deterministic ring schedule,
    so no range metadata travels). Each range streams in double-
    buffered sub-chunks (parity offsets inside the result slot): the
    root deposits sub-chunk k+1 while members copy sub-chunk k; the
    members' publish after copying k is the root's reuse fence for
    that parity (lag-2 wait). ``close()`` fences the tail (root waits
    until every member copied everything) and commits the generation —
    the sub-chunk count depends only on the ranges, so every member
    commits the same value and the arena's barrier lockstep holds for
    the next collective.

    Byte accounting: root deposits count ``sent``, member copy-outs
    count ``recv`` — same contract as every arena move."""

    __slots__ = ("arena", "flat", "root", "u8", "itemsize",
                 "chunk_elems", "k", "g")

    def __init__(self, arena: "ShmArena", flat, root: int):
        import numpy as np

        self.arena = arena
        self.flat = flat
        self.root = root
        self.itemsize = flat.itemsize
        self.u8 = flat.view(np.uint8).reshape(-1)
        self.chunk_elems = arena._leg_chunk_elems(self.itemsize)
        self.k = 0
        self.g = arena._gen

    def _subchunks(self, lo: int, hi: int):
        for start in range(lo, hi, self.chunk_elems):
            yield start, min(self.chunk_elems, hi - start)

    def deposit(self, lo: int, hi: int) -> None:
        a = self.arena
        for start, n in self._subchunks(lo, hi):
            nbytes = n * self.itemsize
            off = (self.k % 2) * (self.chunk_elems * self.itemsize)
            v = self.g + self.k + 1
            if self.k >= 2:
                a._wait_all(v - 2, "bcast reuse wait")
            a._result[off:off + nbytes] = \
                self.u8[start * self.itemsize:
                        start * self.itemsize + nbytes]
            a._publish(v)
            if a.m_sent is not None:
                a.m_sent.inc(nbytes)
            self.k += 1

    def copy(self, lo: int, hi: int) -> None:
        a = self.arena
        for start, n in self._subchunks(lo, hi):
            nbytes = n * self.itemsize
            off = (self.k % 2) * (self.chunk_elems * self.itemsize)
            v = self.g + self.k + 1
            a._wait_rank(self.root, v, "bcast deposit wait")
            self.u8[start * self.itemsize:
                    start * self.itemsize + nbytes] = \
                a._result[off:off + nbytes]
            a._publish(v)
            if a.m_recv is not None:
                a.m_recv.inc(nbytes)
            self.k += 1

    def close(self) -> None:
        if self.arena.index == self.root:
            # Closing fence: every member copied the tail sub-chunks.
            self.arena._wait_all(self.g + self.k, "bcast close wait")
        self.arena._gen = self.g + self.k


class ShmArenaSet:
    """Per-channel lazy arena factory for one CO-LOCATED GROUP of one
    backend (see the concurrency contract above). ``group`` is the
    sorted list of global ranks sharing the host (agreed via the
    rendezvous locality rows): the whole world on a fully co-located
    mesh (the SHM_ARENA_ALLREDUCE plane) or one host's local group on a
    multi-host mesh (the leader schedule's arena legs). Arena files
    carry the group's lowest rank, so two simulated "hosts" sharing one
    box (distinct HOROVOD_HOSTNAME) can never map each other's arenas.
    All group members materialize channel c's arena from the same
    deterministic path on first use, so creation needs no extra
    coordination beyond the establishment-time nonce."""

    def __init__(self, base_dir: str, scope: str, nonce: str,
                 group: List[int], rank: int, slot_bytes: int,
                 timeout: float = 0.0):
        self._dir = base_dir
        self._scope = scope
        self._nonce = nonce
        self.group = sorted(group)
        self.index = self.group.index(rank)
        self.size = len(self.group)
        self._slot_bytes = slot_bytes
        self._timeout = timeout
        self._lock = threading.Lock()
        self._arenas: Dict[int, ShmArena] = {}
        self.dead_cb = None
        self.m_sent = None
        self.m_recv = None

    def get(self, channel: int) -> ShmArena:
        with self._lock:
            a = self._arenas.get(channel)
            if a is None:
                path = os.path.join(
                    self._dir,
                    f"hvd_shm_{self._scope}_{self._nonce}_arena"
                    f"_g{self.group[0]}_c{channel}")
                a = ShmArena(path, self.index, self.size,
                             self._slot_bytes, timeout=self._timeout)
                a.dead_cb = self.dead_cb
                a.m_sent = self.m_sent
                a.m_recv = self.m_recv
                self._arenas[channel] = a
            return a

    def sever(self, reason: str = "severed") -> None:
        with self._lock:
            arenas = list(self._arenas.values())
        for a in arenas:
            a.sever(reason)

    def status(self) -> dict:
        with self._lock:
            channels = {str(ch): a.status()
                        for ch, a in sorted(self._arenas.items())}
        return {"group": list(self.group), "channels": channels}

    def close(self) -> None:
        with self._lock:
            arenas = list(self._arenas.values())
            self._arenas.clear()
        for a in arenas:
            a.close()
