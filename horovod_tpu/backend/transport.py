"""Pluggable per-peer transport layer for the eager data plane.

PAPER.md's L1 layer makes backends interchangeable behind one op
interface (``AllreduceOp::Execute``); this module does the same one
level down, for the bytes themselves: a ``Transport`` is one peer
connection's framed byte plane — send / send_async / recv / recv_into
with channel tags — and the mesh backend (``backend/tcp.py``) composes
one per peer from a registry keyed by **peer locality**:

* ``tcp``  — the socket mesh (always present; bootstrap, control plane
  and heartbeats ride it unconditionally — the FIN/RST is what makes
  dead-peer detection bounded);
* ``shm``  — mmap'd shared-memory ring buffers for co-located ranks
  (``backend/shm.py``): data-channel frames cross the host without
  touching the kernel network stack;
* ``inproc`` — an in-process pair for tests: the same framing, channel
  demux, sever and fault-injection surface with no sockets at all
  (``InprocMesh`` below; the threaded test backend's p2p plane rides
  it).

Frame model (shared by every transport): a u64 payload length + u8
channel tag header, then the payload — exactly the TCP wire framing,
so the conformance suite (tests/test_transport.py) can run the same
checks against all three. Channel demultiplexing (per-channel inboxes,
single reader at a time) is the transport's job; FIFO-per-channel is
the ordering contract, cross-channel overtaking is allowed.

Selection is dynamic: ``HOROVOD_TRANSPORT`` is read per send/recv (see
utils/env.py), so a paired benchmark can flip the route between
barrier-separated rounds; ring *establishment* happens once, at mesh
init, and only when the launch-time value allowed shm.
"""
from __future__ import annotations

import collections
import queue as _queue
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..common import tracing
from ..common.exceptions import TransportError
from ..utils import clock

# Frame header shared by every transport: u64 payload length + u8
# channel tag (backend/tcp.py aliases this for its wire format).
FRAME_HDR = struct.Struct("<QB")
FRAME_HDR_LEN = FRAME_HDR.size


class SendTicket:
    """Completion handle for one frame queued on a persistent peer
    sender; ``wait()`` re-raises the sender thread's TransportError on
    the caller's thread."""

    __slots__ = ("_event", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._error: Optional[BaseException] = None

    def _done(self, error: Optional[BaseException] = None):
        self._error = error
        self._event.set()

    def wait(self):
        self._event.wait()
        if self._error is not None:
            raise self._error


class CompletedTicket:
    """No-op ticket for transports whose sends never block."""

    __slots__ = ()

    def wait(self):
        pass


COMPLETED = CompletedTicket()

_SENDER_STOP = object()


class PeerSender:
    """Persistent queue-fed sender worker for one peer link. Created
    lazily at the first async send to the peer, reused for the owner's
    lifetime, drained on shutdown/sever. The queue holds memoryviews —
    enqueueing a ring segment costs no copy. ``send_fn(payload,
    channel)`` does the actual wire write (under the owner's wire
    mutex), so fault-injection verdicts (drop/delay/sever) apply inside
    the worker: a delay rule stalls the queue and a sever fails the
    ticket exactly like a synchronous send would."""

    def __init__(self, send_fn: Callable, label: str,
                 trace_emit: Optional[Callable] = None):
        self._send_fn = send_fn
        self._trace_emit = trace_emit
        self.label = label
        self.queue: "_queue.Queue" = _queue.Queue()
        # _closed is flipped under _lock BEFORE the stop sentinel is
        # queued, and send() checks it under the same lock — so a put
        # either lands ahead of the sentinel (FIFO: the worker still
        # processes it) or fails fast.
        self._lock = threading.Lock()
        self._closed = False
        # Frames accepted but not yet fully written, per channel tag.
        # The synchronous-send fast path may bypass the worker only
        # while ITS channel has nothing pending here — same-channel
        # order is the only order a receive demux cannot restore.
        self.pending: Dict[int, int] = {}
        self.thread = threading.Thread(
            target=self._loop, name=f"hvd-sender-{label}", daemon=True)
        self.thread.start()

    def send(self, payload, channel: int) -> SendTicket:
        ticket = SendTicket()
        # The trace id is captured on the CALLER's thread (the sender
        # worker has no trace scope of its own), like the channel tag.
        t_enq = clock.mono_ns()
        trace_id = tracing.current_trace()
        with self._lock:
            if self._closed:
                ticket._done(TransportError(
                    f"sender for {self.label} shut down"))
                return ticket
            self.pending[channel] = self.pending.get(channel, 0) + 1
            self.queue.put((payload, channel, ticket, t_enq, trace_id))
        return ticket

    def channel_idle(self, channel: int) -> bool:
        with self._lock:
            return not self._closed and self.pending.get(channel, 0) == 0

    def _frame_done(self, channel: int):
        with self._lock:
            n = self.pending.get(channel, 1) - 1
            if n <= 0:
                self.pending.pop(channel, None)
            else:
                self.pending[channel] = n

    def stop(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self.queue.put(_SENDER_STOP)

    def _loop(self):
        while True:
            item = self.queue.get()
            if item is _SENDER_STOP:
                break
            payload, channel, ticket, t_enq, trace_id = item
            try:
                self._send_fn(payload, channel)
            except BaseException as e:
                self._frame_done(channel)
                ticket._done(e)
            else:
                # Decrement strictly AFTER the frame hit the wire: a
                # fast-path sender that then observes pending == 0 can
                # only order itself after this frame.
                self._frame_done(channel)
                ticket._done()
                if self._trace_emit is not None:
                    self._trace_emit(channel, t_enq, trace_id)
        # Belt-and-braces drain: _closed guarantees nothing lands after
        # the sentinel, but fail anything unexpectedly left anyway
        # rather than leave a waiter parked.
        while True:
            try:
                item = self.queue.get_nowait()
            except _queue.Empty:
                break
            if item is not _SENDER_STOP:  # pragma: no cover - _closed gates
                item[2]._done(TransportError(
                    f"sender for {self.label} shut down"))


class Transport:
    """One peer connection's framed byte plane. Implementations must
    preserve FIFO order within a channel, demultiplex frames by channel
    tag, and translate their failure modes to OSError/TimeoutError —
    the owning backend severs the peer and wraps them in the attributed
    TransportError contract.

    ``name`` keys the registry and the
    horovod_transport_bytes_total{transport=} telemetry label."""

    name = "base"

    def send(self, payload, channel: int) -> None:
        """Synchronous framed send; accepts bytes | memoryview | numpy
        buffer | list of buffers (scatter-gather)."""
        raise NotImplementedError

    def send_async(self, payload, channel: int):
        """Queue a framed send; returns a ticket with .wait()."""
        self.send(payload, channel)
        return COMPLETED

    def recv(self, channel: int) -> bytearray:
        """Next frame tagged `channel`, as an exclusively-owned
        writable buffer."""
        raise NotImplementedError

    def recv_into(self, view: memoryview, channel: int) -> int:
        """Next frame tagged `channel` directly into `view`; the frame
        length must match len(view) exactly (desynced peer otherwise —
        raise OSError with base.desync_message)."""
        raise NotImplementedError

    def sever(self) -> None:
        """Hard-close: every parked or future op on this transport must
        unblock/fail promptly. Idempotent."""
        raise NotImplementedError

    @property
    def alive(self) -> bool:
        raise NotImplementedError

    def drain_idle(self, max_frames: int = 64) -> int:
        """Opportunistic liveness sweep while nobody is reading:
        consume (or observe) peer progress without blocking. Returns
        frames drained; transports where progress is observable without
        consuming (shm write cursors) may return 0 yet still stamp
        activity."""
        return 0

    def status(self) -> dict:
        return {"transport": self.name, "alive": self.alive}

    def close(self) -> None:
        self.sever()


# ---------------------------------------------------------------------------
# registry, keyed by transport name; the mesh backend picks names by
# peer locality (co-located -> shm overlay, remote -> tcp).
_REGISTRY: Dict[str, Callable] = {}


def register_transport(name: str, factory: Callable) -> None:
    """factory(backend, peer, **kw) -> Transport."""
    _REGISTRY[name] = factory


def transport_names() -> List[str]:
    return sorted(_REGISTRY)


def create_transport(name: str, backend, peer: int, **kw) -> Transport:
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown transport {name!r} (registered: {transport_names()})"
        ) from None
    return factory(backend, peer, **kw)


# ---------------------------------------------------------------------------
# In-process transport: the same framing/demux/sever surface with no
# sockets. Used by the conformance suite and by the threaded test
# backend's p2p plane; also handy as a reference implementation of the
# Transport contract.
class _InprocEndpointState:
    """Shared state for one DIRECTED edge a->b: the frames a sent that
    b has not yet consumed, keyed by channel."""

    def __init__(self):
        self.cond = threading.Condition()
        self.inbox: Dict[int, "collections.deque"] = {}
        self.severed = False
        self.deposited_at: Optional[float] = None


class InprocMesh:
    """Process-global mesh of in-process transports for `size` ranks.
    ``transport(rank, peer)`` returns rank's endpoint of the (rank,
    peer) link; both directions share this mesh's state, so severing
    one end unblocks the other."""

    def __init__(self, size: int, timeout: float = 60.0):
        self.size = size
        self.timeout = timeout
        self._edges: Dict[Tuple[int, int], _InprocEndpointState] = {}
        self._lock = threading.Lock()
        self._transports: Dict[Tuple[int, int], "InprocTransport"] = {}

    def edge(self, src: int, dst: int) -> _InprocEndpointState:
        with self._lock:
            e = self._edges.get((src, dst))
            if e is None:
                e = self._edges[(src, dst)] = _InprocEndpointState()
            return e

    def transport(self, rank: int, peer: int) -> "InprocTransport":
        # Construct OUTSIDE the lock: __init__ re-enters edge(), which
        # takes it too. Double-checked insert keeps one instance per
        # directed pair.
        with self._lock:
            t = self._transports.get((rank, peer))
        if t is None:
            t = InprocTransport(self, rank, peer)
            with self._lock:
                t = self._transports.setdefault((rank, peer), t)
        return t


class InprocTransport(Transport):
    """In-process Transport endpoint: rank's side of the (rank, peer)
    link inside an InprocMesh. Payloads are flattened to immutable
    bytes at the send boundary (the \"wire\"), so a memoryview of a
    sender-side numpy chunk can never alias mutable state across
    \"ranks\" — recv hands back a fresh bytearray per frame, keeping
    the owned-buffer contract every transport shares."""

    name = "inproc"

    def __init__(self, mesh: InprocMesh, rank: int, peer: int):
        self.mesh = mesh
        self.rank = rank
        self.peer = peer
        self._tx = mesh.edge(rank, peer)   # frames I send
        self._rx = mesh.edge(peer, rank)   # frames I receive
        self.activity_cb: Optional[Callable] = None
        self.health_cb: Optional[Callable] = None
        self.injector = None  # set by owners that want chaos hooks

    # -- helpers -------------------------------------------------------
    @staticmethod
    def _flatten(payload) -> bytes:
        # star.join_buffers is the one scatter-gather coalescer; the
        # bytes() wrap makes the "wire" copy immutable so a sender-side
        # memoryview can never alias mutable state across "ranks".
        from .star import join_buffers

        return bytes(join_buffers(payload))

    def _check_io(self, op: str):
        inj = self.injector
        if inj is not None and inj.active:
            return inj.check_io(self.rank, self.peer, op)
        return None

    # -- Transport interface -------------------------------------------
    def send(self, payload, channel: int) -> None:
        from ..common import fault_injection

        if self._check_io("send") == fault_injection.DROP:
            return
        blob = self._flatten(payload)
        with self._tx.cond:
            if self._tx.severed:
                raise ConnectionError(
                    f"inproc link {self.rank}->{self.peer} severed")
            self._tx.inbox.setdefault(
                channel, collections.deque()).append(blob)
            self._tx.deposited_at = time.monotonic()
            self._tx.cond.notify_all()

    def recv(self, channel: int) -> bytearray:
        self._check_io("recv")
        deadline = time.monotonic() + self.mesh.timeout
        with self._rx.cond:
            while True:
                q = self._rx.inbox.get(channel)
                if q:
                    buf = bytearray(q.popleft())
                    cb = self.activity_cb
                    if cb is not None:
                        cb(self.peer)
                    return buf
                if self._rx.severed:
                    raise ConnectionError(
                        f"inproc link {self.peer}->{self.rank} severed")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"inproc recv from peer {self.peer} timed out "
                        f"after {self.mesh.timeout:.1f}s")
                self._rx.cond.wait(min(remaining, 1.0))

    def recv_into(self, view: memoryview, channel: int) -> int:
        from .base import desync_message

        data = self.recv(channel)
        if len(data) != len(view):
            raise OSError(desync_message(len(data), len(view),
                                         rank=self.rank, peer=self.peer))
        view[:len(data)] = data
        return len(data)

    def sever(self) -> None:
        for edge in (self._tx, self._rx):
            with edge.cond:
                edge.severed = True
                edge.cond.notify_all()

    @property
    def alive(self) -> bool:
        return not (self._rx.severed or self._tx.severed)

    def drain_idle(self, max_frames: int = 64) -> int:
        """Health frames deposited by the peer are consumed here;
        anything else stays for its reader. A deposit since the last
        sweep counts as activity evidence.
        """
        consumed = 0
        cb = self.activity_cb
        with self._rx.cond:
            from .base import HEALTH_CHANNEL

            q = self._rx.inbox.get(HEALTH_CHANNEL)
            frames = []
            while q and consumed < max_frames:
                frames.append(q.popleft())
                consumed += 1
            fresh = self._rx.deposited_at
            self._rx.deposited_at = None
        hb = self.health_cb
        for payload in frames:
            if hb is not None:
                hb(self.peer, bytes(payload))
        if (frames or fresh is not None) and cb is not None:
            cb(self.peer)
        return consumed


# ---------------------------------------------------------------------------
# In-process mesh backend: the full TcpBackend peer surface (p2p +
# star primitives + liveness + fault injection + sever semantics) over
# InprocTransport links. The conformance suite runs the same checks
# against this, the socket mesh and the shm overlay; it is also the
# cheapest way to exercise sever/attribution paths in-process.
def _inproc_factory(backend, peer: int, **kw) -> InprocTransport:
    t = backend.mesh.transport(backend.rank, peer)
    t.activity_cb = backend._note_activity
    t.health_cb = backend._route_health
    t.injector = backend._injector
    return t


register_transport("inproc", _inproc_factory)


class InprocBackend:
    """One rank of an in-process mesh (see module docstring). Mixed in
    with the collectives mixins lazily in `make_inproc_backends` to
    avoid a module-import cycle with backend/ring.py."""


def make_inproc_backends(size: int, timeout: float = 60.0):
    """Build a `size`-rank in-process mesh; returns the backends. Each
    one supports the same data-plane + liveness surface the TCP mesh
    does (send_to/recv_from/recv_into_from/send_async, gather/bcast/
    scatter, declare_dead/death_reason/peer_activity/try_drain_idle/
    set_health_callback), so tests exercise identical contracts."""
    from ..common import fault_injection
    from .ring import RingCollectivesMixin

    mesh = InprocMesh(size, timeout=timeout)

    class _InprocMeshBackend(RingCollectivesMixin, InprocBackend):
        def __init__(self, rank: int):
            self.mesh = mesh
            self.rank = rank
            self.size = size
            self._injector = fault_injection.get_injector()
            self._death_lock = threading.Lock()
            self._death_reasons: Dict[int, str] = {}
            self._health_cb = None
            self._last_activity: Dict[int, float] = {}
            self._transports: Dict[int, InprocTransport] = {
                p: create_transport("inproc", self, p)
                for p in range(size) if p != rank
            }

        # -- liveness surface (mirrors backend/tcp.py) -----------------
        def set_health_callback(self, cb) -> None:
            self._health_cb = cb

        def _route_health(self, peer: int, payload) -> None:
            self._note_activity(peer)
            cb = self._health_cb
            if cb is not None:
                cb(peer, bytes(payload))

        def _note_activity(self, peer: int) -> None:
            self._last_activity[peer] = time.monotonic()

        def peer_activity(self, peer: int):
            return self._last_activity.get(peer)

        def death_reason(self, peer: int):
            with self._death_lock:
                return self._death_reasons.get(peer)

        def declare_dead(self, peer: int, reason: str) -> None:
            with self._death_lock:
                self._death_reasons.setdefault(peer, reason)
            self._sever(peer)

        def try_drain_idle(self, peer: int, max_frames: int = 64) -> int:
            t = self._transports.get(peer)
            return t.drain_idle(max_frames) if t is not None else 0

        def _sever(self, peer: int):
            t = self._transports.get(peer)
            if t is not None:
                t.sever()

        def _transport_error(self, peer: int, what: str,
                             exc) -> TransportError:
            cause = self.death_reason(peer)
            if cause is not None:
                return TransportError(cause, peer=peer, reporter=self.rank,
                                      root_cause=cause)
            return TransportError(
                f"rank {self.rank}: {what} peer {peer} failed: {exc}",
                peer=peer, reporter=self.rank,
            )

        def _check_alive(self, peer: int):
            t = self._transports[peer]
            if not t.alive:
                raise self._transport_error(
                    peer, "use of severed link to", "severed")

        # -- p2p primitives --------------------------------------------
        def send_to(self, peer: int, payload):
            from .base import current_channel

            t = self._transports[peer]
            try:
                self._check_alive(peer)
                t.send(payload, current_channel())
            except (OSError, TimeoutError) as exc:
                self._sever(peer)
                raise self._transport_error(peer, "send to", exc) from exc

        def recv_from(self, peer: int) -> bytearray:
            from .base import current_channel

            t = self._transports[peer]
            try:
                return t.recv(current_channel())
            except (OSError, TimeoutError) as exc:
                self._sever(peer)
                raise self._transport_error(peer, "recv from", exc) from exc

        def recv_into_from(self, peer: int, buf) -> int:
            from .base import current_channel
            from .star import as_byte_view

            t = self._transports[peer]
            try:
                return t.recv_into(as_byte_view(buf), current_channel())
            except (OSError, TimeoutError) as exc:
                self._sever(peer)
                raise self._transport_error(peer, "recv from", exc) from exc

        def send_async(self, peer: int, payload, channel: Optional[int]
                       = None):
            from .base import current_channel

            t = self._transports[peer]
            ch = current_channel() if channel is None else channel
            try:
                self._check_alive(peer)
                return t.send_async(payload, ch)
            except (OSError, TimeoutError) as exc:
                self._sever(peer)
                raise self._transport_error(peer, "send to", exc) from exc

        # -- star primitives over p2p ----------------------------------
        def gather_bytes(self, payload):
            if self.size == 1:
                return [InprocTransport._flatten(payload)]
            if self.rank == 0:
                out = [InprocTransport._flatten(payload)]
                for r in range(1, self.size):
                    out.append(self.recv_from(r))
                return out
            self.send_to(0, payload)
            return None

        def bcast_bytes(self, payload):
            if self.size == 1:
                assert payload is not None
                return payload
            if self.rank == 0:
                assert payload is not None
                first_error: Optional[TransportError] = None
                for r in range(1, self.size):
                    try:
                        self.send_to(r, payload)
                    except TransportError as exc:
                        if first_error is None:
                            first_error = exc
                if first_error is not None:
                    raise first_error
                return payload
            return self.recv_from(0)

        def scatter_bytes(self, payloads):
            if self.size == 1:
                assert payloads is not None
                return InprocTransport._flatten(payloads[0])
            if self.rank == 0:
                assert payloads is not None
                for r in range(1, self.size):
                    self.send_to(r, payloads[r])
                return InprocTransport._flatten(payloads[0])
            return self.recv_from(0)

        def transport_status(self) -> dict:
            return {
                "mode": "inproc",
                "peers": {str(p): t.status()
                          for p, t in sorted(self._transports.items())},
            }

        def shutdown(self):
            for t in self._transports.values():
                t.sever()

    return [_InprocMeshBackend(r) for r in range(size)]
