"""Worker-side elastic plumbing: rank re-assignment + host-update
notifications.

(ref: horovod/common/gloo/gloo_context.cc:157-200 — on reset a worker
GETs its new rank/size from the rendezvous `rank_and_size` scope keyed
by hostname:local_rank, rank==-1 meaning it was removed; and
horovod/runner/elastic/worker.py — WorkerNotificationService/Manager.)
"""
from __future__ import annotations

import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from ..utils import env as env_cfg
from ..utils.logging import get_logger
from .rendezvous import RendezvousClient

logger = get_logger()

NOTIFY_SCOPE = "workers_notify"


def _rendezvous() -> Optional[RendezvousClient]:
    addr = env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR)
    port = env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0)
    if not addr or not port:
        return None
    return RendezvousClient(addr, port)


def spawn_identity() -> str:
    """Stable worker identity across resets: hostname + the local slot
    it was SPAWNED into (HOROVOD_LOCAL_RANK changes with reassignment;
    the spawn slot does not)."""
    hostname = env_cfg.get_str(env_cfg.HOSTNAME, "localhost")
    spawn_lr = env_cfg.get_str("HOROVOD_SPAWN_LOCAL_RANK") or str(
        env_cfg.get_int(env_cfg.LOCAL_RANK, 0)
    )
    return f"{hostname}:{spawn_lr}"


def _current_epoch() -> Optional[int]:
    scope = env_cfg.get_str(env_cfg.MESH_SCOPE)
    if scope.startswith("hvd_mesh_e"):
        try:
            return int(scope[len("hvd_mesh_e"):])
        except ValueError:
            return None
    return None


def refresh_topology_from_rendezvous(timeout: Optional[float] = None):
    """Update HOROVOD_RANK/SIZE/... env from the driver's next epoch
    assignment (ref: gloo_context.cc:157-200; epoch protocol documented
    in runner/elastic/driver.py). Announces readiness, waits for an epoch
    newer than the one this worker was last in, then reads its row; an
    INVALID row (rank -1) means this worker lost its slot and exits.
    The wait is bounded by HOROVOD_ELASTIC_RESET_TIMEOUT (default 600s)
    unless an explicit `timeout` is passed."""
    if timeout is None:
        timeout = env_cfg.elastic_reset_timeout()
    rdv = _rendezvous()
    if rdv is None:
        return
    key = spawn_identity()
    my_epoch = _current_epoch()
    # Tell the driver this worker is parked at the reset barrier.
    rdv.put(f"ready_e{my_epoch if my_epoch is not None else 0}", key, b"1")

    deadline = time.monotonic() + timeout
    while True:
        raw = rdv.get("meta", "epoch")
        if raw is not None:
            epoch = int(raw.decode())
            if my_epoch is None or epoch > my_epoch:
                break
        if time.monotonic() > deadline:
            raise TimeoutError("no new topology epoch from elastic driver")
        time.sleep(0.1)

    data = rdv.wait_get(f"rank_and_size_e{epoch}", key).decode()
    vals = [int(v) for v in data.split(",")]
    rank, size, lrank, lsize, crank, csize = vals
    if rank == -1:
        logger.info("this worker was removed from the job; exiting")
        sys.exit(0)
    os.environ[env_cfg.RANK] = str(rank)
    os.environ[env_cfg.SIZE] = str(size)
    os.environ[env_cfg.LOCAL_RANK] = str(lrank)
    os.environ[env_cfg.LOCAL_SIZE] = str(lsize)
    os.environ[env_cfg.CROSS_RANK] = str(crank)
    os.environ[env_cfg.CROSS_SIZE] = str(csize)
    # Epoch-scoped mesh rendezvous so the new full mesh never reuses
    # stale peer addresses from before the reset.
    os.environ[env_cfg.MESH_SCOPE] = f"hvd_mesh_e{epoch}"


class _NotifyHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length).decode()
        mgr: WorkerNotificationManager = self.server.manager  # type: ignore
        mgr._on_hosts_updated(body)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class WorkerNotificationManager:
    """Receives HostsUpdated pings from the elastic driver and fans them
    out to registered State listeners
    (ref: horovod/runner/elastic/worker.py:20-110)."""

    def __init__(self):
        self._listeners: List = []
        self._lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._initialized = False
        self._stop = threading.Event()
        self._server_thread: Optional[threading.Thread] = None
        self._watch_thread: Optional[threading.Thread] = None

    def init(self):
        with self._lock:
            if self._initialized:
                return
            rdv = _rendezvous()
            if rdv is None or not env_cfg.get_bool(env_cfg.ELASTIC, False):
                self._initialized = True
                return
            self._stop = threading.Event()
            self._httpd = ThreadingHTTPServer(("0.0.0.0", 0), _NotifyHandler)
            self._httpd.manager = self  # type: ignore
            t = threading.Thread(target=self._httpd.serve_forever,
                                 name="hvd-notify", daemon=True)
            t.start()
            self._server_thread = t
            port = self._httpd.server_address[1]
            # Register by stable spawn identity (ranks change per epoch).
            hostname = env_cfg.get_str(env_cfg.HOSTNAME, "localhost")
            reach = (
                "127.0.0.1"
                if hostname in ("localhost", "127.0.0.1", "")
                or hostname.startswith("process-")
                or os.environ.get("HVDRUN_FORCE_LOCAL")
                else hostname
            )
            rdv.put(NOTIFY_SCOPE, spawn_identity(), f"{reach}:{port}".encode())
            # The driver's HTTP ping is best-effort and one-shot: it is
            # silently skipped for a worker that has not registered yet
            # (e.g. still importing frameworks when the topology
            # changes). The epoch watcher guarantees delivery: any
            # epoch newer than the one this worker is meshed into
            # synthesizes the same notification at the next poll.
            tw = threading.Thread(target=self._epoch_watch,
                                  args=(rdv, self._stop),
                                  name="hvd-epoch-watch", daemon=True)
            tw.start()
            self._watch_thread = tw
            self._initialized = True

    def shutdown(self):
        """Stop the notify HTTP server and the epoch-watch thread
        (wired into basics.shutdown()): without this they survive —
        and accumulate across — init/shutdown cycles, each leaked
        server still registered in the rendezvous KV. Listeners are
        kept: the elastic run loop re-inits the manager after a reset
        and its State must stay subscribed."""
        with self._lock:
            if not self._initialized:
                return
            self._stop.set()
            if self._httpd is not None:
                self._httpd.shutdown()
                self._httpd.server_close()
                self._httpd = None
            server_t, watch_t = self._server_thread, self._watch_thread
            self._server_thread = self._watch_thread = None
            self._initialized = False
        for t in (server_t, watch_t):
            if t is not None:
                t.join(timeout=10)

    def _epoch_watch(self, rdv: RendezvousClient, stop: threading.Event):
        interval = env_cfg.get_float("HOROVOD_ELASTIC_EPOCH_POLL", 0.5)
        notified_epoch: Optional[int] = None
        while not stop.wait(interval):
            try:
                raw = rdv.get("meta", "epoch")
            except OSError:
                continue  # driver tearing down / transient network
            if raw is None:
                continue
            try:
                epoch = int(raw.decode())
            except ValueError:
                continue
            current = _current_epoch()
            if current is None:
                current = 0
            if epoch > current and epoch != notified_epoch:
                # ADDED forces a state sync, the safe default when the
                # watcher can't know what kind of change occurred. Only
                # latch once a listener actually received it — firing
                # into a not-yet-registered listener list (worker still
                # initializing) must retry on the next poll or the
                # guarantee this thread exists for is lost. Delivery
                # count comes from the fan-out itself (single lock
                # acquisition) so an unregister between a snapshot and
                # the delivery can't fake success.
                if self._on_hosts_updated(f"{time.time()},2"):
                    notified_epoch = epoch

    def register_listener(self, state):
        with self._lock:
            self._listeners.append(state)

    def remove_listener(self, state):
        with self._lock:
            if state in self._listeners:
                self._listeners.remove(state)

    def _on_hosts_updated(self, body: str) -> int:
        """Fan a notification out to the registered listeners; returns
        how many received it (0 = nobody was listening yet)."""
        parts = body.split(",")
        ts = float(parts[0]) if parts and parts[0] else time.time()
        res = int(parts[1]) if len(parts) > 1 else 0
        with self._lock:
            for l in self._listeners:
                l.on_hosts_updated(ts, res)
            return len(self._listeners)


notification_manager = WorkerNotificationManager()
