"""Star-topology collective algorithms over abstract gather/bcast.

Rank 0 is the aggregation point, exactly how the reference's controller
runs its control plane over MPI_Gather/Bcast (ref: mpi_controller.cc:
108-199). Any transport providing gather_bytes/bcast_bytes gets the full
data-plane collective set; the TCP mesh and the in-process threaded test
backend both build on this. (On TPU hardware the data plane is XLA/ICI —
this path serves CPU process-mode and tests.)
"""
from __future__ import annotations

import struct
import time
from typing import List, Optional, Tuple

import numpy as np

from ..common.types import ReduceOp
from .base import (Backend, _NATIVE_OP, _reduce, current_wire_codec,
                   wire_codec_stats)

_LEN = struct.Struct("<Q")

# Elementwise fold ufuncs for the streaming compressed reduce — the
# numpy mirror of the native reduce_into kernels (docs/native.md).
_FOLD_UFUNC = {"sum": np.add, "min": np.minimum,
               "max": np.maximum, "prod": np.multiply}


def pack_array(arr: np.ndarray) -> list:
    """Self-describing array frame as a scatter-gather buffer list
    [header, payload-memoryview]: the transport sendmsg's the pieces to
    the wire without ever concatenating them, so packing a tensor costs
    zero copies (unless a non-contiguous input forces one)."""
    # ';' separator: numpy dtype.str can itself contain '|' (e.g. '|u1').
    head = f"{arr.dtype.str};{','.join(map(str, arr.shape))}".encode()
    # reshape(-1) is a view of the contiguous array; memoryview.cast
    # refuses multi-dim views with a zero dim, 1-D always works.
    return [_LEN.pack(len(head)) + head,
            memoryview(np.ascontiguousarray(arr).reshape(-1)).cast("B")]


def unpack_array(buf) -> np.ndarray:
    """Decode an array frame zero-copy: the result ALIASES `buf`
    (writable iff buf is — a recv-into bytearray yields a writable,
    exclusively owned array; immutable bytes yield a read-only view).
    Callers that hand the array to user code or must outlive/mutate a
    shared buffer wrap the result in `own_array`."""
    view = buf if isinstance(buf, memoryview) else memoryview(buf)
    (hn,) = _LEN.unpack(view[:8])
    head = bytes(view[8 : 8 + hn]).decode()
    dtype_str, shape_str = head.split(";")
    shape = tuple(int(s) for s in shape_str.split(",")) if shape_str else ()
    return np.frombuffer(view[8 + hn :], dtype=np.dtype(dtype_str)).reshape(shape)


def pack_wire(arr: np.ndarray, codec, enc: np.ndarray) -> list:
    """Compressed array frame [header, encoded-payload] (docs/running.md
    "Wire compression"): like pack_array but the payload is the codec's
    wire bytes and the header names the codec, so the peer decodes
    without out-of-band state. `enc` is passed in (not recomputed) so
    call sites can count wire savings and reuse the encode."""
    head = (f"{arr.dtype.str};{','.join(map(str, arr.shape))};"
            f"{codec.name}").encode()
    return [_LEN.pack(len(head)) + head, memoryview(enc)]


def unpack_wire(buf) -> np.ndarray:
    """Decode a pack_wire frame back to a full-width array. The result
    is freshly allocated by the codec decode — always owned and
    writable, unlike unpack_array's aliasing view."""
    from ..common import compression

    view = buf if isinstance(buf, memoryview) else memoryview(buf)
    (hn,) = _LEN.unpack(view[:8])
    head = bytes(view[8: 8 + hn]).decode()
    dtype_str, shape_str, codec_name = head.split(";")
    shape = tuple(int(s) for s in shape_str.split(",")) if shape_str else ()
    codec = compression.codec_by_name(codec_name)
    if codec is None:
        raise ValueError(f"unknown wire codec {codec_name!r} in frame "
                         f"(version skew between ranks?)")
    count = 1
    for d in shape:
        count *= d
    out = codec.decode(view[8 + hn:], count)
    return out.astype(np.dtype(dtype_str), copy=False).reshape(shape)


def own_array(a: np.ndarray) -> np.ndarray:
    """Return `a` as an owned, writable array: zero-copy when its
    backing buffer is already exclusively ours (every TCP recv allocates
    a fresh writable bytearray per frame), a copy when the transport
    handed us a shared or read-only blob (the threaded test backend
    broadcasts one immutable bytes object to every rank)."""
    return a if a.flags.writeable else a.copy()


def as_byte_view(buf) -> memoryview:
    """Normalize one buffer-protocol object (bytes, bytearray,
    memoryview, numpy array) to a flat 1-D byte memoryview, zero-copy.
    memoryview.cast refuses multi-dim views with a zero dim — an empty
    buffer is an empty buffer."""
    v = buf if isinstance(buf, memoryview) else memoryview(buf)
    if v.ndim != 1 or v.format != "B":
        v = v.cast("B") if v.nbytes else memoryview(b"")
    return v


def join_buffers(payload):
    """Coalesce a scatter-gather buffer list into one bytes-like blob —
    the LOCAL-consumption path only (rank 0 decoding its own gathered
    payload, queue transports); the wire path never joins, the frames go
    out via sendmsg. Single buffers (and plain bytes) pass through
    untouched."""
    if not isinstance(payload, (list, tuple)):
        return payload
    views = [as_byte_view(item) for item in payload]
    if len(views) == 1:
        return views[0]
    out = bytearray(sum(len(v) for v in views))
    off = 0
    for v in views:
        out[off : off + len(v)] = v
        off += len(v)
    return out


class StarCollectivesMixin(Backend):
    """Data-plane collectives via rank-0 aggregation."""

    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        if self.size == 1:
            return arr.copy()
        codec = current_wire_codec()
        if codec is not None and codec.applicable(arr.dtype):
            return self._allreduce_compressed(arr, op, codec)
        # Tracing-plane phase spans (docs/tracing.md): gather / reduce /
        # bcast, inheriting the executor's trace scope so the merged
        # trace shows which phase of WHICH collective ate the time.
        tr = self.tracer
        with tr.span("star.gather", cat="xfer",
                     args={"bytes": int(arr.nbytes)}):
            gathered = self.gather_bytes(pack_array(arr))
        if self.rank == 0:
            with tr.span("star.reduce", cat="compute"):
                arrays = [unpack_array(b) for b in gathered]
                # Joined ranks contribute empty arrays == zeros
                # (ref: JoinOp semantics, controller.cc:220-231).
                nonempty = [a for a in arrays if a.size > 0]
                out = _reduce(op, nonempty) if nonempty else arrays[0]
            with tr.span("star.bcast", cat="xfer"):
                self.bcast_bytes(pack_array(out))
            return out.reshape(arr.shape) if arr.size else out
        with tr.span("star.bcast", cat="xfer"):
            out = own_array(unpack_array(self.bcast_bytes(None)))
        return out.reshape(arr.shape) if arr.size and out.size == arr.size else out

    def _allreduce_compressed(self, arr: np.ndarray, op: ReduceOp,
                              codec) -> np.ndarray:
        """Compressed star allreduce (docs/running.md "Wire
        compression"): every rank gathers its payload ENCODED, the
        root decodes and reduces in full-width fp32, then broadcasts
        the result encoded again — both legs ship the codec's bytes.
        The root's own return value is the DECODED result (not its
        full-width reduction): every rank must finish holding the
        bitwise-identical value its peers decoded off the wire, the
        same determinism contract the uncompressed path has.

        Zero-redundancy first hop (docs/running.md "Wire compression"):
        the gather frame IS the op's first hop, so when the engine's
        error-feedback grid projection already encoded this
        contribution, those bytes ship directly (bitwise what a
        re-encode would produce — encode is value-deterministic) and
        the only encode pass observed for the hop is the engine's."""
        from .base import take_first_hop_encoded

        tr = self.tracer
        stats = wire_codec_stats()
        flat = np.ascontiguousarray(arr).reshape(-1)
        enc = take_first_hop_encoded(codec.wire_bytes(flat.size))
        if enc is None:
            t0 = time.perf_counter()
            enc = codec.encode(flat)
            if stats is not None:
                stats.observe("encode", time.perf_counter() - t0)
        if stats is not None and self.rank != 0:
            # Only frames that actually hit a transport count as
            # wire savings; rank 0's gather contribution is local.
            stats.saved(codec.name, flat.nbytes - enc.nbytes)
        with tr.span("star.gather", cat="xfer",
                     args={"bytes": int(enc.nbytes), "codec": codec.name}):
            gathered = self.gather_bytes(pack_wire(flat, codec, enc))
        if self.rank == 0:
            fold = _NATIVE_OP.get(op)
            with tr.span("star.reduce", cat="compute"):
                if fold is None:
                    t0 = time.perf_counter()
                    arrays = [unpack_wire(b) for b in gathered]
                    if stats is not None:
                        stats.observe("decode", time.perf_counter() - t0)
                    nonempty = [a for a in arrays if a.size > 0]
                    out = _reduce(op, nonempty) if nonempty else arrays[0]
                else:
                    # Streaming decode+fold (docs/native.md): decode one
                    # frame at a time and reduce it straight into the
                    # running accumulator — native reduce_into when the
                    # .so is loaded, the matching ufunc otherwise — so
                    # peak memory is two full-width arrays instead of
                    # world_size + 1.  Rank order is preserved, keeping
                    # the result bitwise identical to decode-all+_reduce.
                    from ..cc import native

                    dec = 0.0
                    out = None
                    first = None
                    n_contrib = 0
                    for b in gathered:
                        t0 = time.perf_counter()
                        a = unpack_wire(b)
                        dec += time.perf_counter() - t0
                        if first is None:
                            first = a
                        if a.size == 0:
                            # Joined ranks contribute empty == zeros.
                            continue
                        n_contrib += 1
                        if out is None:
                            out = own_array(np.ascontiguousarray(a))
                        elif not native.reduce_into(fold, out, a):
                            _FOLD_UFUNC[fold](out, a, out=out)
                    if stats is not None:
                        stats.observe("decode", dec)
                    if out is None:
                        out = first
                    elif op == ReduceOp.AVERAGE:
                        out = out / n_contrib
            out_flat = np.ascontiguousarray(out).reshape(-1)
            t0 = time.perf_counter()
            enc_out = codec.encode(out_flat)
            # What every peer will decode — and what this rank must
            # return for bitwise cross-rank agreement.
            result = codec.decode(enc_out, out_flat.size)
            if stats is not None:
                stats.observe("encode", time.perf_counter() - t0)
                stats.saved(codec.name, (self.size - 1)
                            * (out_flat.nbytes - enc_out.nbytes))
            with tr.span("star.bcast", cat="xfer",
                         args={"bytes": int(enc_out.nbytes)}):
                self.bcast_bytes(pack_wire(out_flat, codec, enc_out))
            result = result.astype(arr.dtype, copy=False)
            return result.reshape(arr.shape) if arr.size else result
        with tr.span("star.bcast", cat="xfer"):
            blob = self.bcast_bytes(None)
        t0 = time.perf_counter()
        out = unpack_wire(blob)
        if stats is not None:
            stats.observe("decode", time.perf_counter() - t0)
        out = out.reshape(-1).astype(arr.dtype, copy=False)
        return (out.reshape(arr.shape)
                if arr.size and out.size == arr.size else out)

    def adasum_allreduce_all(self, arr: np.ndarray) -> np.ndarray:
        if self.size == 1:
            return arr.copy()
        gathered = self.gather_bytes(pack_array(arr))
        if self.rank == 0:
            arrays = [unpack_array(b) for b in gathered]
            nonempty = [a for a in arrays if a.size > 0]
            if len(nonempty) & (len(nonempty) - 1) != 0:
                # Must never silently degrade: the controller rejects
                # Adasum+join, and enqueue rejects non-power-of-2 worlds,
                # so this is an internal invariant violation.
                raise RuntimeError(
                    f"Adasum requires a power-of-2 contributor count, got "
                    f"{len(nonempty)}"
                )
            if nonempty:
                from ..cc import native

                combined = native.adasum(nonempty)
                if combined is None:
                    from ..ops.adasum import adasum_numpy

                    combined = adasum_numpy(nonempty)
                out = np.asarray(combined[0])
            else:
                out = arrays[0]
            self.bcast_bytes(pack_array(out))
            return out
        return own_array(unpack_array(self.bcast_bytes(None)))

    def allgatherv(self, arr: np.ndarray, first_dims: List[int]) -> np.ndarray:
        if self.size == 1:
            return arr.copy()
        gathered = self.gather_bytes(pack_array(arr))
        if self.rank == 0:
            arrays = [unpack_array(b) for b in gathered]
            out = (
                np.concatenate(arrays, axis=0)
                if arrays[0].ndim
                else np.stack(arrays)
            )
            self.bcast_bytes(pack_array(out))
            return out
        return own_array(unpack_array(self.bcast_bytes(None)))

    def broadcast(self, arr: Optional[np.ndarray], root: int) -> np.ndarray:
        if self.size == 1:
            assert arr is not None
            return arr.copy()
        # Root contributes its payload through the gather; rank 0 relays.
        payload = pack_array(arr) if self.rank == root else b""
        gathered = self.gather_bytes(payload)
        if self.rank == 0:
            chosen = gathered[root]
            self.bcast_bytes(chosen)
            return own_array(unpack_array(chosen))
        return own_array(unpack_array(self.bcast_bytes(None)))

    def alltoallv(
        self, arr: np.ndarray, splits: List[int]
    ) -> Tuple[np.ndarray, List[int]]:
        if self.size == 1:
            return arr.copy(), list(splits)
        # Root-mediated exchange: gather (splits, data), redistribute.
        head = struct.pack(f"<{self.size}q", *splits)
        gathered = self.gather_bytes(
            [_LEN.pack(len(head)) + head] + pack_array(arr)
        )
        if self.rank == 0:
            all_splits, all_arrays = [], []
            for buf in gathered:
                view = memoryview(buf)
                (hn,) = _LEN.unpack(view[:8])
                all_splits.append(list(struct.unpack(
                    f"<{self.size}q", view[8 : 8 + hn])))
                all_arrays.append(unpack_array(view[8 + hn :]))
            src_offsets = [
                np.concatenate([[0], np.cumsum(s)]).astype(int) for s in all_splits
            ]
            per_dest: List[list] = []
            recv_splits_all: List[List[int]] = []
            for dest in range(self.size):
                parts = []
                rsplits = []
                for src in range(self.size):
                    offs = src_offsets[src]
                    parts.append(all_arrays[src][offs[dest] : offs[dest + 1]])
                    rsplits.append(all_splits[src][dest])
                out = np.concatenate(parts, axis=0)
                rs_head = struct.pack(f"<{self.size}q", *rsplits)
                per_dest.append(
                    [_LEN.pack(len(rs_head)) + rs_head] + pack_array(out))
                recv_splits_all.append(rsplits)
            self.scatter_bytes(per_dest)
            buf = join_buffers(per_dest[0])
        else:
            buf = self.scatter_bytes(None)
        view = memoryview(buf)
        (hn,) = _LEN.unpack(view[:8])
        recv_splits = list(struct.unpack(f"<{self.size}q", view[8 : 8 + hn]))
        return own_array(unpack_array(view[8 + hn :])), recv_splits

    def scatter_bytes(self, payloads: Optional[List[bytes]]) -> bytes:
        """Root sends payloads[r] to rank r. Default: r-indexed bcast
        fallback; transports override with true point-to-point."""
        raise NotImplementedError

    def allreduce_words(self, words: List[int], op: str) -> List[int]:
        """Bitwise and/or of 64-bit word vectors across ranks (the cache
        coordinator's control collective; ref: CrossRankBitwiseAnd/Or,
        mpi_controller.cc:88-106). Ranks may disagree on vector length
        for a cycle (cache sizes converge lazily): a missing word is 0,
        so 'and' zero-fills and 'or' extends to the longest vector."""
        payload = struct.pack(f"<{len(words)}Q", *words)
        gathered = self.gather_bytes(payload)
        if self.rank == 0:
            acc = list(words)
            for buf in gathered[1:]:
                other = struct.unpack(f"<{len(buf) // 8}Q", buf)
                if op == "or" and len(other) > len(acc):
                    acc.extend([0] * (len(other) - len(acc)))
                for i in range(min(len(acc), len(other))):
                    acc[i] = (acc[i] & other[i]) if op == "and" else (acc[i] | other[i])
                if op == "and" and len(other) < len(acc):
                    for i in range(len(other), len(acc)):
                        acc[i] = 0
            self.bcast_bytes(struct.pack(f"<{len(acc)}Q", *acc))
            return acc
        buf = self.bcast_bytes(None)
        return list(struct.unpack(f"<{len(buf) // 8}Q", buf))

    def barrier(self):
        self.gather_bytes(b"")
        self.bcast_bytes(b"" if self.rank == 0 else None)
