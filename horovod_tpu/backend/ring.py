"""Ring allreduce over point-to-point links — the bandwidth-optimal
CPU data plane (ref: GlooAllreduce's ring algorithm,
horovod/common/ops/gloo_operations.cc:119-166).

The star mixin funnels every byte through rank 0: O(N·bytes) on one
link. The ring moves each byte across each link ~2(N-1)/N times total —
flat per-rank bandwidth regardless of N. Reduce-scatter then allgather,
the classic two-phase schedule:

  phase 1 (N-1 steps): send chunk (r-s), recv chunk (r-s-1), reduce in.
  phase 2 (N-1 steps): send chunk (r-s+1), recv chunk (r-s) verbatim.

Selection: ring runs for elementwise ops when the payload exceeds
HOROVOD_RING_THRESHOLD bytes; smaller tensors stay on the star path
(latency-optimal). Sizes are coordinator-negotiated — every rank,
including joined ranks (which the engine hands full-shape zero
buffers), holds the same element count, so the decision is local yet
globally consistent. HOROVOD_CPU_OPERATIONS=star forces the old path.

Byte movement is zero-copy and pipelined: ring steps enqueue their
send chunk as memoryview segments on the transport's persistent peer
sender (send_async), receive the incoming chunk segment-by-segment
straight into a persistent scratch buffer (recv_into_from), and
reduce in place (np.add(tgt, seg, out=tgt)) — so the send of segment
k overlaps the recv+reduce of segment k-1 on the wire.
HOROVOD_RING_SEGMENT_BYTES sets the segment size (must match on every
rank, like the ring threshold); 0 restores the single-shot
frame-per-chunk schedule.
"""
from __future__ import annotations

import os
import struct
import time
from typing import Dict, List, Optional

import numpy as np

from ..cc import native
from ..common import tracing
from ..common.exceptions import HorovodInternalError
from ..common.types import ReduceOp
from ..utils import clock
from .base import (
    _NATIVE_OP,
    _reduce,
    channel_scope,
    current_channel,
    current_wire_codec,
    desync_message,
    take_first_hop_encoded,
    wire_codec_stats,
)
from .transport import COMPLETED as _COMPLETED
from .star import (
    StarCollectivesMixin,
    as_byte_view,
    own_array,
    pack_array,
    unpack_array,
)

# Measured crossover on loopback (examples/microbench_allreduce.py,
# np=3): star wins <=64KB (fewer rounds), parity ~1MB, ring 1.5x at
# 16MB. Real networks shift this left as N grows (star's rank-0 link
# saturates at O(N*bytes)); the env knob tunes it per deployment.
DEFAULT_RING_THRESHOLD = 262144  # bytes; smaller tensors stay on star

# Pipeline segment size for ring steps: large enough that per-frame
# overhead (header, queue handoff, telemetry) stays negligible, small
# enough that multi-MB chunks split into overlapped segments. Measured
# on loopback (np=4, 16MB): 2MB segments run at single-shot parity
# (the wire has no latency to hide there) while 256KB segments lose
# ~2x to frame overhead; real networks reward smaller segments.
DEFAULT_RING_SEGMENT_BYTES = 2 << 20

_RING_OPS = (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.MIN, ReduceOp.MAX,
             ReduceOp.PRODUCT)

# In-place reduction kernels for the ring's recv+reduce step: the
# allocating base._reduce is replaced by ufunc(tgt, seg, out=tgt)
# (AVERAGE lowers to SUM before the ring phases run).
_INPLACE_UFUNC = {
    ReduceOp.SUM: np.add,
    ReduceOp.AVERAGE: np.add,
    ReduceOp.MIN: np.minimum,
    ReduceOp.MAX: np.maximum,
    ReduceOp.PRODUCT: np.multiply,
}


def _reduce_into(op: ReduceOp, tgt: np.ndarray, incoming: np.ndarray,
                 hint_bytes: int = 0):
    """tgt = tgt ⊕ incoming without allocating.

    Native first (cc/core.cc hvd_reduce_into — ctypes releases the
    GIL, so segment k's reduce overlaps segment k+1's recv on the
    engine's worker threads), bitwise-identical ufunc fallback.
    ``hint_bytes`` carries the full-message size when ``tgt`` is a ring
    segment, so the native size floor judges the real working set."""
    name = _NATIVE_OP.get(op)
    if name is not None and native.reduce_into(name, tgt, incoming,
                                               hint_bytes=hint_bytes):
        return
    ufunc = _INPLACE_UFUNC.get(op)
    if ufunc is None:  # pragma: no cover - _RING_OPS gates dispatch
        tgt[:] = _reduce(op, [tgt, incoming])
    else:
        ufunc(tgt, incoming, out=tgt)


def _ring_codec(dtype):
    """The ring phases' active wire codec: fixed-width (the ring
    segments frames by ELEMENT offsets, so a codec with a per-tensor
    header cannot be cut mid-stream — variable-width codecs ship
    full-width here) and applicable to the payload dtype. Both inputs
    are collectively consistent: the codec id rides the Response wire
    message and the dtype is negotiated, so every rank takes the same
    branch and frame sizes always agree."""
    codec = current_wire_codec()
    if (codec is not None and codec.wire_itemsize
            and codec.applicable(dtype)):
        return codec
    return None


# _COMPLETED (imported above): the transport layer's shared no-op
# ticket for sends that never block — one class, so an identity or
# behavior change can never miss a copy.


# -- eligibility predicates -------------------------------------------
# Shared by the mixin's own dispatch AND the engine's OperationManager
# (Enabled() in the reference, operation_manager.cc:42-122) so the two
# can never drift. All inputs are coordinator-negotiated or
# collectively-agreed, so every rank reaches the same decision locally.

def ring_threshold() -> int:
    try:
        return int(os.environ.get("HOROVOD_RING_THRESHOLD",
                                  DEFAULT_RING_THRESHOLD))
    except ValueError:
        return DEFAULT_RING_THRESHOLD


def ring_segment_bytes() -> int:
    """Pipeline segment size for ring steps; 0 = single-shot (one frame
    per chunk, the pre-pipelining schedule). Read per call so tests and
    sweeps can flip it; must be identical on every rank — frame counts
    are derived from it, so a mismatch desyncs the ring (the launcher
    propagates HOROVOD_* env to all workers, like the threshold)."""
    try:
        v = int(os.environ.get("HOROVOD_RING_SEGMENT_BYTES",
                               DEFAULT_RING_SEGMENT_BYTES))
    except ValueError:
        return DEFAULT_RING_SEGMENT_BYTES
    return max(v, 0)


def ring_eligible(backend, nbytes: int, op: ReduceOp) -> bool:
    if os.environ.get("HOROVOD_CPU_OPERATIONS", "").lower() == "star":
        return False
    return (
        hasattr(backend, "send_to") and hasattr(backend, "recv_from")
        and op in _RING_OPS
        and nbytes >= ring_threshold()
    )


def arena_eligible(backend, nbytes: int, op: ReduceOp) -> bool:
    """Intra-host arena allreduce (backend/shm.py ShmArena): highest-
    priority plane, available only when the mesh backend established a
    WHOLE-WORLD co-located arena at init AND HOROVOD_TRANSPORT still
    routes to shared memory at call time. Arenas are host-scoped now —
    a multi-host mesh gets one per host for the leader schedule's
    intra-host legs (_host_arena) — so the whole-world plane gates on
    the arena's group covering every rank. Every input is collectively
    consistent: arena existence comes from rendezvous-agreed locality,
    the env knobs are launcher-propagated (benchmarks flip them between
    barriers), and nbytes/op are coordinator-negotiated."""
    aset = getattr(backend, "arena_set", None)
    if aset is None or getattr(aset, "size", 0) != backend.size:
        return False
    if os.environ.get("HOROVOD_CPU_OPERATIONS", "").lower() in (
            "star", "ring"):
        return False
    from ..utils import env as env_cfg

    if env_cfg.transport_mode() == "tcp":
        return False
    return op in _RING_OPS and nbytes >= ring_threshold()


def hierarchical_eligible(backend, nbytes: int, op: ReduceOp) -> bool:
    return (
        ring_eligible(backend, nbytes, op)
        and backend.hierarchical
        and hierarchy_valid(backend)
    )


def hierarchical_mode(backend) -> str:
    """Cross-host schedule for the two-level allreduce: "slice" (every
    local rank drives its own cross ring on its owned slice — parallel
    inter-host streams) or "leader" (one leader per host gathers the
    host-reduced vector over the intra-host transport and runs a single
    segmented inter-host ring — the NCCL-hierarchical shape, the right
    call when intra-host bytes are ~free over shared memory).
    HOROVOD_HIERARCHICAL_MODE=auto resolves through the backend's
    `leader_hier_ok` flag, which the ENGINE sets from a collectively
    agreed capability bit — a per-rank local answer here could deadlock
    the schedule."""
    from ..utils import env as env_cfg

    mode = env_cfg.hierarchical_mode()
    if mode != "auto":
        return mode
    return "leader" if getattr(backend, "leader_hier_ok", False) else "slice"


def ring_allgather_eligible(backend, nbytes: int) -> bool:
    """Ring allgather for large payloads (ref: GlooAllgather's ring,
    gloo_operations.cc:184): nbytes is the negotiated TOTAL output size,
    identical on every rank, so the decision is collectively
    consistent."""
    if os.environ.get("HOROVOD_CPU_OPERATIONS", "").lower() == "star":
        return False
    return (
        hasattr(backend, "send_to") and hasattr(backend, "recv_from")
        and nbytes >= ring_threshold()
    )


def hierarchical_allgather_eligible(backend, nbytes: int,
                                    ndim: int = 1) -> bool:
    """(ref: MPIHierarchicalAllgather, mpi_operations.cc:190 — node
    leaders gather locally, exchange across hosts, redistribute.) The
    `hier_allgather` flag is set by the engine from the collectively
    agreed topology validity + HOROVOD_HIERARCHICAL_ALLGATHER, so no
    rank can pick a different algorithm. 0-d (scalar) gathers use
    np.stack semantics the two-level path doesn't implement — ndim is
    negotiated, so the gate is collectively consistent."""
    return (
        ndim > 0
        and getattr(backend, "hier_allgather", False)
        and ring_allgather_eligible(backend, nbytes)
        and hierarchy_valid(backend)
    )


def hierarchical_capable(backend) -> bool:
    """Static capability (used for the engine's collective validity
    agreement at init): p2p transport + homogeneous topology. The
    per-call gate is hierarchical_eligible (adds toggle + size + op)."""
    return (
        hasattr(backend, "send_to") and hasattr(backend, "recv_from")
        and hierarchy_valid(backend)
    )


def hierarchy_valid(backend) -> bool:
    """Hierarchical needs a homogeneous contiguous host packing
    (rank == cross_rank*local_size + local_rank), like the
    reference's is_homogeneous gate (nccl_operations.cc:190-405)."""
    return (
        backend.local_size > 1
        and backend.cross_size > 1
        and backend.size == backend.local_size * backend.cross_size
        and backend.rank
        == backend.cross_rank * backend.local_size + backend.local_rank
    )


class RingCollectivesMixin(StarCollectivesMixin):
    """Adds a ring allreduce on transports providing p2p primitives
    `send_to(rank, bytes)` / `recv_from(rank) -> bytes`."""

    def _ring_threshold(self) -> int:
        return ring_threshold()

    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        if self.size == 1:
            return arr.copy()
        # No eligibility exchange is needed: allreduce sizes are
        # negotiated by the coordinator, so every rank (including joined
        # ranks, which the engine hands full-shape zero buffers) holds
        # the same element count and reaches the same ring/star decision
        # from its own arr.nbytes. The hierarchical toggle flips only at
        # autotune sync boundaries, collectively.
        if arena_eligible(self, arr.nbytes, op):
            return self._arena_allreduce(arr, op)
        if hierarchical_eligible(self, arr.nbytes, op):
            return self._hierarchical_allreduce(arr, op)
        if ring_eligible(self, arr.nbytes, op):
            return self._ring_allreduce(arr, op)
        return super().allreduce(arr, op)  # star: latency-optimal

    def _hierarchy_valid(self) -> bool:
        return hierarchy_valid(self)

    def allgatherv(self, arr: np.ndarray, first_dims: List[int]) -> np.ndarray:
        if self.size == 1:
            return super().allgatherv(arr, first_dims)
        # Total output bytes from the NEGOTIATED first_dims + validated
        # trailing shape — identical on every rank (a 0-row local block
        # still knows its trailing shape), so the ring/star decision is
        # collectively consistent.
        row = int(np.prod(arr.shape[1:])) if arr.ndim else 1
        total = sum(first_dims) * row * arr.dtype.itemsize
        if hierarchical_allgather_eligible(self, total, arr.ndim):
            return self._hierarchical_allgatherv(arr, first_dims)
        if ring_allgather_eligible(self, total):
            return self._ring_allgatherv(arr, first_dims)
        return super().allgatherv(arr, first_dims)

    def _hierarchical_allgatherv(self, arr: np.ndarray,
                                 first_dims: List[int]) -> np.ndarray:
        """Two-level allgather (ref: MPIHierarchicalAllgather,
        mpi_operations.cc:190 — leader gather into POSIX shm + cross
        allgather + redistribute): members send to their host leader,
        leaders ring-allgather whole host blocks across hosts (one
        crossing per byte on the slow links instead of local_size of
        them), then fan the full result back out locally."""
        L = self.local_size
        base = self.cross_rank * L
        leader = base

        if self.rank != leader:
            self.send_to(leader, pack_array(np.ascontiguousarray(arr)))
            blob = self.recv_from(leader)
            # 1-byte status prefix: the leader reports its own failure
            # instead of leaving members blocked in recv forever.
            if blob[:1] == b"E":
                raise RuntimeError(
                    "hierarchical allgather failed on host leader: "
                    + bytes(blob[1:]).decode(errors="replace")
                )
            # memoryview slice (bytearray slicing would copy the whole
            # payload); recv-into hands us an exclusively owned buffer,
            # so own_array is zero-copy on the TCP path and only copies
            # when the transport returned a shared/read-only blob.
            return own_array(unpack_array(memoryview(blob)[1:]))

        try:
            # Leader: gather this host's blocks in local-rank order
            # (global rank order, since packing is contiguous),
            # validating each against the negotiated dims like the flat
            # ring does per block.
            local_blocks = [np.ascontiguousarray(arr)]
            for i in range(1, L):
                blk = unpack_array(self.recv_from(base + i))
                if blk.shape[0] != first_dims[base + i]:
                    raise ValueError(
                        f"allgather block from rank {base + i} has first "
                        f"dim {blk.shape[0]}, negotiated "
                        f"{first_dims[base + i]}"
                    )
                local_blocks.append(blk)
            if arr.shape[0] != first_dims[self.rank]:
                raise ValueError(
                    f"allgather local block has first dim {arr.shape[0]},"
                    f" negotiated {first_dims[self.rank]}"
                )
            host_block = np.concatenate(local_blocks, axis=0)

            # Cross phase: ring allgather of host blocks among leaders.
            C = self.cross_size
            leaders = [h * L for h in range(C)]
            pos = self.cross_rank
            right, left = leaders[(pos + 1) % C], leaders[(pos - 1) % C]
            host_blocks: List[Optional[np.ndarray]] = [None] * C
            host_blocks[pos] = host_block
            payload = pack_array(host_block)
            for s in range(C - 1):
                payload = self._sendrecv(right, payload, left)
                src = (pos - s - 1) % C
                host_blocks[src] = unpack_array(payload)
                want = sum(first_dims[src * L:(src + 1) * L])
                if host_blocks[src].shape[0] != want:
                    raise ValueError(
                        f"allgather host block from host {src} has first "
                        f"dim {host_blocks[src].shape[0]}, negotiated "
                        f"{want}"
                    )
            out = np.concatenate(host_blocks, axis=0)
        except Exception as exc:
            # Unblock local members with an error frame before
            # propagating — they are parked in recv_from(leader).
            msg = b"E" + str(exc).encode()
            for i in range(1, L):
                try:
                    self.send_to(base + i, msg)
                except Exception:  # pragma: no cover - peer gone
                    pass
            raise

        # Local fan-out of the assembled result (scatter-gather: the
        # status byte, header and payload go out as separate buffers).
        blob = [b"O"] + pack_array(out)
        for i in range(1, L):
            self.send_to(base + i, blob)
        return out

    def _ring_allgatherv(self, arr: np.ndarray,
                         first_dims: List[int]) -> np.ndarray:
        """Ring allgather of variable-first-dim blocks: each step sends
        the most recently received block right and receives a new one
        from the left; after N-1 rotations every rank holds all blocks.
        Each byte crosses each link once — flat per-rank bandwidth vs
        star's O(N*bytes) on rank 0 (ref: gloo_operations.cc:184)."""
        n = self.size
        right = (self.rank + 1) % n
        left = (self.rank - 1) % n
        blocks: List[Optional[np.ndarray]] = [None] * n
        blocks[self.rank] = np.ascontiguousarray(arr)
        payload = pack_array(blocks[self.rank])
        for s in range(n - 1):
            payload = self._sendrecv(right, payload, left)
            src = (self.rank - s - 1) % n
            blocks[src] = unpack_array(payload)
            if arr.ndim and blocks[src].shape[0] != first_dims[src]:
                # Negotiated dims are the contract the threshold decision
                # was made from; a mismatch means a desynced peer.
                raise ValueError(
                    f"allgather block from rank {src} has first dim "
                    f"{blocks[src].shape[0]}, negotiated {first_dims[src]}"
                )
        if arr.ndim:
            out = np.concatenate(blocks, axis=0)
        else:
            out = np.stack(blocks)
        return out

    # -- p2p transport defaults ----------------------------------------
    # The TCP backend overrides both with true zero-copy/async versions;
    # these defaults keep any transport providing only send_to/recv_from
    # (the in-process ThreadedBackend) ring-capable.

    def send_async(self, peer: int, payload):
        """Default: synchronous send + completed ticket. Queue-backed
        transports never block on send_to, so this cannot deadlock the
        ring; socket transports override with a persistent per-peer
        sender worker."""
        self.send_to(peer, payload)
        return _COMPLETED

    def recv_into_from(self, peer: int, buf) -> int:
        """Default recv-into: one copy out of recv_from's frame. Socket
        transports override with a true recv_into."""
        data = self.recv_from(peer)
        view = as_byte_view(buf)
        if len(data) != len(view):
            raise HorovodInternalError(
                desync_message(len(data), len(view),
                               rank=self.rank, peer=peer))
        if data:
            view[:] = data
        return len(data)

    # ------------------------------------------------------------------
    def _sendrecv(self, dest: int, payload, src: int):
        """Simultaneous send+recv (MPI_Sendrecv shape): the send rides
        the transport's persistent sender worker (send_async) so a full
        socket buffer cannot deadlock the ring (every rank sends right
        while receiving left) — no helper thread per step."""
        ticket = self.send_async(dest, payload)
        data = self.recv_from(src)
        ticket.wait()
        return data

    # -- group-parameterized ring phases -------------------------------
    # `group` is the ordered list of global ranks forming the ring; this
    # rank's position is group.index(self.rank). With group == all ranks
    # this is the flat ring; the hierarchical path runs the same phases
    # over the local and cross subgroups (disjoint socket pairs, so
    # concurrent subgroup rings never interleave frames).

    @staticmethod
    def _bounds(total: int, n: int) -> List[int]:
        base = total // n
        return [i * base for i in range(n)] + [total]

    @staticmethod
    def _segment_bounds(nelems: int, seg_elems: int) -> List[int]:
        """Split one ring chunk into pipeline segments. A zero-size
        chunk is one empty segment — the (empty) frame still flows, so
        ring steps stay aligned even when total < group size. With
        seg_elems == 0 (single-shot) or >= nelems the chunk is one
        segment; a non-divisible size leaves the remainder in the last
        segment. Deterministic from (nelems, seg_elems) only, so the
        sender's and receiver's frame counts always agree."""
        if nelems <= 0 or seg_elems <= 0 or seg_elems >= nelems:
            return [0, max(nelems, 0)]
        return list(range(0, nelems, seg_elems)) + [nelems]

    @staticmethod
    def _segment_elems(itemsize: int) -> int:
        sb = ring_segment_bytes()
        if sb <= 0:
            return 0  # single-shot
        return max(1, sb // itemsize)

    # Persistent recv scratch for the reduce-scatter phase, keyed
    # (executor channel, dtype), grown to the largest double-buffer
    # seen. Channel executors run collectives concurrently, so each
    # channel owns its scratch; within a channel execution is serial
    # (per-channel FIFO), so no further locking is needed — dict
    # insertion itself is GIL-atomic and the keys are disjoint.
    _ring_scratch_store: Optional[Dict[tuple, np.ndarray]] = None

    def _ring_scratch(self, dtype: np.dtype, nelems: int) -> np.ndarray:
        store = self._ring_scratch_store
        if store is None:
            store = self._ring_scratch_store = {}
        from .base import current_channel

        key = (current_channel(), dtype.str)
        buf = store.get(key)
        if buf is None or buf.size < nelems:
            buf = store[key] = np.empty(max(nelems, 1), dtype)
        return buf

    def _count_segments(self, k: int):
        m = getattr(self, "_m_ring_segments", None)
        if m is not None:
            m.inc(k)

    def _ring_reduce_scatter(self, group: List[int], flat: np.ndarray,
                             op: ReduceOp, first_hop=None):
        """In-place, pipelined ring reduce-scatter over `group`. On
        return, the rank at position p holds group-chunk (p+1)%n fully
        reduced (ref: gloo ring reduce-scatter schedule,
        gloo_operations.cc:119-166).

        Each step queues its send chunk as HOROVOD_RING_SEGMENT_BYTES
        memoryview segments on the persistent peer sender — zero copies
        on the send side — while receiving the incoming chunk segment by
        segment into a double-buffered persistent scratch and reducing
        in place, so the wire write of segment k overlaps this rank's
        recv+reduce of segment k-1.

        ``first_hop`` (zero-redundancy first hop, docs/running.md "Wire
        compression") is the engine's already-encoded wire bytes for
        the WHOLE of ``flat``: step 0 — the only step that ships
        unmutated engine values — slices it instead of re-encoding.
        Callers pass it explicitly from their entry point's
        consume-once take; a nested ring on reduced values never sees
        it."""
        n = len(group)
        pos = group.index(self.rank)
        right, left = group[(pos + 1) % n], group[(pos - 1) % n]
        bounds = self._bounds(flat.size, n)
        seg = self._segment_elems(flat.itemsize)
        red = op if op != ReduceOp.AVERAGE else ReduceOp.SUM
        max_chunk = max(bounds[i + 1] - bounds[i] for i in range(n))
        seg_cap = min(seg, max_chunk) if seg else max_chunk
        seg_cap = max(seg_cap, 1)
        # Wire compression (docs/running.md "Wire compression"): with
        # an active fixed-width codec each step encodes its send chunk
        # (segments are memoryview slices of the encoded bytes),
        # receives the incoming chunk's encoded segments into a byte
        # scratch, and decompresses-then-reduces per segment — the
        # accumulation stays full-width, only the wire narrows.
        # Segment bounds stay in ELEMENT space on both sides, so the
        # sender's and receiver's frame byte counts agree by
        # construction ((b-a) * wire_itemsize).
        codec = _ring_codec(flat.dtype)
        stats = wire_codec_stats() if codec is not None else None
        wis = codec.wire_itemsize if codec is not None else 0
        # Two alternating scratch halves: segment k's recv target never
        # aliases segment k-1's decode-reduce source — the invariant
        # the overlapped path below depends on (its decode stage may
        # still be reading half k-1 while half k receives).
        if codec is None:
            first_hop = None
            scratch = self._ring_scratch(flat.dtype, 2 * seg_cap)
        else:
            scratch = self._ring_scratch(
                np.dtype(np.uint8), 2 * seg_cap * wis)

        def chunk(i):
            i %= n
            return flat[bounds[i]: bounds[i + 1]]

        # Tracing-plane segment spans (docs/tracing.md): recv + reduce
        # per pipeline segment, send completion per step. The wire time
        # of the overlapped sends shows up as tcp.sender_dwell spans on
        # the persistent sender's lane (tagged with this thread's trace
        # scope, captured at enqueue).
        tr = self.tracer
        # Codec/wire overlap (HOROVOD_RING_CODEC_OVERLAP, default on):
        # one bounded single-worker stage per direction — the encode
        # stage encodes segment k+1 and hands it to the transport while
        # segment k is on the wire; the decode stage decodes-reduces
        # segment k-1 while this thread receives k. FIFO holds because
        # every send of the phase funnels through the one encode worker
        # in submission order; results are bitwise identical to the
        # serial path because fixed-width encode is elementwise (a
        # segment's encode == the same slice of the chunk's encode) and
        # decode-reduce targets are disjoint per segment. Purely local:
        # each rank may flip it independently. Single-segment chunks
        # (small ops, or single-shot mode) have nothing to pipeline —
        # they stay serial rather than paying 2 worker threads per
        # phase on the latency lane.
        from ..utils import env as env_cfg

        overlap = (codec is not None and 0 < seg < max_chunk
                   and env_cfg.ring_codec_overlap())
        enc_stage = dec_stage = None
        enc_secs: List[float] = []
        dec_secs: List[float] = []
        ch = current_channel()
        if overlap:
            from ..common.compression import PipelineStage

            enc_stage = PipelineStage(f"ring-enc-c{ch}")
            dec_stage = PipelineStage(f"ring-dec-c{ch}")
        try:
            for s in range(n - 1):
                send_c = chunk(pos - s)
                tgt = chunk(pos - s - 1)
                sb = self._segment_bounds(send_c.size, seg)
                send_futs = tickets = None
                if codec is None:
                    tickets = [self.send_async(right, send_c[a:b])
                               for a, b in zip(sb, sb[1:])]
                else:
                    send_base = bounds[(pos - s) % n]
                    reuse = first_hop if s == 0 else None
                    if overlap:
                        send_futs = []
                        # Channel AND trace scope are captured on THIS
                        # thread and re-entered in the worker, so the
                        # sender-dwell spans stay attributed exactly as
                        # in the serial path.
                        tid = tracing.current_trace()
                        for a, b in zip(sb, sb[1:]):
                            def enc_job(a=a, b=b, send_c=send_c,
                                        send_base=send_base, reuse=reuse,
                                        tid=tid):
                                if reuse is not None:
                                    ev = reuse[(send_base + a) * wis:
                                               (send_base + b) * wis]
                                else:
                                    t0 = time.perf_counter()
                                    ev = codec.encode(send_c[a:b])
                                    enc_secs.append(
                                        time.perf_counter() - t0)
                                if stats is not None:
                                    stats.saved(
                                        codec.name,
                                        (b - a) * flat.itemsize
                                        - ev.nbytes)
                                with channel_scope(ch), \
                                        tracing.trace_scope(tid):
                                    return self.send_async(right, ev)

                            send_futs.append(enc_stage.submit(enc_job))
                    else:
                        if reuse is not None:
                            enc = reuse[send_base * wis:
                                        (send_base + send_c.size) * wis]
                            if stats is not None:
                                stats.saved(codec.name,
                                            send_c.nbytes - enc.nbytes)
                        else:
                            t0 = time.perf_counter()
                            enc = codec.encode(send_c)
                            if stats is not None:
                                stats.observe(
                                    "encode", time.perf_counter() - t0)
                                stats.saved(codec.name,
                                            send_c.nbytes - enc.nbytes)
                        # `enc` stays referenced until the tickets
                        # complete below, so the queued memoryview
                        # slices never dangle.
                        tickets = [
                            self.send_async(right, enc[a * wis:b * wis])
                            for a, b in zip(sb, sb[1:])]
                self._count_segments(len(sb) - 1)
                rb = self._segment_bounds(tgt.size, seg)
                if overlap:
                    dec_futs: List = []
                    for k, (a, b) in enumerate(zip(rb, rb[1:])):
                        # Reusing half k%2 requires its last reader
                        # (decode job k-2) to be done.
                        if k >= 2 and dec_futs[k - 2] is not None:
                            dec_futs[k - 2].result()
                        half = scratch[(k % 2) * seg_cap * wis:][
                            : (b - a) * wis]
                        with tr.span("ring.recv", cat="xfer",
                                     args={"bytes": int(half.nbytes)}):
                            self.recv_into_from(left, half)
                        if b > a:
                            # The trace id is captured on THIS thread
                            # (the worker has no trace scope), like the
                            # sender-dwell spans — so the per-segment
                            # ring.reduce spans docs/tracing.md
                            # documents survive the overlap mode, on
                            # the worker's tid sub-lane.
                            tid = tracing.current_trace()

                            def dec_job(a=a, b=b, half=half, tgt=tgt,
                                        tid=tid):
                                t_ns = clock.mono_ns()
                                t0 = time.perf_counter()
                                dec = codec.decode(half, b - a)
                                dec_secs.append(time.perf_counter() - t0)
                                _reduce_into(red, tgt[a:b], dec,
                                             hint_bytes=tgt.nbytes)
                                if tr.enabled:
                                    tr.emit("ring.reduce", "compute",
                                            t_ns, clock.mono_ns() - t_ns,
                                            trace_id=tid)

                            dec_futs.append(dec_stage.submit(dec_job))
                        else:
                            dec_futs.append(None)
                    # tgt must be fully reduced before the next step
                    # may encode it as its send chunk.
                    for f in dec_futs:
                        if f is not None:
                            f.result()
                else:
                    dec_s = 0.0
                    for k, (a, b) in enumerate(zip(rb, rb[1:])):
                        if codec is None:
                            half = scratch[(k % 2) * seg_cap:][: b - a]
                        else:
                            half = scratch[(k % 2) * seg_cap * wis:][
                                : (b - a) * wis]
                        with tr.span("ring.recv", cat="xfer",
                                     args={"bytes": int(half.nbytes)}):
                            self.recv_into_from(left, half)
                        if b > a:
                            with tr.span("ring.reduce", cat="compute"):
                                if codec is None:
                                    _reduce_into(red, tgt[a:b], half,
                                                 hint_bytes=tgt.nbytes)
                                else:
                                    t0 = time.perf_counter()
                                    dec = codec.decode(half, b - a)
                                    dec_s += time.perf_counter() - t0
                                    _reduce_into(red, tgt[a:b], dec,
                                                 hint_bytes=tgt.nbytes)
                    if stats is not None and dec_s:
                        stats.observe("decode", dec_s)
                with tr.span("ring.send_wait", cat="xfer",
                             args={"segments": len(sb) - 1}):
                    if send_futs is not None:
                        for f in send_futs:
                            f.result().wait()
                    else:
                        for t in tickets:
                            t.wait()
                if overlap and stats is not None:
                    # One aggregated observation per step per phase —
                    # the same count accounting as the serial path, so
                    # horovod_compression_seconds{phase=} counts stay
                    # mode-independent (the first-hop test relies on
                    # per-op encode counts).
                    if enc_secs:
                        stats.observe("encode", sum(enc_secs))
                        del enc_secs[:]
                    if dec_secs:
                        stats.observe("decode", sum(dec_secs))
                        del dec_secs[:]
        finally:
            if enc_stage is not None:
                enc_stage.stop()
            if dec_stage is not None:
                dec_stage.stop()

    def _ring_allgather_chunks(self, group: List[int], flat: np.ndarray,
                               on_chunk=None):
        """Ring allgather of the per-position chunks: position p starts
        owning chunk (p+1)%n; after n-1 rotations every rank holds all.
        Pipelined like the reduce-scatter, except incoming segments land
        straight in their final chunk slice — no scratch, no copy (a
        small decode scratch returns when a wire codec is active).

        ``on_chunk(lo_elem, hi_elem)`` fires the moment a SEGMENT of
        ``flat`` is FINAL on this rank — the owned chunk's segments up
        front, each received segment as it lands (after its decode
        under a codec) — chunks in the deterministic order (pos+1),
        (pos), (pos-1), ... and segments in order within each chunk,
        exactly the ranges _segment_bounds yields, so any observer can
        replay the identical range sequence from the schedule alone.
        The leader-mode hierarchical allreduce hooks its intra-host
        bcast here, so the fan-out of a segment overlaps the wire time
        of the next (docs/running.md "Transports")."""
        n = len(group)
        pos = group.index(self.rank)
        right, left = group[(pos + 1) % n], group[(pos - 1) % n]
        bounds = self._bounds(flat.size, n)
        seg = self._segment_elems(flat.itemsize)
        codec = _ring_codec(flat.dtype)
        stats = wire_codec_stats() if codec is not None else None
        wis = codec.wire_itemsize if codec is not None else 0

        def chunk(i):
            i %= n
            return flat[bounds[i]: bounds[i + 1]]

        scratch = None
        seg_cap = 0
        own_enc = None
        if codec is not None:
            # Project the chunk this rank OWNS (fully reduced in the
            # scatter phase) onto the codec grid before the first send:
            # receivers hold decode(encode(chunk)), so the owner must
            # hold the same value or ranks finish with different
            # results. Later rotations forward already-projected
            # values, whose re-encode is lossless for the fixed-width
            # codecs — so one projection at the source is enough. The
            # projection's encode does double duty: step 0 sends the
            # SAME chunk, so it ships these bytes directly instead of
            # re-encoding them (zero-redundancy first hop — the wire
            # carries exactly decode's input, bitwise).
            own = chunk(pos + 1)
            if own.size:
                t0 = time.perf_counter()
                own_enc = codec.encode(own)
                if stats is not None:
                    stats.observe("encode", time.perf_counter() - t0)
                own[:] = codec.decode(own_enc, own.size)
            max_chunk = max(bounds[i + 1] - bounds[i] for i in range(n))
            seg_cap = min(seg, max_chunk) if seg else max_chunk
            seg_cap = max(seg_cap, 1)
            scratch = self._ring_scratch(
                np.dtype(np.uint8), 2 * seg_cap * wis)

        tr = self.tracer
        if on_chunk is not None:
            # The owned chunk is final before the first rotation.
            i = (pos + 1) % n
            lo = bounds[i]
            sbo = self._segment_bounds(bounds[i + 1] - lo, seg)
            for a, b in zip(sbo, sbo[1:]):
                on_chunk(lo + a, lo + b)
        # Same codec/wire overlap stages as the reduce-scatter (see
        # there); the decode stage writes disjoint final slices, so no
        # reduce ordering is involved at all. Single-segment chunks
        # stay serial (nothing to pipeline; max_chunk is always set
        # when codec is, and the `and` short-circuits otherwise).
        from ..utils import env as env_cfg

        overlap = (codec is not None and 0 < seg < max_chunk
                   and env_cfg.ring_codec_overlap())
        enc_stage = dec_stage = None
        enc_secs: List[float] = []
        dec_secs: List[float] = []
        ch = current_channel()
        if overlap:
            from ..common.compression import PipelineStage

            enc_stage = PipelineStage(f"ring-enc-c{ch}")
            dec_stage = PipelineStage(f"ring-dec-c{ch}")
        try:
            for s in range(n - 1):
                send_c = chunk(pos - s + 1)
                tgt = chunk(pos - s)
                sb = self._segment_bounds(send_c.size, seg)
                send_futs = tickets = None
                if codec is None:
                    tickets = [self.send_async(right, send_c[a:b])
                               for a, b in zip(sb, sb[1:])]
                else:
                    # own_enc covers exactly the step-0 send chunk.
                    reuse = own_enc if s == 0 else None
                    if overlap:
                        send_futs = []
                        tid = tracing.current_trace()
                        for a, b in zip(sb, sb[1:]):
                            def enc_job(a=a, b=b, send_c=send_c,
                                        reuse=reuse, tid=tid):
                                if reuse is not None:
                                    ev = reuse[a * wis:b * wis]
                                else:
                                    t0 = time.perf_counter()
                                    ev = codec.encode(send_c[a:b])
                                    enc_secs.append(
                                        time.perf_counter() - t0)
                                if stats is not None:
                                    stats.saved(
                                        codec.name,
                                        (b - a) * flat.itemsize
                                        - ev.nbytes)
                                with channel_scope(ch), \
                                        tracing.trace_scope(tid):
                                    return self.send_async(right, ev)

                            send_futs.append(enc_stage.submit(enc_job))
                    else:
                        if reuse is not None:
                            enc = reuse
                            if stats is not None:
                                stats.saved(codec.name,
                                            send_c.nbytes - enc.nbytes)
                        else:
                            t0 = time.perf_counter()
                            enc = codec.encode(send_c)
                            if stats is not None:
                                stats.observe(
                                    "encode", time.perf_counter() - t0)
                                stats.saved(codec.name,
                                            send_c.nbytes - enc.nbytes)
                        tickets = [
                            self.send_async(right, enc[a * wis:b * wis])
                            for a, b in zip(sb, sb[1:])]
                self._count_segments(len(sb) - 1)
                rb = self._segment_bounds(tgt.size, seg)
                tgt_lo = bounds[(pos - s) % n]
                if codec is None:
                    for k, (a, b) in enumerate(zip(rb, rb[1:])):
                        with tr.span("ring.recv", cat="xfer",
                                     args={"bytes":
                                           (b - a) * flat.itemsize}):
                            self.recv_into_from(left, tgt[a:b])
                        if on_chunk is not None:
                            on_chunk(tgt_lo + a, tgt_lo + b)
                elif overlap:
                    dec_futs: List = []
                    for k, (a, b) in enumerate(zip(rb, rb[1:])):
                        if k >= 2 and dec_futs[k - 2] is not None:
                            dec_futs[k - 2].result()
                        half = scratch[(k % 2) * seg_cap * wis:][
                            : (b - a) * wis]
                        with tr.span("ring.recv", cat="xfer",
                                     args={"bytes": int(half.nbytes)}):
                            self.recv_into_from(left, half)
                        if b > a:
                            def dec_job(a=a, b=b, half=half, tgt=tgt):
                                t0 = time.perf_counter()
                                tgt[a:b] = codec.decode(half, b - a)
                                dec_secs.append(time.perf_counter() - t0)

                            dec_futs.append(dec_stage.submit(dec_job))
                        else:
                            dec_futs.append(None)
                    # tgt is next step's send chunk: decoded fully
                    # before the loop advances.
                    for f in dec_futs:
                        if f is not None:
                            f.result()
                    if on_chunk is not None:
                        # Segments fired in order, post-drain (the
                        # decode stage is FIFO, so they are final).
                        for a, b in zip(rb, rb[1:]):
                            on_chunk(tgt_lo + a, tgt_lo + b)
                else:
                    dec_s = 0.0
                    for k, (a, b) in enumerate(zip(rb, rb[1:])):
                        half = scratch[(k % 2) * seg_cap * wis:][
                            : (b - a) * wis]
                        with tr.span("ring.recv", cat="xfer",
                                     args={"bytes": int(half.nbytes)}):
                            self.recv_into_from(left, half)
                        if b > a:
                            t0 = time.perf_counter()
                            tgt[a:b] = codec.decode(half, b - a)
                            dec_s += time.perf_counter() - t0
                        if on_chunk is not None:
                            on_chunk(tgt_lo + a, tgt_lo + b)
                    if stats is not None and dec_s:
                        stats.observe("decode", dec_s)
                with tr.span("ring.send_wait", cat="xfer",
                             args={"segments": len(sb) - 1}):
                    if send_futs is not None:
                        for f in send_futs:
                            f.result().wait()
                    else:
                        for t in tickets:
                            t.wait()
                if overlap and stats is not None:
                    if enc_secs:
                        stats.observe("encode", sum(enc_secs))
                        del enc_secs[:]
                    if dec_secs:
                        stats.observe("decode", sum(dec_secs))
                        del dec_secs[:]
        finally:
            if enc_stage is not None:
                enc_stage.stop()
            if dec_stage is not None:
                dec_stage.stop()

    def _ring_allreduce_group(self, group: List[int], flat: np.ndarray,
                              op: ReduceOp, first_hop=None,
                              on_chunk=None):
        self._ring_reduce_scatter(group, flat, op, first_hop=first_hop)
        self._ring_allgather_chunks(group, flat, on_chunk=on_chunk)

    def _take_first_hop(self, flat: np.ndarray):
        """Entry-point consume of the engine's first-hop encode (see
        base.take_first_hop_encoded): taken ONCE per op, while ``flat``
        still holds the engine's grid-projected values, and threaded
        down explicitly — deeper phases operate on reduced values and
        must never reach for the thread-local themselves."""
        codec = _ring_codec(flat.dtype)
        if codec is None:
            return None
        return take_first_hop_encoded(flat.size * codec.wire_itemsize)

    def _ring_allreduce(self, arr: np.ndarray, op: ReduceOp,
                        owned: bool = False) -> np.ndarray:
        """`owned=True` (engine-set for freshly packed/scaled fusion
        buffers) lets the ring reduce in place without the defensive
        copy of the input; a caller-owned tensor must never be
        mutated."""
        flat = np.ascontiguousarray(arr).reshape(-1)
        if not owned and np.shares_memory(flat, arr):
            flat = flat.copy()
        self._ring_allreduce_group(list(range(self.size)), flat, op,
                                   first_hop=self._take_first_hop(flat))
        if op == ReduceOp.AVERAGE:
            flat = (flat / self.size).astype(arr.dtype)
        return flat.reshape(arr.shape)

    def _arena_allreduce(self, arr: np.ndarray, op: ReduceOp,
                         owned: bool = False) -> np.ndarray:
        """Whole-world intra-host allreduce through the shared-memory
        arena: deposit once, reduce an equal subslice straight from
        every peer's slot, copy the shared result out. The arena is
        keyed by the calling thread's executor channel — cross-rank
        ordering is per-channel FIFO (PR 4's invariant), so barrier
        generations advance in lockstep on every rank."""
        from .base import current_channel

        flat = np.ascontiguousarray(arr).reshape(-1)
        # No defensive input copy: unlike the in-place ring, the arena
        # reads the input and writes a separate output, so a caller-
        # owned tensor is never mutated — the ring path's biggest
        # per-op memcpy simply disappears here.
        out = flat if (owned or not np.shares_memory(flat, arr)) \
            else np.empty_like(flat)
        red = op if op != ReduceOp.AVERAGE else ReduceOp.SUM
        ufunc = _INPLACE_UFUNC[red]
        arena = self.arena_set.get(current_channel())
        # Wire compression: the arena deposits ENCODED slots (halving
        # the aggregate private->shared memcpy that bounds this box's
        # shm throughput) and each reducer decodes peers' subslices on
        # the fly; the shared result stays full-width, so the copy-out
        # and the returned values are fp32 (docs/running.md "Wire
        # compression"). Fixed-width codecs only, like the ring. The
        # deposit is the op's FIRST hop, so the engine's first-hop
        # encode is sliced straight into the slots — zero re-encode.
        codec = _ring_codec(flat.dtype)
        tr = self.tracer
        try:
            with tr.span("shm.arena_allreduce", cat="xfer",
                         args={"bytes": int(flat.nbytes)}):
                arena.allreduce_into(
                    flat, lambda dst, src: ufunc(dst, src, out=dst),
                    out=out, codec=codec,
                    stats=wire_codec_stats() if codec is not None
                    else None,
                    first_hop=self._take_first_hop(flat),
                    op_name=_NATIVE_OP.get(red))
        except (OSError, TimeoutError) as exc:
            from ..common.exceptions import TransportError

            reason = None
            get_dead = getattr(self, "_arena_dead_reason", None)
            if get_dead is not None:
                reason = get_dead()
            raise TransportError(
                reason or (f"rank {self.rank}: shm arena allreduce "
                           f"failed: {exc}"),
                reporter=self.rank, root_cause=reason) from exc
        if op == ReduceOp.AVERAGE:
            out = (out / self.size).astype(arr.dtype)
        return out.reshape(arr.shape)

    def _hierarchical_allreduce(self, arr: np.ndarray, op: ReduceOp,
                                owned: bool = False) -> np.ndarray:
        """Two-level allreduce; the cross-host schedule is picked by
        `hierarchical_mode` (slice-parallel or leader-based — see its
        docstring). Both start with an intra-host ring reduce-scatter,
        which rides the shm overlay wherever peers are co-located."""
        L = self.local_size
        base = self.cross_rank * L
        local_group = list(range(base, base + L))
        flat = np.ascontiguousarray(arr).reshape(-1)
        # The arena-legged leader schedule reads the input and writes a
        # separate output (members deposit FROM the input and receive
        # the bcast INTO the output), so — like the whole-world arena —
        # it needs no defensive copy of a caller-owned tensor; the ring
        # schedules reduce in place and still do.
        aset = (self._host_arena(local_group)
                if hierarchical_mode(self) == "leader" else None)
        if aset is None and not owned and np.shares_memory(flat, arr):
            flat = flat.copy()
        # Consume-once entry-point take: `flat` still holds the
        # engine's grid-projected values here; whichever schedule runs,
        # only its FIRST intra-host hop may ship these bytes.
        first_hop = self._take_first_hop(flat)

        if aset is not None:
            out = flat if (owned or not np.shares_memory(flat, arr)) \
                else np.empty_like(flat)
            self._hierarchical_leader_arena(aset, local_group, flat,
                                            out, op)
        elif hierarchical_mode(self) == "leader":
            out = flat
            self._hierarchical_leader(local_group, flat, op,
                                      first_hop=first_hop)
        else:
            out = flat
            self._hierarchical_slice(local_group, flat, op,
                                     first_hop=first_hop)

        if op == ReduceOp.AVERAGE:
            out = (out / self.size).astype(arr.dtype)
        return out.reshape(arr.shape)

    def _hierarchical_slice(self, local_group: List[int], flat: np.ndarray,
                            op: ReduceOp, first_hop=None):
        """Local reduce-scatter -> cross allreduce per slice -> local
        allgather (ref: NCCLHierarchicalAllreduce's ReduceScatter /
        cross-MPI_Allreduce / AllGather shape, nccl_operations.cc:190-405;
        here the cross phase rides the DCN-equivalent links while each
        local ring stays on its host's links)."""
        L = self.local_size
        cross_group = [self.local_rank + h * L for h in range(self.cross_size)]

        # Phase A: local reduce-scatter; position local_rank ends owning
        # local chunk (local_rank+1)%L, reduced across the host. The
        # only hop that ships unmutated engine values — first_hop goes
        # here and nowhere else.
        self._ring_reduce_scatter(local_group, flat, op,
                                  first_hop=first_hop)

        # Phase B: cross-host ring allreduce on the owned slice only —
        # every local rank drives its own cross ring concurrently, so
        # cross bandwidth scales with local_size like the reference's
        # parallel per-local-rank MPI_Allreduce slices.
        bounds = self._bounds(flat.size, L)
        own = (self.local_rank + 1) % L
        own_slice = flat[bounds[own]: bounds[own + 1]]
        if own_slice.size:
            self._ring_allreduce_group(cross_group, own_slice, op)

        # Phase C: local allgather of the fully reduced chunks.
        self._ring_allgather_chunks(local_group, flat)

    def _host_arena(self, local_group: List[int]):
        """The host-scoped arena covering exactly `local_group`, when
        the collectively agreed capability bit (engine-set
        arena_hier_ok — a host that can't map its arena degrades EVERY
        host to per-pair rings consistently) allows it AND the per-call
        knobs still route intra-host data to shared memory
        (HOROVOD_HIER_ARENA / HOROVOD_TRANSPORT, read per call like the
        route: the launcher propagates env to every rank, so the
        per-call answer is collectively consistent and paired
        benchmarks may flip the legs between barrier-separated
        rounds)."""
        if not getattr(self, "arena_hier_ok", False):
            return None
        aset = getattr(self, "arena_set", None)
        if aset is None or list(getattr(aset, "group", ())) != local_group:
            return None
        from ..utils import env as env_cfg

        if (env_cfg.hier_arena_setting() == "off"
                or env_cfg.transport_mode() == "tcp"):
            return None
        return aset

    def _hierarchical_leader(self, local_group: List[int], flat: np.ndarray,
                             op: ReduceOp, first_hop=None):
        """Leader-based two-level schedule: intra-host ring
        reduce-scatter -> gather the reduced slices to the host leader
        -> ONE segmented inter-host ring between leaders -> intra-host
        bcast of the result. The right shape when intra-host bytes are
        ~free (shared memory) and inter-host links favor one stream per
        host pair; gather/bcast legs use send_async so the leader's
        per-peer senders stream to all members concurrently. When the
        host arena covers the local group, _hierarchical_allreduce
        dispatches to _hierarchical_leader_arena instead — both
        intra-host legs ride the arena there."""
        L = self.local_size
        base = local_group[0]
        leader = base
        bounds = self._bounds(flat.size, L)

        def owned_slice(local_rank: int) -> np.ndarray:
            own = (local_rank + 1) % L
            return flat[bounds[own]: bounds[own + 1]]

        # Phase A: intra-host reduce-scatter (over shm when co-located).
        self._ring_reduce_scatter(local_group, flat, op,
                                  first_hop=first_hop)

        tr = self.tracer
        if self.rank == leader:
            # Phase B1: collect every member's reduced slice — the
            # leader then holds the full host-reduced vector.
            with tr.span("hier.leader_gather", cat="xfer",
                         args={"bytes": int(flat.nbytes)}):
                for i in range(1, L):
                    seg = owned_slice(i)
                    if seg.size:
                        self.recv_into_from(base + i, seg)
            # Phase B2: segmented inter-host ring between leaders only.
            leaders = [h * L for h in range(self.cross_size)]
            self._ring_allreduce_group(leaders, flat, op)
            # Phase C: intra-host bcast of the finished vector.
            with tr.span("hier.leader_bcast", cat="xfer",
                         args={"bytes": int(flat.nbytes)}):
                tickets = [self.send_async(base + i, flat)
                           for i in range(1, L)]
                for t in tickets:
                    t.wait()
        else:
            with tr.span("hier.member_exchange", cat="xfer",
                         args={"bytes": int(flat.nbytes)}):
                seg = owned_slice(self.local_rank)
                if seg.size:
                    self.send_to(leader, seg)
                self.recv_into_from(leader, flat)

    def _hierarchical_leader_arena(self, aset, local_group: List[int],
                                   flat: np.ndarray, out: np.ndarray,
                                   op: ReduceOp):
        """Arena-legged leader schedule (docs/running.md "Transports"):
        one FUSED arena reduce replaces the intra-host ring
        reduce-scatter + gather-to-leader pair — every member deposits
        its vector once into its slot, all members reduce equal
        subslices from every slot in parallel, and the leader copies
        the host-reduced vector out (2 data movements + 2 waited
        barriers per chunk, vs 2(L-1) scheduled pairwise ring steps
        plus a separate gather leg). The leaders then run the same
        segmented inter-host ring, and one arena bcast replaces the
        per-pair send_async fan-out. The arena is keyed by the calling
        thread's executor channel like the whole-world plane, so
        barrier generations advance in lockstep on every member.

        Intra-host legs are full-width by design: those bytes never
        meet a wire, and PR 11 measured codec passes on shm memcpy as
        pure cost. The engine's first-hop encode is therefore NOT
        consumed here — and must not leak into the inter-host ring,
        which carries host-REDUCED values; the entry point's
        consume-once take already retired it. Bitwise agreement holds:
        leaders finish the inter-host ring bitwise identical (the
        allgather grid projection), and the bcast is a memcpy of the
        leader's bytes.

        ``flat`` is only READ (member deposits, the root's own
        contribution); the result lands in ``out`` — which is why the
        caller can skip the ring path's defensive input copy."""
        L = len(local_group)
        leader = local_group[0]
        red = op if op != ReduceOp.AVERAGE else ReduceOp.SUM
        ufunc = _INPLACE_UFUNC[red]
        arena = aset.get(current_channel())
        tr = self.tracer
        try:
            with tr.span("hier.arena_reduce", cat="xfer",
                         args={"bytes": int(flat.nbytes)}):
                arena.reduce_to_member(
                    flat, lambda dst, src: ufunc(dst, src, out=dst),
                    root=0, out=out, op_name=_NATIVE_OP.get(red))
            # Overlapped bcast: the leader deposits each element range
            # into the arena THE MOMENT the inter-host allgather
            # finishes it (on_chunk fires per ring SEGMENT), so the
            # intra-host fan-out hides behind inter-host wire time
            # instead of following it. Members replay the identical
            # range sequence from the schedule alone — chunks in ring
            # order (pos+1), (pos), (pos-1), ... of the cross bounds,
            # segments in _segment_bounds order within each — so no
            # range metadata travels and the session's sub-chunk
            # streams agree range by range.
            session = arena.bcast_session(out, root=0)
            if self.rank == leader:
                leaders = [h * L for h in range(self.cross_size)]
                with tr.span("hier.arena_inter_bcast", cat="xfer",
                             args={"bytes": int(flat.nbytes)}):
                    self._ring_allreduce_group(
                        leaders, out, op, on_chunk=session.deposit)
                    session.close()
            else:
                n_c = self.cross_size
                p = self.cross_rank
                cb = self._bounds(out.size, n_c)
                seg = self._segment_elems(out.itemsize)
                with tr.span("hier.arena_bcast", cat="xfer",
                             args={"bytes": int(flat.nbytes)}):
                    order = [(p + 1) % n_c] + [
                        (p - s) % n_c for s in range(n_c - 1)]
                    for i in order:
                        lo = cb[i]
                        sbo = self._segment_bounds(cb[i + 1] - lo, seg)
                        for a, b in zip(sbo, sbo[1:]):
                            session.copy(lo + a, lo + b)
                    session.close()
        except (OSError, TimeoutError) as exc:
            from ..common.exceptions import TransportError

            reason = None
            get_dead = getattr(self, "_arena_dead_reason", None)
            if get_dead is not None:
                reason = get_dead()
            raise TransportError(
                reason or (f"rank {self.rank}: shm arena hierarchical "
                           f"allreduce failed: {exc}"),
                reporter=self.rank, root_cause=reason) from exc
        m = getattr(self, "_m_hier_arena", None)
        if m is not None:
            m.inc()
