"""Ring allreduce over point-to-point links — the bandwidth-optimal
CPU data plane (ref: GlooAllreduce's ring algorithm,
horovod/common/ops/gloo_operations.cc:119-166).

The star mixin funnels every byte through rank 0: O(N·bytes) on one
link. The ring moves each byte across each link ~2(N-1)/N times total —
flat per-rank bandwidth regardless of N. Reduce-scatter then allgather,
the classic two-phase schedule:

  phase 1 (N-1 steps): send chunk (r-s), recv chunk (r-s-1), reduce in.
  phase 2 (N-1 steps): send chunk (r-s+1), recv chunk (r-s) verbatim.

Selection: ring runs for elementwise ops when the payload exceeds
HOROVOD_RING_THRESHOLD bytes; smaller tensors stay on the star path
(latency-optimal). Sizes are coordinator-negotiated — every rank,
including joined ranks (which the engine hands full-shape zero
buffers), holds the same element count, so the decision is local yet
globally consistent. HOROVOD_CPU_OPERATIONS=star forces the old path.
"""
from __future__ import annotations

import os
import struct
import threading
from typing import List, Optional

import numpy as np

from ..common.types import ReduceOp
from .base import _reduce
from .star import StarCollectivesMixin, pack_array, unpack_array

# Measured crossover on loopback (examples/microbench_allreduce.py,
# np=3): star wins <=64KB (fewer rounds), parity ~1MB, ring 1.5x at
# 16MB. Real networks shift this left as N grows (star's rank-0 link
# saturates at O(N*bytes)); the env knob tunes it per deployment.
DEFAULT_RING_THRESHOLD = 262144  # bytes; smaller tensors stay on star

_RING_OPS = (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.MIN, ReduceOp.MAX,
             ReduceOp.PRODUCT)


class RingCollectivesMixin(StarCollectivesMixin):
    """Adds a ring allreduce on transports providing p2p primitives
    `send_to(rank, bytes)` / `recv_from(rank) -> bytes`."""

    def _ring_enabled(self) -> bool:
        if os.environ.get("HOROVOD_CPU_OPERATIONS", "").lower() == "star":
            return False
        return hasattr(self, "send_to") and hasattr(self, "recv_from")

    def _ring_threshold(self) -> int:
        try:
            return int(os.environ.get("HOROVOD_RING_THRESHOLD",
                                      DEFAULT_RING_THRESHOLD))
        except ValueError:
            return DEFAULT_RING_THRESHOLD

    def allreduce(self, arr: np.ndarray, op: ReduceOp = ReduceOp.SUM) -> np.ndarray:
        if self.size == 1:
            return arr.copy()
        if (
            not self._ring_enabled()
            or op not in _RING_OPS
            or arr.nbytes < self._ring_threshold()
        ):
            return super().allreduce(arr, op)
        # No eligibility exchange is needed: allreduce sizes are
        # negotiated by the coordinator, so every rank (including joined
        # ranks, which the engine hands full-shape zero buffers) holds
        # the same element count and reaches the same ring/star decision
        # from its own arr.nbytes.
        return self._ring_allreduce(arr, op)

    # ------------------------------------------------------------------
    def _sendrecv(self, dest: int, payload: bytes, src: int) -> bytes:
        """Simultaneous send+recv (MPI_Sendrecv shape): the send runs on
        a helper thread so a full socket buffer cannot deadlock the ring
        (every rank sends right while receiving left)."""
        err: List[BaseException] = []

        def _send():
            try:
                self.send_to(dest, payload)
            except BaseException as e:  # pragma: no cover - network death
                err.append(e)

        t = threading.Thread(target=_send, daemon=True)
        t.start()
        data = self.recv_from(src)
        t.join()
        if err:
            raise err[0]
        return data

    def _ring_allreduce(self, arr: np.ndarray, op: ReduceOp) -> np.ndarray:
        n = self.size
        right = (self.rank + 1) % n
        left = (self.rank - 1) % n
        flat = np.ascontiguousarray(arr).reshape(-1).copy()
        # Chunk boundaries (last chunk absorbs the remainder).
        base = flat.size // n
        bounds = [i * base for i in range(n)] + [flat.size]

        def chunk(i):
            i %= n
            return flat[bounds[i]: bounds[i + 1]]

        # Phase 1: reduce-scatter. After step s, chunk (r-s-1) holds the
        # partial reduction of s+2 ranks; after N-1 steps chunk (r+1) is
        # fully reduced here (ref: gloo ring reduce-scatter schedule).
        for s in range(n - 1):
            send_c = chunk(self.rank - s)
            recv_buf = self._sendrecv(right, send_c.tobytes(), left)
            incoming = np.frombuffer(recv_buf, dtype=flat.dtype)
            tgt = chunk(self.rank - s - 1)
            tgt[:] = _reduce(
                op if op != ReduceOp.AVERAGE else ReduceOp.SUM,
                [tgt, incoming],
            )

        # Phase 2: allgather the reduced chunks around the ring.
        for s in range(n - 1):
            send_c = chunk(self.rank - s + 1)
            recv_buf = self._sendrecv(right, send_c.tobytes(), left)
            chunk(self.rank - s)[:] = np.frombuffer(recv_buf, dtype=flat.dtype)

        if op == ReduceOp.AVERAGE:
            flat = (flat / n).astype(arr.dtype)
        return flat.reshape(arr.shape)
