"""In-process multi-rank backend for tests.

N Engine instances in one process, each with a ThreadedBackend sharing a
ThreadedGroup — queue-based gather/bcast/scatter stand in for sockets.
This lets the full negotiation/fusion/cache/join machinery run cross-
"rank" in a single pytest process (the reference's analogue is running
its test matrix under `horovodrun -np 2` on localhost; with one CPU core
in CI, threads are the cheaper spelling).

Channel isolation: every queue set is keyed by the caller's executor
channel (backend/base.py thread-local scope; CTRL_CHANNEL outside any
scope), mirroring the TCP backend's channel-tagged frame demultiplexer —
two in-flight collectives on different channels exchange through
disjoint queues and can never steal each other's payloads.
"""
from __future__ import annotations

import queue
import struct
import threading
from typing import Dict, List, Optional

from .base import current_channel
from .ring import RingCollectivesMixin
from .star import join_buffers


def _blob(payload) -> bytes:
    """Queues stand in for the wire here, so scatter-gather buffer
    lists and memoryviews are flattened to immutable bytes at the
    'send' boundary — the queue may hand one object to several ranks
    (bcast), and a memoryview of a sender-side numpy chunk must not
    alias mutable state across \"ranks\". Read-only-ness downstream is
    what makes star's own_array copy, exactly as intended."""
    joined = join_buffers(payload)
    return joined if isinstance(joined, bytes) else bytes(joined)


class _ChannelQueues:
    """One channel's worth of exchange queues for the whole group."""

    def __init__(self, size: int):
        self.up = [queue.Queue() for _ in range(size)]    # rank -> root
        self.down = [queue.Queue() for _ in range(size)]  # root -> rank


class ThreadedGroup:
    def __init__(self, size: int):
        from .transport import InprocMesh

        self.size = size
        self._lock = threading.Lock()
        self._channels: Dict[int, _ChannelQueues] = {}
        # Point-to-point plane (ring/hierarchical collectives): the
        # in-process Transport from the pluggable transport layer —
        # same framing/channel-demux contract as the TCP mesh and the
        # shm overlay, exercised by the same conformance suite.
        self.mesh = InprocMesh(size)

    def chan(self, channel: int) -> _ChannelQueues:
        with self._lock:
            c = self._channels.get(channel)
            if c is None:
                c = self._channels[channel] = _ChannelQueues(self.size)
            return c

    def backend(self, rank: int) -> "ThreadedBackend":
        return ThreadedBackend(self, rank)


class ThreadedBackend(RingCollectivesMixin):
    def __init__(self, group: ThreadedGroup, rank: int):
        self.group = group
        self.rank = rank
        self.size = group.size

    def gather_bytes(self, payload) -> Optional[List[bytes]]:
        payload = _blob(payload)
        if self.size == 1:
            return [payload]
        ch = self.group.chan(current_channel())
        if self.rank == 0:
            out = [payload]
            for r in range(1, self.size):
                out.append(ch.up[r].get(timeout=60))
            return out
        ch.up[self.rank].put(payload)
        return None

    def bcast_bytes(self, payload) -> bytes:
        if payload is not None:
            payload = _blob(payload)
        if self.size == 1:
            assert payload is not None
            return payload
        ch = self.group.chan(current_channel())
        if self.rank == 0:
            assert payload is not None
            for r in range(1, self.size):
                ch.down[r].put(payload)
            return payload
        return ch.down[self.rank].get(timeout=60)

    def scatter_bytes(self, payloads: Optional[List]) -> bytes:
        if self.size == 1:
            assert payloads is not None
            return _blob(payloads[0])
        ch = self.group.chan(current_channel())
        if self.rank == 0:
            assert payloads is not None
            for r in range(1, self.size):
                ch.down[r].put(_blob(payloads[r]))
            return _blob(payloads[0])
        return ch.down[self.rank].get(timeout=60)

    # -- p2p primitives (ring/hierarchical data planes) ----------------
    # Ride the in-process transport: send flattens to immutable bytes
    # at the "wire" (the same aliasing contract _blob enforces for the
    # star queues), recv hands back a fresh exclusively-owned bytearray
    # per frame — the owned-buffer contract every transport shares.
    def send_to(self, peer: int, payload):
        self.group.mesh.transport(self.rank, peer).send(
            payload, current_channel())

    def recv_from(self, peer: int) -> bytearray:
        return self.group.mesh.transport(self.rank, peer).recv(
            current_channel())
