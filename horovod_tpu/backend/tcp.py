"""TCP full-mesh backend: the Gloo-equivalent control+data plane.

Workers rendezvous through the HTTP KV store (each PUTs its listening
address, then connects to every lower rank — the same connectFullMesh
bootstrap gloo performs against the KV store, ref: horovod/common/gloo/
gloo_context.cc:70-151). All collective traffic then runs over the mesh
sockets from the engine's single background thread, so no framing tags
are needed beyond a length prefix (the reference relies on the same
single-communication-thread invariant, ref: operations.cc:332-351).

Control plane is star-topology at rank 0 (like MPIController's
Gather/Bcast, ref: mpi_controller.cc:108-199); the data-plane algorithms
come from StarCollectivesMixin. On TPU hardware the data plane is
XLA/ICI — this path serves CPU process-mode and tests; the C++ engine
(horovod_tpu/cc) supersedes it for performance.
"""
from __future__ import annotations

import os
import socket
import struct
from typing import Dict, List, Optional

from ..common.exceptions import HorovodInternalError
from ..utils import env as env_cfg
from ..utils.logging import get_logger
from .rendezvous import RendezvousClient
from .ring import RingCollectivesMixin

logger = get_logger()

_LEN = struct.Struct("<Q")


def _send_all(sock: socket.socket, data: bytes):
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, 8))
    return _recv_exact(sock, n)


class TcpBackend(RingCollectivesMixin):
    """Full-mesh sockets; rank 0 doubles as the coordinator."""

    def __init__(
        self,
        rank: int,
        size: int,
        rendezvous: Optional[RendezvousClient] = None,
        scope: Optional[str] = None,
    ):
        self.rank = rank
        self.size = size
        if scope is None:
            # Elastic re-init: the driver bumps HOROVOD_MESH_SCOPE per
            # topology epoch (stale peer addresses must not be reused).
            scope = env_cfg.get_str(env_cfg.MESH_SCOPE, "hvd_mesh")
        self.peers: Dict[int, socket.socket] = {}
        if size == 1:
            return
        if rendezvous is None:
            addr = env_cfg.get_str(env_cfg.RENDEZVOUS_ADDR, "127.0.0.1")
            port = env_cfg.get_int(env_cfg.RENDEZVOUS_PORT, 0)
            if port == 0:
                raise RuntimeError(
                    "TcpBackend needs HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT "
                    "(set by the hvdrun launcher)"
                )
            rendezvous = RendezvousClient(addr, port)
        self._rendezvous = rendezvous
        self._connect_full_mesh(scope)

    # ------------------------------------------------------------------
    def _connect_full_mesh(self, scope: str):
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("0.0.0.0", 0))
        listener.listen(self.size)
        my_port = listener.getsockname()[1]
        # HOROVOD_MESH_ADDR separates the ADVERTISED address from the
        # slot identity: Spark-task slots carry logical hostnames
        # ("sparktaskN") that no resolver knows, so the executor-side
        # spawner pins the real address here (HOROVOD_HOSTNAME must
        # stay logical — spawn_identity and the elastic registry key
        # on it).
        my_host = (os.environ.get("HOROVOD_MESH_ADDR")
                   or os.environ.get(env_cfg.HOSTNAME) or "127.0.0.1")
        if os.environ.get("HVDRUN_FORCE_LOCAL") or my_host in (
            "localhost", "") or my_host.startswith("process-"):
            my_host = "127.0.0.1"
        self._rendezvous.put(scope, str(self.rank), f"{my_host}:{my_port}".encode())

        # Connect to all lower ranks; accept from all higher ranks. The
        # accept side is bounded: a higher rank that dies during
        # bootstrap (or never starts) must surface as an error here, not
        # an indefinite hang (ref: gloo's store_timeout on rendezvous).
        bootstrap_timeout = env_cfg.get_float(
            "HOROVOD_MESH_BOOTSTRAP_TIMEOUT", 300.0)
        for peer in range(self.rank):
            addr = self._rendezvous.wait_get(scope, str(peer)).decode()
            host, port = addr.rsplit(":", 1)
            s = socket.create_connection((host, int(port)), timeout=60)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_all(s, struct.pack("<i", self.rank))
            self.peers[peer] = s
        listener.settimeout(bootstrap_timeout)
        for _ in range(self.rank + 1, self.size):
            s = None
            try:
                s, _ = listener.accept()
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                # The rank-frame read stays under the bootstrap timeout:
                # a peer that connects but never identifies (half-dead
                # host, stray port scan) must not wedge the job either.
                s.settimeout(bootstrap_timeout)
                (peer,) = struct.unpack("<i", _recv_frame(s))
                s.settimeout(None)
            except (socket.timeout, TimeoutError):
                # An accepted-but-unidentified socket is not in
                # self.peers yet; close it here or it leaks an fd on
                # every elastic retry.
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                missing = sorted(
                    set(range(self.rank + 1, self.size)) - set(self.peers))
                # Elastic retries catch HorovodInternalError and re-init;
                # abandoned sockets must not accumulate across retries.
                listener.close()
                for p in self.peers.values():
                    try:
                        p.close()
                    except OSError:
                        pass
                self.peers.clear()
                raise HorovodInternalError(
                    f"rank {self.rank}: mesh bootstrap timed out after "
                    f"{bootstrap_timeout:.0f}s waiting for rank(s) "
                    f"{missing} to connect (HOROVOD_MESH_BOOTSTRAP_TIMEOUT)"
                )
            self.peers[peer] = s
        listener.close()
        logger.debug("rank %d: TCP mesh connected (%d peers)", self.rank, len(self.peers))

    # ------------------------------------------------------------------
    # transport primitives
    def gather_bytes(self, payload: bytes) -> Optional[List[bytes]]:
        if self.size == 1:
            return [payload]
        if self.rank == 0:
            out = [payload]
            for r in range(1, self.size):
                out.append(_recv_frame(self.peers[r]))
            return out
        _send_all(self.peers[0], payload)
        return None

    def bcast_bytes(self, payload: Optional[bytes]) -> bytes:
        if self.size == 1:
            assert payload is not None
            return payload
        if self.rank == 0:
            assert payload is not None
            for r in range(1, self.size):
                _send_all(self.peers[r], payload)
            return payload
        return _recv_frame(self.peers[0])

    def scatter_bytes(self, payloads: Optional[List[bytes]]) -> bytes:
        if self.size == 1:
            assert payloads is not None
            return payloads[0]
        if self.rank == 0:
            assert payloads is not None
            for r in range(1, self.size):
                _send_all(self.peers[r], payloads[r])
            return payloads[0]
        return _recv_frame(self.peers[0])

    # ------------------------------------------------------------------
    def send_to(self, peer: int, payload: bytes):
        """Point-to-point framed send (ring data plane primitive)."""
        _send_all(self.peers[peer], payload)

    def recv_from(self, peer: int) -> bytes:
        return _recv_frame(self.peers[peer])

    def shutdown(self):
        for s in self.peers.values():
            try:
                s.close()
            except OSError:
                pass
        self.peers.clear()
